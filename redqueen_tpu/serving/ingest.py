"""Idempotent, order-tolerant micro-batch sequencing.

The stream contract: the source stamps consecutive sequence numbers and
MAY deliver duplicates (retransmits after a lost ack), out-of-order
batches (parallel transport), or gaps (lost batches awaiting
retransmit).  The :class:`Sequencer` turns that into the strictly
in-order, exactly-once apply stream the journal/recovery protocol
requires:

- ``seq <= last applied``  → **duplicate**: dropped and counted; the
  apply stream never sees a batch twice (idempotence — a recovering
  source can blindly retransmit its whole window).
- ``seq == next expected`` → ready now, plus every consecutive follower
  buffered in the window (their out-of-order arrival is counted as
  ``reordered`` when they drain).
- within the window        → buffered (bounded: at most ``window``
  batches of lookahead, so memory is bounded no matter how long a gap
  stays open).
- beyond the window        → typed :class:`IngestError` rejection — the
  source must back off and retransmit the gap first; silently widening
  the window would unbound memory, silently dropping would corrupt the
  stream.

``missing_seqs()`` is the backpressure/retransmit signal: the exact gap
list a source needs to close before the window can drain.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .events import EventBatch, IngestError

__all__ = ["Sequencer"]


class Sequencer:
    """Reorder/dedup stage between ``submit`` and apply.  Not
    thread-safe by design — the serving runtime owns one and serializes
    access (the apply path is single-writer by construction: one journal,
    one carry)."""

    def __init__(self, start_seq: int = 0, window: int = 8):
        if window < 1:
            raise ValueError(f"reorder window must be >= 1, got {window}")
        self.next_seq = int(start_seq)
        self.window = int(window)
        self._held: Dict[int, EventBatch] = {}
        self.duplicates = 0
        self.reordered = 0
        self.window_rejects = 0

    @property
    def held(self) -> int:
        return len(self._held)

    def classify(self, seq: int) -> str:
        """Read-only probe: ``applied`` (seq is behind the apply stream
        — a retransmit the source may treat as an ack), ``held``
        (buffered in the window, NOT yet applied — the arrival is
        redundant but the batch is not durable, so the admission must
        not read as an ack), or ``new``.  The runtime consults this
        BEFORE its queue-capacity shed check so neither redundant class
        is ever miscounted as shed."""
        seq = int(seq)
        if seq < self.next_seq:
            return "applied"
        return "held" if seq in self._held else "new"

    def missing_seqs(self) -> List[int]:
        """The gap list blocking the window from draining — the
        retransmit request the backpressure signal carries."""
        if not self._held:
            return []
        return [s for s in range(self.next_seq, max(self._held))
                if s not in self._held]

    def offer(self, batch: EventBatch) -> Tuple[str, List[EventBatch]]:
        """Feed one validated batch; returns ``(status, ready)`` where
        ``status`` is ``accepted`` / ``duplicate`` and ``ready`` the
        in-order run now unblocked (empty for a buffered out-of-order
        batch — status is still ``accepted``: it WILL apply once the gap
        closes).  Raises :class:`IngestError` when the batch lands
        beyond the bounded window."""
        seq = int(batch.seq)
        if seq < self.next_seq:
            self.duplicates += 1
            return "duplicate", []
        if seq in self._held:
            # Redundant arrival of a batch already buffered: counted as
            # a duplicate delivery, but reported ``accepted`` — it has
            # NOT applied yet, so the source must not take this as an
            # ack (a crash before the gap closes would lose it).
            self.duplicates += 1
            return "accepted", []
        if seq >= self.next_seq + self.window:
            self.window_rejects += 1
            raise IngestError(
                f"seq {seq} is beyond the reorder window "
                f"[{self.next_seq}, {self.next_seq + self.window}) — "
                f"retransmit the missing batches "
                f"{self.missing_seqs() or [self.next_seq]} first",
                seq=seq)
        if seq != self.next_seq:
            # Held for later: counted as a reorder when it drains (it
            # arrived before its predecessors).
            self._held[seq] = batch
            return "accepted", []
        ready = [batch]
        self.next_seq += 1
        while self.next_seq in self._held:
            nxt = self._held.pop(self.next_seq)
            self.reordered += 1
            ready.append(nxt)
            self.next_seq += 1
        return "accepted", ready
