"""Elastic topology: crash-safe live resharding + follow-graph churn.

The PR 7 :func:`serving.cluster.reshard` migrates a cluster only when it
is fully DRAINED and offline — the paper's broadcasters live in a social
graph that churns while u*(t) keeps firing, so the serving tier must
resize and rewire **under traffic**.  This module is that substrate
(ROADMAP item 5): a journaled topology log + a resumable per-range
migration driver, built on the same journal/epoch pattern
``serving.paramswap`` proved for live parameters.

**The topology log** (``<cluster dir>/topology.log``) is an append-only,
per-record-checksummed, fsynced JSONL file: every topology mutation —
new shard slots, range fences, ownership flips, edge adds/drops, shard
retirements — lands as a monotonically-epoch-numbered record BEFORE it
takes effect, and ``ServingCluster.recover`` replays the log exactly
like the parameter-epoch records: a crashed router reconstructs the
live ownership map bit-identically, and a torn tail (the
``reshard:torn_plan`` fault) is quarantined by truncation, never
trusted.

**Two-phase per-range handoff** (:class:`Migration.step`, one feed
range at a time while the other shards keep serving):

1. **fence** — the cluster drains to a uniform applied watermark ``W``;
   the source shard's carry slice for the range is extracted and its
   canonical :func:`range_digest` journaled in a ``fence`` record.
   From fence to flip the router refuses (status ``"fenced"``, counted
   ``fenced_retried``, retransmitted by the source later) any batch
   with ``seq > W`` touching a feed the fenced SOURCE shard still owns
   — the whole source shard is paused, because one posting decision
   resets every healthy rank on the shard and would silently mutate
   the fenced slice under the migration.  Batches for every other
   shard keep flowing (the source receives their empty sub-batches,
   which advance its seq but cannot change rank/health — the digest
   is position-independent by construction).
2. **install + flip** — the destination journals a digest-asserted
   ``topo_epoch`` record in its OWN shard journal
   (:meth:`ServingRuntime.install_range` — an idempotent scatter-set,
   replayed in stream order on recovery exactly like a param epoch)
   and snapshots; then the router journals the ``flip`` record and
   atomically rewires ownership.  No apply can land on a stale owner:
   admission routes by the flipped ownership map, and every fenced
   seq admitted pre-flip was already applied cluster-wide (the
   watermark barrier), so a post-flip retransmit is a pure duplicate
   at every shard regardless of geometry.

SIGKILL of source, destination, or router mid-migration resumes from
the last fenced range: the fence record carries the range digest, the
resumed step re-extracts from the recovered (frozen) source and asserts
bit-identity, the re-install is idempotent, and the flip lands once.

**Churn.**  ``add_edges`` assigns new feeds to the least-loaded shard
(:func:`churn_assign`, deterministic ties) and materializes the growth
as a mini-migration into a fresh pre-sized slot — growing a live
runtime's arrays in place would invalidate every journaled state
digest, so *growth is resharding*: the old slot's feeds move (digest-
asserted) into the new slot, the old slot retires.  ``drop_edges``
journals the drop and poisons the carry slice on the owning shard
(rank 0, health bit set — the edge stops contributing intensity), with
the feed excluded from routing and from :meth:`edge_digest`.

See docs/DESIGN.md "Elastic topology & live resharding".
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..runtime import telemetry as _telemetry

__all__ = ["TopologyLog", "TopologyState", "Migration", "TopologyError",
           "MigrationInterrupted", "MigrationStalled", "read_topology_log",
           "tear_topology_tail", "range_digest", "plan_moves",
           "churn_assign", "TOPOLOGY_LOG", "TOPOLOGY_KINDS"]

#: The topology log filename inside the cluster directory.
TOPOLOGY_LOG = "topology.log"

#: Every record kind the log may carry (the recovery replay refuses an
#: unknown kind loudly — a newer writer's record must never be half-
#: understood by an older reader).
TOPOLOGY_KINDS = ("plan", "add_slot", "add_edges", "fence", "flip",
                  "retire", "complete", "drop_edges")


class TopologyError(ValueError):
    """A topology operation refused (undrained cluster, pending plan,
    unknown feed, ...) — the cluster state is untouched."""


class MigrationInterrupted(RuntimeError):
    """A migration step died mid-handoff (injected kill or torn plan):
    the fence record is durable; ``resume_migration()``/``step()``
    continues from the last fenced range after recovery."""


class MigrationStalled(RuntimeError):
    """The injected ``reshard:wedge`` stall — one counted no-progress
    step; retrying the step proceeds normally."""


def _canon(rec: Dict[str, Any]) -> bytes:
    return json.dumps(rec, sort_keys=True,
                      separators=(",", ":")).encode()


class TopologyLog:
    """Append-only fsynced topology record log.  One JSON line per
    record: ``{"rec": <record>, "sha": sha256(canonical record)}`` —
    the per-line checksum is what lets recovery tell a torn tail from
    a corrupt middle (truncate the first, refuse the second is not
    needed: any bad line truncates, because records after it were
    never acknowledged as durable to the driver)."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "ab")

    def append(self, rec: Dict[str, Any]) -> None:
        if rec.get("kind") not in TOPOLOGY_KINDS:
            raise ValueError(f"unknown topology record kind "
                             f"{rec.get('kind')!r}")
        line = json.dumps(
            {"rec": rec,
             "sha": hashlib.sha256(_canon(rec)).hexdigest()},
            sort_keys=True, separators=(",", ":"))
        self._f.write(line.encode() + b"\n")
        self._f.flush()
        # A topology record takes effect only after it is durable —
        # same contract as the parameter-epoch records: the flip the
        # router acts on must be the flip recovery will replay.
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_topology_log(path: str, quarantine_torn_tail: bool = True
                      ) -> Tuple[List[Dict[str, Any]], bool]:
    """Read + verify every record; a torn/corrupt tail is TRUNCATED
    (when ``quarantine_torn_tail``) so the next append continues from
    the last provable record.  Returns ``(records, torn)``."""
    with _telemetry.span("serving.topo.log.verify"):
        return _read_topology_log(path, quarantine_torn_tail)


def _read_topology_log(path: str, quarantine_torn_tail: bool
                       ) -> Tuple[List[Dict[str, Any]], bool]:
    if not os.path.exists(path):
        return [], False
    records: List[Dict[str, Any]] = []
    good_end = 0
    torn = False
    with open(path, "rb") as f:
        data = f.read()
    at = 0
    while at < len(data):
        nl = data.find(b"\n", at)
        if nl < 0:
            torn = True  # unterminated tail line
            break
        line = data[at:nl]
        try:
            obj = json.loads(line)
            rec = obj["rec"]
            if obj["sha"] != hashlib.sha256(_canon(rec)).hexdigest():
                raise ValueError("checksum mismatch")
            if rec.get("kind") not in TOPOLOGY_KINDS:
                raise ValueError(f"unknown kind {rec.get('kind')!r}")
        except (ValueError, KeyError, TypeError):
            torn = True
            break
        records.append(rec)
        good_end = nl + 1
        at = nl + 1
    if torn and quarantine_torn_tail:
        with open(path, "r+b") as f:
            f.truncate(good_end)
    return records, torn


def tear_topology_tail(path: str, nbytes: int = 9) -> None:
    """Chaos helper (the ``reshard:torn_plan`` fault body): cut the
    last ``nbytes`` bytes so the final record is mid-line torn — what a
    power loss during the fence append leaves behind."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(0, size - nbytes))


def range_digest(feeds: Sequence[int], rank: np.ndarray,
                 health: np.ndarray) -> str:
    """Canonical digest of one moved range's carry slice — global feed
    ids + per-edge ``(rank f32, health u32)``.  Deliberately EXCLUDES
    the stream position: the fenced source keeps applying empty
    sub-batches (seq advances) while its rank/health are frozen, so
    the digest taken at fence time must equal the one re-extracted
    after a crash + recovery + catch-up."""
    feeds = np.ascontiguousarray(np.asarray(feeds, np.int64))
    rank = np.ascontiguousarray(np.asarray(rank, np.float32))
    health = np.ascontiguousarray(np.asarray(health, np.uint32))
    if not (len(feeds) == len(rank) == len(health)):
        raise ValueError(
            f"range arrays disagree: {len(feeds)} feeds, "
            f"{len(rank)} ranks, {len(health)} health words")
    h = hashlib.sha256()
    h.update(np.int64(len(feeds)).tobytes())
    h.update(feeds.tobytes())
    h.update(rank.tobytes())
    h.update(health.tobytes())
    return h.hexdigest()


def churn_assign(counts: Dict[int, int], n_add: int) -> List[int]:
    """Deal ``n_add`` new edges greedily onto the least-loaded live
    shards (ties break to the lowest shard id) — deterministic, and it
    never widens the load spread beyond ``max(initial spread, 1)``:
    each pick raises a current minimum by one."""
    if n_add < 0:
        raise ValueError(f"n_add must be >= 0, got {n_add}")
    if not counts and n_add:
        raise ValueError("no live shards to assign new edges to")
    live = dict(counts)
    out: List[int] = []
    for _ in range(int(n_add)):
        k = min(live, key=lambda i: (live[i], i))
        out.append(k)
        live[k] += 1
    return out


def _balanced_sizes(total: int, n: int) -> List[int]:
    base, rem = divmod(int(total), int(n))
    return [base + 1 if i < rem else base for i in range(n)]


def plan_moves(owned: Dict[int, np.ndarray], new_slot_ids: List[int],
               range_size: Optional[int] = None
               ) -> Tuple[Dict[int, List[int]], List[Dict[str, Any]]]:
    """Build a grow-migration plan: existing shards only SHED feeds
    (an existing runtime never receives — growing its arrays in place
    would invalidate its journaled state digests), new slots are
    created pre-sized with their full target feed set.

    Target sizes are the ±1-balanced deal of the live feed count over
    the post-migration shard count, largest targets matched to the
    currently-largest shards; each existing shard keeps its first
    ``target`` feeds in ascending feed order and sheds the tail, and
    the shed feeds fill the new slots in slot order, chunked into
    ranges of at most ``range_size`` feeds (one range per (src, dst)
    chunk by default).  Returns ``(new slot feed sets, ranges)`` where
    each range is ``{"id", "src", "dst", "feeds"}``."""
    slot_ids = sorted(owned)
    total = sum(len(owned[k]) for k in slot_ids)
    m = len(slot_ids) + len(new_slot_ids)
    if not new_slot_ids:
        raise ValueError("a grow plan needs at least one new slot")
    if total < m:
        raise TopologyError(
            f"{total} live edges cannot fill {m} shards with at least "
            f"one edge each")
    sizes = _balanced_sizes(total, m)  # descending by construction
    by_load = sorted(slot_ids, key=lambda k: (-len(owned[k]), k))
    keep: Dict[int, int] = {}
    for pos, k in enumerate(by_load):
        keep[k] = min(len(owned[k]), sizes[pos])
    surplus_total = total - sum(keep.values())
    new_sizes = _balanced_sizes(surplus_total, len(new_slot_ids))
    if min(new_sizes) < 1:
        raise TopologyError(
            f"surplus of {surplus_total} edges cannot give each of "
            f"{len(new_slot_ids)} new shards at least one edge — the "
            f"cluster is already as wide as its edge count allows")
    shed: List[Tuple[int, List[int]]] = []
    for k in slot_ids:
        feeds = sorted(int(f) for f in owned[k])
        tail = feeds[keep[k]:]
        if tail:
            shed.append((k, tail))
    new_feeds: Dict[int, List[int]] = {k: [] for k in new_slot_ids}
    ranges: List[Dict[str, Any]] = []
    di = 0
    need = new_sizes[0]
    rid = 0
    for src, tail in shed:
        at = 0
        while at < len(tail):
            while need == 0:
                di += 1
                need = new_sizes[di]
            take = need if range_size is None else min(need, range_size)
            chunk = tail[at:at + take]
            dst = new_slot_ids[di]
            new_feeds[dst].extend(chunk)
            ranges.append({"id": rid, "src": int(src), "dst": int(dst),
                           "feeds": [int(f) for f in chunk]})
            rid += 1
            need -= len(chunk)
            at += len(chunk)
    for k in new_feeds:
        new_feeds[k] = sorted(new_feeds[k])
    return new_feeds, ranges


class TopologyState:
    """The router's in-memory topology bookkeeping — epoch counter,
    pending plan, active fences — reconstructed bit-identically from
    the log on recovery (the cluster's owner/local-index arrays are the
    routing half; this is the protocol half)."""

    def __init__(self):
        self.epoch = 0
        self.plan: Optional[Dict[str, Any]] = None
        self.fences: Dict[int, Dict[str, Any]] = {}  # range id -> rec
        self.flipped: set = set()          # flipped range ids (plan)
        self.plans_completed = 0

    def next_epoch(self) -> int:
        return self.epoch + 1

    def note_epoch(self, epoch: int) -> None:
        self.epoch = max(self.epoch, int(epoch))

    def assert_fenced(self, plan_id: str, range_id: int) -> None:
        """The RQ1007 ownership guard: an edge-state install is only
        sanctioned for a range the CURRENT plan holds fenced — a stale
        driver (pre-crash object, wrong plan) fails here instead of
        scattering into a live shard."""
        with _telemetry.span("serving.topo.assert", kind="fenced",
                             plan=str(plan_id), range=int(range_id)):
            rec = self.fences.get(int(range_id))
            if rec is None or self.plan is None \
                    or rec.get("plan") != plan_id \
                    or self.plan.get("plan") != plan_id:
                raise TopologyError(
                    f"range {range_id} of plan {plan_id!r} is not "
                    f"fenced under the current topology epoch "
                    f"{self.epoch} — refusing an unfenced edge-state "
                    f"install")

    def assert_owner(self, owners: np.ndarray, k: int,
                     feeds: Sequence[int]) -> None:
        """The RQ1007 ownership guard for churn mutations: every feed
        being mutated must be owned by shard ``k`` under the current
        epoch, and no fence may be pending (a fenced source's slice is
        frozen)."""
        with _telemetry.span("serving.topo.assert", kind="owner",
                             shard=int(k)):
            owners = np.asarray(owners)
            if self.fences:
                raise TopologyError(
                    f"ranges {sorted(self.fences)} are fenced — finish "
                    f"the pending migration before mutating edge state")
            if (owners != int(k)).any():
                bad = [int(f) for f, o in zip(feeds, owners)
                       if int(o) != int(k)]
                raise TopologyError(
                    f"feeds {bad} are not owned by shard {k} under "
                    f"epoch {self.epoch} — refusing a stale-owner "
                    f"mutation")


class Migration:
    """The resumable per-range migration driver over one journaled
    plan.  ``step()`` moves exactly one range (fence → extract →
    install → flip) on a drained cluster; the caller interleaves
    traffic between steps.  Injected ``reshard:*`` faults land at
    exact range ids; after an interruption, recover the killed shard
    (or ``ServingCluster.recover`` the directory) and keep stepping —
    the fence record pins the range digest across the outage."""

    def __init__(self, cluster, plan: Dict[str, Any], fault=None):
        self.cluster = cluster
        self.plan = plan
        self._fault = fault
        self._fault_spent = False
        if fault is not None \
                and int(fault.range) >= len(plan["ranges"]):
            raise ValueError(
                f"RQ_FAULT targets reshard range {fault.range} but "
                f"this plan has {len(plan['ranges'])} range(s) (valid: "
                f"0..{len(plan['ranges']) - 1}) — the fault could "
                f"never fire")

    @property
    def plan_id(self) -> str:
        return str(self.plan["plan"])

    @property
    def ranges(self) -> List[Dict[str, Any]]:
        return list(self.plan["ranges"])

    def remaining(self) -> List[Dict[str, Any]]:
        t = self.cluster._topo
        return [r for r in self.plan["ranges"]
                if int(r["id"]) not in t.flipped]

    @property
    def done(self) -> bool:
        return self.cluster._topo.plan is None or not self.remaining()

    def run(self, max_steps: Optional[int] = None) -> int:
        """Step to completion (no interleaved traffic — the drained
        convenience path); returns the number of ranges moved."""
        moved = 0
        while not self.done:
            self.step()
            moved += 1
            if max_steps is not None and moved >= max_steps:
                break
        return moved

    def _drain(self, drain_rounds: int) -> int:
        cl = self.cluster
        for _ in range(int(drain_rounds)):
            if cl.pending == 0:
                break
            cl.poll()
        if cl.pending:
            raise TopologyError(
                f"cluster will not drain ({cl.pending} sub-batches "
                f"pending after {drain_rounds} poll rounds) — "
                f"retransmit the gap seqs, then step again")
        return cl._uniform_applied_seq(
            "a range handoff needs every shard at one watermark")

    def step(self, drain_rounds: int = 64) -> Optional[int]:
        """Move the next unflipped range; returns its id (None when the
        plan is already complete)."""
        cl = self.cluster
        t = cl._topo
        todo = self.remaining()
        if not todo:
            return None
        r = todo[0]
        rid = int(r["id"])
        watermark = self._drain(drain_rounds)
        src = cl._slots[int(r["src"])]
        dst = cl._slots[int(r["dst"])]
        for slot, role in ((src, "source"), (dst, "destination")):
            if slot.runtime is None:
                raise TopologyError(
                    f"range {rid} {role} shard {slot.k} is quarantined "
                    f"— recover_shard({slot.k}) before stepping")
        fault = None if self._fault_spent else self._fault
        fire = fault is not None and int(fault.range) == rid
        if fire and fault.mode == "wedge":
            self._fault_spent = True
            cl.metrics.observe_migration_stall()
            raise MigrationStalled(
                f"migration stalled at range {rid} (injected wedge) — "
                f"step again to proceed")
        feeds = np.asarray(r["feeds"], np.int64)
        local_src = cl._local_index[feeds]
        rank, health = src.runtime.extract_range(
            [int(i) for i in local_src])
        digest = range_digest(feeds, rank, health)
        fence = t.fences.get(rid)
        if fence is None:
            fence = {"kind": "fence", "epoch": t.next_epoch(),
                     "plan": self.plan_id, "range": rid,
                     "src": int(r["src"]), "dst": int(r["dst"]),
                     "watermark": int(watermark), "digest": digest}
            cl._append_topo(fence)
        elif fence["digest"] != digest:
            raise RuntimeError(
                f"live reshard diverged at range {rid}: re-extracted "
                f"range digest {digest[:12]}.. != fenced "
                f"{str(fence['digest'])[:12]}.. — the source carry "
                f"mutated under the fence; refusing to install")
        if fire and fault.mode == "kill_router":
            # The router process dies with the fence durable and the
            # flip unwritten — the chaos scenario recovers the
            # directory and resumes from exactly here.
            os._exit(21)
        if fire and fault.mode == "torn_plan":
            self._fault_spent = True
            if cl._topo_log is not None:
                tear_topology_tail(cl._topo_log.path)
            raise MigrationInterrupted(
                f"topology log torn at fence of range {rid} "
                f"(injected) — recover the directory to resume")
        if fire and fault.mode == "kill_src":
            self._fault_spent = True
            cl.kill_shard(src.k,
                          reason=f"reshard:kill_src at range {rid} "
                                 f"(injected)")
            raise MigrationInterrupted(
                f"source shard {src.k} killed mid-handoff of range "
                f"{rid} (injected) — recover it and step again")
        # Install: ownership-guarded (RQ1007), digest-asserted,
        # idempotent — a resumed step re-installs over a half-landed
        # copy bit-identically.
        local_dst = np.searchsorted(dst.feeds, feeds)
        t.assert_fenced(self.plan_id, rid)
        dst.runtime.install_range(
            [int(i) for i in local_dst], rank, health,
            feeds=[int(f) for f in feeds],
            topo_epoch=int(fence["epoch"]), digest=digest,
            plan_id=self.plan_id, range_id=rid)
        dst.runtime.snapshot()
        if fire and fault.mode == "kill_dst":
            self._fault_spent = True
            cl.kill_shard(dst.k,
                          reason=f"reshard:kill_dst at range {rid} "
                                 f"(injected)")
            raise MigrationInterrupted(
                f"destination shard {dst.k} killed after install of "
                f"range {rid} (injected) — recover it and step again")
        flip = {"kind": "flip", "epoch": t.next_epoch(),
                "plan": self.plan_id, "range": rid,
                "src": int(r["src"]), "dst": int(r["dst"]),
                "feeds": [int(f) for f in feeds], "digest": digest}
        cl._append_topo(flip)
        if not self.remaining():
            self._complete()
        return rid

    def _complete(self) -> None:
        cl = self.cluster
        t = cl._topo
        srcs = sorted({int(r["src"]) for r in self.plan["ranges"]})
        cl._append_topo({"kind": "complete",
                         "epoch": t.next_epoch(),
                         "plan": self.plan_id})
        for k in srcs:
            if not (cl._owner == k).any():
                cl._append_topo({"kind": "retire",
                                 "epoch": t.next_epoch(), "k": k})
