"""Sharded serving fault domains: per-shard journals, health-aware
routing, and crash isolation at corpus scale.

One :class:`ServingCluster` partitions the feed-edge state by EDGE HASH
into ``n_shards`` independent fault domains.  Each shard is a full
PR 6 :class:`~redqueen_tpu.serving.service.ServingRuntime` — its OWN
journal segments, orbax snapshot tree, ``Sequencer``, carry, and health
state under ``<dir>/shard-KKKK/`` — so recovery, torn-tail quarantine,
and overload shedding are decided per shard, never per service: one
wedged apply, torn journal, or killed carry takes down 1/N of the edge
graph while the other shards keep serving.

**Routing (the ShardRouter role).**  ``submit`` validates the global
micro-batch once, splits it by the deterministic edge-hash partition
(:func:`partition` — hash-ordered round-robin dealing, balanced to ±1
edge, pure function of ``(n_feeds, n_shards, PARTITION_VERSION)``), and
offers every shard its sub-batch **under the global sequence number**
(empty slices included) — so each shard's journal is independently
replayable and each shard's decision stream is a pure function of
``(shard carry, global stream)``.  ``poll`` dispatches one sub-batch at
a time per shard with timeout detection, exponential poll-round backoff
for wedged shards, and per-shard health tracking:

    healthy --timeout/transient--> degraded --HEAL_AFTER clean--> healthy
    degraded --QUARANTINE_AFTER consecutive failures--> quarantined
    any --crash / torn journal / journal-append failure--> quarantined
    quarantined --recover_shard (snapshot + digest-asserted replay)-->
        degraded (probation)

**Crash isolation.**  A crashed shard loses exactly what SIGKILL leaves
behind: its in-memory carry, queue, and reorder window die; its fsynced
journal records and snapshots survive.  ``recover_shard`` rebuilds the
shard in place through :func:`serving.service.recover` (newest provable
snapshot + digest-asserted journal replay — bit-identical carry AND
decisions) while healthy shards keep serving; sub-batches offered to a
quarantined shard are shed-with-recorded-seqs (``shed_unavailable``),
and the batches that died un-applied inside the crashed shard are
reclassified ``lost_on_crash`` — the router-side
:class:`~redqueen_tpu.serving.metrics.ClusterMetrics` ledger keeps the
closed accounting identity ``ingested == applied + shed + rejected +
duplicates (+ pending)`` true per shard and cluster-wide at every
instant, including mid-recovery.

**Fault injection.**  Every failure mode runs deterministically in CI on
CPU via ``runtime.faultinject``'s ``shard`` kinds
(``RQ_FAULT=shard:crash|wedge|torn_journal|corrupt_snapshot@shardK
[,batchN]``), applied by the router at exact sub-batch sequence numbers;
:meth:`ServingCluster.kill_shard` is the same teardown as an operator
chaos hook.

**Worker placement (out-of-process shards).**  ``placement="workers"``
moves every fault domain into its own SUBPROCESS
(:mod:`serving.worker`): the shard directory layout, journals,
snapshots, and recovery protocol are unchanged on disk — in-process and
worker placements are interchangeable and bit-identical — but the crash
domain becomes REAL: a SIGSEGV/OOM/SIGKILL in one shard is a child
exit the router observes, not a cluster death, and the N per-shard
journal fsyncs run in N processes in parallel instead of serializing
behind one GIL.  The router drives each worker over the checksummed
frame protocol (:mod:`serving.transport`) with split
``start_*``/``finish_*`` calls, so submits and polls fan out to every
worker before any response is collected — that overlap is the
parallel-serving win.  Failure classification maps transport shapes
onto the SAME health state machine: a request deadline expiry or stale
heartbeat is a timeout (degrade + backoff, quarantine-and-SIGKILL
after ``QUARANTINE_AFTER``), a child exit / pipe EOF is a crash, and a
poisoned byte stream (checksum/magic/desync, or a worker-side error
reply) tears the worker down — never a router crash, never a
silently-trusted payload.  A dead worker is restarted under the
``runtime.supervisor`` :class:`~redqueen_tpu.runtime.supervisor
.RetryPolicy` (crash-loop exponential backoff, give-up →
quarantined-for-the-operator) and recovers IN PLACE from its own
journal while the survivors keep serving.  Worker-level faults
(``RQ_FAULT=worker:kill|hang|eof|garbage@shardK[,batchN]``) are applied
by the worker child itself at exact sub-batch seqs, so the
SIGKILL-a-real-process chaos scenario runs deterministically on CPU.

**Socket placement (cross-host shards).**  ``placement="sockets"``
keeps everything above but moves the frames onto authenticated TCP
(:class:`transport.Listener` per shard, hello token via
``RQ_WORKER_TOKEN``): a worker may run on ANY host that can dial the
router.  The network becomes a first-class failure domain with its own
healing path — a dead LINK is not a dead WORKER: the worker redials
under a deterministic RetryPolicy, the router reattaches the same live
process (pid-matched hello, ``worker_reattach_grace_s``), classifies
the episode as a timeout (degrade → probation), and RESYNCS the
decisions whose response frames the link ate from the worker's bounded
recent-ring (``replay_decisions``) — no journal replay for a mere
partition, and the accounting identity stays closed (a resync and a
salvaged late frame can never double-count: both filter to
still-outstanding seqs).  ``net:drop|delay|partition|reconnect@shardK
[,batchN]`` fault kinds drive every link failure deterministically in
CI; :meth:`partition_shard` is the router-side chaos hook; and
:meth:`remote_worker_commands` + ``SocketWorkerHandle.await_external``
are the remote-spawn recipe.  See docs/DESIGN.md "Durability modes &
the ack contract".

**Wire-speed ingest.**  ``coalesce=K`` + ``flush_mode="group"`` +
:meth:`submit_many` form the high-throughput path (ROADMAP item 2):
one frame per round per shard, one jitted dispatch + one journal
record per round per worker, acks inside an explicit bounded
durability window (``max_unflushed_records`` / ``max_flush_delay_ms``,
recorded by :meth:`durability` in every metrics artifact; a consumed
window is reported per shard as ``lost_in_window`` and healed by
retransmit).

**Reshard (grow without genesis replay).**  :func:`reshard` migrates a
drained N-shard directory to M shards by per-edge state migration: the
per-edge ``(rank, health)`` carry, the cluster clock, and the stream
position move to the new partition, each new shard lands an immediate
snapshot at the migrated seq (recovery never replays from genesis), and
the whole move is **digest-asserted** — the canonical per-edge
:meth:`~ServingCluster.edge_digest` must be bit-identical before and
after, or the reshard raises instead of serving silently-migrated-wrong
state.  Per-shard lifetime counters (``n_events``/``n_posts``) reset at
a reshard (they are fault-domain metrics, not stream state); the stream
position (``seq``/``n_batches``) migrates.

**Elastic topology (live resharding + graph churn).**
:meth:`begin_reshard` grows the cluster N→M shards UNDER TRAFFIC via
:mod:`serving.topology`: a journaled, resumable migration plan moves
feed ranges one at a time through a two-phase fence→install→flip
handoff — the source shard is fenced (admissions touching it refuse
with status ``"fenced"`` and retransmit after the flip), the carry
slice streams to a pre-sized fresh destination as a digest-asserted
``install_range`` journal record, and the router flips ownership via a
fsynced topology-epoch record in ``topology.log``.  SIGKILL of source,
destination, or router mid-migration resumes from the last fenced
range (``resume_migration``), with the per-range digest asserted
bit-identical across the outage; :meth:`ServingCluster.recover`
replays the topology log exactly like param epochs.  ``add_edges`` /
``drop_edges`` are journaled live graph churn on the same substrate;
``reshard:kill_src|kill_dst|kill_router|wedge|torn_plan@rangeK``
fault kinds drive every interruption deterministically in CI.  See
docs/DESIGN.md "Elastic topology & live resharding".

See docs/DESIGN.md "Sharded serving & fault domains".
"""

from __future__ import annotations

import hashlib
import os
import shutil
import time
from typing import (Any, Dict, List, NamedTuple, Optional, Sequence,
                    Tuple)

import numpy as np

from ..runtime import faultinject as _faultinject
from ..runtime import integrity as _integrity
from ..runtime import telemetry as _telemetry
from ..runtime.supervisor import RetryPolicy
from . import topology as _topology
from .events import EventBatch, IngestError, validate_batch
from .metrics import ClusterMetrics
from .service import (RecoveryInfo, ServingRuntime, SNAPSHOTS_DIRNAME,
                      _CONFIG as _SHARD_CONFIG,
                      recover as _recover_runtime)
from .topology import TopologyError
from .transport import TransportEOF, TransportError, TransportTimeout

# NOTE: serving.worker is imported lazily (in _spawn_worker) — it
# doubles as a ``python -m`` entry point, and an eager import here
# would trip runpy's found-in-sys.modules warning on every manual
# invocation.

__all__ = ["ServingCluster", "ShardRouter", "ClusterAdmission",
           "ClusterDecision", "partition", "shard_seed", "reshard",
           "CLUSTER_SCHEMA", "RESHARD_SCHEMA", "PARTITION_VERSION",
           "PLACEMENTS", "WORKER_PLACEMENTS", "HEALTHY", "DEGRADED",
           "QUARANTINED", "RETIRED", "HEAL_AFTER", "QUARANTINE_AFTER",
           "WEDGE_FIRES", "MAX_BACKOFF_ROUNDS",
           "DEFAULT_RESTART_POLICY"]

CLUSTER_SCHEMA = "rq.serving.cluster/1"
RESHARD_SCHEMA = "rq.serving.reshard/1"
_CLUSTER_CONFIG = "cluster.json"

# Bump when the partition function changes: a directory written under a
# different partition CANNOT be reopened (edges would silently route to
# the wrong journals) — the config check refuses instead.
PARTITION_VERSION = 1

# Health states + state-machine constants (see the module docstring).
HEALTHY = "healthy"
DEGRADED = "degraded"
QUARANTINED = "quarantined"
# A migration source that shed its last feed: its directory and journal
# stay on disk (history), but it owns no edges, receives no traffic,
# and is never auto-recovered.  Terminal — distinct from QUARANTINED so
# reads don't count a retired slot as degraded serving.
RETIRED = "retired"
HEAL_AFTER = 3          # consecutive clean applies: degraded -> healthy
QUARANTINE_AFTER = 3    # consecutive timeouts: degraded -> quarantined
WEDGE_FIRES = 2         # injected-wedge timeouts before the stall clears
MAX_BACKOFF_ROUNDS = 8  # cap on the wedged-shard poll-round backoff
RECOVERY_GIVE_UP = 3    # failed auto-recoveries before poll() raises

# Shard placement modes: every fault domain lives in the router's
# process ("in-process", PR 7), in its own supervised subprocess over
# pipes ("workers", PR 8), or in a subprocess over an authenticated TCP
# connection ("sockets" — same frame protocol, plus reconnect: the
# cross-host placement, where a shard worker may run on ANY host that
# can dial the router's per-shard listener).  Interchangeable on disk —
# NOT part of the directory identity.
PLACEMENTS = ("in-process", "workers", "sockets")
# The placements whose shards live out of process (drive WorkerHandle
# surfaces over frames).
WORKER_PLACEMENTS = ("workers", "sockets")

# Worker restart schedule (placement="workers"): the runtime.supervisor
# RetryPolicy drives the crash-loop backoff — restart n of a crash
# streak waits delay(n), and max_attempts consecutive FAILED recoveries
# is the give-up bound (the shard stays quarantined and poll() raises
# for the operator).  seed=0: the jitter — and with it the whole chaos
# timeline — is deterministic in CI.
DEFAULT_RESTART_POLICY = RetryPolicy(
    max_attempts=RECOVERY_GIVE_UP, base_delay_s=0.25, multiplier=2.0,
    max_delay_s=10.0, jitter=0.1, seed=0)
_CRASH_STREAK_CAP = 10  # backoff exponent cap (delay saturates anyway)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer over uint64 (vectorized; wraparound is the
    point)."""
    x = np.asarray(x, np.uint64)
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x


def partition(n_feeds: int, n_shards: int) -> np.ndarray:
    """``assign[feed] = owning shard``: edges are ordered by their
    splitmix64 hash, then dealt round-robin — decorrelated from feed-id
    locality like a plain ``hash % N`` but balanced BY CONSTRUCTION
    (shard sizes differ by at most one edge, so no shard can come up
    empty while ``n_shards <= n_feeds``).  Pure function of
    ``(n_feeds, n_shards)`` under :data:`PARTITION_VERSION`."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    if n_shards > n_feeds:
        raise ValueError(
            f"n_shards={n_shards} > n_feeds={n_feeds}: every shard must "
            f"own at least one edge")
    h = _mix64(np.arange(n_feeds, dtype=np.uint64))
    order = np.argsort(h, kind="stable")
    assign = np.empty(n_feeds, np.int64)
    assign[order] = np.arange(n_feeds, dtype=np.int64) % n_shards
    return assign


def shard_seed(seed: int, shard: int) -> int:
    """Deterministic per-shard PRNG seed derivation — distinct shards
    must draw from distinct decision streams (the PR 4 RQ501 lesson:
    never reuse one key across independent consumers)."""
    return (int(seed) * 1_000_003 + 7_919 * (int(shard) + 1)) \
        % (2 ** 31 - 1)


class ClusterAdmission(NamedTuple):
    """One global ``submit``'s outcome: ``status`` summarizes
    (``accepted`` = every shard accepted or acked a duplicate;
    ``partial`` = at least one shard shed / was unavailable / rejected;
    ``shed`` = no shard kept it; ``rejected`` = failed global
    validation before fan-out); ``per_shard`` is the exact per-shard
    admission status list."""

    status: str
    seq: Optional[int] = None
    backpressure: bool = False
    reason: Optional[str] = None
    per_shard: Tuple[str, ...] = ()


class ClusterDecision(NamedTuple):
    """The cluster read path's aggregate: summed intensity over the
    shards that have decided, ``post`` if any shard's latest decision
    posted, total unapplied backlog as staleness, and how many fault
    domains are reporting vs quarantined (degraded-serving visibility,
    never a blocked read)."""

    seq: int                 # min applied seq over reporting shards
    post: bool
    intensity: float
    stale_batches: int
    shards_reporting: int
    shards_quarantined: int


class _ShardSlot:
    """One fault domain's router-side bookkeeping (the runtime itself is
    replaced wholesale on crash/recovery; this slot identity persists)."""

    __slots__ = ("k", "dir", "feeds", "s_slice", "runtime", "health",
                 "fail_streak", "clean_streak", "skip_rounds",
                 "recover_failures", "crash_streak", "restart_at",
                 "outstanding", "listener", "acked_seq", "retired",
                 "start_seq")

    def __init__(self, k: int, dir: Optional[str], feeds: np.ndarray,
                 s_slice: np.ndarray, start_seq: int = 0):
        self.k = k
        self.dir = dir
        # Global feed ids this slot's RUNTIME carries (ascending) — the
        # shard geometry.  Ownership can be narrower: a migration
        # source keeps its geometry until it retires, but the router's
        # ``_owner`` map (flipped per range) decides routing.
        self.feeds = feeds
        self.s_slice = s_slice
        # The stream position this slot's runtime was born at — genesis
        # slots share the cluster start_seq; a migration destination
        # starts at the fence watermark + 1.
        self.start_seq = int(start_seq)
        # Terminal migrated-away state (see RETIRED).
        self.retired = False
        # Socket placement: the per-shard accept point (survives worker
        # restarts — the replacement dials the same address).
        self.listener: Optional[Any] = None
        # Highest seq OBSERVED applied (the ack watermark): what the
        # group-commit loss report compares against at recovery.
        self.acked_seq = -1
        # In-process: a ServingRuntime.  Worker placement: a
        # WorkerHandle presenting the same surface over the frame
        # protocol.  None = quarantined (no live fault domain).
        self.runtime: Optional[Any] = None
        self.health = HEALTHY
        self.fail_streak = 0
        self.clean_streak = 0
        self.skip_rounds = 0
        self.recover_failures = 0
        self.crash_streak = 0       # consecutive crashes since last heal
        self.restart_at = 0.0       # worker restart gate (RetryPolicy)
        # seq -> (arrival stamp, n_events): accepted but not yet applied
        # (mirrors the shard's queue + reorder window; reclassified
        # lost_on_crash if the carry dies under them)
        self.outstanding: Dict[int, Tuple[float, int]] = {}


class ServingCluster:
    """See the module docstring.  Single-writer like the per-shard
    runtime: one process owns the cluster directory."""

    def __init__(self, n_feeds: int, n_shards: int,
                 dir: Optional[str] = None, q: float = 1.0,
                 s_sink: Optional[np.ndarray] = None, seed: int = 0,
                 start_seq: int = 0, snapshot_every: int = 8,
                 reorder_window: int = 8, queue_capacity: int = 64,
                 max_batch_events: int = 256, fsync_every_n: int = 1,
                 flush_mode: str = "sync",
                 max_unflushed_records: int = 64,
                 max_flush_delay_ms: float = 50.0, coalesce: int = 1,
                 journal_format: Optional[str] = None,
                 replication_factor: int = 0,
                 replication_quorum: Optional[int] = None,
                 replication_mode: str = "thread",
                 placement: str = "in-process",
                 restart_policy: Optional[RetryPolicy] = None,
                 worker_request_timeout_s: float = 30.0,
                 worker_open_timeout_s: float = 300.0,
                 worker_heartbeat_every_s: float = 1.0,
                 worker_heartbeat_timeout_s: float = 30.0,
                 worker_read_timeout_s: float = 5.0,
                 worker_reattach_grace_s: float = 8.0,
                 listen_host: str = "127.0.0.1",
                 token: Optional[str] = None,
                 external_workers: bool = False,
                 clock=time.monotonic,
                 auto_recover: bool = True, _open_runtimes: bool = True):
        if placement not in PLACEMENTS:
            raise ValueError(f"placement must be one of {PLACEMENTS}, "
                             f"got {placement!r}")
        if placement in WORKER_PLACEMENTS and dir is None:
            raise ValueError(
                f"placement={placement!r} needs a cluster directory — a "
                f"worker subprocess owns its shard's on-disk state; an "
                f"in-memory fault domain cannot leave the process")
        self.n_feeds = int(n_feeds)
        self.n_shards = int(n_shards)
        self.dir = dir
        self.q = float(q)
        self.seed = int(seed)
        self.start_seq = int(start_seq)
        self.snapshot_every = int(snapshot_every)
        self.reorder_window = int(reorder_window)
        self.queue_capacity = int(queue_capacity)
        self.max_batch_events = int(max_batch_events)
        if int(fsync_every_n) < 1:
            raise ValueError(
                f"fsync_every_n must be >= 1, got {fsync_every_n}")
        self.fsync_every_n = int(fsync_every_n)
        from .journal import FLUSH_MODES as _FLUSH_MODES

        if flush_mode not in _FLUSH_MODES:
            raise ValueError(f"flush_mode must be one of "
                             f"{_FLUSH_MODES}, got {flush_mode!r}")
        self.flush_mode = str(flush_mode)
        self.max_unflushed_records = int(max_unflushed_records)
        self.max_flush_delay_ms = float(max_flush_delay_ms)
        if int(coalesce) < 1:
            raise ValueError(f"coalesce must be >= 1, got {coalesce}")
        self.coalesce = int(coalesce)
        if int(replication_factor) < 0:
            raise ValueError(f"replication_factor must be >= 0, got "
                             f"{replication_factor}")
        self.journal_format = journal_format
        self.replication_factor = int(replication_factor)
        self.replication_quorum = (None if replication_quorum is None
                                   else int(replication_quorum))
        self.replication_mode = str(replication_mode)
        self.placement = placement
        self.restart_policy = restart_policy or DEFAULT_RESTART_POLICY
        self._restart_rng = self.restart_policy.rng()
        self.worker_request_timeout_s = float(worker_request_timeout_s)
        self.worker_open_timeout_s = float(worker_open_timeout_s)
        self.worker_heartbeat_every_s = float(worker_heartbeat_every_s)
        self.worker_heartbeat_timeout_s = float(
            worker_heartbeat_timeout_s)
        self.worker_read_timeout_s = float(worker_read_timeout_s)
        self.worker_reattach_grace_s = float(worker_reattach_grace_s)
        self.listen_host = str(listen_host)
        # The per-cluster socket credential: hello frames must carry it
        # (and, on reattach, the same pid) or the connection is refused.
        self.token = (token if token is not None
                      else os.urandom(16).hex())
        if external_workers and placement != "sockets":
            raise ValueError(
                f"external_workers=True needs placement='sockets' "
                f"(only a TCP listener can adopt a worker someone else "
                f"spawned), got placement={placement!r}")
        self.external_workers = bool(external_workers)
        self.auto_recover = bool(auto_recover)
        self._clock = clock
        s = (np.ones(n_feeds) if s_sink is None
             else np.asarray(s_sink, np.float64))
        if s.shape != (self.n_feeds,):
            raise ValueError(
                f"s_sink must have shape ({n_feeds},), got {s.shape}")
        # Router-side copy of the global baseline sink vector (each
        # runtime holds its own live slice) — grows with add_edges.
        self._sink = s

        self._assign = partition(self.n_feeds, self.n_shards)
        # Live ownership map: assign is the GENESIS partition (part of
        # the directory identity, immutable); _owner is what routing
        # uses, rewritten by journaled topology flips (-1 = dropped
        # edge, -2 = added edge awaiting its slot attach).
        self._owner = self._assign.copy()
        # local index of each global feed within its owning shard
        self._local_index = np.empty(self.n_feeds, np.int32)
        self._slots: List[_ShardSlot] = []
        for k in range(self.n_shards):
            feeds = np.flatnonzero(self._assign == k)
            self._local_index[feeds] = np.arange(len(feeds),
                                                 dtype=np.int32)
            sdir = (None if dir is None
                    else os.path.join(dir, f"shard-{k:04d}"))
            self._slots.append(_ShardSlot(k, sdir, feeds, s[feeds],
                                          start_seq=self.start_seq))
        # Elastic-topology protocol state + journal (serving.topology);
        # the log opens lazily on the first topology mutation.
        self._topo = _topology.TopologyState()
        self._topo_log: Optional[_topology.TopologyLog] = None

        if dir is not None:
            os.makedirs(dir, exist_ok=True)
            self._check_or_write_config()
            tlog = os.path.join(dir, _topology.TOPOLOGY_LOG)
            if _open_runtimes and os.path.exists(tlog) \
                    and os.path.getsize(tlog) > 0:
                raise ValueError(
                    f"cluster dir {dir} carries topology records — its "
                    f"shard layout evolved past the genesis config "
                    f"this constructor would build; use "
                    f"ServingCluster.recover({dir!r}) instead")

        self.metrics = ClusterMetrics(self.n_shards, clock=clock)
        self._fault = _faultinject.shard_fault()
        if self._fault is not None and self._fault.shard >= self.n_shards:
            # faultinject's contract: a spec that can never fire dies
            # loudly, not as a vacuously-green chaos run.
            raise ValueError(
                f"RQ_FAULT targets shard {self._fault.shard} but this "
                f"cluster has {self.n_shards} shard(s) (valid: 0.."
                f"{self.n_shards - 1}) — the fault could never fire")
        if self._fault is not None and self._worker_mode:
            raise ValueError(
                f"RQ_FAULT=shard:{self._fault.mode} is applied by the "
                f"IN-PROCESS router and could never fire under "
                f"placement={self.placement!r} — use the worker:* kinds "
                f"(the worker child injures itself at the same seqs)")
        wfault = _faultinject.worker_fault()
        if wfault is not None:
            if not self._worker_mode:
                raise ValueError(
                    f"RQ_FAULT=worker:{wfault.mode} targets an "
                    f"out-of-process shard worker but this cluster runs "
                    f"placement={self.placement!r} — the fault could "
                    f"never fire")
            if wfault.shard >= self.n_shards:
                raise ValueError(
                    f"RQ_FAULT targets worker shard {wfault.shard} but "
                    f"this cluster has {self.n_shards} shard(s) (valid: "
                    f"0..{self.n_shards - 1}) — the fault could never "
                    f"fire")
        nfault = _faultinject.net_fault()
        if nfault is not None:
            if self.placement != "sockets":
                raise ValueError(
                    f"RQ_FAULT=net:{nfault.mode} targets a SOCKET "
                    f"worker's connection but this cluster runs "
                    f"placement={self.placement!r} — the fault could "
                    f"never fire (pipes cannot partition)")
            if nfault.shard >= self.n_shards:
                raise ValueError(
                    f"RQ_FAULT targets net shard {nfault.shard} but "
                    f"this cluster has {self.n_shards} shard(s) (valid: "
                    f"0..{self.n_shards - 1}) — the fault could never "
                    f"fire")
        self._fault_spent = False
        self._wedge_left = WEDGE_FIRES

        if _open_runtimes:
            if self.external_workers:
                # The operator's workers dial in later
                # (adopt_external_worker); create the listeners now so
                # remote_worker_commands() can print the addresses.
                from .transport import Listener

                for slot in self._slots:
                    slot.listener = Listener(host=self.listen_host,
                                             clock=self._clock)
            elif self._worker_mode:
                self._open_workers(recover=False)
            else:
                for slot in self._slots:
                    slot.runtime = self._fresh_runtime(slot)

    @property
    def _worker_mode(self) -> bool:
        """True when shards live out of process (pipe or socket
        placement) — the router drives WorkerHandle surfaces."""
        return self.placement in WORKER_PLACEMENTS

    # ---- construction / config identity ----

    def _config(self) -> Dict[str, Any]:
        return {
            "n_feeds": self.n_feeds, "n_shards": self.n_shards,
            "q": self.q, "s_sink": [float(x) for x in self._sink],
            "seed": self.seed, "start_seq": self.start_seq,
            "snapshot_every": self.snapshot_every,
            "reorder_window": self.reorder_window,
            "queue_capacity": self.queue_capacity,
            "max_batch_events": self.max_batch_events,
            "partition_version": PARTITION_VERSION,
            # Durability/throughput knobs — recorded so recover()
            # reuses them, EXCLUDED from the identity refusal below:
            # group commit changes when records hit media and coalescing
            # changes how many batches share a dispatch/record, never
            # what either says.  (placement is likewise not identity:
            # in-process, worker, and socket modes are interchangeable
            # over the same directory.)
            "fsync_every_n": self.fsync_every_n,
            "flush_mode": self.flush_mode,
            "max_unflushed_records": self.max_unflushed_records,
            "max_flush_delay_ms": self.max_flush_delay_ms,
            "coalesce": self.coalesce,
            # Likewise non-identity: the journal encoding and the
            # replication group shape change where/when records
            # persist, never what they say.
            "journal_format": self.journal_format,
            "replication_factor": self.replication_factor,
            "replication_quorum": self.replication_quorum,
            "replication_mode": self.replication_mode,
        }

    def _check_or_write_config(self) -> None:
        cfg_path = os.path.join(self.dir, _CLUSTER_CONFIG)
        cfg = self._config()
        if os.path.exists(cfg_path):
            # Same refusal contract as the per-shard config: the stored
            # config is the directory's identity — a silently different
            # partition/seed would route edges into the wrong journals.
            stored = _integrity.read_json(cfg_path, schema=CLUSTER_SCHEMA)
            for field in ("n_feeds", "n_shards", "q", "s_sink", "seed",
                          "start_seq", "max_batch_events",
                          "partition_version"):
                if stored.get(field) != cfg[field]:
                    raise ValueError(
                        f"cluster dir {self.dir} was created with "
                        f"{field}={stored.get(field)!r} but this cluster "
                        f"was constructed with {field}={cfg[field]!r} — "
                        f"edges would route to the wrong shards / replay "
                        f"would diverge; recover() with the stored "
                        f"config, reshard(), or use a fresh directory")
        else:
            _integrity.write_json(cfg_path, cfg, schema=CLUSTER_SCHEMA)

    def _fresh_runtime(self, slot: _ShardSlot) -> ServingRuntime:
        return ServingRuntime(
            n_feeds=len(slot.feeds), q=self.q, s_sink=slot.s_slice,
            seed=shard_seed(self.seed, slot.k), dir=slot.dir,
            start_seq=slot.start_seq, snapshot_every=self.snapshot_every,
            reorder_window=self.reorder_window,
            queue_capacity=self.queue_capacity,
            max_batch_events=self.max_batch_events,
            fsync_every_n=self.fsync_every_n,
            flush_mode=self.flush_mode,
            max_unflushed_records=self.max_unflushed_records,
            max_flush_delay_ms=self.max_flush_delay_ms,
            coalesce=self.coalesce,
            journal_format=self.journal_format,
            replication_factor=self.replication_factor,
            replication_quorum=self.replication_quorum,
            replication_mode=self.replication_mode, clock=self._clock)

    # ---- worker placement plumbing ----

    def _worker_config(self, slot: _ShardSlot) -> Dict[str, Any]:
        """The ``open`` request payload — the exact ServingRuntime
        constructor args :meth:`_fresh_runtime` would use, so the two
        placements build bit-identical shard state."""
        return {"n_feeds": int(len(slot.feeds)), "q": self.q,
                "s_sink": [float(x) for x in slot.s_slice],
                "seed": shard_seed(self.seed, slot.k),
                "start_seq": slot.start_seq,
                "snapshot_every": self.snapshot_every,
                "reorder_window": self.reorder_window,
                "queue_capacity": self.queue_capacity,
                "max_batch_events": self.max_batch_events,
                "fsync_every_n": self.fsync_every_n,
                "flush_mode": self.flush_mode,
                "max_unflushed_records": self.max_unflushed_records,
                "max_flush_delay_ms": self.max_flush_delay_ms,
                "coalesce": self.coalesce,
                "journal_format": self.journal_format,
                "replication_factor": self.replication_factor,
                "replication_quorum": self.replication_quorum,
                "replication_mode": self.replication_mode}

    def _spawn_worker(self, slot: _ShardSlot) -> "WorkerHandle":  # noqa: F821
        from .worker import SocketWorkerHandle, WorkerHandle

        if self.placement == "sockets":
            if slot.listener is None:
                from .transport import Listener

                slot.listener = Listener(host=self.listen_host,
                                         clock=self._clock)
            return SocketWorkerHandle.spawn_socket(
                slot.dir, slot.k, slot.listener, self.token,
                heartbeat_every_s=self.worker_heartbeat_every_s,
                request_timeout_s=self.worker_request_timeout_s,
                open_timeout_s=self.worker_open_timeout_s,
                read_timeout_s=self.worker_read_timeout_s,
                clock=self._clock)
        return WorkerHandle.spawn(
            slot.dir, slot.k,
            heartbeat_every_s=self.worker_heartbeat_every_s,
            request_timeout_s=self.worker_request_timeout_s,
            open_timeout_s=self.worker_open_timeout_s,
            read_timeout_s=self.worker_read_timeout_s,
            clock=self._clock)

    def remote_worker_commands(self) -> List[Dict[str, Any]]:
        """The REMOTE-SPAWN recipe (socket placement): one entry per
        shard — the argv to run on any host that can reach this
        router's listeners, plus the env var carrying the cluster token
        (value supplied out of band, never printed).  The shard
        directory path in the argv is as THIS host sees it; a remote
        worker needs the same path visible (shared filesystem) or a
        synced copy."""
        if self.placement != "sockets":
            raise ValueError(
                f"remote spawn needs placement='sockets', this cluster "
                f"runs {self.placement!r}")
        from .transport import Listener
        from .worker import SocketWorkerHandle

        out = []
        for slot in self._slots:
            if slot.listener is None:
                slot.listener = Listener(host=self.listen_host,
                                         clock=self._clock)
            out.append({
                "shard": slot.k,
                **SocketWorkerHandle.remote_command(
                    slot.dir, slot.k, slot.listener.address,
                    self.worker_heartbeat_every_s)})
        return out

    def _open_workers(self, recover: bool) -> List[RecoveryInfo]:
        """Spawn one worker per shard and open/recover them ALL in
        flight (the fan-out parallelism the placement exists for: N
        jax imports + first compiles overlap instead of serializing).
        Any failure tears every worker down and raises — a cluster
        must come up whole or not at all."""
        infos: List[RecoveryInfo] = []
        procs: List[Any] = []
        try:
            if self.placement == "sockets":
                # Launch ALL children first, then accept each hello —
                # interpreter start + package import + dial overlap
                # across shards (the same in-flight discipline the
                # open/recover fan-out below uses).
                from .transport import Listener
                from .worker import SocketWorkerHandle

                live = [s for s in self._slots if not s.retired]
                for slot in live:
                    if slot.listener is None:
                        slot.listener = Listener(host=self.listen_host,
                                                 clock=self._clock)
                    procs.append(SocketWorkerHandle.launch(
                        slot.dir, slot.k, slot.listener, self.token,
                        heartbeat_every_s=self.worker_heartbeat_every_s))
                for slot, proc in zip(live, procs):
                    slot.runtime = SocketWorkerHandle.from_child(
                        proc, slot.k, slot.listener, self.token,
                        request_timeout_s=self.worker_request_timeout_s,
                        open_timeout_s=self.worker_open_timeout_s,
                        read_timeout_s=self.worker_read_timeout_s,
                        clock=self._clock)
            else:
                for slot in self._slots:
                    if slot.retired:
                        continue
                    slot.runtime = self._spawn_worker(slot)
            pending = []
            for slot in self._slots:
                if slot.retired:
                    continue
                h = slot.runtime
                # A slot journaled into existence mid-migration whose
                # process died before the runtime wrote config.json has
                # nothing on disk to recover — it opens fresh and the
                # resumed migration re-streams its ranges.
                use_rec = recover and slot.dir is not None \
                    and os.path.exists(
                        os.path.join(slot.dir, _SHARD_CONFIG))
                pending.append((slot, use_rec,
                                h.start_recover() if use_rec
                                else h.start_open(
                                    self._worker_config(slot))))
            for slot, use_rec, rid in pending:
                if use_rec:
                    infos.append(slot.runtime.finish_recover(rid))
                else:
                    slot.runtime.finish_open(rid)
        except (TransportError, OSError) as e:
            for slot in self._slots:
                if slot.runtime is not None:
                    slot.runtime.kill()
                    slot.runtime = None
            for proc in procs:
                # launched-but-never-adopted children (the adopt loop
                # raised before reaching them) must not outlive the
                # failed open
                if proc.poll() is None:
                    try:
                        proc.kill()
                    except OSError:
                        pass
            raise RuntimeError(
                f"worker cluster failed to "
                f"{'recover' if recover else 'open'}: "
                f"{type(e).__name__}: {e}") from e
        return infos

    @classmethod
    def recover(cls, dir: str, clock=time.monotonic,
                auto_recover: bool = True,
                placement: str = "in-process",
                restart_policy: Optional[RetryPolicy] = None,
                worker_request_timeout_s: float = 30.0,
                worker_open_timeout_s: float = 300.0,
                worker_heartbeat_every_s: float = 1.0,
                worker_heartbeat_timeout_s: float = 30.0,
                worker_read_timeout_s: float = 5.0,
                ) -> Tuple["ServingCluster", List[RecoveryInfo]]:
        """Rebuild a cluster from its directory after a crash: read the
        enveloped cluster config, then :func:`serving.service.recover`
        EVERY shard fault domain independently (each one = newest
        provable snapshot + digest-asserted journal replay).  Shards
        killed at different points recover to different seqs; the
        source's retransmit of everything past :attr:`applied_seq`
        (the cluster min) reconverges them — duplicate drop absorbs the
        rest.  ``placement`` picks where the recovered shards live (the
        directory does not care — either placement recovers the other's
        state bit-identically); with ``"workers"`` every shard recovers
        in its own subprocess, all in flight."""
        cfg = _integrity.read_json(os.path.join(dir, _CLUSTER_CONFIG),
                                   schema=CLUSTER_SCHEMA)
        if cfg.get("partition_version") != PARTITION_VERSION:
            raise ValueError(
                f"cluster dir {dir} uses partition_version="
                f"{cfg.get('partition_version')!r}, this code is "
                f"{PARTITION_VERSION} — reshard() with the old code "
                f"first")
        cl = cls(n_feeds=int(cfg["n_feeds"]),
                 n_shards=int(cfg["n_shards"]), dir=dir,
                 q=float(cfg["q"]),
                 s_sink=np.asarray(cfg["s_sink"], np.float64),
                 seed=int(cfg["seed"]), start_seq=int(cfg["start_seq"]),
                 snapshot_every=int(cfg["snapshot_every"]),
                 reorder_window=int(cfg["reorder_window"]),
                 queue_capacity=int(cfg["queue_capacity"]),
                 max_batch_events=int(cfg["max_batch_events"]),
                 fsync_every_n=int(cfg.get("fsync_every_n", 1)),
                 flush_mode=str(cfg.get("flush_mode", "sync")),
                 max_unflushed_records=int(
                     cfg.get("max_unflushed_records", 64)),
                 max_flush_delay_ms=float(
                     cfg.get("max_flush_delay_ms", 50.0)),
                 coalesce=int(cfg.get("coalesce", 1)),
                 placement=placement, restart_policy=restart_policy,
                 worker_request_timeout_s=worker_request_timeout_s,
                 worker_open_timeout_s=worker_open_timeout_s,
                 worker_heartbeat_every_s=worker_heartbeat_every_s,
                 worker_heartbeat_timeout_s=worker_heartbeat_timeout_s,
                 worker_read_timeout_s=worker_read_timeout_s,
                 clock=clock, auto_recover=auto_recover,
                 _open_runtimes=False)
        # Replay the topology log BEFORE opening runtimes: every slot
        # added / ownership flip / retirement since genesis re-applies
        # in journal order (the param-epoch replay pattern, lifted to
        # the shard layout itself).
        records, _torn = _topology.read_topology_log(
            os.path.join(dir, _topology.TOPOLOGY_LOG))
        for rec in records:
            cl._apply_topo_record(rec, recovering=True)
        if placement in WORKER_PLACEMENTS:
            return cl, cl._open_workers(recover=True)
        infos: List[RecoveryInfo] = []
        for slot in cl._slots:
            if slot.retired:
                continue
            if os.path.exists(os.path.join(slot.dir, _SHARD_CONFIG)):
                rt, info = _recover_runtime(slot.dir, clock=clock)
                slot.runtime = rt
                infos.append(info)
            else:
                # Journaled into existence but crashed before its
                # runtime persisted anything — open fresh; the resumed
                # migration re-streams whatever it was owed.
                slot.runtime = cl._fresh_runtime(slot)
        return cl, infos

    # ---- routing: the ingest path ----

    def _split_batch(self, batch: EventBatch) -> List[EventBatch]:
        """One sub-batch per shard in ONE pass over the events (a
        per-shard boolean mask would make the measured ingest path
        O(n_shards x events) per global batch): stable-sort the events
        by owning shard — intra-shard event order is preserved — and
        slice the contiguous runs."""
        seq = int(batch.seq)
        if len(batch.feeds) == 0:
            empty = EventBatch(seq, np.empty(0, np.float64),
                               np.empty(0, np.int32))
            return [empty] * self.n_shards
        assign = self._owner[batch.feeds]
        order = np.argsort(assign, kind="stable")
        times_s = batch.times[order]
        local_s = self._local_index[batch.feeds[order]]
        bounds = np.searchsorted(assign[order],
                                 np.arange(self.n_shards + 1))
        return [EventBatch(seq, times_s[bounds[k]:bounds[k + 1]],
                           local_s[bounds[k]:bounds[k + 1]])
                for k in range(self.n_shards)]

    def submit(self, batch: EventBatch) -> ClusterAdmission:
        """Admit one GLOBAL micro-batch: validate once, fan out one
        sub-batch per shard under the global seq (empty slices included
        — every shard's journal tracks the full stream position).  Never
        raises on bad input; a quarantined shard's slice is shed with
        its seq recorded (``shed_unavailable``) so the source
        retransmits it after recovery."""
        with _telemetry.span("cluster.submit") as tsp:
            adm = self._submit(batch)
            tsp.set(status=adm.status)
            return adm

    def _submit(self, batch: EventBatch) -> ClusterAdmission:
        try:
            batch = validate_batch(batch, self.n_feeds,
                                   max_events=self.max_batch_events)
        except IngestError as e:
            # Rejected before fan-out: one rejected sub-outcome per
            # shard keeps the ledger's sub-batch units uniform.
            self.metrics.global_rejected += 1
            for k in range(self.n_shards):
                self.metrics.observe_submitted(k)
                self.metrics.observe_rejected(k)
            return ClusterAdmission(
                "rejected", seq=e.seq, reason=str(e),
                per_shard=("rejected",) * self.n_shards)
        reason = self._route_block(batch)
        if reason is not None:
            if reason.startswith("fenced"):
                # Refused BEFORE fan-out: nothing entered any shard
                # ledger, so the closed accounting identity is
                # untouched — the source just retransmits after the
                # flip lands.
                self.metrics.observe_fenced_retry()
                return ClusterAdmission("fenced", seq=int(batch.seq),
                                        reason=reason)
            self.metrics.global_rejected += 1
            for k in range(self.n_shards):
                self.metrics.observe_submitted(k)
                self.metrics.observe_rejected(k)
            return ClusterAdmission(
                "rejected", seq=int(batch.seq), reason=reason,
                per_shard=("rejected",) * self.n_shards)
        seq = int(batch.seq)
        subs = self._split_batch(batch)
        now = self._clock()
        statuses: List[Optional[str]] = [None] * self.n_shards
        backpressure = False
        if self._worker_mode:
            # Fan the sub-batches out to EVERY live worker before
            # collecting any admission — N journal fsyncs in flight at
            # once (the parallel-ingest win).  A worker that dies
            # mid-submit is torn down and its slice shed-with-seq: the
            # sub-batch was never acked, so the source retransmits it
            # (if the worker did journal it first, the retransmit comes
            # back "duplicate" — an ack, absorbed).
            sent: List[Tuple[_ShardSlot, int]] = []
            for slot in self._slots:
                if slot.retired:
                    statuses[slot.k] = "retired"
                    continue
                self.metrics.observe_submitted(slot.k)
                if slot.runtime is None:
                    statuses[slot.k] = "unavailable"
                    self.metrics.observe_shed_unavailable(slot.k, seq)
                    backpressure = True
                    continue
                try:
                    sent.append((slot,
                                 slot.runtime.start_submit(subs[slot.k])))
                except TransportError as e:
                    # A severed socket link reattaches (degrade) rather
                    # than crashing the worker; either way this round's
                    # slice is shed-with-seq and retransmit covers it.
                    self._lost_link(
                        slot, e, f"worker died on submit send: {e}")
                    statuses[slot.k] = "unavailable"
                    self.metrics.observe_shed_unavailable(slot.k, seq)
                    backpressure = True
            for slot, rid in sent:
                try:
                    adm = slot.runtime.finish_submit(rid)
                except TransportTimeout as e:
                    # Alive but past the deadline (e.g. still inside a
                    # long apply the previous poll round timed out on):
                    # degrade + backoff, never SIGKILL a busy worker.
                    # The slice is not acked — the source retransmits
                    # it and duplicate drop absorbs any overshoot if
                    # the worker did journal it before answering late.
                    self._on_timeout(slot, f"submit deadline expired: "
                                           f"{e}")
                    statuses[slot.k] = "unavailable"
                    self.metrics.observe_shed_unavailable(slot.k, seq)
                    backpressure = True
                    continue
                except TransportError as e:
                    self._lost_link(
                        slot, e, f"submit to worker failed: "
                                 f"{type(e).__name__}: {e}")
                    statuses[slot.k] = "unavailable"
                    self.metrics.observe_shed_unavailable(slot.k, seq)
                    backpressure = True
                    continue
                statuses[slot.k] = adm.status
                backpressure |= self._note_admission(
                    slot, adm, subs[slot.k].n_events, seq, now)
        else:
            for slot in self._slots:
                if slot.retired:
                    statuses[slot.k] = "retired"
                    continue
                self.metrics.observe_submitted(slot.k)
                if slot.runtime is None:
                    statuses[slot.k] = "unavailable"
                    self.metrics.observe_shed_unavailable(slot.k, seq)
                    backpressure = True
                    continue
                sub = subs[slot.k]
                adm = slot.runtime.submit(sub, _validated=True)
                statuses[slot.k] = adm.status
                backpressure |= self._note_admission(
                    slot, adm, sub.n_events, seq, now)
        live = [st for st in statuses if st != "retired"]
        if all(st in ("accepted", "duplicate") for st in live):
            status = "accepted"
        elif all(st in ("shed", "unavailable") for st in live):
            status = "shed"
        else:
            status = "partial"
        return ClusterAdmission(status, seq=seq,
                                backpressure=backpressure,
                                per_shard=tuple(statuses))

    def submit_many(self, batches: List[EventBatch]
                    ) -> List[ClusterAdmission]:
        """Admit a whole ROUND of global micro-batches with ONE frame
        round-trip per shard (``submit_many`` op) instead of one per
        batch — the batched-frame half of the wire-speed ingest path.
        Semantically identical to calling :meth:`submit` per batch (same
        validation, same per-shard admissions, same ledger); only the
        transport amortization differs.  In-process placement simply
        loops (there is no frame to batch)."""
        if not batches:
            return []
        with _telemetry.span("cluster.submit_round") as tsp:
            tsp.set(n=len(batches))
            return self._submit_many(batches)

    def _submit_many(self, batches: List[EventBatch]
                     ) -> List[ClusterAdmission]:
        if not self._worker_mode:
            return [self.submit(b) for b in batches]
        prepared = []  # (batch|None, subs|None, admission-or-None)
        for batch in batches:
            try:
                v = validate_batch(batch, self.n_feeds,
                                   max_events=self.max_batch_events)
            except IngestError as e:
                self.metrics.global_rejected += 1
                for k in range(self.n_shards):
                    self.metrics.observe_submitted(k)
                    self.metrics.observe_rejected(k)
                prepared.append((None, None, ClusterAdmission(
                    "rejected", seq=e.seq, reason=str(e),
                    per_shard=("rejected",) * self.n_shards)))
                continue
            reason = self._route_block(v)
            if reason is not None:
                if reason.startswith("fenced"):
                    self.metrics.observe_fenced_retry()
                    prepared.append((None, None, ClusterAdmission(
                        "fenced", seq=int(v.seq), reason=reason)))
                else:
                    self.metrics.global_rejected += 1
                    for k in range(self.n_shards):
                        self.metrics.observe_submitted(k)
                        self.metrics.observe_rejected(k)
                    prepared.append((None, None, ClusterAdmission(
                        "rejected", seq=int(v.seq), reason=reason,
                        per_shard=("rejected",) * self.n_shards)))
                continue
            prepared.append((v, self._split_batch(v), None))
        now = self._clock()
        n_valid = sum(1 for b, _, _ in prepared if b is not None)
        statuses: Dict[int, List[Optional[str]]] = {}
        bps: Dict[int, List[bool]] = {}
        sent: List[Tuple[_ShardSlot, int]] = []

        def shed_round(slot: _ShardSlot) -> None:
            """One shard's whole-round failure outcome — the single
            place the unavailable accounting lives (four failure paths
            share it; a missed copy would skew the closed identity)."""
            statuses[slot.k] = ["unavailable"] * n_valid
            bps[slot.k] = [True] * n_valid
            for b, _, _ in prepared:
                if b is not None:
                    self.metrics.observe_shed_unavailable(
                        slot.k, int(b.seq))

        for slot in self._slots:
            if slot.retired:
                statuses[slot.k] = ["retired"] * n_valid
                bps[slot.k] = [False] * n_valid
                continue
            for _ in range(n_valid):
                self.metrics.observe_submitted(slot.k)
            if slot.runtime is None:
                shed_round(slot)
                continue
            shard_batches = [subs[slot.k] for b, subs, _ in prepared
                             if b is not None]
            try:
                sent.append((slot, slot.runtime.start_submit_many(
                    shard_batches)))
            except TransportError as e:
                self._lost_link(slot, e,
                                f"worker died on submit_many send: {e}")
                shed_round(slot)
        for slot, rid in sent:
            try:
                adms = slot.runtime.finish_submit_many(rid)
            except TransportTimeout as e:
                self._on_timeout(slot,
                                 f"submit_many deadline expired: {e}")
                shed_round(slot)
                continue
            except TransportError as e:
                self._lost_link(slot, e,
                                f"submit_many to worker failed: "
                                f"{type(e).__name__}: {e}")
                shed_round(slot)
                continue
            sts: List[Optional[str]] = []
            bp_list: List[bool] = []
            i = 0
            for b, subs, _ in prepared:
                if b is None:
                    continue
                adm = adms[i]
                i += 1
                sts.append(adm.status)
                bp_list.append(self._note_admission(
                    slot, adm, subs[slot.k].n_events, int(b.seq), now))
            statuses[slot.k] = sts
            bps[slot.k] = bp_list
        out: List[ClusterAdmission] = []
        vi = 0
        for b, _subs, rejected in prepared:
            if rejected is not None:
                out.append(rejected)
                continue
            per = tuple(statuses[k][vi] for k in range(self.n_shards))
            # Per-BATCH backpressure, same as submit(): only the
            # batches whose own admissions signalled it — a source must
            # not over-throttle a whole round for one shed slice.
            bp = any(bps[k][vi] for k in range(self.n_shards))
            vi += 1
            live = [st for st in per if st != "retired"]
            if all(st in ("accepted", "duplicate") for st in live):
                status = "accepted"
            elif all(st in ("shed", "unavailable") for st in live):
                status = "shed"
            else:
                status = "partial"
            out.append(ClusterAdmission(status, seq=int(b.seq),
                                        backpressure=bp,
                                        per_shard=per))
        return out

    def _note_admission(self, slot: _ShardSlot, adm, n_events: int,
                        seq: int, now: float) -> bool:
        """Ledger one sub-batch admission (both placements share this
        exactly); returns the admission's backpressure bit."""
        if adm.status == "accepted":
            if seq in slot.outstanding:
                # retransmit of a batch still held in the shard's
                # reorder window: redundant delivery, not durable —
                # the ledger counts the extra submission a duplicate
                self.metrics.observe_duplicate(slot.k)
            else:
                slot.outstanding[seq] = (now, int(n_events))
        elif adm.status == "duplicate":
            self.metrics.observe_duplicate(slot.k)
        elif adm.status == "shed":
            self.metrics.observe_shed_queue(slot.k, seq)
        else:  # "rejected" — per-shard validation (shouldn't happen
            self.metrics.observe_rejected(slot.k)  # post-global)
        return bool(adm.backpressure)

    # ---- routing: the apply path (health-aware dispatch) ----

    def poll(self, max_batches_per_shard: Optional[int] = None
             ) -> Dict[int, List[Any]]:
        """One dispatch round: every serviceable shard applies up to
        ``max_batches_per_shard`` queued sub-batches (all, by default),
        one at a time so faults and health observations land at exact
        sequence numbers.  Wedged shards back off (skip rounds,
        exponential, capped); quarantined shards auto-recover in place
        when ``auto_recover`` (healthy shards are NOT blocked on it —
        they were already drained by the time recovery runs, and their
        admissions never depend on the dead shard).  Returns the
        per-shard decision lists."""
        with _telemetry.span("cluster.poll") as tsp:
            out = self._poll(max_batches_per_shard)
            tsp.set(applied=sum(len(v) for v in out.values()))
            return out

    def _poll(self, max_batches_per_shard: Optional[int] = None
              ) -> Dict[int, List[Any]]:
        if self._worker_mode:
            return self._poll_workers(max_batches_per_shard)
        out: Dict[int, List[Any]] = {}
        for slot in self._slots:
            if slot.retired:
                out[slot.k] = []
                continue
            if slot.runtime is None:
                if self.auto_recover and slot.dir is not None \
                        and slot.skip_rounds == 0:
                    self._try_auto_recover(slot)
                elif slot.skip_rounds > 0:
                    slot.skip_rounds -= 1
                if slot.runtime is None:
                    out[slot.k] = []
                    continue
            if slot.skip_rounds > 0:
                slot.skip_rounds -= 1  # backoff: the wedged shard rests
                out[slot.k] = []
                continue
            out[slot.k] = self._poll_slot(slot, max_batches_per_shard)
        return out

    def _poll_workers(self, max_batches: Optional[int]
                      ) -> Dict[int, List[Any]]:
        """One worker-placement dispatch round: liveness-check every
        slot (child exit = crash, stale heartbeat = hang), fan
        ``poll`` out to every serviceable worker, THEN collect — the
        N workers apply and fsync concurrently while the router waits
        once.  Transport failures classify onto the same health state
        machine as in-process faults; a quarantined worker restarts
        under the RetryPolicy gate and recovers from its own journal
        while the survivors' requests are already in flight."""
        out: Dict[int, List[Any]] = {k: [] for k in
                                     range(self.n_shards)}
        dispatch: List[Tuple[_ShardSlot, int]] = []
        for slot in self._slots:
            if slot.retired:
                continue
            if slot.runtime is None:
                if self.auto_recover \
                        and self._clock() >= slot.restart_at:
                    self._try_auto_recover(slot)
                if slot.runtime is None:
                    continue
            if slot.skip_rounds > 0:
                slot.skip_rounds -= 1  # backoff: the wedged shard rests
                continue
            h = slot.runtime
            # Crash detection via child exit: cheaper and earlier than
            # discovering the EOF on the next request.
            if not h.alive():
                self._crash_slot(
                    slot, f"worker process exited "
                          f"rc={h.proc.returncode}")
                continue
            h.drain_beats()
            self._salvage_stale(slot, out[slot.k])
            # Heartbeat-staleness hang detection: the worker owes a
            # beat every worker_heartbeat_every_s even when idle — an
            # age past the bound means the child is alive but wedged.
            age = h.beat_age()
            if age > self.worker_heartbeat_timeout_s:
                self._on_timeout(
                    slot, f"worker heartbeat stale {age:.1f}s > "
                          f"{self.worker_heartbeat_timeout_s:.1f}s")
                continue
            try:
                dispatch.append((slot, h.start_poll(max_batches)))
            except TransportError as e:
                self._lost_link(slot, e,
                                f"worker died on poll send: {e}")
        for slot, rid in dispatch:
            h = slot.runtime
            try:
                ds = h.finish_poll(rid)
            except TransportTimeout:
                # The wedged-worker shape: the request deadline expired
                # with the child still running.  The sub-batch stays
                # queued worker-side; degrade, back off, retry — and
                # a late answer is salvaged by id, never misattributed.
                self._on_timeout(
                    slot, f"poll deadline "
                          f"{h.request_timeout_s:.1f}s expired "
                          f"(worker alive but unresponsive)")
                continue
            except TransportEOF as e:
                # A dead LINK is not yet a dead WORKER under socket
                # placement: give the same live process a grace window
                # to redial (partition heal), then retry the response
                # wait once — a reconnect-mode worker answers on the
                # new connection; a partition-mode worker's response is
                # gone and the retry times out (resync heals later).
                if not self._lost_link(
                        slot, e,
                        f"poll failed: {type(e).__name__}: {e}"):
                    continue
                try:
                    # Short retry deadline: only a clean link flap
                    # (net:reconnect) re-delivers the response; a real
                    # partition ate it and resync heals that — the
                    # whole round must not stall on the apply budget.
                    ds = h.finish_poll(rid, timeout_s=h.read_timeout_s)
                except TransportTimeout:
                    # The partition ate the response — the EXPECTED
                    # outcome, already paid for by the reattach's
                    # timeout strike; a second strike here would burn
                    # 2/3 of the quarantine budget per episode.  Resync
                    # recovers the journaled decisions.
                    self._maybe_resync(slot, out[slot.k])
                    continue
                except TransportError as e2:
                    self._crash_slot(
                        slot, f"poll failed after reattach: "
                              f"{type(e2).__name__}: {e2}")
                    continue
            except TransportError as e:
                # FrameError (poisoned byte stream) or WorkerOpError
                # (the worker's runtime raised): the fault domain
                # cannot be trusted mid-stream — SIGKILL + quarantine,
                # recovery from durable state only.
                self._crash_slot(
                    slot,
                    f"poll failed: {type(e).__name__}: {e}")
                continue
            self._observe_decisions(slot, ds, out[slot.k], clean=True)
            self._salvage_stale(slot, out[slot.k])
            self._maybe_resync(slot, out[slot.k])
        return out

    def _lost_link(self, slot: _ShardSlot, e: Exception,
                   reason: str, wait: bool = True) -> bool:
        """Classify a transport failure: under socket placement an EOF
        from a still-running worker gets a reattach grace (the worker
        redials under its RetryPolicy) — heal as a TIMEOUT (degrade,
        probation) and resync, never a journal recovery.  Everything
        else is a crash.  Returns True iff the link was reattached.

        ``wait=False`` is the READ-path contract (decide/status/
        digest): those ops are bounded by the short read deadline, so
        they may only adopt an ALREADY-redialed worker (near-zero
        grace) — a still-down link degrades the read immediately (one
        fewer reporter) and the next poll round pays the full grace."""
        h = slot.runtime
        if (isinstance(e, TransportEOF) and h is not None
                and getattr(h, "listener", None) is not None
                and h.alive()):
            grace = self.worker_reattach_grace_s if wait else 0.05
            if h.try_reattach(grace):
                self.metrics.observe_reattach(slot.k)
                self._on_timeout(slot, f"link lost, worker "
                                       f"reattached: {reason}")
                return True
            if not wait:
                # Alive but not yet redialed: degrade this read, leave
                # the worker for the poll round's full-grace reattach.
                self._on_timeout(slot, f"link down (read path): "
                                       f"{reason}")
                return False
        self._crash_slot(slot, reason)
        return False

    def _maybe_resync(self, slot: _ShardSlot, into: List[Any]) -> None:
        """Heal the ledger after lost response frames: any outstanding
        seq at or below the worker's last reported ``applied_seq`` was
        applied+journaled worker-side but its decisions never reached
        the router (net drop / partition / reconnect).  Pull them from
        the worker's recent-ring (``replay_decisions``); an incomplete
        ring sends the shard to the journal-recovery path rather than
        trusting a hole."""
        h = slot.runtime
        if h is None or not slot.outstanding:
            return
        top = getattr(h, "last_polled_seq", None)
        if top is None:
            return
        missed = [s for s in slot.outstanding if s <= top]
        if not missed:
            return
        try:
            ds, complete = h.replay_decisions(min(missed) - 1)
        except TransportError as e:
            self._lost_link(slot, e,
                            f"resync failed: {type(e).__name__}: {e}")
            return
        if not complete:
            self._crash_slot(
                slot, f"resync ring incomplete for seqs {missed} — "
                      f"recovering from the journal instead")
            return
        ds = [d for d in ds if int(d.seq) in slot.outstanding]
        self.metrics.observe_resync(slot.k, len(ds))
        # Late facts, not health evidence (clean=False) — the shard
        # heals on in-deadline replies.
        self._observe_decisions(slot, ds, into, clean=False)

    def _observe_decisions(self, slot: _ShardSlot, decisions: List[Any],
                           into: List[Any], clean: bool) -> None:
        """Ledger applied decisions (both placements share this
        exactly): pop the outstanding seq, observe latency/events,
        collect the decision, and count clean applies toward heal."""
        for d in decisions:
            arrival = slot.outstanding.pop(int(d.seq), None)
            if arrival is None:
                # This seq was never ledgered "accepted" — its
                # admission response died with the link and the slice
                # was recorded shed_unavailable.  The books are already
                # balanced (shed now, duplicate-ack on the healing
                # retransmit), so ALSO counting an apply here would
                # break the closed identity: submitted=1 but
                # shed+applied=2.
                continue
            latency = self._clock() - arrival[0]
            n_events = arrival[1]
            self.metrics.observe_applied(slot.k, n_events, d.post,
                                         latency)
            # The ack watermark: what the group-commit loss report
            # compares against when this shard next recovers.
            if int(d.seq) > slot.acked_seq:
                slot.acked_seq = int(d.seq)
            into.append(d)
            if clean:
                self._on_clean(slot)

    def _salvage_stale(self, slot: _ShardSlot,
                       into: List[Any]) -> None:
        """Ledger the decisions of poll responses that answered after
        their request timed out: the worker APPLIED and JOURNALED those
        batches, so the router must observe them or the accounting
        identity would leak.  Late answers are not evidence of health
        (``clean=False``) — the shard heals on in-deadline replies."""
        if slot.runtime is None:
            return
        for value in slot.runtime.drain_stale_polls():
            ds = [slot.runtime._decision(d)
                  for d in value.get("decisions", [])]
            # Only seqs still outstanding: a late answer may race the
            # resync protocol (or a crash reclassification) for the
            # same seqs, and observing a seq twice would double-count
            # ``applied`` and break the closed identity.
            ds = [d for d in ds if int(d.seq) in slot.outstanding]
            self._observe_decisions(slot, ds, into, clean=False)

    def _poll_slot(self, slot: _ShardSlot,
                   max_batches: Optional[int]) -> List[Any]:
        decisions: List[Any] = []
        fault = None if self._fault_spent else self._fault
        if fault is None:
            # No shard fault armed: let the runtime drain its queue in
            # coalesced groups (one dispatch + one record per group) —
            # the in-process router only steps batch-by-batch to land
            # injected faults at exact seqs.
            try:
                ds = slot.runtime.poll(max_batches=max_batches)
            except Exception as e:  # noqa: BLE001 — apply/journal
                # failure: the fault domain can no longer be made
                # durable; quarantine it, keep the cluster serving.
                self._crash_slot(slot, f"apply failed: {e}")
                return decisions
            self._observe_decisions(slot, ds, decisions, clean=True)
            return decisions
        while max_batches is None or len(decisions) < max_batches:
            seq = slot.runtime.next_queued_seq()
            if seq is None:
                break
            if (fault is not None and fault.mode == "wedge"
                    and fault.shard == slot.k
                    and (fault.batch is None or fault.batch == seq)):
                if self._wedge_left > 0:
                    # The deadline-expiry detection point: the dispatch
                    # did not come back in time, the batch stays queued,
                    # the shard degrades and backs off.
                    self._wedge_left -= 1
                    self._on_timeout(
                        slot, f"apply deadline expired at sub-batch "
                              f"{seq} (injected wedge)")
                    break
                self._fault_spent = True
                fault = None
            try:
                ds = slot.runtime.poll(max_batches=1)
            except Exception as e:  # noqa: BLE001 — any apply/journal
                # failure means the fault domain can no longer be made
                # durable: quarantine it, keep the cluster serving.
                self._crash_slot(slot, f"apply failed: {e}")
                break
            if not ds:
                break
            d = ds[0]
            fire = (fault is not None and fault.shard == slot.k
                    and fault.mode in ("crash", "torn_journal",
                                       "corrupt_snapshot")
                    and (fault.batch is None or fault.batch == d.seq))
            if fire and fault.mode == "torn_journal":
                # The append for this batch went out torn and the shard
                # died before acknowledging: the decision never left the
                # dying fault domain, so it is NOT observed applied —
                # the seq stays outstanding and reclassifies as lost.
                self._fault_spent = True
                from .journal import tear_tail

                if slot.runtime.journal_path:
                    tear_tail(slot.runtime.journal_path)
                self._crash_slot(
                    slot, f"journal append torn at sub-batch {d.seq} "
                          f"(injected)")
                break
            self._observe_decisions(slot, [d], decisions, clean=True)
            if fire:  # crash | corrupt_snapshot: batch d.seq was acked
                self._fault_spent = True
                if fault.mode == "corrupt_snapshot":
                    self._corrupt_newest_snapshot(slot)
                self._crash_slot(
                    slot, f"{fault.mode} after sub-batch {d.seq} "
                          f"(injected)")
                break
        return decisions

    # ---- health state machine ----

    def _on_clean(self, slot: _ShardSlot) -> None:
        slot.fail_streak = 0
        if slot.health == DEGRADED:
            slot.clean_streak += 1
            if slot.clean_streak >= HEAL_AFTER:
                slot.health = HEALTHY
                slot.clean_streak = 0
                # A heal ends the crash loop: the restart backoff
                # schedule starts over at the next (unrelated) crash.
                slot.crash_streak = 0

    def _on_timeout(self, slot: _ShardSlot, reason: str) -> None:
        slot.fail_streak += 1
        slot.clean_streak = 0
        if slot.health == HEALTHY:
            slot.health = DEGRADED
        backoff = min(2 ** slot.fail_streak, MAX_BACKOFF_ROUNDS)
        self.metrics.observe_timeout(slot.k, backoff)
        if slot.fail_streak >= QUARANTINE_AFTER:
            # A shard that will not come back is presumed dead: its
            # volatile state cannot be trusted mid-apply — same teardown
            # as a crash, recovery from durable state only.
            self._crash_slot(
                slot, f"quarantined after {slot.fail_streak} "
                      f"consecutive timeouts: {reason}")
        else:
            slot.skip_rounds = backoff

    def _crash_slot(self, slot: _ShardSlot, reason: str) -> None:
        rt, slot.runtime = slot.runtime, None
        slot.health = QUARANTINED
        slot.fail_streak = slot.clean_streak = slot.skip_rounds = 0
        slot.crash_streak += 1
        if self._worker_mode:
            # Crash-loop backoff (runtime.supervisor RetryPolicy): the
            # n-th crash of a streak gates its restart delay(n) out —
            # a worker that dies on every recovery can't hot-loop the
            # spawn+jax-import cost.  A heal resets the streak.
            slot.restart_at = self._clock() + self.restart_policy.delay(
                min(slot.crash_streak, _CRASH_STREAK_CAP),
                self._restart_rng)
        if rt is not None:
            teardown = getattr(rt, "kill", None)
            if teardown is not None:
                # A worker handle: SIGKILL the real process (wedged or
                # poisoned children don't get a graceful goodbye) and
                # close the pipes.  Never raises.
                teardown()
            else:
                # In-process: releases the journal fd only — every
                # acknowledged record was already fsynced; the carry/
                # queue/reorder window are dropped un-flushed, exactly
                # the SIGKILL leave-behind.
                try:
                    rt.close()
                except OSError:
                    pass
        for seq in sorted(slot.outstanding):
            self.metrics.observe_lost_on_crash(slot.k, seq)
        slot.outstanding.clear()
        self.metrics.observe_crash(slot.k, reason)
        self._salvage_flight(slot)

    def _salvage_flight(self, slot: _ShardSlot) -> None:
        """Pull the dead fault domain's flight-recorder ring (the last
        ~N spans the process completed before it died — a SIGKILL'd
        worker's only testimony) into the crash report: the span dicts
        land on the shard's metrics block AND in this router's telemetry
        buffer, so an exported trace stitches the child's final moments
        under their original trace ids.  Best-effort by design: a
        missing or torn ring is an empty salvage, never an error in the
        crash path.  The ring file is consumed (removed) so a later,
        unrelated crash cannot re-report stale evidence."""
        if slot.dir is None:
            return
        ring = os.path.join(slot.dir, _telemetry.FLIGHT_FILENAME)
        spans = _telemetry.read_flight(ring)
        if not spans:
            return
        self.metrics.observe_flight_salvage(slot.k, spans)
        tel = _telemetry.get()
        if tel.enabled:
            tel.adopt_spans(spans)
        try:
            os.remove(ring)
        except OSError:
            pass

    def _corrupt_newest_snapshot(self, slot: _ShardSlot) -> None:
        """The ``corrupt_snapshot`` fault body: scribble every file of
        the shard's newest landed orbax step (recovery must fall back
        past it via ``latest_valid_step`` and replay more journal)."""
        if slot.dir is None:
            return
        snaps = os.path.join(slot.dir, SNAPSHOTS_DIRNAME)
        if not os.path.isdir(snaps):
            return
        steps = sorted((int(n) for n in os.listdir(snaps)
                        if n.isdigit()), reverse=True)
        if not steps:
            return
        for root, _, files in os.walk(os.path.join(snaps,
                                                   str(steps[0]))):
            for f in files:
                with open(os.path.join(root, f), "wb") as fh:
                    fh.write(b"garbage (injected corrupt_snapshot)")

    def _try_auto_recover(self, slot: _ShardSlot) -> None:
        if self.external_workers:
            return  # the operator owns the processes; adoption is
            # explicit (adopt_external_worker), never an auto-respawn
        try:
            self.recover_shard(slot.k)
        except Exception as e:  # noqa: BLE001 — a failed recovery must
            # not take down the healthy shards; back off and retry, give
            # up loudly after the bound (RECOVERY_GIVE_UP in process,
            # the RetryPolicy's max_attempts for worker restarts).
            slot.recover_failures += 1
            if self._worker_mode:
                give_up = self.restart_policy.max_attempts
                slot.restart_at = (self._clock()
                                   + self.restart_policy.delay(
                                       min(slot.crash_streak
                                           + slot.recover_failures,
                                           _CRASH_STREAK_CAP),
                                       self._restart_rng))
            else:
                give_up = RECOVERY_GIVE_UP
                slot.skip_rounds = MAX_BACKOFF_ROUNDS
            self.metrics.observe_crash(
                slot.k, f"recovery attempt {slot.recover_failures} "
                        f"failed: {e}")
            if slot.recover_failures >= give_up:
                raise RuntimeError(
                    f"shard {slot.k} failed {slot.recover_failures} "
                    f"recovery attempts (last: {e}) — the fault domain "
                    f"at {slot.dir} needs operator attention") from e

    # ---- crash / recovery (the operator surface) ----

    def kill_shard(self, k: int, reason: str = "operator kill") -> None:
        """Chaos hook: destroy shard ``k``'s volatile state exactly the
        way the ``shard:crash`` fault does (carry, queue, and reorder
        window die; fsynced journal + snapshots survive).  What the
        MTTR bench and the chaos acceptance test drive."""
        slot = self._slots[k]
        if slot.runtime is None:
            raise ValueError(f"shard {k} is already quarantined")
        self._crash_slot(slot, reason)

    def adopt_external_worker(self, k: int,
                              accept_timeout_s: float = 300.0,
                              recover: bool = False):
        """PUBLIC remote-spawn adoption (socket placement): wait for an
        operator-launched worker — another host, a container scheduler
        — to dial shard ``k``'s listener (run the
        :meth:`remote_worker_commands` recipe there), authenticate it,
        and ``open`` (fresh) or ``recover`` (existing on-disk state,
        with the group-commit loss window reported against the router's
        ack watermark) its shard.  Returns the ``RecoveryInfo`` when
        ``recover`` else None.  The cluster never SIGKILLs an adopted
        worker's process (the remote supervisor owns it); a dead one is
        quarantined until the operator adopts a replacement."""
        if self.placement != "sockets":
            raise ValueError(
                f"adopt_external_worker needs placement='sockets', "
                f"this cluster runs {self.placement!r}")
        from .transport import Listener
        from .worker import SocketWorkerHandle

        slot = self._slots[k]
        if slot.runtime is not None:
            raise ValueError(f"shard {k} already has a live worker — "
                             f"kill_shard it first")
        if slot.listener is None:
            slot.listener = Listener(host=self.listen_host,
                                     clock=self._clock)
        h = SocketWorkerHandle.await_external(
            slot.k, slot.listener, self.token,
            accept_timeout_s=accept_timeout_s,
            request_timeout_s=self.worker_request_timeout_s,
            open_timeout_s=self.worker_open_timeout_s,
            read_timeout_s=self.worker_read_timeout_s,
            clock=self._clock)
        info = None
        try:
            if recover:
                info = h.finish_recover(h.start_recover(
                    acked_seq=(slot.acked_seq if slot.acked_seq >= 0
                               else None)))
            else:
                h.finish_open(h.start_open(self._worker_config(slot)))
        except TransportError as e:
            h.kill()
            raise RuntimeError(
                f"adopted worker for shard {k} failed to "
                f"{'recover' if recover else 'open'}: "
                f"{type(e).__name__}: {e}") from e
        slot.runtime = h
        # Probation only after a crash history; a first adoption serves
        # healthy.
        slot.health = DEGRADED if slot.crash_streak else HEALTHY
        slot.fail_streak = slot.clean_streak = slot.skip_rounds = 0
        slot.recover_failures = 0
        if info is not None:
            self.metrics.observe_recovery(k, info.replayed, 0.0)
            for seq in info.lost_acked_seqs:
                self.metrics.observe_lost_in_window(k, seq)
        return info

    def partition_shard(self, k: int) -> None:
        """Chaos hook (socket placement): sever shard ``k``'s connection
        abruptly — the ROUTER side of a network partition.  The worker
        process survives with its runtime intact, redials under its
        RetryPolicy, and the next poll round reattaches it (hello pid
        must match) and resyncs the decisions the dead link ate — no
        journal replay, no bit divergence, accounting reconciles."""
        if self.placement != "sockets":
            raise ValueError(
                f"partition_shard needs placement='sockets' (a pipe "
                f"cannot partition), this cluster runs "
                f"{self.placement!r}")
        slot = self._slots[k]
        if slot.runtime is None:
            raise ValueError(f"shard {k} is quarantined — no link to "
                             f"partition")
        slot.runtime.sever_link()

    def recover_shard(self, k: int) -> RecoveryInfo:
        """Recover quarantined shard ``k`` in place: newest provable
        snapshot + digest-asserted journal replay (bit-identical carry
        and decision stream), then probation (``degraded`` until
        ``HEAL_AFTER`` clean applies).  Healthy shards are untouched —
        under worker placement they are literally other processes, so
        the replacement worker's spawn + jax import + replay never
        blocks their serving."""
        slot = self._slots[k]
        if slot.runtime is not None:
            raise ValueError(f"shard {k} is not quarantined")
        if slot.dir is None:
            raise ValueError(
                f"shard {k} has no directory — an in-memory cluster "
                f"cannot recover a crashed fault domain")
        if self.external_workers:
            raise ValueError(
                f"shard {k}'s workers are operator-spawned "
                f"(external_workers=True) — this router cannot restart "
                f"a process it does not own; launch a replacement from "
                f"remote_worker_commands() and "
                f"adopt_external_worker({k}, recover=True)")
        t0 = self._clock()
        if self._worker_mode:
            handle = self._spawn_worker(slot)
            try:
                info = handle.finish_recover(handle.start_recover(
                    acked_seq=(slot.acked_seq if slot.acked_seq >= 0
                               else None)))
            except TransportError as e:
                handle.kill()
                raise RuntimeError(
                    f"replacement worker for shard {k} failed to "
                    f"recover: {type(e).__name__}: {e}") from e
            rt = handle
        else:
            rt, info = _recover_runtime(
                slot.dir, clock=self._clock,
                acked_seq=(slot.acked_seq if slot.acked_seq >= 0
                           else None))
        ms = (self._clock() - t0) * 1e3
        slot.runtime = rt
        slot.health = DEGRADED
        slot.fail_streak = slot.clean_streak = slot.skip_rounds = 0
        slot.recover_failures = 0
        self.metrics.observe_recovery(k, info.replayed, ms)
        # The group-commit durability window a power-style crash
        # consumed: acked seqs the journal did not keep.  Recorded
        # (diagnostic, never silent), healed by the source's
        # retransmit-past-applied_seq contract — each retransmit
        # re-enters the ledger as its own (submitted, applied) pair.
        for seq in info.lost_acked_seqs:
            self.metrics.observe_lost_in_window(k, seq)
        return info

    # ---- elastic topology (live resharding + graph churn) ----

    def _uniform_applied_seq(self, why: str) -> int:
        """Every active shard's applied seq, asserted equal — the
        watermark a topology mutation anchors to."""
        seqs: Dict[int, int] = {}
        for slot in self._slots:
            if slot.retired:
                continue
            if slot.runtime is None:
                raise TopologyError(
                    f"shard {slot.k} is quarantined — "
                    f"recover_shard({slot.k}) first: {why}")
            seqs[slot.k] = int(slot.runtime.applied_seq)
        if len(set(seqs.values())) != 1:
            raise TopologyError(
                f"shards disagree on applied seq ({seqs}) — {why}; "
                f"retransmit the gap seqs and poll until uniform")
        return next(iter(seqs.values()))

    def _drain_for_topology(self, drain_rounds: int) -> int:
        for _ in range(int(drain_rounds)):
            if self.pending == 0:
                break
            self.poll()
        if self.pending:
            raise TopologyError(
                f"cluster will not drain ({self.pending} sub-batches "
                f"still pending after {drain_rounds} poll rounds) — "
                f"retransmit the gap seqs first")
        return self._uniform_applied_seq(
            "a topology mutation anchors to one uniform watermark")

    def _append_topo(self, rec: Dict[str, Any]) -> None:
        """Journal one topology record (durable BEFORE it takes
        effect — the flip the router acts on must be the flip recovery
        will replay), then apply it to the live routing state.  A
        dirless cluster keeps the topology in memory only."""
        if self._topo_log is None and self.dir is not None:
            self._topo_log = _topology.TopologyLog(
                os.path.join(self.dir, _topology.TOPOLOGY_LOG))
        if self._topo_log is not None:
            self._topo_log.append(rec)
        self._apply_topo_record(rec, recovering=False)

    def _apply_topo_record(self, rec: Dict[str, Any],
                           recovering: bool) -> None:
        """The ONE place topology records mutate router state — the
        live path and the recovery replay run the same transitions, so
        a recovered router's ownership map is bit-identical to the one
        that journaled the records.  ``recovering`` suppresses only the
        COUNTING observers (the ledger is per-router-process); the
        structural ones (``add_shard``, the epoch) always run."""
        t = self._topo
        kind = rec["kind"]
        t.note_epoch(int(rec["epoch"]))
        if kind == "add_edges":
            first, count = int(rec["first"]), int(rec["count"])
            if first != self.n_feeds:
                raise ValueError(
                    f"topology log corrupt: add_edges starts at feed "
                    f"{first} but the cluster holds {self.n_feeds}")
            self.n_feeds += count
            self._owner = np.concatenate(
                [self._owner,
                 np.full(count, -2, self._owner.dtype)])
            self._local_index = np.concatenate(
                [self._local_index, np.zeros(count, np.int32)])
            self._sink = np.concatenate(
                [self._sink, np.asarray(rec["s_sink"], np.float64)])
            if not recovering:
                self.metrics.observe_edges_added(count)
        elif kind == "add_slot":
            k = int(rec["k"])
            if k != len(self._slots):
                raise ValueError(
                    f"topology log corrupt: add_slot k={k} but the "
                    f"cluster holds {len(self._slots)} slots")
            feeds = np.asarray(rec["feeds"], np.int64)
            sdir = (None if self.dir is None
                    else os.path.join(self.dir, f"shard-{k:04d}"))
            self._slots.append(_ShardSlot(
                k, sdir, feeds, self._sink[feeds],
                start_seq=int(rec["start_seq"])))
            self.n_shards = len(self._slots)
            self.metrics.add_shard()
            # Pending-attach feeds (added by the add_edges record this
            # slot was created to serve) become live here.
            pend = feeds[self._owner[feeds] == -2]
            if len(pend):
                self._owner[pend] = k
                self._local_index[pend] = np.searchsorted(
                    feeds, pend).astype(np.int32)
        elif kind == "plan":
            t.plan = dict(rec)
            t.fences = {}
            t.flipped = set()
        elif kind == "fence":
            t.fences[int(rec["range"])] = dict(rec)
        elif kind == "flip":
            feeds = np.asarray(rec["feeds"], np.int64)
            dst = self._slots[int(rec["dst"])]
            self._owner[feeds] = dst.k
            self._local_index[feeds] = np.searchsorted(
                dst.feeds, feeds).astype(np.int32)
            t.fences.pop(int(rec["range"]), None)
            t.flipped.add(int(rec["range"]))
            if not recovering:
                self.metrics.observe_range_migrated()
        elif kind == "retire":
            slot = self._slots[int(rec["k"])]
            slot.retired = True
            slot.health = RETIRED
            if slot.runtime is not None:
                try:
                    slot.runtime.close()
                except (TransportError, OSError):
                    pass
                slot.runtime = None
            if slot.listener is not None:
                slot.listener.close()
                slot.listener = None
            slot.outstanding.clear()
        elif kind == "complete":
            t.plan = None
            t.fences = {}
            t.flipped = set()
            t.plans_completed += 1
            if not recovering:
                self.metrics.observe_plan_complete()
        elif kind == "drop_edges":
            feeds = np.asarray(rec["feeds"], np.int64)
            self._owner[feeds] = -1
            if not recovering:
                self.metrics.observe_edges_dropped(len(feeds))
        else:
            raise ValueError(
                f"unknown topology record kind {kind!r}")
        self.metrics.set_topology_epoch(t.epoch)

    def _open_slot_runtime(self, slot: _ShardSlot) -> None:
        """Bring a just-journaled slot's runtime up (fresh, pre-sized).
        Separate from :meth:`_apply_topo_record` because the RECOVERY
        replay must not open runtimes mid-replay — it rebuilds the slot
        table first and opens everything afterwards."""
        if self._worker_mode:
            h = self._spawn_worker(slot)
            try:
                h.finish_open(h.start_open(self._worker_config(slot)))
            except TransportError as e:
                h.kill()
                raise RuntimeError(
                    f"worker for new shard {slot.k} failed to open: "
                    f"{type(e).__name__}: {e}") from e
            slot.runtime = h
        else:
            slot.runtime = self._fresh_runtime(slot)

    def _route_block(self, batch: EventBatch) -> Optional[str]:
        """The admission-time topology gate: dropped feeds reject;
        a batch past the fence watermark touching a FENCED source shard
        is refused ("fenced" — the source retransmits after the flip).
        Seqs at or below ``max(watermark, source acked seq)`` pass:
        the source already applied them, so re-admission is a pure
        duplicate there — and it is exactly what lets a recovered,
        lagging destination catch up to the watermark mid-migration."""
        if len(batch.feeds) == 0:
            return None
        owners = self._owner[batch.feeds]
        if (owners < 0).any():
            bad = np.unique(batch.feeds[owners < 0])
            return (f"batch {int(batch.seq)} touches dropped feeds "
                    f"{[int(f) for f in bad[:8]]} — removed by "
                    f"drop_edges, no longer routable")
        t = self._topo
        if not t.fences:
            return None
        seq = int(batch.seq)
        for rec in t.fences.values():
            src = self._slots[int(rec["src"])]
            if seq <= max(int(rec["watermark"]), src.acked_seq):
                continue
            if (owners == src.k).any():
                return (f"fenced: seq {seq} touches shard {src.k}, "
                        f"paused for range {int(rec['range'])} handoff "
                        f"(watermark {int(rec['watermark'])}) — "
                        f"retransmit after the flip")
        return None

    @property
    def migration_pending(self) -> bool:
        return self._topo.plan is not None

    @property
    def topology_epoch(self) -> int:
        return self._topo.epoch

    def begin_reshard(self, n_shards: int,
                      range_size: Optional[int] = None,
                      drain_rounds: int = 64) -> "_topology.Migration":
        """Start a LIVE N→M grow-migration: journal the new pre-sized
        slots and the range plan, then return the resumable
        :class:`serving.topology.Migration` driver — the caller
        interleaves ``step()`` with traffic.  Only grows (existing
        runtimes never receive into their live arrays — that would
        invalidate their journaled digests); shrink via the drained,
        offline :func:`reshard`."""
        if self.migration_pending:
            raise TopologyError(
                f"plan {self._topo.plan.get('plan')!r} is still "
                f"migrating — resume_migration() and finish it first")
        if self.external_workers:
            raise TopologyError(
                "this router does not own its workers "
                "(external_workers=True) — it cannot spawn new shard "
                "processes; reshard offline instead")
        active = [s for s in self._slots if not s.retired]
        n_new = int(n_shards) - len(active)
        if n_new < 1:
            raise TopologyError(
                f"live resharding only grows ({len(active)} active "
                f"shards, asked for {int(n_shards)}) — use reshard() "
                f"(drained, offline) to shrink")
        w = self._drain_for_topology(drain_rounds)
        owned = {s.k: np.flatnonzero(self._owner == s.k)
                 for s in active}
        new_ids = [len(self._slots) + i for i in range(n_new)]
        new_feeds, ranges = _topology.plan_moves(owned, new_ids,
                                                 range_size)
        for k in new_ids:
            # A fresh runtime at start_seq=w+1 sits at applied_seq=w —
            # already level with the drained cluster, so the next
            # drain stays uniform while the new slot rides the stream.
            self._append_topo({"kind": "add_slot",
                               "epoch": self._topo.next_epoch(),
                               "k": int(k),
                               "feeds": [int(f) for f in new_feeds[k]],
                               "start_seq": int(w) + 1})
            self._open_slot_runtime(self._slots[k])
        plan_id = f"plan-{self._topo.next_epoch():06d}"
        plan = {"kind": "plan", "epoch": self._topo.next_epoch(),
                "plan": plan_id, "ranges": ranges,
                "watermark": int(w),
                "new_slots": [int(k) for k in new_ids]}
        self._append_topo(plan)
        return _topology.Migration(self, plan,
                                   fault=_faultinject.reshard_fault())

    def resume_migration(self) -> "_topology.Migration":
        """Re-arm the driver for the journaled in-flight plan (after a
        crash + recovery, or just a new driver object) — it continues
        from the first unflipped range, re-asserting the fenced
        digest."""
        if self._topo.plan is None:
            raise TopologyError("no migration is pending")
        return _topology.Migration(self, self._topo.plan,
                                   fault=_faultinject.reshard_fault())

    def add_edges(self, n: int,
                  s_sink: Optional[np.ndarray] = None,
                  drain_rounds: int = 64) -> List[int]:
        """Grow the follow graph by ``n`` new feeds under traffic:
        journal the new feed block, assign it to the least-loaded
        shards (:func:`serving.topology.churn_assign`), and materialize
        each receiving shard as a mini-migration into a fresh pre-sized
        slot (growth IS resharding — a live runtime's arrays never grow
        in place).  Returns the new feed ids."""
        if self.migration_pending:
            raise TopologyError(
                f"plan {self._topo.plan.get('plan')!r} is still "
                f"migrating — finish it before churning the graph")
        if self.external_workers:
            raise TopologyError(
                "this router does not own its workers — it cannot "
                "spawn the replacement shard add_edges needs")
        n = int(n)
        if n < 1:
            raise TopologyError(f"add_edges needs n >= 1, got {n}")
        if s_sink is None:
            s_new = np.ones(n, np.float64)
        else:
            s_new = np.asarray(s_sink, np.float64)
            if s_new.shape != (n,):
                raise TopologyError(
                    f"s_sink must have shape ({n},), got "
                    f"{s_new.shape}")
        w = self._drain_for_topology(drain_rounds)
        active = [s for s in self._slots if not s.retired]
        counts = {s.k: int((self._owner == s.k).sum())
                  for s in active}
        choice = _topology.churn_assign(counts, n)
        first = self.n_feeds
        new_ids = list(range(first, first + n))
        self._append_topo({"kind": "add_edges",
                           "epoch": self._topo.next_epoch(),
                           "first": int(first), "count": n,
                           "s_sink": [float(x) for x in s_new]})
        ranges: List[Dict[str, Any]] = []
        for old_k in sorted(set(choice)):
            new_k = len(self._slots)
            owned_old = np.flatnonzero(self._owner == old_k)
            attach = [f for f, c in zip(new_ids, choice)
                      if c == old_k]
            feeds = sorted([int(f) for f in owned_old] + attach)
            self._append_topo({"kind": "add_slot",
                               "epoch": self._topo.next_epoch(),
                               "k": int(new_k), "feeds": feeds,
                               "start_seq": int(w) + 1})
            self._open_slot_runtime(self._slots[new_k])
            if len(owned_old):
                ranges.append({"id": len(ranges), "src": int(old_k),
                               "dst": int(new_k),
                               "feeds": [int(f) for f in owned_old]})
        if ranges:
            plan_id = f"plan-{self._topo.next_epoch():06d}"
            plan = {"kind": "plan",
                    "epoch": self._topo.next_epoch(),
                    "plan": plan_id, "ranges": ranges,
                    "watermark": int(w), "new_slots": []}
            self._append_topo(plan)
            _topology.Migration(
                self, plan,
                fault=_faultinject.reshard_fault()).run()
        return new_ids

    def drop_edges(self, feeds: Sequence[int],
                   drain_rounds: int = 64) -> None:
        """Remove feeds from the live graph: poison their carry on the
        owning shard (rank 0, health bit set — no intensity
        contribution, journaled in the shard's OWN journal so recovery
        replays it) and journal the routing drop (owner -1: future
        batches touching them reject, and they leave
        :meth:`edge_digest`).  The poison lands before the drop record
        — a crash between the two re-runs ``drop_edges`` idempotently."""
        if self.migration_pending:
            raise TopologyError(
                f"plan {self._topo.plan.get('plan')!r} is still "
                f"migrating — finish it before churning the graph")
        feeds = np.unique(np.asarray(feeds, np.int64))
        if len(feeds) == 0:
            return
        if feeds.min() < 0 or feeds.max() >= self.n_feeds:
            raise TopologyError(
                f"drop_edges feed ids out of range 0..{self.n_feeds - 1}")
        owners = self._owner[feeds]
        if (owners < 0).any():
            bad = [int(f) for f, o in zip(feeds, owners) if o < 0]
            raise TopologyError(
                f"feeds {bad[:8]} are already dropped")
        self._drain_for_topology(drain_rounds)
        for k in sorted(set(int(o) for o in owners)):
            slot = self._slots[k]
            sel = feeds[owners == k]
            local = self._local_index[sel]
            r0 = np.zeros(len(sel), np.float32)
            h1 = np.ones(len(sel), np.uint32)
            dg = _topology.range_digest(sel, r0, h1)
            self._topo.assert_owner(self._owner[sel], k, sel)
            slot.runtime.install_range(
                [int(i) for i in local], r0, h1,
                feeds=[int(f) for f in sel],
                topo_epoch=self._topo.next_epoch(), digest=dg,
                plan_id="drop", range_id=-1)
            slot.runtime.snapshot()
        self._append_topo({"kind": "drop_edges",
                           "epoch": self._topo.next_epoch(),
                           "feeds": [int(f) for f in feeds]})

    # ---- read / inspection paths ----

    def _slot_pending(self, slot: _ShardSlot) -> int:
        """One shard's pending count; a worker that died since the last
        round classifies as a crash here (its pending died with it —
        the outstanding seqs were reclassified lost)."""
        if slot.runtime is None:
            return 0
        try:
            return int(slot.runtime.pending)
        except TransportTimeout as e:
            # The short read deadline expired with the child alive —
            # busy or stalled, not proven dead: degrade and back off,
            # exactly like a poll deadline.  SIGKILLing a healthy
            # worker over one slow read would convert a hiccup into a
            # full journal-replay recovery.
            self._on_timeout(slot, f"status read timed out: {e}")
            return 0
        except TransportError as e:
            self._lost_link(slot, e, f"worker died on status: {e}",
                            wait=False)
            return 0

    @property
    def pending(self) -> int:
        return sum(self._slot_pending(s) for s in self._slots)

    @property
    def pending_by_shard(self) -> List[int]:
        return [self._slot_pending(s) for s in self._slots]

    @property
    def health_by_shard(self) -> List[str]:
        return [s.health for s in self._slots]

    @property
    def shard_dirs(self) -> List[Optional[str]]:
        return [s.dir for s in self._slots]

    @property
    def edges_per_shard(self) -> List[int]:
        # Ownership, not geometry: a slot's array can still HOLD a
        # range that migrated off it (frozen, excluded from reads).
        return [int((self._owner == s.k).sum()) for s in self._slots]

    @property
    def applied_seq(self) -> int:
        """The cluster's acknowledged stream position: the MIN applied
        seq over shards (a quarantined shard counts -1 — everything
        must be retransmitted until it recovers and reports)."""
        seqs = []
        for s in self._slots:
            if s.retired:
                continue
            if s.runtime is None:
                seqs.append(-1)
                continue
            try:
                seqs.append(int(s.runtime.applied_seq))
            except TransportTimeout as e:
                # Alive but slow: degrade (see _slot_pending) and
                # report -1 — the source retransmits, duplicate drop
                # absorbs any overshoot once the shard answers again.
                self._on_timeout(s, f"status read timed out: {e}")
                seqs.append(-1)
            except TransportError as e:
                self._lost_link(s, e, f"worker died on status: {e}",
                                wait=False)
                seqs.append(-1)
        return min(seqs)

    def decide(self) -> Optional[ClusterDecision]:
        """The non-blocking cluster read: aggregate the latest applied
        decision of every reporting shard (quarantined shards are
        excluded and COUNTED — degraded serving is visible, never a
        blocked read).  None until a first batch applies somewhere."""
        self.metrics.decisions_served += 1
        per = []
        for slot in self._slots:
            if slot.runtime is None:
                continue
            try:
                d = slot.runtime.decide()
            except TransportTimeout as e:
                # Alive but past the short read deadline: one fewer
                # reporter THIS read, degrade + backoff — never a
                # SIGKILL over a slow answer.
                self._on_timeout(slot, f"decide read timed out: {e}")
                continue
            except TransportError as e:
                # A dead worker degrades the read (one fewer reporter),
                # never blocks it; a severed socket link reattaches
                # only if the worker already redialed (wait=False: the
                # read path never pays the full grace).
                self._lost_link(slot, e, f"worker died on decide: {e}",
                                wait=False)
                continue
            if d is not None:
                per.append(d)
        if not per:
            return None
        stale = self.pending
        if stale:
            self.metrics.stale_decisions += 1
        return ClusterDecision(
            seq=min(d.seq for d in per),
            post=any(d.post for d in per),
            intensity=float(sum(d.intensity for d in per)),
            stale_batches=stale,
            shards_reporting=len(per),
            shards_quarantined=sum(1 for s in self._slots
                                   if s.runtime is None
                                   and not s.retired))

    def shard_digests(self) -> Dict[int, Optional[str]]:
        out: Dict[int, Optional[str]] = {}
        for s in self._slots:
            if s.retired:
                continue
            if s.runtime is None:
                out[s.k] = None
                continue
            try:
                out[s.k] = s.runtime.state_digest()
            except TransportTimeout as e:
                self._on_timeout(s, f"digest read timed out: {e}")
                out[s.k] = None
            except TransportError as e:
                self._lost_link(s, e, f"worker died on digest: {e}",
                                wait=False)
                out[s.k] = None
        return out

    def cluster_digest(self,
                       digests: Optional[Dict[int, Optional[str]]] = None
                       ) -> str:
        """sha256 over the per-shard carry digests (every shard must be
        live) — the whole-cluster bit-identity witness the chaos tests
        compare.  Pass ``digests`` (a :meth:`shard_digests` result) to
        reuse already-computed digests: each one is a full device→host
        transfer + hash of the shard carry."""
        h = hashlib.sha256()
        if digests is None:
            digests = self.shard_digests()
        for k, d in sorted(digests.items()):
            if d is None:
                raise ValueError(
                    f"shard {k} is quarantined — recover it before "
                    f"taking a cluster digest")
            h.update(f"{k}:{d}\n".encode())
        return h.hexdigest()

    def _gather_edges(self) -> Tuple[np.ndarray, np.ndarray, int, float,
                                     int]:
        """Assemble the global per-edge carry ``(rank, health)`` plus
        the stream position ``(seq, cluster clock, n_batches)`` from the
        live shards.  ``ServingRuntime.gather`` owns the one explicit
        device→host boundary per shard; ``WorkerHandle.gather`` answers
        it bit-identically over the frame protocol, so both placements
        produce byte-equal edge digests.  Requires every shard live and
        at the SAME seq (drained)."""
        rank = np.zeros(self.n_feeds, np.float32)
        health = np.zeros(self.n_feeds, np.uint32)
        seqs, ts, nbs = [], [], []
        for slot in self._slots:
            if slot.retired:
                continue
            if slot.runtime is None:
                raise ValueError(
                    f"shard {slot.k} is quarantined — recover before "
                    f"gathering edge state")
            r, h, sq, t, nb = slot.runtime.gather()
            # Ownership-masked: a migrated-off range still sits frozen
            # in the source's arrays (and a dropped edge sits poisoned
            # in its old owner's) — only the feeds this slot OWNS
            # contribute to the global view.
            own = self._owner[slot.feeds] == slot.k
            sel = slot.feeds[own]
            rank[sel] = r[own]
            health[sel] = h[own]
            seqs.append(int(sq))
            ts.append(float(t))
            nbs.append(int(nb))
        if len(set(seqs)) != 1:
            raise ValueError(
                f"shards disagree on applied seq ({seqs}) — drain "
                f"(retransmit + poll) before gathering edge state")
        return rank, health, seqs[0], max(ts), max(nbs)

    def edge_digest(self) -> str:
        """Canonical digest of the cluster's PER-EDGE serving state —
        global ``(rank, health)`` by feed id, the stream seq, and the
        cluster clock — independent of the partition, so it is THE
        reshard witness: an N→M migration must preserve it bitwise."""
        rank, health, seq, t_max, _ = self._gather_edges()
        live = np.flatnonzero(self._owner >= 0)
        h = hashlib.sha256()
        h.update(np.int64(len(live)).tobytes())
        h.update(np.int64(seq).tobytes())
        h.update(np.float32(t_max).tobytes())
        if len(live) != self.n_feeds:
            # Dropped edges leave holes: the surviving feed ids become
            # part of the witness.  When nothing was ever dropped the
            # digest stays byte-identical to the pre-elastic format.
            h.update(live.astype(np.int64).tobytes())
        h.update(rank[live].tobytes())
        h.update(health[live].tobytes())
        return h.hexdigest()

    # ---- durability / artifacts ----

    def snapshot_all(self) -> Dict[int, Optional[int]]:
        out: Dict[int, Optional[int]] = {}
        for s in self._slots:
            if s.runtime is None:
                continue
            try:
                out[s.k] = s.runtime.snapshot()
            except TransportTimeout as e:
                self._on_timeout(s, f"snapshot deadline expired: {e}")
            except TransportError as e:
                self._lost_link(s, e, f"worker died on snapshot: {e}")
        return out

    def write_metrics(self, path: Optional[str] = None,
                      extra: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        """The ``rq.serving.metrics/2`` artifact (defaults into the
        cluster directory)."""
        if path is None:
            if self.dir is None:
                raise ValueError("no cluster directory and no path given")
            path = os.path.join(self.dir, "metrics.json")
        base = {"n_feeds": self.n_feeds, "q": self.q,
                "applied_seq": self.applied_seq,
                "durability": self.durability()}
        if extra:
            base.update(extra)
        return self.metrics.write(path, self.pending_by_shard,
                                  self.health_by_shard, extra=base)

    def durability(self) -> Dict[str, Any]:
        """The cluster's configured durability window (identical on
        every shard) — committed in the ``rq.serving.metrics/2``
        artifact so no throughput number is ever quoted without its
        durability cost (``journal.durability_info`` is the one
        definition)."""
        from .journal import durability_info

        repl = (None if not self.replication_factor
                else {"factor": self.replication_factor,
                      "quorum": (self.replication_quorum
                                 if self.replication_quorum is not None
                                 else self.replication_factor // 2 + 1)})
        return durability_info(self.flush_mode, self.fsync_every_n,
                               self.max_unflushed_records,
                               self.max_flush_delay_ms, self.coalesce,
                               replication=repl)

    def close(self) -> None:
        for slot in self._slots:
            if slot.runtime is not None:
                slot.runtime.close()
            if slot.listener is not None:
                slot.listener.close()
                slot.listener = None
        if self._topo_log is not None:
            self._topo_log.close()
            self._topo_log = None

    def reset_metrics(self) -> None:
        """Fresh router ledger (bench warm-up exclusion); refused while
        sub-batches are pending anywhere — see
        ``ServingRuntime.reset_metrics``."""
        if self.pending:
            raise ValueError(
                f"cannot reset metrics with {self.pending} sub-batches "
                f"pending — drain (poll) first")
        for slot in self._slots:
            if slot.runtime is not None:
                try:
                    slot.runtime.reset_metrics()
                except TransportTimeout as e:
                    self._on_timeout(
                        slot, f"reset_metrics timed out: {e}")
                except TransportError as e:
                    self._lost_link(
                        slot, e, f"worker died on reset_metrics: {e}")
            slot.outstanding.clear()
        self.metrics = ClusterMetrics(self.n_shards, clock=self._clock)
        # Counters restart; the epoch is structural state, not a count.
        self.metrics.set_topology_epoch(self._topo.epoch)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# The class IS the router (ISSUE 7 naming): routing, health, and
# recovery live on the cluster object itself — no extra indirection.
ShardRouter = ServingCluster


# ---------------------------------------------------------------------------
# Reshard: digest-asserted N -> M state migration (grow without genesis
# replay)
# ---------------------------------------------------------------------------

def reshard(src_dir: str, dst_dir: str, n_shards: int,
            clock=time.monotonic) -> Dict[str, Any]:
    """Migrate a DRAINED cluster directory from its current shard count
    to ``n_shards`` fault domains under ``dst_dir`` (which must not
    exist or be empty; ``src_dir`` is left intact as the rollback).

    Protocol: recover every source shard (provable snapshot + journal
    replay — nothing unproven migrates), require a uniform applied seq
    (an undrained cluster refuses), gather the global per-edge
    ``(rank, health)`` carry and the stream position, deal the edges to
    the NEW partition, install the migrated carry into each fresh shard
    and land an IMMEDIATE snapshot at the migrated seq (post-reshard
    recovery never replays from genesis), then **assert the per-edge
    digest is bit-identical across the move** — a divergent migration
    raises instead of serving silently-wrong state.  Per-shard decision
    keys re-derive from the new shard ids (decisions from ``seq+1`` on
    are deterministic in the new geometry); per-shard lifetime counters
    (``n_events``/``n_posts``) reset — they are fault-domain metrics,
    not stream state.  Returns the enveloped report also written to
    ``<dst_dir>/reshard.json`` (schema ``rq.serving.reshard/1``)."""
    import jax.numpy as jnp

    src, _ = ServingCluster.recover(src_dir, clock=clock,
                                    auto_recover=False)
    try:
        rank_g, health_g, seq, t_max, n_batches = src._gather_edges()
        edge_before = src.edge_digest()
        cfg = src._config()
    finally:
        src.close()

    if os.path.exists(dst_dir) and os.listdir(dst_dir):
        raise ValueError(
            f"reshard destination {dst_dir} is not empty — refusing to "
            f"mix with existing serving state")
    dst = None
    try:
        # Construction INSIDE the cleanup scope: a shard runtime that
        # fails to open mid-constructor has already written the cluster
        # config and the earlier shards' directories — that partial,
        # unverified destination must die with the failure too, not
        # just failures past this point.
        dst = ServingCluster(
            n_feeds=int(cfg["n_feeds"]), n_shards=int(n_shards),
            dir=dst_dir, q=float(cfg["q"]),
            s_sink=np.asarray(cfg["s_sink"], np.float64),
            seed=int(cfg["seed"]), start_seq=int(cfg["start_seq"]),
            snapshot_every=int(cfg["snapshot_every"]),
            reorder_window=int(cfg["reorder_window"]),
            queue_capacity=int(cfg["queue_capacity"]),
            max_batch_events=int(cfg["max_batch_events"]),
            fsync_every_n=int(cfg.get("fsync_every_n", 1)),
            flush_mode=str(cfg.get("flush_mode", "sync")),
            max_unflushed_records=int(
                cfg.get("max_unflushed_records", 64)),
            max_flush_delay_ms=float(
                cfg.get("max_flush_delay_ms", 50.0)),
            coalesce=int(cfg.get("coalesce", 1)), clock=clock)
        for slot in dst._slots:
            st = slot.runtime.carry
            migrated = st.replace(
                rank=jnp.asarray(rank_g[slot.feeds]),
                health=jnp.asarray(health_g[slot.feeds]),
                t=jnp.asarray(t_max, st.t.dtype),
                seq=jnp.asarray(seq, jnp.int32),
                n_batches=jnp.asarray(n_batches, jnp.int32))
            slot.runtime.install_carry(migrated)
            slot.runtime.snapshot()
        edge_after = dst.edge_digest()
        if edge_after != edge_before:
            raise RuntimeError(
                f"reshard diverged: per-edge digest "
                f"{edge_after[:12]}.. after migration != "
                f"{edge_before[:12]}.. before — refusing to serve "
                f"migrated state (src left intact at {src_dir}, "
                f"divergent destination removed)")
        report = {
            "src_dir": os.path.abspath(src_dir),
            "dst_dir": os.path.abspath(dst_dir),
            "n_shards_src": int(cfg["n_shards"]),
            "n_shards_dst": int(n_shards),
            "n_feeds": int(cfg["n_feeds"]),
            "seq": int(seq),
            "n_batches": int(n_batches),
            "edge_digest": edge_before,
            "edges_per_shard": [int(len(s.feeds)) for s in dst._slots],
            "verified": True,
        }
        _integrity.write_json(os.path.join(dst_dir, "reshard.json"),
                              report, schema=RESHARD_SCHEMA)
    except BaseException:
        # A half-built destination is a fully-formed cluster directory
        # holding UNVERIFIED migrated state — left on disk, a later
        # ServingCluster.recover(dst_dir) would serve exactly the
        # silently-wrong state the digest assert refuses, so the
        # destination (created by us: it was empty at entry) dies with
        # the failure.
        if dst is not None:
            dst.close()
        shutil.rmtree(dst_dir, ignore_errors=True)
        raise
    dst.close()
    return report
