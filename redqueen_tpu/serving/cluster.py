"""Sharded serving fault domains: per-shard journals, health-aware
routing, and crash isolation at corpus scale.

One :class:`ServingCluster` partitions the feed-edge state by EDGE HASH
into ``n_shards`` independent fault domains.  Each shard is a full
PR 6 :class:`~redqueen_tpu.serving.service.ServingRuntime` — its OWN
journal segments, orbax snapshot tree, ``Sequencer``, carry, and health
state under ``<dir>/shard-KKKK/`` — so recovery, torn-tail quarantine,
and overload shedding are decided per shard, never per service: one
wedged apply, torn journal, or killed carry takes down 1/N of the edge
graph while the other shards keep serving.

**Routing (the ShardRouter role).**  ``submit`` validates the global
micro-batch once, splits it by the deterministic edge-hash partition
(:func:`partition` — hash-ordered round-robin dealing, balanced to ±1
edge, pure function of ``(n_feeds, n_shards, PARTITION_VERSION)``), and
offers every shard its sub-batch **under the global sequence number**
(empty slices included) — so each shard's journal is independently
replayable and each shard's decision stream is a pure function of
``(shard carry, global stream)``.  ``poll`` dispatches one sub-batch at
a time per shard with timeout detection, exponential poll-round backoff
for wedged shards, and per-shard health tracking:

    healthy --timeout/transient--> degraded --HEAL_AFTER clean--> healthy
    degraded --QUARANTINE_AFTER consecutive failures--> quarantined
    any --crash / torn journal / journal-append failure--> quarantined
    quarantined --recover_shard (snapshot + digest-asserted replay)-->
        degraded (probation)

**Crash isolation.**  A crashed shard loses exactly what SIGKILL leaves
behind: its in-memory carry, queue, and reorder window die; its fsynced
journal records and snapshots survive.  ``recover_shard`` rebuilds the
shard in place through :func:`serving.service.recover` (newest provable
snapshot + digest-asserted journal replay — bit-identical carry AND
decisions) while healthy shards keep serving; sub-batches offered to a
quarantined shard are shed-with-recorded-seqs (``shed_unavailable``),
and the batches that died un-applied inside the crashed shard are
reclassified ``lost_on_crash`` — the router-side
:class:`~redqueen_tpu.serving.metrics.ClusterMetrics` ledger keeps the
closed accounting identity ``ingested == applied + shed + rejected +
duplicates (+ pending)`` true per shard and cluster-wide at every
instant, including mid-recovery.

**Fault injection.**  Every failure mode runs deterministically in CI on
CPU via ``runtime.faultinject``'s ``shard`` kinds
(``RQ_FAULT=shard:crash|wedge|torn_journal|corrupt_snapshot@shardK
[,batchN]``), applied by the router at exact sub-batch sequence numbers;
:meth:`ServingCluster.kill_shard` is the same teardown as an operator
chaos hook.

**Reshard (grow without genesis replay).**  :func:`reshard` migrates a
drained N-shard directory to M shards by per-edge state migration: the
per-edge ``(rank, health)`` carry, the cluster clock, and the stream
position move to the new partition, each new shard lands an immediate
snapshot at the migrated seq (recovery never replays from genesis), and
the whole move is **digest-asserted** — the canonical per-edge
:meth:`~ServingCluster.edge_digest` must be bit-identical before and
after, or the reshard raises instead of serving silently-migrated-wrong
state.  Per-shard lifetime counters (``n_events``/``n_posts``) reset at
a reshard (they are fault-domain metrics, not stream state); the stream
position (``seq``/``n_batches``) migrates.

See docs/DESIGN.md "Sharded serving & fault domains".
"""

from __future__ import annotations

import hashlib
import os
import shutil
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..runtime import faultinject as _faultinject
from ..runtime import integrity as _integrity
from .events import EventBatch, IngestError, validate_batch
from .metrics import ClusterMetrics
from .service import (RecoveryInfo, ServingRuntime, SNAPSHOTS_DIRNAME,
                      recover as _recover_runtime)

__all__ = ["ServingCluster", "ShardRouter", "ClusterAdmission",
           "ClusterDecision", "partition", "shard_seed", "reshard",
           "CLUSTER_SCHEMA", "RESHARD_SCHEMA", "PARTITION_VERSION",
           "HEALTHY", "DEGRADED", "QUARANTINED", "HEAL_AFTER",
           "QUARANTINE_AFTER", "WEDGE_FIRES", "MAX_BACKOFF_ROUNDS"]

CLUSTER_SCHEMA = "rq.serving.cluster/1"
RESHARD_SCHEMA = "rq.serving.reshard/1"
_CLUSTER_CONFIG = "cluster.json"

# Bump when the partition function changes: a directory written under a
# different partition CANNOT be reopened (edges would silently route to
# the wrong journals) — the config check refuses instead.
PARTITION_VERSION = 1

# Health states + state-machine constants (see the module docstring).
HEALTHY = "healthy"
DEGRADED = "degraded"
QUARANTINED = "quarantined"
HEAL_AFTER = 3          # consecutive clean applies: degraded -> healthy
QUARANTINE_AFTER = 3    # consecutive timeouts: degraded -> quarantined
WEDGE_FIRES = 2         # injected-wedge timeouts before the stall clears
MAX_BACKOFF_ROUNDS = 8  # cap on the wedged-shard poll-round backoff
RECOVERY_GIVE_UP = 3    # failed auto-recoveries before poll() raises


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer over uint64 (vectorized; wraparound is the
    point)."""
    x = np.asarray(x, np.uint64)
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x


def partition(n_feeds: int, n_shards: int) -> np.ndarray:
    """``assign[feed] = owning shard``: edges are ordered by their
    splitmix64 hash, then dealt round-robin — decorrelated from feed-id
    locality like a plain ``hash % N`` but balanced BY CONSTRUCTION
    (shard sizes differ by at most one edge, so no shard can come up
    empty while ``n_shards <= n_feeds``).  Pure function of
    ``(n_feeds, n_shards)`` under :data:`PARTITION_VERSION`."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    if n_shards > n_feeds:
        raise ValueError(
            f"n_shards={n_shards} > n_feeds={n_feeds}: every shard must "
            f"own at least one edge")
    h = _mix64(np.arange(n_feeds, dtype=np.uint64))
    order = np.argsort(h, kind="stable")
    assign = np.empty(n_feeds, np.int64)
    assign[order] = np.arange(n_feeds, dtype=np.int64) % n_shards
    return assign


def shard_seed(seed: int, shard: int) -> int:
    """Deterministic per-shard PRNG seed derivation — distinct shards
    must draw from distinct decision streams (the PR 4 RQ501 lesson:
    never reuse one key across independent consumers)."""
    return (int(seed) * 1_000_003 + 7_919 * (int(shard) + 1)) \
        % (2 ** 31 - 1)


class ClusterAdmission(NamedTuple):
    """One global ``submit``'s outcome: ``status`` summarizes
    (``accepted`` = every shard accepted or acked a duplicate;
    ``partial`` = at least one shard shed / was unavailable / rejected;
    ``shed`` = no shard kept it; ``rejected`` = failed global
    validation before fan-out); ``per_shard`` is the exact per-shard
    admission status list."""

    status: str
    seq: Optional[int] = None
    backpressure: bool = False
    reason: Optional[str] = None
    per_shard: Tuple[str, ...] = ()


class ClusterDecision(NamedTuple):
    """The cluster read path's aggregate: summed intensity over the
    shards that have decided, ``post`` if any shard's latest decision
    posted, total unapplied backlog as staleness, and how many fault
    domains are reporting vs quarantined (degraded-serving visibility,
    never a blocked read)."""

    seq: int                 # min applied seq over reporting shards
    post: bool
    intensity: float
    stale_batches: int
    shards_reporting: int
    shards_quarantined: int


class _ShardSlot:
    """One fault domain's router-side bookkeeping (the runtime itself is
    replaced wholesale on crash/recovery; this slot identity persists)."""

    __slots__ = ("k", "dir", "feeds", "s_slice", "runtime", "health",
                 "fail_streak", "clean_streak", "skip_rounds",
                 "recover_failures", "outstanding")

    def __init__(self, k: int, dir: Optional[str], feeds: np.ndarray,
                 s_slice: np.ndarray):
        self.k = k
        self.dir = dir
        self.feeds = feeds          # global feed ids owned (ascending)
        self.s_slice = s_slice
        self.runtime: Optional[ServingRuntime] = None
        self.health = HEALTHY
        self.fail_streak = 0
        self.clean_streak = 0
        self.skip_rounds = 0
        self.recover_failures = 0
        # seq -> (arrival stamp, n_events): accepted but not yet applied
        # (mirrors the shard's queue + reorder window; reclassified
        # lost_on_crash if the carry dies under them)
        self.outstanding: Dict[int, Tuple[float, int]] = {}


class ServingCluster:
    """See the module docstring.  Single-writer like the per-shard
    runtime: one process owns the cluster directory."""

    def __init__(self, n_feeds: int, n_shards: int,
                 dir: Optional[str] = None, q: float = 1.0,
                 s_sink: Optional[np.ndarray] = None, seed: int = 0,
                 start_seq: int = 0, snapshot_every: int = 8,
                 reorder_window: int = 8, queue_capacity: int = 64,
                 max_batch_events: int = 256, clock=time.monotonic,
                 auto_recover: bool = True, _open_runtimes: bool = True):
        self.n_feeds = int(n_feeds)
        self.n_shards = int(n_shards)
        self.dir = dir
        self.q = float(q)
        self.seed = int(seed)
        self.start_seq = int(start_seq)
        self.snapshot_every = int(snapshot_every)
        self.reorder_window = int(reorder_window)
        self.queue_capacity = int(queue_capacity)
        self.max_batch_events = int(max_batch_events)
        self.auto_recover = bool(auto_recover)
        self._clock = clock
        s = (np.ones(n_feeds) if s_sink is None
             else np.asarray(s_sink, np.float64))
        if s.shape != (self.n_feeds,):
            raise ValueError(
                f"s_sink must have shape ({n_feeds},), got {s.shape}")
        self._s_sink = s

        self._assign = partition(self.n_feeds, self.n_shards)
        # local index of each global feed within its owning shard
        self._local_index = np.empty(self.n_feeds, np.int32)
        self._slots: List[_ShardSlot] = []
        for k in range(self.n_shards):
            feeds = np.flatnonzero(self._assign == k)
            self._local_index[feeds] = np.arange(len(feeds),
                                                 dtype=np.int32)
            sdir = (None if dir is None
                    else os.path.join(dir, f"shard-{k:04d}"))
            self._slots.append(_ShardSlot(k, sdir, feeds, s[feeds]))

        if dir is not None:
            os.makedirs(dir, exist_ok=True)
            self._check_or_write_config()

        self.metrics = ClusterMetrics(self.n_shards, clock=clock)
        self._fault = _faultinject.shard_fault()
        if self._fault is not None and self._fault.shard >= self.n_shards:
            # faultinject's contract: a spec that can never fire dies
            # loudly, not as a vacuously-green chaos run.
            raise ValueError(
                f"RQ_FAULT targets shard {self._fault.shard} but this "
                f"cluster has {self.n_shards} shard(s) (valid: 0.."
                f"{self.n_shards - 1}) — the fault could never fire")
        self._fault_spent = False
        self._wedge_left = WEDGE_FIRES

        if _open_runtimes:
            for slot in self._slots:
                slot.runtime = self._fresh_runtime(slot)

    # ---- construction / config identity ----

    def _config(self) -> Dict[str, Any]:
        return {
            "n_feeds": self.n_feeds, "n_shards": self.n_shards,
            "q": self.q, "s_sink": [float(x) for x in self._s_sink],
            "seed": self.seed, "start_seq": self.start_seq,
            "snapshot_every": self.snapshot_every,
            "reorder_window": self.reorder_window,
            "queue_capacity": self.queue_capacity,
            "max_batch_events": self.max_batch_events,
            "partition_version": PARTITION_VERSION,
        }

    def _check_or_write_config(self) -> None:
        cfg_path = os.path.join(self.dir, _CLUSTER_CONFIG)
        cfg = self._config()
        if os.path.exists(cfg_path):
            # Same refusal contract as the per-shard config: the stored
            # config is the directory's identity — a silently different
            # partition/seed would route edges into the wrong journals.
            stored = _integrity.read_json(cfg_path, schema=CLUSTER_SCHEMA)
            for field in ("n_feeds", "n_shards", "q", "s_sink", "seed",
                          "start_seq", "max_batch_events",
                          "partition_version"):
                if stored.get(field) != cfg[field]:
                    raise ValueError(
                        f"cluster dir {self.dir} was created with "
                        f"{field}={stored.get(field)!r} but this cluster "
                        f"was constructed with {field}={cfg[field]!r} — "
                        f"edges would route to the wrong shards / replay "
                        f"would diverge; recover() with the stored "
                        f"config, reshard(), or use a fresh directory")
        else:
            _integrity.write_json(cfg_path, cfg, schema=CLUSTER_SCHEMA)

    def _fresh_runtime(self, slot: _ShardSlot) -> ServingRuntime:
        return ServingRuntime(
            n_feeds=len(slot.feeds), q=self.q, s_sink=slot.s_slice,
            seed=shard_seed(self.seed, slot.k), dir=slot.dir,
            start_seq=self.start_seq, snapshot_every=self.snapshot_every,
            reorder_window=self.reorder_window,
            queue_capacity=self.queue_capacity,
            max_batch_events=self.max_batch_events, clock=self._clock)

    @classmethod
    def recover(cls, dir: str, clock=time.monotonic,
                auto_recover: bool = True
                ) -> Tuple["ServingCluster", List[RecoveryInfo]]:
        """Rebuild a cluster from its directory after a crash: read the
        enveloped cluster config, then :func:`serving.service.recover`
        EVERY shard fault domain independently (each one = newest
        provable snapshot + digest-asserted journal replay).  Shards
        killed at different points recover to different seqs; the
        source's retransmit of everything past :attr:`applied_seq`
        (the cluster min) reconverges them — duplicate drop absorbs the
        rest."""
        cfg = _integrity.read_json(os.path.join(dir, _CLUSTER_CONFIG),
                                   schema=CLUSTER_SCHEMA)
        if cfg.get("partition_version") != PARTITION_VERSION:
            raise ValueError(
                f"cluster dir {dir} uses partition_version="
                f"{cfg.get('partition_version')!r}, this code is "
                f"{PARTITION_VERSION} — reshard() with the old code "
                f"first")
        cl = cls(n_feeds=int(cfg["n_feeds"]),
                 n_shards=int(cfg["n_shards"]), dir=dir,
                 q=float(cfg["q"]),
                 s_sink=np.asarray(cfg["s_sink"], np.float64),
                 seed=int(cfg["seed"]), start_seq=int(cfg["start_seq"]),
                 snapshot_every=int(cfg["snapshot_every"]),
                 reorder_window=int(cfg["reorder_window"]),
                 queue_capacity=int(cfg["queue_capacity"]),
                 max_batch_events=int(cfg["max_batch_events"]),
                 clock=clock, auto_recover=auto_recover,
                 _open_runtimes=False)
        infos: List[RecoveryInfo] = []
        for slot in cl._slots:
            rt, info = _recover_runtime(slot.dir, clock=clock)
            slot.runtime = rt
            infos.append(info)
        return cl, infos

    # ---- routing: the ingest path ----

    def _split_batch(self, batch: EventBatch) -> List[EventBatch]:
        """One sub-batch per shard in ONE pass over the events (a
        per-shard boolean mask would make the measured ingest path
        O(n_shards x events) per global batch): stable-sort the events
        by owning shard — intra-shard event order is preserved — and
        slice the contiguous runs."""
        seq = int(batch.seq)
        if len(batch.feeds) == 0:
            empty = EventBatch(seq, np.empty(0, np.float64),
                               np.empty(0, np.int32))
            return [empty] * self.n_shards
        assign = self._assign[batch.feeds]
        order = np.argsort(assign, kind="stable")
        times_s = batch.times[order]
        local_s = self._local_index[batch.feeds[order]]
        bounds = np.searchsorted(assign[order],
                                 np.arange(self.n_shards + 1))
        return [EventBatch(seq, times_s[bounds[k]:bounds[k + 1]],
                           local_s[bounds[k]:bounds[k + 1]])
                for k in range(self.n_shards)]

    def submit(self, batch: EventBatch) -> ClusterAdmission:
        """Admit one GLOBAL micro-batch: validate once, fan out one
        sub-batch per shard under the global seq (empty slices included
        — every shard's journal tracks the full stream position).  Never
        raises on bad input; a quarantined shard's slice is shed with
        its seq recorded (``shed_unavailable``) so the source
        retransmits it after recovery."""
        try:
            batch = validate_batch(batch, self.n_feeds,
                                   max_events=self.max_batch_events)
        except IngestError as e:
            # Rejected before fan-out: one rejected sub-outcome per
            # shard keeps the ledger's sub-batch units uniform.
            self.metrics.global_rejected += 1
            for k in range(self.n_shards):
                self.metrics.observe_submitted(k)
                self.metrics.observe_rejected(k)
            return ClusterAdmission(
                "rejected", seq=e.seq, reason=str(e),
                per_shard=("rejected",) * self.n_shards)
        seq = int(batch.seq)
        subs = self._split_batch(batch)
        now = self._clock()
        statuses: List[str] = []
        backpressure = False
        for slot in self._slots:
            self.metrics.observe_submitted(slot.k)
            if slot.runtime is None:
                statuses.append("unavailable")
                self.metrics.observe_shed_unavailable(slot.k, seq)
                backpressure = True
                continue
            sub = subs[slot.k]
            adm = slot.runtime.submit(sub, _validated=True)
            statuses.append(adm.status)
            backpressure |= adm.backpressure
            if adm.status == "accepted":
                if seq in slot.outstanding:
                    # retransmit of a batch still held in the shard's
                    # reorder window: redundant delivery, not durable —
                    # the ledger counts the extra submission a duplicate
                    self.metrics.observe_duplicate(slot.k)
                else:
                    slot.outstanding[seq] = (now, sub.n_events)
            elif adm.status == "duplicate":
                self.metrics.observe_duplicate(slot.k)
            elif adm.status == "shed":
                self.metrics.observe_shed_queue(slot.k, seq)
            else:  # "rejected" — per-shard validation (shouldn't happen
                self.metrics.observe_rejected(slot.k)  # post-global)
        if all(st in ("accepted", "duplicate") for st in statuses):
            status = "accepted"
        elif all(st in ("shed", "unavailable") for st in statuses):
            status = "shed"
        else:
            status = "partial"
        return ClusterAdmission(status, seq=seq,
                                backpressure=backpressure,
                                per_shard=tuple(statuses))

    # ---- routing: the apply path (health-aware dispatch) ----

    def poll(self, max_batches_per_shard: Optional[int] = None
             ) -> Dict[int, List[Any]]:
        """One dispatch round: every serviceable shard applies up to
        ``max_batches_per_shard`` queued sub-batches (all, by default),
        one at a time so faults and health observations land at exact
        sequence numbers.  Wedged shards back off (skip rounds,
        exponential, capped); quarantined shards auto-recover in place
        when ``auto_recover`` (healthy shards are NOT blocked on it —
        they were already drained by the time recovery runs, and their
        admissions never depend on the dead shard).  Returns the
        per-shard decision lists."""
        out: Dict[int, List[Any]] = {}
        for slot in self._slots:
            if slot.runtime is None:
                if self.auto_recover and slot.dir is not None \
                        and slot.skip_rounds == 0:
                    self._try_auto_recover(slot)
                elif slot.skip_rounds > 0:
                    slot.skip_rounds -= 1
                if slot.runtime is None:
                    out[slot.k] = []
                    continue
            if slot.skip_rounds > 0:
                slot.skip_rounds -= 1  # backoff: the wedged shard rests
                out[slot.k] = []
                continue
            out[slot.k] = self._poll_slot(slot, max_batches_per_shard)
        return out

    def _poll_slot(self, slot: _ShardSlot,
                   max_batches: Optional[int]) -> List[Any]:
        decisions: List[Any] = []
        fault = None if self._fault_spent else self._fault
        while max_batches is None or len(decisions) < max_batches:
            seq = slot.runtime.next_queued_seq()
            if seq is None:
                break
            if (fault is not None and fault.mode == "wedge"
                    and fault.shard == slot.k
                    and (fault.batch is None or fault.batch == seq)):
                if self._wedge_left > 0:
                    # The deadline-expiry detection point: the dispatch
                    # did not come back in time, the batch stays queued,
                    # the shard degrades and backs off.
                    self._wedge_left -= 1
                    self._on_timeout(
                        slot, f"apply deadline expired at sub-batch "
                              f"{seq} (injected wedge)")
                    break
                self._fault_spent = True
                fault = None
            try:
                ds = slot.runtime.poll(max_batches=1)
            except Exception as e:  # noqa: BLE001 — any apply/journal
                # failure means the fault domain can no longer be made
                # durable: quarantine it, keep the cluster serving.
                self._crash_slot(slot, f"apply failed: {e}")
                break
            if not ds:
                break
            d = ds[0]
            fire = (fault is not None and fault.shard == slot.k
                    and fault.mode in ("crash", "torn_journal",
                                       "corrupt_snapshot")
                    and (fault.batch is None or fault.batch == d.seq))
            if fire and fault.mode == "torn_journal":
                # The append for this batch went out torn and the shard
                # died before acknowledging: the decision never left the
                # dying fault domain, so it is NOT observed applied —
                # the seq stays outstanding and reclassifies as lost.
                self._fault_spent = True
                from .journal import tear_tail

                if slot.runtime.journal_path:
                    tear_tail(slot.runtime.journal_path)
                self._crash_slot(
                    slot, f"journal append torn at sub-batch {d.seq} "
                          f"(injected)")
                break
            arrival = slot.outstanding.pop(int(d.seq), None)
            latency = (None if arrival is None
                       else self._clock() - arrival[0])
            n_events = 0 if arrival is None else arrival[1]
            self.metrics.observe_applied(slot.k, n_events, d.post,
                                         latency)
            decisions.append(d)
            self._on_clean(slot)
            if fire:  # crash | corrupt_snapshot: batch d.seq was acked
                self._fault_spent = True
                if fault.mode == "corrupt_snapshot":
                    self._corrupt_newest_snapshot(slot)
                self._crash_slot(
                    slot, f"{fault.mode} after sub-batch {d.seq} "
                          f"(injected)")
                break
        return decisions

    # ---- health state machine ----

    def _on_clean(self, slot: _ShardSlot) -> None:
        slot.fail_streak = 0
        if slot.health == DEGRADED:
            slot.clean_streak += 1
            if slot.clean_streak >= HEAL_AFTER:
                slot.health = HEALTHY
                slot.clean_streak = 0

    def _on_timeout(self, slot: _ShardSlot, reason: str) -> None:
        slot.fail_streak += 1
        slot.clean_streak = 0
        if slot.health == HEALTHY:
            slot.health = DEGRADED
        backoff = min(2 ** slot.fail_streak, MAX_BACKOFF_ROUNDS)
        self.metrics.observe_timeout(slot.k, backoff)
        if slot.fail_streak >= QUARANTINE_AFTER:
            # A shard that will not come back is presumed dead: its
            # volatile state cannot be trusted mid-apply — same teardown
            # as a crash, recovery from durable state only.
            self._crash_slot(
                slot, f"quarantined after {slot.fail_streak} "
                      f"consecutive timeouts: {reason}")
        else:
            slot.skip_rounds = backoff

    def _crash_slot(self, slot: _ShardSlot, reason: str) -> None:
        rt, slot.runtime = slot.runtime, None
        slot.health = QUARANTINED
        slot.fail_streak = slot.clean_streak = slot.skip_rounds = 0
        if rt is not None:
            # Releases the journal fd only — every acknowledged record
            # was already fsynced; the carry/queue/reorder window are
            # dropped un-flushed, exactly the SIGKILL leave-behind.
            try:
                rt.close()
            except OSError:
                pass
        for seq in sorted(slot.outstanding):
            self.metrics.observe_lost_on_crash(slot.k, seq)
        slot.outstanding.clear()
        self.metrics.observe_crash(slot.k, reason)

    def _corrupt_newest_snapshot(self, slot: _ShardSlot) -> None:
        """The ``corrupt_snapshot`` fault body: scribble every file of
        the shard's newest landed orbax step (recovery must fall back
        past it via ``latest_valid_step`` and replay more journal)."""
        if slot.dir is None:
            return
        snaps = os.path.join(slot.dir, SNAPSHOTS_DIRNAME)
        if not os.path.isdir(snaps):
            return
        steps = sorted((int(n) for n in os.listdir(snaps)
                        if n.isdigit()), reverse=True)
        if not steps:
            return
        for root, _, files in os.walk(os.path.join(snaps,
                                                   str(steps[0]))):
            for f in files:
                with open(os.path.join(root, f), "wb") as fh:
                    fh.write(b"garbage (injected corrupt_snapshot)")

    def _try_auto_recover(self, slot: _ShardSlot) -> None:
        try:
            self.recover_shard(slot.k)
        except Exception as e:  # noqa: BLE001 — a failed recovery must
            # not take down the healthy shards; back off and retry, give
            # up loudly after RECOVERY_GIVE_UP attempts.
            slot.recover_failures += 1
            slot.skip_rounds = MAX_BACKOFF_ROUNDS
            self.metrics.observe_crash(
                slot.k, f"recovery attempt {slot.recover_failures} "
                        f"failed: {e}")
            if slot.recover_failures >= RECOVERY_GIVE_UP:
                raise RuntimeError(
                    f"shard {slot.k} failed {slot.recover_failures} "
                    f"recovery attempts (last: {e}) — the fault domain "
                    f"at {slot.dir} needs operator attention") from e

    # ---- crash / recovery (the operator surface) ----

    def kill_shard(self, k: int, reason: str = "operator kill") -> None:
        """Chaos hook: destroy shard ``k``'s volatile state exactly the
        way the ``shard:crash`` fault does (carry, queue, and reorder
        window die; fsynced journal + snapshots survive).  What the
        MTTR bench and the chaos acceptance test drive."""
        slot = self._slots[k]
        if slot.runtime is None:
            raise ValueError(f"shard {k} is already quarantined")
        self._crash_slot(slot, reason)

    def recover_shard(self, k: int) -> RecoveryInfo:
        """Recover quarantined shard ``k`` in place: newest provable
        snapshot + digest-asserted journal replay (bit-identical carry
        and decision stream), then probation (``degraded`` until
        ``HEAL_AFTER`` clean applies).  Healthy shards are untouched."""
        slot = self._slots[k]
        if slot.runtime is not None:
            raise ValueError(f"shard {k} is not quarantined")
        if slot.dir is None:
            raise ValueError(
                f"shard {k} has no directory — an in-memory cluster "
                f"cannot recover a crashed fault domain")
        t0 = self._clock()
        rt, info = _recover_runtime(slot.dir, clock=self._clock)
        ms = (self._clock() - t0) * 1e3
        slot.runtime = rt
        slot.health = DEGRADED
        slot.fail_streak = slot.clean_streak = slot.skip_rounds = 0
        slot.recover_failures = 0
        self.metrics.observe_recovery(k, info.replayed, ms)
        return info

    # ---- read / inspection paths ----

    @property
    def pending(self) -> int:
        return sum(s.runtime.pending for s in self._slots
                   if s.runtime is not None)

    @property
    def pending_by_shard(self) -> List[int]:
        return [0 if s.runtime is None else s.runtime.pending
                for s in self._slots]

    @property
    def health_by_shard(self) -> List[str]:
        return [s.health for s in self._slots]

    @property
    def shard_dirs(self) -> List[Optional[str]]:
        return [s.dir for s in self._slots]

    @property
    def edges_per_shard(self) -> List[int]:
        return [int(len(s.feeds)) for s in self._slots]

    @property
    def applied_seq(self) -> int:
        """The cluster's acknowledged stream position: the MIN applied
        seq over shards (a quarantined shard counts -1 — everything
        must be retransmitted until it recovers and reports)."""
        return min((-1 if s.runtime is None else s.runtime.applied_seq)
                   for s in self._slots)

    def decide(self) -> Optional[ClusterDecision]:
        """The non-blocking cluster read: aggregate the latest applied
        decision of every reporting shard (quarantined shards are
        excluded and COUNTED — degraded serving is visible, never a
        blocked read).  None until a first batch applies somewhere."""
        self.metrics.decisions_served += 1
        per = []
        for slot in self._slots:
            if slot.runtime is None:
                continue
            d = slot.runtime.decide()
            if d is not None:
                per.append(d)
        if not per:
            return None
        stale = self.pending
        if stale:
            self.metrics.stale_decisions += 1
        return ClusterDecision(
            seq=min(d.seq for d in per),
            post=any(d.post for d in per),
            intensity=float(sum(d.intensity for d in per)),
            stale_batches=stale,
            shards_reporting=len(per),
            shards_quarantined=sum(1 for s in self._slots
                                   if s.runtime is None))

    def shard_digests(self) -> Dict[int, Optional[str]]:
        return {s.k: (None if s.runtime is None
                      else s.runtime.state_digest())
                for s in self._slots}

    def cluster_digest(self,
                       digests: Optional[Dict[int, Optional[str]]] = None
                       ) -> str:
        """sha256 over the per-shard carry digests (every shard must be
        live) — the whole-cluster bit-identity witness the chaos tests
        compare.  Pass ``digests`` (a :meth:`shard_digests` result) to
        reuse already-computed digests: each one is a full device→host
        transfer + hash of the shard carry."""
        h = hashlib.sha256()
        if digests is None:
            digests = self.shard_digests()
        for k, d in sorted(digests.items()):
            if d is None:
                raise ValueError(
                    f"shard {k} is quarantined — recover it before "
                    f"taking a cluster digest")
            h.update(f"{k}:{d}\n".encode())
        return h.hexdigest()

    def _gather_edges(self) -> Tuple[np.ndarray, np.ndarray, int, float,
                                     int]:
        """Assemble the global per-edge carry ``(rank, health)`` plus
        the stream position ``(seq, cluster clock, n_batches)`` from the
        live shards — one explicit device→host boundary per shard.
        Requires every shard live and at the SAME seq (drained)."""
        import jax

        rank = np.zeros(self.n_feeds, np.float32)
        health = np.zeros(self.n_feeds, np.uint32)
        seqs, ts, nbs = [], [], []
        for slot in self._slots:
            if slot.runtime is None:
                raise ValueError(
                    f"shard {slot.k} is quarantined — recover before "
                    f"gathering edge state")
            st = slot.runtime.carry
            r, h, sq, t, nb = jax.device_get(
                (st.rank, st.health, st.seq, st.t, st.n_batches))
            rank[slot.feeds] = r
            health[slot.feeds] = h
            seqs.append(int(sq))
            ts.append(float(t))
            nbs.append(int(nb))
        if len(set(seqs)) != 1:
            raise ValueError(
                f"shards disagree on applied seq ({seqs}) — drain "
                f"(retransmit + poll) before gathering edge state")
        return rank, health, seqs[0], max(ts), max(nbs)

    def edge_digest(self) -> str:
        """Canonical digest of the cluster's PER-EDGE serving state —
        global ``(rank, health)`` by feed id, the stream seq, and the
        cluster clock — independent of the partition, so it is THE
        reshard witness: an N→M migration must preserve it bitwise."""
        rank, health, seq, t_max, _ = self._gather_edges()
        h = hashlib.sha256()
        h.update(np.int64(self.n_feeds).tobytes())
        h.update(np.int64(seq).tobytes())
        h.update(np.float32(t_max).tobytes())
        h.update(rank.tobytes())
        h.update(health.tobytes())
        return h.hexdigest()

    # ---- durability / artifacts ----

    def snapshot_all(self) -> Dict[int, Optional[int]]:
        return {s.k: s.runtime.snapshot() for s in self._slots
                if s.runtime is not None}

    def write_metrics(self, path: Optional[str] = None,
                      extra: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        """The ``rq.serving.metrics/2`` artifact (defaults into the
        cluster directory)."""
        if path is None:
            if self.dir is None:
                raise ValueError("no cluster directory and no path given")
            path = os.path.join(self.dir, "metrics.json")
        base = {"n_feeds": self.n_feeds, "q": self.q,
                "applied_seq": self.applied_seq}
        if extra:
            base.update(extra)
        return self.metrics.write(path, self.pending_by_shard,
                                  self.health_by_shard, extra=base)

    def close(self) -> None:
        for slot in self._slots:
            if slot.runtime is not None:
                slot.runtime.close()

    def reset_metrics(self) -> None:
        """Fresh router ledger (bench warm-up exclusion); refused while
        sub-batches are pending anywhere — see
        ``ServingRuntime.reset_metrics``."""
        if self.pending:
            raise ValueError(
                f"cannot reset metrics with {self.pending} sub-batches "
                f"pending — drain (poll) first")
        for slot in self._slots:
            if slot.runtime is not None:
                slot.runtime.reset_metrics()
            slot.outstanding.clear()
        self.metrics = ClusterMetrics(self.n_shards, clock=self._clock)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# The class IS the router (ISSUE 7 naming): routing, health, and
# recovery live on the cluster object itself — no extra indirection.
ShardRouter = ServingCluster


# ---------------------------------------------------------------------------
# Reshard: digest-asserted N -> M state migration (grow without genesis
# replay)
# ---------------------------------------------------------------------------

def reshard(src_dir: str, dst_dir: str, n_shards: int,
            clock=time.monotonic) -> Dict[str, Any]:
    """Migrate a DRAINED cluster directory from its current shard count
    to ``n_shards`` fault domains under ``dst_dir`` (which must not
    exist or be empty; ``src_dir`` is left intact as the rollback).

    Protocol: recover every source shard (provable snapshot + journal
    replay — nothing unproven migrates), require a uniform applied seq
    (an undrained cluster refuses), gather the global per-edge
    ``(rank, health)`` carry and the stream position, deal the edges to
    the NEW partition, install the migrated carry into each fresh shard
    and land an IMMEDIATE snapshot at the migrated seq (post-reshard
    recovery never replays from genesis), then **assert the per-edge
    digest is bit-identical across the move** — a divergent migration
    raises instead of serving silently-wrong state.  Per-shard decision
    keys re-derive from the new shard ids (decisions from ``seq+1`` on
    are deterministic in the new geometry); per-shard lifetime counters
    (``n_events``/``n_posts``) reset — they are fault-domain metrics,
    not stream state.  Returns the enveloped report also written to
    ``<dst_dir>/reshard.json`` (schema ``rq.serving.reshard/1``)."""
    import jax.numpy as jnp

    src, _ = ServingCluster.recover(src_dir, clock=clock,
                                    auto_recover=False)
    try:
        rank_g, health_g, seq, t_max, n_batches = src._gather_edges()
        edge_before = src.edge_digest()
        cfg = src._config()
    finally:
        src.close()

    if os.path.exists(dst_dir) and os.listdir(dst_dir):
        raise ValueError(
            f"reshard destination {dst_dir} is not empty — refusing to "
            f"mix with existing serving state")
    dst = ServingCluster(
        n_feeds=int(cfg["n_feeds"]), n_shards=int(n_shards), dir=dst_dir,
        q=float(cfg["q"]), s_sink=np.asarray(cfg["s_sink"], np.float64),
        seed=int(cfg["seed"]), start_seq=int(cfg["start_seq"]),
        snapshot_every=int(cfg["snapshot_every"]),
        reorder_window=int(cfg["reorder_window"]),
        queue_capacity=int(cfg["queue_capacity"]),
        max_batch_events=int(cfg["max_batch_events"]), clock=clock)
    try:
        for slot in dst._slots:
            st = slot.runtime.carry
            migrated = st.replace(
                rank=jnp.asarray(rank_g[slot.feeds]),
                health=jnp.asarray(health_g[slot.feeds]),
                t=jnp.asarray(t_max, st.t.dtype),
                seq=jnp.asarray(seq, jnp.int32),
                n_batches=jnp.asarray(n_batches, jnp.int32))
            slot.runtime.install_carry(migrated)
            slot.runtime.snapshot()
        edge_after = dst.edge_digest()
        if edge_after != edge_before:
            raise RuntimeError(
                f"reshard diverged: per-edge digest "
                f"{edge_after[:12]}.. after migration != "
                f"{edge_before[:12]}.. before — refusing to serve "
                f"migrated state (src left intact at {src_dir}, "
                f"divergent destination removed)")
        report = {
            "src_dir": os.path.abspath(src_dir),
            "dst_dir": os.path.abspath(dst_dir),
            "n_shards_src": int(cfg["n_shards"]),
            "n_shards_dst": int(n_shards),
            "n_feeds": int(cfg["n_feeds"]),
            "seq": int(seq),
            "n_batches": int(n_batches),
            "edge_digest": edge_before,
            "edges_per_shard": [int(len(s.feeds)) for s in dst._slots],
            "verified": True,
        }
        _integrity.write_json(os.path.join(dst_dir, "reshard.json"),
                              report, schema=RESHARD_SCHEMA)
    except BaseException:
        # A half-built destination is a fully-formed cluster directory
        # holding UNVERIFIED migrated state — left on disk, a later
        # ServingCluster.recover(dst_dir) would serve exactly the
        # silently-wrong state the digest assert refuses, so the
        # destination (created by us: it was empty at entry) dies with
        # the failure.
        dst.close()
        shutil.rmtree(dst_dir, ignore_errors=True)
        raise
    dst.close()
    return report
