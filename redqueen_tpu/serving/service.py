"""The online serving runtime: bounded ingest, journaled apply, recovery.

One :class:`ServingRuntime` owns one feed-state carry, one journal, and
one snapshot directory.  The data path is

    submit(batch) -> [validate -> dedup/reorder -> bounded queue]
    poll()        -> [apply (jit, donated) -> journal (fsync) -> commit]
    decide()      -> [read the latest applied carry, never blocks]

Three robustness layers, each deterministic and CI-driven through
``runtime.faultinject``'s ``ingest`` kinds:

**Crash safety.**  Every applied batch lands as one fsynced checksummed
journal record (``serving.journal``) BEFORE the apply is acknowledged,
and every ``snapshot_every`` batches the carry goes through
``utils.checkpoint`` (orbax, corrupt-tolerant ``latest_valid_step``).
:func:`recover` = newest provable snapshot + journal replay: because the
apply step is a pure function of (carry, batch) with counter-addressed
draws, replay reconstructs the killed process's carry and decision
stream **bit-identically** (asserted per record against the journaled
state digest — a divergent replay raises instead of serving wrong
state).

**Idempotent, order-tolerant ingest.**  Sequence-numbered batches;
duplicates drop, a bounded reorder window holds early arrivals, beyond
the window is a typed rejection carrying the missing-seq retransmit
list, malformed events are typed :class:`IngestError` rejections, and a
non-finite rank quarantines exactly that edge via the PR 3 health bits
while healthy edges keep serving.

**Graceful degradation.**  The ingest queue is bounded: past capacity,
new batches are SHED (counted, seqs recorded — never a silent gap) and
the admission carries ``backpressure=True`` from the high-water mark on;
``decide`` always answers from the latest applied carry (stale-but-
served beats blocked) with the backlog depth reported as staleness.
Everything lands in the ``rq.serving.metrics/1`` artifact with the
closed accounting identity ``ingested == applied + shed + rejected +
duplicates (+ pending)``.
"""

from __future__ import annotations

import collections
import os
import time
from typing import (Any, Dict, List, NamedTuple, Optional, Sequence,
                    Tuple)

import numpy as np

from ..runtime import faultinject as _faultinject
from ..runtime import integrity as _integrity
from ..runtime import telemetry as _telemetry
from .events import EventBatch, IngestError, validate_batch
from .ingest import Sequencer
from .journal import (FLUSH_MODES, JOURNAL_FILENAME, Journal,
                      JournalError, replay as journal_replay)
from .metrics import ServingMetrics
from .paramswap import (PARAMS_LOG_FILENAME, PARAMS_LOG_SCHEMA,
                        ValidatedParams, params_digest)
from .state import (Decision, FeedState, init_feed_state, make_apply_fn,
                    make_coalesced_apply_fn, poison_edge, state_digest)

__all__ = ["ServingRuntime", "Admission", "RecoveryInfo", "recover",
           "journal_decisions", "CONFIG_SCHEMA", "SNAPSHOTS_DIRNAME"]

CONFIG_SCHEMA = "rq.serving.config/1"
_JOURNAL = JOURNAL_FILENAME  # shared contract lives in serving.journal
# Public: the cluster layer (serving.cluster) addresses a shard's
# snapshot tree for the corrupt_snapshot fault + recovery assertions.
SNAPSHOTS_DIRNAME = "snapshots"
_SNAPSHOTS = SNAPSHOTS_DIRNAME
_CONFIG = "config.json"


class Admission(NamedTuple):
    """The outcome of one ``submit``: ``status`` is ``accepted`` /
    ``duplicate`` / ``shed`` / ``rejected``; ``backpressure`` asks the
    source to slow down; ``missing`` is the retransmit list when the
    reorder window is blocked on a gap."""

    status: str
    seq: Optional[int] = None
    backpressure: bool = False
    reason: Optional[str] = None
    missing: Tuple[int, ...] = ()


class RecoveryInfo(NamedTuple):
    """What :func:`recover` did: where the carry came from and what the
    journal contributed."""

    snapshot_seq: Optional[int]   # orbax step restored, None = fresh
    replayed: int                 # journal batches re-applied
    skipped: int                  # batches already inside the snapshot
    torn: Optional[Dict[str, Any]]  # quarantined-tail info, None = clean
    recovered_seq: int            # the carry's seq after recovery
    # Acked seqs the journal did NOT keep — the group-commit durability
    # window a power-style crash actually consumed.  Non-empty only when
    # the caller told recover() its ack high-water mark (``acked_seq``);
    # the source's retransmit past ``recovered_seq`` heals exactly these.
    # Under quorum replication this is the EXACT quorum-loss set: with
    # ``heal_replicas`` the surviving holders re-seed the journal before
    # replay, so a seq appears here iff EVERY holder died before its
    # lagging checkpoint.
    lost_acked_seqs: Tuple[int, ...] = ()
    # Seqs re-seeded from surviving replica holders before replay
    # (``heal_replicas``): records the leader's disk lost but the quorum
    # kept — present in the recovered carry, absent from the loss set.
    healed_seqs: Tuple[int, ...] = ()


#: Smallest padded dispatch width the live apply paths use: pad widths
#: are pow-2 BUCKETS between this floor and ``max_batch_events`` (the
#: unified lane layer, ``parallel.lanes.bucket_width``), so a 3-event
#: micro-batch no longer pads to the full configured width while the
#: number of compiled apply shapes stays <= log2(E/floor)+1.  The apply
#: step is bitwise invariant to the pad width (every padded slot is
#: ``valid``-masked; asserted against the full-width path in
#: tests/test_serving_wirespeed.py), so replay/recovery — which may pad
#: at a different width — stays bit-identical.
_PAD_WIDTH_FLOOR = 16


def _pad_width(n_events: int, max_batch_events: int) -> int:
    """Bucketed pad width for a group of ``n_events`` valid events."""
    from ..parallel.lanes import bucket_width

    return bucket_width(int(n_events), floor=_PAD_WIDTH_FLOOR,
                        cap=int(max_batch_events))


def _pad_events(times, feeds, width: int):
    """Pad one batch to the dispatch width (a pow-2 bucket on the live
    path, the full ``max_batch_events`` on replay — the apply step's
    bitwise pad-width invariance makes the two interchangeable)."""
    E = int(width)
    t = np.zeros(E, np.float32)
    f = np.zeros(E, np.int32)
    n = len(times)
    t[:n] = np.asarray(times, np.float64)
    f[:n] = np.asarray(feeds, np.int64)
    return t, f, np.int32(n)


class ServingRuntime:
    """See the module docstring.  Single-writer by design: one process
    owns the directory (the watchdog/lease layer guards multi-process
    misuse at deployment granularity, not here)."""

    def __init__(self, n_feeds: int, q: float = 1.0,
                 s_sink: Optional[np.ndarray] = None, seed: int = 0,
                 dir: Optional[str] = None, start_seq: int = 0,
                 snapshot_every: int = 8, reorder_window: int = 8,
                 queue_capacity: int = 64, max_batch_events: int = 256,
                 fsync_every_n: int = 1, flush_mode: str = "sync",
                 max_unflushed_records: int = 64,
                 max_flush_delay_ms: float = 50.0, coalesce: int = 1,
                 journal_format: Optional[str] = None,
                 replication_factor: int = 0,
                 replication_quorum: Optional[int] = None,
                 replication_mode: str = "thread",
                 replication_ack_timeout_s: float = 1.0,
                 clock=time.monotonic,
                 _state: Optional[FeedState] = None):
        import jax.numpy as jnp

        if n_feeds < 1:
            raise ValueError(f"n_feeds must be >= 1, got {n_feeds}")
        if not (np.isfinite(q) and q > 0):
            raise ValueError(f"q must be finite and > 0, got {q!r}")
        if queue_capacity < 1:
            raise ValueError(
                f"queue_capacity must be >= 1, got {queue_capacity}")
        if snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {snapshot_every}")
        self.n_feeds = int(n_feeds)
        self.q = float(q)
        s = (np.ones(n_feeds) if s_sink is None
             else np.asarray(s_sink, np.float64))
        if s.shape != (n_feeds,):
            raise ValueError(
                f"s_sink must have shape ({n_feeds},), got {s.shape}")
        bad = ~(np.isfinite(s) & (s >= 0))
        if bad.any():
            i = int(np.flatnonzero(bad)[0])
            raise ValueError(
                f"s_sink must be finite and >= 0, got {s[i]!r} at {i}")
        self.seed = int(seed)
        self.dir = dir
        self.snapshot_every = int(snapshot_every)
        self.queue_capacity = int(queue_capacity)
        self.max_batch_events = int(max_batch_events)
        if int(fsync_every_n) < 1:
            raise ValueError(
                f"fsync_every_n must be >= 1, got {fsync_every_n}")
        self.fsync_every_n = int(fsync_every_n)
        if flush_mode not in FLUSH_MODES:
            raise ValueError(f"flush_mode must be one of {FLUSH_MODES}, "
                             f"got {flush_mode!r}")
        self.flush_mode = flush_mode
        self.max_unflushed_records = int(max_unflushed_records)
        self.max_flush_delay_ms = float(max_flush_delay_ms)
        if int(coalesce) < 1:
            raise ValueError(f"coalesce must be >= 1, got {coalesce}")
        self.coalesce = int(coalesce)
        if int(replication_factor) < 0:
            raise ValueError(f"replication_factor must be >= 0, got "
                             f"{replication_factor}")
        self.journal_format = journal_format
        self.replication_factor = int(replication_factor)
        self.replication_quorum = (None if replication_quorum is None
                                   else int(replication_quorum))
        self.replication_mode = str(replication_mode)
        self.replication_ack_timeout_s = float(replication_ack_timeout_s)
        self._clock = clock
        self._s_sink = jnp.asarray(s, jnp.float32)
        self._q = jnp.asarray(self.q, jnp.float32)
        # Two-slot epoch state for the guarded hot-swap (serving.
        # paramswap): epoch 0 is the constructor's vetted params; every
        # install bumps the epoch and retains the outgoing slot as the
        # rollback target.  The jnp param arrays are immutable and the
        # jitted applies take them as ARGUMENTS, so an in-flight apply
        # that captured the old arrays finishes on the old epoch with
        # no lock on the decision path.
        self._param_epoch = 0
        self._param_fingerprint = "initial"
        self._param_prev: Optional[Dict[str, Any]] = None
        self._apply = make_apply_fn()
        self._apply_many = (make_coalesced_apply_fn()
                            if self.coalesce > 1 else None)
        self._queue: collections.deque = collections.deque()
        # arrival stamps for batches held in the reorder window (popped
        # when they drain into the queue; bounded by the window size)
        self._arrival: Dict[int, float] = {}
        self._seq = Sequencer(start_seq=start_seq, window=reorder_window)
        self.metrics = ServingMetrics(clock=clock)
        self._last_decision: Optional[Decision] = None
        self._since_snapshot = 0
        self._fault = _faultinject.ingest_fault()

        if _state is not None:
            self._state = _state
            self._seq.next_seq = int(np.asarray(_state.seq)) + 1
        else:
            self._state = init_feed_state(n_feeds, seed,
                                          start_seq=start_seq)
            self._state = self._maybe_poison(self._state)

        self._prewarm_pad_widths()
        self._journal: Optional[Journal] = None
        if dir is not None:
            os.makedirs(dir, exist_ok=True)
            cfg_path = os.path.join(dir, _CONFIG)
            cfg = {
                "n_feeds": self.n_feeds, "q": self.q,
                "s_sink": [float(x) for x in s],
                "seed": self.seed, "start_seq": int(start_seq),
                "snapshot_every": self.snapshot_every,
                "reorder_window": int(reorder_window),
                "queue_capacity": self.queue_capacity,
                "max_batch_events": self.max_batch_events,
                # Durability/throughput knobs, NOT replay identity:
                # group commit changes when records hit media and
                # coalescing changes how many batches share a dispatch/
                # record, never what either says (the coalesced apply is
                # grouping-invariant bitwise — asserted in tests) — so
                # they are recorded (recover() reuses them) but excluded
                # from the mismatch refusal below.
                "fsync_every_n": self.fsync_every_n,
                "flush_mode": self.flush_mode,
                "max_unflushed_records": self.max_unflushed_records,
                "max_flush_delay_ms": self.max_flush_delay_ms,
                "coalesce": self.coalesce,
                # Same non-identity class: the journal encoding and the
                # replication group shape where/when records persist,
                # never what they say — replay is format-sniffing and
                # quorum is an ack property, so both are recorded for
                # recover() but excluded from the refusal below.
                "journal_format": self.journal_format,
                "replication_factor": self.replication_factor,
                "replication_quorum": self.replication_quorum,
                "replication_mode": self.replication_mode,
            }
            if os.path.exists(cfg_path):
                # The stored config is the directory's identity: the
                # journal/snapshots in it were produced under these
                # parameters, and recover() rebuilds from them.  A
                # constructor that silently disagrees on a
                # determinism-critical field would journal records the
                # stored config can no longer replay — wedging the
                # directory with a misleading digest-divergence error
                # at the NEXT recovery.  Refuse loudly instead.
                stored = _integrity.read_json(cfg_path,
                                              schema=CONFIG_SCHEMA)
                for field in ("n_feeds", "q", "s_sink", "seed",
                              "start_seq", "max_batch_events"):
                    if stored.get(field) != cfg[field]:
                        raise ValueError(
                            f"serving dir {dir} was created with "
                            f"{field}={stored.get(field)!r} but this "
                            f"runtime was constructed with "
                            f"{field}={cfg[field]!r} — replay would "
                            f"diverge; recover() the directory with "
                            f"its stored config, or use a fresh "
                            f"directory")
            else:
                _integrity.write_json(cfg_path, cfg,
                                      schema=CONFIG_SCHEMA)
            if self.replication_factor >= 1:
                from .replication import ReplicatedJournal
                self._journal = ReplicatedJournal(
                    os.path.join(dir, _JOURNAL),
                    factor=self.replication_factor,
                    quorum=self.replication_quorum,
                    mode=self.replication_mode,
                    ack_timeout_s=self.replication_ack_timeout_s,
                    fsync_every_n=self.fsync_every_n,
                    max_unflushed_records=self.max_unflushed_records,
                    max_flush_delay_ms=self.max_flush_delay_ms,
                    fmt=self.journal_format)
            else:
                self._journal = Journal(
                    os.path.join(dir, _JOURNAL),
                    fsync_every_n=self.fsync_every_n,
                    flush_mode=self.flush_mode,
                    max_unflushed_records=self.max_unflushed_records,
                    max_flush_delay_ms=self.max_flush_delay_ms,
                    fmt=self.journal_format)

    # ---- ingest path ----

    def _maybe_poison(self, state: FeedState) -> FeedState:
        """The ``numeric`` fault kind addresses serving EDGES the way it
        addresses sim lanes (deterministic stand-in for an in-memory bit
        flip), so the edge-quarantine path runs in CI."""
        hit = _faultinject.active_numeric_lane(self.n_feeds)
        if hit is None:
            return state
        lane, mode = hit
        return poison_edge(state, lane, mode)

    @property
    def pending(self) -> int:
        """Batches accepted but not yet applied (queued + held in the
        reorder window)."""
        return len(self._queue) + self._seq.held

    @property
    def applied_seq(self) -> int:
        return int(np.asarray(self._state.seq))

    @property
    def carry(self) -> FeedState:
        """Read-only view of the live carry — the cluster layer's state-
        migration (reshard) and edge-digest paths read it through one
        explicit ``jax.device_get`` boundary on their side; mutating it
        would desynchronize the journal, so don't."""
        return self._state

    @property
    def journal_path(self) -> Optional[str]:
        """The LIVE journal file (None when running without a directory)
        — what the cluster's ``shard:torn_journal`` fault tears."""
        return None if self._journal is None else self._journal.path

    def next_queued_seq(self) -> Optional[int]:
        """Sequence number of the batch the next ``poll(max_batches=1)``
        would apply, or None when the queue is empty — the cluster
        router's per-batch dispatch peek (it polls one batch at a time
        so shard faults land at exact sequence numbers)."""
        return int(self._queue[0][0].seq) if self._queue else None

    def reset_metrics(self) -> None:
        """Start a fresh metrics block (same contract as recovery: the
        report describes steady state from this instant).  Refused while
        batches are pending — zeroing the counters under a live backlog
        would break the closed accounting identity."""
        if self.pending:
            raise ValueError(
                f"cannot reset metrics with {self.pending} batches "
                f"pending — drain (poll) first")
        self.metrics = ServingMetrics(clock=self._clock)
        # submit() copies the sequencer's lifetime counters into the
        # report by absolute overwrite — pre-reset duplicate/reorder
        # traffic would resurface as phantom counts and break the
        # closed identity, so they reset with the ledger.
        self._seq.duplicates = 0
        self._seq.reordered = 0
        self._seq.window_rejects = 0

    def install_carry(self, state: FeedState) -> None:
        """Replace the carry with a MIGRATED one (the cluster reshard
        path).  Only legal on a fresh runtime — nothing applied, nothing
        queued, nothing journaled — and the caller must ``snapshot()``
        right after so the migrated state has a durable recovery base
        (the journal holds no records for it)."""
        # Freshness witness is the carry's apply counter, NOT
        # applied_seq: a fresh runtime built with start_seq=S sits at
        # applied_seq=S-1 (>= 0 for any S > 0), but n_batches is 0
        # until something actually applies.
        n_applied = int(np.asarray(self._state.n_batches))
        if self.pending or n_applied:
            raise ValueError(
                f"install_carry needs a fresh runtime (pending="
                f"{self.pending}, batches applied={n_applied}) — "
                f"migrating over live serving state would desync the "
                f"journal")
        if state.rank.shape != (self.n_feeds,):
            raise ValueError(
                f"migrated carry has {state.rank.shape[0]} edges, this "
                f"runtime serves {self.n_feeds}")
        self._state = state
        self._seq.next_seq = int(np.asarray(state.seq)) + 1

    # ---- live resharding range handoff (serving.topology drives) ----

    def extract_range(self, idx: Sequence[int]
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """The carry slice for local feed indices ``idx`` as host
        arrays ``(rank f32, health u32)`` — what the migration fence
        streams to the destination.  Read-only, but only meaningful on
        a drained runtime (a pending batch could still mutate the
        slice)."""
        if self.pending:
            raise ValueError(
                f"extract_range with {self.pending} batches pending — "
                f"drain (poll) first; a queued apply could mutate the "
                f"fenced slice")
        idx = np.asarray(idx, np.int64)
        if idx.size and (idx.min() < 0 or idx.max() >= self.n_feeds):
            raise ValueError(
                f"extract_range indices out of range for {self.n_feeds}"
                f" feeds")
        r, h, _sq, _t, _nb = self.gather()
        return r[idx].copy(), h[idx].copy()

    def install_range(self, idx: Sequence[int], rank: np.ndarray,
                      health: np.ndarray, *, feeds: Sequence[int],
                      topo_epoch: int, digest: str, plan_id: str,
                      range_id: int) -> None:
        """Install one migrated range into the carry — the journaled,
        digest-asserted, IDEMPOTENT scatter-set the live-reshard flip
        depends on (``serving.topology.Migration`` calls this only
        after ``assert_fenced`` — rqlint RQ1007 flags unguarded call
        sites).

        The record lands in this shard's own journal (fsynced, like a
        parameter-epoch record) BEFORE the in-memory flip, keyed by
        ``topo_epoch`` and pinned to the current applied seq, so
        recovery re-applies it at exactly the same stream position —
        and because it is a pure set of journaled values, replaying it
        twice (a resumed migration re-installs after a crash) is
        bit-identical to once."""
        import jax.numpy as jnp

        if self.pending:
            raise ValueError(
                f"install_range with {self.pending} batches pending — "
                f"drain (poll) first; the install must land at a "
                f"well-defined stream position")
        idx = np.asarray(idx, np.int32)
        r = np.ascontiguousarray(np.asarray(rank, np.float32))
        h = np.ascontiguousarray(np.asarray(health, np.uint32))
        feeds = [int(f) for f in feeds]
        if not (idx.shape == r.shape == h.shape
                and len(feeds) == idx.shape[0]):
            raise ValueError(
                f"install_range arrays disagree: {idx.shape[0]} "
                f"indices, {r.shape[0]} ranks, {h.shape[0]} health "
                f"words, {len(feeds)} feeds")
        if idx.size and (idx.min() < 0 or idx.max() >= self.n_feeds):
            raise ValueError(
                f"install_range indices out of range for "
                f"{self.n_feeds} feeds")
        from .topology import range_digest
        got = range_digest(feeds, r, h)
        if got != digest:
            raise RuntimeError(
                f"range payload digest mismatch at install: fence "
                f"says {str(digest)[:12]}.., arrays hash to "
                f"{got[:12]}.. — the slice was altered between fence "
                f"and install; refusing")
        new = self._state.replace(
            rank=self._state.rank.at[idx].set(jnp.asarray(r)),
            health=self._state.health.at[idx].set(jnp.asarray(h)))
        rec = {
            "topo_epoch": int(topo_epoch),
            "plan": str(plan_id),
            "range": int(range_id),
            "seq": self.applied_seq,
            "idx": [int(i) for i in idx],
            "feeds": feeds,
            "rank": [float(x) for x in r],
            "health": [int(x) for x in h],
            "digest": str(digest),
            "state_digest": state_digest(new),
        }
        with _telemetry.span("serving.topo.install_range",
                             plan=str(plan_id), range=int(range_id)):
            if self._journal is not None:
                try:
                    self._journal.append(rec, seq=self.applied_seq)
                    # Same durability contract as a param install: the
                    # flip the router is about to journal must never
                    # outlive this record in a crash.
                    self._journal.sync()
                except OSError as e:
                    raise RuntimeError(
                        f"journal append failed for topology epoch "
                        f"{topo_epoch} range install: {e} — range "
                        f"installs must be durable; restart and recover "
                        f"from {self.dir}") from e
            self._state = new

    # ---- live-parameter epoch swap (serving.paramswap is the gate) ----

    def live_params(self) -> Dict[str, Any]:
        """The policy parameters currently deciding, as host arrays —
        what the swapper snapshots before an install (the rollback
        target) and what ``status`` surfaces."""
        return {
            "s_sink": np.asarray(self._s_sink, np.float64).copy(),
            "q": float(np.asarray(self._q)),
            "epoch": self._param_epoch,
            "fingerprint": self._param_fingerprint,
        }

    def previous_params(self) -> Optional[Dict[str, Any]]:
        """The retained previous slot (last-good before the newest
        install); None before any install."""
        return None if self._param_prev is None else dict(self._param_prev)

    def install_params(self, vp: ValidatedParams) -> int:
        """Atomically install gate-validated parameters as a new epoch.

        Takes ONLY a :class:`serving.paramswap.ValidatedParams` token
        (minted by ``ParamGate`` — the validation gate is the one road
        into the live policy; rqlint RQ1006 flags raw-assignment
        bypasses).  The token's digest is re-derived from the arrays
        immediately before the flip — a mismatch means tampering
        between gate and install and refuses loudly.  Returns the new
        epoch."""
        if not isinstance(vp, ValidatedParams):
            raise TypeError(
                f"install_params takes a ValidatedParams token minted "
                f"by serving.paramswap.ParamGate, got "
                f"{type(vp).__name__} — raw parameters cannot be "
                f"installed into the live policy")
        s = np.ascontiguousarray(np.asarray(vp.s_sink, np.float64))
        if s.shape != (self.n_feeds,):
            raise ValueError(
                f"candidate s_sink has shape {s.shape}, this runtime "
                f"serves {self.n_feeds} feeds")
        q = float(vp.q)
        got = params_digest(s, q)
        if got != vp.digest:
            raise RuntimeError(
                f"params digest mismatch at install: token says "
                f"{vp.digest}, arrays hash to {got} — the token was "
                f"altered after validation; refusing to install")
        return self._install_validated(s, q, vp.fingerprint, vp.digest)

    def _install_validated(self, s64: np.ndarray, q: float,
                           fingerprint: str, digest: str,
                           journal: bool = True) -> int:
        """The ONE sanctioned assignment site for the live policy
        params outside ``__init__`` (RQ1006's allowlist).  Journals the
        install (digest-asserted epoch record, fsynced — never inside
        the group-commit loss window) and mirrors it into the
        ``params_log.json`` sidecar so recovery replays every batch
        under the epoch that decided it even after segment pruning;
        ``journal=False`` is recovery re-installing an epoch the
        journal already carries.

        Durability-before-swap (RQ1302): the epoch record reaches the
        journal (append + sync) BEFORE the in-memory slots flip, so a
        crash anywhere in the gap either replays the old epoch (record
        never landed, swap never happened) or the new one (record is
        durable) — never serves parameters the journal cannot
        reproduce.  A failed append leaves the previous epoch serving
        untouched."""
        import jax.numpy as jnp

        epoch = self._param_epoch + 1
        if journal and self._journal is not None:
            rec = {
                "epoch": epoch,
                "seq": self.applied_seq,
                "s_sink": [float(x) for x in s64],
                "q": float(q),
                "fingerprint": str(fingerprint),
                "digest": str(digest),
                "state_digest": state_digest(self._state),
            }
            try:
                self._journal.append(rec, seq=self.applied_seq)
                # The install record must never sit in the async loss
                # window: a crash right after an install has to replay
                # under the installed epoch, so force it to media (and
                # to the replicas' checkpoint path) before the swap
                # below makes it live.
                self._journal.sync()
            except OSError as e:
                raise RuntimeError(
                    f"journal append failed for epoch "
                    f"{epoch} install: {e} — parameter "
                    f"installs must be durable; restart and recover "
                    f"from {self.dir}") from e
            self._append_params_log(rec)
        # the guarded swap: by here the epoch record is on media, so
        # the span's start strictly follows the durability spans — the
        # ordering --calibrate replays a chaos trace against (RQ1302)
        with _telemetry.span("serving.params.install", epoch=epoch):
            self._param_prev = self.live_params()
            self._param_epoch = epoch
            self._param_fingerprint = str(fingerprint)
            self._s_sink = jnp.asarray(s64, jnp.float32)
            self._q = jnp.asarray(q, jnp.float32)
            self.q = float(q)
        return self._param_epoch

    def _append_params_log(self, rec: Dict[str, Any]) -> None:
        """Mirror one install into the sidecar log (full history,
        atomic rewrite — installs are rare; the journal's epoch record
        is the hot-path write, this is the prune-survivable index)."""
        path = os.path.join(self.dir, PARAMS_LOG_FILENAME)
        try:
            log = _integrity.read_json(path, schema=PARAMS_LOG_SCHEMA)
        except FileNotFoundError:
            log = {"installs": []}
        except _integrity.CorruptArtifactError:
            # The new params are already live and their epoch record is
            # journaled + fsynced — a corrupt sidecar must not fail the
            # install (it would raise post-install and then fail every
            # future install too).  read_json quarantined the bad file;
            # rebuild the index from the journal's own epoch records.
            # Installs whose segments were pruned are unrecoverable
            # here, degrading recovery to journal-reachable epochs.
            log = {"installs": self._rebuild_params_log_installs(
                before_epoch=int(rec["epoch"]))}
        log["installs"].append(
            {k: rec[k] for k in ("epoch", "seq", "s_sink", "q",
                                 "fingerprint", "digest")})
        _integrity.write_json(path, log, schema=PARAMS_LOG_SCHEMA)

    def _rebuild_params_log_installs(
            self, before_epoch: int) -> List[Dict[str, Any]]:
        """Reconstruct the sidecar's install list from the journal's
        epoch records (every install is appended + fsynced there before
        the sidecar mirror, so all epochs < ``before_epoch`` that still
        have their segments are on media).  Read-only: the live file's
        tail is never quarantined from here.  A journal that cannot be
        replayed yields an empty list — a fresh sidecar beats wedging
        the install path."""
        try:
            records, _ = journal_replay(
                os.path.join(self.dir, _JOURNAL),
                quarantine_torn_tail=False)
        except (OSError, JournalError):
            return []
        return [{k: r[k] for k in ("epoch", "seq", "s_sink", "q",
                                   "fingerprint", "digest")}
                for r in records
                if "epoch" in r and int(r["epoch"]) < before_epoch]

    def submit(self, batch: EventBatch,
               _validated: bool = False) -> Admission:
        """Admit one micro-batch; never raises on bad input — typed
        failures come back as the admission status (the source-facing
        boundary must stay up under garbage).  ``_validated`` is the
        cluster router's trusted path: a sub-batch it fans out is a
        masked slice of a batch that already passed ``validate_batch``
        (coerced dtypes, non-decreasing times, in-range local feeds by
        construction), so re-validating every slice would double the
        O(events) host work on the measured ingest path."""
        with _telemetry.span("serving.admit") as tsp:
            adm = self._submit(batch, _validated)
            tsp.set(status=adm.status)
            return adm

    def _submit(self, batch: EventBatch,
                _validated: bool = False) -> Admission:
        self.metrics.ingested += 1
        backpressure = self.pending >= max(self.queue_capacity * 3 // 4, 1)
        if not _validated:
            try:
                batch = validate_batch(batch, self.n_feeds,
                                       max_events=self.max_batch_events)
            except IngestError as e:
                self.metrics.rejected += 1
                return Admission("rejected", seq=e.seq, reason=str(e),
                                 backpressure=backpressure)
        cls = self._seq.classify(batch.seq)
        if cls != "new":
            # Redundant deliveries drop BEFORE the capacity check — they
            # must never pollute the shed accounting.  "applied" comes
            # back as a duplicate ADMISSION (an ack: the batch is in the
            # journal, the source may stop retransmitting); a retransmit
            # of a merely HELD batch comes back "accepted" — it is
            # buffered but NOT yet durable, and acking it would lose it
            # if the process dies before the gap closes.
            self._seq.offer(batch)  # counts it; touches no queue state
            self.metrics.duplicates = self._seq.duplicates
            return Admission(
                "duplicate" if cls == "applied" else "accepted",
                seq=batch.seq, backpressure=backpressure,
                missing=tuple(self._seq.missing_seqs()))
        if len(self._queue) >= self.queue_capacity:
            # Overload: bounded queue sheds the NEWEST arrival (the
            # in-window backlog stays coherent) and records exactly what
            # was dropped; the source retransmits when admission opens.
            # (A gap-closing batch may drain up to reorder_window held
            # batches past this check in one append — they are in-order
            # and cannot be shed without corrupting the stream — so the
            # hard memory bound is queue_capacity + reorder_window.)
            self.metrics.observe_shed(batch.seq, batch.n_events)
            return Admission("shed", seq=batch.seq, backpressure=True,
                             reason="ingest queue at capacity")
        try:
            _, ready = self._seq.offer(batch)
        except IngestError as e:
            self.metrics.rejected += 1
            self.metrics.window_rejects = self._seq.window_rejects
            return Admission(
                "rejected", seq=batch.seq, backpressure=True,
                reason=str(e),
                missing=tuple(self._seq.missing_seqs()
                              or [self._seq.next_seq]))
        # Latency is wall-clock ARRIVAL->decision: a batch held in the
        # reorder window keeps its original arrival stamp, so the time
        # it spent waiting for the gap to close is measured, not hidden.
        now = self._clock()
        self._arrival[int(batch.seq)] = now
        for b in ready:
            self._queue.append((b, self._arrival.pop(int(b.seq), now)))
        self.metrics.reordered = self._seq.reordered
        self.metrics.duplicates = self._seq.duplicates
        return Admission("accepted", seq=batch.seq,
                         backpressure=backpressure,
                         missing=tuple(self._seq.missing_seqs()))

    # ---- apply path ----

    def _prewarm_pad_widths(self) -> None:
        """Compile every bucketed apply shape UP FRONT: pad widths are a
        small bounded set (pow-2 from ``_PAD_WIDTH_FLOOR`` to
        ``max_batch_events``), and paying the traces at construction
        keeps the wire-speed path free of mid-traffic compile stalls
        when a rare width first appears.  Each warm call runs on a
        THROWAWAY state (never the live carry — the jitted fns donate
        their state argument on donating backends), and the jit dispatch
        cache is process-global, so later runtimes with the same feed
        count warm for free."""
        import jax.numpy as jnp

        # No telemetry span here on purpose: construction runs OUTSIDE
        # any serving trace root, and an orphan span would break the
        # one-trace-per-round invariant the span-chain tests pin.
        widths, E = [], _PAD_WIDTH_FLOOR
        while E < int(self.max_batch_events):
            widths.append(E)
            E *= 2
        widths.append(int(self.max_batch_events))
        for E in sorted(set(min(w, int(self.max_batch_events))
                            for w in widths)):
            dummy = init_feed_state(self.n_feeds, 0)
            t = np.zeros(E, np.float32)
            f = np.zeros(E, np.int32)
            self._apply(dummy, t, f, np.int32(0), np.int32(0),
                        self._s_sink, self._q)
            if self._apply_many is not None:
                K = self.coalesce
                dummy = init_feed_state(self.n_feeds, 0)
                self._apply_many(
                    dummy, jnp.zeros((K, E), jnp.float32),
                    jnp.zeros((K, E), jnp.int32),
                    jnp.zeros((K,), jnp.int32),
                    jnp.zeros((K,), jnp.int32), np.int32(0),
                    self._s_sink, self._q)

    def _pad(self, batch: EventBatch):
        E = _pad_width(batch.n_events, self.max_batch_events)
        _telemetry.counter("lanes.pad.real_elems", int(batch.n_events))
        _telemetry.counter("lanes.pad.padded_elems",
                           E - int(batch.n_events))
        return _pad_events(batch.times, batch.feeds, E)

    def _append_record(self, batch: EventBatch, decision: Decision,
                       new_state: FeedState) -> None:
        self._journal.append({
            "seq": int(batch.seq),
            "times": [float(t) for t in batch.times],
            "feeds": [int(f) for f in batch.feeds],
            "decision": {"post": decision.post,
                         "post_time": decision.post_time,
                         "intensity": decision.intensity},
            "state_digest": state_digest(new_state),
        })

    def _apply_one(self, batch: EventBatch, submitted_at: float) -> Decision:
        import jax

        # Stage spans under the current trace (the poll round / the
        # worker request): coalesce = host-side packing, dispatch = the
        # jitted enqueue, sync = the device→host wait (async dispatch
        # means the device time surfaces HERE, not in dispatch — the
        # same honesty split the benches use), then journal (its own
        # span inside Journal.append) and ack.
        with _telemetry.span("serving.coalesce"):
            times, feeds, n = self._pad(batch)
        with _telemetry.span("serving.dispatch"):
            new_state, (posted, t_new, lam) = self._apply(
                self._state, times, feeds, n, np.int32(batch.seq),
                self._s_sink, self._q)
        # The ONE deliberate device→host boundary of the apply path: the
        # decision must reach the caller and the journal this batch, so
        # the transfer is per-batch by CONTRACT (serving, not batch sim);
        # it is explicit and batched into a single device_get.
        with _telemetry.span("serving.sync"):
            posted, t_new, lam = jax.device_get((posted, t_new, lam))  # rqlint: disable=RQ702 per-batch decision boundary
        decision = Decision(
            seq=batch.seq, post=bool(posted), post_time=float(t_new),
            intensity=float(lam), stale_batches=self.pending)
        if self._journal is not None:
            # Journal BEFORE commit: the record is the acknowledgement.
            # digest is of the POST-apply carry — the replay witness.
            # An append failure (disk full, yanked volume) is FATAL by
            # design: the carry can no longer be made durable (and on a
            # donating backend the pre-apply buffers are already gone),
            # so continuing would silently widen the unjournaled window
            # — fail fast, restart, recover() from the last durable
            # state; the source retransmits everything un-acked.
            try:
                self._append_record(batch, decision, new_state)
            except OSError as e:
                raise RuntimeError(
                    f"journal append failed for batch {batch.seq}: {e} "
                    f"— serving state can no longer be made durable; "
                    f"restart and recover from {self.dir}") from e
            self._post_append_faults(int(batch.seq))
        with _telemetry.span("serving.ack"):
            self._state = new_state
            self._last_decision = decision
            latency = (self._clock() - submitted_at
                       if submitted_at is not None else None)
            self.metrics.observe_apply(batch.n_events, decision.post,
                                       latency)
            self._since_snapshot += 1
        if self.dir is not None and \
                self._since_snapshot >= self.snapshot_every:
            self.snapshot()
        self._post_commit_faults(int(batch.seq))
        return decision

    def _post_append_faults(self, seq: int) -> None:
        """Ingest faults that fire right after seq's journal append
        (shared by the per-batch and coalesced paths — a coalesced group
        is pre-split so the addressed batch always ENDS its record)."""
        f = self._fault
        if f is None or f.batch != seq:
            return
        if f.mode == "torn_journal":
            # Crash DURING this append: the record went out torn and
            # the process died before the commit/snapshot — the batch
            # was never acknowledged, so the journal and snapshots stay
            # mutually consistent at the previous seq and the source
            # will retransmit.  Tear the line we just wrote, then die
            # without cleanup.
            from .journal import tear_tail

            tear_tail(self._journal.path)
            os._exit(19)
        if f.mode == "crash_in_window":
            # The POWER-LOSS shape: the append was acked but its fsync
            # had not landed — drop every byte past the durability
            # watermark (what a machine crash provably keeps), then die.
            # Under flush_mode="sync"/fsync_every_n=1 the watermark IS
            # the last append and this degenerates to a plain crash;
            # under group commit it consumes the documented loss window.
            self._journal.power_loss()
            os._exit(23)

    def _post_commit_faults(self, seq: int) -> None:
        f = self._fault
        if (f is not None and f.mode == "crash_after_apply"
                and f.batch == seq):
            # The kill -9 shape: no atexit, no flush beyond the fsyncs
            # already landed — the acceptance test's mid-stream SIGKILL.
            # (Flushed-but-unfsynced group-commit bytes survive a
            # process kill in the page cache, so this stays lossless
            # under async group commit too.)
            os._exit(17)

    # The ingest fault modes that must END a coalesced group at their
    # addressed batch (so they fire at the exact seq, like the
    # per-batch path).
    _SPLIT_FAULTS = ("torn_journal", "crash_after_apply",
                     "crash_in_window")

    def _apply_group(self, group) -> List[Decision]:
        """Apply one coalesced group — ONE jitted dispatch, ONE
        device→host transfer, ONE journal record for up to ``coalesce``
        queued batches.  Bitwise identical to applying them one at a
        time (``state.make_coalesced_apply_fn``), so recovery and the
        chaos acceptance digests are grouping-independent."""
        import jax

        K = self.coalesce
        k = len(group)
        # Bucketed pad width for the WHOLE group (one dispatch shape per
        # poll round): the widest member's bucket, not the configured
        # max — the unified lane layer's pad-waste lever, bitwise
        # invariant to the width (see _PAD_WIDTH_FLOOR).
        real = sum(int(b.n_events) for b, _ in group)
        E = _pad_width(max(int(b.n_events) for b, _ in group),
                       self.max_batch_events)
        with _telemetry.span("serving.coalesce") as csp:
            # Waste is accounted at the DISPATCH shape (K, E) — the
            # (K - k) empty group rows are padding too, and on lightly
            # loaded rounds they are the dominant term.
            csp.set(k=k, pad_width=E,
                    pad_frac=round(1.0 - real / (K * E), 4))
            _telemetry.counter("lanes.pad.real_elems", real)
            _telemetry.counter("lanes.pad.padded_elems", K * E - real)
            times = np.zeros((K, E), np.float32)
            feeds = np.zeros((K, E), np.int32)
            nvalid = np.zeros((K,), np.int32)
            seqs = np.zeros((K,), np.int32)
            for j, (b, _at) in enumerate(group):
                t, f, n = _pad_events(b.times, b.feeds, E)
                times[j], feeds[j], nvalid[j], seqs[j] = \
                    t, f, n, int(b.seq)
        with _telemetry.span("serving.dispatch"):
            new_state, (posted, t_new, lam) = self._apply_many(
                self._state, times, feeds, nvalid, seqs, np.int32(k),
                self._s_sink, self._q)
        # The ONE deliberate device→host boundary of the coalesced apply
        # path: one transfer per poll ROUND (amortized over the group),
        # not per batch.
        with _telemetry.span("serving.sync"):
            posted, t_new, lam = jax.device_get((posted, t_new, lam))  # rqlint: disable=RQ702 per-round decision boundary
        stale = self.pending
        decisions = [
            Decision(seq=int(b.seq), post=bool(posted[j]),
                     post_time=float(t_new[j]), intensity=float(lam[j]),
                     stale_batches=stale)
            for j, (b, _at) in enumerate(group)]
        if self._journal is not None:
            seqs_l = [int(b.seq) for b, _ in group]
            dec_l = [{"post": d.post, "post_time": d.post_time,
                      "intensity": d.intensity} for d in decisions]
            digest = state_digest(new_state)
            try:
                if self.journal_format == "binary":
                    # Zero-copy group record: the validated batch
                    # arrays land in the binary slot as raw bytes
                    # (journal.pack_group_body) — no per-event JSON
                    # float walk on the leader (ROADMAP residue 1(a)).
                    from .journal import pack_group_body
                    body = pack_group_body(
                        seqs_l,
                        [int(b.n_events) for b, _ in group],
                        np.concatenate(
                            [np.asarray(b.times, np.float64)
                             for b, _ in group]),
                        np.concatenate(
                            [np.asarray(b.feeds, np.int64)
                             for b, _ in group]),
                        dec_l, digest)
                    self._journal.append_raw(body, seq=seqs_l[-1])
                else:
                    rec = {
                        "seqs": seqs_l,
                        "counts": [int(b.n_events) for b, _ in group],
                        "times": [float(t) for b, _ in group
                                  for t in b.times],
                        "feeds": [int(f) for b, _ in group
                                  for f in b.feeds],
                        "decisions": dec_l,
                        "state_digest": digest,
                    }
                    self._journal.append(rec, seq=seqs_l[-1])
            except OSError as e:
                raise RuntimeError(
                    f"journal append failed for batches "
                    f"{seqs_l[0]}..{seqs_l[-1]}: {e} — serving "
                    f"state can no longer be made durable; restart and "
                    f"recover from {self.dir}") from e
            self._post_append_faults(int(group[-1][0].seq))
        with _telemetry.span("serving.ack"):
            self._state = new_state
            self._last_decision = decisions[-1]
            now = self._clock()
            for (b, at), d in zip(group, decisions):
                self.metrics.observe_apply(
                    b.n_events, d.post, None if at is None else now - at)
            self._since_snapshot += k
        if self.dir is not None and \
                self._since_snapshot >= self.snapshot_every:
            self.snapshot()
        self._post_commit_faults(int(group[-1][0].seq))
        return decisions

    def _take_group(self, limit: int):
        """Pop up to ``limit`` queued batches, cutting the group so an
        armed split-fault batch lands LAST in its record."""
        f = self._fault
        split_at = (f.batch if f is not None
                    and f.mode in self._SPLIT_FAULTS else None)
        group = []
        while self._queue and len(group) < limit:
            b, at = self._queue.popleft()
            group.append((b, at))
            if split_at is not None and int(b.seq) == split_at:
                break
        return group

    def poll(self, max_batches: Optional[int] = None) -> List[Decision]:
        """Apply up to ``max_batches`` queued batches (all, by default);
        returns their decisions.  With ``coalesce > 1`` the batches are
        applied in groups of up to ``coalesce`` — one jitted dispatch,
        one device→host transfer, and one journal record per group (the
        wire-speed ingest path).  Bounding the per-poll work is the
        overload throttle: a slow consumer polls small, the queue fills,
        and submit() starts shedding — bounded memory, no deadlock."""
        with _telemetry.span("serving.poll") as tsp:
            out: List[Decision] = []
            if self.coalesce == 1:
                while self._queue and (max_batches is None
                                       or len(out) < max_batches):
                    batch, submitted_at = self._queue.popleft()
                    out.append(self._apply_one(batch, submitted_at))
                tsp.set(applied=len(out))
                return out
            while self._queue and (max_batches is None
                                   or len(out) < max_batches):
                limit = self.coalesce
                if max_batches is not None:
                    limit = min(limit, max_batches - len(out))
                group = self._take_group(limit)
                if not group:
                    break
                out.extend(self._apply_group(group))
            tsp.set(applied=len(out))
            return out

    # ---- decision path (never blocks on the backlog) ----

    def decide(self) -> Optional[Decision]:
        """The deadline-bounded read path: the latest applied decision,
        immediately, with the unapplied backlog reported as staleness —
        stale-but-served beats blocked.  None until a first batch
        applies."""
        self.metrics.decisions_served += 1
        if self._last_decision is None:
            return None
        stale = self.pending
        if stale:
            self.metrics.stale_decisions += 1
        return self._last_decision._replace(stale_batches=stale)

    # ---- durability ----

    def snapshot(self) -> Optional[int]:
        """Land the carry as an orbax step (step number = applied seq),
        then rotate the live journal into a segment and prune segments
        covered by every retained snapshot — the journal's total size
        stays bounded by the retained-snapshot window instead of growing
        for the process lifetime (recovery reads segments + live).
        No-op without a serving directory.  Returns the step written."""
        if self.dir is None:
            return None
        seq = self.applied_seq
        if seq < 0:
            return None
        with _telemetry.span("serving.snapshot") as tsp:
            tsp.set(seq=seq)
            # Inside the span on purpose: the FIRST snapshot pays the
            # orbax import (~1s) right here, and unattributed it reads
            # as mystery poll self-time in every breakdown (found by
            # this subsystem's own rqtrace output).
            from ..utils import checkpoint as _checkpoint
            from . import journal as _journal_mod
            snap_dir = os.path.join(self.dir, _SNAPSHOTS)
            _checkpoint.save(snap_dir, seq, self._state)
            self._since_snapshot = 0
            if self._journal is not None:
                steps = [int(n) for n in os.listdir(snap_dir)
                         if n.isdigit()]
                oldest = min(steps) if steps else None
                if hasattr(self._journal, "rotate_local"):
                    # Replicated: rotate leader + replicas in stream
                    # order, keeping the follower group attached.
                    self._journal.rotate_local(seq, oldest)
                else:
                    path = self._journal.path
                    self._journal.close()
                    _journal_mod.rotate(path, seq)
                    if oldest is not None:
                        _journal_mod.prune_segments(path, oldest)
                    self._journal = Journal(
                        path, fsync_every_n=self.fsync_every_n,
                        flush_mode=self.flush_mode,
                        max_unflushed_records=self.max_unflushed_records,
                        max_flush_delay_ms=self.max_flush_delay_ms,
                        fmt=self.journal_format)
        return seq

    def durability(self) -> Dict[str, Any]:
        """The configured durability window — what an ack MEANS under
        this runtime's flush mode (committed beside every throughput
        number so bench results are never quoted without their
        durability cost; ``journal.durability_info`` is the one
        definition)."""
        from .journal import durability_info

        repl = None
        if self.replication_factor >= 1:
            repl = {"factor": self.replication_factor,
                    "quorum": (self.replication_quorum
                               if self.replication_quorum is not None
                               else self.replication_factor // 2 + 1)}
        return durability_info(self.flush_mode, self.fsync_every_n,
                               self.max_unflushed_records,
                               self.max_flush_delay_ms, self.coalesce,
                               replication=repl)

    def write_metrics(self, path: Optional[str] = None) -> Dict[str, Any]:
        """The ``rq.serving.metrics/1`` artifact (defaults into the
        serving directory)."""
        if path is None:
            if self.dir is None:
                raise ValueError("no serving directory and no path given")
            path = os.path.join(self.dir, "metrics.json")
        return self.metrics.write(
            path, pending=self.pending,
            extra={"n_feeds": self.n_feeds, "q": self.q,
                   "applied_seq": self.applied_seq,
                   "param_epoch": self._param_epoch,
                   "param_fingerprint": self._param_fingerprint,
                   "durability": self.durability(),
                   # The journal-health block (flush_errors, fsync
                   # attempts, checkpoint-lag watermark, replication
                   # follower states): a silently failing fsync thread
                   # or a lagging checkpoint is visible in every
                   # metrics artifact BEFORE a crash makes it matter.
                   "journal": (None if self._journal is None
                               else self._journal.health()),
                   "health_sick_edges": int(np.count_nonzero(
                       np.asarray(self._state.health)))})

    def state_digest(self) -> str:
        return state_digest(self._state)

    def gather(self) -> Tuple[np.ndarray, np.ndarray, int, float, int]:
        """The per-edge carry as host arrays — ``(rank f32[F], health
        u32[F], seq, t, n_batches)`` through ONE explicit device→host
        boundary.  The cluster's edge-digest / reshard paths drive this
        uniformly for in-process runtimes and out-of-process workers
        (``serving.worker.WorkerHandle.gather`` answers bit-identically
        over the frame protocol)."""
        import jax

        st = self._state
        r, h, sq, t, nb = jax.device_get(
            (st.rank, st.health, st.seq, st.t, st.n_batches))
        return (np.asarray(r, np.float32), np.asarray(h, np.uint32),
                int(sq), float(t), int(nb))

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Recovery: snapshot + journal replay -> bit-identical carry
# ---------------------------------------------------------------------------

def _record_batches(rec: Dict[str, Any]
                    ) -> List[Tuple[int, list, list, Dict[str, Any]]]:
    """One journal record → its ``(seq, times, feeds, decision)`` batch
    tuples, for BOTH record shapes: a /1 record is one batch, a /2 group
    record (flat concatenated events + per-batch ``counts``) is several.
    The single flat-record parser every journal reader shares."""
    if "epoch" in rec or "topo_epoch" in rec:
        # A parameter-install record (serving.paramswap) or a migrated
        # range install (serving.topology): positional metadata for
        # replay, not a batch — contributes no decisions.
        return []
    if "seqs" not in rec:
        return [(int(rec["seq"]), rec["times"], rec["feeds"],
                 rec["decision"])]
    out = []
    at = 0
    for seq, n, d in zip(rec["seqs"], rec["counts"], rec["decisions"]):
        n = int(n)
        out.append((int(seq), rec["times"][at:at + n],
                    rec["feeds"][at:at + n], d))
        at += n
    if at != len(rec["times"]):
        raise ValueError(
            f"group record {rec['seqs'][0]}..{rec['seqs'][-1]} counts "
            f"sum to {at} but carries {len(rec['times'])} events — "
            f"corrupt group structure")
    return out


def recover(dir: str, clock=time.monotonic,
            acked_seq: Optional[int] = None,
            heal_replicas: Optional[List[str]] = None
            ) -> Tuple[ServingRuntime, RecoveryInfo]:
    """Rebuild a runtime from its serving directory after a crash.

    Protocol: read the enveloped config; restore the newest snapshot
    that PROVES restorable (``utils.checkpoint.latest_valid_step`` —
    torn steps are quarantined, never trusted); verify-and-replay the
    journal (torn tail quarantined by ``serving.journal.replay``),
    re-applying every record past the snapshot through the same pure
    apply step — per-batch records through :func:`make_apply_fn`, group
    records through the coalesced fn (grouping-invariant bitwise, so
    both paths reconstruct the same carry).  Each replayed record's
    recomputed carry digest must equal the journaled one — the
    bit-identity witness; divergence raises ``RuntimeError`` rather than
    serving reconstructed-but-wrong state.

    ``acked_seq`` is the caller's ack high-water mark (what the source /
    router saw acknowledged before the crash): when the journal kept
    less — the async-group-commit loss window a power-style crash
    consumed — the exact lost seqs come back in
    ``RecoveryInfo.lost_acked_seqs`` so the caller can retransmit them
    deliberately instead of discovering the gap by timeout.

    ``heal_replicas`` (quorum-replicated directories): the surviving
    follower replica dirs — acked records the leader's disk lost are
    re-seeded from them (``replication.heal_from_replicas``) BEFORE the
    replay, so ``lost_acked_seqs`` shrinks to exactly the records EVERY
    holder lost.  None (the default) auto-discovers the default local
    replica root (``<dir>/replicas/replica*``) when the stored config
    says the directory ran replicated; pass ``[]`` to skip healing."""
    import jax
    import jax.numpy as jnp

    cfg = _integrity.read_json(os.path.join(dir, _CONFIG),
                               schema=CONFIG_SCHEMA)
    from ..utils import checkpoint as _checkpoint

    like = init_feed_state(int(cfg["n_feeds"]), int(cfg["seed"]),
                           start_seq=int(cfg["start_seq"]))
    snap_dir = os.path.join(dir, _SNAPSHOTS)
    step = _checkpoint.latest_valid_step(snap_dir, like=like)
    state = (like if step is None
             else _checkpoint.restore(snap_dir, step=step, like=like))
    journal_path = os.path.join(dir, _JOURNAL)
    healed: Tuple[int, ...] = ()
    if heal_replicas is None \
            and int(cfg.get("replication_factor") or 0) >= 1:
        from .replication import REPLICA_DIR_PREFIX
        root = os.path.join(dir, "replicas")
        if os.path.isdir(root):
            heal_replicas = sorted(
                os.path.join(root, n) for n in os.listdir(root)
                if n.startswith(REPLICA_DIR_PREFIX))
    if heal_replicas:
        from .replication import heal_from_replicas
        h = heal_from_replicas(journal_path, list(heal_replicas),
                               fmt=cfg.get("journal_format"))
        healed = tuple(h["healed_seqs"])
    records, torn = journal_replay(journal_path)
    apply_fn = make_apply_fn()
    co_fn = None
    s_sink = jnp.asarray(np.asarray(cfg["s_sink"], np.float64),
                         jnp.float32)
    qv = jnp.asarray(float(cfg["q"]), jnp.float32)
    E = int(cfg["max_batch_events"])
    K_cfg = int(cfg.get("coalesce", 1))
    replayed = skipped = 0
    last_decision: Optional[Decision] = None
    start_seq_state = int(jax.device_get(state.seq))
    # Parameter-epoch base for the replay (serving.paramswap): installs
    # made BEFORE the restored snapshot may live in pruned segments, so
    # the params that were live at the snapshot come from the sidecar
    # install log — the newest entry with seq <= the restored seq
    # (pruning only drops segments covered by the OLDEST retained
    # snapshot, so any install past that point still has its journal
    # record and is replayed in stream order below).
    live_install: Optional[Dict[str, Any]] = None
    try:
        plog = _integrity.read_json(
            os.path.join(dir, PARAMS_LOG_FILENAME),
            schema=PARAMS_LOG_SCHEMA)
    except FileNotFoundError:
        plog = None
    if plog:
        base = [e for e in plog["installs"]
                if int(e["seq"]) <= start_seq_state]
        if base:
            live_install = dict(base[-1])
    if live_install is not None:
        s64 = np.asarray(live_install["s_sink"], np.float64)
        if params_digest(s64, float(live_install["q"])) \
                != live_install["digest"]:
            raise RuntimeError(
                f"params_log epoch {live_install['epoch']} digest "
                f"mismatch — the sidecar install log is corrupt; "
                f"refusing to replay under unverified parameters")
        s_sink = jnp.asarray(s64, jnp.float32)
        qv = jnp.asarray(float(live_install["q"]), jnp.float32)
    for rec in records:
        if "topo_epoch" in rec:
            # A migrated-range install (serving.topology): re-apply
            # the journaled scatter-set at its stream position — the
            # values come from the record itself (f32/u32 round-trip
            # exactly through JSON), so replaying it is bit-identical
            # to the live install, and re-applying an already-
            # snapshotted install would be too (pure set); we skip
            # those only because later batch records may since have
            # re-ranked the installed edges.
            if int(rec["seq"]) > start_seq_state:
                raise RuntimeError(
                    f"journal topology record (epoch "
                    f"{rec['topo_epoch']}) claims install at seq "
                    f"{rec['seq']} but replay is at {start_seq_state} "
                    f"— out-of-order install record")
            if int(rec["seq"]) == start_seq_state:
                t_idx = np.asarray(rec["idx"], np.int32)
                state = state.replace(
                    rank=state.rank.at[t_idx].set(jnp.asarray(
                        np.asarray(rec["rank"], np.float32))),
                    health=state.health.at[t_idx].set(jnp.asarray(
                        np.asarray(rec["health"], np.uint32))))
                got = state_digest(state)
                if got != rec["state_digest"]:
                    raise RuntimeError(
                        f"journal replay diverged at topology epoch "
                        f"{rec['topo_epoch']} range install (seq "
                        f"{rec['seq']}): recomputed carry digest "
                        f"{got[:12]}.. != journaled "
                        f"{str(rec['state_digest'])[:12]}..")
            continue
        if "epoch" in rec:
            # A journaled install: switch the replay params from this
            # stream position on — every batch replays under the epoch
            # that decided it.  Digest-asserted twice: the params
            # against the record's own digest, and (when the install
            # falls inside the replayed range) the carry against the
            # journaled state digest at the install point.
            s64 = np.asarray(rec["s_sink"], np.float64)
            if params_digest(s64, float(rec["q"])) != rec["digest"]:
                raise RuntimeError(
                    f"journaled epoch {rec['epoch']} params digest "
                    f"mismatch — refusing to replay under unverified "
                    f"parameters")
            if int(rec["seq"]) > start_seq_state:
                raise RuntimeError(
                    f"journal epoch record {rec['epoch']} claims "
                    f"install at seq {rec['seq']} but replay is at "
                    f"{start_seq_state} — out-of-order install record")
            if int(rec["seq"]) == start_seq_state:
                got = state_digest(state)
                if got != rec["state_digest"]:
                    raise RuntimeError(
                        f"journal replay diverged at epoch "
                        f"{rec['epoch']} install (seq {rec['seq']}): "
                        f"recomputed carry digest {got[:12]}.. != "
                        f"journaled "
                        f"{str(rec['state_digest'])[:12]}..")
            s_sink = jnp.asarray(s64, jnp.float32)
            qv = jnp.asarray(float(rec["q"]), jnp.float32)
            live_install = dict(rec)
            continue
        batches = _record_batches(rec)
        last_seq = batches[-1][0]
        if last_seq <= start_seq_state:
            skipped += len(batches)
            seq, _, _, d = batches[-1]
            last_decision = Decision(seq=seq, post=bool(d["post"]),
                                     post_time=float(d["post_time"]),
                                     intensity=float(d["intensity"]))
            continue
        if batches[0][0] <= start_seq_state:
            # Snapshots land only at record boundaries, so a record
            # straddling the restored seq cannot come from this
            # directory's own history.
            raise RuntimeError(
                f"journal record {batches[0][0]}..{last_seq} straddles "
                f"the restored snapshot seq {start_seq_state} — mixed "
                f"directories or a foreign journal; refusing to replay")
        if len(batches) == 1 and "seqs" not in rec:
            seq, r_times, r_feeds, _ = batches[0]
            times, feeds, n = _pad_events(r_times, r_feeds, E)
            state, (posted, t_new, lam) = apply_fn(
                state, times, feeds, n, np.int32(seq), s_sink, qv)
            posted, t_new, lam = jax.device_get((posted, t_new, lam))  # rqlint: disable=RQ702 replay decision boundary
            posted_l, t_l, lam_l = [posted], [t_new], [lam]
        else:
            # Group record: replay through the coalesced fn — the bulk
            # path recovery shares with live serving (one dispatch per
            # journal record, so replaying a wire-speed journal is as
            # amortized as writing it was).
            if co_fn is None:
                co_fn = make_coalesced_apply_fn()
            k = len(batches)
            K = max(K_cfg, k)  # an over-wide group still replays
            g_times = np.zeros((K, E), np.float32)
            g_feeds = np.zeros((K, E), np.int32)
            g_nvalid = np.zeros((K,), np.int32)
            g_seqs = np.zeros((K,), np.int32)
            for j, (seq, r_times, r_feeds, _) in enumerate(batches):
                t, f, n = _pad_events(r_times, r_feeds, E)
                g_times[j], g_feeds[j] = t, f
                g_nvalid[j], g_seqs[j] = n, seq
            state, (posted, t_new, lam) = co_fn(
                state, g_times, g_feeds, g_nvalid, g_seqs, np.int32(k),
                s_sink, qv)
            posted, t_new, lam = jax.device_get((posted, t_new, lam))  # rqlint: disable=RQ702 replay decision boundary
            posted_l = [posted[j] for j in range(k)]
            t_l = [t_new[j] for j in range(k)]
            lam_l = [lam[j] for j in range(k)]
        got = state_digest(state)
        if got != rec["state_digest"]:
            raise RuntimeError(
                f"journal replay diverged at seq {last_seq}: recomputed "
                f"carry digest {got[:12]}.. != journaled "
                f"{str(rec['state_digest'])[:12]}.. — the journal and the "
                f"snapshot disagree (mixed directories? code drift across "
                f"the restart?); refusing to serve reconstructed state")
        last_decision = Decision(
            seq=last_seq, post=bool(posted_l[-1]),
            post_time=float(t_l[-1]), intensity=float(lam_l[-1]))
        replayed += len(batches)
        start_seq_state = last_seq
    rt = ServingRuntime(
        n_feeds=int(cfg["n_feeds"]), q=float(cfg["q"]),
        s_sink=np.asarray(cfg["s_sink"], np.float64),
        seed=int(cfg["seed"]), dir=dir,
        start_seq=int(cfg["start_seq"]),
        snapshot_every=int(cfg["snapshot_every"]),
        reorder_window=int(cfg["reorder_window"]),
        queue_capacity=int(cfg["queue_capacity"]),
        max_batch_events=E,
        fsync_every_n=int(cfg.get("fsync_every_n", 1)),
        flush_mode=str(cfg.get("flush_mode", "sync")),
        max_unflushed_records=int(cfg.get("max_unflushed_records", 64)),
        max_flush_delay_ms=float(cfg.get("max_flush_delay_ms", 50.0)),
        coalesce=K_cfg,
        journal_format=cfg.get("journal_format"),
        replication_factor=int(cfg.get("replication_factor") or 0),
        replication_quorum=cfg.get("replication_quorum"),
        replication_mode=str(cfg.get("replication_mode", "thread")),
        clock=clock, _state=state)
    rt._last_decision = last_decision
    if live_install is not None and int(live_install["epoch"]) > 0:
        # Re-install the last-good live parameters without re-journaling
        # (the install record is already durable); then pin the epoch
        # counter to the journaled value so post-recovery installs
        # continue the sequence instead of restarting it.
        rt._install_validated(
            np.asarray(live_install["s_sink"], np.float64),
            float(live_install["q"]),
            str(live_install["fingerprint"]),
            str(live_install["digest"]), journal=False)
        rt._param_epoch = int(live_install["epoch"])
    recovered_seq = int(jax.device_get(state.seq))
    lost: Tuple[int, ...] = ()
    if acked_seq is not None and int(acked_seq) > recovered_seq:
        # Seqs are consecutive by the stream contract, so the lost
        # window is exactly the integer gap.
        lost = tuple(range(recovered_seq + 1, int(acked_seq) + 1))
    info = RecoveryInfo(
        snapshot_seq=step, replayed=replayed, skipped=skipped, torn=torn,
        recovered_seq=recovered_seq, lost_acked_seqs=lost,
        healed_seqs=healed)
    return rt, info


def journal_decisions(dir: str) -> List[Decision]:
    """The full decision history a serving directory's journal records —
    what the crash-recovery acceptance test compares against the
    uninterrupted run (read-only: the torn tail, if any, is skipped, not
    quarantined).  Group records contribute one decision per batch."""
    records, _ = journal_replay(os.path.join(dir, _JOURNAL),
                                quarantine_torn_tail=False)
    out = []
    for rec in records:
        for seq, _times, _feeds, d in _record_batches(rec):
            out.append(Decision(seq=seq, post=bool(d["post"]),
                                post_time=float(d["post_time"]),
                                intensity=float(d["intensity"])))
    return out
