"""Corpus replay: native-loader rows → sequenced serving micro-batches.

ROADMAP item 2's second named gap: the ingest path accepted only
synthetic streams, while the repo already parses real trace corpora at
~10M rows/s through the native C++ loader.  This module closes the loop:

    CSV corpus --load_csv(engine=auto: native C++ when it builds)-->
    per-user traces --merge_traces (one global time-ordered event
    stream; ties keep user order, deterministically)-->
    corpus_batches (fixed-size sequence-numbered micro-batches)-->
    ServingCluster.submit/poll (sharded, journaled, fault-isolated)

Every stage is a pure function of the corpus bytes, so a crashed replay
regenerates the byte-identical batch stream — the same retransmit model
as ``serving.events.synthetic_stream`` — and the sharded runtime's
recovery invariants hold unchanged under real data.

CLI: ``python -m redqueen_tpu.serving.corpus --csv corpus.csv --dir D
--shards 4`` (see ``--help``); lands the ``rq.serving.metrics/2``
artifact plus a ``rq.serving.corpus/1`` summary (rows, users, loader
engine, rows/s served).
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..runtime import integrity as _integrity
from .events import EventBatch

__all__ = ["merge_traces", "corpus_batches", "serve_corpus", "main",
           "CORPUS_SCHEMA"]

CORPUS_SCHEMA = "rq.serving.corpus/1"

# Bounded retransmit: each round resends everything past the acked
# position (auto-recovery runs inside poll), so a healthy cluster
# converges in one; a shard that stays down past this is an operator
# problem and the replay fails loudly instead of under-serving.
_RETRANSMIT_ROUNDS = 8


def merge_traces(traces: List[np.ndarray],
                 max_rows: Optional[int] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Merge per-user ascending trace arrays into ONE globally
    time-ordered event stream ``(times f64[R], feeds i32[R])`` where
    ``feeds`` is the user index (= the serving feed/edge id).

    The sort is stable, so rows with equal timestamps keep user order —
    the merge is a pure function of the corpus, which is what makes a
    restarted replay regenerate the byte-identical stream.
    ``max_rows`` truncates the MERGED stream (a time-prefix of the
    corpus: the earliest ``max_rows`` events), never a per-user bite."""
    n_users = len(traces)
    if n_users == 0:
        return np.empty(0, np.float64), np.empty(0, np.int32)
    times = np.concatenate([np.asarray(t, np.float64) for t in traces]) \
        if any(len(t) for t in traces) else np.empty(0, np.float64)
    feeds = np.repeat(np.arange(n_users, dtype=np.int32),
                      [len(t) for t in traces])
    order = np.argsort(times, kind="stable")
    times, feeds = times[order], feeds[order]
    if max_rows is not None and len(times) > int(max_rows):
        times, feeds = times[: int(max_rows)], feeds[: int(max_rows)]
    return times, feeds


def corpus_batches(times: np.ndarray, feeds: np.ndarray,
                   batch_events: int,
                   start_seq: int = 0) -> Iterator[EventBatch]:
    """Chunk a merged event stream into consecutive sequence-numbered
    micro-batches of at most ``batch_events`` events each (the last may
    be short).  Views, not copies — 8.58M corpus rows stream through
    without a second resident copy."""
    if batch_events < 1:
        raise ValueError(f"batch_events must be >= 1, got {batch_events}")
    n = len(times)
    seq = int(start_seq)
    for lo in range(0, n, int(batch_events)):
        hi = min(lo + int(batch_events), n)
        yield EventBatch(seq, times[lo:hi], feeds[lo:hi])
        seq += 1


def serve_corpus(csv_path: str, dir: Optional[str], n_shards: int,
                 batch_events: int = 512, engine: str = "auto",
                 max_rows: Optional[int] = None, seed: int = 0,
                 q: float = 1.0, snapshot_every: int = 256,
                 queue_capacity: int = 64,
                 placement: str = "in-process", clock=time.monotonic,
                 log=None) -> dict:
    """End-to-end corpus serving: load (native C++ loader when it
    builds), merge, batch, and drive the full stream through a sharded
    :class:`~redqueen_tpu.serving.cluster.ServingCluster` (submit+poll
    per batch — the steady-state serving shape, journal fsync in the
    measured path when ``dir`` is given).  ``placement="workers"``
    replays through out-of-process shard workers (requires ``dir``) —
    same batches, bit-identical decisions, N-process parallel applies.
    Returns the summary payload (also landed as ``<dir>/corpus.json``
    when ``dir`` is set)."""
    from ..data import traces as traces_mod
    from ..native import loader as native_loader
    from .cluster import ServingCluster

    def _log(*a):
        if log is not None:
            log(*a)

    engine_used = ("native" if (engine in ("auto", "native")
                                and native_loader.available())
                   else "python")
    t0 = clock()
    traces, stats = traces_mod.load_csv(csv_path, engine=engine,
                                        return_stats=True)
    load_s = clock() - t0
    times, feeds = merge_traces(traces, max_rows=max_rows)
    n_feeds = max(len(traces), 1)
    _log(f"corpus: {stats.n_rows} rows / {stats.n_users} users loaded "
         f"in {load_s:.2f}s via the {engine_used} loader; serving "
         f"{len(times)} rows through {n_shards} shard(s)")
    cl = ServingCluster(
        n_feeds=n_feeds, n_shards=n_shards, dir=dir, q=q, seed=seed,
        snapshot_every=snapshot_every, queue_capacity=queue_capacity,
        max_batch_events=batch_events, placement=placement, clock=clock)
    n_batches = 0
    t1 = clock()
    with cl:
        for b in corpus_batches(times, feeds, batch_events):
            cl.submit(b)
            cl.poll()
            n_batches += 1
        # The retransmit model made real: if a shard crashed/shed
        # mid-replay, regenerate the (pure-function) batch stream and
        # resend everything past the cluster's acked position until it
        # converges — rows_served must mean APPLIED, not offered.
        final_seq = n_batches - 1
        for _ in range(_RETRANSMIT_ROUNDS):
            if cl.applied_seq >= final_seq:
                break
            cl.poll()
            for b in corpus_batches(times, feeds, batch_events):
                if int(b.seq) > cl.applied_seq:
                    cl.submit(b)
                    cl.poll()
        if n_batches and cl.applied_seq < final_seq:
            raise RuntimeError(
                f"corpus replay did not converge: applied_seq="
                f"{cl.applied_seq} < {final_seq} after "
                f"{_RETRANSMIT_ROUNDS} retransmit rounds "
                f"(health={cl.health_by_shard}) — a shard is not "
                f"recovering; the metrics artifact in {dir!r} has the "
                f"per-shard breakdown")
        serve_s = max(clock() - t1, 1e-9)
        report = cl.metrics.report(cl.pending_by_shard,
                                   cl.health_by_shard)
        payload = {
            "csv": os.path.abspath(csv_path),
            "loader_engine": engine_used,
            "corpus_rows": int(stats.n_rows),
            "corpus_users": int(stats.n_users),
            "duplicate_timestamps": int(stats.duplicate_timestamps),
            "non_monotonic_rows": int(stats.non_monotonic_rows),
            "rows_served": int(len(times)),
            "rows_truncated": bool(max_rows is not None
                                   and stats.n_rows > len(times)),
            "n_shards": int(n_shards),
            "n_batches": n_batches,
            "batch_events": int(batch_events),
            "load_secs": round(load_s, 3),
            "load_rows_per_sec": round(stats.n_rows / max(load_s, 1e-9),
                                       1),
            "serve_secs": round(serve_s, 3),
            "serve_rows_per_sec": round(len(times) / serve_s, 1),
            "reconciles": report["reconciles"],
            "applied_seq": cl.applied_seq,
            "decision_latency": report["decision_latency"],
        }
        if dir is not None:
            cl.write_metrics()
            _integrity.write_json(os.path.join(dir, "corpus.json"),
                                  payload, schema=CORPUS_SCHEMA)
    _log(f"corpus: served {payload['rows_served']} rows in "
         f"{payload['serve_secs']:.2f}s -> "
         f"{payload['serve_rows_per_sec']:,.0f} rows/s across "
         f"{n_shards} shard(s); reconciles={payload['reconciles']}")
    return payload


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m redqueen_tpu.serving.corpus",
        description="replay a trace corpus through the sharded serving "
                    "cluster as sequenced micro-batches (native C++ "
                    "loader when available)")
    ap.add_argument("--csv", required=True, help="corpus CSV "
                    "(user,timestamp rows — data.traces format)")
    ap.add_argument("--dir", default=None,
                    help="cluster directory (journals + snapshots + "
                         "metrics); omit for an in-memory dry run")
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--batch-events", type=int, default=512)
    ap.add_argument("--max-rows", type=int, default=None,
                    help="serve only the earliest N merged rows")
    ap.add_argument("--engine", choices=["auto", "native", "python"],
                    default="auto")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--q", type=float, default=1.0)
    ap.add_argument("--snapshot-every", type=int, default=256)
    ap.add_argument("--workers", action="store_true",
                    help="replay through out-of-process shard workers "
                         "(requires --dir; serving.worker)")
    args = ap.parse_args(argv)
    if args.workers and args.dir is None:
        ap.error("--workers needs --dir (a worker subprocess owns its "
                 "shard's on-disk state)")
    payload = serve_corpus(
        args.csv, args.dir, args.shards,
        batch_events=args.batch_events, engine=args.engine,
        max_rows=args.max_rows, seed=args.seed, q=args.q,
        snapshot_every=args.snapshot_every,
        placement="workers" if args.workers else "in-process",
        log=lambda *a: print(*a, file=sys.stderr, flush=True))
    import json

    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
