"""Steady-state serving metrics: counters, latency percentiles, and the
enveloped ``rq.serving.metrics/1`` artifact.

Accounting is CLOSED by construction and asserted in CI: every submitted
batch ends in exactly one of {applied, shed, rejected, duplicate, still
pending}, so after a drain

    ingested == applied + shed + rejected + duplicates

— load shedding records exactly what was shed (count, events, and the
shed sequence numbers), never a silent gap.  Decision latency is
wall-clock submit→decision per applied batch (``time.monotonic``),
reported as p50/p99; events/s sustained divides applied events by the
busy window.  The artifact is written through ``runtime.integrity`` so
it carries the standard checksummed envelope.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..runtime import integrity as _integrity

__all__ = ["ServingMetrics", "METRICS_SCHEMA", "MAX_SHED_SEQS",
           "LATENCY_WINDOW"]

METRICS_SCHEMA = "rq.serving.metrics/1"

# Hard caps keeping a long-lived runtime's metrics state bounded (the
# overload contract promises bounded MEMORY, which must include the
# accounting itself): the first MAX_SHED_SEQS shed seqs are recorded
# verbatim (the artifact flags truncation; the total count is always
# exact), and latency percentiles are computed over a sliding window of
# the most recent LATENCY_WINDOW applies.
MAX_SHED_SEQS = 1024
LATENCY_WINDOW = 8192


class ServingMetrics:
    """Mutable counter block owned by the serving runtime; one instance
    per runtime lifetime (recovery starts a fresh one — the artifact
    describes THIS process's steady state, not history)."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self.t_start = clock()
        # batch counters (the reconciliation identity's terms)
        self.ingested = 0       # submit() calls that carried a batch
        self.applied = 0        # batches applied to the carry
        self.shed = 0           # dropped by overload policy (queue full)
        self.rejected = 0       # typed IngestError rejections
        self.duplicates = 0     # duplicate-seq drops
        self.reordered = 0      # batches that arrived out of order
        self.window_rejects = 0  # rejected for landing beyond the window
        # event / decision counters
        self.events_applied = 0
        self.posts = 0
        self.shed_events = 0
        self.shed_seqs: List[int] = []  # first MAX_SHED_SEQS only
        self.decisions_served = 0   # decide() calls answered (incl. stale)
        self.stale_decisions = 0    # decide() served with backlog pending
        self._latencies: collections.deque = collections.deque(
            maxlen=LATENCY_WINDOW)

    def observe_apply(self, n_events: int, posted: bool,
                      latency_s: Optional[float]) -> None:
        self.applied += 1
        self.events_applied += int(n_events)
        self.posts += int(bool(posted))
        if latency_s is not None:
            self._latencies.append(float(latency_s))

    def observe_shed(self, seq: int, n_events: int) -> None:
        self.shed += 1
        self.shed_events += int(n_events)
        if len(self.shed_seqs) < MAX_SHED_SEQS:
            self.shed_seqs.append(int(seq))

    def latency_percentiles(self) -> Dict[str, Optional[float]]:
        if not self._latencies:
            return {"p50_ms": None, "p99_ms": None, "max_ms": None}
        lat = np.asarray(self._latencies)
        return {
            "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
            "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
            "max_ms": round(float(lat.max()) * 1e3, 3),
        }

    def reconciles(self, pending: int = 0) -> bool:
        """The closed-accounting identity (pending = batches accepted
        but not yet applied: queued or held in the reorder window)."""
        return self.ingested == (self.applied + self.shed + self.rejected
                                 + self.duplicates + pending)

    def report(self, pending: int = 0,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        busy_s = max(self._clock() - self.t_start, 1e-9)
        out: Dict[str, Any] = {
            "ingested": self.ingested,
            "applied": self.applied,
            "shed": self.shed,
            "rejected": self.rejected,
            "duplicates": self.duplicates,
            "reordered": self.reordered,
            "window_rejects": self.window_rejects,
            "pending": int(pending),
            "reconciles": self.reconciles(pending),
            "events_applied": self.events_applied,
            "posts": self.posts,
            "shed_events": self.shed_events,
            "shed_seqs": list(self.shed_seqs),
            "shed_seqs_truncated": self.shed > len(self.shed_seqs),
            "decisions_served": self.decisions_served,
            "stale_decisions": self.stale_decisions,
            "busy_s": round(busy_s, 6),
            "events_per_sec": round(self.events_applied / busy_s, 1),
            "batches_per_sec": round(self.applied / busy_s, 1),
            "decision_latency": self.latency_percentiles(),
        }
        if extra:
            out.update(extra)
        return out

    def write(self, path: str, pending: int = 0,
              extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Land the report as the enveloped ``rq.serving.metrics/1``
        artifact (atomic + checksummed); returns the payload."""
        payload = self.report(pending=pending, extra=extra)
        _integrity.write_json(path, payload, schema=METRICS_SCHEMA)
        return payload
