"""Steady-state serving metrics: counters, latency percentiles, and the
enveloped ``rq.serving.metrics/1`` / ``rq.serving.metrics/2`` artifacts.

Accounting is CLOSED by construction and asserted in CI: every submitted
batch ends in exactly one of {applied, shed, rejected, duplicate, still
pending}, so after a drain

    ingested == applied + shed + rejected + duplicates

— load shedding records exactly what was shed (count, events, and the
shed sequence numbers), never a silent gap.  Decision latency is
wall-clock submit→decision per applied batch (``time.monotonic``),
reported as p50/p99; events/s sustained divides applied events by the
busy window.  The artifact is written through ``runtime.integrity`` so
it carries the standard checksummed envelope.

Two schema versions:

- :class:`ServingMetrics` → ``rq.serving.metrics/1``: one single-domain
  runtime's counters (PR 6).
- :class:`ClusterMetrics` → ``rq.serving.metrics/2``: the sharded
  cluster's ROUTER-side accounting — one breakdown per shard fault
  domain plus cluster aggregates, health states, and recovery stats.
  Router counters are authoritative across shard crashes (a recovered
  shard starts a fresh in-process metrics block, but the router observed
  every admission and every decision, so the cluster identity
  ``ingested == applied + shed + rejected + duplicates (+ pending)``
  reconciles per shard AND cluster-wide, including mid-recovery — the
  units are SUB-batches: every global micro-batch fans out to exactly
  one sub-outcome per shard).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional

from ..runtime import integrity as _integrity
from ..runtime import telemetry as _telemetry

__all__ = ["ServingMetrics", "ClusterMetrics", "METRICS_SCHEMA",
           "CLUSTER_METRICS_SCHEMA", "MAX_SHED_SEQS", "LATENCY_WINDOW",
           "MAX_SEQS_PER_SHARD", "MAX_FLIGHT_SPANS"]

METRICS_SCHEMA = "rq.serving.metrics/1"
CLUSTER_METRICS_SCHEMA = "rq.serving.metrics/2"

# Hard caps keeping a long-lived runtime's metrics state bounded (the
# overload contract promises bounded MEMORY, which must include the
# accounting itself): the first MAX_SHED_SEQS shed seqs are recorded
# verbatim (the artifact flags truncation; the total count is always
# exact), and latency percentiles are computed over a sliding window of
# the most recent LATENCY_WINDOW applies.
MAX_SHED_SEQS = 1024
LATENCY_WINDOW = 8192
# Per-shard cap on each recorded seq list (shed/lost) in ClusterMetrics —
# totals stay exact, truncation is flagged, memory stays bounded per
# fault domain.
MAX_SEQS_PER_SHARD = 256


# Trimmed/windowed percentile parameters — re-exported from
# runtime.telemetry, which owns THE histogram/percentile implementation
# (this module is a consumer, not a second definition: the /1 and /2
# `decision_latency` blocks, every telemetry histogram, and the rqtrace
# breakdowns all share one percentile function).
TRIM_FRACTION = _telemetry.TRIM_FRACTION
PCTL_WINDOW = _telemetry.PCTL_WINDOW

#: The one percentile definition (see runtime.telemetry
#: .latency_percentiles) — kept under its historical name because the
#: serving tests and the cluster artifact builders address it here.
_latency_percentiles = _telemetry.latency_percentiles

#: Cap on salvaged flight-recorder spans retained per shard (the crash
#: forensics the router pulls from a dead worker's ring — bounded like
#: every other per-shard ledger; the count stays exact).  ONE policy,
#: owned by runtime.telemetry: the supervisor's RunReport salvage uses
#: the same constant, so the two crash-evidence paths never drift.
MAX_FLIGHT_SPANS = _telemetry.FLIGHT_SALVAGE_SPANS


class ServingMetrics:
    """Mutable counter block owned by the serving runtime; one instance
    per runtime lifetime (recovery starts a fresh one — the artifact
    describes THIS process's steady state, not history)."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self.t_start = clock()
        # batch counters (the reconciliation identity's terms)
        self.ingested = 0       # submit() calls that carried a batch
        self.applied = 0        # batches applied to the carry
        self.shed = 0           # dropped by overload policy (queue full)
        self.rejected = 0       # typed IngestError rejections
        self.duplicates = 0     # duplicate-seq drops
        self.reordered = 0      # batches that arrived out of order
        self.window_rejects = 0  # rejected for landing beyond the window
        # event / decision counters
        self.events_applied = 0
        self.posts = 0
        self.shed_events = 0
        self.shed_seqs: List[int] = []  # first MAX_SHED_SEQS only
        self.decisions_served = 0   # decide() calls answered (incl. stale)
        self.stale_decisions = 0    # decide() served with backlog pending
        self._latencies: collections.deque = collections.deque(
            maxlen=LATENCY_WINDOW)

    def observe_apply(self, n_events: int, posted: bool,
                      latency_s: Optional[float]) -> None:
        self.applied += 1
        self.events_applied += int(n_events)
        self.posts += int(bool(posted))
        if latency_s is not None:
            self._latencies.append(float(latency_s))
            # One observation, two consumers: the report's percentile
            # window here, the exported telemetry histogram there (a
            # no-op branch when tracing is disabled).
            _telemetry.observe("serving.decision_latency_s", latency_s)

    def observe_shed(self, seq: int, n_events: int) -> None:
        self.shed += 1
        self.shed_events += int(n_events)
        if len(self.shed_seqs) < MAX_SHED_SEQS:
            self.shed_seqs.append(int(seq))

    def latency_percentiles(self) -> Dict[str, Optional[float]]:
        return _latency_percentiles(self._latencies)

    def reconciles(self, pending: int = 0) -> bool:
        """The closed-accounting identity (pending = batches accepted
        but not yet applied: queued or held in the reorder window)."""
        return self.ingested == (self.applied + self.shed + self.rejected
                                 + self.duplicates + pending)

    def report(self, pending: int = 0,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        busy_s = max(self._clock() - self.t_start, 1e-9)
        out: Dict[str, Any] = {
            "ingested": self.ingested,
            "applied": self.applied,
            "shed": self.shed,
            "rejected": self.rejected,
            "duplicates": self.duplicates,
            "reordered": self.reordered,
            "window_rejects": self.window_rejects,
            "pending": int(pending),
            "reconciles": self.reconciles(pending),
            "events_applied": self.events_applied,
            "posts": self.posts,
            "shed_events": self.shed_events,
            "shed_seqs": list(self.shed_seqs),
            "shed_seqs_truncated": self.shed > len(self.shed_seqs),
            "decisions_served": self.decisions_served,
            "stale_decisions": self.stale_decisions,
            "busy_s": round(busy_s, 6),
            "events_per_sec": round(self.events_applied / busy_s, 1),
            "batches_per_sec": round(self.applied / busy_s, 1),
            "decision_latency": self.latency_percentiles(),
        }
        if extra:
            out.update(extra)
        return out

    def write(self, path: str, pending: int = 0,
              extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Land the report as the enveloped ``rq.serving.metrics/1``
        artifact (atomic + checksummed); returns the payload."""
        payload = self.report(pending=pending, extra=extra)
        _integrity.write_json(path, payload, schema=METRICS_SCHEMA)
        return payload


class _ShardStats:
    """One shard fault domain's router-side counters.  Mutated only by
    :class:`ClusterMetrics` observers; every sub-batch the router offers
    the shard ends in exactly one bucket (or pending), so

        submitted == applied + shed_queue + shed_unavailable
                     + lost_on_crash + rejected + duplicates + pending

    holds at every instant — including while the shard is quarantined
    (its accepted-but-unapplied sub-batches were reclassified
    ``lost_on_crash`` the moment the carry died; pending is then 0)."""

    __slots__ = ("submitted", "applied", "events_applied", "posts",
                 "shed_queue", "shed_unavailable", "lost_on_crash",
                 "rejected", "duplicates", "timeouts", "backoff_rounds",
                 "crashes", "recoveries", "replayed", "recovery_ms",
                 "shed_seqs", "lost_seqs", "last_crash_reason",
                 "lost_in_window", "lost_window_seqs", "resyncs",
                 "resynced_decisions", "reattaches",
                 "flight_salvaged", "flight_spans")

    def __init__(self):
        self.submitted = 0
        self.applied = 0
        self.events_applied = 0
        self.posts = 0
        self.shed_queue = 0
        self.shed_unavailable = 0
        self.lost_on_crash = 0
        self.rejected = 0
        self.duplicates = 0
        self.timeouts = 0
        self.backoff_rounds = 0
        self.crashes = 0
        self.recoveries = 0
        self.replayed = 0
        self.recovery_ms: List[float] = []
        self.shed_seqs: List[int] = []       # queue + unavailable sheds
        self.lost_seqs: List[int] = []
        self.last_crash_reason: Optional[str] = None
        # Group-commit durability window consumed by a power-style
        # crash: seqs that were ACKED (observed applied) but the journal
        # did not keep.  Diagnostic, NOT an identity term — the healing
        # retransmit re-enters as its own (submitted, applied) pair.
        self.lost_in_window = 0
        self.lost_window_seqs: List[int] = []
        # Socket-transport link-failure bookkeeping: reattached
        # partitions and the decisions resynced after a lost response
        # frame (also diagnostic — the resynced decisions ARE the
        # applied observations, counted once where they land).
        self.resyncs = 0
        self.resynced_decisions = 0
        self.reattaches = 0
        # Flight-recorder salvage: the dead worker's last spans, read
        # from its on-disk ring after a crash (count exact, retained
        # spans capped at MAX_FLIGHT_SPANS — the evidence a SIGKILL'd
        # process leaves behind).
        self.flight_salvaged = 0
        self.flight_spans: List[Dict[str, Any]] = []

    @property
    def shed_total(self) -> int:
        return self.shed_queue + self.shed_unavailable + self.lost_on_crash

    def reconciles(self, pending: int) -> bool:
        return self.submitted == (self.applied + self.shed_total
                                  + self.rejected + self.duplicates
                                  + int(pending))

    def as_dict(self, pending: int, health: str) -> Dict[str, Any]:
        return {
            "health": health,
            "submitted": self.submitted,
            "applied": self.applied,
            "events_applied": self.events_applied,
            "posts": self.posts,
            "shed_queue": self.shed_queue,
            "shed_unavailable": self.shed_unavailable,
            "lost_on_crash": self.lost_on_crash,
            "rejected": self.rejected,
            "duplicates": self.duplicates,
            "pending": int(pending),
            "reconciles": self.reconciles(pending),
            "timeouts": self.timeouts,
            "backoff_rounds": self.backoff_rounds,
            "crashes": self.crashes,
            "last_crash_reason": self.last_crash_reason,
            "recoveries": self.recoveries,
            "replayed": self.replayed,
            "recovery_ms": [round(x, 3) for x in self.recovery_ms],
            "shed_seqs": list(self.shed_seqs),
            "lost_seqs": list(self.lost_seqs),
            "lost_in_window": self.lost_in_window,
            "lost_window_seqs": list(self.lost_window_seqs),
            "reattaches": self.reattaches,
            "resyncs": self.resyncs,
            "resynced_decisions": self.resynced_decisions,
            "flight_salvaged": self.flight_salvaged,
            "flight_spans": list(self.flight_spans),
            "seqs_truncated": (
                self.shed_queue + self.shed_unavailable
                > len(self.shed_seqs)
                or self.lost_on_crash > len(self.lost_seqs)
                or self.lost_in_window > len(self.lost_window_seqs)),
        }


def _capped_append(seqs: List[int], seq: int) -> None:
    if len(seqs) < MAX_SEQS_PER_SHARD:
        seqs.append(int(seq))


class ClusterMetrics:
    """Router-side accounting for the sharded serving cluster — the
    authoritative ledger across shard crashes (per-shard in-process
    metrics die with the shard; the router's view of admissions and
    decisions does not).  Units are SUB-batches: one global micro-batch
    = one sub-outcome per shard, so per-shard identities sum to the
    cluster identity exactly."""

    def __init__(self, n_shards: int, clock=time.monotonic):
        self._clock = clock
        self.t_start = clock()
        self.n_shards = int(n_shards)
        self.shards = [_ShardStats() for _ in range(n_shards)]
        self.global_rejected = 0   # rejected before fan-out (bad batch)
        self.decisions_served = 0
        self.stale_decisions = 0
        # The elastic-topology block (serving.topology): epoch is the
        # current journaled topology epoch; the counters are THIS
        # router process's observations.  ``fenced_retried`` counts
        # admissions refused at the router because a pending range
        # fence covered their feeds — refused BEFORE fan-out, so they
        # never enter any per-shard ledger and the closed sub-batch
        # identity holds unchanged across a mid-migration window (the
        # source's retransmit after the flip enters as a normal
        # submission).
        self.topology: Dict[str, int] = {
            "epoch": 0, "plans_completed": 0, "ranges_migrated": 0,
            "fenced_retried": 0, "edges_added": 0, "edges_dropped": 0,
            "migration_stalls": 0}
        self._latencies: collections.deque = collections.deque(
            maxlen=LATENCY_WINDOW)

    # -- observers (the router calls exactly one per sub-batch outcome) --

    def add_shard(self) -> None:
        """A migration destination joined the cluster (topology
        ``add_slot``): one more fault domain in the ledger, zeroed —
        the per-shard identity holds from its first sub-batch."""
        self.shards.append(_ShardStats())
        self.n_shards = len(self.shards)

    def set_topology_epoch(self, epoch: int) -> None:
        self.topology["epoch"] = max(self.topology["epoch"], int(epoch))

    def observe_fenced_retry(self) -> None:
        self.topology["fenced_retried"] += 1

    def observe_range_migrated(self) -> None:
        self.topology["ranges_migrated"] += 1

    def observe_edges_added(self, n: int) -> None:
        self.topology["edges_added"] += int(n)

    def observe_edges_dropped(self, n: int) -> None:
        self.topology["edges_dropped"] += int(n)

    def observe_migration_stall(self) -> None:
        self.topology["migration_stalls"] += 1

    def observe_plan_complete(self) -> None:
        self.topology["plans_completed"] += 1

    def observe_submitted(self, shard: int) -> None:
        self.shards[shard].submitted += 1

    def observe_applied(self, shard: int, n_events: int, posted: bool,
                        latency_s: Optional[float]) -> None:
        s = self.shards[shard]
        s.applied += 1
        s.events_applied += int(n_events)
        s.posts += int(bool(posted))
        if latency_s is not None:
            self._latencies.append(float(latency_s))
            # Distinct histogram from ServingMetrics' on purpose: under
            # IN-PROCESS placement both ledgers observe the same
            # decision (runtime- and router-level latency are different
            # definitions), and one shared name would double-count and
            # blend them.
            _telemetry.observe("cluster.decision_latency_s", latency_s)

    def observe_shed_queue(self, shard: int, seq: int) -> None:
        s = self.shards[shard]
        s.shed_queue += 1
        _capped_append(s.shed_seqs, seq)

    def observe_shed_unavailable(self, shard: int, seq: int) -> None:
        s = self.shards[shard]
        s.shed_unavailable += 1
        _capped_append(s.shed_seqs, seq)

    def observe_lost_on_crash(self, shard: int, seq: int) -> None:
        s = self.shards[shard]
        s.lost_on_crash += 1
        _capped_append(s.lost_seqs, seq)

    def observe_lost_in_window(self, shard: int, seq: int) -> None:
        """An acked seq the recovered journal did not keep — the
        group-commit loss window (healed by retransmit; diagnostic,
        not an identity term)."""
        s = self.shards[shard]
        s.lost_in_window += 1
        _capped_append(s.lost_window_seqs, seq)

    def observe_reattach(self, shard: int) -> None:
        self.shards[shard].reattaches += 1

    def observe_resync(self, shard: int, n_decisions: int) -> None:
        s = self.shards[shard]
        s.resyncs += 1
        s.resynced_decisions += int(n_decisions)

    def observe_flight_salvage(self, shard: int,
                               spans: List[Dict[str, Any]]) -> None:
        """The dead worker's flight-recorder ring, salvaged by the
        router after a crash: the count is exact, the retained spans
        are the most recent ``MAX_FLIGHT_SPANS`` (newest evidence
        matters most after a SIGKILL)."""
        s = self.shards[shard]
        s.flight_salvaged += len(spans)
        s.flight_spans = [dict(sp) for sp in spans[-MAX_FLIGHT_SPANS:]]

    def observe_rejected(self, shard: int) -> None:
        self.shards[shard].rejected += 1

    def observe_duplicate(self, shard: int) -> None:
        self.shards[shard].duplicates += 1

    def observe_timeout(self, shard: int, backoff_rounds: int) -> None:
        s = self.shards[shard]
        s.timeouts += 1
        s.backoff_rounds += int(backoff_rounds)

    def observe_crash(self, shard: int, reason: str) -> None:
        s = self.shards[shard]
        s.crashes += 1
        s.last_crash_reason = str(reason)

    def observe_recovery(self, shard: int, replayed: int,
                         ms: float) -> None:
        s = self.shards[shard]
        s.recoveries += 1
        s.replayed += int(replayed)
        s.recovery_ms.append(float(ms))

    # -- reporting --

    def latency_percentiles(self) -> Dict[str, Optional[float]]:
        return _latency_percentiles(self._latencies)

    def reconciles(self, pending_by_shard: List[int]) -> bool:
        """True iff EVERY shard's sub-batch identity closes (the cluster
        aggregate then closes by summation)."""
        return all(s.reconciles(p)
                   for s, p in zip(self.shards, pending_by_shard))

    def report(self, pending_by_shard: List[int],
               health_by_shard: List[str],
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        if len(pending_by_shard) != self.n_shards or \
                len(health_by_shard) != self.n_shards:
            raise ValueError(
                f"need one pending/health entry per shard "
                f"({self.n_shards}), got {len(pending_by_shard)}/"
                f"{len(health_by_shard)}")
        busy_s = max(self._clock() - self.t_start, 1e-9)
        agg = {k: sum(getattr(s, k) for s in self.shards)
               for k in ("submitted", "applied", "events_applied",
                         "posts", "shed_queue", "shed_unavailable",
                         "lost_on_crash", "rejected", "duplicates",
                         "timeouts", "crashes", "recoveries",
                         "replayed", "lost_in_window", "reattaches",
                         "resyncs")}
        pending = sum(int(p) for p in pending_by_shard)
        out: Dict[str, Any] = {
            "version": 2,
            "n_shards": self.n_shards,
            "ingested": agg["submitted"],
            "applied": agg["applied"],
            "shed": (agg["shed_queue"] + agg["shed_unavailable"]
                     + agg["lost_on_crash"]),
            "rejected": agg["rejected"],
            "duplicates": agg["duplicates"],
            "pending": pending,
            "reconciles": self.reconciles(pending_by_shard),
            "events_applied": agg["events_applied"],
            "posts": agg["posts"],
            "timeouts": agg["timeouts"],
            "crashes": agg["crashes"],
            "recoveries": agg["recoveries"],
            "replayed": agg["replayed"],
            "lost_in_window": agg["lost_in_window"],
            "reattaches": agg["reattaches"],
            "resyncs": agg["resyncs"],
            "global_rejected_batches": self.global_rejected,
            "topology": dict(self.topology),
            "decisions_served": self.decisions_served,
            "stale_decisions": self.stale_decisions,
            "busy_s": round(busy_s, 6),
            "events_per_sec": round(agg["events_applied"] / busy_s, 1),
            "batches_per_sec": round(agg["applied"] / busy_s, 1),
            "decision_latency": self.latency_percentiles(),
            "shards": [s.as_dict(p, h)
                       for s, p, h in zip(self.shards, pending_by_shard,
                                          health_by_shard)],
        }
        if extra:
            out.update(extra)
        return out

    def write(self, path: str, pending_by_shard: List[int],
              health_by_shard: List[str],
              extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Land the report as the enveloped ``rq.serving.metrics/2``
        artifact (atomic + checksummed); returns the payload."""
        payload = self.report(pending_by_shard, health_by_shard,
                              extra=extra)
        _integrity.write_json(path, payload,
                              schema=CLUSTER_METRICS_SCHEMA)
        return payload
