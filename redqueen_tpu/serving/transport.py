"""Length-prefixed, checksummed frame protocol between the shard router
and its out-of-process workers — over pipes or TCP sockets.

One frame = ``MAGIC(4) | length(u32 BE) | crc32(u32 BE) | payload`` with
a UTF-8 JSON payload.  The checksum covers the payload bytes, so a
bit-flip in transit is a DETECTED :class:`FrameError`, never a silently
trusted message; a declared length past :data:`MAX_FRAME_BYTES` is
refused before a single payload byte is read (a garbage length field
must not drive an allocation).  Frames ride ordinary pipes — the worker
owns one pipe pair per process, which is exactly the fault-domain
boundary: a SIGKILLed worker is an EOF, a wedged one is a timeout, a
corrupted one is a checksum mismatch, and each maps to its own typed
error so the router can degrade that one shard instead of guessing.

**TCP mode** (the cross-host placement): the router owns one
:class:`Listener` per shard; a worker launched with ``--connect
HOST:PORT`` dials it and authenticates with a **hello frame** carrying
its shard index and the per-cluster token (read from the
``RQ_WORKER_TOKEN`` environment, never argv — ``ps`` must not leak it).
The byte protocol is IDENTICAL to the pipe mode — a connected socket's
fd plugs straight into :class:`FrameReader`/:func:`write_frame` — so
every corruption/EOF/timeout shape classifies the same way; what TCP
adds is RECONNECTION: a worker that loses its link redials under
``runtime.supervisor.RetryPolicy`` backoff and re-hellos, and the
router re-accepts the SAME live process (hello pid must match) instead
of declaring it dead — a network partition degrades and heals without
journal replay.  Plain loopback/LAN framing with checksums, not
transport encryption: the token gates accidental cross-talk, not a
hostile network (run cross-host deployments over a trusted link).

Error taxonomy (all subclass :class:`TransportError`):

- :class:`FrameError`      — the byte stream is poisoned (bad magic,
  checksum mismatch, oversized declared length, non-JSON payload, or a
  protocol-level desync).  The connection cannot be resynchronized —
  the router must tear the worker down.
- :class:`TransportEOF`    — the peer closed the pipe (clean after a
  frame boundary, or torn mid-frame: ``partial_bytes`` says which).
- :class:`TransportTimeout` — no complete frame before the deadline
  (the wedged-worker shape; the peer may still be alive).

Stdlib only; safe to import before jax — the worker child stays
importable without a backend until it loads its shard.
"""

from __future__ import annotations

import json
import os
import select
import struct
import time
import zlib
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "MAGIC",
    "HEADER_BYTES",
    "MAX_FRAME_BYTES",
    "TransportError",
    "FrameError",
    "TransportEOF",
    "TransportTimeout",
    "encode_frame",
    "write_frame",
    "FrameReader",
    "Listener",
    "connect_worker",
    "HELLO_KIND",
    "ENV_WORKER_TOKEN",
    "TRACE_KEY",
    "attach_trace",
    "extract_trace",
]

HELLO_KIND = "hello"
# The cluster token travels by environment, never argv: a secret on the
# command line is visible to every local `ps`.
ENV_WORKER_TOKEN = "RQ_WORKER_TOKEN"

#: Reserved frame field carrying the telemetry trace context
#: (``{"tid", "sid"}``) across the worker protocol — pipes AND sockets
#: ride the same frames, so one request's spans stitch across processes
#: and hosts with no second mechanism.  Absent when tracing is off (the
#: wire cost of disabled telemetry is zero bytes).
TRACE_KEY = "trace"


def attach_trace(frame: Dict[str, Any]) -> Dict[str, Any]:
    """Stamp the CURRENT telemetry wire context onto an outgoing
    request frame (mutates + returns it): the live ``{"tid", "sid"}``,
    or the explicit ``{"drop": 1}`` marker inside a sampled-OUT trace
    (the receiver must drop the subtree too — sampling is trace-global,
    never per-process).  No-op when tracing is disabled or no span is
    open."""
    from ..runtime import telemetry as _telemetry

    ctx = _telemetry.wire_context()
    if ctx is not None:
        frame[TRACE_KEY] = ctx
    return frame


def extract_trace(frame: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The trace context a received frame carries, or None.  Feed it to
    ``runtime.telemetry.attach`` so the handler's spans chain under the
    remote sender's span."""
    ctx = frame.get(TRACE_KEY)
    return ctx if isinstance(ctx, dict) else None


MAGIC = b"RQF1"
_HEADER = struct.Struct(">4sII")  # magic, payload length, crc32(payload)
HEADER_BYTES = _HEADER.size
# Generous bound (a million-edge gather is ~20 MB of JSON) that still
# refuses a garbage length field before it drives an allocation.
MAX_FRAME_BYTES = 64 << 20


class TransportError(RuntimeError):
    """Base of every worker-transport failure."""


class FrameError(TransportError):
    """The byte stream is poisoned (bad magic / checksum / length /
    payload, or a response that violates the request protocol).  There
    is no way to find the next frame boundary in a corrupt stream, so
    the connection must be torn down, never resynchronized by guess."""


class TransportEOF(TransportError):
    """The peer closed the pipe.  ``partial_bytes`` > 0 means the close
    tore a frame mid-transmission (the crash-mid-response shape)."""

    def __init__(self, message: str, partial_bytes: int = 0):
        self.partial_bytes = int(partial_bytes)
        super().__init__(message)


class TransportTimeout(TransportError):
    """No complete frame arrived before the deadline — the peer may be
    wedged (distinct from dead: EOF) or merely slow."""


def encode_frame(payload: Dict[str, Any]) -> bytes:
    """One frame as bytes: header + JSON payload.  ``allow_nan`` stays
    on (Python json round-trips NaN/Inf) — serving carries quarantined
    non-finite ranks through ``gather`` frames."""
    body = json.dumps(payload, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise FrameError(
            f"refusing to send a {len(body)}-byte frame "
            f"(MAX_FRAME_BYTES={MAX_FRAME_BYTES})")
    return _HEADER.pack(MAGIC, len(body), zlib.crc32(body)) + body


def write_frame(fd: int, payload: Dict[str, Any]) -> None:
    """Write one frame to a pipe fd.  A single writer per pipe by
    construction (the worker's main loop / the router's handle), so
    frames never interleave; short writes are completed in a loop."""
    data = encode_frame(payload)
    view = memoryview(data)
    while view:
        n = os.write(fd, view)
        view = view[n:]


class FrameReader:
    """Buffered frame reader over a pipe fd with deadline support.

    One instance owns the read side; :meth:`read_frame` returns the next
    decoded payload dict or raises the typed transport errors above.
    ``timeout_s=None`` blocks, ``0`` polls (used to drain heartbeat
    frames without waiting)."""

    def __init__(self, fd: int, clock=time.monotonic):
        self._fd = fd
        self._buf = bytearray()
        self._clock = clock
        self._eof = False

    def _fill(self, deadline: Optional[float]) -> bool:
        """Pull more bytes; False on timeout, raises on EOF with data
        pending (torn frame handled by the caller)."""
        if self._eof:
            return True
        if deadline is not None:
            # Clamp, never early-return: an expired (or zero) deadline
            # must still POLL the fd once — ``timeout_s=0`` is the
            # heartbeat-drain contract, and frames already delivered to
            # the pipe must be readable without waiting.
            remaining = max(0.0, deadline - self._clock())
            try:
                r, _, _ = select.select([self._fd], [], [], remaining)
            except (OSError, ValueError):
                self._eof = True  # fd torn down under us: peer is gone
                return True
            if not r:
                return False
        try:
            chunk = os.read(self._fd, 1 << 16)
        except OSError:
            # A reset/closed socket (ECONNRESET, EBADF after a hard
            # teardown) is the same fact as EOF for the caller: the
            # peer is gone mid-stream.
            chunk = b""
        if not chunk:
            self._eof = True
        else:
            self._buf.extend(chunk)
        return True

    def read_frame(self, timeout_s: Optional[float] = None
                   ) -> Dict[str, Any]:
        """Next payload dict.  Raises :class:`TransportTimeout` when no
        complete frame lands in ``timeout_s``, :class:`TransportEOF` on
        a closed pipe (``partial_bytes`` set for a torn frame), and
        :class:`FrameError` for every corruption shape."""
        deadline = (None if timeout_s is None
                    else self._clock() + float(timeout_s))
        while True:
            frame = self._try_decode()
            if frame is not None:
                return frame
            if self._eof:
                n = len(self._buf)
                raise TransportEOF(
                    f"peer closed the pipe"
                    + (f" mid-frame ({n} torn bytes pending)" if n
                       else ""), partial_bytes=n)
            if not self._fill(deadline):
                raise TransportTimeout(
                    f"no complete frame within {timeout_s}s "
                    f"({len(self._buf)} bytes buffered)")

    def read_bytes(self, n: int, timeout_s: Optional[float] = None
                   ) -> bytes:
        """Exactly ``n`` raw bytes that FOLLOW a frame — the replication
        append sub-protocol's out-of-band record body (a small JSON
        header frame announces ``body_len``, then the pre-serialized
        record bytes ride the stream verbatim: no base64, no second
        JSON encode, no ``MAX_FRAME_BYTES`` coupling).  Same deadline
        semantics as :meth:`read_frame`; an EOF mid-body raises
        :class:`TransportEOF` with the torn length in
        ``partial_bytes``."""
        n = int(n)
        if n < 0:
            raise FrameError(f"negative raw-body length {n}")
        deadline = (None if timeout_s is None
                    else self._clock() + float(timeout_s))
        while len(self._buf) < n:
            if self._eof:
                raise TransportEOF(
                    f"peer closed the pipe mid-body "
                    f"({len(self._buf)} of {n} bytes arrived)",
                    partial_bytes=len(self._buf))
            if not self._fill(deadline):
                raise TransportTimeout(
                    f"no complete {n}-byte body within {timeout_s}s "
                    f"({len(self._buf)} bytes buffered)")
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def _try_decode(self) -> Optional[Dict[str, Any]]:
        if len(self._buf) < HEADER_BYTES:
            return None
        magic, length, crc = _HEADER.unpack_from(self._buf)
        if magic != MAGIC:
            raise FrameError(
                f"bad frame magic {bytes(magic)!r} (want {MAGIC!r}) — "
                f"the stream is poisoned")
        if length > MAX_FRAME_BYTES:
            raise FrameError(
                f"declared frame length {length} exceeds "
                f"MAX_FRAME_BYTES={MAX_FRAME_BYTES} — refusing before "
                f"reading the payload")
        if len(self._buf) < HEADER_BYTES + length:
            return None
        body = bytes(self._buf[HEADER_BYTES:HEADER_BYTES + length])
        del self._buf[:HEADER_BYTES + length]
        got = zlib.crc32(body)
        if got != crc:
            raise FrameError(
                f"frame checksum mismatch (crc32 {got:#010x} != "
                f"declared {crc:#010x}) — payload corrupted in transit")
        try:
            payload = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise FrameError(
                f"frame payload is not valid JSON: {e}") from e
        if not isinstance(payload, dict):
            raise FrameError(
                f"frame payload must be an object, got "
                f"{type(payload).__name__}")
        return payload


# ---------------------------------------------------------------------------
# TCP mode: router-side listener + worker-side dialer
# ---------------------------------------------------------------------------


class Listener:
    """The router's accept point for ONE socket-placed shard.

    Per-shard on purpose: accept routing is unambiguous (whatever dials
    this port claims this shard, and the hello proves it), and a
    replacement or reconnecting worker re-uses the same address — the
    remote-spawn contract is just "run the printed command on any host
    that can reach this port".

    :meth:`accept` validates the hello frame (kind/shard/token, and
    optionally the pid for reattach-after-partition: only the SAME live
    process may resume its shard); connections failing validation are
    closed and the wait continues until the deadline — a port-scanner or
    a mis-wired worker cannot occupy the slot."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 clock=time.monotonic):
        import socket as _socket

        self._clock = clock
        self._sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        self._sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(8)
        self.host, self.port = self._sock.getsockname()[:2]

    @property
    def address(self) -> str:
        """``host:port`` — what the worker's ``--connect`` takes."""
        return f"{self.host}:{self.port}"

    def accept(self, token: str, expect_shard: int,
               timeout_s: float = 30.0,
               expect_pid: Optional[int] = None
               ) -> Tuple[Any, Dict[str, Any], "FrameReader"]:
        """Wait for a worker to dial + hello; returns ``(socket, hello,
        reader)``.  The returned reader already owns any bytes buffered
        past the hello — callers MUST keep it (constructing a fresh
        reader would drop them)."""
        import socket as _socket

        deadline = self._clock() + float(timeout_s)
        while True:
            remaining = deadline - self._clock()
            if remaining <= 0:
                raise TransportTimeout(
                    f"no worker for shard {expect_shard} dialed "
                    f"{self.address} within {timeout_s}s")
            r, _, _ = select.select([self._sock], [], [], remaining)
            if not r:
                continue
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                continue
            try:
                conn.setsockopt(_socket.IPPROTO_TCP,
                                _socket.TCP_NODELAY, 1)
                reader = FrameReader(conn.fileno(), clock=self._clock)
                hello = reader.read_frame(timeout_s=min(5.0, remaining))
            except (TransportError, OSError):
                # A hello that never arrives, a reset mid-handshake, a
                # setsockopt on an already-dead conn: close the fd —
                # leaking it here wedges the slot — and keep waiting.
                conn.close()
                continue
            except BaseException:
                conn.close()  # unexpected: still never leak the fd
                raise
            if (hello.get("kind") != HELLO_KIND
                    or hello.get("token") != token
                    or int(hello.get("shard", -1)) != int(expect_shard)
                    or (expect_pid is not None
                        and int(hello.get("pid", -1)) != int(expect_pid))):
                # Wrong credentials or a stranger process: refuse the
                # connection, keep the slot open for the real worker.
                conn.close()
                continue
            return conn, hello, reader

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def connect_worker(address: str, shard: int, token: str,
                   timeout_s: float = 10.0):
    """Worker-side dial: connect to the router's per-shard listener and
    send the hello frame.  Returns the connected socket (blocking, with
    TCP_NODELAY — request/response frames must not sit in Nagle's
    buffer).  Raises ``OSError`` on connection failure — the caller owns
    the RetryPolicy redial loop."""
    import socket as _socket

    host, _, port = address.rpartition(":")
    sock = _socket.create_connection((host or "127.0.0.1", int(port)),
                                     timeout=float(timeout_s))
    try:
        sock.settimeout(None)
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        write_frame(sock.fileno(),
                    {"kind": HELLO_KIND, "shard": int(shard),
                     "token": str(token), "pid": os.getpid()})
    except BaseException:
        # the redial loop retries for hours under RetryPolicy backoff —
        # leaking one fd per failed hello exhausts the process fd table
        sock.close()
        raise
    return sock
