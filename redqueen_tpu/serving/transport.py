"""Length-prefixed, checksummed frame protocol between the shard router
and its out-of-process workers.

One frame = ``MAGIC(4) | length(u32 BE) | crc32(u32 BE) | payload`` with
a UTF-8 JSON payload.  The checksum covers the payload bytes, so a
bit-flip in transit is a DETECTED :class:`FrameError`, never a silently
trusted message; a declared length past :data:`MAX_FRAME_BYTES` is
refused before a single payload byte is read (a garbage length field
must not drive an allocation).  Frames ride ordinary pipes — the worker
owns one pipe pair per process, which is exactly the fault-domain
boundary: a SIGKILLed worker is an EOF, a wedged one is a timeout, a
corrupted one is a checksum mismatch, and each maps to its own typed
error so the router can degrade that one shard instead of guessing.

Error taxonomy (all subclass :class:`TransportError`):

- :class:`FrameError`      — the byte stream is poisoned (bad magic,
  checksum mismatch, oversized declared length, non-JSON payload, or a
  protocol-level desync).  The connection cannot be resynchronized —
  the router must tear the worker down.
- :class:`TransportEOF`    — the peer closed the pipe (clean after a
  frame boundary, or torn mid-frame: ``partial_bytes`` says which).
- :class:`TransportTimeout` — no complete frame before the deadline
  (the wedged-worker shape; the peer may still be alive).

Stdlib only; safe to import before jax — the worker child stays
importable without a backend until it loads its shard.
"""

from __future__ import annotations

import json
import os
import select
import struct
import time
import zlib
from typing import Any, Dict, Optional

__all__ = [
    "MAGIC",
    "HEADER_BYTES",
    "MAX_FRAME_BYTES",
    "TransportError",
    "FrameError",
    "TransportEOF",
    "TransportTimeout",
    "encode_frame",
    "write_frame",
    "FrameReader",
]

MAGIC = b"RQF1"
_HEADER = struct.Struct(">4sII")  # magic, payload length, crc32(payload)
HEADER_BYTES = _HEADER.size
# Generous bound (a million-edge gather is ~20 MB of JSON) that still
# refuses a garbage length field before it drives an allocation.
MAX_FRAME_BYTES = 64 << 20


class TransportError(RuntimeError):
    """Base of every worker-transport failure."""


class FrameError(TransportError):
    """The byte stream is poisoned (bad magic / checksum / length /
    payload, or a response that violates the request protocol).  There
    is no way to find the next frame boundary in a corrupt stream, so
    the connection must be torn down, never resynchronized by guess."""


class TransportEOF(TransportError):
    """The peer closed the pipe.  ``partial_bytes`` > 0 means the close
    tore a frame mid-transmission (the crash-mid-response shape)."""

    def __init__(self, message: str, partial_bytes: int = 0):
        self.partial_bytes = int(partial_bytes)
        super().__init__(message)


class TransportTimeout(TransportError):
    """No complete frame arrived before the deadline — the peer may be
    wedged (distinct from dead: EOF) or merely slow."""


def encode_frame(payload: Dict[str, Any]) -> bytes:
    """One frame as bytes: header + JSON payload.  ``allow_nan`` stays
    on (Python json round-trips NaN/Inf) — serving carries quarantined
    non-finite ranks through ``gather`` frames."""
    body = json.dumps(payload, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise FrameError(
            f"refusing to send a {len(body)}-byte frame "
            f"(MAX_FRAME_BYTES={MAX_FRAME_BYTES})")
    return _HEADER.pack(MAGIC, len(body), zlib.crc32(body)) + body


def write_frame(fd: int, payload: Dict[str, Any]) -> None:
    """Write one frame to a pipe fd.  A single writer per pipe by
    construction (the worker's main loop / the router's handle), so
    frames never interleave; short writes are completed in a loop."""
    data = encode_frame(payload)
    view = memoryview(data)
    while view:
        n = os.write(fd, view)
        view = view[n:]


class FrameReader:
    """Buffered frame reader over a pipe fd with deadline support.

    One instance owns the read side; :meth:`read_frame` returns the next
    decoded payload dict or raises the typed transport errors above.
    ``timeout_s=None`` blocks, ``0`` polls (used to drain heartbeat
    frames without waiting)."""

    def __init__(self, fd: int, clock=time.monotonic):
        self._fd = fd
        self._buf = bytearray()
        self._clock = clock
        self._eof = False

    def _fill(self, deadline: Optional[float]) -> bool:
        """Pull more bytes; False on timeout, raises on EOF with data
        pending (torn frame handled by the caller)."""
        if self._eof:
            return True
        if deadline is not None:
            # Clamp, never early-return: an expired (or zero) deadline
            # must still POLL the fd once — ``timeout_s=0`` is the
            # heartbeat-drain contract, and frames already delivered to
            # the pipe must be readable without waiting.
            remaining = max(0.0, deadline - self._clock())
            r, _, _ = select.select([self._fd], [], [], remaining)
            if not r:
                return False
        chunk = os.read(self._fd, 1 << 16)
        if not chunk:
            self._eof = True
        else:
            self._buf.extend(chunk)
        return True

    def read_frame(self, timeout_s: Optional[float] = None
                   ) -> Dict[str, Any]:
        """Next payload dict.  Raises :class:`TransportTimeout` when no
        complete frame lands in ``timeout_s``, :class:`TransportEOF` on
        a closed pipe (``partial_bytes`` set for a torn frame), and
        :class:`FrameError` for every corruption shape."""
        deadline = (None if timeout_s is None
                    else self._clock() + float(timeout_s))
        while True:
            frame = self._try_decode()
            if frame is not None:
                return frame
            if self._eof:
                n = len(self._buf)
                raise TransportEOF(
                    f"peer closed the pipe"
                    + (f" mid-frame ({n} torn bytes pending)" if n
                       else ""), partial_bytes=n)
            if not self._fill(deadline):
                raise TransportTimeout(
                    f"no complete frame within {timeout_s}s "
                    f"({len(self._buf)} bytes buffered)")

    def _try_decode(self) -> Optional[Dict[str, Any]]:
        if len(self._buf) < HEADER_BYTES:
            return None
        magic, length, crc = _HEADER.unpack_from(self._buf)
        if magic != MAGIC:
            raise FrameError(
                f"bad frame magic {bytes(magic)!r} (want {MAGIC!r}) — "
                f"the stream is poisoned")
        if length > MAX_FRAME_BYTES:
            raise FrameError(
                f"declared frame length {length} exceeds "
                f"MAX_FRAME_BYTES={MAX_FRAME_BYTES} — refusing before "
                f"reading the payload")
        if len(self._buf) < HEADER_BYTES + length:
            return None
        body = bytes(self._buf[HEADER_BYTES:HEADER_BYTES + length])
        del self._buf[:HEADER_BYTES + length]
        got = zlib.crc32(body)
        if got != crc:
            raise FrameError(
                f"frame checksum mismatch (crc32 {got:#010x} != "
                f"declared {crc:#010x}) — payload corrupted in transit")
        try:
            payload = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise FrameError(
                f"frame payload is not valid JSON: {e}") from e
        if not isinstance(payload, dict):
            raise FrameError(
                f"frame payload must be an object, got "
                f"{type(payload).__name__}")
        return payload
