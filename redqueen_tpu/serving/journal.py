"""Crash-safe state journaling: checksummed append-only record log.

The journal is the serving runtime's write-ahead source of truth: every
applied micro-batch (schema ``rq.serving.journal/1``) or coalesced GROUP
of micro-batches (schema ``rq.serving.journal/2`` — the wire-speed
ingest path journals one record per poll round) lands as ONE JSONL
record — the events, the decision(s) taken, and the post-apply carry
digest — wrapped in the same checksummed envelope format as every other
artifact in the repo (``runtime.integrity.make_envelope``).
Under the default durability mode appends are flushed + fsynced before
the apply is acknowledged, so a SIGKILL at ANY instruction boundary
leaves one of exactly two shapes:

- every acknowledged batch is a complete, verifiable record;
- plus at most one **torn tail** — a partial last line from an append the
  kill interrupted (that batch was never acknowledged).

Recovery (:func:`replay`) verifies records front-to-back.  A torn or
corrupt TAIL is quarantined — the bad bytes move to a
``<journal>.torn-<utc-ts>`` sidecar with a structured report beside it
(``runtime.integrity.quarantine`` semantics, scoped to the tail), the
journal truncates back to its last good record, and replay returns the
verified prefix: torn bytes are never trusted and never silently
deleted.  A bad record in the MIDDLE of the file is a different animal —
an fsynced record can only fail verification through real corruption
(bit rot, truncation by a non-atomic copier), and nothing after it can
be trusted to follow the right state — so that raises a typed
:class:`JournalError` instead of guessing.

Stdlib + numpy only; safe to import before jax.
"""

from __future__ import annotations

import errno as _errno
import json
import mmap
import os
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..runtime import faultinject as _faultinject
from ..runtime import integrity as _integrity
from ..runtime import telemetry as _telemetry

__all__ = ["Journal", "JournalError", "replay", "tear_tail",
           "rotate", "prune_segments", "segment_paths",
           "durability_info", "migrate_to_binary", "journal_format",
           "JOURNAL_SCHEMA", "JOURNAL_GROUP_SCHEMA", "JOURNAL_FILENAME",
           "FLUSH_MODES", "JOURNAL_FORMATS",
           "BINARY_HEADER_MAGIC", "BINARY_RECORD_MAGIC",
           "BINARY_SLOT_BYTES", "GROUP_BODY_MAGIC",
           "pack_group_body", "unpack_group_body"]

JOURNAL_SCHEMA = "rq.serving.journal/1"
# One coalesced poll ROUND per record: {"seqs", "counts", flat "times"/
# "feeds", "decisions", "state_digest"} — times/feeds stay flat so
# flat-array consumers (learn.ingest.from_journal) read both schemas
# through one code path.
JOURNAL_GROUP_SCHEMA = "rq.serving.journal/2"

# Durability modes (the ack contract; see docs/DESIGN.md "Durability
# modes & the ack contract"):
#
# - "sync"  — append() returns only after the record is flushed, and
#   fsynced every ``fsync_every_n``-th append (n=1: every append — the
#   PR 6 contract: the ack IS the fsync).
# - "group" — ASYNC GROUP COMMIT: append() returns after the OS-level
#   flush; a background thread forces the fsync within
#   ``max_flush_delay_ms``, and append() forces it inline the moment
#   ``max_unflushed_records`` acked records are in flight.  The ack
#   races the fsync inside an EXPLICIT, bounded durability window: a
#   power-style crash loses at most ``max_unflushed_records`` acked
#   records (or ``max_flush_delay_ms`` of acks, whichever bound fires
#   first); recovery reports exactly which acked seqs were lost
#   (``RecoveryInfo.lost_acked_seqs``) and the source's retransmit
#   heals them.  A plain process SIGKILL loses nothing: the flushed
#   bytes survive in the page cache.
FLUSH_MODES = ("sync", "group")

# The on-disk journal filename inside a runtime/shard directory — a
# cross-subsystem contract: the serving runtime writes it and external
# consumers (learn.ingest.from_journal) locate it by this name.  The
# name is format-agnostic on purpose: a file that BEGINS with
# ``BINARY_HEADER_MAGIC`` holds the binary fixed-slot segment format,
# anything else is JSONL — every reader sniffs (:func:`journal_format`),
# so migration never breaks a consumer that locates journals by name.
JOURNAL_FILENAME = "journal.jsonl"

# On-disk record encodings.  ``jsonl`` is the PR 6 format: one
# checksummed ``make_envelope`` JSON object per line.  ``binary`` is the
# mmap'd FIXED-SLOT segment format (modeled on the telemetry flight
# ring): records land in slot-aligned frames — a 20-byte header
# (``BINARY_RECORD_MAGIC`` | payload_len | crc32 | trailing seq) + the
# compact-JSON payload, zero-padded to a multiple of
# ``BINARY_SLOT_BYTES`` — written through one mmap'd preallocated
# region.  What it buys: no per-record sha256 envelope and ONE
# serialization instead of two (the envelope serializes the payload for
# its digest, then serializes the wrapper again), with crc32 as the
# integrity check; what it keeps: bit-identical replay (the payload
# dict round-trips through the same JSON), the torn-tail quarantine
# (slot alignment localizes a torn write, exactly like a torn flight-
# ring slot), and the mid-file-corruption refusal.  Migration from
# JSONL is ONE-WAY (:func:`migrate_to_binary`).
JOURNAL_FORMATS = ("jsonl", "binary")

#: First bytes of a binary-format journal file (the sniffing contract).
BINARY_HEADER_MAGIC = b"RQJH"
#: Per-record frame magic inside a binary journal.
BINARY_RECORD_MAGIC = b"RQJ3"
#: Fixed slot width: record frames are zero-padded to a multiple of
#: this, so a torn concurrent/crashed write is localized to its own
#: frame and the scan resynchronizes on slot boundaries.
BINARY_SLOT_BYTES = 256
#: mmap grow granularity (slots): the region is extended in chunks so
#: the append path never pays a per-record ftruncate+remap.
_BINARY_GROW_SLOTS = 4096
#: ``>4sIIq``: record magic, payload byte length, crc32(payload),
#: trailing applied seq (-1 = none recorded).
_BINARY_RECORD_HDR = struct.Struct(">4sIIq")


def durability_info(flush_mode: str, fsync_every_n: int,
                    max_unflushed_records: int,
                    max_flush_delay_ms: float,
                    coalesce: int,
                    replication: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """THE durability-window description (one definition — the runtime
    and the cluster both embed it in their metrics artifacts, and the
    two must never drift): what an ack MEANS under this configuration,
    and the bounded loss a machine-level crash may consume.  See
    docs/DESIGN.md "Durability tiers & the ack contract".

    ``replication`` (the quorum tier) is the
    ``{"factor": R, "quorum": Q}`` description of a replication group:
    an ack then additionally means Q of the R+1 holders (leader
    included) held the record in memory at ack time, so the loss
    window applies only when EVERY holder dies before the lagging
    checkpoint — any single-node loss (SIGKILL, machine crash of one
    host) is survived outright."""
    if flush_mode == "group":
        window_records = int(max_unflushed_records) - 1
    else:
        window_records = int(fsync_every_n) - 1
    out = {
        "flush_mode": str(flush_mode),
        "fsync_every_n": int(fsync_every_n),
        "max_unflushed_records": int(max_unflushed_records),
        "max_flush_delay_ms": float(max_flush_delay_ms),
        "coalesce": int(coalesce),
        # True iff an ack implies the record is on media (the PR 6
        # contract); False means the ack races the fsync inside the
        # bounded window below.
        "ack_is_durable": window_records == 0,
        # A machine-level crash loses at most this many acked journal
        # RECORDS; one record covers up to ``coalesce`` batches, so the
        # batch bound is the product.
        "loss_window_records": window_records,
        "loss_window_batches": window_records * int(coalesce),
        # The three-tier name: "sync" (ack == fsync), "window" (ack
        # races a bounded fsync), "quorum" (ack == Q in-memory holders;
        # fsync is the lagging checkpoint).
        "tier": "sync" if window_records == 0 else "window",
        "ack_survives_single_node_loss": window_records == 0,
    }
    if replication:
        out["replication"] = {
            "factor": int(replication.get("factor", 0)),
            "quorum": int(replication.get("quorum", 0)),
        }
        if out["replication"]["factor"] >= 1 \
                and out["replication"]["quorum"] >= 1:
            out["tier"] = "quorum"
            out["ack_survives_single_node_loss"] = True
    return out


def _slot_ceil(n: int) -> int:
    """Round up to the fixed slot width."""
    return -(-int(n) // BINARY_SLOT_BYTES) * BINARY_SLOT_BYTES


def journal_format(path: str) -> Optional[str]:
    """Sniff a journal file's on-disk format: ``"binary"`` when it
    begins with ``BINARY_HEADER_MAGIC``, ``"jsonl"`` for any other
    non-empty file, None when the file is missing or empty (no format
    committed yet).  Every reader goes through this, so a mixed tree
    (JSONL segments + binary live file, mid-migration) replays."""
    try:
        with open(path, "rb") as f:
            head = f.read(len(BINARY_HEADER_MAGIC))
    except (FileNotFoundError, IsADirectoryError, NotADirectoryError):
        return None
    if not head:
        return None
    return "binary" if head == BINARY_HEADER_MAGIC else "jsonl"


def _binary_header_slot() -> bytes:
    meta = json.dumps({"kind": "rq.jbin/1", "slot": BINARY_SLOT_BYTES},
                      separators=(",", ":")).encode("utf-8")
    hdr = BINARY_HEADER_MAGIC + meta
    return hdr + b"\x00" * (BINARY_SLOT_BYTES - len(hdr))


def _pack_binary_frame(body: bytes, seq: Optional[int]) -> bytes:
    """One slot-padded record frame: header + compact-JSON payload
    bytes, zero-padded to the slot multiple."""
    frame = _BINARY_RECORD_HDR.pack(
        BINARY_RECORD_MAGIC, len(body), zlib.crc32(body) & 0xFFFFFFFF,
        -1 if seq is None else int(seq)) + body
    return frame + b"\x00" * (_slot_ceil(len(frame)) - len(frame))


# A PACKED group-record body: the coalesced-apply flat arrays land in
# the binary slot as raw little-endian bytes instead of being walked
# float-by-float through the JSON encoder (the leader's ~0.9 ms/round
# encode at coalesce=32 — ROADMAP durability residue 1(a)).  The body
# is self-describing (this magic is not a valid JSON first byte), so
# every reader — binary replay, ``append_raw`` on a JSONL journal,
# replica heal — sniffs per RECORD and a mixed journal replays through
# one code path.  Times stay float64: the packed record must ingest
# (learn.ingest.from_journal) bit-identically to the JSONL encoding of
# the same stream.
GROUP_BODY_MAGIC = b"RQGB"
_GROUP_BODY_HDR = struct.Struct(">II")  # head_json_len, n_events


def pack_group_body(seqs, counts, times, feeds, decisions,
                    state_digest: str) -> bytes:
    """Encode one coalesced group record as a packed binary body:
    small JSON head (seqs/counts/decisions/digest — O(coalesce)) plus
    the flat event arrays as raw ``<f8``/``<i4`` bytes (O(events),
    a memcpy instead of a JSON float walk)."""
    import numpy as np

    t = np.ascontiguousarray(np.asarray(times, "<f8"))
    f = np.ascontiguousarray(np.asarray(feeds, "<i4"))
    if t.ndim != 1 or t.shape != f.shape:
        raise ValueError(f"flat event arrays must be 1-D and equal "
                         f"length, got times {t.shape} feeds {f.shape}")
    head = json.dumps(
        {"seqs": [int(s) for s in seqs],
         "counts": [int(c) for c in counts],
         "decisions": decisions, "state_digest": str(state_digest)},
        separators=(",", ":")).encode("utf-8")
    return b"".join((GROUP_BODY_MAGIC,
                     _GROUP_BODY_HDR.pack(len(head), t.size),
                     head, t.tobytes(), f.tobytes()))


def unpack_group_body(body: bytes) -> Dict[str, Any]:
    """Decode a :func:`pack_group_body` record back into the exact
    payload dict the JSON encoding carries (``rq.serving.journal/2``
    shape) — replay is representation-blind."""
    import numpy as np

    if not body.startswith(GROUP_BODY_MAGIC):
        raise ValueError("not a packed group body")
    at = len(GROUP_BODY_MAGIC)
    head_len, n = _GROUP_BODY_HDR.unpack_from(body, at)
    at += _GROUP_BODY_HDR.size
    payload = json.loads(body[at:at + head_len].decode("utf-8"))
    at += head_len
    if len(body) != at + 8 * n + 4 * n:
        raise ValueError(
            f"packed group body length {len(body)} does not match "
            f"head_len {head_len} + {n} events")
    payload["times"] = np.frombuffer(body, "<f8", n, at).tolist()
    payload["feeds"] = np.frombuffer(body, "<i4", n, at + 8 * n).tolist()
    return payload


def _payload_trailing_seq(payload: Dict[str, Any]) -> Optional[int]:
    """The record's last applied seq, derived the same way for both
    schemas (group records carry ``seqs``, singles ``seq``)."""
    if "seqs" in payload and payload["seqs"]:
        return int(payload["seqs"][-1])
    if "seq" in payload:
        return int(payload["seq"])
    return None


def _parse_binary(data: bytes
                  ) -> Tuple[List[Tuple[int, bytes, Optional[int]]],
                             int, Optional[Tuple[int, str]]]:
    """Parse a binary journal image.  Returns ``(records, used, bad)``:
    ``records`` is ``[(frame_offset, payload_bytes, seq), ...]`` for
    each verified frame, ``used`` is the offset after the last verified
    frame (>= the header slot), and ``bad`` is None for a clean image
    else ``(offset, detail)`` where the first invalid bytes start.  A
    zero-filled remainder is NOT bad — it is the preallocated tail
    (clean EOF), exactly like an unwritten flight-ring slot."""
    hdr = _BINARY_RECORD_HDR
    records: List[Tuple[int, bytes, Optional[int]]] = []
    off = BINARY_SLOT_BYTES
    n = len(data)
    while off < n:
        chunk = data[off:off + hdr.size]
        if len(chunk) < hdr.size:
            if chunk.strip(b"\x00") == b"":
                return records, off, None
            return records, off, (off, "truncated frame header")
        magic, plen, crc, seq = hdr.unpack(chunk)
        if magic == b"\x00\x00\x00\x00":
            # Zero frame magic: clean preallocated EOF iff every
            # remaining byte is zero.
            if data[off:].strip(b"\x00") == b"":
                return records, off, None
            return records, off, (off,
                                  "nonzero bytes after zero frame magic")
        if magic != BINARY_RECORD_MAGIC:
            return records, off, (off, f"bad record magic {magic!r}")
        end = off + hdr.size + int(plen)
        if end > n:
            return records, off, (off, "frame extends past EOF")
        body = data[off + hdr.size:end]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            return records, off, (off, "crc32 mismatch")
        pad_end = off + _slot_ceil(hdr.size + int(plen))
        if data[end:min(pad_end, n)].strip(b"\x00") != b"":
            return records, off, (off, "nonzero slot padding")
        records.append((off, body, None if seq == -1 else int(seq)))
        off = min(pad_end, n)
    return records, off, None


def _scan_binary_end(path: str) -> Tuple[int, bool]:
    """(offset after the last whole record, tail-is-clean)."""
    with open(path, "rb") as f:
        data = f.read()
    _, used, bad = _parse_binary(data)
    return used, bad is None


def _binary_frame_after(data: bytes, off: int) -> bool:
    """True when a VALID record frame exists on a slot boundary after
    ``off`` — the mid-file-corruption discriminator: a torn append can
    only damage the final frame, so valid records after the bad bytes
    mean real corruption of the fsynced prefix (refuse), not a tear
    (quarantine)."""
    hdr = _BINARY_RECORD_HDR
    pos = off + BINARY_SLOT_BYTES
    while pos + hdr.size <= len(data):
        magic, plen, crc, _seq = hdr.unpack_from(data, pos)
        if magic == BINARY_RECORD_MAGIC:
            end = pos + hdr.size + int(plen)
            if end <= len(data) and \
                    zlib.crc32(data[pos + hdr.size:end]) & 0xFFFFFFFF \
                    == crc:
                return True
        pos += BINARY_SLOT_BYTES
    return False


class JournalError(RuntimeError):
    """A journal record BEFORE the tail failed verification: the file is
    corrupt in a way crash-tearing cannot produce (fsynced prefix), so
    replay refuses to trust anything past it.  Carries the path and the
    0-based record index that failed."""

    def __init__(self, path: str, record: int, reason: str):
        self.path = path
        self.record = record
        super().__init__(
            f"journal {path} record {record}: {reason} — a non-tail "
            f"record can only fail through real corruption; refusing to "
            f"replay past it (recover from the snapshot + a fresh "
            f"journal, or restore the file from backup)")


class Journal:
    """Append-only writer.  One instance owns the file handle; appends
    are atomic at the OS-write level (single ``write`` of one line).

    ``flush_mode="sync"`` (default): appends are durable (flush + fsync)
    before :meth:`append` returns — the "applied" acknowledgement the
    serving runtime gives its source is backed by this fsync.
    ``fsync_every_n`` is the SYNCHRONOUS group-commit option (default 1
    = fsync per append): with n > 1 the fsync lands every n-th append
    (and at :meth:`sync`/:meth:`close`/rotation), trading the per-batch
    fsync for a bounded loss window of n-1 acked records.

    ``flush_mode="group"`` is ASYNC group commit — the wire-speed mode:
    :meth:`append` returns after the OS-level flush, a daemon thread
    forces the fsync within ``max_flush_delay_ms``, and the window is
    hard-bounded because append() fsyncs INLINE once
    ``max_unflushed_records`` acked records are un-forced.  The
    durability watermark (:attr:`durable_seq` / ``durable_offset``) is
    what a power-style crash provably keeps; everything acked past it is
    the documented loss window, healed by retransmit (see the module
    docstring and docs/DESIGN.md "Durability modes & the ack
    contract")."""

    def __init__(self, path: str, fsync_every_n: int = 1,
                 flush_mode: str = "sync",
                 max_unflushed_records: int = 64,
                 max_flush_delay_ms: float = 50.0,
                 fmt: Optional[str] = None,
                 stage: str = "serving.journal.append"):
        if int(fsync_every_n) < 1:
            raise ValueError(
                f"fsync_every_n must be >= 1, got {fsync_every_n}")
        if flush_mode not in FLUSH_MODES:
            raise ValueError(f"flush_mode must be one of {FLUSH_MODES}, "
                             f"got {flush_mode!r}")
        if int(max_unflushed_records) < 1:
            raise ValueError(f"max_unflushed_records must be >= 1, got "
                             f"{max_unflushed_records}")
        if float(max_flush_delay_ms) <= 0:
            raise ValueError(f"max_flush_delay_ms must be > 0, got "
                             f"{max_flush_delay_ms}")
        if fmt is not None and fmt not in JOURNAL_FORMATS:
            raise ValueError(f"fmt must be one of {JOURNAL_FORMATS}, "
                             f"got {fmt!r}")
        self.path = path
        self.fsync_every_n = int(fsync_every_n)
        self.flush_mode = flush_mode
        self.max_unflushed_records = int(max_unflushed_records)
        self.max_flush_delay_ms = float(max_flush_delay_ms)
        # Telemetry stage name for appends: replica-side journals label
        # theirs differently (serving.repl.replica.append) so the
        # serving round's stage breakdown never conflates the leader's
        # critical-path append with background replica copies.
        self._stage = str(stage)
        self._unsynced = 0
        # Format resolution: explicit wins; an EXISTING file's sniffed
        # format wins over the default (a binary-migrated directory
        # reopened without the knob must never append JSONL lines into
        # a binary file); a fresh file defaults to JSONL.
        on_disk = journal_format(path)
        self.fmt = fmt or on_disk or "jsonl"
        if on_disk is not None and self.fmt != on_disk:
            raise ValueError(
                f"journal {path} holds the {on_disk!r} format but the "
                f"writer was constructed with fmt={self.fmt!r} — "
                f"migration is one-way and explicit "
                f"(journal.migrate_to_binary), never an append-time "
                f"rewrite")
        self._mm: Optional[mmap.mmap] = None
        self._mm_size = 0
        self._lock = threading.Lock()
        if self.fmt == "binary":
            self._open_binary()
        else:
            self._f = open(path, "a", encoding="utf-8")
            self._written_offset = self._f.tell()
        # Durability watermark.  Pre-existing bytes were fsynced by the
        # writer that produced them (close/rotation/recovery all sync),
        # so the baseline is the current EOF; ``durable_seq`` is None
        # until this instance forces its first fsync (records before
        # this instance are outside its ack window by construction).
        self._written_seq: Optional[int] = None
        self._written_records = 0
        self._durable_offset = self._written_offset
        self._durable_seq: Optional[int] = None
        self._durable_records = 0
        # The EXACT live durability window: one entry per acked-but-not-
        # yet-forced record (its trailing seq, or None when the record
        # carried no seq), trimmed as the watermark advances — what
        # power_loss() reports record-exactly under BOTH flush modes.
        self._pending_seqs: List[Optional[int]] = []
        # 1-based lifetime fsync-attempt counter — the ``disk:*`` fault
        # kind addresses "the N-th fsync this instance attempts", and
        # the health block reports attempts/failures side by side.
        self._fsync_attempts = 0
        self._fsync_lock = threading.Lock()
        self._disk_fault = _faultinject.disk_fault()
        self._stop = threading.Event()
        self._flush_errors = 0
        self._flusher: Optional[threading.Thread] = None
        if self.flush_mode == "group":
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True,
                name=f"journal-flush:{os.path.basename(path)}")
            self._flusher.start()

    # -- binary fixed-slot backend ------------------------------------

    def _open_binary(self) -> None:
        """Open (or create) the mmap'd fixed-slot file.  The region is
        preallocated in ``_BINARY_GROW_SLOTS`` chunks; records append at
        slot-aligned offsets through the mapping (page-cache durability
        — exactly what a process SIGKILL preserves, the same contract
        as the flight ring); close() truncates back to the used bytes
        so segments and cleanly-closed files are exact-sized."""
        existed = os.path.exists(self.path) \
            and os.path.getsize(self.path) > 0
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            self._f = os.fdopen(fd, "r+b", buffering=0)
        except BaseException:
            os.close(fd)
            raise
        try:
            # ``self._f`` owns the descriptor from here on — post-open
            # work addresses it through fileno() and the except arm
            # closes the owner, which closes the fd.
            if existed:
                used, clean = _scan_binary_end(self.path)
                if not clean:
                    # Same trust rule as replay: torn bytes are never
                    # appended after and never silently deleted.
                    _quarantine_tail(self.path, used, "torn tail record",
                                     "unterminated binary frame at reopen")
            else:
                os.pwrite(self._f.fileno(), _binary_header_slot(), 0)
                used = BINARY_SLOT_BYTES
            want = (_slot_ceil(used)
                    + _BINARY_GROW_SLOTS * BINARY_SLOT_BYTES)
            size = os.fstat(self._f.fileno()).st_size
            if size < want:
                os.ftruncate(self._f.fileno(), want)
                size = want
            mm = mmap.mmap(self._f.fileno(), size)
        except BaseException:
            self._f.close()
            raise
        # Publication happens under the lock only to make the handoff
        # explicit: the flusher thread does not exist yet (it starts at
        # the end of __init__), but the invariant "offset fields mutate
        # under _lock" should not carry an asterisk.
        with self._lock:
            self._written_offset = used
            self._mm = mm
            self._mm_size = size

    def _write_binary_locked(self, frame: bytes) -> None:
        """Append one padded record frame through the mapping (caller
        holds ``_lock``)."""
        off = self._written_offset
        end = off + len(frame)
        if end > self._mm_size:
            grow = _slot_ceil(end) \
                + _BINARY_GROW_SLOTS * BINARY_SLOT_BYTES
            os.ftruncate(self._f.fileno(), grow)
            self._mm.resize(grow)
            self._mm_size = grow
        self._mm[off:end] = frame
        self._written_offset = end

    # -- durability watermark (what a power-style crash provably keeps) --

    @property
    def durable_offset(self) -> int:
        with self._lock:
            return self._durable_offset

    @property
    def durable_seq(self) -> Optional[int]:
        """Highest appended seq known forced to media by THIS instance
        (None before its first fsync — earlier records belong to a
        previous, cleanly-synced instance)."""
        with self._lock:
            return self._durable_seq

    @property
    def flush_errors(self) -> int:
        """Background-flush fsync failures survived so far (each one
        delayed the time bound by one tick; persistent failure ends in
        the inline fsync raising)."""
        with self._lock:
            return self._flush_errors

    @property
    def unsynced(self) -> int:
        """Acked-but-not-yet-forced records — the live durability
        window (always <= ``max_unflushed_records`` in group mode)."""
        with self._lock:
            return self._written_records - self._durable_records

    def health(self) -> Dict[str, Any]:
        """The journal-health block the metrics artifacts embed:
        background-flush failures, lifetime fsync attempts, and the
        checkpoint-lag watermark (acked-but-unforced records/bytes and
        the written-vs-durable seq pair) — a silently failing fsync
        thread is visible here BEFORE a crash makes it matter."""
        with self._lock:
            return {
                "format": self.fmt,
                "flush_mode": self.flush_mode,
                "flush_errors": self._flush_errors,
                "fsync_attempts": self._fsync_attempts,
                "unsynced_records": (self._written_records
                                     - self._durable_records),
                "unsynced_bytes": (self._written_offset
                                   - self._durable_offset),
                "written_seq": self._written_seq,
                "durable_seq": self._durable_seq,
            }

    def _do_fsync(self, fd: int) -> None:
        """One fsync attempt — THE media barrier both durability paths
        (inline and background) funnel through, and therefore the one
        place the ``disk:*`` fault kind applies: when the 1-based
        lifetime attempt counter matches ``disk:eio@fsyncN`` /
        ``disk:enospc@fsyncN`` the corresponding OSError is raised
        instead of syncing.  On Linux fsync(fd) also writes back dirty
        mmap pages, so the binary backend needs no separate msync."""
        with self._fsync_lock:
            self._fsync_attempts += 1
            n = self._fsync_attempts
        df = self._disk_fault
        if df is not None and n == df.fsync:
            err = _errno.EIO if df.mode == "eio" else _errno.ENOSPC
            raise OSError(err, f"{os.strerror(err)} "
                               f"(injected disk fault: fsync #{n})")
        os.fsync(fd)

    def _fsync_locked(self) -> None:
        """fsync + advance the watermark.  Caller holds ``_lock`` —
        the INLINE path only (window bound, sync mode, close): blocking
        the ack here is the contract, not a stall.  An OSError (real or
        a ``disk:*`` injected one) propagates — the fatal-append
        contract — WITHOUT advancing the watermark."""
        self._do_fsync(self._f.fileno())
        self._durable_offset = self._written_offset
        self._durable_seq = self._written_seq
        self._durable_records = self._written_records
        self._unsynced = 0
        self._pending_seqs.clear()

    def _flush_loop(self) -> None:
        """The background group-commit flusher: every
        ``max_flush_delay_ms`` it forces any acked-but-unfsynced tail to
        media — the TIME bound of the durability window (the RECORD
        bound is enforced inline by :meth:`append`).  The fsync runs
        OUTSIDE the journal lock: on this class of filesystem an fsync
        costs tens of milliseconds, and holding the lock across it
        would stall every concurrent append — reintroducing exactly the
        ack-blocks-on-media tax async group commit exists to remove.
        The watermark is captured before the fsync and advanced after,
        so it is always conservative (never claims more durable than
        the fsync actually covered)."""
        delay = self.max_flush_delay_ms / 1e3
        while not self._stop.wait(delay):
            with self._lock:
                if self._f.closed \
                        or self._written_records == self._durable_records:
                    continue
                off = self._written_offset
                seq = self._written_seq
                recs = self._written_records
                lag = recs - self._durable_records
                fd = self._f.fileno()
            # The checkpoint-lag watermark, exported per tick so the
            # rqtrace histogram report shows how far behind the media
            # barrier actually runs (not just that it runs).
            _telemetry.observe("serving.journal.checkpoint_lag_records",
                               float(lag))
            try:
                self._do_fsync(fd)
                # Counter, not a span: this thread has no trace context
                # (a span here would start orphan root traces per tick).
                _telemetry.counter("serving.journal.bg_fsync")
            except ValueError:
                return  # fd closed under us: clean shutdown race
            except OSError:
                # A transient fsync failure must not PERMANENTLY void
                # the advertised time bound: count it (visible via
                # ``flush_errors`` and the metrics journal-health
                # block) and retry next tick — the volume may heal.  A
                # persistent failure still fails loudly: the window
                # fills, append()'s INLINE fsync raises, and the
                # runtime's fatal-append contract takes the process
                # down.
                with self._lock:
                    self._flush_errors += 1
                _telemetry.counter("serving.journal.flush_error")
                continue
            with self._lock:
                if off > self._durable_offset:
                    # Trim the EXACT pending window by how many records
                    # this fsync made durable (captured count minus the
                    # already-durable count — an inline fsync cannot
                    # have advanced past ``recs`` or we'd skip here).
                    del self._pending_seqs[:recs - self._durable_records]
                    self._durable_offset = off
                    self._durable_seq = seq
                    self._durable_records = recs
                    self._unsynced = max(
                        0, self._written_records - recs)

    def append(self, payload: Dict[str, Any],
               seq: Optional[int] = None) -> None:
        """Append one record.  ``seq`` tags the record's LAST applied
        sequence number for the durability watermark (group records pass
        their trailing seq)."""
        with _telemetry.span(self._stage):
            rec_seq: Optional[int] = None
            if seq is not None:
                rec_seq = int(seq)
            elif "seq" in payload:
                rec_seq = int(payload["seq"])
            if self.fmt == "binary":
                # ONE serialization, crc32 instead of the sha256
                # envelope: the frame header carries the integrity
                # check and the trailing seq.
                body = json.dumps(payload,
                                  separators=(",", ":")).encode("utf-8")
                self._commit(_pack_binary_frame(body, rec_seq), None,
                             rec_seq)
            else:
                env = _integrity.make_envelope(
                    payload,
                    schema=(JOURNAL_GROUP_SCHEMA if "seqs" in payload
                            else JOURNAL_SCHEMA))
                line = json.dumps(env, separators=(",", ":")) + "\n"
                self._commit(None, line, rec_seq)

    def append_raw(self, body: bytes,
                   seq: Optional[int] = None) -> None:
        """Append one PRE-SERIALIZED record body — the exact compact-
        JSON bytes :meth:`append` would produce.  The replication
        path's single-serialization contract: the leader encodes a
        record once and the same bytes land in its own binary journal,
        on the wire, and in every replica — bit-identical replay by
        construction, no per-follower re-encode.  A JSONL journal
        still pays its envelope (the body is parsed back and routed
        through :meth:`append`); a binary journal frames the bytes
        directly."""
        rec_seq = None if seq is None else int(seq)
        if self.fmt != "binary":
            payload = (unpack_group_body(body)
                       if body.startswith(GROUP_BODY_MAGIC)
                       else json.loads(body.decode("utf-8")))
            self.append(payload, seq=rec_seq)
            return
        with _telemetry.span(self._stage):
            self._commit(_pack_binary_frame(body, rec_seq), None,
                         rec_seq)

    def _commit(self, frame: Optional[bytes], line: Optional[str],
                rec_seq: Optional[int]) -> None:
        """The locked tail shared by :meth:`append` / :meth:`append_raw`:
        land the encoded record, advance the watermark bookkeeping, and
        enforce the flush-mode bound."""
        with self._lock:
            if frame is not None:
                self._write_binary_locked(frame)
            else:
                self._f.write(line)
                self._f.flush()
                self._written_offset = self._f.tell()
            self._written_records += 1
            if rec_seq is not None:
                self._written_seq = rec_seq
            self._pending_seqs.append(rec_seq)
            self._unsynced += 1
            if self.flush_mode == "group":
                # The record bound: the ack below may precede the
                # fsync by at most max_unflushed_records records —
                # when the window is full the append BLOCKS on the
                # fsync (the hard bound; the background thread
                # normally keeps the window far from full).
                if (self._written_records - self._durable_records
                        >= self.max_unflushed_records):
                    with _telemetry.span("serving.journal.fsync"):
                        self._fsync_locked()
            elif self._unsynced >= self.fsync_every_n:
                with _telemetry.span("serving.journal.fsync"):
                    self._fsync_locked()

    def sync(self) -> None:
        """Force any group-commit tail to media now (a no-op at
        ``fsync_every_n=1`` in sync mode)."""
        with self._lock:
            if not self._f.closed \
                    and self._written_records > self._durable_records:
                self._f.flush()
                self._fsync_locked()

    def power_loss(self) -> Dict[str, Any]:
        """TEST RIG (the ``ingest:crash_in_window`` fault body): drop
        every byte past the durability watermark, exactly what a
        machine-level crash (power loss, kernel panic) does to acked
        records whose fsync had not yet landed.  A plain SIGKILL does
        NOT do this — flushed bytes survive the process in the page
        cache — so the loss window is simulated deterministically here.
        Returns what was dropped, for assertions.  The journal is dead
        afterwards (the caller exits)."""
        with self._lock:
            self._stop.set()
            if self._mm is not None:
                end = self._written_offset
                self._mm.close()
                self._mm = None
            else:
                self._f.flush()
                end = self._f.tell()
            self._f.close()
            # EXACT accounting under BOTH flush modes: the pending
            # window (one entry per acked-but-unforced record) is
            # trimmed precisely as the watermark advances, so count and
            # seqs here are record-exact — what the chaos soak asserts
            # against the recovery report.
            dropped = self._written_records - self._durable_records
            dropped_seqs = tuple(
                s for s in self._pending_seqs if s is not None)
            os.truncate(self.path, self._durable_offset)
            return {"path": self.path,
                    "durable_offset": self._durable_offset,
                    "durable_seq": self._durable_seq,
                    "dropped_bytes": end - self._durable_offset,
                    "dropped_records": dropped,
                    "dropped_seqs": dropped_seqs}

    def close(self) -> None:
        self._stop.set()
        if self._flusher is not None and self._flusher.is_alive():
            self._flusher.join(timeout=5.0)
        with self._lock:
            if not self._f.closed:
                if self._written_records > self._durable_records:
                    self._f.flush()
                    self._fsync_locked()
                if self._mm is not None:
                    # Exact-size the file (drop the preallocated zero
                    # tail) so cleanly-closed files and rotated
                    # segments carry no slack bytes.
                    self._mm.flush()
                    self._mm.close()
                    self._mm = None
                    os.ftruncate(self._f.fileno(), self._written_offset)
                    os.fsync(self._f.fileno())
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _quarantine_tail(path: str, offset: int, reason: str,
                     detail: str) -> Tuple[str, str]:
    """Move the bytes from ``offset`` to EOF into a ``.torn-<ts>``
    sidecar (never deleted — the bytes are evidence), write the
    structured report beside it, and truncate the journal back to the
    last verified record.  Returns ``(sidecar_path, report_path)``."""
    import datetime as _dt
    import time as _time

    ts = _dt.datetime.fromtimestamp(
        _time.time(), _dt.timezone.utc).strftime(  # rqlint: disable=RQ1201 sidecar naming only — quarantined bytes are evidence, never replayed; collision loop below absorbs clock ties
            "%Y%m%dT%H%M%SZ")
    sidecar = f"{path}.torn-{ts}"
    n = 0
    while os.path.exists(sidecar):
        n += 1
        sidecar = f"{path}.torn-{ts}-{n}"
    with open(path, "rb") as f:
        f.seek(offset)
        torn = f.read()
    with open(sidecar, "wb") as f:
        f.write(torn)
        f.flush()
        os.fsync(f.fileno())
    os.truncate(path, offset)
    report = f"{sidecar}.report.json"
    _integrity.write_json(report, {
        "journal": os.path.abspath(path),
        "quarantined_to": os.path.abspath(sidecar),
        "tail_offset": offset,
        "tail_bytes": len(torn),
        "reason": reason,
        "detail": detail,
    }, schema="rq.quarantine-report/1")
    return sidecar, report


def _replay_file(path: str, quarantine_torn_tail: bool,
                 tail_allowed: bool, record_base: int
                 ) -> Tuple[List[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """Verify one journal file, dispatching on the sniffed per-file
    format — a mid-migration tree (JSONL segments + binary live file,
    or the reverse) replays through one code path."""
    if journal_format(path) == "binary":
        return _replay_binary_file(path, quarantine_torn_tail,
                                   tail_allowed, record_base)
    return _replay_jsonl_file(path, quarantine_torn_tail,
                              tail_allowed, record_base)


def _replay_binary_file(path: str, quarantine_torn_tail: bool,
                        tail_allowed: bool, record_base: int
                        ) -> Tuple[List[Dict[str, Any]],
                                   Optional[Dict[str, Any]]]:
    """Binary-format counterpart of :func:`_replay_jsonl_file`: same
    trust rules (tail tear quarantined, mid-file corruption refused),
    enforced per slot-aligned frame instead of per line."""
    with open(path, "rb") as f:
        data = f.read()
    records, used, bad = _parse_binary(data)
    payloads: List[Dict[str, Any]] = []
    for i, (_off, body, _seq) in enumerate(records):
        try:
            if body.startswith(GROUP_BODY_MAGIC):
                payloads.append(unpack_group_body(body))
            else:
                payloads.append(json.loads(body.decode("utf-8")))
        except ValueError as e:
            raise JournalError(path, record_base + i,
                               f"undecodable payload (crc32 passed — "
                               f"writer bug or targeted corruption): "
                               f"{e}") from e
    torn_info: Optional[Dict[str, Any]] = None
    if bad is not None:
        off, detail = bad
        if _binary_frame_after(data, off):
            raise JournalError(
                path, record_base + len(payloads),
                f"{detail}, with valid records after it — a torn "
                f"append can only damage the final frame, so this is "
                f"mid-file corruption")
        if not tail_allowed:
            raise JournalError(path, record_base + len(payloads),
                               f"{detail} (rotated segments are "
                               f"complete by construction)")
        torn_info = {"reason": "torn tail record", "detail": detail,
                     "records_kept": record_base + len(payloads),
                     "sidecar": None, "report": None}
        if quarantine_torn_tail:
            sidecar, report = _quarantine_tail(
                path, used, "torn tail record", detail)
            torn_info["sidecar"] = sidecar
            torn_info["report"] = report
    return payloads, torn_info


def _replay_jsonl_file(path: str, quarantine_torn_tail: bool,
                       tail_allowed: bool, record_base: int
                       ) -> Tuple[List[Dict[str, Any]],
                                  Optional[Dict[str, Any]]]:
    """Verify one JSONL journal file.  ``tail_allowed`` is True only
    for the LIVE (unsuffixed) file: a rotated segment was complete and
    fsynced at rotation, so ANY failure there is real corruption, never
    a torn append.  ``record_base`` offsets the record index in
    errors."""
    payloads: List[Dict[str, Any]] = []
    bad: Optional[Tuple[int, str, str]] = None  # (offset, reason, detail)
    offset = 0
    with open(path, "rb") as f:
        lines = f.read().split(b"\n")
    # A well-formed journal ends with a newline, so the final split
    # element is empty; a NON-empty final element is unterminated bytes
    # — the only shape a crash-torn append can leave, and the ONLY
    # record the torn-tail quarantine may claim.  A newline-terminated
    # last record was written whole and fsynced — its batch was
    # ACKNOWLEDGED (the source stopped retransmitting), so a
    # verification failure there is real corruption of acked data and
    # must raise like any mid-file failure, never be silently dropped.
    for i, raw in enumerate(lines):
        at_tail = tail_allowed and i == len(lines) - 1
        if not raw:
            offset += len(raw) + 1
            continue
        try:
            obj = json.loads(raw.decode("utf-8"))
            where = f"{path} record {record_base + len(payloads)}"
            payload = _integrity.verify_envelope(obj, where=where)
            if obj.get("schema") not in (JOURNAL_SCHEMA,
                                         JOURNAL_GROUP_SCHEMA):
                raise _integrity.CorruptArtifactError(
                    where, f"schema mismatch (want {JOURNAL_SCHEMA!r} "
                           f"or {JOURNAL_GROUP_SCHEMA!r}, found "
                           f"{obj.get('schema')!r})")
        except (ValueError, _integrity.CorruptArtifactError) as e:
            if not at_tail:
                raise JournalError(path, record_base + len(payloads),
                                   str(e)) from e
            bad = (offset, "torn tail record", str(e))
            break
        payloads.append(payload)
        offset += len(raw) + 1
    torn_info: Optional[Dict[str, Any]] = None
    if bad is not None:
        off, reason, detail = bad
        torn_info = {"reason": reason, "detail": detail,
                     "records_kept": record_base + len(payloads),
                     "sidecar": None, "report": None}
        if quarantine_torn_tail:
            sidecar, report = _quarantine_tail(path, off, reason, detail)
            torn_info["sidecar"] = sidecar
            torn_info["report"] = report
    return payloads, torn_info


def segment_paths(path: str) -> List[str]:
    """Rotated segments of ``path`` (``<path>.<seq>``), oldest first."""
    import glob as _glob

    out = []
    for p in sorted(_glob.glob(path + ".*")):
        suffix = p[len(path) + 1:]
        if suffix.isdigit():
            out.append((int(suffix), p))
    return [p for _, p in sorted(out)]


def replay(path: str, quarantine_torn_tail: bool = True
           ) -> Tuple[List[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """Read + verify every retained record — rotated segments (oldest
    first), then the live file; returns ``(payloads, torn_info)``.

    ``torn_info`` is None for a clean journal, else a dict describing
    the quarantined tail (``{reason, sidecar, report, records_kept}``);
    only the LIVE file can have a torn tail (segments were complete at
    rotation — any failure there raises :class:`JournalError`).  A
    missing journal returns ``([], None)`` — absence is a fresh stream,
    not corruption.  Pass ``quarantine_torn_tail=False`` to only skip
    the tail (read-only inspection)."""
    payloads: List[Dict[str, Any]] = []
    for seg in segment_paths(path):
        recs, _ = _replay_file(seg, quarantine_torn_tail=False,
                               tail_allowed=False,
                               record_base=len(payloads))
        payloads.extend(recs)
    torn_info: Optional[Dict[str, Any]] = None
    if os.path.exists(path):
        recs, torn_info = _replay_file(
            path, quarantine_torn_tail=quarantine_torn_tail,
            tail_allowed=True, record_base=len(payloads))
        payloads.extend(recs)
    return payloads, torn_info


def rotate(path: str, seq: int) -> Optional[str]:
    """Close out the live journal as segment ``<path>.<seq>`` (records
    ≤ seq, complete by construction: rotation runs right after the
    snapshot at ``seq`` landed, and appends are serialized with it).
    Bounds the live file; :func:`prune_segments` bounds the segments.
    No-op (returns None) when the live file is missing or empty — for
    the binary format "empty" means header slot only (no record
    frames)."""
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        return None
    if journal_format(path) == "binary" \
            and os.path.getsize(path) <= BINARY_SLOT_BYTES:
        return None
    seg = f"{path}.{int(seq):012d}"
    os.replace(path, seg)
    return seg


def prune_segments(path: str, oldest_retained_seq: int) -> List[str]:
    """Delete segments fully covered by EVERY retained snapshot: a
    segment ``<path>.<k>`` holds records with seq ≤ k, so once the
    oldest retained snapshot is ≥ k no recovery path can need it.
    Returns the removed paths.  This is what keeps total journal size
    bounded (~retained-snapshot window), at the documented cost that
    ``journal_decisions`` returns the retained history, not all time."""
    removed = []
    for seg in segment_paths(path):
        k = int(seg[len(path) + 1:])
        if k <= int(oldest_retained_seq):
            os.remove(seg)
            removed.append(seg)
    return removed


def tear_tail(path: str, keep_bytes: Optional[int] = None) -> dict:
    """Deterministically tear the journal's LAST record mid-line — the
    crash-mid-append shape the ``ingest:torn_journal`` fault kind drives:
    the final line is truncated to half its length (or ``keep_bytes``),
    exactly as if the process died between the ``write`` and the
    ``fsync`` landing the full line (binary format: the final frame is
    cut mid-slot).  Returns what was done, for test assertions.  No
    randomness: same bytes in, same tear out."""
    with open(path, "rb") as f:
        data = f.read()
    if journal_format(path) == "binary":
        records, _used, _bad = _parse_binary(data)
        if not records:
            raise ValueError(f"cannot tear empty journal {path}")
        start, body, _seq = records[-1]
        full = _BINARY_RECORD_HDR.size + len(body)
        keep = full // 2 if keep_bytes is None else int(keep_bytes)
        os.truncate(path, start + keep)
        return {"path": path, "record_offset": start,
                "record_was": full, "record_now": keep}
    if not data.strip():
        raise ValueError(f"cannot tear empty journal {path}")
    body = data[:-1] if data.endswith(b"\n") else data
    start = body.rfind(b"\n") + 1  # 0 when the file holds one record
    last = body[start:]
    keep = len(last) // 2 if keep_bytes is None else int(keep_bytes)
    os.truncate(path, start + keep)
    return {"path": path, "record_offset": start,
            "record_was": len(last), "record_now": keep}


def migrate_to_binary(path: str) -> Dict[str, Any]:
    """ONE-WAY in-place migration of a JSONL journal tree (rotated
    segments, then the live file) to the binary fixed-slot format.
    Each file is fully verified first (no quarantine — a torn or
    corrupt file REFUSES migration; run recovery to quarantine the
    tail, then migrate), rewritten beside itself and atomically
    ``os.replace``d, with the directory fsynced at the end.  Payloads
    round-trip bit-identically: :func:`replay` of the migrated tree
    returns the same payload dicts in the same order as before.  There
    is deliberately no reverse migration — the binary frame does not
    carry the envelope sha256, so "migrating back" would mint
    envelopes the original writer never signed."""
    targets = segment_paths(path)
    if os.path.exists(path) and os.path.getsize(path) > 0:
        targets.append(path)
    migrated: List[str] = []
    total = 0
    for p in targets:
        if journal_format(p) == "binary":
            continue  # idempotent re-run / mixed tree
        recs, torn = _replay_file(p, quarantine_torn_tail=False,
                                  tail_allowed=(p == path),
                                  record_base=0)
        if torn is not None:
            raise ValueError(
                f"refusing to migrate {p}: torn tail present "
                f"({torn['detail']}) — recover first, then migrate")
        tmp = p + ".migrate"
        with open(tmp, "wb") as f:
            f.write(_binary_header_slot())
            for payload in recs:
                body = json.dumps(
                    payload, separators=(",", ":")).encode("utf-8")
                f.write(_pack_binary_frame(
                    body, _payload_trailing_seq(payload)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
        migrated.append(p)
        total += len(recs)
    dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                  os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    return {"path": path, "format": "binary", "migrated": migrated,
            "records": total}
