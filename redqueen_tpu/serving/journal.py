"""Crash-safe state journaling: checksummed append-only record log.

The journal is the serving runtime's write-ahead source of truth: every
applied micro-batch lands as ONE JSONL record — the batch's events, the
decision taken, and the post-apply carry digest — wrapped in the same
checksummed envelope format as every other artifact in the repo
(``runtime.integrity.make_envelope``; schema ``rq.serving.journal/1``).
Appends are flushed + fsynced before the apply is acknowledged, so a
SIGKILL at ANY instruction boundary leaves one of exactly two shapes:

- every acknowledged batch is a complete, verifiable record;
- plus at most one **torn tail** — a partial last line from an append the
  kill interrupted (that batch was never acknowledged).

Recovery (:func:`replay`) verifies records front-to-back.  A torn or
corrupt TAIL is quarantined — the bad bytes move to a
``<journal>.torn-<utc-ts>`` sidecar with a structured report beside it
(``runtime.integrity.quarantine`` semantics, scoped to the tail), the
journal truncates back to its last good record, and replay returns the
verified prefix: torn bytes are never trusted and never silently
deleted.  A bad record in the MIDDLE of the file is a different animal —
an fsynced record can only fail verification through real corruption
(bit rot, truncation by a non-atomic copier), and nothing after it can
be trusted to follow the right state — so that raises a typed
:class:`JournalError` instead of guessing.

Stdlib + numpy only; safe to import before jax.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..runtime import integrity as _integrity

__all__ = ["Journal", "JournalError", "replay", "tear_tail",
           "rotate", "prune_segments", "segment_paths",
           "JOURNAL_SCHEMA", "JOURNAL_FILENAME"]

JOURNAL_SCHEMA = "rq.serving.journal/1"

# The on-disk journal filename inside a runtime/shard directory — a
# cross-subsystem contract: the serving runtime writes it and external
# consumers (learn.ingest.from_journal) locate it by this name.
JOURNAL_FILENAME = "journal.jsonl"


class JournalError(RuntimeError):
    """A journal record BEFORE the tail failed verification: the file is
    corrupt in a way crash-tearing cannot produce (fsynced prefix), so
    replay refuses to trust anything past it.  Carries the path and the
    0-based record index that failed."""

    def __init__(self, path: str, record: int, reason: str):
        self.path = path
        self.record = record
        super().__init__(
            f"journal {path} record {record}: {reason} — a non-tail "
            f"record can only fail through real corruption; refusing to "
            f"replay past it (recover from the snapshot + a fresh "
            f"journal, or restore the file from backup)")


class Journal:
    """Append-only writer.  One instance owns the file handle; appends
    are atomic at the OS-write level (single ``write`` of one line) and
    durable (flush + fsync) before :meth:`append` returns — the "applied"
    acknowledgement the serving runtime gives its source is backed by
    this fsync.

    ``fsync_every_n`` is the GROUP-COMMIT option (default 1 = fsync per
    append, today's behavior): with n > 1 the fsync lands every n-th
    append (and at :meth:`sync`/:meth:`close`/rotation), trading the
    per-batch fsync — the measured per-shard isolation tax — for a
    BOUNDED durability loss window: a hard crash may lose up to the
    last n-1 acknowledged records (they were flushed to the OS, not
    forced to media).  Recovery semantics are unchanged: replay still
    verifies the surviving prefix record-by-record and quarantines a
    torn tail; the source's retransmit-past-``applied_seq`` contract
    re-covers the lost suffix exactly as it covers a crash between
    batches.  See docs/DESIGN.md "Out-of-process shard workers"."""

    def __init__(self, path: str, fsync_every_n: int = 1):
        if int(fsync_every_n) < 1:
            raise ValueError(
                f"fsync_every_n must be >= 1, got {fsync_every_n}")
        self.path = path
        self.fsync_every_n = int(fsync_every_n)
        self._unsynced = 0
        self._f = open(path, "a", encoding="utf-8")

    def append(self, payload: Dict[str, Any]) -> None:
        env = _integrity.make_envelope(payload, schema=JOURNAL_SCHEMA)
        line = json.dumps(env, separators=(",", ":")) + "\n"
        self._f.write(line)
        self._f.flush()
        self._unsynced += 1
        if self._unsynced >= self.fsync_every_n:
            os.fsync(self._f.fileno())
            self._unsynced = 0

    def sync(self) -> None:
        """Force any group-commit tail to media now (a no-op at
        ``fsync_every_n=1``)."""
        if not self._f.closed and self._unsynced:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._unsynced = 0

    def close(self) -> None:
        if not self._f.closed:
            self.sync()
            self._f.flush()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _quarantine_tail(path: str, offset: int, reason: str,
                     detail: str) -> Tuple[str, str]:
    """Move the bytes from ``offset`` to EOF into a ``.torn-<ts>``
    sidecar (never deleted — the bytes are evidence), write the
    structured report beside it, and truncate the journal back to the
    last verified record.  Returns ``(sidecar_path, report_path)``."""
    import datetime as _dt
    import time as _time

    ts = _dt.datetime.fromtimestamp(
        _time.time(), _dt.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    sidecar = f"{path}.torn-{ts}"
    n = 0
    while os.path.exists(sidecar):
        n += 1
        sidecar = f"{path}.torn-{ts}-{n}"
    with open(path, "rb") as f:
        f.seek(offset)
        torn = f.read()
    with open(sidecar, "wb") as f:
        f.write(torn)
        f.flush()
        os.fsync(f.fileno())
    os.truncate(path, offset)
    report = f"{sidecar}.report.json"
    _integrity.write_json(report, {
        "journal": os.path.abspath(path),
        "quarantined_to": os.path.abspath(sidecar),
        "tail_offset": offset,
        "tail_bytes": len(torn),
        "reason": reason,
        "detail": detail,
    }, schema="rq.quarantine-report/1")
    return sidecar, report


def _replay_file(path: str, quarantine_torn_tail: bool,
                 tail_allowed: bool, record_base: int
                 ) -> Tuple[List[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """Verify one journal file.  ``tail_allowed`` is True only for the
    LIVE (unsuffixed) file: a rotated segment was complete and fsynced
    at rotation, so ANY failure there is real corruption, never a torn
    append.  ``record_base`` offsets the record index in errors."""
    payloads: List[Dict[str, Any]] = []
    bad: Optional[Tuple[int, str, str]] = None  # (offset, reason, detail)
    offset = 0
    with open(path, "rb") as f:
        lines = f.read().split(b"\n")
    # A well-formed journal ends with a newline, so the final split
    # element is empty; a NON-empty final element is unterminated bytes
    # — the only shape a crash-torn append can leave, and the ONLY
    # record the torn-tail quarantine may claim.  A newline-terminated
    # last record was written whole and fsynced — its batch was
    # ACKNOWLEDGED (the source stopped retransmitting), so a
    # verification failure there is real corruption of acked data and
    # must raise like any mid-file failure, never be silently dropped.
    for i, raw in enumerate(lines):
        at_tail = tail_allowed and i == len(lines) - 1
        if not raw:
            offset += len(raw) + 1
            continue
        try:
            obj = json.loads(raw.decode("utf-8"))
            payload = _integrity.verify_envelope(
                obj, schema=JOURNAL_SCHEMA,
                where=f"{path} record {record_base + len(payloads)}")
        except (ValueError, _integrity.CorruptArtifactError) as e:
            if not at_tail:
                raise JournalError(path, record_base + len(payloads),
                                   str(e)) from e
            bad = (offset, "torn tail record", str(e))
            break
        payloads.append(payload)
        offset += len(raw) + 1
    torn_info: Optional[Dict[str, Any]] = None
    if bad is not None:
        off, reason, detail = bad
        torn_info = {"reason": reason, "detail": detail,
                     "records_kept": record_base + len(payloads),
                     "sidecar": None, "report": None}
        if quarantine_torn_tail:
            sidecar, report = _quarantine_tail(path, off, reason, detail)
            torn_info["sidecar"] = sidecar
            torn_info["report"] = report
    return payloads, torn_info


def segment_paths(path: str) -> List[str]:
    """Rotated segments of ``path`` (``<path>.<seq>``), oldest first."""
    import glob as _glob

    out = []
    for p in _glob.glob(path + ".*"):
        suffix = p[len(path) + 1:]
        if suffix.isdigit():
            out.append((int(suffix), p))
    return [p for _, p in sorted(out)]


def replay(path: str, quarantine_torn_tail: bool = True
           ) -> Tuple[List[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """Read + verify every retained record — rotated segments (oldest
    first), then the live file; returns ``(payloads, torn_info)``.

    ``torn_info`` is None for a clean journal, else a dict describing
    the quarantined tail (``{reason, sidecar, report, records_kept}``);
    only the LIVE file can have a torn tail (segments were complete at
    rotation — any failure there raises :class:`JournalError`).  A
    missing journal returns ``([], None)`` — absence is a fresh stream,
    not corruption.  Pass ``quarantine_torn_tail=False`` to only skip
    the tail (read-only inspection)."""
    payloads: List[Dict[str, Any]] = []
    for seg in segment_paths(path):
        recs, _ = _replay_file(seg, quarantine_torn_tail=False,
                               tail_allowed=False,
                               record_base=len(payloads))
        payloads.extend(recs)
    torn_info: Optional[Dict[str, Any]] = None
    if os.path.exists(path):
        recs, torn_info = _replay_file(
            path, quarantine_torn_tail=quarantine_torn_tail,
            tail_allowed=True, record_base=len(payloads))
        payloads.extend(recs)
    return payloads, torn_info


def rotate(path: str, seq: int) -> Optional[str]:
    """Close out the live journal as segment ``<path>.<seq>`` (records
    ≤ seq, complete by construction: rotation runs right after the
    snapshot at ``seq`` landed, and appends are serialized with it).
    Bounds the live file; :func:`prune_segments` bounds the segments.
    No-op (returns None) when the live file is missing or empty."""
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        return None
    seg = f"{path}.{int(seq):012d}"
    os.replace(path, seg)
    return seg


def prune_segments(path: str, oldest_retained_seq: int) -> List[str]:
    """Delete segments fully covered by EVERY retained snapshot: a
    segment ``<path>.<k>`` holds records with seq ≤ k, so once the
    oldest retained snapshot is ≥ k no recovery path can need it.
    Returns the removed paths.  This is what keeps total journal size
    bounded (~retained-snapshot window), at the documented cost that
    ``journal_decisions`` returns the retained history, not all time."""
    removed = []
    for seg in segment_paths(path):
        k = int(seg[len(path) + 1:])
        if k <= int(oldest_retained_seq):
            os.remove(seg)
            removed.append(seg)
    return removed


def tear_tail(path: str, keep_bytes: Optional[int] = None) -> dict:
    """Deterministically tear the journal's LAST record mid-line — the
    crash-mid-append shape the ``ingest:torn_journal`` fault kind drives:
    the final line is truncated to half its length (or ``keep_bytes``),
    exactly as if the process died between the ``write`` and the
    ``fsync`` landing the full line.  Returns what was done, for test
    assertions.  No randomness: same bytes in, same tear out."""
    with open(path, "rb") as f:
        data = f.read()
    if not data.strip():
        raise ValueError(f"cannot tear empty journal {path}")
    body = data[:-1] if data.endswith(b"\n") else data
    start = body.rfind(b"\n") + 1  # 0 when the file holds one record
    last = body[start:]
    keep = len(last) // 2 if keep_bytes is None else int(keep_bytes)
    os.truncate(path, start + keep)
    return {"path": path, "record_offset": start,
            "record_was": len(last), "record_now": keep}
