"""Crash-safe state journaling: checksummed append-only record log.

The journal is the serving runtime's write-ahead source of truth: every
applied micro-batch (schema ``rq.serving.journal/1``) or coalesced GROUP
of micro-batches (schema ``rq.serving.journal/2`` — the wire-speed
ingest path journals one record per poll round) lands as ONE JSONL
record — the events, the decision(s) taken, and the post-apply carry
digest — wrapped in the same checksummed envelope format as every other
artifact in the repo (``runtime.integrity.make_envelope``).
Under the default durability mode appends are flushed + fsynced before
the apply is acknowledged, so a SIGKILL at ANY instruction boundary
leaves one of exactly two shapes:

- every acknowledged batch is a complete, verifiable record;
- plus at most one **torn tail** — a partial last line from an append the
  kill interrupted (that batch was never acknowledged).

Recovery (:func:`replay`) verifies records front-to-back.  A torn or
corrupt TAIL is quarantined — the bad bytes move to a
``<journal>.torn-<utc-ts>`` sidecar with a structured report beside it
(``runtime.integrity.quarantine`` semantics, scoped to the tail), the
journal truncates back to its last good record, and replay returns the
verified prefix: torn bytes are never trusted and never silently
deleted.  A bad record in the MIDDLE of the file is a different animal —
an fsynced record can only fail verification through real corruption
(bit rot, truncation by a non-atomic copier), and nothing after it can
be trusted to follow the right state — so that raises a typed
:class:`JournalError` instead of guessing.

Stdlib + numpy only; safe to import before jax.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..runtime import integrity as _integrity
from ..runtime import telemetry as _telemetry

__all__ = ["Journal", "JournalError", "replay", "tear_tail",
           "rotate", "prune_segments", "segment_paths",
           "durability_info",
           "JOURNAL_SCHEMA", "JOURNAL_GROUP_SCHEMA", "JOURNAL_FILENAME",
           "FLUSH_MODES"]

JOURNAL_SCHEMA = "rq.serving.journal/1"
# One coalesced poll ROUND per record: {"seqs", "counts", flat "times"/
# "feeds", "decisions", "state_digest"} — times/feeds stay flat so
# flat-array consumers (learn.ingest.from_journal) read both schemas
# through one code path.
JOURNAL_GROUP_SCHEMA = "rq.serving.journal/2"

# Durability modes (the ack contract; see docs/DESIGN.md "Durability
# modes & the ack contract"):
#
# - "sync"  — append() returns only after the record is flushed, and
#   fsynced every ``fsync_every_n``-th append (n=1: every append — the
#   PR 6 contract: the ack IS the fsync).
# - "group" — ASYNC GROUP COMMIT: append() returns after the OS-level
#   flush; a background thread forces the fsync within
#   ``max_flush_delay_ms``, and append() forces it inline the moment
#   ``max_unflushed_records`` acked records are in flight.  The ack
#   races the fsync inside an EXPLICIT, bounded durability window: a
#   power-style crash loses at most ``max_unflushed_records`` acked
#   records (or ``max_flush_delay_ms`` of acks, whichever bound fires
#   first); recovery reports exactly which acked seqs were lost
#   (``RecoveryInfo.lost_acked_seqs``) and the source's retransmit
#   heals them.  A plain process SIGKILL loses nothing: the flushed
#   bytes survive in the page cache.
FLUSH_MODES = ("sync", "group")

# The on-disk journal filename inside a runtime/shard directory — a
# cross-subsystem contract: the serving runtime writes it and external
# consumers (learn.ingest.from_journal) locate it by this name.
JOURNAL_FILENAME = "journal.jsonl"


def durability_info(flush_mode: str, fsync_every_n: int,
                    max_unflushed_records: int,
                    max_flush_delay_ms: float,
                    coalesce: int) -> Dict[str, Any]:
    """THE durability-window description (one definition — the runtime
    and the cluster both embed it in their metrics artifacts, and the
    two must never drift): what an ack MEANS under this configuration,
    and the bounded loss a machine-level crash may consume.  See
    docs/DESIGN.md "Durability modes & the ack contract"."""
    if flush_mode == "group":
        window_records = int(max_unflushed_records) - 1
    else:
        window_records = int(fsync_every_n) - 1
    return {
        "flush_mode": str(flush_mode),
        "fsync_every_n": int(fsync_every_n),
        "max_unflushed_records": int(max_unflushed_records),
        "max_flush_delay_ms": float(max_flush_delay_ms),
        "coalesce": int(coalesce),
        # True iff an ack implies the record is on media (the PR 6
        # contract); False means the ack races the fsync inside the
        # bounded window below.
        "ack_is_durable": window_records == 0,
        # A machine-level crash loses at most this many acked journal
        # RECORDS; one record covers up to ``coalesce`` batches, so the
        # batch bound is the product.
        "loss_window_records": window_records,
        "loss_window_batches": window_records * int(coalesce),
    }


class JournalError(RuntimeError):
    """A journal record BEFORE the tail failed verification: the file is
    corrupt in a way crash-tearing cannot produce (fsynced prefix), so
    replay refuses to trust anything past it.  Carries the path and the
    0-based record index that failed."""

    def __init__(self, path: str, record: int, reason: str):
        self.path = path
        self.record = record
        super().__init__(
            f"journal {path} record {record}: {reason} — a non-tail "
            f"record can only fail through real corruption; refusing to "
            f"replay past it (recover from the snapshot + a fresh "
            f"journal, or restore the file from backup)")


class Journal:
    """Append-only writer.  One instance owns the file handle; appends
    are atomic at the OS-write level (single ``write`` of one line).

    ``flush_mode="sync"`` (default): appends are durable (flush + fsync)
    before :meth:`append` returns — the "applied" acknowledgement the
    serving runtime gives its source is backed by this fsync.
    ``fsync_every_n`` is the SYNCHRONOUS group-commit option (default 1
    = fsync per append): with n > 1 the fsync lands every n-th append
    (and at :meth:`sync`/:meth:`close`/rotation), trading the per-batch
    fsync for a bounded loss window of n-1 acked records.

    ``flush_mode="group"`` is ASYNC group commit — the wire-speed mode:
    :meth:`append` returns after the OS-level flush, a daemon thread
    forces the fsync within ``max_flush_delay_ms``, and the window is
    hard-bounded because append() fsyncs INLINE once
    ``max_unflushed_records`` acked records are un-forced.  The
    durability watermark (:attr:`durable_seq` / ``durable_offset``) is
    what a power-style crash provably keeps; everything acked past it is
    the documented loss window, healed by retransmit (see the module
    docstring and docs/DESIGN.md "Durability modes & the ack
    contract")."""

    def __init__(self, path: str, fsync_every_n: int = 1,
                 flush_mode: str = "sync",
                 max_unflushed_records: int = 64,
                 max_flush_delay_ms: float = 50.0):
        if int(fsync_every_n) < 1:
            raise ValueError(
                f"fsync_every_n must be >= 1, got {fsync_every_n}")
        if flush_mode not in FLUSH_MODES:
            raise ValueError(f"flush_mode must be one of {FLUSH_MODES}, "
                             f"got {flush_mode!r}")
        if int(max_unflushed_records) < 1:
            raise ValueError(f"max_unflushed_records must be >= 1, got "
                             f"{max_unflushed_records}")
        if float(max_flush_delay_ms) <= 0:
            raise ValueError(f"max_flush_delay_ms must be > 0, got "
                             f"{max_flush_delay_ms}")
        self.path = path
        self.fsync_every_n = int(fsync_every_n)
        self.flush_mode = flush_mode
        self.max_unflushed_records = int(max_unflushed_records)
        self.max_flush_delay_ms = float(max_flush_delay_ms)
        self._unsynced = 0
        self._f = open(path, "a", encoding="utf-8")
        # Durability watermark.  Pre-existing bytes were fsynced by the
        # writer that produced them (close/rotation/recovery all sync),
        # so the baseline is the current EOF; ``durable_seq`` is None
        # until this instance forces its first fsync (records before
        # this instance are outside its ack window by construction).
        self._lock = threading.Lock()
        self._written_offset = self._f.tell()
        self._written_seq: Optional[int] = None
        self._written_records = 0
        self._durable_offset = self._written_offset
        self._durable_seq: Optional[int] = None
        self._durable_records = 0
        self._stop = threading.Event()
        self._flush_errors = 0
        self._flusher: Optional[threading.Thread] = None
        if self.flush_mode == "group":
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True,
                name=f"journal-flush:{os.path.basename(path)}")
            self._flusher.start()

    # -- durability watermark (what a power-style crash provably keeps) --

    @property
    def durable_offset(self) -> int:
        with self._lock:
            return self._durable_offset

    @property
    def durable_seq(self) -> Optional[int]:
        """Highest appended seq known forced to media by THIS instance
        (None before its first fsync — earlier records belong to a
        previous, cleanly-synced instance)."""
        with self._lock:
            return self._durable_seq

    @property
    def flush_errors(self) -> int:
        """Background-flush fsync failures survived so far (each one
        delayed the time bound by one tick; persistent failure ends in
        the inline fsync raising)."""
        with self._lock:
            return self._flush_errors

    @property
    def unsynced(self) -> int:
        """Acked-but-not-yet-forced records — the live durability
        window (always <= ``max_unflushed_records`` in group mode)."""
        with self._lock:
            return self._written_records - self._durable_records

    def _fsync_locked(self) -> None:
        """fsync + advance the watermark.  Caller holds ``_lock`` —
        the INLINE path only (window bound, sync mode, close): blocking
        the ack here is the contract, not a stall."""
        os.fsync(self._f.fileno())
        self._durable_offset = self._written_offset
        self._durable_seq = self._written_seq
        self._durable_records = self._written_records
        self._unsynced = 0

    def _flush_loop(self) -> None:
        """The background group-commit flusher: every
        ``max_flush_delay_ms`` it forces any acked-but-unfsynced tail to
        media — the TIME bound of the durability window (the RECORD
        bound is enforced inline by :meth:`append`).  The fsync runs
        OUTSIDE the journal lock: on this class of filesystem an fsync
        costs tens of milliseconds, and holding the lock across it
        would stall every concurrent append — reintroducing exactly the
        ack-blocks-on-media tax async group commit exists to remove.
        The watermark is captured before the fsync and advanced after,
        so it is always conservative (never claims more durable than
        the fsync actually covered)."""
        delay = self.max_flush_delay_ms / 1e3
        while not self._stop.wait(delay):
            with self._lock:
                if self._f.closed \
                        or self._written_records == self._durable_records:
                    continue
                off = self._written_offset
                seq = self._written_seq
                recs = self._written_records
                fd = self._f.fileno()
            try:
                os.fsync(fd)
                # Counter, not a span: this thread has no trace context
                # (a span here would start orphan root traces per tick).
                _telemetry.counter("serving.journal.bg_fsync")
            except ValueError:
                return  # fd closed under us: clean shutdown race
            except OSError:
                # A transient fsync failure must not PERMANENTLY void
                # the advertised time bound: count it (visible via
                # ``flush_errors``) and retry next tick — the volume
                # may heal.  A persistent failure still fails loudly:
                # the window fills, append()'s INLINE fsync raises, and
                # the runtime's fatal-append contract takes the
                # process down.
                with self._lock:
                    self._flush_errors += 1
                continue
            with self._lock:
                if off > self._durable_offset:
                    self._durable_offset = off
                    self._durable_seq = seq
                    self._durable_records = recs
                    self._unsynced = max(
                        0, self._written_records - recs)

    def append(self, payload: Dict[str, Any],
               seq: Optional[int] = None) -> None:
        """Append one record.  ``seq`` tags the record's LAST applied
        sequence number for the durability watermark (group records pass
        their trailing seq)."""
        with _telemetry.span("serving.journal.append"):
            env = _integrity.make_envelope(
                payload, schema=(JOURNAL_GROUP_SCHEMA if "seqs" in payload
                                 else JOURNAL_SCHEMA))
            line = json.dumps(env, separators=(",", ":")) + "\n"
            with self._lock:
                self._f.write(line)
                self._f.flush()
                self._written_offset = self._f.tell()
                self._written_records += 1
                if seq is not None:
                    self._written_seq = int(seq)
                elif "seq" in payload:
                    self._written_seq = int(payload["seq"])
                self._unsynced += 1
                if self.flush_mode == "group":
                    # The record bound: the ack below may precede the
                    # fsync by at most max_unflushed_records records —
                    # when the window is full the append BLOCKS on the
                    # fsync (the hard bound; the background thread
                    # normally keeps the window far from full).
                    if (self._written_records - self._durable_records
                            >= self.max_unflushed_records):
                        with _telemetry.span("serving.journal.fsync"):
                            self._fsync_locked()
                elif self._unsynced >= self.fsync_every_n:
                    with _telemetry.span("serving.journal.fsync"):
                        self._fsync_locked()

    def sync(self) -> None:
        """Force any group-commit tail to media now (a no-op at
        ``fsync_every_n=1`` in sync mode)."""
        with self._lock:
            if not self._f.closed \
                    and self._written_records > self._durable_records:
                self._f.flush()
                self._fsync_locked()

    def power_loss(self) -> Dict[str, Any]:
        """TEST RIG (the ``ingest:crash_in_window`` fault body): drop
        every byte past the durability watermark, exactly what a
        machine-level crash (power loss, kernel panic) does to acked
        records whose fsync had not yet landed.  A plain SIGKILL does
        NOT do this — flushed bytes survive the process in the page
        cache — so the loss window is simulated deterministically here.
        Returns what was dropped, for assertions.  The journal is dead
        afterwards (the caller exits)."""
        with self._lock:
            self._stop.set()
            self._f.flush()
            end = self._f.tell()
            os.truncate(self.path, self._durable_offset)
            return {"path": self.path,
                    "durable_offset": self._durable_offset,
                    "durable_seq": self._durable_seq,
                    "dropped_bytes": end - self._durable_offset,
                    "dropped_records": self._unsynced}

    def close(self) -> None:
        self._stop.set()
        if self._flusher is not None and self._flusher.is_alive():
            self._flusher.join(timeout=5.0)
        with self._lock:
            if not self._f.closed:
                if self._written_records > self._durable_records:
                    self._f.flush()
                    self._fsync_locked()
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _quarantine_tail(path: str, offset: int, reason: str,
                     detail: str) -> Tuple[str, str]:
    """Move the bytes from ``offset`` to EOF into a ``.torn-<ts>``
    sidecar (never deleted — the bytes are evidence), write the
    structured report beside it, and truncate the journal back to the
    last verified record.  Returns ``(sidecar_path, report_path)``."""
    import datetime as _dt
    import time as _time

    ts = _dt.datetime.fromtimestamp(
        _time.time(), _dt.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    sidecar = f"{path}.torn-{ts}"
    n = 0
    while os.path.exists(sidecar):
        n += 1
        sidecar = f"{path}.torn-{ts}-{n}"
    with open(path, "rb") as f:
        f.seek(offset)
        torn = f.read()
    with open(sidecar, "wb") as f:
        f.write(torn)
        f.flush()
        os.fsync(f.fileno())
    os.truncate(path, offset)
    report = f"{sidecar}.report.json"
    _integrity.write_json(report, {
        "journal": os.path.abspath(path),
        "quarantined_to": os.path.abspath(sidecar),
        "tail_offset": offset,
        "tail_bytes": len(torn),
        "reason": reason,
        "detail": detail,
    }, schema="rq.quarantine-report/1")
    return sidecar, report


def _replay_file(path: str, quarantine_torn_tail: bool,
                 tail_allowed: bool, record_base: int
                 ) -> Tuple[List[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """Verify one journal file.  ``tail_allowed`` is True only for the
    LIVE (unsuffixed) file: a rotated segment was complete and fsynced
    at rotation, so ANY failure there is real corruption, never a torn
    append.  ``record_base`` offsets the record index in errors."""
    payloads: List[Dict[str, Any]] = []
    bad: Optional[Tuple[int, str, str]] = None  # (offset, reason, detail)
    offset = 0
    with open(path, "rb") as f:
        lines = f.read().split(b"\n")
    # A well-formed journal ends with a newline, so the final split
    # element is empty; a NON-empty final element is unterminated bytes
    # — the only shape a crash-torn append can leave, and the ONLY
    # record the torn-tail quarantine may claim.  A newline-terminated
    # last record was written whole and fsynced — its batch was
    # ACKNOWLEDGED (the source stopped retransmitting), so a
    # verification failure there is real corruption of acked data and
    # must raise like any mid-file failure, never be silently dropped.
    for i, raw in enumerate(lines):
        at_tail = tail_allowed and i == len(lines) - 1
        if not raw:
            offset += len(raw) + 1
            continue
        try:
            obj = json.loads(raw.decode("utf-8"))
            where = f"{path} record {record_base + len(payloads)}"
            payload = _integrity.verify_envelope(obj, where=where)
            if obj.get("schema") not in (JOURNAL_SCHEMA,
                                         JOURNAL_GROUP_SCHEMA):
                raise _integrity.CorruptArtifactError(
                    where, f"schema mismatch (want {JOURNAL_SCHEMA!r} "
                           f"or {JOURNAL_GROUP_SCHEMA!r}, found "
                           f"{obj.get('schema')!r})")
        except (ValueError, _integrity.CorruptArtifactError) as e:
            if not at_tail:
                raise JournalError(path, record_base + len(payloads),
                                   str(e)) from e
            bad = (offset, "torn tail record", str(e))
            break
        payloads.append(payload)
        offset += len(raw) + 1
    torn_info: Optional[Dict[str, Any]] = None
    if bad is not None:
        off, reason, detail = bad
        torn_info = {"reason": reason, "detail": detail,
                     "records_kept": record_base + len(payloads),
                     "sidecar": None, "report": None}
        if quarantine_torn_tail:
            sidecar, report = _quarantine_tail(path, off, reason, detail)
            torn_info["sidecar"] = sidecar
            torn_info["report"] = report
    return payloads, torn_info


def segment_paths(path: str) -> List[str]:
    """Rotated segments of ``path`` (``<path>.<seq>``), oldest first."""
    import glob as _glob

    out = []
    for p in _glob.glob(path + ".*"):
        suffix = p[len(path) + 1:]
        if suffix.isdigit():
            out.append((int(suffix), p))
    return [p for _, p in sorted(out)]


def replay(path: str, quarantine_torn_tail: bool = True
           ) -> Tuple[List[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """Read + verify every retained record — rotated segments (oldest
    first), then the live file; returns ``(payloads, torn_info)``.

    ``torn_info`` is None for a clean journal, else a dict describing
    the quarantined tail (``{reason, sidecar, report, records_kept}``);
    only the LIVE file can have a torn tail (segments were complete at
    rotation — any failure there raises :class:`JournalError`).  A
    missing journal returns ``([], None)`` — absence is a fresh stream,
    not corruption.  Pass ``quarantine_torn_tail=False`` to only skip
    the tail (read-only inspection)."""
    payloads: List[Dict[str, Any]] = []
    for seg in segment_paths(path):
        recs, _ = _replay_file(seg, quarantine_torn_tail=False,
                               tail_allowed=False,
                               record_base=len(payloads))
        payloads.extend(recs)
    torn_info: Optional[Dict[str, Any]] = None
    if os.path.exists(path):
        recs, torn_info = _replay_file(
            path, quarantine_torn_tail=quarantine_torn_tail,
            tail_allowed=True, record_base=len(payloads))
        payloads.extend(recs)
    return payloads, torn_info


def rotate(path: str, seq: int) -> Optional[str]:
    """Close out the live journal as segment ``<path>.<seq>`` (records
    ≤ seq, complete by construction: rotation runs right after the
    snapshot at ``seq`` landed, and appends are serialized with it).
    Bounds the live file; :func:`prune_segments` bounds the segments.
    No-op (returns None) when the live file is missing or empty."""
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        return None
    seg = f"{path}.{int(seq):012d}"
    os.replace(path, seg)
    return seg


def prune_segments(path: str, oldest_retained_seq: int) -> List[str]:
    """Delete segments fully covered by EVERY retained snapshot: a
    segment ``<path>.<k>`` holds records with seq ≤ k, so once the
    oldest retained snapshot is ≥ k no recovery path can need it.
    Returns the removed paths.  This is what keeps total journal size
    bounded (~retained-snapshot window), at the documented cost that
    ``journal_decisions`` returns the retained history, not all time."""
    removed = []
    for seg in segment_paths(path):
        k = int(seg[len(path) + 1:])
        if k <= int(oldest_retained_seq):
            os.remove(seg)
            removed.append(seg)
    return removed


def tear_tail(path: str, keep_bytes: Optional[int] = None) -> dict:
    """Deterministically tear the journal's LAST record mid-line — the
    crash-mid-append shape the ``ingest:torn_journal`` fault kind drives:
    the final line is truncated to half its length (or ``keep_bytes``),
    exactly as if the process died between the ``write`` and the
    ``fsync`` landing the full line.  Returns what was done, for test
    assertions.  No randomness: same bytes in, same tear out."""
    with open(path, "rb") as f:
        data = f.read()
    if not data.strip():
        raise ValueError(f"cannot tear empty journal {path}")
    body = data[:-1] if data.endswith(b"\n") else data
    start = body.rfind(b"\n") + 1  # 0 when the file holds one record
    last = body[start:]
    keep = len(last) // 2 if keep_bytes is None else int(keep_bytes)
    os.truncate(path, start + keep)
    return {"path": path, "record_offset": start,
            "record_was": len(last), "record_now": keep}
