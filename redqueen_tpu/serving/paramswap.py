"""Guarded live parameter hot-swap: the only road into the policy.

The serving runtime's control law u*(t) = sqrt(s/q)*r(t) is only as
good as the intensity parameters feeding it, and a live parameter swap
is the one mutation the serving stack has no other defense for: a bad
fit installed uncritically is a silent correctness outage.  This module
is the defense — docs/DESIGN.md "Fit-while-serving & guarded hot-swap":

- **Gate policy** (:class:`ParamGate`): a candidate fit must be
  structurally sound (finite, non-negative, shapes consistent),
  SUBCRITICAL (spectral radius of the branching matrix alpha/beta < 1
  — the same domain contract ``config.add_hawkes`` warns on), and must
  not regress a held-back-window NLL canary past a relative bound.
  Only the gate mints :class:`ValidatedParams`; rqlint RQ1006 makes a
  raw assignment to the live policy params a tier-1 finding, so the
  type system and the linter close the same door.
- **Epoch protocol**: ``ServingRuntime.install_params`` performs a
  two-slot epoch swap — the new arrays are installed under an
  incremented epoch and the previous slot is retained; in-flight
  jitted applies captured the old arrays as arguments, so they finish
  on the old epoch with no lock on the decision path.  Every install
  is journaled (epoch, params, fit fingerprint, params digest) and
  mirrored into a ``params_log.json`` sidecar so recovery replays
  every batch under the epoch that actually decided it, even after
  pre-install segments are pruned.
- **Rollback**: a post-install canary regression (or the forced
  ``swap:rollback`` fault) re-installs the previous last-good params
  as a NEW epoch through the same gate/install path — rollback is an
  install, never a mutation.
- **Staleness contract** (:meth:`ParamSwapper.status`): a learner dead
  past ``stale_after_s`` degrades to a surfaced ``stale_params`` state
  — serving keeps last-good and keeps answering; staleness is a
  reported condition, never an error on the decision path.

Failure drill (``runtime.faultinject``): ``swap:corrupt`` scribbles
the candidate artifact before the gate reads it (integrity quarantine,
keep last-good), ``swap:reject`` forces a gate veto on a good
candidate (counted rejection), ``swap:rollback`` forces a post-install
canary regression (rollback path).  All deterministic, CPU-only.

jax-free on purpose: the gate, the swapper, and the artifact I/O run
in jax-free contexts (chaos soak, worker children); the NLL canary is
a caller-supplied callable so the jax-backed loglik scan stays in
:mod:`redqueen_tpu.learn.streaming`.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Callable, Dict, NamedTuple, Optional

import numpy as np

from ..runtime import faultinject as _faultinject
from ..runtime import integrity as _integrity
from ..runtime import telemetry as _telemetry

__all__ = [
    "CANDIDATE_SCHEMA",
    "CANDIDATE_FILENAME",
    "PARAMS_LOG_SCHEMA",
    "PARAMS_LOG_FILENAME",
    "ValidatedParams",
    "GateResult",
    "ParamGate",
    "ParamSwapper",
    "write_candidate",
    "read_candidate",
    "params_digest",
    "spectral_radius",
]

# The learner's hand-off artifact: an enveloped JSON candidate fit.
# Enveloped (sha256) so a torn/scribbled hand-off is DETECTED at the
# gate, quarantined, and serving keeps last-good — never a crash.
CANDIDATE_SCHEMA = "rq.learn.candidate/1"
CANDIDATE_FILENAME = "candidate_fit.json"

# Sidecar install log beside the journal: the full install history
# (epoch, seq, params, fingerprint).  Recovery needs it when the
# journal segments holding old epoch records have been pruned: prune
# only drops segments covered by the OLDEST retained snapshot, so the
# newest sidecar entry with seq <= the restored snapshot's seq is
# always the params that were live at that snapshot.
PARAMS_LOG_SCHEMA = "rq.serving.params_log/1"
PARAMS_LOG_FILENAME = "params_log.json"

# Gate defaults: a candidate may not regress the held-back-window NLL
# by more than this relative bound, and the branching matrix's
# spectral radius must stay strictly below the cap (subcritical — a
# supercritical fit predicts infinite stationary intensity and the
# control law's sqrt(s/q) scaling is meaningless).
DEFAULT_NLL_BOUND = 0.05
DEFAULT_BRANCHING_CAP = 1.0


def params_digest(s_sink: np.ndarray, q: float) -> str:
    """16-hex digest of exactly the arrays that go live.  Asserted by
    ``install_params`` immediately before the flip (the gate computed
    it from the arrays it validated; a mismatch means the token was
    tampered with between gate and install) and journaled with the
    epoch so recovery can re-assert bit-identity."""
    h = hashlib.sha256(b"rq.params/1")
    a = np.ascontiguousarray(np.asarray(s_sink, np.float64))
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    h.update(np.float64(q).tobytes())
    return h.hexdigest()[:16]


def spectral_radius(branching: np.ndarray) -> float:
    """max |eigenvalue| of the branching matrix B = alpha / beta (the
    expected direct-offspring counts); rho(B) < 1 iff the fitted
    process is stationary."""
    b = np.asarray(branching, np.float64)
    if b.ndim == 1:
        b = np.diag(b)
    return float(np.max(np.abs(np.linalg.eigvals(b))))


class ValidatedParams(NamedTuple):
    """The install token: parameters that passed the gate.  Minted ONLY
    by :class:`ParamGate` — ``ServingRuntime.install_params`` refuses
    anything else, and rqlint RQ1006 flags raw assignments that would
    bypass both."""

    s_sink: np.ndarray    # f64[F] significance vector, normalized
    q: float              # cost price (operator-set; fits may echo it)
    fingerprint: str      # the FIT fingerprint (learner ckpt identity)
    digest: str           # params_digest(s_sink, q) — asserted at install
    step: int             # learner update step that produced the fit
    meta: Dict[str, Any]  # gate measurements (nll, rho, ...) for the log


class GateResult(NamedTuple):
    ok: bool
    reason: str                        # "" when ok
    params: Optional[ValidatedParams]  # None when rejected
    measurements: Dict[str, Any]       # rho, nll_candidate, nll_baseline


def write_candidate(path: str, *, mu, alpha, beta, s_sink,
                    fingerprint: str, step: int, q: Optional[float] = None,
                    meta: Optional[Dict[str, Any]] = None) -> None:
    """Atomically land the learner's candidate fit as an enveloped
    artifact.  ``alpha`` is the FULL branching-numerator matrix (the
    off-diagonal mass is what the gate's subcriticality check needs;
    ``s_sink`` is already the stationary-intensity reduction of it)."""
    payload = {
        "mu": np.asarray(mu, np.float64).tolist(),
        "alpha": np.asarray(alpha, np.float64).tolist(),
        "beta": np.asarray(beta, np.float64).tolist(),
        "s_sink": np.asarray(s_sink, np.float64).tolist(),
        "q": None if q is None else float(q),
        "fingerprint": str(fingerprint),
        "step": int(step),
        "meta": dict(meta or {}),
    }
    _integrity.write_json(path, payload, schema=CANDIDATE_SCHEMA)


def read_candidate(path: str) -> Dict[str, Any]:
    """Read + verify a candidate artifact; raises
    :class:`runtime.integrity.CorruptArtifactError` (after moving the
    file aside) on any integrity failure — the quarantine path the
    ``swap:corrupt`` fault exercises."""
    return _integrity.read_json(path, schema=CANDIDATE_SCHEMA)


class ParamGate:
    """The validation gate.  Stateless apart from its bounds; every
    :meth:`validate` call is a fresh verdict."""

    def __init__(self, nll_bound: float = DEFAULT_NLL_BOUND,
                 branching_cap: float = DEFAULT_BRANCHING_CAP):
        if not (nll_bound >= 0.0):
            raise ValueError(f"nll_bound must be >= 0, got {nll_bound}")
        if not (0.0 < branching_cap <= 1.0):
            raise ValueError(
                f"branching_cap must be in (0, 1], got {branching_cap}")
        self.nll_bound = float(nll_bound)
        self.branching_cap = float(branching_cap)

    def validate(self, candidate: Dict[str, Any],
                 current_q: float,
                 canary: Optional[Callable[..., float]] = None,
                 baseline_nll: Optional[float] = None) -> GateResult:
        """Judge one candidate fit.

        ``canary(mu, alpha, beta) -> float`` computes the candidate's
        NLL on a held-back window; ``baseline_nll`` is last-good's NLL
        on the SAME window.  Either absent -> the canary check is
        skipped (structural + subcriticality still hold the line)."""
        meas: Dict[str, Any] = {}
        sf = _faultinject.swap_fault()
        if sf is not None and sf.mode == "reject":
            return GateResult(False, "forced reject (swap:reject fault)",
                              None, meas)
        try:
            mu = np.asarray(candidate["mu"], np.float64)
            alpha = np.asarray(candidate["alpha"], np.float64)
            beta = np.asarray(candidate["beta"], np.float64)
            s_sink = np.asarray(candidate["s_sink"], np.float64)
            fingerprint = str(candidate["fingerprint"])
            step = int(candidate["step"])
        except (KeyError, TypeError, ValueError) as e:
            return GateResult(False, f"malformed candidate: {e}", None, meas)
        if alpha.ndim == 1:
            alpha = np.diag(alpha)
        d = mu.shape[0] if mu.ndim == 1 else -1
        if (mu.ndim != 1 or beta.shape != (d,) or alpha.shape != (d, d)
                or s_sink.ndim != 1 or s_sink.size == 0):
            return GateResult(
                False, f"inconsistent shapes: mu {mu.shape}, alpha "
                       f"{alpha.shape}, beta {beta.shape}, s_sink "
                       f"{s_sink.shape}", None, meas)
        for name, arr in (("mu", mu), ("alpha", alpha), ("beta", beta),
                          ("s_sink", s_sink)):
            if not np.all(np.isfinite(arr)):
                return GateResult(False, f"non-finite {name}", None, meas)
            if np.any(arr < 0.0):
                return GateResult(False, f"negative {name}", None, meas)
        if np.any(beta <= 0.0):
            return GateResult(False, "beta must be > 0", None, meas)
        if not (s_sink.sum() > 0.0):
            return GateResult(False, "s_sink sums to 0", None, meas)
        rho = spectral_radius(alpha / beta[None, :])
        meas["rho"] = rho
        if not (rho < self.branching_cap):
            return GateResult(
                False, f"supercritical fit: spectral radius {rho:.4f} "
                       f">= {self.branching_cap}", None, meas)
        if canary is not None and baseline_nll is not None:
            cand_nll = float(canary(mu, alpha, beta))
            meas["nll_candidate"] = cand_nll
            meas["nll_baseline"] = float(baseline_nll)
            if not np.isfinite(cand_nll):
                return GateResult(False, "non-finite canary NLL",
                                  None, meas)
            bound = baseline_nll + self.nll_bound * abs(baseline_nll)
            if cand_nll > bound:
                return GateResult(
                    False, f"canary NLL regression: {cand_nll:.6g} > "
                           f"bound {bound:.6g} (baseline "
                           f"{baseline_nll:.6g})", None, meas)
        try:
            q = float(current_q if candidate.get("q") is None
                      else candidate["q"])
        except (TypeError, ValueError) as e:
            return GateResult(False, f"malformed candidate q: {e}",
                              None, meas)
        # q feeds the live sqrt(s/q) control law directly — a NaN or
        # non-positive q through the gate is exactly the silent outage
        # it exists to stop (and revalidate() would then refuse to
        # roll it back).  Mirror revalidate()'s check.
        if not (np.isfinite(q) and q > 0.0):
            return GateResult(False,
                              f"q must be finite and > 0, got {q}",
                              None, meas)
        s64 = np.ascontiguousarray(s_sink, dtype=np.float64)
        vp = ValidatedParams(s_sink=s64, q=q, fingerprint=fingerprint,
                             digest=params_digest(s64, q), step=step,
                             meta=meas)
        return GateResult(True, "", vp, meas)

    def revalidate(self, s_sink, q: float, fingerprint: str,
                   step: int = 0) -> ValidatedParams:
        """Re-mint a token for parameters that ALREADY served live (the
        rollback path re-installs last-good): structural checks only —
        they held the line once; the canary cannot be re-run against a
        window that has moved on."""
        s = np.ascontiguousarray(np.asarray(s_sink, np.float64))
        if s.ndim != 1 or s.size == 0 or not np.all(np.isfinite(s)) \
                or np.any(s < 0.0) or not (s.sum() > 0.0):
            raise ValueError(f"rollback params fail structural checks: "
                             f"{s!r}")
        qf = float(q)
        if not (np.isfinite(qf) and qf > 0.0):
            raise ValueError(f"rollback q must be finite > 0, got {qf}")
        return ValidatedParams(s_sink=s, q=qf, fingerprint=str(fingerprint),
                               digest=params_digest(s, qf), step=int(step),
                               meta={"rollback": True})


class ParamSwapper:
    """Drives candidates from the learner's artifact into the live
    policy, owns the reject/quarantine/rollback counters, and surfaces
    the staleness contract.  One swapper per runtime; the swap path is
    serialized by construction (one candidate in flight)."""

    def __init__(self, runtime, gate: Optional[ParamGate] = None,
                 stale_after_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self._rt = runtime
        self.gate = gate or ParamGate()
        self.stale_after_s = float(stale_after_s)
        self._clock = clock
        self._last_seen_fingerprint: Optional[str] = None
        # The learner is considered alive as of swapper birth: serving
        # just started with vetted initial params.
        self._last_candidate_t = clock()
        self.installs = 0
        self.rejections = 0
        self.quarantined = 0
        self.rollbacks = 0

    # -- the swap ----------------------------------------------------------

    def offer(self, candidate: Dict[str, Any],
              canary: Optional[Callable[..., float]] = None,
              baseline_nll: Optional[float] = None) -> Dict[str, Any]:
        """Gate one candidate and, on pass, install it.  Returns a
        result dict; never raises on a rejected fit (rejection is an
        accounted outcome, not an error)."""
        with _telemetry.span("serving.paramswap.offer",
                             fingerprint=str(candidate.get(
                                 "fingerprint", "?"))) as sp:
            self._last_candidate_t = self._clock()
            prev = self._rt.live_params()
            res = self.gate.validate(candidate, current_q=prev["q"],
                                     canary=canary,
                                     baseline_nll=baseline_nll)
            if not res.ok:
                self.rejections += 1
                _telemetry.counter("serving.paramswap.rejected")
                sp.set(outcome="rejected", reason=res.reason)
                return {"installed": False, "rolled_back": False,
                        "reason": res.reason, "epoch": prev["epoch"],
                        "measurements": res.measurements}
            epoch = self._rt.install_params(res.params)
            self.installs += 1
            _telemetry.counter("serving.paramswap.installed")
            sp.event("swap", epoch=epoch,
                     fingerprint=res.params.fingerprint,
                     digest=res.params.digest)
            out = {"installed": True, "rolled_back": False, "reason": "",
                   "epoch": epoch, "measurements": res.measurements}
            sf = _faultinject.swap_fault()
            regressed = sf is not None and sf.mode == "rollback"
            if regressed:
                out.update(self.rollback(
                    "forced post-install canary regression "
                    "(swap:rollback fault)", previous=prev))
                out["rolled_back"] = True
            sp.set(outcome="rolled_back" if regressed else "installed",
                   epoch=out["epoch"])
            return out

    def rollback(self, reason: str,
                 previous: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
        """Re-install the previous params as a NEW epoch (rollback is
        an install, never a mutation).  ``previous`` defaults to the
        runtime's retained previous slot."""
        prev = previous if previous is not None \
            else self._rt.previous_params()
        if prev is None:
            raise RuntimeError("no previous parameter slot to roll "
                               "back to")
        vp = self.gate.revalidate(prev["s_sink"], prev["q"],
                                  prev["fingerprint"])
        epoch = self._rt.install_params(vp)
        self.rollbacks += 1
        _telemetry.counter("serving.paramswap.rollback")
        _telemetry.event("swap", epoch=epoch, fingerprint=vp.fingerprint,
                         digest=vp.digest, rollback=True, reason=reason)
        return {"epoch": epoch, "rollback_reason": reason}

    # -- the artifact poll loop --------------------------------------------

    def poll_artifact(self, path: str,
                      canary: Optional[Callable[..., float]] = None,
                      baseline_nll: Optional[float] = None
                      ) -> Optional[Dict[str, Any]]:
        """Check the learner's hand-off path; offer a NEW candidate
        (unseen fingerprint), return None when there is nothing new.
        The ``swap:corrupt`` fault scribbles the artifact here, before
        the read — the integrity envelope catches it, the file is
        quarantined (moved aside), and serving stays on last-good."""
        if not os.path.exists(path):
            return None
        sf = _faultinject.swap_fault()
        if sf is not None and sf.mode == "corrupt":
            _faultinject.corrupt_file(path, "bitflip")
        try:
            candidate = read_candidate(path)
        except _integrity.CorruptArtifactError as e:
            self.quarantined += 1
            _telemetry.counter("serving.paramswap.quarantined")
            return {"installed": False, "rolled_back": False,
                    "reason": f"quarantined candidate artifact: {e}",
                    "epoch": self._rt.live_params()["epoch"],
                    "measurements": {}}
        fp = str(candidate.get("fingerprint", ""))
        if fp and fp == self._last_seen_fingerprint:
            self._last_candidate_t = self._clock()  # learner is alive
            return None
        self._last_seen_fingerprint = fp
        return self.offer(candidate, canary=canary,
                          baseline_nll=baseline_nll)

    # -- the staleness contract --------------------------------------------

    def status(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The surfaced learner/params state: ``fresh`` while
        candidates keep arriving, ``stale_params`` once the learner has
        been silent past the deadline.  Never an error — serving keeps
        answering on last-good either way."""
        t = self._clock() if now is None else now
        age = max(0.0, t - self._last_candidate_t)
        live = self._rt.live_params()
        return {
            "state": ("stale_params" if age > self.stale_after_s
                      else "fresh"),
            "age_s": age,
            "stale_after_s": self.stale_after_s,
            "epoch": live["epoch"],
            "fingerprint": live["fingerprint"],
            "installs": self.installs,
            "rejections": self.rejections,
            "quarantined": self.quarantined,
            "rollbacks": self.rollbacks,
        }
