"""The five BASELINE benchmark configurations as named presets (SURVEY.md
section 5 "Config/flag system": the reference's ``SimOpts.update`` sweep
idiom becomes frozen configs + these factory presets; configs listed in
BASELINE.md "Benchmark configs").

Each preset returns a ready-to-run bundle:

- batch-path presets (1, 3, 5) -> ``("batch", cfg, params, adj, opt_row)``
  for ``sim.simulate_batch`` / ``parallel.shard.simulate_sharded``;
- star-path presets (2, 4)     -> ``("star", cfg, wall, ctrl)`` for
  ``parallel.bigf.simulate_star``.

``run_preset`` executes either kind and reports one consistent metrics dict
— the shared entry point for bench.py, benchmarks/, and tests. All presets
accept a ``scale`` in (0, 1] shrinking them for CPU smoke runs.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["PRESETS", "build_preset", "run_preset", "power_law_graph"]


def _scaled(n: int, scale: float, lo: int = 1) -> int:
    return max(int(round(n * scale)), lo)


def config1_toy(scale: float = 1.0, end_time: float = 100.0, q: float = 1.0,
                wall_rate: float = 1.0, n_components: int = 1,
                capacity: int = 2048):
    """1 Opt broadcaster vs 10 Poisson-feed followers — the paper toy and
    the NumPy-parity anchor (BASELINE config 1)."""
    from .config import GraphBuilder, stack_components

    n_followers = _scaled(10, scale)
    gb = GraphBuilder(n_sinks=n_followers, end_time=end_time)
    opt = gb.add_opt(q=q)
    for i in range(n_followers):
        gb.add_poisson(rate=wall_rate, sinks=[i])
    cfg, p0, a0 = gb.build(capacity=capacity)
    if n_components > 1:
        params, adj = stack_components([p0] * n_components, [a0] * n_components)
        return ("batch", cfg, params, adj, opt)
    return ("batch", cfg, p0, a0, opt)


def config2_hawkes(scale: float = 1.0, end_time: float = 100.0,
                   q: float = 1.0, l0: float = 0.5, alpha: float = 0.8,
                   beta: float = 2.0, wall_cap: int = 512,
                   post_cap: int = 4096):
    """1 broadcaster vs 1k self-exciting Hawkes feeds — the vmapped-thinning
    config (BASELINE config 2), on the follower-sharded star path."""
    from .parallel.bigf import StarBuilder

    n_feeds = _scaled(1000, scale)
    sb = StarBuilder(n_feeds=n_feeds, end_time=end_time)
    for f in range(n_feeds):
        sb.wall_hawkes(f, l0=l0, alpha=alpha, beta=beta)
    sb.ctrl_opt(q=q)
    cfg, wall, ctrl = sb.build(wall_cap=wall_cap, post_cap=post_cap)
    return ("star", cfg, wall, ctrl)


def config3_bipartite(scale: float = 1.0, end_time: float = 100.0,
                      q: float = 1.0, wall_rate: float = 1.0,
                      followers_per: int = 10, capacity: int = 2048):
    """1k broadcasters x 10k followers bipartite — shards over broadcasters
    (BASELINE config 3). RedQueen broadcasters do not couple, so the graph
    decomposes into independent per-broadcaster components run as one
    batch axis (SURVEY.md section 7)."""
    from .config import GraphBuilder, stack_components

    B = _scaled(1000, scale)
    gb = GraphBuilder(n_sinks=followers_per, end_time=end_time)
    opt = gb.add_opt(q=q)
    for i in range(followers_per):
        gb.add_poisson(rate=wall_rate, sinks=[i])
    cfg, p0, a0 = gb.build(capacity=capacity)
    params, adj = stack_components([p0] * B, [a0] * B)
    return ("batch", cfg, params, adj, opt)


def config4_replay(scale: float = 1.0, end_time: float = 100.0,
                   q: float = 1.0, seed: int = 7, mean_rate: float = 1.0,
                   traces=None, post_cap: int = 4096,
                   trace_max_len: Optional[int] = 256):
    """Twitter retweet-cascade replay: RealData walls, 100k followers
    (BASELINE config 4). Uses the synthetic heavy-tailed corpus when no real
    trace is supplied (no network in this environment). ``trace_max_len``
    bounds per-user trace length at generation: the Opt-controlled component
    is one coupled system (see data.replay_buckets for why it cannot be
    bucketed), so the replay tensor pads to the longest trace — unbounded
    heavy tails would waste GBs on +inf padding."""
    from .data import star_from_traces, synthetic_twitter

    n_feeds = _scaled(100_000, scale)
    if traces is None:
        traces = synthetic_twitter(seed, n_feeds, end_time,
                                   mean_rate=mean_rate,
                                   max_len=trace_max_len)
    cfg, wall, ctrl = star_from_traces(traces, end_time, ctrl="opt", q=q,
                                       post_cap=post_cap)
    return ("star", cfg, wall, ctrl)


def config5_rmtpp(scale: float = 1.0, end_time: float = 100.0,
                  wall_rate: float = 1.0, hidden: int = 8,
                  train_steps: int = 120, seed: int = 0,
                  capacity: int = 2048, weights=None):
    """Neural-intensity lambda_theta (RMTPP) as the controlled broadcaster
    (BASELINE config 5) behind the same policy seam — the north star's
    "registers as an Opt subclass" extension point.

    Trains a small model on synthetic gap sequences unless ``weights`` is
    given (utils.checkpoint round-trips them)."""
    import jax.numpy as jnp
    from jax import random as jr

    from .config import GraphBuilder
    from .models import rmtpp

    n_followers = _scaled(10, scale)
    if weights is None:
        rng = np.random.RandomState(seed)
        taus = rng.exponential(0.7, (32, 24)).astype(np.float32)
        mask = np.ones_like(taus, bool)
        weights, _, _ = rmtpp.fit(jr.PRNGKey(seed), taus, mask,
                                  hidden=hidden, steps=train_steps)
    gb = GraphBuilder(n_sinks=n_followers, end_time=end_time)
    row = gb.add_rmtpp()
    for i in range(n_followers):
        gb.add_poisson(rate=wall_rate, sinks=[i])
    cfg, params, adj = gb.build(capacity=capacity, rmtpp_hidden=hidden)
    params = rmtpp.attach(params, weights)
    return ("batch", cfg, params, adj, row)


def power_law_graph(B, alpha: float = 2.2, seed: int = 0,
                    min_followers: int = 1, max_followers: int = 1024,
                    end_time: float = 100.0, q: float = 1.0,
                    wall_rate: float = 1.0, scale: float = 1.0):
    """``B`` independent broadcaster components whose follower counts
    follow a truncated power law ``P(F = k) ∝ k^-alpha`` on
    ``[min_followers, max_followers]`` — the paper's "millions of users"
    feed-graph shape, where a handful of hubs have thousands of
    followers and the long tail has a few.  Returns a ragged bundle
    ``("ragged", counts, opts)`` for
    :func:`~redqueen_tpu.parallel.lanes.simulate_ragged` (via
    :func:`run_preset`), so a 10⁶-lane config is one call.

    Host-side domain validation is typed
    (:class:`~redqueen_tpu.config.ConfigValidationError`): ``B`` must be
    a true integer (a float 1e6 would silently truncate), ``alpha``
    finite and > 0, and ``max_followers >= 2`` — an all-single-follower
    graph is a degenerate star with no raggedness to bucket (use
    ``config1_toy``/``config3_bipartite`` for fixed-width graphs)."""
    from .config import ConfigValidationError

    if isinstance(B, bool) or not isinstance(B, (int, np.integer)):
        raise ConfigValidationError(
            f"B must be an integer broadcaster count, got {B!r} "
            f"({type(B).__name__}) — a float would silently truncate "
            f"the lane count")
    if B < 1:
        raise ConfigValidationError(f"B must be >= 1, got {B}")
    alpha = float(alpha)
    if not (np.isfinite(alpha) and alpha > 0):
        raise ConfigValidationError(
            f"alpha must be finite and > 0, got {alpha!r} (the tail "
            f"exponent of P(F=k) ∝ k^-alpha)")
    min_f, max_f = int(min_followers), int(max_followers)
    if min_f < 1:
        raise ConfigValidationError(
            f"min_followers must be >= 1, got {min_followers!r}")
    if max_f < min_f:
        raise ConfigValidationError(
            f"max_followers ({max_followers!r}) must be >= min_followers "
            f"({min_followers!r})")
    if max_f < 2:
        raise ConfigValidationError(
            "max_followers < 2 makes every broadcaster a single-follower "
            "component — a degenerate star with no raggedness to bucket; "
            "use config1_toy/config3_bipartite for fixed-width graphs")
    B_s = _scaled(B, scale)
    max_f = max(_scaled(max_f, scale), 2)
    min_f = min(min_f, max_f)
    ks = np.arange(min_f, max_f + 1, dtype=np.float64)
    p = ks ** -alpha
    p /= p.sum()
    rng = np.random.RandomState(seed)
    counts = rng.choice(np.arange(min_f, max_f + 1), size=B_s, p=p)
    return ("ragged", counts.astype(np.int64),
            dict(end_time=float(end_time), q=float(q),
                 wall_rate=float(wall_rate)))


PRESETS = {
    1: config1_toy,
    2: config2_hawkes,
    3: config3_bipartite,
    4: config4_replay,
    5: config5_rmtpp,
    "toy": config1_toy,
    "hawkes": config2_hawkes,
    "bipartite": config3_bipartite,
    "replay": config4_replay,
    "rmtpp": config5_rmtpp,
    "power_law": power_law_graph,
}


def build_preset(which, **kw):
    """Build BASELINE preset ``which`` (1-5 or name). Keyword args override
    the preset's defaults — the reference's ``SimOpts.update`` role."""
    if which not in PRESETS:
        raise KeyError(f"unknown preset {which!r}; have {sorted(PRESETS, key=str)}")
    return PRESETS[which](**kw)


def run_preset(bundle, seeds, mesh=None, max_chunks: int = 256,
               metric_K: int = 1):
    """Run a preset bundle over ``seeds`` and return a metrics dict:
    events (total), mean time-in-top-K, mean posts per broadcaster, and the
    per-seed values. Batch bundles treat an int-array ``seeds`` as the
    component batch (must match the stacked batch dim if any); star bundles
    loop seeds host-side (each run is one big component)."""
    import jax
    import jax.numpy as jnp

    kind = bundle[0]
    if kind == "batch":
        _, cfg, params, adj, opt_row = bundle
        from .sim import simulate_batch
        from .utils.metrics import feed_metrics_batch, num_posts

        seeds = np.asarray(seeds)
        batched = params.kind.ndim == 2
        if batched:
            from .parallel.shard import simulate_sharded

            B = params.kind.shape[0]
            if seeds.ndim == 0:
                seeds = np.arange(B) + int(seeds)  # base seed -> one per lane
            elif len(seeds) != B:
                raise ValueError(
                    f"batched preset needs {B} seeds (one per component) or "
                    f"a scalar base seed; got {len(seeds)}"
                )

            if mesh is not None:
                log = simulate_sharded(cfg, params, adj, seeds, mesh,
                                       max_chunks=max_chunks)
            else:
                log = simulate_batch(cfg, params, adj, seeds,
                                     max_chunks=max_chunks)
            adj_b = adj if adj.ndim == 3 else jnp.broadcast_to(
                adj, (len(seeds),) + adj.shape
            )
            m = feed_metrics_batch(log.times, log.srcs, adj_b, opt_row,
                                   cfg.end_time, K=metric_K)
            # explicit device->host boundary: the run is over, fetch the
            # reduced metrics once instead of syncing np-call by np-call
            tops = jax.device_get(m.mean_time_in_top_k())
            posts = jax.device_get(num_posts(log.srcs, opt_row))
            events = int(jax.device_get(log.n_events).sum())
        else:
            # Seed sweep = a vmap batch axis (SURVEY.md section 3.5), not a
            # host loop: stack the single component once per seed.
            from .config import stack_components

            seeds = np.atleast_1d(seeds)
            n = len(seeds)
            params_b, adj_b = stack_components([params] * n, [adj] * n)
            log = simulate_batch(cfg, params_b, adj_b, seeds,
                                 max_chunks=max_chunks)
            m = feed_metrics_batch(log.times, log.srcs, adj_b, opt_row,
                                   cfg.end_time, K=metric_K)
            tops = jax.device_get(m.mean_time_in_top_k())
            posts = jax.device_get(num_posts(log.srcs, opt_row))
            events = int(jax.device_get(log.n_events).sum())
    elif kind == "ragged":
        # Power-law ragged bundle: bucketed dispatch through the unified
        # lane layer (parallel.lanes) — per-lane seeds, original order.
        # Chunk budgets are derived per bucket by the lane layer
        # (lanes.shape_budget), so ``max_chunks`` does not apply here.
        if mesh is not None:
            raise ValueError(
                "ragged presets dispatch through parallel.lanes."
                "simulate_ragged, which does not shard over a mesh yet "
                "(the ROADMAP item 3 remainder) — drop mesh or use a "
                "batch/star preset")
        _, counts, opts = bundle
        from .parallel.lanes import simulate_ragged

        B = len(counts)
        seeds = np.asarray(seeds)
        if seeds.ndim == 0:
            seeds = np.arange(B) + int(seeds)  # base seed -> one per lane
        elif len(seeds) != B:
            raise ValueError(
                f"ragged preset needs {B} seeds (one per lane) or a "
                f"scalar base seed; got {len(seeds)}"
            )
        # RaggedResult fields are host numpy by contract (the ragged
        # dispatch crosses device->host once per bucket slab, at its
        # documented _dg boundary) — no hidden sync below.
        rr = simulate_ragged(counts, seeds, metric_K=metric_K, **opts)
        return {
            "events": rr.events,
            "mean_time_in_top_k": float(rr.top_k.mean()),  # rqlint: disable=RQ701 host numpy
            "mean_posts": float(rr.posts.mean()),  # rqlint: disable=RQ701 host numpy
            "per_seed_top_k": rr.top_k.tolist(),  # rqlint: disable=RQ701 host numpy
            "per_seed_posts": rr.posts.tolist(),  # rqlint: disable=RQ701 host numpy
            "end_time": opts["end_time"],
        }
    elif kind == "star":
        _, cfg, wall, ctrl = bundle
        seeds_arr = np.asarray(seeds).ravel()
        mesh_axes = dict(mesh.shape) if mesh is not None else {}
        if len(seeds_arr) == 1 or (mesh is not None
                                   and "data" not in mesh_axes):
            # One seed, or a feed-only mesh (a single 100k-feed component
            # sharded over followers): the per-run star path.
            from .parallel.bigf import simulate_star

            tops, posts, events = [], [], 0
            for s in seeds_arr:
                res = simulate_star(cfg, wall, ctrl, seed=int(s), mesh=mesh,
                                    metric_K=metric_K)
                tops.append(
                    float(np.asarray(res.metrics.mean_time_in_top_k()))
                )
                posts.append(res.n_posts)
                events += int(res.wall_n.sum()) + res.n_posts
            tops, posts = np.asarray(tops), np.asarray(posts)
        else:
            # Seed sweep = one vmapped batch (SURVEY.md section 3.5), not a
            # host loop; per-seed results are bit-identical to the loop
            # because lane PRNG streams depend only on the lane's seed.
            from .parallel.bigf import broadcast_star, simulate_star_batch

            B = len(seeds_arr)
            wb, cb = broadcast_star(wall, ctrl, B)
            res = simulate_star_batch(
                cfg, wb, cb, seeds_arr, mesh=mesh,
                feed_axis=("feed" if "feed" in mesh_axes else None),
                metric_K=metric_K,
            )
            tops = np.asarray(res.metrics.mean_time_in_top_k())
            posts = np.asarray(res.n_posts)
            events = int(res.wall_n.sum()) + int(res.n_posts.sum())
    else:
        raise ValueError(f"unknown bundle kind {kind!r}")
    return {
        "events": events,
        "mean_time_in_top_k": float(tops.mean()),
        "mean_posts": float(posts.mean()),
        "per_seed_top_k": tops.tolist(),
        "per_seed_posts": posts.tolist(),
        "end_time": cfg.end_time,
    }
