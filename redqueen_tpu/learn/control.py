"""Close the loop: fitted Hawkes parameters → RedQueen control.

The paper's control algorithm treats the followers' feed dynamics as
GIVEN; ``learn.hawkes_mle`` makes them LEARNED.  This module is the seam
between the two: a :class:`~redqueen_tpu.learn.hawkes_mle.HawkesFit`
becomes ``config.add_hawkes`` sources of a simulation component, with a
RedQueen (Opt) broadcaster layered on top — "fit real feeds, then
broadcast smartly".  ``experiments/closed_loop.py`` drives the full
simulate → fit → re-simulate-under-control pipeline and emits the
fitted-vs-true control-cost artifact.

The simulator's Hawkes sources are per-source SELF-exciting (diagonal in
the multivariate model); a fit with substantial off-diagonal excitation
cannot be represented faithfully, so :func:`add_fit_walls` measures the
cross-excitation mass and warns (never silently drops it) before adding
the diagonal projection.
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence

import numpy as np

__all__ = [
    "builder_params",
    "cross_excitation_mass",
    "add_fit_walls",
    "control_component",
    "control_cost",
    "stationary_rates",
    "fit_s_sink",
    "simulate_cross_exciting",
]

# Above this fraction of learned branching mass living off-diagonal, the
# diagonal projection is materially wrong and the warning fires.
CROSS_EXCITATION_WARN = 0.25


def cross_excitation_mass(fit) -> float:
    """Fraction of the fitted branching mass (``alpha_ij / beta_j``)
    that is OFF-diagonal — what the simulator's self-exciting sources
    cannot represent.  0.0 for a pure self-exciting fit."""
    b = np.asarray(fit.branching(), np.float64)
    total = float(b.sum())
    if total <= 0:
        return 0.0
    off = float(total - np.trace(b))
    return max(off, 0.0) / max(total, 1e-300)


def builder_params(fit, warn: bool = True):
    """``(mu, alpha_diag, beta)`` f64 arrays for per-source simulation —
    the diagonal projection of the fit, with the cross-excitation check.
    Quarantined dimensions (``fit.health`` non-zero) carry fallback
    values; the caller decides whether to include them (the arrays are
    returned whole — mask with ``fit.health == 0`` to drop them)."""
    mu = np.asarray(fit.mu, np.float64)
    alpha = np.asarray(fit.alpha, np.float64)
    beta = np.asarray(fit.beta, np.float64)
    if warn:
        frac = cross_excitation_mass(fit)
        if frac > CROSS_EXCITATION_WARN:
            warnings.warn(
                f"{frac:.1%} of the fitted branching mass is "
                f"off-diagonal cross-excitation — the simulator's "
                f"per-source Hawkes walls keep only the diagonal, so the "
                f"re-simulated feeds will be tamer than the fit; treat "
                f"control costs as approximate", stacklevel=3)
    return mu, np.diag(alpha).copy(), beta


def add_fit_walls(gb, fit, sinks_per_dim: Optional[Sequence] = None,
                  warn: bool = True):
    """Add one Hawkes wall per fitted dimension to a
    :class:`~redqueen_tpu.config.GraphBuilder` (domain checks +
    supercritical warnings apply to the LEARNED parameters exactly as to
    hand-written specs).  ``sinks_per_dim[k]`` is dimension k's sink
    list (default: dim k → sink k, the closed-loop layout).  Returns the
    added source rows."""
    mu, a_diag, beta = builder_params(fit, warn=warn)
    rows = []
    for k in range(fit.n_dims):
        sinks = [k] if sinks_per_dim is None else sinks_per_dim[k]
        rows.append(gb.add_hawkes(float(mu[k]), float(a_diag[k]),
                                  float(beta[k]), sinks=sinks))
    return rows


def control_component(fit_or_params, end_time: float, q: float = 1.0,
                      capacity: int = 4096, warn: bool = True):
    """The closed-loop component: one RedQueen (Opt) broadcaster posting
    into every feed, against one fitted (or true) Hawkes wall per feed.

    ``fit_or_params`` — a :class:`HawkesFit`, or a ``(mu, alpha_diag,
    beta)`` triple of [D] arrays (the true-parameter twin, so fitted and
    true worlds build through the IDENTICAL path).  Returns
    ``((cfg, params, adj), opt_row)`` ready for
    :func:`~redqueen_tpu.sweep.run_sweep`."""
    from ..config import GraphBuilder

    if hasattr(fit_or_params, "alpha") and hasattr(fit_or_params, "mu"):
        mu, a_diag, beta = builder_params(fit_or_params, warn=warn)
    else:
        mu, a_diag, beta = (np.asarray(x, np.float64)
                            for x in fit_or_params)
    D = len(mu)
    gb = GraphBuilder(n_sinks=D, end_time=float(end_time))
    opt_row = gb.add_opt(q=float(q))
    for k in range(D):
        gb.add_hawkes(float(mu[k]), float(a_diag[k]), float(beta[k]),
                      sinks=[k])
    return gb.build(capacity=int(capacity)), opt_row


def simulate_cross_exciting(mu, alpha, beta, t_end: float,
                            seed: int = 0, t_start: float = 0.0,
                            max_events: int = 1_000_000):
    """Seeded Ogata-thinning simulation of a FULL multivariate Hawkes
    model — off-diagonal ``alpha`` included, which the jax simulator's
    per-source self-exciting walls cannot produce.  This is the ground
    truth generator that validates fitted cross-excitation end-to-end
    (simulate a known off-diagonal model → journal it → fit → compare
    :func:`cross_excitation_mass`).

    Parameterization matches ``learn.loglik`` exactly: ``alpha`` is the
    jump matrix, ``lambda_i(t) = mu_i + sum_l alpha[i, u_l] *
    exp(-beta[u_l] (t - t_l))``.  Host NumPy (O(n·D) with exponential
    state decay between candidates — no event-history rescan), so it
    stays test-sized; corpus-scale generation is the jax simulator's
    job.  Returns ``(times f64[n], dims i32[n])``, globally ordered.
    Raises if the model is supercritical (the simulation would explode)
    or ``max_events`` is exceeded."""
    mu = np.asarray(mu, np.float64)
    alpha = np.asarray(alpha, np.float64)
    if alpha.ndim == 1:
        alpha = np.diag(alpha)
    beta = np.asarray(beta, np.float64)
    D = len(mu)
    if alpha.shape != (D, D) or beta.shape != (D,):
        raise ValueError(
            f"shape mismatch: mu [{D}], alpha {alpha.shape}, "
            f"beta {beta.shape}")
    if (mu < 0).any() or (alpha < 0).any() or (beta <= 0).any():
        raise ValueError("need mu >= 0, alpha >= 0, beta > 0")
    B = alpha / np.maximum(beta[None, :], 1e-300)
    rho = float(np.max(np.abs(np.linalg.eigvals(B)))) if D else 0.0
    if rho >= 1.0:
        raise ValueError(
            f"supercritical model (spectral radius {rho:.3f} >= 1) — "
            f"the cluster sizes diverge; scale alpha down")
    rng = np.random.default_rng(seed)
    t = float(t_start)
    r = np.zeros(D)  # decayed excitation state per SOURCE dimension
    times, dims = [], []
    while True:
        lam = mu + alpha @ r
        M = float(lam.sum())
        if M <= 0:
            break  # silent model: no further events ever
        t_cand = t + rng.exponential(1.0 / max(M, 1e-300))
        if t_cand >= t_end:
            break
        # Host-side sampler, not kernel code: the exponent is <= 0 so the
        # decay factor lives in (0, 1] — no overflow to guard.
        r_cand = r * np.exp(-beta * (t_cand - t))  # rqlint: disable=RQ301
        lam_cand = mu + alpha @ r_cand
        tot = float(lam_cand.sum())
        t, r = t_cand, r_cand
        if rng.uniform() * M <= tot:
            i = int(rng.choice(D, p=lam_cand / max(tot, 1e-300)))
            times.append(t)
            dims.append(i)
            r[i] += 1.0
            if len(times) > max_events:
                raise RuntimeError(
                    f"simulate_cross_exciting exceeded {max_events} "
                    f"events before t_end={t_end} — rate too high for "
                    f"a host-side test simulation")
    return (np.asarray(times, np.float64),
            np.asarray(dims, np.int32))


def stationary_rates(mu, alpha, beta) -> np.ndarray:
    """Stationary event rates ``Lambda = (I - B)^{-1} mu`` of a
    subcritical multivariate Hawkes model (B the branching matrix
    ``alpha_ij / beta_j`` — the full matrix, so off-diagonal
    cross-excitation contributes exactly its share of the long-run
    rate).  Falls back to ``mu`` when the fit is supercritical or the
    resolvent is singular: a rate is needed even for a fit the install
    gate is about to reject."""
    mu = np.asarray(mu, np.float64)
    alpha = np.asarray(alpha, np.float64)
    if alpha.ndim == 1:  # diagonal (self-exciting) parameterization
        alpha = np.diag(alpha)
    beta = np.asarray(beta, np.float64)
    B = alpha / np.maximum(beta[None, :], 1e-300)
    try:
        ev = np.max(np.abs(np.linalg.eigvals(B))) if B.size else 0.0
        if not np.isfinite(ev) or ev >= 1.0:
            return np.maximum(mu, 0.0)
        lam = np.linalg.solve(np.eye(len(mu)) - B, mu)
    except np.linalg.LinAlgError:
        return np.maximum(mu, 0.0)
    if not np.isfinite(lam).all() or (lam < 0).any():
        return np.maximum(mu, 0.0)
    return lam


def fit_s_sink(fit_or_params, normalize: bool = True) -> np.ndarray:
    """Per-feed significance weights for the serving decision rule,
    derived from a fit: each feed's stationary rate (how much organic
    traffic competes there), mean-normalized to 1 so the learned
    weights land on the same scale as the hand-written ``s_sink=1``
    defaults — the serving ``q`` keeps its meaning across a hot-swap.
    Accepts a :class:`~redqueen_tpu.learn.hawkes_mle.HawkesFit` or a
    ``(mu, alpha, beta)`` triple.  All-zero rates (a dead stream)
    degrade to uniform ones — a weight vector must never be zero."""
    if hasattr(fit_or_params, "alpha") and hasattr(fit_or_params, "mu"):
        mu = np.asarray(fit_or_params.mu, np.float64)
        alpha = np.asarray(fit_or_params.alpha, np.float64)
        beta = np.asarray(fit_or_params.beta, np.float64)
    else:
        mu, alpha, beta = (np.asarray(x, np.float64)
                           for x in fit_or_params)
    lam = stationary_rates(mu, alpha, beta)
    if normalize:
        m = float(lam.mean()) if lam.size else 0.0
        if m <= 0 or not np.isfinite(m):
            return np.ones_like(lam) if lam.size else lam
        lam = lam / max(m, 1e-300)
    return lam


def control_cost(result, q: float) -> np.ndarray:
    """The paper's control objective per sweep lane: ``int r^2 dt + q *
    posts`` over the horizon (the quantity RedQueen trades off) — the
    scalar the fitted-vs-true comparison scores.  ``result`` is a
    :class:`~redqueen_tpu.sweep.SweepResult`."""
    return (np.asarray(result.int_rank2, np.float64)
            + float(q) * np.asarray(result.n_posts, np.float64))
