"""Close the loop: fitted Hawkes parameters → RedQueen control.

The paper's control algorithm treats the followers' feed dynamics as
GIVEN; ``learn.hawkes_mle`` makes them LEARNED.  This module is the seam
between the two: a :class:`~redqueen_tpu.learn.hawkes_mle.HawkesFit`
becomes ``config.add_hawkes`` sources of a simulation component, with a
RedQueen (Opt) broadcaster layered on top — "fit real feeds, then
broadcast smartly".  ``experiments/closed_loop.py`` drives the full
simulate → fit → re-simulate-under-control pipeline and emits the
fitted-vs-true control-cost artifact.

The simulator's Hawkes sources are per-source SELF-exciting (diagonal in
the multivariate model); a fit with substantial off-diagonal excitation
cannot be represented faithfully, so :func:`add_fit_walls` measures the
cross-excitation mass and warns (never silently drops it) before adding
the diagonal projection.
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence

import numpy as np

__all__ = [
    "builder_params",
    "cross_excitation_mass",
    "add_fit_walls",
    "control_component",
    "control_cost",
]

# Above this fraction of learned branching mass living off-diagonal, the
# diagonal projection is materially wrong and the warning fires.
CROSS_EXCITATION_WARN = 0.25


def cross_excitation_mass(fit) -> float:
    """Fraction of the fitted branching mass (``alpha_ij / beta_j``)
    that is OFF-diagonal — what the simulator's self-exciting sources
    cannot represent.  0.0 for a pure self-exciting fit."""
    b = np.asarray(fit.branching(), np.float64)
    total = float(b.sum())
    if total <= 0:
        return 0.0
    off = float(total - np.trace(b))
    return max(off, 0.0) / max(total, 1e-300)


def builder_params(fit, warn: bool = True):
    """``(mu, alpha_diag, beta)`` f64 arrays for per-source simulation —
    the diagonal projection of the fit, with the cross-excitation check.
    Quarantined dimensions (``fit.health`` non-zero) carry fallback
    values; the caller decides whether to include them (the arrays are
    returned whole — mask with ``fit.health == 0`` to drop them)."""
    mu = np.asarray(fit.mu, np.float64)
    alpha = np.asarray(fit.alpha, np.float64)
    beta = np.asarray(fit.beta, np.float64)
    if warn:
        frac = cross_excitation_mass(fit)
        if frac > CROSS_EXCITATION_WARN:
            warnings.warn(
                f"{frac:.1%} of the fitted branching mass is "
                f"off-diagonal cross-excitation — the simulator's "
                f"per-source Hawkes walls keep only the diagonal, so the "
                f"re-simulated feeds will be tamer than the fit; treat "
                f"control costs as approximate", stacklevel=3)
    return mu, np.diag(alpha).copy(), beta


def add_fit_walls(gb, fit, sinks_per_dim: Optional[Sequence] = None,
                  warn: bool = True):
    """Add one Hawkes wall per fitted dimension to a
    :class:`~redqueen_tpu.config.GraphBuilder` (domain checks +
    supercritical warnings apply to the LEARNED parameters exactly as to
    hand-written specs).  ``sinks_per_dim[k]`` is dimension k's sink
    list (default: dim k → sink k, the closed-loop layout).  Returns the
    added source rows."""
    mu, a_diag, beta = builder_params(fit, warn=warn)
    rows = []
    for k in range(fit.n_dims):
        sinks = [k] if sinks_per_dim is None else sinks_per_dim[k]
        rows.append(gb.add_hawkes(float(mu[k]), float(a_diag[k]),
                                  float(beta[k]), sinks=sinks))
    return rows


def control_component(fit_or_params, end_time: float, q: float = 1.0,
                      capacity: int = 4096, warn: bool = True):
    """The closed-loop component: one RedQueen (Opt) broadcaster posting
    into every feed, against one fitted (or true) Hawkes wall per feed.

    ``fit_or_params`` — a :class:`HawkesFit`, or a ``(mu, alpha_diag,
    beta)`` triple of [D] arrays (the true-parameter twin, so fitted and
    true worlds build through the IDENTICAL path).  Returns
    ``((cfg, params, adj), opt_row)`` ready for
    :func:`~redqueen_tpu.sweep.run_sweep`."""
    from ..config import GraphBuilder

    if hasattr(fit_or_params, "alpha") and hasattr(fit_or_params, "mu"):
        mu, a_diag, beta = builder_params(fit_or_params, warn=warn)
    else:
        mu, a_diag, beta = (np.asarray(x, np.float64)
                            for x in fit_or_params)
    D = len(mu)
    gb = GraphBuilder(n_sinks=D, end_time=float(end_time))
    opt_row = gb.add_opt(q=float(q))
    for k in range(D):
        gb.add_hawkes(float(mu[k]), float(a_diag[k]), float(beta[k]),
                      sinks=[k])
    return gb.build(capacity=int(capacity)), opt_row


def control_cost(result, q: float) -> np.ndarray:
    """The paper's control objective per sweep lane: ``int r^2 dt + q *
    posts`` over the horizon (the quantity RedQueen trades off) — the
    scalar the fitted-vs-true comparison scores.  ``result`` is a
    :class:`~redqueen_tpu.sweep.SweepResult`."""
    return (np.asarray(result.int_rank2, np.float64)
            + float(q) * np.asarray(result.n_posts, np.float64))
