"""Exact multivariate exponential-kernel Hawkes log-likelihood — the O(n)
recursion, shared by both solvers in ``learn.hawkes_mle``.

Model (the simulator's own convention, ``models/hawkes.py`` generalized to
cross-excitation): intensity of dimension ``i``

    lambda_i(t) = mu_i + sum_j alpha_ij * sum_{t_l < t, u_l = j}
                                exp(-beta_j (t - t_l))

``alpha`` is the JUMP matrix (``alpha_ii``/``beta_i`` match the
simulator's per-source ``alpha``/``beta`` exactly), ``beta`` decays per
EXCITING dimension.  The naive likelihood is O(n^2) in event pairs; the
exponential kernel collapses it to O(n * D) via the classic decay
recursions carried event-to-event in GLOBAL time order:

    R_j(t_k) = sum_{t_l < t_k, u_l = j} exp(-beta_j (t_k - t_l))
    Q_j(t_k) = sum_{t_l < t_k, u_l = j} (t_k - t_l) exp(-beta_j (t_k - t_l))

    R(t + d) = e^{-beta d} R(t)            [+1 on own dim at an event]
    Q(t + d) = e^{-beta d} (Q(t) + d R(t))

``Q`` exists for the EM solver's closed-form decay update (the weighted
-lag sufficient statistic); the likelihood itself needs only ``R``:

    LL = sum_k log lambda_{u_k}(t_k)
         - sum_i [mu_i T + sum_j alpha_ij G_j],
    G_j = sum_{u_l = j} (1 - e^{-beta_j (T - t_l)}) / beta_j

Everything runs through ``runtime.numerics`` safe_* primitives and the
scan carries a per-DIMENSION health word (``BIT_NONFINITE_STATE`` when a
dimension's intensity goes non-finite or non-positive at one of its own
events): a degenerate trace quarantines a dimension instead of NaN-ing
the fit — the same protocol the sim kernel applies per lane.

The event scan streams ``ChunkedEvents`` chunks (outer ``lax.scan`` over
chunks, inner over the chunk's events) and emits PER-CHUNK partial sums
that reduce pairwise afterwards — at 8.58M corpus events a single f32
running sum would accumulate sequential rounding; per-chunk partials keep
every accumulation short.  Masked pad events are exact no-ops (``dt = 0``
⇒ decay 1; every add is mask-gated).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..runtime.numerics import (
    BIT_NONFINITE_STATE,
    safe_div,
    safe_exp,
    safe_log,
)
from .ingest import ChunkedEvents, EventStream, chunk_events

__all__ = ["hawkes_loglik", "LoglikResult"]


class LoglikResult(NamedTuple):
    """Scored likelihood of a stream under (mu, alpha, beta).

    ``loglik`` — the exact log-likelihood (np float64 scalar);
    ``loglik_events`` / ``compensator`` — its two terms;
    ``health`` — u32[D] per-dimension bits (``runtime.numerics``):
    non-zero marks a dimension whose intensity went non-finite or
    non-positive at one of its own events; such events contribute
    exactly ZERO to ``loglik_events`` (never a NaN, and never a clamped
    stand-in that could poison sibling dimensions' statistics) — the
    score is not trustworthy for a flagged dimension."""

    loglik: float
    loglik_events: float
    compensator: float
    health: np.ndarray


def _event_step(mu, alpha, beta, carry, ev):
    """One event of the decay recursion.  ``carry`` = (R, Q, chunk-local
    partials); ``ev`` = (dt, dim, mask).  Exact no-op when masked.

    An event whose intensity is invalid (non-finite or non-positive)
    flags ITS dimension's health bit and contributes ZERO to every
    accumulator — the estimator's version of the sim kernel's lane
    freeze: a sick dimension can never smuggle a NaN into the shared
    sufficient statistics and poison its siblings' M-step."""
    (R, Q, s0, S, W, ll, health) = carry
    dt, i, m = ev
    d = safe_exp(-beta * dt)                     # <= 1: never overflows
    Q = d * (Q + dt * R)
    R = d * R
    exc = alpha[i] * R                           # [D] alpha_ij R_j
    lam = mu[i] + exc.sum()
    ok = jnp.isfinite(lam) & (lam > 0)
    use = m & ok
    # Responsibilities (the EM E-step, aggregated per exciting dim);
    # they cost one fused multiply over [D] and make this ONE scan serve
    # likelihood scoring and the EM sufficient statistics alike.  The
    # `where` wraps OUTSIDE safe_div: num/NaN is NaN and 0 * NaN is NaN
    # — gating must select, not scale.
    zero = jnp.zeros((), lam.dtype)
    p0 = jnp.where(use, safe_div(mu[i], lam, when_zero=0.0), zero)
    pr = jnp.where(use, safe_div(exc, lam, when_zero=0.0),
                   jnp.zeros_like(exc))
    plag = jnp.where(use, safe_div(alpha[i] * Q, lam, when_zero=0.0),
                     jnp.zeros_like(exc))
    s0 = s0.at[i].add(p0)
    S = S.at[i].add(pr)
    W = W + plag
    ll = ll + jnp.where(use, safe_log(lam), zero)
    health = health.at[i].set(
        health[i] | jnp.where(m & ~ok, jnp.uint32(BIT_NONFINITE_STATE),
                              jnp.uint32(0)))
    # This event starts exciting regardless of intensity validity: the
    # recursion state R is a function of the observed TIMES, not of the
    # (possibly mid-fit-corrupt) parameters being scored.
    R = R.at[i].add(jnp.asarray(m, lam.dtype))
    return (R, Q, s0, S, W, ll, health), None


@functools.partial(jax.jit, static_argnames=("n_dims",), donate_argnums=())
def _stream_pass(dt, dims, mask, mu, alpha, beta, n_dims: int):
    """The full O(n) pass: scan chunks, return reduced sufficient stats.

    Returns ``(ll_events, s0[D], S[D, D], W[D], health u32[D])`` — the
    event-side statistics both solvers and the scorer share.  All inputs
    f32 except the integer/bool streams."""
    D = n_dims
    f = mu.dtype

    def chunk_step(carry, ch):
        R, Q = carry
        z = (R, Q, jnp.zeros(D, f), jnp.zeros((D, D), f), jnp.zeros(D, f),
             jnp.zeros((), f), jnp.zeros(D, jnp.uint32))
        (R, Q, s0, S, W, ll, health), _ = lax.scan(
            functools.partial(_event_step, mu, alpha, beta), z, ch)
        return (R, Q), (s0, S, W, ll, health)

    carry0 = (jnp.zeros(D, f), jnp.zeros(D, f))
    _, (s0c, Sc, Wc, llc, hc) = lax.scan(
        chunk_step, carry0, (dt, dims, mask))
    health = lax.reduce(hc, jnp.uint32(0), jnp.bitwise_or, (0,))
    return llc.sum(), s0c.sum(0), Sc.sum(0), Wc.sum(0), health


@functools.partial(jax.jit, static_argnames=("n_dims",))
def _censored_mass(tail, dims, mask, counts, beta, n_dims: int):
    """``G_j = sum_{u_l = j} (1 - exp(-beta_j (T - t_l))) / beta_j`` —
    the per-dimension censored kernel mass, one vectorized segment-sum
    over the padded stream (pad entries are mask-gated to contribute 0).
    THE one definition of the compensator's excitation term: the
    likelihood scorer and the EM M-step both call it, so the objective
    can never drift between them.  Clamped at zero — f32 cancellation in
    ``counts - E`` must not manufacture a negative mass (and through it
    a negative alpha)."""
    e = jnp.where(mask.reshape(-1),
                  safe_exp(-beta[dims.reshape(-1)] * tail.reshape(-1)),
                  0.0)
    E = jax.ops.segment_sum(e, dims.reshape(-1), num_segments=n_dims)
    return safe_div(jnp.maximum(counts - E, 0.0), beta, when_zero=0.0)


def _ll_event_step(mu, alpha, beta, carry, ev):
    """Lean, differentiable twin of :func:`_event_step`: only the decay
    recursion + sum of log-intensities (the Frank-Wolfe objective's
    event term — no index-add accumulators beyond R, so the backward
    pass stays cheap)."""
    R, ll = carry
    dt, i, m = ev
    R = safe_exp(-beta * dt) * R
    lam = mu[i] + (alpha[i] * R).sum()
    mf = jnp.asarray(m, lam.dtype)
    ll = ll + mf * safe_log(lam)
    R = R.at[i].add(mf)
    return (R, ll), None


def _ll_events_fn(dt, dims, mask, mu, alpha, beta):
    """Differentiable sum of per-event log-intensities (traced under
    ``jax.grad`` by the Frank-Wolfe solver — not jitted here; the solver
    jits the whole objective)."""
    D = mu.shape[0]

    def chunk_step(carry, ch):
        return lax.scan(
            functools.partial(_ll_event_step, mu, alpha, beta), carry,
            ch)[0], None

    (_, ll), _ = lax.scan(
        chunk_step, (jnp.zeros(D, mu.dtype), jnp.zeros((), mu.dtype)),
        (dt, dims, mask))
    return ll


def _compensator_G(data: ChunkedEvents, beta):
    """``G_j`` over a host :class:`ChunkedEvents` (thin wrapper over
    :func:`_censored_mass`).  ``integral_0^T lambda_i`` then equals
    ``mu_i T + sum_j alpha_ij G_j``."""
    return _censored_mass(
        jnp.asarray(data.tail), jnp.asarray(data.dims),
        jnp.asarray(data.mask), jnp.asarray(data.counts, beta.dtype),
        beta, n_dims=data.n_dims)


def hawkes_loglik(data, mu, alpha, beta,
                  chunk_size: int = 4096) -> LoglikResult:
    """Exact log-likelihood of an event stream under an exponential-kernel
    multivariate Hawkes model — the scored metric both solvers optimize,
    callable standalone (model comparison, held-out scoring).

    ``data`` — :class:`~redqueen_tpu.learn.ingest.EventStream` or
    pre-chunked :class:`~redqueen_tpu.learn.ingest.ChunkedEvents`;
    ``mu`` f[D], ``alpha`` f[D, D] (jump convention), ``beta`` f[D]
    (decay per exciting dimension).  Runs the O(n) recursion on device
    (one compiled kernel per padded shape) and returns host scalars —
    ``jax.device_get`` is the one explicit transfer."""
    if isinstance(data, EventStream):
        data = chunk_events(data, chunk_size=chunk_size)
    D = data.n_dims
    mu = jnp.asarray(mu, jnp.float32).reshape(D)
    alpha = jnp.asarray(alpha, jnp.float32).reshape(D, D)
    beta = jnp.asarray(beta, jnp.float32).reshape(D)
    ll_ev, _s0, _S, _W, health = _stream_pass(
        jnp.asarray(data.dt), jnp.asarray(data.dims),
        jnp.asarray(data.mask), mu, alpha, beta, n_dims=D)
    G = _compensator_G(data, beta)
    comp = mu.sum() * data.span + (alpha * G[None, :]).sum()
    ll_host, comp_host, health_host = jax.device_get((ll_ev, comp, health))
    return LoglikResult(
        loglik=float(ll_host) - float(comp_host),
        loglik_events=float(ll_host), compensator=float(comp_host),
        health=np.asarray(health_host, np.uint32))
