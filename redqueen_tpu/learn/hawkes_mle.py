"""Corpus-scale multivariate Hawkes estimation: two solvers, one interface.

Fits ``(mu, alpha, beta)`` of the exponential-kernel multivariate Hawkes
model (``learn.loglik`` — the simulator's own parameterization, so a fit
closes the simulate→fit→control loop via ``learn.control``) from one
:class:`~redqueen_tpu.learn.ingest.EventStream`:

- ``solver="em"`` — MM/EM: the closed-form branching-ratio E-step rides
  the SAME O(n·D) decay scan as the likelihood (``loglik._stream_pass``
  aggregates responsibilities per exciting dimension — the D-pair sums
  are one fused vector op per event, the vmap-over-pairs laid out as
  arithmetic), and the M-step is closed-form:

      mu_i     <- S0_i / T
      alpha_ij <- S_ij / G_j            (G = censored kernel mass)
      beta_j   <- P_j / W_j             (weighted-lag exponential MLE,
                                         the standard MM surrogate)

- ``solver="fw"`` — Frank-Wolfe (arXiv:2212.06081): minimizes the exact
  NLL over ``mu in [0, mu_max]^D`` and branching-ratio rows
  ``a_i. in {a >= 0, sum_j a_ij <= rho < 1}`` (the scaled-simplex
  constraint that makes every iterate provably SUBCRITICAL — a learned
  model that cannot explode when simulated).  The linear-minimization
  oracle over box x simplex-cross-product is closed-form (one vertex
  pick per row), gradients come from ``jax.grad`` through the O(n) scan,
  and the duality gap is a convergence CERTIFICATE (the NLL is convex in
  (mu, a) at fixed beta).  ``beta`` is fixed from ``fw_beta_warmup`` EM
  iterations (or ``beta0``).

Both solvers are jitted with donated parameter carries and stream the
chunked event arrays through one compiled kernel per padded shape (no
recompilation across iterations or across same-bucket corpora — the
sweep layer's lane-batching discipline applied to fitting).  Device→host
syncs are BLOCKED: the objective trajectory is fetched once per
``sync_every`` iterations, never per step.

Fits are resumable and preempt-clean: ``ckpt_path`` lands an enveloped
``rq.learn.fit/1`` checkpoint (``learn.ckpt`` → ``runtime.integrity``)
every ``ckpt_every`` iterations, keyed by a fingerprint of the event
bytes + solver config; after each durable save the fitter heartbeats and
honors a pending SIGTERM/SIGINT exactly like ``run_sweep_checkpointed``.

Degenerate inputs quarantine per DIMENSION (``HawkesFit.health`` u32[D],
``runtime.numerics`` bits): a dimension whose intensity or parameters go
non-finite is sanitized to a safe fallback (Poisson-rate ``mu``, zeroed
``alpha`` row+column, unit ``beta``) and flagged — returned rates are
never NaN or negative.  Only when EVERY dimension dies does the fit
raise the typed :class:`FitError`.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import preempt as _preempt
from ..runtime import telemetry as _telemetry
from ..runtime.numerics import (
    BIT_NONFINITE_STATE,
    describe_health,
    safe_div,
)
from ..runtime.supervisor import heartbeat as _heartbeat
from . import ckpt as _ckpt
from .ingest import ChunkedEvents, EventStream, chunk_events
from .loglik import _censored_mass, _ll_events_fn, _stream_pass

__all__ = ["HawkesFit", "FitError", "fit_hawkes", "SOLVERS"]

SOLVERS = ("em", "fw")


class FitError(RuntimeError):
    """Every dimension of a fit died numerically (mirror of the sim
    driver's ``NumericalHealthError``, at the estimator boundary).
    Carries the per-dimension ``health`` bitmask and decoded
    ``reasons``; partial degeneracy never raises — sick dimensions are
    sanitized + flagged in ``HawkesFit.health`` instead."""

    def __init__(self, health, context: str = "hawkes fit"):
        self.health = np.atleast_1d(np.asarray(health))
        self.reasons = describe_health(self.health)
        dims = ", ".join(
            f"dim {i}: {'; '.join(r)}"
            for i, r in sorted(self.reasons.items())[:8])
        more = "" if len(self.reasons) <= 8 else (
            f" (+{len(self.reasons) - 8} more)")
        super().__init__(
            f"{context}: all {self.health.size} dimension(s) numerically "
            f"dead — {dims}{more}. The stream was host-validated, so the "
            f"trace is degenerate for this model (or parameters "
            f"diverged); inspect the stream or widen beta bounds.")


class HawkesFit(NamedTuple):
    """A fitted multivariate Hawkes model (host float64 arrays).

    ``alpha`` is the JUMP matrix — ``(mu[i], alpha[i, i], beta[i])``
    plugs straight into ``config.GraphBuilder.add_hawkes`` (which also
    accepts the fit object whole; ``learn.control`` is the loop-closer).
    ``health`` u32[D]: non-zero marks a sanitized/quarantined dimension
    whose values are fallbacks, not estimates.  ``loglik`` is the
    objective trajectory (log-likelihood, one entry per iteration,
    evaluated at the pre-update parameters); ``final_loglik`` scores the
    returned parameters exactly."""

    mu: np.ndarray         # f64[D]
    alpha: np.ndarray      # f64[D, D]
    beta: np.ndarray       # f64[D]
    health: np.ndarray     # u32[D]
    loglik: np.ndarray     # f64[n_iter]
    final_loglik: float
    converged: bool
    n_iter: int
    solver: str
    n_events: int
    n_dims: int
    t_end: float
    t_start: float

    def branching(self) -> np.ndarray:
        """Branching-ratio matrix ``alpha_ij / beta_j`` (expected direct
        offspring in dim i per event of dim j)."""
        return self.alpha / np.maximum(self.beta[None, :], 1e-300)


# ---------------------------------------------------------------------------
# EM / MM iteration (jitted, donated parameter carry)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_dims",),
                   donate_argnums=(4, 5, 6))
def _em_iter(dt, dims, mask, tail, mu, alpha, beta, counts, span,
             beta_floor, beta_cap, n_dims: int):
    """One EM sweep: E-step sufficient statistics from the shared O(n)
    scan, closed-form M-step.  Returns the NEW parameters plus the
    log-likelihood and per-dimension health AT THE OLD parameters (the
    pass that produced the statistics)."""
    ll_ev, s0, S, W, health = _stream_pass(dt, dims, mask, mu, alpha,
                                           beta, n_dims=n_dims)
    G = _censored_mass(tail, dims, mask, counts, beta, n_dims=n_dims)
    comp = mu.sum() * span + (alpha * G[None, :]).sum()
    mu_n = safe_div(s0, span, when_zero=0.0)
    alpha_n = safe_div(S, G[None, :], when_zero=0.0)
    P = S.sum(0)  # total triggered mass attributed to each source dim
    beta_n = jnp.clip(
        jnp.where(W > 0, safe_div(P, W, when_zero=0.0), beta),
        beta_floor, beta_cap)
    return mu_n, alpha_n, beta_n, ll_ev - comp, health


# ---------------------------------------------------------------------------
# Frank-Wolfe iteration (jitted, donated parameter carry)
# ---------------------------------------------------------------------------

#: Added to the FW step-schedule denominator: ``gamma_t = 2 / (t + 2 +
#: offset)``.  The classic ``2/(t+2)`` takes gamma_0 = 1 — a first step
#: that lands EXACTLY on a vertex, obliterating the EM warm start (and
#: measurably stalling low-mass dimensions at the boundary); any constant
#: offset keeps the O(1/t) guarantee while letting the warm start count.
FW_STEP_OFFSET = 8.0


@functools.partial(jax.jit, static_argnames=("n_dims",),
                   donate_argnums=(6, 7))
def _fw_iter(dt, dims, mask, G, mu_max, t, mu, a, beta, span, rho,
             n_dims: int):
    """One Frank-Wolfe step on the exact NLL over box x scaled-simplex.

    ``t`` is the (traced) iteration index — the offset ``2/(t + 2 +
    FW_STEP_OFFSET)`` schedule stays inside one compiled kernel for the
    whole fit.  Returns the new iterate, the NLL at the old iterate, and
    the duality gap ``<grad, x - s>`` (>= suboptimality for this convex
    objective — the stopping certificate)."""

    def nll(mu, a):
        alpha = a * beta[None, :]
        ll_ev = _ll_events_fn(dt, dims, mask, mu, alpha, beta)
        comp = mu.sum() * span + (alpha * G[None, :]).sum()
        return comp - ll_ev

    val, (g_mu, g_a) = jax.value_and_grad(nll, argnums=(0, 1))(mu, a)
    # LMO, closed form per block: box vertex for mu, a rho-scaled
    # simplex vertex (or the origin) per alpha row.
    s_mu = jnp.where(g_mu < 0, mu_max, 0.0)
    row_min = g_a.min(axis=1)
    pick = jax.nn.one_hot(jnp.argmin(g_a, axis=1), n_dims, dtype=a.dtype)
    s_a = jnp.where((row_min < 0)[:, None], rho * pick,
                    jnp.zeros_like(pick))
    gap = (g_mu * (mu - s_mu)).sum() + (g_a * (a - s_a)).sum()
    gamma = safe_div(2.0, t + 2.0 + FW_STEP_OFFSET, when_zero=0.0)
    return (mu + gamma * (s_mu - mu), a + gamma * (s_a - a), val, gap)


# ---------------------------------------------------------------------------
# Host-side driver
# ---------------------------------------------------------------------------

def _sanitize(mu, alpha, beta, counts64, span, prior_bits):
    """Quarantine sick dimensions (host side, at sync boundaries): a
    dimension with non-finite parameters — or one already flagged by the
    scan's per-dimension health word (quarantine is STICKY, like the sim
    kernel's frozen lanes) — gets fallback parameters: Poisson-rate
    ``mu``, zeroed ``alpha`` row+column, unit ``beta``, plus its health
    bit.  Returns ``(mu, alpha, beta, bits)`` with rates guaranteed
    finite and non-negative."""
    mu = np.asarray(mu, np.float64).copy()
    alpha = np.asarray(alpha, np.float64).copy()
    beta = np.asarray(beta, np.float64).copy()
    bad = ~(np.isfinite(mu) & (mu >= 0))
    bad |= ~np.isfinite(alpha).all(axis=1) | ~np.isfinite(alpha).all(axis=0)
    bad |= ~(np.isfinite(beta) & (beta > 0))
    bits = np.asarray(prior_bits, np.uint32).copy()
    bits[bad] |= np.uint32(BIT_NONFINITE_STATE)
    bad |= bits != 0
    if bad.any():
        fallback_mu = np.clip(
            counts64 / max(span, 1e-300), 0.0, np.finfo(np.float32).max)
        mu[bad] = fallback_mu[bad]
        alpha[bad, :] = 0.0
        alpha[:, bad] = 0.0
        beta[bad] = 1.0
    # Numerical dust below zero is clipped silently (not degeneracy).
    alpha = np.maximum(alpha, 0.0)
    mu = np.maximum(mu, 0.0)
    return mu, alpha, beta, bits


def _default_beta0(counts64, span, beta_floor, beta_cap):
    """Decay init: the reciprocal mean own-gap per dimension (a dim's
    rate scale) — the weighted-lag M-step refines it from there."""
    rate = counts64 / max(span, 1e-300)
    return np.clip(np.where(rate > 0, rate, 1.0), beta_floor, beta_cap)


def fit_hawkes(data, solver: str = "em", max_iters: int = 200,
               tol: float = 1e-4, chunk_size: int = 4096,
               beta0=None, beta_floor: float = 1e-3,
               beta_cap: float = 1e4, rho: float = 0.8,
               mu_max_scale: float = 4.0, fw_beta_warmup: int = 30,
               sync_every: int = 8, ckpt_path: Optional[str] = None,
               ckpt_every: int = 32) -> HawkesFit:
    """Fit a multivariate exponential-kernel Hawkes model to one event
    stream.  See the module docstring for the two solvers.

    ``data`` — :class:`~redqueen_tpu.learn.ingest.EventStream` (or
    pre-chunked :class:`~redqueen_tpu.learn.ingest.ChunkedEvents`).
    ``tol`` — EM: relative log-likelihood improvement; FW: relative
    duality gap.  ``beta0`` — initial (EM) / fixed (FW, unless the EM
    warm-up runs) decay, scalar or [D].  ``ckpt_path`` — enveloped
    ``rq.learn.fit/1`` resume point, written every ``ckpt_every``
    iterations (a killed fit rerun with the same arguments continues; a
    changed stream or config restarts — fingerprinted).
    """
    if solver not in SOLVERS:
        raise ValueError(f"unknown solver {solver!r} (want "
                         f"{'|'.join(SOLVERS)})")
    if max_iters < 1:
        raise ValueError(f"max_iters must be >= 1, got {max_iters}")
    if not 0.0 < rho < 1.0:
        raise ValueError(f"rho must be in (0, 1) — the simplex scale IS "
                         f"the subcriticality guarantee; got {rho!r}")
    if isinstance(data, EventStream):
        data = chunk_events(data, chunk_size=chunk_size)
    if not isinstance(data, ChunkedEvents):
        raise TypeError(f"data must be EventStream or ChunkedEvents, "
                        f"got {type(data).__name__}")
    D = data.n_dims
    span = float(data.span)
    counts64 = np.asarray(data.counts, np.float64)

    beta0_arr = (
        _default_beta0(counts64, span, beta_floor, beta_cap)
        if beta0 is None
        else np.broadcast_to(np.asarray(beta0, np.float64), (D,)).copy())
    if not (np.isfinite(beta0_arr).all() and (beta0_arr > 0).all()):
        raise ValueError(f"beta0 must be finite and > 0, got {beta0_arr}")

    fp = None
    if ckpt_path is not None:
        config = dict(
            solver=solver, chunk_size=int(chunk_size), n_dims=int(D),
            span=float(span), beta_floor=float(beta_floor),
            beta_cap=float(beta_cap), rho=float(rho),
            mu_max_scale=float(mu_max_scale),
            fw_beta_warmup=int(fw_beta_warmup),
            n_events=int(data.n_events),
            beta0=("default" if beta0 is None
                   else beta0_arr.tobytes().hex()),
        )
        # mask is hashed too: a stream extended by one dt=0 trailing
        # event pads to byte-identical dt/dims and differs ONLY in the
        # mask — without it, two different streams could share a resume
        # trajectory.  (Only computed when checkpointing: hashing 100+MB
        # of corpus chunks has no other consumer.)
        fp = _ckpt.fingerprint_arrays(config, data.dt, data.dims,
                                      data.mask)

    # Device-resident stream (converted once — iterations then move no
    # event data at all) + initial parameters.
    dt = jnp.asarray(data.dt)
    dims = jnp.asarray(data.dims)
    mask = jnp.asarray(data.mask)
    tail = jnp.asarray(data.tail)
    counts = jnp.asarray(counts64, jnp.float32)
    mu0 = 0.5 * counts64 / max(span, 1e-300)
    alpha0 = np.broadcast_to((0.1 * beta0_arr / max(D, 1))[None, :],
                             (D, D)).copy()

    start_it, curve, bits = 0, [], np.zeros(D, np.uint32)
    params = (mu0, alpha0, beta0_arr)
    loaded = (_ckpt.load_fit(ckpt_path, fp)
              if ckpt_path is not None else None)
    if loaded is not None:
        start_it, arrays, meta = loaded
        params = (arrays["mu"], arrays["alpha"], arrays["beta"])
        curve = list(np.asarray(arrays["curve"], np.float64))
        bits = np.asarray(arrays["health"], np.uint32)

    def save(it, params_np, extra_meta=None):
        if ckpt_path is None:
            return
        mu_c, alpha_c, beta_c = params_np
        _ckpt.save_fit(
            ckpt_path, fp, it,
            {"mu": mu_c, "alpha": alpha_c, "beta": beta_c,
             "curve": np.asarray(curve, np.float64), "health": bits},
            meta=dict(solver=solver, n_dims=D,
                      n_events=data.n_events, **(extra_meta or {})))
        # Durable boundary: prove progress, then honor a pending
        # SIGTERM/SIGINT (the resumed fit continues from this artifact).
        _heartbeat()
        _preempt.check_preempt(f"fit_hawkes[{solver}] iteration {it}")

    # The fit's root span: every per-iteration / sync-boundary span
    # below chains under it, so `rqtrace` answers "where did this
    # EM/FW fit spend its time" without a hand-inserted timer.
    with _telemetry.span("learn.fit", solver=solver, n_dims=int(D),
                         n_events=int(data.n_events)) as fit_sp:
        if solver == "em":
            fit_arrays, n_iter, converged = _run_em(
                dt, dims, mask, tail, counts, counts64, span, D, params,
                start_it, max_iters, tol, beta_floor, beta_cap,
                sync_every, ckpt_every, curve, bits, save)
        else:
            fit_arrays, n_iter, converged = _run_fw(
                dt, dims, mask, tail, counts, counts64, span, D,
                params, start_it, max_iters, tol, beta_floor, beta_cap,
                rho, mu_max_scale, fw_beta_warmup, sync_every,
                ckpt_every, curve, bits, save)
        fit_sp.set(n_iter=int(n_iter), converged=bool(converged))
    mu_f, alpha_f, beta_f = fit_arrays

    def _score(mu_s, alpha_s, beta_s):
        """Exact log-likelihood + scan health at host params (one shared
        pass + compensator; one blocked transfer)."""
        mu32 = jnp.asarray(mu_s, jnp.float32)
        a32 = jnp.asarray(alpha_s, jnp.float32)
        b32 = jnp.asarray(beta_s, jnp.float32)
        ll_ev, _s0, _S, _W, health_dev = _stream_pass(
            dt, dims, mask, mu32, a32, b32, n_dims=D)
        G = _censored_mass(tail, dims, mask, counts, b32, n_dims=D)
        comp = mu32.sum() * span + (a32 * G[None, :]).sum()
        ll_host, comp_host, health_host = jax.device_get(
            (ll_ev, comp, health_dev))
        return (float(ll_host) - float(comp_host),
                np.asarray(health_host, np.uint32))

    # Final exact score (the trajectory's entries are pre-update), then
    # sanitize; if quarantine changed any parameter, score ONCE more so
    # final_loglik describes exactly the RETURNED parameters — never a
    # diverged pre-fallback iterate (healthy fits pay no second pass).
    final_ll, health_host = _score(mu_f, alpha_f, beta_f)
    bits = bits | health_host
    pre = (mu_f.copy(), alpha_f.copy(), beta_f.copy())
    mu_f, alpha_f, beta_f, bits = _sanitize(
        mu_f, alpha_f, beta_f, counts64, span, bits)
    if D and (bits != 0).all():
        raise FitError(bits, context=f"fit_hawkes[{solver}]")
    if not all(np.array_equal(a, b)
               for a, b in zip(pre, (mu_f, alpha_f, beta_f))):
        final_ll, _rescored = _score(mu_f, alpha_f, beta_f)

    return HawkesFit(
        mu=mu_f, alpha=alpha_f, beta=beta_f, health=bits,
        loglik=np.asarray(curve, np.float64),
        final_loglik=final_ll,
        converged=bool(converged), n_iter=int(n_iter), solver=solver,
        n_events=int(data.n_events), n_dims=int(D),
        t_end=float(data.t_end), t_start=float(data.t_start))


def _run_em(dt, dims, mask, tail, counts, counts64, span, D, params,
            start_it, max_iters, tol, beta_floor, beta_cap, sync_every,
            ckpt_every, curve, bits, save):
    mu = jnp.asarray(params[0], jnp.float32)
    alpha = jnp.asarray(params[1], jnp.float32)
    beta = jnp.asarray(params[2], jnp.float32)
    pending = []
    converged = False
    it = start_it
    while it < max_iters and not converged:
        # Per-iteration span = the jitted EM sweep's ENQUEUE; the
        # blocked device wait is the sync span at the window boundary
        # below — the sync-boundary split the learn arc's breakdowns
        # need (iterations between syncs cost host-dispatch only).
        with _telemetry.span("learn.em.iter") as isp:
            isp.set(it=it)
            mu, alpha, beta, ll, health = _em_iter(
                dt, dims, mask, tail, mu, alpha, beta, counts,
                jnp.float32(span), jnp.float32(beta_floor),
                jnp.float32(beta_cap), n_dims=D)
        pending.append((ll, health))
        it += 1
        if len(pending) >= sync_every or it >= max_iters:
            # ONE blocked transfer per sync window (never per step): the
            # trajectory tail the convergence check needs, the scan's
            # per-dimension health words, and the tiny parameter carry.
            with _telemetry.span("learn.em.sync") as ssp:
                ssp.set(iters=len(pending))
                vals, mu_h, alpha_h, beta_h = jax.device_get(  # rqlint: disable=RQ701,RQ702 one blocked sync per sync_every iterations
                    (pending, mu, alpha, beta))
            curve.extend(float(v) for v, _h in vals)
            scan_bits = np.zeros_like(bits)
            for _v, h in vals:
                scan_bits |= np.asarray(h, np.uint32)
            pending = []
            mu_h, alpha_h, beta_h, bits_new = _sanitize(
                mu_h, alpha_h, beta_h, counts64, span, bits | scan_bits)
            if (bits_new != bits).any():
                bits[:] = bits_new
                if (bits != 0).all():
                    raise FitError(bits, context="fit_hawkes[em]")
                mu = jnp.asarray(mu_h, jnp.float32)
                alpha = jnp.asarray(alpha_h, jnp.float32)
                beta = jnp.asarray(beta_h, jnp.float32)
            if len(curve) >= 2:
                converged = (abs(curve[-1] - curve[-2])
                             <= tol * (1.0 + abs(curve[-2])))
            if converged or it >= max_iters or (
                    ckpt_every and it % ckpt_every < sync_every):
                save(it, (mu_h, alpha_h, beta_h))
    mu_h, alpha_h, beta_h = jax.device_get((mu, alpha, beta))  # rqlint: disable=RQ701 final parameter fetch: one transfer per fit
    return ((np.asarray(mu_h, np.float64),
             np.asarray(alpha_h, np.float64),
             np.asarray(beta_h, np.float64)), it, converged)


def _run_fw(dt, dims, mask, tail, counts, counts64, span, D,
            params, start_it, max_iters, tol, beta_floor, beta_cap, rho,
            mu_max_scale, fw_beta_warmup, sync_every, ckpt_every, curve,
            bits, save):
    mu_np, alpha_np, beta_np = params
    if start_it == 0 and fw_beta_warmup > 0:
        # Decay warm-start: a few EM sweeps pin beta (FW then optimizes
        # the convex (mu, a) problem at that fixed decay).
        mu = jnp.asarray(mu_np, jnp.float32)
        alpha = jnp.asarray(alpha_np, jnp.float32)
        beta = jnp.asarray(beta_np, jnp.float32)
        with _telemetry.span("learn.fw.warmup") as wsp:
            wsp.set(iters=int(fw_beta_warmup))
            for _ in range(int(fw_beta_warmup)):
                mu, alpha, beta, _ll, _h = _em_iter(
                    dt, dims, mask, tail, mu, alpha, beta,
                    counts, jnp.float32(span), jnp.float32(beta_floor),
                    jnp.float32(beta_cap), n_dims=D)
            mu_np, alpha_np, beta_np = (
                np.asarray(leaf, np.float64)
                for leaf in jax.device_get((mu, alpha, beta)))  # rqlint: disable=RQ701 one blocked transfer: the warm-started decay crosses to host exactly once
        mu_np, alpha_np, beta_np, bits[:] = _sanitize(
            mu_np, alpha_np, beta_np, counts64, span, bits)
    beta = jnp.asarray(beta_np, jnp.float32)
    G = _censored_mass(tail, dims, mask, counts, beta, n_dims=D)
    mu_max = jnp.asarray(
        mu_max_scale * (counts64 + 1.0) / max(span, 1e-300), jnp.float32)
    mu = jnp.asarray(mu_np, jnp.float32)
    # Branching-ratio iterate, projected into the feasible simplex (the
    # warm start may sit outside it).
    a_np = alpha_np / np.maximum(beta_np[None, :], 1e-300)
    row = a_np.sum(axis=1, keepdims=True)
    # Tolerance-gated: an f32 iterate can overshoot the simplex by an
    # ulp; rescaling THAT would perturb a resumed fit away from the
    # uninterrupted trajectory for no feasibility gain.
    a_np = np.where(row > rho * (1.0 + 1e-6),
                    a_np * (rho / np.maximum(row, 1e-300)), a_np)
    a = jnp.asarray(a_np, jnp.float32)

    pending = []
    converged = False
    it = start_it
    while it < max_iters and not converged:
        # Same enqueue/sync split as the EM loop (see _run_em).
        with _telemetry.span("learn.fw.iter") as isp:
            isp.set(it=it)
            mu, a, nll, gap = _fw_iter(
                dt, dims, mask, G, mu_max, jnp.float32(it), mu, a, beta,
                jnp.float32(span), jnp.float32(rho), n_dims=D)
        pending.append((nll, gap))
        it += 1
        if len(pending) >= sync_every or it >= max_iters:
            with _telemetry.span("learn.fw.sync") as ssp:
                ssp.set(iters=len(pending))
                vals, mu_h, a_h = jax.device_get((pending, mu, a))  # rqlint: disable=RQ701,RQ702 one blocked sync per sync_every iterations
            last_gap = float(vals[-1][1])
            last_nll = float(vals[-1][0])
            curve.extend(-float(v[0]) for v in vals)
            pending = []
            alpha_h = np.asarray(a_h, np.float64) * beta_np[None, :]
            mu_h, alpha_h, beta_s, bits_new = _sanitize(
                mu_h, alpha_h, beta_np, counts64, span, bits)
            if (bits_new != bits).any():
                bits[:] = bits_new
                if (bits != 0).all():
                    raise FitError(bits, context="fit_hawkes[fw]")
                mu = jnp.asarray(mu_h, jnp.float32)
                a = jnp.asarray(
                    alpha_h / np.maximum(beta_s[None, :], 1e-300),
                    jnp.float32)
            converged = last_gap <= tol * (1.0 + abs(last_nll))
            if converged or it >= max_iters or (
                    ckpt_every and it % ckpt_every < sync_every):
                save(it, (mu_h, alpha_h, beta_np),
                     extra_meta={"phase": "fw"})
    mu_h, a_h = jax.device_get((mu, a))  # rqlint: disable=RQ701 final parameter fetch: one transfer per fit
    alpha_h = np.asarray(a_h, np.float64) * beta_np[None, :]
    return ((np.asarray(mu_h, np.float64), alpha_h,
             np.asarray(beta_np, np.float64)), it, converged)
