"""Enveloped fit checkpoints — ``rq.learn.fit/1``.

One artifact format for every resumable fit in the repo (the Hawkes
solvers here, ``models.rmtpp.fit``): a checksummed NPZ envelope
(``runtime.integrity.savez`` — atomic rename + sha256 verify-on-read +
quarantine, exactly the sweep-chunk machinery) holding the fit's array
state plus a JSON meta record, keyed by a FINGERPRINT of everything that
determines the trajectory (data bytes + solver configuration).  A resumed
fit only trusts a checkpoint whose fingerprint matches bit-for-bit;
stale (edited inputs) loads as None and the fit restarts — silently
mixing trajectories is the failure mode this prevents.  A corrupt file
is quarantined by ``load_npz`` (``*.corrupt-<ts>`` + report) and the fit
restarts too: corruption is never a crash and never trusted.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..runtime import integrity as _integrity

__all__ = ["FIT_SCHEMA", "save_fit", "load_fit", "fingerprint_arrays"]

FIT_SCHEMA = "rq.learn.fit/1"
_META_KEY = "fit_meta"


def fingerprint_arrays(config: Dict[str, Any], *arrays) -> str:
    """Content hash of a fit's inputs: the solver config (repr of a
    key-sorted dict — keep values primitive) plus every data array's
    dtype + shape + raw bytes.  Same canonical-bytes idiom as the sweep
    chunk fingerprint."""
    h = hashlib.sha256()
    h.update(repr(sorted(config.items())).encode())
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a))
        h.update(str((a.dtype.str, a.shape)).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


def save_fit(path: str, fingerprint: str, step: int,
             arrays: Dict[str, np.ndarray],
             meta: Optional[Dict[str, Any]] = None) -> None:
    """Atomically land a fit checkpoint (called at durable boundaries —
    the fitter heartbeats + honors preemption right after, like
    ``run_sweep_checkpointed`` chunks)."""
    if _META_KEY in arrays:
        raise ValueError(f"array name {_META_KEY!r} is reserved")
    record = {"fingerprint": str(fingerprint), "step": int(step),
              "meta": dict(meta or {})}
    _integrity.savez(
        path, schema=FIT_SCHEMA,
        **{k: np.asarray(v) for k, v in arrays.items()},
        **{_META_KEY: np.asarray(json.dumps(record))})


def load_fit(path: str, fingerprint: str
             ) -> Optional[Tuple[int, Dict[str, np.ndarray],
                                 Dict[str, Any]]]:
    """Load a checkpoint for THIS fit; returns ``(step, arrays, meta)``
    or None when there is nothing trustworthy to resume from (missing
    file; corrupt → quarantined by ``load_npz``; schema or fingerprint
    mismatch → stale, left on disk untouched)."""
    try:
        z = _integrity.load_npz(path, schema=FIT_SCHEMA,
                                quarantine_schema_mismatch=False)
    except FileNotFoundError:
        return None
    except _integrity.CorruptArtifactError:
        # Quarantined (or schema-stale, left in place): recompute.
        return None
    try:
        record = json.loads(str(z.pop(_META_KEY)))
        step = int(record["step"])
        fp = str(record["fingerprint"])
        meta = dict(record.get("meta", {}))
    except (KeyError, ValueError, TypeError):
        return None  # layout drift without a schema bump: stale
    if fp != str(fingerprint):
        return None  # different data/config: never mix trajectories
    return step, z, meta
