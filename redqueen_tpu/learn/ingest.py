"""Event-log adapters: every event source the repo produces → one fit format.

The estimator (``learn.hawkes_mle``) consumes a single canonical shape —
:class:`EventStream`, one globally time-ordered multivariate event stream
``(times f64[n], dims i32[n])`` over ``n_dims`` dimensions — chunked into
fixed-size padded device arrays (:class:`ChunkedEvents`) so corpus-scale
traces stream through ONE compiled kernel (pad + mask; the chunk count is
bucketed, so compile count stays bounded the same way the sweep layer's
lane batching bounds it).

Three producers, three adapters:

- :func:`from_event_log` — the simulator's own output
  (:class:`~redqueen_tpu.sim.EventLog`): the simulate→fit→recover loop.
- :func:`from_traces` — per-user trace lists (``data.traces.load_csv``,
  i.e. the native C++ loader's corpus rows).  A 100k-user corpus cannot be
  a 100k-dimensional Hawkes (the alpha matrix alone would be 10^10
  entries — the corpus-scale regime of arXiv:2002.12501): ``n_dims``
  groups users into hash-assigned dimensions, so the fit learns the
  group-level excitation structure at any corpus size.
- :func:`from_journal` — serving journal segments (``serving.journal``
  records carry the ingested ``times``/``feeds`` of every applied batch),
  for both single-runtime dirs and sharded ``shard-KKKK/`` cluster dirs:
  fit the feeds a serving deployment actually saw.

Host-side code: times stay float64 here; the kernel consumes per-event
DIFFERENCES (``dt``, ``tail``) computed in f64 and cast to f32 — absolute
corpus timestamps would quantize consecutive-event gaps at f32.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import numpy as np

__all__ = [
    "EventStream",
    "ChunkedEvents",
    "chunk_events",
    "from_event_log",
    "from_traces",
    "from_journal",
    "StreamValidationError",
]


class StreamValidationError(ValueError):
    """An event stream failed host-side domain validation (non-finite or
    decreasing times, out-of-range dimension ids, a horizon before the
    last event) — the validated boundary of the fit, mirroring
    ``config.ConfigValidationError`` for simulation specs."""


class EventStream(NamedTuple):
    """One multivariate point-process realization on ``[t_start, t_end]``.

    ``times`` f64[n] non-decreasing, ``dims`` i32[n] in ``[0, n_dims)``.
    The stream is the *sufficient statistic* the estimator sees — every
    adapter below reduces to this."""

    times: np.ndarray
    dims: np.ndarray
    n_dims: int
    t_end: float
    t_start: float = 0.0

    @property
    def n_events(self) -> int:
        return int(len(self.times))

    def counts(self) -> np.ndarray:
        """Events per dimension, f64[n_dims]."""
        return np.bincount(self.dims, minlength=self.n_dims).astype(
            np.float64)


def _validate_stream(times: np.ndarray, dims: np.ndarray, n_dims: int,
                     t_end: float, t_start: float) -> None:
    if n_dims < 1:
        raise StreamValidationError(f"n_dims must be >= 1, got {n_dims}")
    if not (np.isfinite(t_end) and np.isfinite(t_start)
            and t_end > t_start):
        raise StreamValidationError(
            f"need finite t_end > t_start, got [{t_start!r}, {t_end!r}]")
    if times.shape != dims.shape or times.ndim != 1:
        raise StreamValidationError(
            f"times/dims must be equal-length 1-D, got {times.shape} vs "
            f"{dims.shape}")
    if len(times):
        if not np.isfinite(times).all():
            i = int(np.flatnonzero(~np.isfinite(times))[0])
            raise StreamValidationError(
                f"times must be finite, got {times[i]!r} at event {i}")
        if not np.all(np.diff(times) >= 0):
            i = int(np.flatnonzero(np.diff(times) < 0)[0])
            raise StreamValidationError(
                f"times must be non-decreasing, but times[{i + 1}] = "
                f"{times[i + 1]!r} < times[{i}] = {times[i]!r} — merge/"
                f"sort the stream before fitting")
        if float(times[0]) < t_start or float(times[-1]) > t_end:
            raise StreamValidationError(
                f"events [{times[0]!r}, {times[-1]!r}] fall outside the "
                f"window [{t_start!r}, {t_end!r}] — pass the window the "
                f"stream was observed on (the compensator integrates it)")
        bad = (dims < 0) | (dims >= n_dims)
        if bad.any():
            i = int(np.flatnonzero(bad)[0])
            raise StreamValidationError(
                f"dims must lie in [0, {n_dims}), got {int(dims[i])} at "
                f"event {i}")


def make_stream(times, dims, n_dims: int, t_end: float,
                t_start: float = 0.0) -> EventStream:
    """Validated :class:`EventStream` constructor (every adapter funnels
    through here; fit code may assume a stream is well-formed)."""
    times = np.asarray(times, np.float64)
    dims = np.asarray(dims, np.int32)
    _validate_stream(times, dims, int(n_dims), float(t_end),
                     float(t_start))
    return EventStream(times=times, dims=dims, n_dims=int(n_dims),
                       t_end=float(t_end), t_start=float(t_start))


class ChunkedEvents(NamedTuple):
    """Device-ready fit format: the stream reshaped to ``[C, K]`` padded
    chunks of ``K`` events (pad rides at the tail: ``dt = tail = 0``,
    ``mask = False`` — an exact no-op in the decay recursion).

    ``dt`` is the f32 gap since the previous event (``dt[0]`` from
    ``t_start``) and ``tail`` the f32 time to the horizon (``t_end - t``)
    — both differenced in f64 on host first, so corpus-scale absolute
    timestamps never meet f32.  ``C`` is bucketed (pow2 below 256
    chunks, multiples of 256 above): unequal corpora land on a bounded
    set of compiled shapes with <~10% pad waste at corpus scale."""

    dt: np.ndarray      # f32[C, K]
    dims: np.ndarray    # i32[C, K]
    mask: np.ndarray    # bool[C, K]
    tail: np.ndarray    # f32[C, K]
    counts: np.ndarray  # f64[D] events per dimension
    n_dims: int
    n_events: int
    t_end: float
    t_start: float

    @property
    def span(self) -> float:
        """Observation-window length T the compensator integrates."""
        return self.t_end - self.t_start


def _next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length() if n > 1 else 1


# Chunk-count bucketing: pow2 below the knee (few shapes for small
# streams), multiples of the knee above it (a corpus at C=2095 pads to
# 2304, ~10% waste — pow2 there would pad to 4096 and DOUBLE every
# iteration's scan work).  Compile count stays bounded either way.
_CHUNK_BUCKET = 256


def _pad_chunks(c: int) -> int:
    if c <= _CHUNK_BUCKET:
        return _next_pow2(c)
    return _CHUNK_BUCKET * ((c + _CHUNK_BUCKET - 1) // _CHUNK_BUCKET)


def chunk_events(stream: EventStream, chunk_size: int = 4096
                 ) -> ChunkedEvents:
    """Pad + mask + reshape a stream into :class:`ChunkedEvents`."""
    if chunk_size < 1:
        raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
    n = stream.n_events
    K = int(chunk_size)
    C = _pad_chunks(max((n + K - 1) // K, 1))
    N = C * K
    dt64 = np.diff(stream.times, prepend=stream.t_start)
    tail64 = stream.t_end - stream.times
    dt = np.zeros(N, np.float32)
    tail = np.zeros(N, np.float32)
    dims = np.zeros(N, np.int32)
    mask = np.zeros(N, bool)
    dt[:n] = dt64
    tail[:n] = tail64
    dims[:n] = stream.dims
    mask[:n] = True
    return ChunkedEvents(
        dt=dt.reshape(C, K), dims=dims.reshape(C, K),
        mask=mask.reshape(C, K), tail=tail.reshape(C, K),
        counts=stream.counts(), n_dims=stream.n_dims, n_events=n,
        t_end=stream.t_end, t_start=stream.t_start)


# ---------------------------------------------------------------------------
# Adapters
# ---------------------------------------------------------------------------

def from_event_log(log, sources: Optional[Sequence[int]] = None,
                   lane: Optional[int] = None) -> EventStream:
    """Simulator :class:`~redqueen_tpu.sim.EventLog` → stream.

    ``sources`` selects which source rows become fit dimensions (dim k =
    ``sources[k]``; default: every source that emitted at least one
    event, in row order) — pass the Hawkes wall rows to fit the walls
    without the controlled broadcaster's posts polluting the estimate.
    ``lane`` picks one lane of a batched log (required when batched).
    """
    import jax

    times, srcs, n_events = jax.device_get(
        (log.times, log.srcs, log.n_events))
    times = np.asarray(times)
    srcs = np.asarray(srcs)
    if times.ndim == 2:
        if lane is None:
            raise ValueError(
                f"batched EventLog ({times.shape[0]} lanes): pass lane=")
        times, srcs = times[lane], srcs[lane]
        n_events = np.asarray(n_events).reshape(-1)[lane]
    n = int(n_events)
    times, srcs = times[:n].astype(np.float64), srcs[:n].astype(np.int64)
    if sources is None:
        sources = sorted(set(int(s) for s in srcs))
    sources = [int(s) for s in sources]
    if not sources:
        raise StreamValidationError(
            "no sources selected (empty log?) — nothing to fit")
    lut = np.full(int(max(max(sources), srcs.max(initial=0))) + 1, -1,
                  np.int64)
    lut[sources] = np.arange(len(sources))
    dim = lut[srcs]
    keep = dim >= 0
    return make_stream(times[keep], dim[keep], len(sources),
                       t_end=float(log.cfg.end_time),
                       t_start=float(log.cfg.start_time))


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Deterministic 64-bit mix — user→dimension assignment that is
    stable across runs and processes, never Python ``hash``.  ONE
    implementation repo-wide: this is the serving cluster's edge
    partitioner (``serving.cluster._mix64``), so grouping here can never
    silently diverge from shard partitioning."""
    from ..serving.cluster import _mix64

    return _mix64(x)


def _group_dims(ids: np.ndarray, n_ids: int, n_dims: Optional[int],
                assign: str):
    """Map entity ids (users, feeds) onto fit dimensions: identity when
    ``n_dims`` covers them all, else splitmix64-hash or modulo grouping
    (both stable across runs/processes)."""
    if n_dims is None or int(n_dims) >= n_ids:
        return ids, max(int(n_ids), 1)
    D = int(n_dims)
    if assign == "hash":
        return ((_splitmix64(ids.astype(np.uint64)) % np.uint64(D))
                .astype(np.int32), D)
    if assign == "modulo":
        return (ids % D).astype(np.int32), D
    raise ValueError(f"unknown assign {assign!r} (want hash|modulo)")


def _window(times: np.ndarray, t_end: Optional[float],
            t_start: Optional[float]):
    """Default observation window for absolute-timestamp corpora.  The
    compensator integrates the WHOLE window, so a corpus observed over
    ``[t0, t1]`` must say so: with epoch-scale timestamps the default
    ``t_start=0`` would charge a huge dead ``[0, t_first]`` interval and
    bias every base rate toward zero — pass the true window."""
    if t_end is None:
        t_end = float(times[-1]) if len(times) else 1.0
    if t_start is None:
        t_start = min(float(times[0]), 0.0) if len(times) else 0.0
    return float(t_end), float(t_start)


def from_traces(traces: List[np.ndarray], n_dims: Optional[int] = None,
                t_end: Optional[float] = None, assign: str = "hash",
                max_rows: Optional[int] = None,
                t_start: Optional[float] = None) -> EventStream:
    """Per-user trace lists (the ``data.traces.load_csv`` / native-loader
    corpus format) → stream.

    ``n_dims=None`` keeps one dimension per user (only sane for small
    corpora — the alpha matrix is ``D x D``); otherwise users are grouped
    into ``n_dims`` dimensions: ``assign="hash"`` (splitmix64 of the user
    index — balanced in expectation, stable) or ``"modulo"``.
    ``max_rows`` fits a time-prefix of the merged stream (the earliest
    rows, like ``serving.corpus``).  ``(t_start, t_end)`` is the
    observation window the compensator integrates — it defaults to
    ``[min(t_first, 0), t_last]``, which is right for windows anchored at
    zero (the synthetic corpora) but WRONG for absolute epoch timestamps:
    there, pass the corpus's real observation window explicitly, or the
    fit charges the dead ``[0, t_first]`` span and biases ``mu`` low."""
    from ..serving.corpus import merge_traces

    times, users = merge_traces(traces, max_rows=max_rows)
    dims, D = _group_dims(users, max(len(traces), 1), n_dims, assign)
    t_end, t_start = _window(times, t_end, t_start)
    return make_stream(times, dims, D, t_end=t_end, t_start=t_start)


def from_journal(dir: str, n_dims: Optional[int] = None,
                 t_end: Optional[float] = None, assign: str = "hash",
                 t_start: Optional[float] = None) -> EventStream:
    """Serving journal → stream: replay + verify every retained record
    (``serving.journal.replay`` — rotated segments then the live file,
    checksum-enveloped per record) of a runtime dir, or of every
    ``shard-KKKK/`` under a sharded cluster dir, and fit the ingested
    ``(times, feeds)`` they journaled.  Feeds group into ``n_dims``
    dimensions exactly like :func:`from_traces` users; the
    ``(t_start, t_end)`` window defaults/caveats are
    :func:`from_traces`'s too.

    Shard journals record shard-LOCAL feed indices (the router maps
    global feed → local slot before submit), so each shard's ids are
    namespaced by its directory here — shard 0's feed 3 and shard 1's
    feed 3 are DIFFERENT entities and never collapse into one
    dimension."""
    import glob as _glob
    import os

    from ..serving.journal import JOURNAL_FILENAME, replay as journal_replay

    shard_dirs = sorted(_glob.glob(os.path.join(dir, "shard-[0-9]*")))
    roots = shard_dirs or [dir]
    times_l: List[np.ndarray] = []
    feeds_l: List[np.ndarray] = []
    base = 0
    for root in roots:
        records, _torn = journal_replay(
            os.path.join(root, JOURNAL_FILENAME),
            quarantine_torn_tail=False)
        top = -1
        for rec in records:
            if "feeds" not in rec:
                # Parameter-install (epoch) records share the journal
                # stream with batch records but carry no events.
                continue
            f = np.asarray(rec["feeds"], np.int64)
            times_l.append(np.asarray(rec["times"], np.float64))
            feeds_l.append(f + base)
            if len(f):
                top = max(top, int(f.max()))
        base += top + 1
    if times_l:
        times = np.concatenate(times_l)
        feeds = np.concatenate(feeds_l)
    else:
        times = np.empty(0, np.float64)
        feeds = np.empty(0, np.int64)
    order = np.argsort(times, kind="stable")
    times, feeds = times[order], feeds[order]
    n_ids = int(feeds.max()) + 1 if len(feeds) else 1
    dims, D = _group_dims(feeds, n_ids, n_dims, assign)
    t_end, t_start = _window(times, t_end, t_start)
    return make_stream(times, dims, D, t_end=t_end, t_start=t_start)
