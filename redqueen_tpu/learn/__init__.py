"""redqueen_tpu.learn — corpus-scale multivariate Hawkes estimation.

The learning subsystem closes the simulate→fit→control loop (ROADMAP
item 3): fit ``(mu, alpha, beta)`` of an exponential-kernel multivariate
Hawkes model from any event log the repo produces, then feed the learned
parameters back into a RedQueen-controlled simulation.

- ``learn.ingest``     — adapters (simulator ``EventLog``, native-loader
  corpus rows, serving journal segments) → one chunked fit format.
- ``learn.loglik``     — the exact O(n) recursive log-likelihood (shared
  scan; per-dimension health bits via ``runtime.numerics``).
- ``learn.hawkes_mle`` — the two solvers (MM/EM, Frank-Wolfe) behind
  :func:`fit_hawkes`; enveloped ``rq.learn.fit/1`` resume checkpoints.
- ``learn.control``    — fitted :class:`HawkesFit` → ``config.add_hawkes``
  sources for re-simulation under control; stationary-rate reduction
  (:func:`fit_s_sink`) and the seeded cross-exciting ground-truth
  simulator (:func:`simulate_cross_exciting`).
- ``learn.streaming``  — fit WHILE serving: :class:`StreamingEM` tails
  a serving journal, folds events into exponentially-forgotten
  sufficient statistics, checkpoints every step, and emits candidate
  fits for the ``serving.paramswap`` hot-swap gate (docs/DESIGN.md
  "Fit-while-serving & guarded hot-swap").
- ``learn.ckpt``       — the shared fit-checkpoint envelope (also used by
  ``models.rmtpp.fit``).

Importing this package pulls jax (the solvers are kernel-side code);
jax-free contexts (the watchdog, the rqlint CLI) simply don't import it
— same policy as ``redqueen_tpu.ops``.
"""

from __future__ import annotations

from .ckpt import FIT_SCHEMA
from .control import (
    add_fit_walls,
    builder_params,
    control_component,
    control_cost,
    cross_excitation_mass,
    fit_s_sink,
    simulate_cross_exciting,
    stationary_rates,
)
from .hawkes_mle import SOLVERS, FitError, HawkesFit, fit_hawkes
from .ingest import (
    ChunkedEvents,
    EventStream,
    StreamValidationError,
    chunk_events,
    from_event_log,
    from_journal,
    from_traces,
)
from .loglik import LoglikResult, hawkes_loglik
from .streaming import StreamingEM, StreamingUpdate, holdout_nll, run_sidecar

__all__ = [
    "EventStream",
    "ChunkedEvents",
    "StreamValidationError",
    "chunk_events",
    "from_event_log",
    "from_traces",
    "from_journal",
    "hawkes_loglik",
    "LoglikResult",
    "fit_hawkes",
    "HawkesFit",
    "FitError",
    "SOLVERS",
    "FIT_SCHEMA",
    "builder_params",
    "cross_excitation_mass",
    "add_fit_walls",
    "control_component",
    "control_cost",
    "stationary_rates",
    "fit_s_sink",
    "simulate_cross_exciting",
    "StreamingEM",
    "StreamingUpdate",
    "holdout_nll",
    "run_sidecar",
]
