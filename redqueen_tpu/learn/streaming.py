"""Fit-while-serving: streaming EM over the live serving journal.

The offline fitter (``learn.hawkes_mle``) answers "what model explains
this corpus"; this module answers "what model explains the feeds a
RUNNING deployment is seeing RIGHT NOW" — and keeps the answer fresh as
the traffic regime drifts.  It is the learner half of the fit-while-
serving loop; ``serving.paramswap`` is the serving half (gate + atomic
epoch install).  The two halves share exactly one artifact: the
integrity-enveloped candidate fit (``rq.learn.candidate/1``).

Design:

- **Tail, don't re-fit.**  Each update step replays the retained journal
  (``learn.ingest.from_journal`` — JSONL and binary segments alike),
  keeps only events past the last consumed timestamp, and folds that
  batch into decayed sufficient statistics::

      acc <- gamma * acc + batch_stats

  with ``batch_stats = (s0, S, W, G, counts, span)`` from the SAME
  O(n·D) scan the offline EM solver uses (``loglik._stream_pass`` /
  ``_censored_mass`` — one objective definition repo-wide).  The M-step
  is the offline solver's closed form on the accumulated statistics, so
  a stationary stream converges to the batch EM fixed point while a
  regime shift decays the stale past at rate ``gamma`` per step.

- **Crash-only.**  The learner runs as a supervised sidecar
  (``runtime.supervisor`` heartbeats + ``runtime.preempt`` checkpoints):
  its checkpoint (``learn.ckpt``, fingerprinted by the streaming
  CONFIG — the data is unbounded, the trajectory key is the recipe) is
  the only state that survives, and every step lands it atomically
  BEFORE honoring preemption.  A SIGKILL'd learner rerun with the same
  arguments resumes mid-stream; serving never notices either way.

- **Hand-off is an artifact, not a call.**  ``emit_candidate`` writes
  the enveloped candidate next to the journal; serving's
  :class:`~redqueen_tpu.serving.paramswap.ParamSwapper` polls, gates,
  and installs it.  The learner holds NO handle to the runtime — a
  learner crash/hang/OOM structurally cannot touch serving.

Deterministic fault drill (``RQ_FAULT``, ``runtime.faultinject``):
``learn:kill@stepN`` SIGKILLs the process mid-update (after statistics,
before the checkpoint — the worst spot); ``learn:hang@stepN`` wedges it
so the supervisor's staleness bound must fire; ``learn:badfit@stepN``
poisons the M-step output (NaN mu, supercritical alpha) and STILL emits
the candidate — the serving gate must reject it; ``learn:stale@stepN``
silences candidate emission without killing the process — serving must
surface ``stale_params``.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, Dict, NamedTuple, Optional

import numpy as np

from ..runtime import faultinject as _faultinject
from ..runtime import preempt as _preempt
from ..runtime import telemetry as _telemetry
from ..runtime.supervisor import heartbeat as _heartbeat
from . import ckpt as _ckpt
from .control import fit_s_sink
from .hawkes_mle import _default_beta0, _sanitize
from .ingest import chunk_events, from_journal, make_stream
from .loglik import _censored_mass, _stream_pass

__all__ = [
    "StreamingEM",
    "StreamingUpdate",
    "holdout_nll",
    "run_sidecar",
]


class StreamingUpdate(NamedTuple):
    """One ``run_once`` outcome: what the learner did this step."""

    step: int              # 1-based update-step counter (the fault clock)
    n_events: int          # events folded in this step (0 = idle poll)
    loglik: float          # batch loglik at the PRE-update parameters
    candidate: Optional[str]   # emitted artifact path, or None
    fingerprint: Optional[str]  # candidate fingerprint when emitted


def holdout_nll(stream, mu, alpha, beta, chunk_size: int = 1024) -> float:
    """Exact negative log-likelihood of ``(mu, alpha, beta)`` on a
    held-back event stream — the canary the install gate compares
    candidate-vs-live on (``serving.paramswap.ParamGate``).  One shared
    scan + compensator: the SAME objective the fit optimizes, so the
    gate can never pass a candidate on a different score than the one
    it was trained against."""
    import jax
    import jax.numpy as jnp

    data = chunk_events(stream, chunk_size=chunk_size)
    D = data.n_dims
    mu32 = jnp.asarray(np.asarray(mu, np.float64), jnp.float32)
    a32 = jnp.asarray(np.asarray(alpha, np.float64), jnp.float32)
    b32 = jnp.asarray(np.asarray(beta, np.float64), jnp.float32)
    ll_ev, _s0, _S, _W, _h = _stream_pass(
        jnp.asarray(data.dt), jnp.asarray(data.dims),
        jnp.asarray(data.mask), mu32, a32, b32, n_dims=D)
    G = _censored_mass(jnp.asarray(data.tail), jnp.asarray(data.dims),
                       jnp.asarray(data.mask),
                       jnp.asarray(data.counts, jnp.float32), b32,
                       n_dims=D)
    comp = mu32.sum() * float(data.span) + (a32 * G[None, :]).sum()
    ll, c = jax.device_get((ll_ev, comp))  # rqlint: disable=RQ701 one blocked transfer per canary evaluation
    return float(c) - float(ll)


class StreamingEM:
    """Streaming EM consumer of one serving runtime directory.

    ``gamma`` is the per-step forgetting factor on every sufficient
    statistic (1.0 = never forget — plain incremental EM; smaller
    adapts faster to regime shifts at the cost of variance).
    ``holdout_frac`` of each ingested batch (its TAIL — the freshest
    events) is held back from fitting and kept as the canary window the
    install gate scores candidates on.  ``ckpt_path`` lands a resumable
    ``rq.learn.fit/1`` checkpoint every step; ``candidate_path``
    defaults to ``<dir>/candidate_fit.json``."""

    def __init__(self, dir: str, n_feeds: int, gamma: float = 0.9,
                 chunk_size: int = 1024, beta_floor: float = 1e-3,
                 beta_cap: float = 1e4, holdout_frac: float = 0.2,
                 ckpt_path: Optional[str] = None,
                 candidate_path: Optional[str] = None,
                 emit_every: int = 1):
        if not 0.0 < gamma <= 1.0:
            raise ValueError(f"gamma must be in (0, 1], got {gamma!r}")
        if not 0.0 <= holdout_frac < 1.0:
            raise ValueError(
                f"holdout_frac must be in [0, 1), got {holdout_frac!r}")
        if n_feeds < 1:
            raise ValueError(f"n_feeds must be >= 1, got {n_feeds}")
        if emit_every < 1:
            raise ValueError(f"emit_every must be >= 1, got {emit_every}")
        self.dir = str(dir)
        self.n_feeds = int(n_feeds)
        self.gamma = float(gamma)
        self.chunk_size = int(chunk_size)
        self.beta_floor = float(beta_floor)
        self.beta_cap = float(beta_cap)
        self.holdout_frac = float(holdout_frac)
        self.emit_every = int(emit_every)
        from ..serving.paramswap import CANDIDATE_FILENAME
        self.candidate_path = (
            os.path.join(self.dir, CANDIDATE_FILENAME)
            if candidate_path is None else str(candidate_path))
        self.ckpt_path = ckpt_path
        # The trajectory key: the RECIPE, not the data (the stream is
        # unbounded — a resumed learner continues the same trajectory
        # iff it would compute the same updates from the same journal).
        self._fp = _ckpt.fingerprint_arrays(dict(
            kind="streaming_em", n_feeds=self.n_feeds, gamma=self.gamma,
            chunk_size=self.chunk_size, beta_floor=self.beta_floor,
            beta_cap=self.beta_cap, holdout_frac=self.holdout_frac,
            emit_every=self.emit_every))
        D = self.n_feeds
        self.step = 0               # 1-based after the first update
        self.last_t = -np.inf       # consume-watermark (event time)
        self.holdout = None         # EventStream | None — canary window
        # Decayed sufficient statistics (host f64).
        self.acc_s0 = np.zeros(D)
        self.acc_S = np.zeros((D, D))
        self.acc_W = np.zeros(D)
        self.acc_G = np.zeros(D)
        self.acc_counts = np.zeros(D)
        self.acc_span = 0.0
        # Current parameter estimate (sanitized after every M-step).
        self.mu = np.zeros(D)
        self.alpha = np.zeros((D, D))
        self.beta = np.ones(D)
        self.health = np.zeros(D, np.uint32)
        self._resume()

    # -- checkpoint / resume ----------------------------------------------

    def _resume(self) -> None:
        if self.ckpt_path is None:
            return
        loaded = _ckpt.load_fit(self.ckpt_path, self._fp)
        if loaded is None:
            return
        step, z, meta = loaded
        self.step = int(step)
        self.last_t = float(meta.get("last_t", -np.inf))
        self.mu = np.asarray(z["mu"], np.float64)
        self.alpha = np.asarray(z["alpha"], np.float64)
        self.beta = np.asarray(z["beta"], np.float64)
        self.health = np.asarray(z["health"], np.uint32)
        self.acc_s0 = np.asarray(z["s0"], np.float64)
        self.acc_S = np.asarray(z["S"], np.float64)
        self.acc_W = np.asarray(z["W"], np.float64)
        self.acc_G = np.asarray(z["G"], np.float64)
        self.acc_counts = np.asarray(z["counts"], np.float64)
        self.acc_span = float(meta.get("span", 0.0))

    def _checkpoint(self) -> None:
        if self.ckpt_path is None:
            return
        _ckpt.save_fit(
            self.ckpt_path, self._fp, self.step,
            {"mu": self.mu, "alpha": self.alpha, "beta": self.beta,
             "health": self.health, "s0": self.acc_s0, "S": self.acc_S,
             "W": self.acc_W, "G": self.acc_G,
             "counts": self.acc_counts},
            meta={"last_t": float(self.last_t),
                  "span": float(self.acc_span),
                  "n_feeds": self.n_feeds})
        # Durable boundary: prove progress, then honor a pending
        # SIGTERM/SIGINT (a rerun resumes from this artifact).
        _heartbeat()
        _preempt.check_preempt(f"streaming EM step {self.step}")

    # -- the stream tail ---------------------------------------------------

    def ingest(self):
        """New events past the consume-watermark, as a fit window
        ``[last_t, t_newest]`` — or None when the journal has nothing
        new (an idle poll).  Reads BOTH journal formats through the one
        shared adapter (``from_journal`` sniffs per record)."""
        with _telemetry.span("learn.stream.ingest") as sp:
            try:
                full = from_journal(self.dir)
            except FileNotFoundError:
                sp.set(n_events=0)
                return None
            t = np.asarray(full.times, np.float64)
            d = np.asarray(full.dims, np.int64)
            keep = t > self.last_t
            if not keep.any():
                sp.set(n_events=0)
                return None
            t, d = t[keep], d[keep]
            t_start = float(self.last_t) if np.isfinite(self.last_t) \
                else float(min(t[0], 0.0))
            stream = make_stream(t, d, self.n_feeds,
                                 t_end=float(t[-1]), t_start=t_start)
            sp.set(n_events=stream.n_events)
            return stream

    # -- one EM blend ------------------------------------------------------

    def update(self, stream) -> float:
        """Fold one ingested window into the decayed statistics and
        re-solve the closed-form M-step.  Returns the window loglik at
        the pre-update parameters.  The ``learn:*`` fault point: the
        1-based step counter is the learner's logical clock."""
        import jax
        import jax.numpy as jnp

        self.step += 1
        lf = _faultinject.learn_fault()
        fire = (lf is not None
                and (lf.step is None or lf.step == self.step))
        if fire and lf.mode == "hang":
            # Wedge (never heartbeat again): the supervisor's staleness
            # bound — not this process — must end it.
            while True:  # pragma: no cover — killed externally
                time.sleep(0.05)
        with _telemetry.span("learn.stream.update") as sp:
            sp.set(step=self.step, n_events=stream.n_events)
            n = stream.n_events
            # The watermark must advance to the FULL ingested window's
            # end even when no holdout is carved below (small window,
            # or a timestamp tie at the cut): self.holdout can be a
            # PREVIOUS window's stream, and its stale t_end would
            # rewind last_t — re-ingesting events and double-counting
            # them into acc_* on every later poll.
            window_t_end = float(stream.t_end)
            n_hold = int(n * self.holdout_frac)
            if n_hold and n - n_hold >= 1:
                cut = n - n_hold
                t_cut = float(stream.times[cut - 1])
                if t_cut < stream.t_end:
                    self.holdout = make_stream(
                        stream.times[cut:], stream.dims[cut:],
                        self.n_feeds, t_end=stream.t_end, t_start=t_cut)
                    stream = make_stream(
                        stream.times[:cut], stream.dims[:cut],
                        self.n_feeds, t_end=t_cut,
                        t_start=stream.t_start)
            data = chunk_events(stream, chunk_size=self.chunk_size)
            D = self.n_feeds
            if self.acc_span == 0.0 and not self.mu.any():
                # First window: seed the estimate from the batch itself
                # (the offline solver's init).  Zero parameters are an
                # EM fixed point — with ``alpha = 0`` the E-step
                # attributes no excitation, so ``S`` (and with it every
                # later alpha) would stay zero forever.
                counts64 = np.asarray(data.counts, np.float64)
                span0 = max(float(data.span), 1e-300)
                self.mu = 0.5 * counts64 / max(span0, 1e-300)
                self.beta = _default_beta0(counts64, span0,
                                           self.beta_floor, self.beta_cap)
                self.alpha = np.broadcast_to(
                    (0.1 * self.beta / max(D, 1))[None, :], (D, D)).copy()
            mu32 = jnp.asarray(self.mu, jnp.float32)
            a32 = jnp.asarray(self.alpha, jnp.float32)
            b32 = jnp.asarray(self.beta, jnp.float32)
            ll_ev, s0, S, W, health = _stream_pass(
                jnp.asarray(data.dt), jnp.asarray(data.dims),
                jnp.asarray(data.mask), mu32, a32, b32, n_dims=D)
            G = _censored_mass(
                jnp.asarray(data.tail), jnp.asarray(data.dims),
                jnp.asarray(data.mask),
                jnp.asarray(data.counts, jnp.float32), b32, n_dims=D)
            comp = mu32.sum() * float(data.span) + (a32 * G[None, :]).sum()
            ll_h, s0_h, S_h, W_h, G_h, health_h, comp_h = jax.device_get(  # rqlint: disable=RQ701,RQ702 one blocked sync per streaming update
                (ll_ev, s0, S, W, G, health, comp))
            g = self.gamma
            self.acc_s0 = g * self.acc_s0 + np.asarray(s0_h, np.float64)
            self.acc_S = g * self.acc_S + np.asarray(S_h, np.float64)
            self.acc_W = g * self.acc_W + np.asarray(W_h, np.float64)
            self.acc_G = g * self.acc_G + np.asarray(G_h, np.float64)
            self.acc_counts = (g * self.acc_counts
                               + np.asarray(data.counts, np.float64))
            self.acc_span = g * self.acc_span + float(data.span)
            # Closed-form M-step on the accumulated statistics (the
            # offline solver's update, over the decayed horizon).
            span = max(self.acc_span, 1e-300)
            mu_n = self.acc_s0 / max(span, 1e-300)
            alpha_n = self.acc_S / np.maximum(self.acc_G[None, :], 1e-300)
            P = self.acc_S.sum(0)
            W_safe = self.acc_W
            beta_n = np.where(W_safe > 0,
                              P / np.maximum(W_safe, 1e-300), self.beta)
            beta_n = np.clip(beta_n, self.beta_floor, self.beta_cap)
            if fire and lf.mode == "kill":
                # Mid-fit SIGKILL: statistics computed, checkpoint NOT
                # landed — the worst instant.  A rerun resumes from the
                # previous step's checkpoint; serving never notices.
                os.kill(os.getpid(), signal.SIGKILL)
            if fire and lf.mode == "badfit":
                # Poison the fit (NaN base rate + supercritical
                # excitation) but SKIP sanitization and still emit: the
                # serving-side gate is the component under test.
                mu_n = np.full(D, np.nan)
                alpha_n = np.full((D, D), 2.0)
                beta_n = np.ones(D)
                self.mu, self.alpha, self.beta = mu_n, alpha_n, beta_n
            else:
                # Health is NOT sticky across streaming updates (unlike
                # one offline fit): a transient poisoned window must not
                # quarantine a dimension for the rest of an unbounded
                # stream — the next clean window re-estimates it.
                scan_bits = np.asarray(health_h, np.uint32)
                self.mu, self.alpha, self.beta, self.health = _sanitize(
                    mu_n, alpha_n, beta_n, self.acc_counts, span,
                    scan_bits)
            ll = float(ll_h) - float(comp_h)
            self.last_t = window_t_end
            sp.set(loglik=ll)
            return ll

    # -- candidate hand-off ------------------------------------------------

    def candidate_fingerprint(self) -> str:
        return _ckpt.fingerprint_arrays(
            {"step": self.step}, self.mu, self.alpha, self.beta)

    def emit_candidate(self) -> Optional[str]:
        """Write the current estimate as an enveloped candidate for the
        serving gate.  ``learn:stale`` silences this (the process stays
        alive — the staleness the serving side must surface); the write
        itself is atomic (``runtime.integrity``)."""
        lf = _faultinject.learn_fault()
        if (lf is not None and lf.mode == "stale"
                and (lf.step is None or self.step >= lf.step)):
            return None
        from ..serving.paramswap import write_candidate
        fp = self.candidate_fingerprint()
        with _telemetry.span("learn.stream.swap") as sp:
            sp.set(step=self.step, fingerprint=fp)
            s_sink = fit_s_sink((self.mu, self.alpha, self.beta))
            write_candidate(
                self.candidate_path, mu=self.mu, alpha=self.alpha,
                beta=self.beta, s_sink=s_sink, fingerprint=fp,
                step=self.step,
                meta={"gamma": self.gamma,
                      "last_t": float(self.last_t),
                      "span": float(self.acc_span)})
        return self.candidate_path

    # -- the sidecar step --------------------------------------------------

    def run_once(self) -> StreamingUpdate:
        """One sidecar iteration: tail → blend → checkpoint → emit.
        The checkpoint lands BEFORE the candidate: a crash between the
        two re-emits the same candidate on resume (the swapper dedups
        by fingerprint) rather than losing a step."""
        stream = self.ingest()
        if stream is None:
            _heartbeat()
            return StreamingUpdate(self.step, 0, 0.0, None, None)
        ll = self.update(stream)
        self._checkpoint()
        path = fp = None
        if self.step % self.emit_every == 0:
            path = self.emit_candidate()
            fp = self.candidate_fingerprint() if path else None
        return StreamingUpdate(self.step, stream.n_events, ll, path, fp)


def run_sidecar(dir: str, n_feeds: int, poll_s: float = 0.5,
                max_steps: Optional[int] = None,
                idle_limit: Optional[int] = None,
                **kwargs) -> Dict[str, Any]:
    """Supervised-sidecar entry point: loop ``run_once`` against a
    runtime directory, heartbeating every iteration, until ``max_steps``
    updates land (None = forever, the production shape) or the journal
    stays silent for ``idle_limit`` consecutive polls.  Returns a
    summary dict (steps, events, last fingerprint)."""
    em = StreamingEM(dir, n_feeds, **kwargs)
    events = 0
    idle = 0
    last_fp = None
    while True:
        upd = em.run_once()
        if upd.n_events:
            idle = 0
            events += upd.n_events
            if upd.fingerprint:
                last_fp = upd.fingerprint
        else:
            idle += 1
            if idle_limit is not None and idle >= idle_limit:
                break
        if max_steps is not None and em.step >= max_steps:
            break
        if upd.n_events == 0:
            time.sleep(poll_s)
    return {"steps": em.step, "events": events,
            "fingerprint": last_fp, "last_t": float(em.last_t)}
