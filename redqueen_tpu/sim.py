"""Simulation driver: chunked jitted execution of the event-scan kernel.

Reference counterpart: ``Manager.run_till`` / ``run_dynamic`` plus the
seed/q sweep loops of SURVEY.md section 3.5. The TPU shape of it:

- ``simulate``   — one component, jitted chunked scan to the horizon.
- ``simulate_batch`` — a batch of same-shape components, ``vmap`` over the
  leading axis (the sweep axis: seeds, q values, broadcasters of the
  bipartite graph). ``redqueen_tpu.parallel`` shards this axis over a mesh.

Long horizons run as repeated fixed-capacity chunks with the full carry
(SURVEY.md section 5 "long-context" analogue); the driver loops on the host
at *chunk* granularity only, and overflow is detected, never silent: if
``max_chunks`` elapse with active sources, a RuntimeError reports progress.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import random as jr

from .config import SimConfig, SimState, SourceParams
from .ops.scan_core import init_state, make_run_chunk

# Importing the models package registers the built-in policies (the
# reference's Broadcaster subclasses; see models/base.py).
from . import models as _models  # noqa: F401
from .models import base

__all__ = ["EventLog", "simulate", "simulate_batch", "resume"]


class EventLog:
    """Host-side event log: the rebuild's counterpart of the reference's
    ``State.get_dataframe()`` artifact (SURVEY.md section 5 "observability").

    ``times``/``srcs`` are [E] (single component) or [B, E] (batch); invalid
    tail entries hold (+inf, -1). ``n_events`` is the valid-event count
    (scalar or [B]). Use ``redqueen_tpu.utils.dataframe`` to export the
    reference-schema DataFrame, or ``redqueen_tpu.utils.metrics`` to compute
    feed metrics on device without leaving HBM.
    """

    def __init__(self, times, srcs, n_events, cfg: SimConfig):
        self.times = times
        self.srcs = srcs
        self.n_events = n_events
        self.cfg = cfg

    @property
    def batched(self) -> bool:
        return self.times.ndim == 2

    def __repr__(self):
        return (
            f"EventLog(batched={self.batched}, n_events={self.n_events!r}, "
            f"buffer={tuple(self.times.shape)})"
        )


@functools.lru_cache(maxsize=None)
def _chunk_fn_cached(cfg: SimConfig, batched: bool, n_kinds: int):
    # n_kinds keys the cache to the policy registry: registering a new
    # policy after a simulate() with the same SimConfig must re-trace, or
    # lax.switch would silently clamp the new kind onto a stale branch list.
    fn = make_run_chunk(cfg)
    if batched:
        fn = jax.vmap(fn)
    return jax.jit(fn)


def _chunk_fn(cfg: SimConfig, batched: bool):
    return _chunk_fn_cached(cfg, batched, base.n_kinds())


@functools.lru_cache(maxsize=None)
def _init_fn_cached(cfg: SimConfig, batched: bool, n_kinds: int):
    def init(params, adj, key):
        return init_state(cfg, params, adj, key)

    if batched:
        init = jax.vmap(init)
    return jax.jit(init)


def _init_fn(cfg: SimConfig, batched: bool):
    return _init_fn_cached(cfg, batched, base.n_kinds())


def _as_key(seed: Union[int, jnp.ndarray]):
    if isinstance(seed, (int, np.integer)):
        return jr.PRNGKey(seed)
    return seed


def _check_kinds(cfg: SimConfig, params: SourceParams):
    """A specialized config compiles switch branches only for
    cfg.present_kinds; a params row of any other kind would be silently
    clamped onto branch 0 by the local-code gather. Reject host-side."""
    if not cfg.present_kinds:
        return
    present = set(cfg.present_kinds)
    got = set(int(k) for k in np.unique(np.asarray(params.kind)))
    if not got.issubset(present):
        raise ValueError(
            f"params contain source kinds {sorted(got - present)} not in the "
            f"config's present_kinds {sorted(present)} — build params and "
            f"config from the same GraphBuilder structure"
        )


def _check_weights(cfg: SimConfig, params: SourceParams):
    """RMTPP rows need attached weights (models.rmtpp.attach) whose hidden
    size matches the config's recurrent-state slot; catch both misuses
    host-side with clear messages instead of a never-firing source or a
    flax shape error deep in the scan."""
    if not np.any(np.asarray(params.kind) == base.KIND_RMTPP):
        return
    if params.rmtpp is None:
        raise ValueError(
            "component has RMTPP sources but params.rmtpp is None — attach "
            "trained weights via redqueen_tpu.models.rmtpp.attach(params, w)"
        )
    w = params.rmtpp
    try:
        hidden = int(np.asarray(w["v"]["kernel"]).shape[-2])
    except (KeyError, TypeError, IndexError):
        return  # unexpected weight layout; let tracing report it
    if hidden != cfg.rmtpp_hidden:
        raise ValueError(
            f"RMTPP weights have hidden={hidden} but the config was built "
            f"with rmtpp_hidden={cfg.rmtpp_hidden}; pass "
            f"GraphBuilder.build(rmtpp_hidden={hidden})"
        )


def _drive(cfg, params, adj, state, chunk, max_chunks, batched):
    times_chunks, srcs_chunks = [], []
    n_chunks = 0
    n_before = state.n_events  # resume(): count only this drive's events
    while True:
        state, (t_c, s_c) = chunk(params, adj, state)
        times_chunks.append(t_c)
        srcs_chunks.append(s_c)
        n_chunks += 1
        # Host sync at chunk granularity only (SURVEY.md section 7 design).
        alive = state.t_next.min(axis=-1) <= cfg.end_time
        if state.budget is not None:
            alive &= state.n_events < state.budget
        if not bool(jnp.any(alive)):
            break
        if n_chunks >= max_chunks:
            done = np.asarray(state.n_events)
            raise RuntimeError(
                f"simulation still active after {n_chunks} chunks of "
                f"{cfg.capacity} events (events so far: {done}); raise "
                f"capacity or max_chunks — refusing to truncate silently"
            )
    axis = 1 if batched else 0
    times = jnp.concatenate(times_chunks, axis=axis)
    srcs = jnp.concatenate(srcs_chunks, axis=axis)
    return EventLog(times, srcs, state.n_events - n_before, cfg), state


def simulate(cfg: SimConfig, params: SourceParams, adj, seed,
             max_chunks: int = 100, return_state: bool = False,
             max_events: Optional[int] = None):
    """Run one component to its horizon. ``seed`` is an int or a PRNG key.

    ``max_events`` stops after exactly that many events (the oracle's
    ``Manager.run_dynamic`` semantics — SURVEY.md section 2 item 9), not at
    chunk granularity: the scan absorbs mid-chunk once the budget is spent.

    Returns an ``EventLog`` (and the final ``SimState`` if
    ``return_state=True`` — the carry is resumable: pass it to
    :func:`resume` with a longer-horizon ``SimConfig`` to continue)."""
    _check_kinds(cfg, params)
    _check_weights(cfg, params)
    key = _as_key(seed)
    state = _init_fn(cfg, False)(params, adj, key)
    if max_events is not None:
        state = state.replace(budget=jnp.asarray(max_events, jnp.int32))
    log, state = _drive(
        cfg, params, adj, state, _chunk_fn(cfg, False), max_chunks, False
    )
    return (log, state) if return_state else log


def simulate_batch(cfg: SimConfig, params: SourceParams, adj, seeds,
                   max_chunks: int = 100, return_state: bool = False,
                   max_events: Optional[int] = None):
    """Run B same-shape components in lockstep (params/adj have a leading
    batch axis; ``seeds`` is an int array [B] or a key array [B, 2]).

    This is the reference's embarrassingly-parallel sweep loop (SURVEY.md
    section 3.5) turned into a vmap axis: components finish at different
    event counts and simply absorb until the slowest one is done.
    ``max_events`` (scalar or [B]) applies the per-lane run_dynamic stop."""
    _check_kinds(cfg, params)
    _check_weights(cfg, params)
    seeds = jnp.asarray(seeds)
    keys = jax.vmap(jr.PRNGKey)(seeds) if seeds.ndim == 1 else seeds
    state = _init_fn(cfg, True)(params, adj, keys)
    if max_events is not None:
        B = keys.shape[0]
        state = state.replace(
            budget=jnp.broadcast_to(
                jnp.asarray(max_events, jnp.int32), (B,)
            )
        )
    log, state = _drive(
        cfg, params, adj, state, _chunk_fn(cfg, True), max_chunks, True
    )
    return (log, state) if return_state else log


def resume(cfg: SimConfig, params: SourceParams, adj, state: SimState,
           max_chunks: int = 100, max_events: Optional[int] = None):
    """Continue a simulation from a carried ``SimState`` (obtained via
    ``return_state=True``), e.g. after extending the horizon with a new
    ``SimConfig``. Valid because every policy schedules its TRUE next event
    time (never truncated at the old horizon), so an absorbed state wakes up
    under a later ``end_time`` with the correct distribution — the oracle's
    re-entrant ``Manager.run_till`` contract (SURVEY.md section 3.1).

    ``max_events`` bounds the events of THIS call (the oracle's re-entrant
    ``run_till(max_events=...)`` counts per call); None clears any budget a
    previous run_dynamic left on the carry.

    Returns (EventLog-of-the-extension, final state). Batched states resume
    batched."""
    batched = state.t_next.ndim == 2
    if max_events is not None:
        state = state.replace(
            budget=state.n_events + jnp.asarray(max_events, jnp.int32)
        )
    else:
        state = state.replace(budget=None)
    return _drive(
        cfg, params, adj, state, _chunk_fn(cfg, batched), max_chunks, batched
    )
