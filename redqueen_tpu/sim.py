"""Simulation driver: chunked jitted execution of the event-scan kernel.

Reference counterpart: ``Manager.run_till`` / ``run_dynamic`` plus the
seed/q sweep loops of SURVEY.md section 3.5. The TPU shape of it:

- ``simulate``   — one component, jitted chunked scan to the horizon.
- ``simulate_batch`` — a batch of same-shape components, ``vmap`` over the
  leading axis (the sweep axis: seeds, q values, broadcasters of the
  bipartite graph). ``redqueen_tpu.parallel`` shards this axis over a mesh.

Long horizons run as repeated fixed-capacity chunks with the full carry
(SURVEY.md section 5 "long-context" analogue); chunks execute k at a time
inside a device-side ``lax.while_loop`` ("superchunk", early-exiting when
every lane is done), so the host loops — and pays a device round-trip —
only once per k chunks. Overflow is detected, never silent: if
``max_chunks`` elapse with active sources, a RuntimeError reports progress.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import random as jr

from .config import SimConfig, SimState, SourceParams
from .ops.scan_core import init_state, make_run_chunk
from .runtime import faultinject as _faultinject
from .runtime import numerics as _numerics
from .runtime import telemetry as _telemetry
from .runtime.numerics import NumericalHealthError

# Importing the models package registers the built-in policies (the
# reference's Broadcaster subclasses; see models/base.py).
from . import models as _models  # noqa: F401
from .models import base

__all__ = ["EventLog", "simulate", "simulate_batch", "resume",
           "select_engine", "NumericalHealthError"]


class EventLog:
    """Host-side event log: the rebuild's counterpart of the reference's
    ``State.get_dataframe()`` artifact (SURVEY.md section 5 "observability").

    ``times``/``srcs`` are [E] (single component) or [B, E] (batch); invalid
    tail entries hold (+inf, -1). ``n_events`` is the valid-event count
    (scalar or [B]). Use ``redqueen_tpu.utils.dataframe`` to export the
    reference-schema DataFrame, or ``redqueen_tpu.utils.metrics`` to compute
    feed metrics on device without leaving HBM.

    ``health`` is the per-lane numeric-health bitmask (scalar or [B]
    uint32; see ``runtime.numerics``): 0 = healthy, non-zero = the lane
    went numerically sick mid-run and was FROZEN at that point — its
    events up to the freeze are valid, nothing after was emitted, and
    ``times`` is NaN-free by construction.  Decode with
    ``runtime.numerics.describe_health``.

    ``engine`` names the kernel that produced the log (``"scan"`` or
    ``"pallas"``); ``engine_reason`` records why an ``engine="auto"`` /
    ``engine="pallas"`` dispatch fell back to the scan engine (None when
    the requested engine ran).  ``dispatches`` counts the device
    launches the run cost (scan: superchunk dispatches of ``sim._drive``;
    pallas: megakernel superchunk launches) — the denominator of the
    dispatch-amortization story the bench artifacts commit.
    """

    def __init__(self, times, srcs, n_events, cfg: SimConfig, health=None,
                 dispatches=None, engine="scan", engine_reason=None):
        self.times = times
        self.srcs = srcs
        self.n_events = n_events
        self.cfg = cfg
        self.health = health
        self.dispatches = dispatches
        self.engine = engine
        self.engine_reason = engine_reason

    @property
    def batched(self) -> bool:
        return self.times.ndim == 2

    def __repr__(self):
        sick = (_numerics.sick_lanes(self.health).size
                if self.health is not None else 0)
        return (
            f"EventLog(batched={self.batched}, n_events={self.n_events!r}, "
            f"buffer={tuple(self.times.shape)}, sick_lanes={sick})"
        )


@functools.lru_cache(maxsize=None)
def _chunk_fn_cached(cfg: SimConfig, batched: bool, n_kinds: int, k: int = 8):
    # n_kinds keys the cache to the policy registry: registering a new
    # policy after a simulate() with the same SimConfig must re-trace, or
    # lax.switch would silently clamp the new kind onto a stale branch list.
    #
    # The returned "superchunk" advances the simulation by UP TO ``k`` chunks
    # of ``cfg.capacity`` events entirely on device (lax.while_loop), writing
    # each chunk into a preallocated [k * capacity] buffer and early-exiting
    # the moment every lane is past its horizon/budget — so the host loop in
    # ``_drive`` syncs once per k chunks instead of once per chunk. Over the
    # axon TPU tunnel each host sync is a network round-trip, so this divides
    # the dominant non-compute cost by k (round-2 verdict item 3). Dead lanes
    # are masked by vmap-of-while_loop, which is bit-identical to running
    # their absorbing chunks: an absorbed chunk is a true no-op on the carry
    # (every SimState field is ``valid``-gated in scan_core.step and the PRNG
    # is counter-addressed, never key-split per chunk) and its output equals
    # the buffer's (+inf, -1) fill.
    run_chunk = jax.vmap(make_run_chunk(cfg)) if batched else make_run_chunk(cfg)
    cap = cfg.capacity
    end_time = cfg.end_time

    def alive_fn(st):
        # Per-lane liveness; [B] when batched, scalar otherwise.  A sick
        # lane (non-zero health mask) is frozen by the kernel and counts
        # as done: without this gate a lane frozen with a finite t_next
        # would look alive forever and spin the chunk loop to max_chunks.
        a = st.t_next.min(axis=-1) <= end_time
        if st.budget is not None:
            a &= st.n_events < st.budget
        if st.health is not None:
            a &= st.health == 0
        return a

    # The while_loop sits OUTSIDE the vmap with one GLOBAL chunk counter
    # (all lanes advance in lockstep, exactly like the old host loop): a
    # per-lane while_loop under vmap would turn every buffer write into a
    # select over the whole [k*cap] staging buffer (measured 26% slower on
    # the CPU headline shape), whereas a shared counter keeps it one
    # in-place dynamic_update_slice per chunk. Lanes already past their
    # horizon run absorbing chunks — true no-ops emitting the buffer's own
    # (+inf, -1) fill, so lockstep is bit-identical to masking.
    def superchunk(params, adj, state, rem):
        # ``rem`` (dynamic operand — no retrace across calls) is the chunk
        # budget left before ``max_chunks``: the loop never runs past it, so
        # the driver's overflow contract stays exact at chunk granularity,
        # not superchunk granularity.
        dtype = state.t_next.dtype
        lead = state.t_next.shape[:-1]  # () or (B,)
        times0 = jnp.full(lead + (k * cap,), jnp.inf, dtype)
        srcs0 = jnp.full(lead + (k * cap,), -1, jnp.int32)
        offset = (0,) * len(lead)

        def cond(carry):
            c, st, _, _ = carry
            # c == 0: always run at least one chunk per superchunk call,
            # matching the previous driver's run-then-check loop (an
            # already-absorbed state still emits one padding chunk).
            return (c < k) & (c < rem) & ((c == 0) | jnp.any(alive_fn(st)))

        def body(carry):
            c, st, times, srcs = carry
            st, (t_c, s_c) = run_chunk(params, adj, st)
            times = lax.dynamic_update_slice(times, t_c, offset + (c * cap,))
            srcs = lax.dynamic_update_slice(srcs, s_c, offset + (c * cap,))
            return c + 1, st, times, srcs

        c, state, times, srcs = lax.while_loop(
            cond, body, (jnp.zeros((), jnp.int32), state, times0, srcs0)
        )
        return state, times, srcs, c, alive_fn(state)

    return jax.jit(superchunk)


def _chunk_fn(cfg: SimConfig, batched: bool, k: int = 8):
    return _chunk_fn_cached(cfg, batched, base.n_kinds(), k)


@functools.lru_cache(maxsize=None)
def _init_fn_cached(cfg: SimConfig, batched: bool, n_kinds: int):
    def init(params, adj, key):
        return init_state(cfg, params, adj, key)

    if batched:
        init = jax.vmap(init)
    return jax.jit(init)


def _init_fn(cfg: SimConfig, batched: bool):
    return _init_fn_cached(cfg, batched, base.n_kinds())


def _as_key(seed: Union[int, jnp.ndarray]):
    if isinstance(seed, (int, np.integer)):
        return jr.PRNGKey(seed)
    return seed


def _host_view(x) -> np.ndarray:
    """NumPy view of ``x`` for host-side validation. An array sharded over
    MULTIPLE PROCESSES (multihost runs) cannot be materialized whole; its
    locally-addressable shards are enough — every process validates the
    rows it owns, which collectively covers all of them (the SPMD
    contract; exercised by tests/test_multihost.py)."""
    # rqlint: RQ701 pragmas — _host_view IS the validated-input boundary
    # (PR 3): a deliberate, size-capped transfer for host-side checks
    # (_FINITE_CHECK_MAX_ELEMS skips corpus-scale fields).  Sanctioning
    # the sync here keeps every driver call edge's summary clean.
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        return np.concatenate(  # rqlint: disable=RQ701 validated boundary
            [np.asarray(s.data).reshape(-1) for s in x.addressable_shards]
        )
    return np.asarray(x)  # rqlint: disable=RQ701 validated boundary


def _check_kinds(cfg: SimConfig, params: SourceParams):
    """A specialized config compiles switch branches only for
    cfg.present_kinds; a params row of any other kind would be silently
    clamped onto branch 0 by the local-code gather. Reject host-side."""
    if not cfg.present_kinds:
        return
    present = set(cfg.present_kinds)
    got = set(int(k) for k in np.unique(_host_view(params.kind)))
    if not got.issubset(present):
        raise ValueError(
            f"params contain source kinds {sorted(got - present)} not in the "
            f"config's present_kinds {sorted(present)} — build params and "
            f"config from the same GraphBuilder structure"
        )


# (field name, allow +inf) — +inf is a legal padding/sentinel value in the
# piecewise knots and replay timestamps; NaN and -inf never are.
_FINITE_FIELDS = (
    ("rate", False), ("l0", False), ("alpha", False), ("beta", False),
    ("q", False), ("s_sink", False), ("pw_times", True), ("pw_rates", False),
    ("rd_times", True),
)

# Host-validation size ceiling: the check copies the array to host, so a
# big stacked replay/piecewise matrix (B x S x Kr at corpus scale) would
# pay a transfer + O(n) scan on EVERY dispatch re-validating data the
# builder already proved finite.  Larger fields skip the host check — the
# kernel's lane-health mask is the device-side backstop for them.
_FINITE_CHECK_MAX_ELEMS = 2_000_000


def _check_finite_params(cfg: SimConfig, params: SourceParams):
    """Validated boundary (runtime.numerics): garbage parameters are
    rejected HOST-side with a named field and flat index, instead of
    surfacing device-side as a quarantined lane (hand-built SourceParams
    bypass GraphBuilder's per-component validation, so the driver
    re-checks the cheap invariant: no NaN anywhere, no inf outside the
    padding fields).  Fields above ``_FINITE_CHECK_MAX_ELEMS`` are left
    to the in-kernel health mask (see the constant's comment)."""
    for field, allow_posinf in _FINITE_FIELDS:
        arr = getattr(params, field)
        if int(np.prod(np.shape(arr), dtype=np.int64)) > \
                _FINITE_CHECK_MAX_ELEMS:
            continue  # metadata-only size check: no transfer paid
        arr = _host_view(arr)
        bad = np.isnan(arr) | np.isneginf(arr)
        if not allow_posinf:
            bad |= np.isposinf(arr)
        if bad.any():
            flat = int(np.flatnonzero(bad.reshape(-1))[0])
            raise ValueError(
                f"SourceParams.{field} holds a non-finite value at flat "
                f"index {flat} ({arr.reshape(-1)[flat]!r}) — simulation "
                f"inputs must be finite ({'+inf padding allowed' if allow_posinf else 'no inf/NaN'}); "
                f"build components through GraphBuilder or fix the array "
                f"before dispatch"
            )
    if params.rmtpp is not None:
        for path, leaf in jax.tree_util.tree_leaves_with_path(params.rmtpp):
            arr = _host_view(leaf)
            if np.isnan(arr).any() or np.isinf(arr).any():
                raise ValueError(
                    f"params.rmtpp weight leaf "
                    f"{jax.tree_util.keystr(path)} holds a non-finite "
                    f"value — refusing to deploy a diverged checkpoint "
                    f"as a broadcaster policy"
                )


def _check_weights(cfg: SimConfig, params: SourceParams):
    """RMTPP rows need attached weights (models.rmtpp.attach) whose hidden
    size matches the config's recurrent-state slot; catch both misuses
    host-side with clear messages instead of a never-firing source or a
    flax shape error deep in the scan."""
    if not np.any(_host_view(params.kind) == base.KIND_RMTPP):
        return
    if params.rmtpp is None:
        raise ValueError(
            "component has RMTPP sources but params.rmtpp is None — attach "
            "trained weights via redqueen_tpu.models.rmtpp.attach(params, w)"
        )
    w = params.rmtpp
    try:
        # np.shape reads metadata only — no materialization, so this stays
        # valid for weights sharded across processes
        hidden = int(np.shape(w["v"]["kernel"])[-2])
    except (KeyError, TypeError, IndexError):
        return  # unexpected weight layout; let tracing report it
    if hidden != cfg.rmtpp_hidden:
        raise ValueError(
            f"RMTPP weights have hidden={hidden} but the config was built "
            f"with rmtpp_hidden={cfg.rmtpp_hidden}; pass "
            f"GraphBuilder.build(rmtpp_hidden={hidden})"
        )


def _maybe_poison(state: SimState, batch_size: int) -> SimState:
    """Apply the env-configured ``numeric`` fault (RQ_FAULT=
    numeric:mode@laneN[,chunkM]) to the freshly initialized carry, if it
    addresses a lane of this dispatch — the deterministic stand-in for an
    in-computation bit flip, so the detection/quarantine/re-run paths run
    in CI on CPU (runtime.faultinject / runtime.numerics)."""
    hit = _faultinject.active_numeric_lane(batch_size)
    if hit is None:
        return state
    lane, mode = hit
    return _numerics.poison_lane(state, lane, mode)


@jax.jit
def _sync_reduce(c, alive):
    """Global (chunks-executed max, any-lane-alive) as replicated scalars."""
    return jnp.max(c), jnp.any(alive)


def _drive(cfg, params, adj, state, chunk_fn_for, max_chunks, batched,
           sync_every):
    """Host loop at SUPERCHUNK granularity: one device->host sync per k
    chunks (the superchunk's internal while_loop early-exits when every lane
    is done, so no absorbed-chunk compute is wasted).

    The FIRST superchunk always runs k=1: a run that fits one chunk (the
    common case when capacity >= the run's event count, e.g. the presets'
    capacity=2048) pays zero staging-buffer overhead — a fixed k=8 start
    costs it ~30% on CPU (7.5M vs 11.0M events/s, config-3 shape) filling
    and carrying a k*capacity buffer it never uses. Runs that survive chunk
    1 switch to k=sync_every for the tail. Measured on the CPU headline
    shape (10k lanes, 24 chunks/run at capacity 64, best-of-3): syncs drop
    24 -> 4 per simulation with throughput within noise of the per-chunk
    driver; the win is the axon TPU tunnel, where each sync is a network
    round-trip."""
    times_chunks, srcs_chunks = [], []
    n_chunks = 0
    n_dispatches = 0
    n_before = state.n_events  # resume(): count only this drive's events
    cap = cfg.capacity
    k = 1
    # The with-statement (not a manual __enter__/__exit__) so a raising
    # drive stamps its error attribute on the span; the inner finally
    # records the progress attrs on BOTH exits.
    with _telemetry.span("engine.scan.drive", batched=batched) as dsp:
        try:
            while True:
                n_dispatches += 1
                with _telemetry.span("engine.scan.superchunk") as ssp:
                    ssp.set(k=k)
                    state, t_sc, s_sc, c, alive = chunk_fn_for(k)(
                        # np.int32 of two HOST ints (no transfer; keeps
                        # the chunk budget weak-type-stable across
                        # dispatches)
                        params, adj, state,
                        np.int32(max_chunks - n_chunks),  # rqlint: disable=RQ701 host ints
                    )
                k = sync_every
                # The ONE host sync per superchunk: chunks executed +
                # liveness.  Reduced to REPLICATED scalars on-device
                # first: a fully-replicated value is readable on every
                # process, so the same driver serves multihost runs
                # (where the [B] lanes span processes and could not be
                # fetched whole) — and only two scalars cross to the
                # host.  The superchunk span above measured the ENQUEUE
                # (async dispatch); the device wait surfaces in this
                # sync span — the per-stage split the breakdowns rely
                # on.
                with _telemetry.span("engine.scan.sync"):
                    c_max_dev, alive_dev = _sync_reduce(c, alive)
                    # rqlint: RQ702 pragmas — this IS the deliberate,
                    # cadence-controlled sync the comment above
                    # documents (two replicated scalars per superchunk,
                    # not per event); sanctioning it here keeps every
                    # simulate()/sweep caller's summary clean.
                    c_max = int(c_max_dev)  # rqlint: disable=RQ702 the one sync/superchunk
                    alive_any = bool(alive_dev)  # rqlint: disable=RQ702 same sync point
                # Trim unused chunk slots so the returned buffers are
                # bit-identical to the per-chunk driver's (goldens/
                # parity unchanged).
                times_chunks.append(t_sc[..., : c_max * cap])
                srcs_chunks.append(s_sc[..., : c_max * cap])
                n_chunks += c_max
                if not alive_any:
                    break
                if n_chunks >= max_chunks:
                    done = _host_view(state.n_events)
                    raise RuntimeError(
                        f"simulation still active after {n_chunks} "
                        f"chunks of {cfg.capacity} events (events so "
                        f"far: {done}); raise capacity or max_chunks — "
                        f"refusing to truncate silently"
                    )
        finally:
            dsp.set(dispatches=n_dispatches, chunks=n_chunks)
    _telemetry.counter("engine.scan.dispatches", n_dispatches)
    axis = 1 if batched else 0
    times = jnp.concatenate(times_chunks, axis=axis)
    srcs = jnp.concatenate(srcs_chunks, axis=axis)
    if state.health is not None:
        h = _host_view(state.health)
        if h.size and np.all(h != 0):
            # Every lane died numerically: a result would be pure garbage,
            # so replace silent NaN propagation with typed per-lane
            # provenance (partial results for SOME sick lanes flow through
            # EventLog.health instead — the sweep layer quarantines and
            # re-runs exactly those).
            raise NumericalHealthError(
                h, context=f"simulation of {h.size} lane(s)")
    return EventLog(times, srcs, state.n_events - n_before, cfg,
                    health=state.health, dispatches=n_dispatches,
                    engine="scan"), state


def select_engine(cfg: SimConfig, params: Optional[SourceParams] = None, *,
                  engine: str = "auto", max_events=None,
                  return_state: bool = False, platform: Optional[str] = None):
    """Resolve the batch-engine choice -> ``(name, reason)``.

    ``engine="scan"`` short-circuits; ``engine="pallas"`` FORCES the
    megakernel (raising ``ValueError`` with the recorded reason when the
    config cannot run on it); ``engine="auto"`` prefers the megakernel
    when (a) the policy mix is covered (``ops.pallas_engine.coverage``),
    (b) the per-shape VMEM plan fits (``ops.pallas_vmem.plan_vmem``;
    needs ``params`` for the replay/piecewise cube shapes — skipped when
    ``params`` is None), (c) no scan-only feature is requested
    (``max_events`` budgets and ``return_state`` carries are scan
    contracts), and (d) the backend is a TPU (interpret mode exists for
    tests, not timing) — otherwise it falls back to the scan engine with
    the degrade provenance in ``reason`` (surfaced as
    ``EventLog.engine_reason``)."""
    if engine not in ("scan", "pallas", "auto"):
        raise ValueError(
            f"unknown engine {engine!r} (choose scan, pallas, or auto)")
    if engine == "scan":
        return "scan", None
    from .ops import pallas_engine
    from .ops.pallas_vmem import plan_vmem

    forced = engine == "pallas"

    def fall(reason):
        if forced:
            raise ValueError(f"engine='pallas' requested but {reason}")
        return "scan", reason

    ok, why = pallas_engine.coverage(cfg)
    if not ok:
        return fall(why)
    if max_events is not None:
        return fall("the pallas megakernel has no run_dynamic budget "
                    "support — max_events is a scan-engine contract")
    if return_state:
        return fall("the pallas carry is engine-internal (no SimState "
                    "handoff for resume) — return_state is a scan-engine "
                    "contract")
    if params is not None:
        kinds = set(cfg.present_kinds)
        Kr = (params.rd_times.shape[-1]
              if base.KIND_REALDATA in kinds else 0)
        Kp = (params.pw_times.shape[-1]
              if base.KIND_PIECEWISE in kinds else 0)
        plan = plan_vmem(cfg, cfg.n_sources, cfg.n_sinks, Kr, Kp)
        if not plan.fits:
            return fall(plan.reason)
    if not forced:
        if platform is None:
            platform = jax.devices()[0].platform
        if platform != "tpu":
            return "scan", (
                "pallas interpret mode is test-only off-TPU — auto picks "
                "the scan engine (pass engine='pallas' to force it)")
    return "pallas", None


def simulate(cfg: SimConfig, params: SourceParams, adj, seed,
             max_chunks: int = 100, return_state: bool = False,
             max_events: Optional[int] = None, sync_every: int = 8):
    """Run one component to its horizon. ``seed`` is an int or a PRNG key.

    ``max_events`` stops after exactly that many events (the oracle's
    ``Manager.run_dynamic`` semantics — SURVEY.md section 2 item 9), not at
    chunk granularity: the scan absorbs mid-chunk once the budget is spent.

    ``sync_every`` is the device-side superchunk width: chunks run per
    host sync (memory: a [sync_every * capacity] staging buffer per lane).

    Returns an ``EventLog`` (and the final ``SimState`` if
    ``return_state=True`` — the carry is resumable: pass it to
    :func:`resume` with a longer-horizon ``SimConfig`` to continue)."""
    _check_kinds(cfg, params)
    _check_weights(cfg, params)
    _check_finite_params(cfg, params)
    key = _as_key(seed)
    state = _init_fn(cfg, False)(params, adj, key)
    state = _maybe_poison(state, 1)
    if max_events is not None:
        state = state.replace(budget=jnp.asarray(max_events, jnp.int32))
    log, state = _drive(
        cfg, params, adj, state, lambda k: _chunk_fn(cfg, False, k),
        max_chunks, False, sync_every
    )
    return (log, state) if return_state else log


def simulate_batch(cfg: SimConfig, params: SourceParams, adj, seeds,
                   max_chunks: int = 100, return_state: bool = False,
                   max_events: Optional[int] = None, sync_every: int = 8,
                   engine: str = "scan", slab: Optional[int] = None):
    """Run B same-shape components in lockstep (params/adj have a leading
    batch axis; ``seeds`` is an int array [B] or a key array [B, 2]).

    This is the reference's embarrassingly-parallel sweep loop (SURVEY.md
    section 3.5) turned into a vmap axis: components finish at different
    event counts and simply absorb until the slowest one is done.
    ``max_events`` (scalar or [B]) applies the per-lane run_dynamic stop.

    ``engine`` selects the batch kernel: ``"scan"`` (default — the
    general event-scan engine), ``"pallas"`` (the fused megakernel,
    forced; integer seeds only), or ``"auto"`` (megakernel when
    :func:`select_engine` says it covers this dispatch, scan otherwise
    with the fallback reason recorded on ``EventLog.engine_reason``).

    ``slab`` dispatches the batch in consecutive ``slab``-lane pieces
    with bit-identical per-lane results (identical seeds and streams) —
    the CPU cache-locality lever, sized by the measured auto-tuner
    (:func:`~redqueen_tpu.parallel.lanes.measured_slab`) rather than a
    hard-coded constant.  Slab dispatch has no ``SimState`` handoff."""
    if slab is not None and slab < np.shape(seeds)[0]:
        if return_state:
            raise ValueError(
                "slab dispatch has no SimState handoff (per-slab carries "
                "cannot merge) — return_state is an unslabbed contract")
        from .parallel.lanes import simulate_slabbed

        return simulate_slabbed(
            cfg, params, adj, seeds, slab, max_chunks=max_chunks,
            sync_every=sync_every, max_events=max_events, engine=engine)
    _check_kinds(cfg, params)
    _check_weights(cfg, params)
    _check_finite_params(cfg, params)
    engine_reason = None
    if engine != "scan":
        if jnp.asarray(seeds).ndim != 1:
            # Key-array seeds ([B, 2]) are a scan-engine contract: the
            # pallas engine derives its per-source threefry streams from
            # integer seeds.  Catch here with provenance instead of a
            # block-shape error deep inside pallas_call.
            reason = ("the pallas engine takes integer seeds [B] — "
                      "key-array seeds ([B, 2]) are a scan-engine "
                      "contract")
            if engine == "pallas":
                raise ValueError(f"engine='pallas' requested but {reason}")
            engine_reason = reason
        else:
            name, engine_reason = select_engine(
                cfg, params, engine=engine, max_events=max_events,
                return_state=return_state)
            if name == "pallas":
                from .ops.pallas_engine import simulate_pallas

                _telemetry.event("engine.dispatch", engine="pallas",
                                 requested=engine)
                _telemetry.counter("engine.dispatch.pallas")
                return simulate_pallas(cfg, params, adj, seeds,
                                       max_chunks=max_chunks,
                                       sync_every=sync_every)
    # The dispatch-choice provenance, telemetry-side: which engine ran
    # and (for auto/pallas requests that fell back) why — the same fact
    # EventLog.engine_reason carries, folded into the one trace so
    # rqtrace breakdowns never need the ad-hoc field.
    _telemetry.event("engine.dispatch", engine="scan", requested=engine,
                     reason=engine_reason)
    _telemetry.counter("engine.dispatch.scan")
    seeds = jnp.asarray(seeds)
    keys = jax.vmap(jr.PRNGKey)(seeds) if seeds.ndim == 1 else seeds
    state = _init_fn(cfg, True)(params, adj, keys)
    state = _maybe_poison(state, int(keys.shape[0]))
    if max_events is not None:
        B = keys.shape[0]
        state = state.replace(
            budget=jnp.broadcast_to(
                jnp.asarray(max_events, jnp.int32), (B,)
            )
        )
    log, state = _drive(
        cfg, params, adj, state, lambda k: _chunk_fn(cfg, True, k),
        max_chunks, True, sync_every
    )
    log.engine_reason = engine_reason  # why an auto dispatch fell back
    return (log, state) if return_state else log


def resume(cfg: SimConfig, params: SourceParams, adj, state: SimState,
           max_chunks: int = 100, max_events: Optional[int] = None,
           sync_every: int = 8):
    """Continue a simulation from a carried ``SimState`` (obtained via
    ``return_state=True``), e.g. after extending the horizon with a new
    ``SimConfig``. Valid because every policy schedules its TRUE next event
    time (never truncated at the old horizon), so an absorbed state wakes up
    under a later ``end_time`` with the correct distribution — the oracle's
    re-entrant ``Manager.run_till`` contract (SURVEY.md section 3.1).

    ``max_events`` bounds the events of THIS call (the oracle's re-entrant
    ``run_till(max_events=...)`` counts per call); None clears any budget a
    previous run_dynamic left on the carry.

    Returns (EventLog-of-the-extension, final state). Batched states resume
    batched."""
    batched = state.t_next.ndim == 2
    if max_events is not None:
        state = state.replace(
            budget=state.n_events + jnp.asarray(max_events, jnp.int32)
        )
    else:
        state = state.replace(budget=None)
    return _drive(
        cfg, params, adj, state, lambda k: _chunk_fn(cfg, batched, k),
        max_chunks, batched, sync_every
    )
