"""Whole-stream point-process samplers: all events of one source to a horizon.

The batch kernel (ops.scan_core) interleaves sources event-by-event because
policies there may react to each other. The big-F path
(redqueen_tpu.parallel.bigf) exploits the converse fact: the reference's wall
broadcasters — Poisson, Hawkes, PiecewiseConst, RealData (SURVEY.md section 2
items 4–7, reference redqueen/opt_model.py) — never react to other sources,
so each source's FULL event stream over [t0, T] can be sampled independently
and in parallel. These samplers return a fixed-capacity, +inf-padded times
vector plus the valid count; they are pure, jit/vmap-safe, and reuse the
per-draw primitives in ops.sampling so the two kernels cannot drift apart
distributionally.

Overflow is detected, never silent (SURVEY.md section 7 hard parts): each
sampler also returns ``truncated`` — True iff the buffer filled while events
before the horizon remained.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax
from jax import random as jr

from ..runtime.numerics import safe_div, safe_exp
from .sampling import hawkes_next_time, piecewise_next_time, rmtpp_next_delta

__all__ = [
    "Stream",
    "poisson_stream",
    "hawkes_stream",
    "piecewise_stream",
    "realdata_stream",
    "rmtpp_stream",
]


class Stream(NamedTuple):
    """One source's events on [t0, T]: ``times`` [cap] ascending, +inf-padded;
    ``n`` valid events; ``truncated`` True iff capacity cut the stream."""

    times: jnp.ndarray
    n: jnp.ndarray
    truncated: jnp.ndarray


def _finish(times, t0, T, dtype):
    times = jnp.asarray(times, dtype)
    valid = (times > t0) & (times <= T)
    times = jnp.where(valid, times, jnp.inf)
    times = jnp.sort(times)
    n = valid.sum()
    return times, n


def poisson_stream(key, rate, t0, T, cap: int) -> Stream:
    """Constant-rate Poisson events on (t0, T] (reference: ``Poisson`` /
    ``Poisson2`` — the precomputed-block and incremental variants are
    distributionally identical, SURVEY.md section 2 item 4): cumulative sum
    of exponential gaps, one batched draw. A probe draw beyond the buffer
    makes the truncation flag exact: True iff event cap+1 lands in-window."""
    dtype = jnp.result_type(rate, jnp.float32)
    gaps = jr.exponential(key, (cap + 1,), dtype)
    rate = jnp.asarray(rate, dtype)
    times_all = t0 + jnp.where(rate > 0, safe_div(jnp.cumsum(gaps), rate),
                               jnp.inf)
    times, n = _finish(times_all[:cap], t0, T, dtype)
    truncated = (rate > 0) & (times_all[cap] <= T)
    return Stream(times, n, truncated)


def hawkes_stream(key, l0, alpha, beta, t0, T, cap: int) -> Stream:
    """Exponential-kernel Hawkes events on (t0, T] (reference: ``Hawkes``,
    Ogata thinning per event — SURVEY.md section 3.3), as a scan over cap
    slots carrying (t, excitation)."""
    dtype = jnp.result_type(l0, jnp.float32)

    def step(carry, i):
        t, exc, exc_t = carry
        k = jr.fold_in(key, i)
        t_new = hawkes_next_time(k, t, l0, alpha, beta, exc, exc_t, T)
        fired = jnp.isfinite(t_new)
        exc = jnp.where(
            fired, exc * safe_exp(-beta * (jnp.where(fired, t_new, t) - exc_t))
            + alpha, exc
        )
        exc_t = jnp.where(fired, t_new, exc_t)
        t = jnp.where(fired, t_new, jnp.inf)
        return (t, exc, exc_t), t_new

    init = (jnp.asarray(t0, dtype), jnp.asarray(0.0, dtype),
            jnp.asarray(t0, dtype))
    # One probe slot past the buffer makes truncation exact: the stream was
    # cut iff an in-horizon event cap+1 exists.
    _, times_all = lax.scan(step, init, jnp.arange(cap + 1))
    times, n = _finish(times_all[:cap], t0, T, dtype)
    truncated = jnp.isfinite(times_all[cap])
    return Stream(times, n, truncated)


def piecewise_stream(key, change_times, rates, t0, T, cap: int) -> Stream:
    """Inhomogeneous-Poisson events on (t0, T] for a piecewise-constant rate
    (reference: ``PiecewiseConst``), one exact-inversion draw per slot."""
    dtype = jnp.result_type(change_times, jnp.float32)

    def step(t, i):
        k = jr.fold_in(key, i)
        t_new = jnp.where(
            jnp.isfinite(t),
            piecewise_next_time(k, t, change_times, rates), jnp.inf,
        )
        # Absorb once past the horizon — later events can't matter.
        return jnp.where(t_new > T, jnp.inf, t_new), t_new

    _, times_all = lax.scan(
        step, jnp.asarray(t0, dtype), jnp.arange(cap + 1)
    )
    times, n = _finish(times_all[:cap], t0, T, dtype)
    truncated = times_all[cap] <= T
    return Stream(times, n, truncated)


def realdata_stream(times, t0, T) -> Stream:
    """Replay of recorded timestamps clipped to (t0, T] (reference:
    ``RealData``; ``times`` is the +inf-padded [cap] replay row)."""
    dtype = jnp.result_type(times, jnp.float32)
    times, n = _finish(times, t0, T, dtype)
    return Stream(times, n, jnp.asarray(False))


def rmtpp_stream(weights, key, t0, T, cap: int, hidden: int) -> Stream:
    """Self-history-only RMTPP events on (t0, T] (BASELINE config 5 policy):
    the learned intensity depends only on the source's own past, so the whole
    stream samples independently — scan carrying (t, h)."""
    from ..models.rmtpp import _head, _step_h  # local import: avoids cycle

    dtype = jnp.float32

    def step(carry, i):
        t, h = carry
        k = jr.fold_in(key, i)
        a, w = _head(weights, h)
        tau = rmtpp_next_delta(k, a, w, dtype=dtype)
        t_new = t + tau
        fired = jnp.isfinite(t_new) & (t_new <= T)
        h = jnp.where(fired, _step_h(weights, h, tau), h)
        t = jnp.where(fired, t_new, jnp.inf)
        return (t, h), t_new

    init = (jnp.asarray(t0, dtype), jnp.zeros((hidden,), dtype))
    _, times_all = lax.scan(step, init, jnp.arange(cap + 1))
    times, n = _finish(times_all[:cap], t0, T, dtype)
    truncated = times_all[cap] <= T
    return Stream(times, n, truncated)
