"""Threefry-2x32 as pure 32-bit jnp ops — a counter-based PRNG usable INSIDE
Pallas kernels (and in interpret mode), bit-identical to JAX's own
``threefry_2x32`` (Salmon et al., "Parallel random numbers: as easy as
1, 2, 3", SC'11; validated against the random123 test vectors and against
``jax._src.prng`` in tests/test_pallas_chunk.py).

Why this exists: the TPU event-scan Pallas kernel (ops/pallas_chunk.py)
keeps all simulation state in VMEM across a whole chunk; its draws must be
generated in-kernel. ``pltpu.prng_random_bits`` has no interpret-mode
lowering, so the kernel instead uses this implementation — plain shifts,
xors and adds that Mosaic and the interpreter both handle, with the same
per-source (key, counter) stream discipline as the XLA engine.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["threefry2x32", "uniform_from_bits", "exponential_from_bits"]

# Python-int constants (not jnp scalars): Pallas kernels may not capture
# traced constant arrays, and uint32-array (op) python-int stays uint32.
_ROTATIONS = ((13, 15, 26, 6), (17, 29, 16, 24))
_PARITY = 0x1BD11BDA


def _rotl(x, d):
    return (x << d) | (x >> (32 - d))


def threefry2x32(k0, k1, x0, x1):
    """One threefry-2x32 block: key (k0, k1), counter (x0, x1) -> two uint32
    words. All inputs uint32 arrays of a common shape; vectorizes
    elementwise."""
    k0 = jnp.asarray(k0, jnp.uint32)
    k1 = jnp.asarray(k1, jnp.uint32)
    x0 = jnp.asarray(x0, jnp.uint32)
    x1 = jnp.asarray(x1, jnp.uint32)
    ks = (k0, k1, k0 ^ k1 ^ _PARITY)

    x0 = x0 + ks[0]
    x1 = x1 + ks[1]
    # 5 four-round groups with a key injection after each.
    for group in range(5):
        rots = _ROTATIONS[group % 2]
        for r in rots:
            x0 = x0 + x1
            x1 = _rotl(x1, r)
            x1 = x0 ^ x1
        x0 = x0 + ks[(group + 1) % 3]
        x1 = x1 + ks[(group + 2) % 3] + (group + 1)
    return x0, x1


def uniform_from_bits(bits):
    """uint32 bits -> float32 uniform in [0, 1): top 24 bits scaled by 2^-24.
    (Arithmetic rather than the bitcast mantissa trick so the same code
    lowers in Pallas/Mosaic, interpret mode, and plain XLA. The int32 detour
    is exact — the shifted value fits in 24 bits — and avoids the
    uint32->float32 cast Mosaic does not lower.)"""
    return (bits >> 8).astype(jnp.int32).astype(jnp.float32) * 2.0**-24


def exponential_from_bits(bits):
    """uint32 bits -> Exp(1) float32 draw: -log1p(-U), U in [0, 1)."""
    return -jnp.log1p(-uniform_from_bits(bits))
