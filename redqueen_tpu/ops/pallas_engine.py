"""The Pallas event megakernel: the batch engine's full per-step pipeline
fused into ONE kernel, run k chunks per launch ("superchunks"), with all
simulation state resident in VMEM across every step of every chunk.

Grown from the seed chunk engine (``ops/pallas_chunk.py``, Poisson+Opt
only, one ``pallas_call`` + one host round-trip per chunk) into the
repo's primary fused batch engine:

- **Full covered policy mix** — Poisson walls, Opt broadcasters, Hawkes
  excitation state, RealData replay cursors, and piecewise-constant
  rates all run inside the fused step (``ops/pallas_step.py``); only the
  RMTPP neural policy falls back to the scan engine
  (:func:`coverage` reports why, ``sim.select_engine`` dispatches).
- **Superchunk launches** — the grid is ``(lanes/128, k)``: the second,
  innermost axis runs k chunks back-to-back in ONE launch, carrying the
  state through revisited output blocks (fetched once per lane tile,
  written back once) while the per-chunk event-log blocks stream out
  double-buffered by the Pallas pipeline.  The host's liveness check is
  ONE replicated scalar per launch, so a bench run that used to cost
  ~one dispatch per chunk now costs ``chunks / k`` dispatches
  (``EventLog.dispatches`` records the count).
- **In-kernel lane health (PR 3 semantics)** — the per-lane uint32
  bitmask rides the kernel carry and freezes sick lanes exactly like
  the scan engine, so ``EventLog.health`` is populated by this path and
  the sweep-level quarantine/heal machinery is engine-agnostic.
- **Per-shape VMEM plan** — ``ops/pallas_vmem.plan_vmem`` prices every
  block and picks (capacity, k, tile) per config, degrading to the scan
  engine with a recorded reason instead of a Mosaic OOM.

Randomness: in-kernel threefry-2x32 (``ops/threefry.py``), bit-identical
to JAX's generator, so the SAME kernel runs compiled on TPU and under
``interpret=True`` on CPU for tests.  Streams differ from the XLA
engine's call pattern (PARITY.md): parity is statistical for the random
policies and BIT-IDENTICAL for replay-only mixes (no randomness), pinned
by tests/test_pallas_engine.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax
from jax.experimental import pallas as pl

from ..config import SimConfig, SourceParams
from ..models.base import (
    KIND_HAWKES,
    KIND_OPT,
    KIND_PIECEWISE,
    KIND_POISSON,
    KIND_REALDATA,
    get_registry,
)
from ..runtime import faultinject as _faultinject
from ..runtime import numerics as _numerics
from ..runtime import telemetry as _telemetry
from .pallas_step import KernelSpec, make_step, prepare_consts
from .pallas_vmem import TILE as _TILE
from .pallas_vmem import VmemPlan, plan_vmem
from .sampling import piecewise_next_from_target
from .threefry import exponential_from_bits, threefry2x32

__all__ = ["supports", "coverage", "simulate_pallas", "PallasState",
           "COVERED_KINDS", "CHUNK_CALL_CACHE"]


#: Policy kinds the fused step implements; everything else (RMTPP)
#: dispatches to the scan engine.
COVERED_KINDS = frozenset(
    (KIND_POISSON, KIND_OPT, KIND_HAWKES, KIND_REALDATA, KIND_PIECEWISE))


def coverage(cfg: SimConfig):
    """``(covered, reason)`` for a config's policy mix: ``reason`` is
    ``None`` when the megakernel covers it, else the recorded degrade
    provenance (``sim.select_engine`` surfaces it on the fallback)."""
    kinds = set(cfg.present_kinds)
    if not kinds:
        return False, (
            "config carries no present_kinds (hand-built SimConfig) — "
            "build through GraphBuilder so the kernel can specialize")
    extra = kinds - COVERED_KINDS
    if extra:
        reg = get_registry()
        names = ", ".join(sorted(
            reg[k].name if k in reg else f"kind{k}" for k in extra))
        return False, (
            f"policy kind(s) {{{names}}} have no fused-kernel "
            f"implementation (the RMTPP recurrence needs per-step hidden "
            f"state the megakernel does not carry) — the scan engine "
            f"covers them")
    return True, None


def supports(cfg: SimConfig) -> bool:
    """True iff the megakernel covers the config's policy mix."""
    return coverage(cfg)[0]


class PallasState(struct.PyTreeNode):
    """Host-side carry of the pallas engine, batch-first layout [B, ...]
    (``runtime.numerics.poison_lane`` operates on it like a SimState)."""

    t_next: jnp.ndarray    # [B, S]
    ctr: jnp.ndarray       # [B, S] uint32
    t: jnp.ndarray         # [B]
    n_events: jnp.ndarray  # [B] int32
    health: jnp.ndarray    # [B] uint32
    exc: jnp.ndarray       # [B, S] Hawkes excitation
    exc_t: jnp.ndarray     # [B, S] excitation fold time
    rd_ptr: jnp.ndarray    # [B, S] int32 replay cursors
    k0: jnp.ndarray        # [B, S] uint32 (constant across chunks)
    k1: jnp.ndarray


def _source_keys(seeds, S):
    """Per-(component, source) base keys with the engine's own discipline:
    (k0, k1) = threefry(seed, 0; source, 0) — layout-independent."""
    seeds = jnp.asarray(seeds, jnp.uint32)          # [B]
    src = jnp.arange(S, dtype=jnp.uint32)
    k0, k1 = threefry2x32(
        seeds[:, None], jnp.zeros_like(seeds)[:, None],
        src[None, :], jnp.zeros((1, S), jnp.uint32),
    )
    return k0, k1                                    # [B, S]


def _init_state(cfg: SimConfig, params: SourceParams, seeds) -> PallasState:
    """First draws for every covered kind, all from the engine's init
    stream (counter word x1=2, one Exp(1) per source): Poisson inverts
    Exp(rate); Hawkes from an empty history is exactly Exp(l0); RealData
    seeks the first replay timestamp at/after the start; piecewise
    inverts its cumulative hazard from the start time."""
    B, S = params.kind.shape
    k0, k1 = _source_keys(seeds, S)
    bits0, _ = threefry2x32(k0, k1, jnp.zeros_like(k0),
                            jnp.full_like(k0, 2))   # x1=2: the init stream
    e = exponential_from_bits(bits0)                # [B, S]
    kind = params.kind
    kinds = set(cfg.present_kinds)
    t0 = jnp.float32(cfg.start_time)
    # Poisson and empty-history Hawkes share the Exp(rate-like) inversion.
    rate_like = jnp.where(kind == KIND_HAWKES, params.l0, params.rate)
    t_exp = jnp.where(rate_like > 0,
                      t0 + e / jnp.maximum(rate_like, 1e-30), jnp.inf)
    t_next = jnp.where(
        (kind == KIND_POISSON) | (kind == KIND_HAWKES), t_exp, jnp.inf)
    rd_ptr = jnp.zeros((B, S), jnp.int32)
    if KIND_REALDATA in kinds:
        rd = params.rd_times
        Kr = rd.shape[-1]
        # First replay timestamp >= t0 (searchsorted 'left' over the
        # sorted trace, as a rank count so it vmaps freely).
        rd_ptr = jnp.sum(rd < t0, axis=-1).astype(jnp.int32)
        peek = jnp.take_along_axis(
            rd, jnp.minimum(rd_ptr, Kr - 1)[..., None], axis=-1)[..., 0]
        t_rd = jnp.where(rd_ptr < Kr, peek, jnp.inf)
        t_next = jnp.where(kind == KIND_REALDATA, t_rd, t_next)
    if KIND_PIECEWISE in kinds:
        t_pw = piecewise_next_from_target(
            e, t0, params.pw_times, params.pw_rates)
        t_next = jnp.where(kind == KIND_PIECEWISE, t_pw, t_next)
    return PallasState(
        t_next=t_next.astype(jnp.float32),
        ctr=jnp.zeros((B, S), jnp.uint32),
        t=jnp.full((B,), cfg.start_time, jnp.float32),
        n_events=jnp.zeros((B,), jnp.int32),
        health=jnp.zeros((B,), jnp.uint32),
        exc=jnp.zeros((B, S), jnp.float32),
        exc_t=jnp.full((B, S), cfg.start_time, jnp.float32),
        rd_ptr=rd_ptr,
        k0=k0, k1=k1,
    )


def _spec_for(cfg: SimConfig, S, F, Kr, Kp, k, capacity) -> KernelSpec:
    kinds = set(cfg.present_kinds)
    end_time = float(cfg.end_time)  # rqlint: disable=RQ701 host float
    return KernelSpec(
        S=S, F=F, Kr=Kr, Kp=Kp, tile=_TILE, capacity=capacity, k=k,
        end_time=end_time, opt_rows=cfg.opt_rows,
        has_opt=KIND_OPT in kinds, has_hawkes=KIND_HAWKES in kinds,
        has_rd=KIND_REALDATA in kinds, has_pw=KIND_PIECEWISE in kinds,
    )


def _io_names(spec: KernelSpec):
    """(param names, carry names) in kernel argument order — only the
    blocks the policy mix compiles exist at all."""
    ins = ["kind", "rate", "k0", "k1"]
    if spec.has_opt:
        ins += ["q", "ssink", "adj"]
    if spec.has_hawkes:
        ins += ["l0", "alpha", "beta"]
    if spec.has_rd:
        ins += ["rd_times"]
    if spec.has_pw:
        ins += ["pw_times", "pw_rates"]
    carry = ["t_next", "ctr", "t", "nev", "health"]
    if spec.has_hawkes:
        carry += ["exc", "exc_t"]
    if spec.has_rd:
        carry += ["rd_ptr"]
    return ins, carry


# Every carry slot the step function threads, in its fixed order; absent
# slots ride as None (an empty pytree node under fori_loop).
_CARRY_SLOTS = ("t_next", "ctr", "t", "nev", "health", "exc", "exc_t",
                "rd_ptr")

_CARRY_DTYPES = dict(t_next=jnp.float32, ctr=jnp.uint32, t=jnp.float32,
                     nev=jnp.int32, health=jnp.uint32, exc=jnp.float32,
                     exc_t=jnp.float32, rd_ptr=jnp.int32)


def _block_spec(name: str, spec: KernelSpec):
    """BlockSpec per logical input/carry block.  Carry/param blocks are
    constant along the chunk axis j — fetched once per lane tile and, for
    outputs, written back once when the tile advances (the revisited-
    block carry that keeps state on-chip across all k chunks)."""
    T = spec.tile
    if name in ("t", "nev", "health"):
        return pl.BlockSpec((T,), lambda i, j: (i,))
    if name == "ssink":
        return pl.BlockSpec((spec.F, T), lambda i, j: (0, i))
    if name == "adj":
        return pl.BlockSpec((spec.S, spec.F, T), lambda i, j: (0, 0, i))
    if name == "rd_times":
        return pl.BlockSpec((spec.S, spec.Kr, T), lambda i, j: (0, 0, i))
    if name in ("pw_times", "pw_rates"):
        return pl.BlockSpec((spec.S, spec.Kp, T), lambda i, j: (0, 0, i))
    return pl.BlockSpec((spec.S, T), lambda i, j: (0, i))


def _build_kernel(spec: KernelSpec):
    in_names, carry_names = _io_names(spec)
    n_params = len(in_names)
    n_in = n_params + len(carry_names)

    def kernel(*refs):
        params = dict(zip(in_names, refs[:n_params]))
        cin = refs[n_params:n_in]
        cout = refs[n_in:n_in + len(carry_names)]
        times_ref, srcs_ref = refs[n_in + len(carry_names):]
        j = pl.program_id(1)

        # First chunk of the superchunk: seed the carry-out blocks from
        # the carry-in blocks.  For j > 0 the out blocks are REVISITED
        # (same block index), so they still hold the previous chunk's
        # final state — the on-chip carry across all k chunks.
        @pl.when(j == 0)
        def _seed_carry():
            # Static unroll over the ref TUPLE (its length is a compile-
            # time fact of the policy mix), not a traced operand.
            for a, b in zip(cin, cout):
                b[:] = a[:]

        c = prepare_consts(spec, {nm: params[nm][:] for nm in in_names})
        carried = dict(zip(carry_names, (r[:] for r in cout)))
        carry0 = tuple(carried.get(nm) for nm in _CARRY_SLOTS)
        step = make_step(spec, c, times_ref, srcs_ref)
        out = lax.fori_loop(0, spec.capacity, step, carry0)
        final = dict(zip(_CARRY_SLOTS, out))
        # Static unroll over the carry-name list, not a traced operand.
        for nm, r in zip(carry_names, cout):  # rqlint: disable=RQ401 static
            r[:] = final[nm]

    return kernel


#: Bound on the compiled-callable cache (seed bug: ``lru_cache(None)``
#: leaked one compiled superchunk per (cfg, shape) forever — a sweep over
#: many configs grew without bound).  32 comfortably covers every live
#: shape a bench/sweep run cycles through; colder entries recompile.
CHUNK_CALL_CACHE = 32


@functools.lru_cache(maxsize=CHUNK_CALL_CACHE)
def _chunk_call(cfg: SimConfig, S: int, F: int, Kr: int, Kp: int, k: int,
                capacity: int, interpret: bool):
    spec = _spec_for(cfg, S, F, Kr, Kp, k, capacity)
    kernel = _build_kernel(spec)
    in_names, carry_names = _io_names(spec)
    T = _TILE
    end = float(cfg.end_time)

    def call(*args):
        # args: params then carry, lane-last, B_pad lanes (multiple of T).
        B = args[0].shape[-1]
        grid = (B // T, k)
        in_specs = [_block_spec(nm, spec) for nm in in_names + carry_names]
        out_specs = tuple(
            [_block_spec(nm, spec) for nm in carry_names]
            + [pl.BlockSpec((capacity, T), lambda i, j: (j, i))] * 2)

        def shp(nm):
            if nm in ("t", "nev", "health"):
                return jax.ShapeDtypeStruct((B,), _CARRY_DTYPES[nm])
            return jax.ShapeDtypeStruct((S, B), _CARRY_DTYPES[nm])

        out_shape = tuple(
            [shp(nm) for nm in carry_names]
            + [jax.ShapeDtypeStruct((k * capacity, B), jnp.float32),
               jax.ShapeDtypeStruct((k * capacity, B), jnp.int32)])
        outs = pl.pallas_call(
            kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
            out_shape=out_shape, interpret=interpret,
        )(*args)
        carry_out = outs[:len(carry_names)]
        times, srcs = outs[len(carry_names):]
        m = dict(zip(carry_names, carry_out))
        # The launch's ONE liveness scalar: any lane both unfinished and
        # healthy (a frozen sick lane must count as done, or it would
        # spin the superchunk loop to max_chunks).
        alive = jnp.any((jnp.min(m["t_next"], axis=0) <= end)
                        & (m["health"] == 0))
        return carry_out + (times, srcs, alive)

    return jax.jit(call)


def _pad(x, B_pad, fill):
    B = x.shape[-1]
    if B == B_pad:
        return x
    pad = [(0, 0)] * (x.ndim - 1) + [(0, B_pad - B)]
    return jnp.pad(x, pad, constant_values=fill)


def simulate_pallas(cfg: SimConfig, params: SourceParams, adj, seeds,
                    max_chunks: int = 100, interpret: Optional[bool] = None,
                    sync_every: Optional[int] = None,
                    plan: Optional[VmemPlan] = None):
    """Run a batch of components on the megakernel; returns an
    ``EventLog`` (same contract as ``sim.simulate_batch``, different PRNG
    streams — see module docstring).  ``params``/``adj`` carry a leading
    [B] dim; ``seeds`` is an int array [B].

    ``interpret`` defaults to True off-TPU (tests) and False on TPU.
    ``sync_every`` is the superchunk length k: chunks per LAUNCH, with
    the liveness round-trip amortized to one replicated scalar per
    launch (default 1 off-TPU — tests see per-chunk buffers — and 8 on
    TPU, where each sync is a tunnel RTT that dwarfs an absorbed chunk's
    compute; results are identical either way, later-trimmed padding
    aside).  ``EventLog.dispatches`` records the launch count; ``plan``
    overrides the per-shape VMEM plan (tests)."""
    from ..sim import EventLog  # local: avoid import cycle

    ok, why = coverage(cfg)
    if not ok:
        raise ValueError(
            f"pallas engine supports only "
            f"{{poisson, opt, hawkes, realdata, piecewise}} policy mixes "
            f"— {why}")
    seeds = jnp.asarray(seeds)
    if seeds.ndim != 1:
        raise ValueError(
            f"pallas engine takes integer seeds [B] (its per-source "
            f"threefry streams derive from them) — got shape "
            f"{tuple(seeds.shape)}; key-array seeds are a scan-engine "
            f"contract (sim.simulate_batch)")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if sync_every is None:
        sync_every = 1 if interpret else 8
    B, S = params.kind.shape
    F = adj.shape[-1]
    kinds = set(cfg.present_kinds)
    Kr = params.rd_times.shape[-1] if KIND_REALDATA in kinds else 0
    Kp = params.pw_times.shape[-1] if KIND_PIECEWISE in kinds else 0
    if plan is None:
        # int()/bool() below normalize HOST call options for the plan /
        # compile-cache key — no traced value is ever concretized here.
        plan = plan_vmem(cfg, S, F, Kr, Kp, k=int(sync_every))  # rqlint: disable=RQ701 host ints
    # The VMEM plan as a span event: what the planner picked (or why it
    # degraded) rides the trace next to the launches it shaped.
    _telemetry.event("engine.pallas.vmem_plan", fits=plan.fits,
                     k=plan.k, capacity=plan.capacity,
                     reason=plan.reason)
    if not plan.fits:
        raise ValueError(plan.reason)
    k, cap = plan.k, plan.capacity
    # Launch planning through the unified lane layer: the grid's lane
    # axis is B_pad/_TILE, and pad_to_tile records the occupancy (real
    # vs padded lanes) on the trace so tile waste is visible per launch.
    from ..parallel.lanes import pad_to_tile

    B_pad = pad_to_tile(B, _TILE)

    state = _init_state(cfg, params, seeds)
    # The env-configured ``numeric`` fault (RQ_FAULT=numeric:mode@laneN):
    # the same deterministic poisoning the scan driver applies, so the
    # detection/quarantine/heal paths run engine-agnostically in CI.
    hit = _faultinject.active_numeric_lane(B)
    if hit is not None:
        state = _numerics.poison_lane(state, hit[0], hit[1])

    # Lane layout: batch last.  Padded lanes: rate 0 / t_next inf =>
    # absorb from step 0 and never touch the health mask.
    to_lanes = lambda x, fill=0: _pad(  # noqa: E731
        jnp.moveaxis(jnp.asarray(x), 0, -1), B_pad, fill)
    args = {
        "kind": to_lanes(params.kind),
        "rate": to_lanes(params.rate.astype(jnp.float32)),
        "k0": to_lanes(state.k0),
        "k1": to_lanes(state.k1),
    }
    if KIND_OPT in kinds:
        args["q"] = to_lanes(params.q.astype(jnp.float32), 1.0)
        args["ssink"] = to_lanes(params.s_sink.astype(jnp.float32))
        args["adj"] = to_lanes(jnp.asarray(adj).astype(jnp.float32))
    if KIND_HAWKES in kinds:
        args["l0"] = to_lanes(params.l0.astype(jnp.float32))
        args["alpha"] = to_lanes(params.alpha.astype(jnp.float32))
        args["beta"] = to_lanes(params.beta.astype(jnp.float32), 1.0)
    if KIND_REALDATA in kinds:
        args["rd_times"] = to_lanes(
            params.rd_times.astype(jnp.float32), jnp.inf)
    if KIND_PIECEWISE in kinds:
        args["pw_times"] = to_lanes(
            params.pw_times.astype(jnp.float32), jnp.inf)
        args["pw_rates"] = to_lanes(params.pw_rates.astype(jnp.float32))
    carry = {
        "t_next": to_lanes(state.t_next, jnp.inf),
        "ctr": to_lanes(state.ctr),
        "t": _pad(state.t, B_pad, 0.0),
        "nev": _pad(state.n_events, B_pad, 0),
        "health": _pad(state.health, B_pad, 0),
    }
    if KIND_HAWKES in kinds:
        carry["exc"] = to_lanes(state.exc)
        carry["exc_t"] = to_lanes(state.exc_t)
    if KIND_REALDATA in kinds:
        carry["rd_ptr"] = to_lanes(state.rd_ptr)

    call = _chunk_call(cfg, S, F, Kr, Kp, k, cap, bool(interpret))  # rqlint: disable=RQ701 host bool
    spec = _spec_for(cfg, S, F, Kr, Kp, k, cap)
    in_names, carry_names = _io_names(spec)
    carry_vals = tuple(carry[nm] for nm in carry_names)
    param_vals = tuple(args[nm] for nm in in_names)

    # The overflow contract counts chunks of ``cfg.capacity`` events; a
    # VMEM-shrunk kernel capacity scales the allowance so the permitted
    # EVENT budget is unchanged.
    max_kernel_chunks = max_chunks * (-(-cfg.capacity // cap))
    n_launches = -(-max_kernel_chunks // k)
    times_chunks, srcs_chunks = [], []
    dispatches = 0
    # The with-statement (not a manual __enter__/__exit__) so a raising
    # run stamps its error attribute on the span; the inner finally
    # records the launch count on BOTH exits.
    with _telemetry.span("engine.pallas.run", k=k, capacity=cap,
                         interpret=bool(interpret), lanes=B,
                         lanes_padded=B_pad) as run_span:
        try:
            for _ in range(n_launches):
                # The launch span measures the superchunk ENQUEUE; the
                # device wait surfaces in the sync span at the liveness
                # scalar below (async-dispatch honesty, same split as
                # the scan driver).
                with _telemetry.span("engine.pallas.launch"):
                    *carry_vals, times_sc, srcs_sc, alive = call(
                        *param_vals, *carry_vals)
                    carry_vals = tuple(carry_vals)
                dispatches += 1
                times_chunks.append(times_sc[:, :B])
                srcs_chunks.append(srcs_sc[:, :B])
                # THE one liveness sync per superchunk launch: a single
                # replicated scalar, never per chunk, never per event.
                with _telemetry.span("engine.pallas.sync"):
                    done = not bool(alive)  # rqlint: disable=RQ702 one sync per superchunk
                if done:
                    break
            else:
                raise RuntimeError(
                    f"simulation still active after {max_kernel_chunks} "
                    f"chunks of {cap} events ({dispatches} superchunk "
                    f"launches) — raise capacity or max_chunks (refusing "
                    f"to truncate silently)")
        finally:
            run_span.set(dispatches=dispatches)
    _telemetry.counter("engine.pallas.launches", dispatches)

    out = dict(zip(carry_names, carry_vals))
    # The run's ONE results boundary (mirrors sim._drive's): the [B]
    # health mask and event counts cross to host once, after the last
    # launch — never per chunk, never per event.
    health = jax.device_get(out["health"][:B])  # rqlint: disable=RQ701 results boundary
    if health.size and np.all(health != 0):
        raise _numerics.NumericalHealthError(
            health, context=f"pallas simulation of {health.size} lane(s)")
    times = jnp.concatenate(times_chunks, axis=0).T   # [B, E]
    srcs = jnp.concatenate(srcs_chunks, axis=0).T
    nev = jax.device_get(out["nev"][:B])  # rqlint: disable=RQ701 results boundary
    return EventLog(times, srcs, nev, cfg,
                    health=jnp.asarray(health), dispatches=dispatches,
                    engine="pallas")
