"""Pallas event-scan chunk: the batch engine's hot loop as ONE fused TPU
kernel with all simulation state resident in VMEM.

Motivation (docs/DESIGN.md "Pallas status"): under XLA, every step of the
event scan streams the [B, S] state arrays HBM->VMEM->HBM; a chunk of
``capacity`` steps therefore moves ~capacity x state-size of HBM traffic.
This kernel runs the whole chunk inside one ``pallas_call`` — state loads
once, lives in registers/VMEM across all steps, and only the event log
(one (time, src) pair per step) is written out. The batch axis rides the
128-wide lane dimension; sources ride sublanes.

Scope: components whose policy mix is {Poisson walls, Opt broadcasters}
(the headline BASELINE shape — configs 1 and 3). Other mixes fall back to
the XLA engine (``supports`` reports False and callers dispatch there);
reference semantics are identical: argmin event selection with
lowest-index tie-break, absorbing steps past the horizon, per-source
(key, counter) PRNG streams (SURVEY.md sections 3.1-3.2).

Randomness: in-kernel threefry-2x32 (ops/threefry.py — bit-identical to
JAX's generator, pure 32-bit ops, so the SAME kernel runs compiled on TPU
and under ``interpret=True`` on CPU for tests). Streams differ from the
XLA engine's ``jax.random`` call pattern (documented in PARITY.md — parity
is statistical, pinned by tests/test_pallas_chunk.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from ..config import SimConfig, SourceParams
from ..models.base import KIND_OPT, KIND_POISSON
from .threefry import exponential_from_bits, threefry2x32

__all__ = ["supports", "simulate_pallas"]

_TILE = 128


def supports(cfg: SimConfig) -> bool:
    """True iff this kernel covers the config's policy mix."""
    kinds = set(cfg.present_kinds)
    return bool(kinds) and kinds <= {KIND_POISSON, KIND_OPT}


def vmem_bytes(cfg: SimConfig, S: int, F: int) -> int:
    """Per-grid-step VMEM footprint estimate of the kernel's blocks (4-byte
    words x 128 lanes): the [S, F, T] adjacency cube dominates, plus the
    [S, T] state/param rows, [F, T] rows, and the [capacity, T] event log
    pair."""
    rows_S = 7       # rate, q, is_opt, k0, k1, t_next, ctr
    rows_F = 2       # ssink, feeds_hit scratch
    return 4 * _TILE * (S * F + rows_S * S + rows_F * F + 2 * cfg.capacity + 4)


# v5e VMEM is 16 MiB/core; leave headroom for Mosaic's own scratch.
_VMEM_BUDGET = 12 * 2**20


def _check_vmem(cfg: SimConfig, S: int, F: int):
    """Host-side shape guard: the state-resident design bounds S*F and
    capacity; fail with a clear message instead of a Mosaic OOM deep in
    compilation (the scan/star engines cover larger shapes)."""
    need = vmem_bytes(cfg, S, F)
    if need > _VMEM_BUDGET:
        raise ValueError(
            f"pallas engine VMEM estimate {need / 2**20:.1f} MiB exceeds the "
            f"{_VMEM_BUDGET / 2**20:.0f} MiB budget (S={S}, F={F}, "
            f"capacity={cfg.capacity}; the [S, F, 128] adjacency block "
            f"dominates) — use the scan engine (sim.simulate_batch) or the "
            f"star engine (parallel.bigf) for this shape"
        )


def _kernel_body(cfg: SimConfig, opt_rows, rate_ref, q_ref, is_opt_ref,
                 adj_ref, ssink_ref, k0_ref, k1_ref, tnext_ref, ctr_ref,
                 t_ref, nev_ref, tnext_out, ctr_out, t_out, nev_out,
                 times_ref, srcs_ref):
    S = rate_ref.shape[0]
    T = rate_ref.shape[1]
    # Python scalars, not jnp constants: pallas kernels may not capture
    # traced constant arrays.
    end = float(cfg.end_time)
    inf = float(np.inf)

    rate = rate_ref[:]          # [S, T]
    is_opt = is_opt_ref[:]      # [S, T] f32 mask
    adj = adj_ref[:]            # [S, F, T] f32 mask
    ssink = ssink_ref[:]        # [F, T]
    q = q_ref[:]                # [S, T]
    k0 = k0_ref[:]              # [S, T] uint32
    k1 = k1_ref[:]
    iota_s = lax.broadcasted_iota(jnp.int32, (S, T), 0)
    # sqrt(s_f / q_r) panel per opt row, hoisted out of the loop.
    opt_rates = {
        r: jnp.sqrt(ssink / jnp.maximum(q[r][None, :], 1e-30))  # [F, T]
        for r in opt_rows
    }

    def step(i, carry):
        t_next, ctr, t, nev = carry

        tmin = jnp.min(t_next, axis=0)                       # [T]
        prio = jnp.where(t_next == tmin[None, :], iota_s, S)
        s_star = jnp.min(prio, axis=0)                       # [T] lowest idx
        ff = (iota_s == s_star[None, :]).astype(jnp.float32)  # [S, T] onehot
        valid = (tmin <= end) & (s_star < S)                 # [T]

        # ---- fired source resamples (Poisson -> new Exp; Opt -> inf) ----
        # int32 detours: Mosaic lowers f32->i32, bool->i32 and i32->u32 but
        # not f32->u32 / bool->u32 directly.
        ffu = ff.astype(jnp.int32).astype(jnp.uint32)
        k0f = jnp.sum(k0 * ffu, axis=0)                      # [T] fired key
        k1f = jnp.sum(k1 * ffu, axis=0)
        ctrf = jnp.sum(ctr * ffu, axis=0)
        bits0, _ = threefry2x32(k0f, k1f, ctrf, jnp.zeros_like(ctrf))
        e = exponential_from_bits(bits0)                     # [T]
        ratef = jnp.sum(rate * ff, axis=0)
        optf = jnp.sum(is_opt * ff, axis=0) > 0.5
        t_new = jnp.where(
            optf | (ratef <= 0), inf, tmin + e / jnp.maximum(ratef, 1e-30)
        )
        sel = (ff > 0.5) & valid[None, :]
        t_next = jnp.where(sel, t_new[None, :], t_next)
        ctr = ctr + (ffu * valid.astype(jnp.int32).astype(jnp.uint32))

        # ---- react: each Opt row spawns a superposition clock ----
        feeds_hit = jnp.sum(adj * ff[:, None, :], axis=0)    # [F, T]
        for r in opt_rows:
            aff = adj[r] * feeds_hit                         # [F, T]
            rs = jnp.sum(aff * opt_rates[r], axis=0)         # [T]
            react = (rs > 0) & (s_star != r) & valid
            bits_r, _ = threefry2x32(
                k0[r], k1[r], ctr[r], jnp.ones((T,), jnp.uint32)
            )
            cand = tmin + exponential_from_bits(bits_r) / jnp.maximum(rs, 1e-30)
            t_next = t_next.at[r].set(
                jnp.where(react, jnp.minimum(t_next[r], cand), t_next[r])
            )
            ctr = ctr.at[r].set(ctr[r] + react.astype(jnp.int32).astype(jnp.uint32))

        # ---- emit event, advance clock (absorbing past horizon) ----
        times_ref[i, :] = jnp.where(valid, tmin, inf)
        srcs_ref[i, :] = jnp.where(valid, s_star, -1)
        t = jnp.where(valid, tmin, t)
        nev = nev + valid.astype(jnp.int32)
        return t_next, ctr, t, nev

    t_next, ctr, t, nev = lax.fori_loop(
        0, cfg.capacity, step,
        (tnext_ref[:], ctr_ref[:], t_ref[:], nev_ref[:]),
    )
    tnext_out[:] = t_next
    ctr_out[:] = ctr
    t_out[:] = t
    nev_out[:] = nev


class PallasState:
    """Host-side carry of the pallas engine (batch-first layout [B, ...])."""

    def __init__(self, t_next, ctr, t, n_events, k0, k1):
        self.t_next = t_next    # [B, S]
        self.ctr = ctr          # [B, S] uint32
        self.t = t              # [B]
        self.n_events = n_events  # [B] int32
        self.k0 = k0            # [B, S] uint32 (constant across chunks)
        self.k1 = k1


def _source_keys(seeds, S):
    """Per-(component, source) base keys with the engine's own discipline:
    (k0, k1) = threefry(seed, 0; source, 0) — layout-independent."""
    seeds = jnp.asarray(seeds, jnp.uint32)          # [B]
    src = jnp.arange(S, dtype=jnp.uint32)
    k0, k1 = threefry2x32(
        seeds[:, None], jnp.zeros_like(seeds)[:, None],
        src[None, :], jnp.zeros((1, S), jnp.uint32),
    )
    return k0, k1                                    # [B, S]


def _init_state(cfg: SimConfig, params: SourceParams, seeds) -> PallasState:
    B = params.kind.shape[0]
    S = cfg.n_sources
    k0, k1 = _source_keys(seeds, S)
    bits0, _ = threefry2x32(k0, k1, jnp.zeros_like(k0),
                            jnp.full_like(k0, 2))   # x1=2: the init stream
    e = exponential_from_bits(bits0)                # [B, S]
    rate = params.rate
    is_poisson = params.kind == KIND_POISSON
    t_next = jnp.where(
        is_poisson & (rate > 0),
        jnp.float32(cfg.start_time) + e / jnp.maximum(rate, 1e-30),
        jnp.inf,
    ).astype(jnp.float32)
    return PallasState(
        t_next=t_next,
        ctr=jnp.zeros((B, S), jnp.uint32),
        t=jnp.full((B,), cfg.start_time, jnp.float32),
        n_events=jnp.zeros((B,), jnp.int32),
        k0=k0, k1=k1,
    )


@functools.lru_cache(maxsize=None)
def _chunk_call(cfg: SimConfig, S: int, F: int, interpret: bool):
    kernel = functools.partial(_kernel_body, cfg, cfg.opt_rows)
    T = _TILE
    grid = lambda B: (B // T,)  # noqa: E731

    def call(rate, q, is_opt, adj, ssink, k0, k1, t_next, ctr, t, nev):
        B = rate.shape[-1]
        row = pl.BlockSpec((S, T), lambda i: (0, i))
        rowF = pl.BlockSpec((F, T), lambda i: (0, i))
        cube = pl.BlockSpec((S, F, T), lambda i: (0, 0, i))
        vec = pl.BlockSpec((T,), lambda i: (i,))
        log = pl.BlockSpec((cfg.capacity, T), lambda i: (0, i))
        f32, u32, i32 = jnp.float32, jnp.uint32, jnp.int32
        out_shape = (
            jax.ShapeDtypeStruct((S, B), f32),     # t_next
            jax.ShapeDtypeStruct((S, B), u32),     # ctr
            jax.ShapeDtypeStruct((B,), f32),       # t
            jax.ShapeDtypeStruct((B,), i32),       # n_events
            jax.ShapeDtypeStruct((cfg.capacity, B), f32),   # times
            jax.ShapeDtypeStruct((cfg.capacity, B), i32),   # srcs
        )
        return pl.pallas_call(
            kernel,
            grid=grid(B),
            in_specs=[row, row, row, cube, rowF, row, row, row, row, vec, vec],
            out_specs=(row, row, vec, vec, log, log),
            out_shape=out_shape,
            interpret=interpret,
        )(rate, q, is_opt, adj, ssink, k0, k1, t_next, ctr, t, nev)

    return jax.jit(call)


def _pad(x, B_pad, fill):
    B = x.shape[-1]
    if B == B_pad:
        return x
    pad = [(0, 0)] * (x.ndim - 1) + [(0, B_pad - B)]
    return jnp.pad(x, pad, constant_values=fill)


def simulate_pallas(cfg: SimConfig, params: SourceParams, adj, seeds,
                    max_chunks: int = 100, interpret: Optional[bool] = None,
                    sync_every: Optional[int] = None):
    """Run a batch of components on the Pallas engine; returns an
    ``EventLog`` (same contract as ``sim.simulate_batch``, different PRNG
    streams — see module docstring). ``params``/``adj`` carry a leading [B]
    dim; ``seeds`` is an int array [B].

    ``interpret`` defaults to True off-TPU (tests) and False on TPU.
    ``sync_every`` is the liveness-check cadence of the chunk loop: the
    device->host `any(alive)` round-trip runs every that many chunks
    (default 1 off-TPU — tests see per-chunk buffers — and 8 on TPU, where
    each sync is a tunnel RTT that dwarfs an absorbed chunk's compute;
    results are identical either way, later-trimmed padding aside).
    """
    from ..sim import EventLog  # local: avoid import cycle

    if not supports(cfg):
        raise ValueError(
            f"pallas engine supports only Poisson+Opt components, got "
            f"present_kinds={cfg.present_kinds}"
        )
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if sync_every is None:
        sync_every = 1 if interpret else 8
    B, S = params.kind.shape
    F = adj.shape[-1]
    _check_vmem(cfg, S, F)
    B_pad = -(-B // _TILE) * _TILE

    state = _init_state(cfg, params, jnp.asarray(seeds))
    # Lane layout: batch last. Padded lanes: rate 0 / t_next inf => absorb.
    to_lanes = lambda x, fill=0: _pad(  # noqa: E731
        jnp.moveaxis(jnp.asarray(x), 0, -1), B_pad, fill
    )
    rate = to_lanes(params.rate.astype(jnp.float32))
    q = to_lanes(params.q.astype(jnp.float32), 1.0)
    is_opt = to_lanes((params.kind == KIND_OPT).astype(jnp.float32))
    adj_l = to_lanes(jnp.asarray(adj).astype(jnp.float32))
    ssink = to_lanes(params.s_sink.astype(jnp.float32))
    k0 = to_lanes(state.k0)
    k1 = to_lanes(state.k1)
    t_next = to_lanes(state.t_next, jnp.inf)
    ctr = to_lanes(state.ctr)
    t = _pad(state.t, B_pad, 0.0)
    nev = _pad(state.n_events, B_pad, 0)

    call = _chunk_call(cfg, S, F, bool(interpret))
    times_chunks, srcs_chunks = [], []
    for i in range(max_chunks):
        t_next, ctr, t, nev, times_c, srcs_c = call(
            rate, q, is_opt, adj_l, ssink, k0, k1, t_next, ctr, t, nev
        )
        times_chunks.append(times_c[:, :B])
        srcs_chunks.append(srcs_c[:, :B])
        check = (i % sync_every == sync_every - 1) or (i == max_chunks - 1)
        # The docstring's cadence-controlled liveness round-trip: ONE
        # scalar sync every `sync_every` chunks, never per event.
        if check and not bool(  # rqlint: disable=RQ702 cadence-gated sync
            jnp.any(jnp.min(t_next, axis=0) <= cfg.end_time)
        ):
            break
    else:
        raise RuntimeError(
            f"simulation still active after {max_chunks} chunks of "
            f"{cfg.capacity} events — raise capacity or max_chunks "
            f"(refusing to truncate silently)"
        )
    times = jnp.concatenate(times_chunks, axis=0).T   # [B, E]
    srcs = jnp.concatenate(srcs_chunks, axis=0).T
    return EventLog(times, srcs, jax.device_get(nev[:B]), cfg)
