"""Back-compat shim: the seed per-chunk Pallas engine grew into the
full-mix megakernel (``ops/pallas_engine.py`` — superchunk launches,
Hawkes/RealData/piecewise coverage, in-kernel lane health, per-shape
VMEM planning via ``ops/pallas_vmem.py``).  Import from those modules;
this one only preserves the seed entry points for existing callers.
"""

from __future__ import annotations

from .pallas_engine import PallasState, simulate_pallas, supports  # noqa: F401
from .pallas_vmem import DEFAULT_VMEM_BUDGET as _VMEM_BUDGET  # noqa: F401
from .pallas_vmem import plan_vmem, vmem_bytes  # noqa: F401

__all__ = ["supports", "simulate_pallas"]
