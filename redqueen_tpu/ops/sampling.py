"""Point-process sampling primitives as pure, jit/vmap-safe JAX functions.

These are the TPU-native equivalents of the inline samplers in the reference's
``redqueen/opt_model.py`` broadcasters (SURVEY.md sections 2–3; mount empty at
build time, see SURVEY.md section 0): exponential inter-arrival draws
(Poisson), Ogata thinning for exponential-kernel Hawkes intensities rewritten
as a ``lax.while_loop`` (SURVEY.md section 3.3), and exact inverse-CDF
sampling for piecewise-constant rates. All take explicit PRNG keys and
dtype-follow their float inputs.

Numerics discipline (the in-computation guard, ``runtime.numerics``): every
exp/log/division below goes through ``safe_exp``/``safe_log``/``safe_div``
(enforced statically by ``tools/check_resilience.py``'s third AST pass),
and the two ``log1p`` sites whose argument domain is NOT structural — a
model-produced ``z`` that can approach -1, unlike the panel/threefry
uniforms that are < 1 by construction — route through ``safe_log1p``.  All
guards are bit-identical to the raw ops on healthy inputs,
finite-and-detectable on poisoned ones, and the thinning loop is
proposal-capped, so no degenerate parameter can spin a device or launder a
NaN into an event log.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax import random as jr

from ..runtime.numerics import (
    DEFAULT_MAX_PROPOSALS,
    safe_div,
    safe_exp,
    safe_log1p,
)

__all__ = [
    "exponential_delta",
    "exponential_from_uniform",
    "hawkes_intensity",
    "hawkes_next_time",
    "piecewise_next_time",
    "piecewise_next_from_target",
    "rmtpp_next_delta",
    "rmtpp_log_intensity",
    "rmtpp_cum_hazard",
]


def exponential_delta(key, rate, dtype=None):
    """One Exp(rate) inter-arrival time; inf when rate <= 0 (a zero-rate
    process never fires — used for masked/padded sources)."""
    if dtype is None:
        dtype = jnp.result_type(rate, jnp.float32)
    e = jr.exponential(key, dtype=dtype)
    rate = jnp.asarray(rate, dtype)
    return jnp.where(rate > 0, safe_div(e, rate), jnp.inf)


def exponential_from_uniform(u, rate, dtype=None):
    """Exp(rate) inter-arrival from a pre-drawn Uniform[0,1) word — the fused
    per-step draw panel of ops.scan_core (one batched ``jr.uniform`` per scan
    step replaces per-source fold_in/exponential threefry chains; same law,
    ~half the PRNG work). Matches ``jr.exponential``'s -log1p(-u) transform;
    inf when rate <= 0."""
    if dtype is None:
        dtype = jnp.result_type(u, jnp.float32)
    e = -safe_log1p(-jnp.asarray(u, dtype))
    rate = jnp.asarray(rate, dtype)
    return jnp.where(rate > 0, safe_div(e, rate), jnp.inf)


def hawkes_intensity(t, l0, exc, exc_t, beta):
    """lambda(t) = l0 + exc * exp(-beta (t - exc_t)) for t >= exc_t, where
    ``exc`` is the excitation sum alpha * sum_j exp(-beta (exc_t - t_j))
    tracked incrementally at time ``exc_t``.  ``safe_exp`` keeps a
    degenerate (negative-beta / time-reversed) exponent from overflowing
    to +inf — the intensity stays finite and the health layer can see it."""
    return l0 + exc * safe_exp(-beta * (t - exc_t))


def hawkes_next_time(key, t_from, l0, alpha, beta, exc, exc_t, t_max,
                     bound_scale=1.0, max_proposals=DEFAULT_MAX_PROPOSALS,
                     return_ok=False):
    """Next event time of an exponential-kernel Hawkes process after
    ``t_from``, via Ogata thinning (reference: ``Hawkes.get_next_event_time``;
    SURVEY.md section 3.3).

    Because the exponential-kernel intensity strictly decreases between
    events, the intensity at the current proposal time is a valid upper bound
    for all later times — each rejection therefore *tightens* the bound, and
    the acceptance probability is bounded below by l0/lambda_bar, so the loop
    terminates almost surely. ``t_max`` caps the search (proposals beyond it
    exit the loop and return +inf) so all-masked vmap lanes cannot spin.

    ``bound_scale`` (>= 1) inflates every upper bound by that factor. The
    accepted-time DISTRIBUTION is invariant to it — that is the defining
    correctness property of thinning (SURVEY.md section 4.3; a biased
    accept test would shift with the bound) — only the expected number of
    proposals changes. The default 1.0 multiplies bounds by exactly 1
    (IEEE identity), leaving existing streams bit-identical; tests pin the
    invariance statistically at scale 3.

    ``max_proposals`` is defense-in-depth against degenerate parameters
    (a NaN/overflowed bound whose accept test can never pass) spinning the
    device: after that many proposals the loop exits and the function
    returns +inf.  Valid parameters accept within a handful of proposals,
    so the huge default is unreachable — and the counter changes no draw,
    so healthy streams stay bit-identical.

    Returns the accepted absolute time, or +inf if none before ``t_max``.
    With ``return_ok=True`` returns ``(time, ok)`` where ``ok=False``
    flags a sampler failure — the proposal cap was exhausted or the
    initial intensity bound was NaN — for the caller to feed the
    lane-health protocol (``SourceUpdate.ok`` -> ``BIT_SAMPLER_FAILURE``).
    """
    if isinstance(bound_scale, (int, float)) and bound_scale < 1.0:
        # A deflated bound silently biases acceptance early (probability
        # clamps at 1); catch the common static-float misuse host-side.
        raise ValueError(
            f"bound_scale must be >= 1 (got {bound_scale}): a bound below "
            f"the true intensity biases the thinning accept test"
        )
    if not max_proposals >= 1:  # `not >=` also rejects NaN
        raise ValueError(f"max_proposals must be >= 1, got {max_proposals}")
    dtype = jnp.result_type(t_from, l0, jnp.float32)
    t_from = jnp.asarray(t_from, dtype)
    scale = jnp.asarray(bound_scale, dtype)
    lbd0 = hawkes_intensity(t_from, l0, exc, exc_t, beta) * scale

    def cond(c):
        n, _, t, accepted, lbd_bar = c
        return ((~accepted) & (t <= t_max) & (lbd_bar > 0)
                & (n < max_proposals))

    def body(c):
        n, key, t, _, lbd_bar = c
        key, k_w, k_u = jr.split(key, 3)
        t_new = t + safe_div(jr.exponential(k_w, dtype=dtype), lbd_bar)
        lbd_new = hawkes_intensity(t_new, l0, exc, exc_t, beta)
        accept = jr.uniform(k_u, dtype=dtype) * lbd_bar <= lbd_new
        return (n + 1, key, t_new, accept, lbd_new * scale)

    n_out, _, t_out, accepted, lbd_out = lax.while_loop(
        cond, body,
        (jnp.zeros((), jnp.int32), key, t_from, jnp.asarray(False), lbd0),
    )
    t_ret = jnp.where(accepted & (t_out <= t_max), t_out, jnp.inf)
    if not return_ok:
        return t_ret
    # Cap-exhaustion: the loop stopped while its other conditions still
    # held.  A NaN bound exits immediately (every comparison is False)
    # without tripping that test, so flag it explicitly too.
    cap_hit = ((~accepted) & (t_out <= t_max) & (lbd_out > 0)
               & (n_out >= max_proposals))
    ok = ~(cap_hit | jnp.isnan(lbd0))
    return t_ret, ok


def piecewise_next_from_target(target, t_from, change_times, rates):
    """Exact cumulative-hazard inversion for a piecewise-constant rate,
    from a PRE-DRAWN Exp(1) target — the key-free core of
    :func:`piecewise_next_time`, shared with the Pallas megakernel's
    counter-addressed init stream (``ops.pallas_engine``), which draws
    its exponentials from in-kernel threefry rather than ``jax.random``.

    Batched: ``change_times``/``rates`` are [..., K] with the segment
    axis LAST; ``target`` matches the leading shape and ``t_from``
    broadcasts against ``change_times``. Value-identical to the original
    scalar formulation (the segment lookup is ``searchsorted`` rewritten
    as a rank count so it vectorizes over arbitrary leading axes)."""
    dtype = jnp.result_type(t_from, change_times, jnp.float32)
    target = jnp.asarray(target, dtype)
    K = rates.shape[-1]
    seg_end = jnp.concatenate(
        [change_times[..., 1:],
         jnp.full_like(change_times[..., :1], jnp.inf)], axis=-1)
    lo = jnp.maximum(change_times, t_from)  # effective start of each segment
    span = jnp.maximum(seg_end - lo, 0.0)
    # rate * span with 0 * inf := 0 (zero-rate final/padding segments).
    hz = jnp.where(rates > 0, rates * jnp.minimum(span, jnp.inf), 0.0)
    hz = jnp.where(span > 0, hz, 0.0)
    cum = jnp.cumsum(hz, axis=-1)
    # searchsorted 'left' as a rank count: first segment reaching E.
    k = jnp.sum(cum < target[..., None], axis=-1)
    k_safe = jnp.minimum(k, K - 1)
    prev_idx = jnp.maximum(k_safe - 1, 0)
    prev = jnp.where(
        k_safe > 0,
        jnp.take_along_axis(cum, prev_idx[..., None], axis=-1)[..., 0],
        0.0)
    remaining = target - prev
    rate_k = jnp.take_along_axis(rates, k_safe[..., None], axis=-1)[..., 0]
    lo_k = jnp.take_along_axis(lo, k_safe[..., None], axis=-1)[..., 0]
    t_hit = lo_k + jnp.where(rate_k > 0, safe_div(remaining, rate_k),
                             jnp.inf)
    return jnp.where(k < K, t_hit, jnp.inf).astype(dtype)


def piecewise_next_time(key, t_from, change_times, rates):
    """Next event of an inhomogeneous Poisson process with piecewise-constant
    rate, by exact inversion of the cumulative hazard (reference:
    ``PiecewiseConst``); branch-free, so it vectorizes cleanly.

    ``change_times`` [K] ascending segment starts; ``rates`` [K];
    ``rates[k]`` applies on [change_times[k], change_times[k+1]), the last
    segment extending to +inf. The rate before ``change_times[0]`` is 0.
    Padding convention: repeat the last knot with rate 0.

    Draws E ~ Exp(1) and returns the time where the hazard accumulated from
    ``t_from`` reaches E, or +inf if total remaining hazard < E.
    """
    dtype = jnp.result_type(t_from, change_times, jnp.float32)
    target = jr.exponential(key, dtype=dtype)
    return piecewise_next_from_target(target, t_from, change_times, rates)


def rmtpp_log_intensity(a, w, tau):
    """RMTPP conditional intensity (Du et al. 2016, the neural policy of
    BASELINE config 5): log lambda(tau) = a + w * tau, with a = v.h + b the
    history embedding and tau the time since the source's last own event."""
    return a + w * tau


def rmtpp_cum_hazard(a, w, tau):
    """Integral of exp(a + w u) du over [0, tau]: exp(a) * expm1(w tau) / w,
    with the w -> 0 limit exp(a) * tau handled stably."""
    small = jnp.abs(w) < 1e-6
    w_safe = jnp.where(small, 1.0, w)
    return safe_exp(a) * jnp.where(
        small, tau, safe_div(jnp.expm1(w * tau), w_safe)
    )


def rmtpp_next_delta(key, a, w, dtype=None):
    """Exact inverse-CDF sample of the next inter-event time for the RMTPP
    intensity exp(a + w tau). No thinning loop: Lambda(tau) = E with
    E ~ Exp(1) inverts in closed form, tau = log1p(w E exp(-a)) / w. When
    w < 0 the total hazard is finite (exp(a)/(-w)); draws beyond it mean the
    process never fires again (+inf)."""
    if dtype is None:
        dtype = jnp.result_type(a, jnp.float32)
    e = jr.exponential(key, dtype=dtype)
    small = jnp.abs(w) < 1e-6
    w_safe = jnp.where(small, 1.0, w)
    z = w * e * safe_exp(-a)
    tau = jnp.where(
        small,
        e * safe_exp(-a),              # w ~ 0: constant intensity exp(a)
        jnp.where(z > -1.0, safe_div(safe_log1p(z), w_safe), jnp.inf),
    )
    return tau.astype(dtype)
