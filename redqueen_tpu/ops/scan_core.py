"""The event-scan kernel: the reference's ``Manager.run_till`` hot loop
(SURVEY.md section 3.1) re-designed as a fixed-capacity ``lax.scan``.

One scan step == one global event: argmin over the per-source next-event
times picks the fired source (ties -> lowest index, matching the NumPy
oracle's ``np.argmin``), the fired source's resample dispatches through
``lax.switch`` over the registered policy branches, and every registered
react hook (RedQueen's superposition trick) adjusts the remaining sources.
Feed ranks are never materialized in the carry — the superposition clocks
encode them implicitly and the metric layer reconstructs them from the log. Steps after the horizon
are absorbing no-ops, so a chunk is always a statically-shaped computation:
XLA traces it once and the TPU replays it for every chunk of every
simulation of the sweep.

The per-event Python-object churn this deletes is the O(events x sources)
cost called out in SURVEY.md section 3.1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import random as jr

from ..config import SimConfig, SimState, SourceParams
from ..models.base import get_registry
from ..runtime import numerics

__all__ = ["init_state", "make_run_chunk"]


def _normalize_ok(branch):
    """Branch wrapper: coerce ``SourceUpdate.ok`` (Python-bool default for
    policies whose samplers cannot fail, traced bool for e.g. Hawkes
    thinning) to one traced scalar so every ``lax.switch`` branch returns
    an identical pytree structure."""

    def wrapped(*args):
        upd = branch(*args)
        return upd._replace(ok=jnp.asarray(upd.ok, bool))

    return wrapped


def _kinds_for(cfg: SimConfig):
    """Static branch set: only the policy kinds present in the component
    (cfg.present_kinds, filled by GraphBuilder) — a Poisson+Opt component
    never compiles the Hawkes thinning loop. Empty tuple (hand-built
    configs) falls back to every registered kind."""
    reg = get_registry()
    return list(cfg.present_kinds) if cfg.present_kinds else sorted(reg)


def _fire_branches(cfg):
    reg = get_registry()
    return [_normalize_ok(reg[k].on_fire) for k in _kinds_for(cfg)]


def _init_branches(cfg):
    reg = get_registry()
    return [_normalize_ok(reg[k].on_init) for k in _kinds_for(cfg)]


def _react_hooks(cfg):
    reg = get_registry()
    return [
        reg[k].on_react for k in _kinds_for(cfg) if reg[k].on_react is not None
    ]


def _local_kind(cfg, kind):
    """Map global kind codes to indices into the compiled branch list."""
    kinds = _kinds_for(cfg)
    if kinds == list(range(len(kinds))):
        return kind  # identity mapping, skip the gather
    lookup = np.zeros(max(kinds) + 1, np.int32)
    for i, k in enumerate(kinds):
        lookup[k] = i
    return jnp.asarray(lookup)[kind]


def init_state(cfg: SimConfig, params: SourceParams, adj, key,
               dtype=jnp.float32) -> SimState:
    """Build the initial carry: per-source PRNG streams and first draws.

    Per-source keys are ``fold_in(component_key, source_index)`` and every
    subsequent draw is ``fold_in(key_s, counter_s)`` — SURVEY.md section 7
    "PRNG discipline": streams depend only on (component key, source index,
    draw count), never on vmap/mesh layout.
    """
    S = cfg.n_sources
    H = cfg.rmtpp_hidden
    keys = jax.vmap(lambda i: jr.fold_in(key, i))(jnp.arange(S))
    t0 = jnp.asarray(cfg.start_time, dtype)
    state0 = SimState(
        t=t0,
        t_next=jnp.full((S,), jnp.inf, dtype),
        exc=jnp.zeros((S,), dtype),
        exc_t=jnp.full((S,), t0, dtype),
        rd_ptr=jnp.zeros((S,), jnp.int32),
        h=jnp.zeros((S, H), dtype),
        key=key,
        keys=keys,
        ctr=jnp.zeros((S,), jnp.uint32),
        n_events=jnp.zeros((), jnp.int32),
        health=jnp.zeros((), jnp.uint32),
    )
    branches = _init_branches(cfg)
    kind_local = _local_kind(cfg, params.kind)
    init_keys = jax.vmap(jr.fold_in)(keys, jnp.zeros((S,), jnp.uint32))

    def one(s, kl, k):
        return lax.switch(kl, branches, params, state0, s, t0, k)

    upd = jax.vmap(one, in_axes=(0, 0, 0))(jnp.arange(S), kind_local, init_keys)
    # First draws are already health-checked: a NaN first time (poisoned
    # params that slipped host validation) or a failed sampler marks the
    # lane sick from step 0, and the NaN is sanitized to +inf so it can
    # never reach the argmin.  Healthy components take the identity path.
    bits = jnp.where(jnp.isnan(upd.t_next).any(),
                     jnp.uint32(numerics.BIT_NONFINITE_TIME), jnp.uint32(0))
    bits |= jnp.where((~upd.ok).any(),
                      jnp.uint32(numerics.BIT_SAMPLER_FAILURE), jnp.uint32(0))
    return state0.replace(
        t_next=numerics.nan_to_posinf(upd.t_next), exc=upd.exc,
        exc_t=upd.exc_t, rd_ptr=upd.rd_ptr,
        h=upd.h, ctr=jnp.ones((S,), jnp.uint32), health=bits,
    )


def _panel_pairs(cfg: SimConfig, has_react: bool):
    """Static threefry pair indices covering the step's draw-panel slots.

    Slot layout: word 0 = the fire draw; word 1+s = source s's react draw.
    Words come from ``threefry2x32(component_key, (event_index, pair))`` —
    pair j yields words (2j, 2j+1) — so each slot is directly addressable
    and an unrolled-opt config (models.opt.unrolled_rows) pays for exactly
    the pairs its slots touch: the headline Poisson+Opt component needs ONE
    threefry block per step (slots {0, 1+opt_row}). The vectorized fallback
    covers all S+1 slots."""
    from ..models.opt import unrolled_rows

    S = cfg.n_sources
    rows = unrolled_rows(cfg) if has_react else ()
    if rows is None:
        slots = list(range(S + 1))
    else:
        slots = [0] + [1 + r for r in rows]
    return tuple(sorted({s // 2 for s in slots}))


def make_run_chunk(cfg: SimConfig):
    """Returns ``run_chunk(params, adj, state) -> (state, (times, srcs))``,
    advancing the simulation by up to ``cfg.capacity`` events. Pure and
    jit/vmap-safe; the driver (redqueen_tpu.sim) jits/vmaps/shards it."""
    from .threefry import threefry2x32, uniform_from_bits

    fire_branches = _fire_branches(cfg)
    react_hooks = _react_hooks(cfg)
    end_time = cfg.end_time
    pairs = _panel_pairs(cfg, bool(react_hooks))
    reg = get_registry()
    kinds = set(_kinds_for(cfg))
    needs_fire_key = any(reg[k].fire_uses_key for k in kinds)
    # Only per-source state fields the compiled policy mix can touch get
    # scattered + absorb-gated each step; the rest pass through untouched
    # (a Poisson+Opt component never pays Hawkes/replay/RMTPP state
    # traffic). Bit-preserving: untouched branches only ever echoed the
    # old values back.
    from ..models.base import KIND_HAWKES, KIND_REALDATA, KIND_RMTPP

    has_hawkes = KIND_HAWKES in kinds
    has_realdata = KIND_REALDATA in kinds
    has_rmtpp = KIND_RMTPP in kinds

    def run_chunk(params: SourceParams, adj, state: SimState):
        kind_local = _local_kind(cfg, params.kind)

        def step(state: SimState, _):
            s_star = jnp.argmin(state.t_next)
            t_ev = state.t_next[s_star]
            # Lane health (runtime.numerics): a sick lane FREEZES — valid
            # is gated on health so it emits nothing and its carry stops
            # moving, exactly like an absorbed lane, and the sickness can
            # never leak to sibling lanes through the argmin or the
            # driver's early-exit logic.  jnp.argmin treats NaN as
            # minimal, so a poisoned t_next selects itself here and the
            # NaN event time is caught below on the very step it appears.
            health = (state.health if state.health is not None
                      else jnp.zeros((), jnp.uint32))
            healthy = health == 0
            t_ev_bad = jnp.isnan(t_ev)
            # A finite event time that moves BACKWARDS is the same class
            # of corruption as a NaN (a -inf or scrambled carry value);
            # strict < keeps legitimate simultaneous events valid.
            regressed = t_ev < state.t
            valid = (t_ev <= end_time) & healthy & ~regressed
            if state.budget is not None:
                # run_dynamic semantics: absorb once the event budget is
                # spent (exactly the oracle's per-event stop, not chunk
                # granularity).
                valid &= state.n_events < state.budget
            feeds = adj[s_star]                       # [F] feeds hit

            # -- the step's fused draw panel: counter-addressed threefry
            # words keyed on (component key, global event index, slot) cover
            # the fire draw (slot 0) and the react draws (slot 1+s) —
            # layout-independent like the per-source streams they replace,
            # and an unrolled-opt config computes ONLY the pairs its slots
            # touch (one block per step for the headline shape, vs four
            # fold_in/exponential chains before). Policies with open-ended
            # randomness (Hawkes thinning, RMTPP) still get the per-source
            # (key, ctr) stream below; XLA dead-code-eliminates it when no
            # compiled branch uses it.
            S = state.t_next.shape[0]
            ev = state.n_events.astype(jnp.uint32)
            # High bit of the pair counter is a domain separator: without
            # it, event 0's panel blocks (0, pair) would collide with the
            # per-source base keys fold_in(component_key, s) = block (0, s)
            # from init_state.
            pj = np.asarray(pairs, np.uint32) | np.uint32(0x8000_0000)
            w0, w1 = threefry2x32(
                state.key[0], state.key[1],
                jnp.broadcast_to(ev, pj.shape), pj,
            )
            word_idx = np.asarray(
                [w for j in pairs for w in (2 * j, 2 * j + 1)], np.int32
            )
            vals = uniform_from_bits(
                jnp.stack([w0, w1], -1).reshape(-1)
            ).astype(state.t_next.dtype)
            keep = word_idx <= S  # static mask: last pair may overhang
            us = jnp.zeros((S + 1,), state.t_next.dtype).at[
                jnp.asarray(word_idx[keep])
            ].set(vals[np.flatnonzero(keep)])

            # -- fired source resamples (policy dispatch, SURVEY.md 3.1) --
            if needs_fire_key:
                key_fire = jr.fold_in(state.keys[s_star], state.ctr[s_star])
            else:
                # every compiled branch draws from the panel (or not at
                # all); skip the per-source gather + fold_in chain entirely
                key_fire = state.key
            upd = lax.switch(
                kind_local[s_star], fire_branches,
                params, state, s_star, t_ev, key_fire, us[0],
            )

            # Write-back checks: the kernel never stores a NaN time (a
            # poisoned resample becomes an absorbing +inf, with the
            # substitution recorded in the health mask) and every
            # non-finite state slice is flagged the step it is produced.
            # All checks are identities on healthy values, so healthy
            # streams and goldens are bit-identical.
            u32 = jnp.uint32
            bits = jnp.where(healthy & (t_ev_bad | regressed),
                             u32(numerics.BIT_NONFINITE_TIME), u32(0))
            bits |= jnp.where(valid & jnp.isnan(upd.t_next),
                              u32(numerics.BIT_NONFINITE_TIME), u32(0))
            bits |= jnp.where(valid & ~upd.ok,
                              u32(numerics.BIT_SAMPLER_FAILURE), u32(0))
            if has_hawkes:
                bits |= jnp.where(valid & ~jnp.isfinite(upd.exc),
                                  u32(numerics.BIT_NONFINITE_STATE), u32(0))
            if has_rmtpp:
                bits |= jnp.where(
                    valid & ~jnp.all(jnp.isfinite(upd.h)),
                    u32(numerics.BIT_NONFINITE_STATE), u32(0))
            health = health | bits
            t_next = state.t_next.at[s_star].set(
                numerics.nan_to_posinf(upd.t_next))
            # ctr is the per-source (key, ctr) STREAM position — read only
            # by fire branches with fire_uses_key (Hawkes thinning, RMTPP).
            # When no compiled branch reads it (the headline Poisson+Opt
            # mix draws everything from the panel), the scatter + absorb
            # select below are dead carry traffic every step; skip them
            # (bit-preserving: nothing ever consumes the skipped counts).
            ctr = state.ctr.at[s_star].add(1) if needs_fire_key else None

            # -- react hooks: non-fired sources re-decide (RedQueen trick) --
            for hook in react_hooks:
                t_next, bumped = hook(
                    cfg, params, state.replace(t_next=t_next), adj, feeds,
                    s_star, t_ev, valid, us[1:],
                )
                if needs_fire_key:
                    ctr = ctr + bumped.astype(ctr.dtype)

            # Past-horizon steps absorb: emit a sentinel, keep state frozen.
            # Only the fields this policy mix can change are gated/written.
            def sel(a, b):
                return jnp.where(valid, a, b)

            fields = dict(
                t=sel(t_ev, state.t),
                t_next=sel(t_next, state.t_next),
                n_events=state.n_events + valid.astype(state.n_events.dtype),
            )
            if state.health is not None:
                # Written UNGATED: sickness is detected on the very step
                # it appears (which is always an invalid step for the
                # NaN-time case).  For healthy lanes bits == 0, so this
                # is a value-identical no-op — absorbed chunks stay true
                # no-ops on the carry.
                fields["health"] = health
            if needs_fire_key:
                fields["ctr"] = sel(ctr, state.ctr)
            if has_hawkes:
                fields["exc"] = sel(
                    state.exc.at[s_star].set(upd.exc), state.exc
                )
            if has_hawkes or has_rmtpp:
                # exc_t doubles as RMTPP's last-own-event time (its tau
                # input is t - exc_t), not just the Hawkes fold time.
                fields["exc_t"] = sel(
                    state.exc_t.at[s_star].set(upd.exc_t), state.exc_t
                )
            if has_realdata:
                fields["rd_ptr"] = sel(
                    state.rd_ptr.at[s_star].set(upd.rd_ptr), state.rd_ptr
                )
            if has_rmtpp:
                fields["h"] = sel(state.h.at[s_star].set(upd.h), state.h)
            state = state.replace(**fields)
            ev_out = (
                jnp.where(valid, t_ev, jnp.inf),
                jnp.where(valid, s_star, -1).astype(jnp.int32),
            )
            return state, ev_out

        state, (times, srcs) = lax.scan(
            step, state, None, length=cfg.capacity
        )
        return state, (times, srcs)

    return run_chunk
