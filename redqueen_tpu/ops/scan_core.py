"""The event-scan kernel: the reference's ``Manager.run_till`` hot loop
(SURVEY.md section 3.1) re-designed as a fixed-capacity ``lax.scan``.

One scan step == one global event: argmin over the per-source next-event
times picks the fired source (ties -> lowest index, matching the NumPy
oracle's ``np.argmin``), the fired source's resample dispatches through
``lax.switch`` over the registered policy branches, and every registered
react hook (RedQueen's superposition trick) adjusts the remaining sources.
Feed ranks are never materialized in the carry — the superposition clocks
encode them implicitly and the metric layer reconstructs them from the log. Steps after the horizon
are absorbing no-ops, so a chunk is always a statically-shaped computation:
XLA traces it once and the TPU replays it for every chunk of every
simulation of the sweep.

The per-event Python-object churn this deletes is the O(events x sources)
cost called out in SURVEY.md section 3.1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import random as jr

from ..config import SimConfig, SimState, SourceParams
from ..models.base import get_registry

__all__ = ["init_state", "make_run_chunk"]


def _kinds_for(cfg: SimConfig):
    """Static branch set: only the policy kinds present in the component
    (cfg.present_kinds, filled by GraphBuilder) — a Poisson+Opt component
    never compiles the Hawkes thinning loop. Empty tuple (hand-built
    configs) falls back to every registered kind."""
    reg = get_registry()
    return list(cfg.present_kinds) if cfg.present_kinds else sorted(reg)


def _fire_branches(cfg):
    reg = get_registry()
    return [reg[k].on_fire for k in _kinds_for(cfg)]


def _init_branches(cfg):
    reg = get_registry()
    return [reg[k].on_init for k in _kinds_for(cfg)]


def _react_hooks(cfg):
    reg = get_registry()
    return [
        reg[k].on_react for k in _kinds_for(cfg) if reg[k].on_react is not None
    ]


def _local_kind(cfg, kind):
    """Map global kind codes to indices into the compiled branch list."""
    kinds = _kinds_for(cfg)
    if kinds == list(range(len(kinds))):
        return kind  # identity mapping, skip the gather
    lookup = np.zeros(max(kinds) + 1, np.int32)
    for i, k in enumerate(kinds):
        lookup[k] = i
    return jnp.asarray(lookup)[kind]


def init_state(cfg: SimConfig, params: SourceParams, adj, key,
               dtype=jnp.float32) -> SimState:
    """Build the initial carry: per-source PRNG streams and first draws.

    Per-source keys are ``fold_in(component_key, source_index)`` and every
    subsequent draw is ``fold_in(key_s, counter_s)`` — SURVEY.md section 7
    "PRNG discipline": streams depend only on (component key, source index,
    draw count), never on vmap/mesh layout.
    """
    S = cfg.n_sources
    H = cfg.rmtpp_hidden
    keys = jax.vmap(lambda i: jr.fold_in(key, i))(jnp.arange(S))
    t0 = jnp.asarray(cfg.start_time, dtype)
    state0 = SimState(
        t=t0,
        t_next=jnp.full((S,), jnp.inf, dtype),
        exc=jnp.zeros((S,), dtype),
        exc_t=jnp.full((S,), t0, dtype),
        rd_ptr=jnp.zeros((S,), jnp.int32),
        h=jnp.zeros((S, H), dtype),
        keys=keys,
        ctr=jnp.zeros((S,), jnp.uint32),
        n_events=jnp.zeros((), jnp.int32),
    )
    branches = _init_branches(cfg)
    kind_local = _local_kind(cfg, params.kind)
    init_keys = jax.vmap(jr.fold_in)(keys, jnp.zeros((S,), jnp.uint32))

    def one(s, kl, k):
        return lax.switch(kl, branches, params, state0, s, t0, k)

    upd = jax.vmap(one, in_axes=(0, 0, 0))(jnp.arange(S), kind_local, init_keys)
    return state0.replace(
        t_next=upd.t_next, exc=upd.exc, exc_t=upd.exc_t, rd_ptr=upd.rd_ptr,
        h=upd.h, ctr=jnp.ones((S,), jnp.uint32),
    )


def make_run_chunk(cfg: SimConfig):
    """Returns ``run_chunk(params, adj, state) -> (state, (times, srcs))``,
    advancing the simulation by up to ``cfg.capacity`` events. Pure and
    jit/vmap-safe; the driver (redqueen_tpu.sim) jits/vmaps/shards it."""
    fire_branches = _fire_branches(cfg)
    react_hooks = _react_hooks(cfg)
    end_time = cfg.end_time

    def run_chunk(params: SourceParams, adj, state: SimState):
        kind_local = _local_kind(cfg, params.kind)

        def step(state: SimState, _):
            s_star = jnp.argmin(state.t_next)
            t_ev = state.t_next[s_star]
            valid = t_ev <= end_time
            feeds = adj[s_star]                       # [F] feeds hit

            # -- fired source resamples (policy dispatch, SURVEY.md 3.1) --
            key_fire = jr.fold_in(state.keys[s_star], state.ctr[s_star])
            upd = lax.switch(
                kind_local[s_star], fire_branches,
                params, state, s_star, t_ev, key_fire,
            )

            new = state.replace(
                t=t_ev,
                t_next=state.t_next.at[s_star].set(upd.t_next),
                exc=state.exc.at[s_star].set(upd.exc),
                exc_t=state.exc_t.at[s_star].set(upd.exc_t),
                rd_ptr=state.rd_ptr.at[s_star].set(upd.rd_ptr),
                h=state.h.at[s_star].set(upd.h),
                ctr=state.ctr.at[s_star].add(1),
                n_events=state.n_events + 1,
            )

            # -- react hooks: non-fired sources re-decide (RedQueen trick) --
            for hook in react_hooks:
                t_next, bumped = hook(
                    cfg, params, new, adj, feeds, s_star, t_ev, valid
                )
                new = new.replace(
                    t_next=t_next, ctr=new.ctr + bumped.astype(new.ctr.dtype)
                )

            # Past-horizon steps absorb: emit a sentinel, keep state frozen.
            state = jax.tree.map(
                lambda a, b: jnp.where(valid, a, b), new, state
            )
            ev = (
                jnp.where(valid, t_ev, jnp.inf),
                jnp.where(valid, s_star, -1).astype(jnp.int32),
            )
            return state, ev

        state, (times, srcs) = lax.scan(
            step, state, None, length=cfg.capacity
        )
        return state, (times, srcs)

    return run_chunk
