"""The megakernel's fused event step: rank update -> intensity -> threefry
sampling -> argmin commit -> health mask, for the FULL covered policy mix
(Poisson, Opt, Hawkes, RealData replay, piecewise-constant rates), all on
the lane-last ``[..., 128]`` layout with every value resident in
VMEM/registers across the whole chunk.

Semantics mirror ``ops/scan_core.step`` exactly where the policies are
deterministic, and distributionally where they draw randomness (the
engines share per-source (key, counter) threefry streams but not call
patterns — PARITY.md "known intentional differences"):

- **argmin commit** — lowest-index tie-break via the iota/priority trick
  (no argmin primitive in Mosaic), absorbing steps past the horizon.
- **Poisson** — one Exp(rate) per own fire from the per-source stream.
- **Opt** — own fire cancels all candidate clocks (t_next -> +inf); the
  react pass below spawns the superposition clock per affected Opt row,
  identical to the seed chunk kernel.
- **Hawkes** — excitation folds to the fire time and jumps by alpha, then
  the next event comes from EXACT inversion of the exponential-kernel
  compensator (Newton on the concave increasing hazard — a fixed,
  branch-free iteration count, unlike the scan engine's Ogata thinning
  whose rejection loop cannot live on the 128-lane vector unit).  Same
  law; different sampler; statistical parity gates in
  tests/test_pallas_engine.py.
- **RealData** — the replay cursor advances on own fires only; the padded
  ``[S, Kr]`` trace cube is gathered with one-hot ``where`` sums (never
  ``0 * inf`` multiplies).  No randomness at all, so a replay-only mix is
  BIT-IDENTICAL to the scan engine — the one golden the threefry
  discipline allows, pinned in tests.
- **Piecewise** — exact cumulative-hazard inversion unrolled over the
  static ``Kp`` segments (the branch-free twin of
  ``ops.sampling.piecewise_next_time``).
- **health mask (PR 3 in-kernel)** — the per-lane uint32 bitmask rides
  the carry: a NaN/regressed event time, a NaN resample, or a non-finite
  folded excitation ORs the matching ``runtime.numerics`` BIT_* and
  freezes the lane (``valid`` is gated on ``health == 0``), so sickness
  can never cross lanes through the argmin and never emits a NaN event.
  ``BIT_SAMPLER_FAILURE`` cannot arise here — the closed-form inverters
  have no rejection loop to exhaust; their failure shape is a NaN, which
  the TIME/STATE bits catch on the step it appears.

Mosaic lowering discipline (audited against the TPU kernel guide, same
rules as the seed chunk kernel): Python-float constants, int32 detours
for bool/f32 -> uint32 casts, ``broadcasted_iota``, static unrolls over
Opt rows and piecewise segments, ``fori_loop`` for the Newton iteration,
NaN checks as ``x != x`` / ``(x - x) == 0`` arithmetic.
"""

from __future__ import annotations

import dataclasses
from types import SimpleNamespace

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.base import (
    KIND_HAWKES,
    KIND_PIECEWISE,
    KIND_POISSON,
    KIND_REALDATA,
)
from ..runtime import numerics
from ..runtime.numerics import safe_exp
from .threefry import exponential_from_bits, threefry2x32

__all__ = ["KernelSpec", "prepare_consts", "make_step",
           "hawkes_invert", "NEWTON_ITERS"]


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """Static shape/specialization info one compiled megakernel closes
    over (the hashable core of the engine's compile-cache key)."""

    S: int
    F: int
    Kr: int
    Kp: int
    tile: int
    capacity: int
    k: int
    end_time: float
    opt_rows: tuple
    has_opt: bool
    has_hawkes: bool
    has_rd: bool
    has_pw: bool


#: Fixed Newton iteration count for the Hawkes compensator inversion.
#: The map is concave increasing, so iterates climb monotonically from
#: below and converge quadratically; 24 rounds reach f32 precision for
#: every subcritical parameter set the domain validation admits.
NEWTON_ITERS = 24


def hawkes_invert(e, l0, exc, beta, iters: int = NEWTON_ITERS):
    """Exact inversion of the exponential-kernel Hawkes compensator:
    solve ``l0*tau + (exc/beta)*(1 - exp(-beta*tau)) = e`` for the
    inter-event time ``tau`` (Newton, fixed ``iters`` rounds).  When
    ``l0 == 0`` the total remaining hazard is finite (``exc/beta``) and
    draws beyond it never fire (+inf) — the closed-form twin of
    ``ops.sampling.rmtpp_next_delta``'s w<0 branch."""
    c = exc / jnp.maximum(beta, 1e-30)
    never = (l0 <= 0) & (e >= c)
    tau = e / jnp.maximum(l0 + exc, 1e-30)  # tangent-at-0 step: a lower bound

    def newton(_, tau):
        em = safe_exp(-beta * tau)
        g = l0 * tau + c * (1.0 - em) - e
        return tau - g / jnp.maximum(l0 + exc * em, 1e-30)

    tau = lax.fori_loop(0, iters, newton, tau)
    tau = jnp.maximum(tau, 0.0)  # guard rounding below the t=0 tangent
    return jnp.where(never, jnp.asarray(np.inf, tau.dtype), tau)


def prepare_consts(spec: KernelSpec, vals: dict) -> SimpleNamespace:
    """Hoist everything loop-invariant out of the per-event step: the
    source iota, the replay-cursor iota, and each Opt row's
    ``sqrt(s_f / q_r)`` rate panel."""
    c = SimpleNamespace(**vals)
    c.iota_s = lax.broadcasted_iota(jnp.int32, (spec.S, spec.tile), 0)
    if spec.has_rd:
        c.iota_kr = lax.broadcasted_iota(jnp.int32, (spec.Kr, spec.tile), 0)
    if spec.opt_rows:
        c.opt_rates = {
            r: jnp.sqrt(c.ssink / jnp.maximum(c.q[r][None, :], 1e-30))
            for r in spec.opt_rows
        }
    return c


def _piecewise_invert_panel(e, t_from, knots, rates, Kp: int):
    """Branch-free hazard inversion for the FIRED source's piecewise
    profile, unrolled over the static segment count: first segment whose
    cumulative hazard reaches the Exp(1) target ``e`` wins.  ``knots``
    [Kp, lanes] carries the +inf padding convention of
    ``config.GraphBuilder`` (the inf-inf span's NaN is masked by the
    rate/span guards exactly as in ``ops.sampling.piecewise_next_time``)."""
    inf = float(np.inf)
    out = jnp.full(e.shape, inf, e.dtype)
    found = jnp.zeros(e.shape, bool)
    cum = jnp.zeros(e.shape, e.dtype)
    for kseg in range(Kp):
        t0k = knots[kseg]
        t1k = knots[kseg + 1] if kseg + 1 < Kp else jnp.full(
            e.shape, inf, e.dtype)
        rk = rates[kseg]
        lo = jnp.maximum(t0k, t_from)
        span = t1k - lo
        hz = jnp.where((rk > 0) & (span > 0), rk * span, 0.0)
        cum_next = cum + hz
        hit = jnp.logical_not(found) & (cum_next >= e)
        t_hit = lo + (e - cum) / jnp.maximum(rk, 1e-30)
        out = jnp.where(hit, t_hit, out)
        found = found | hit
        cum = cum_next
    return out


def make_step(spec: KernelSpec, c: SimpleNamespace, times_ref, srcs_ref):
    """Build the fused per-event step for ``lax.fori_loop`` over one
    chunk.  ``c`` holds the loaded loop-invariant values
    (:func:`prepare_consts`); the carry is the 8-slot tuple
    ``(t_next, ctr, t, nev, health, exc, exc_t, rd_ptr)`` with ``None``
    for slots the policy mix does not compile."""
    S, Tl = spec.S, spec.tile
    end = float(spec.end_time)
    inf = float(np.inf)
    f32, i32, u32 = jnp.float32, jnp.int32, jnp.uint32
    BIT_TIME = u32(numerics.BIT_NONFINITE_TIME)
    BIT_STATE = u32(numerics.BIT_NONFINITE_STATE)
    U0 = u32(0)

    def step(i, carry):
        t_next, ctr, t, nev, health, exc, exc_t, rd_ptr = carry

        # ---- argmin commit (lowest-index tie-break) + lane gating ----
        tmin = jnp.min(t_next, axis=0)                         # [T]
        prio = jnp.where(t_next == tmin[None, :], c.iota_s, S)
        s_star = jnp.min(prio, axis=0)                         # [T]
        ff = (c.iota_s == s_star[None, :]).astype(f32)         # [S, T]
        healthy = health == U0
        nan_t = tmin != tmin
        regressed = tmin < t
        valid = ((tmin <= end) & (s_star < S) & healthy
                 & jnp.logical_not(nan_t) & jnp.logical_not(regressed))
        bits = jnp.where(healthy & (nan_t | regressed), BIT_TIME, U0)

        # ---- fired source's draw from its (key, ctr) stream ----
        ffi = ff.astype(i32)
        ffu = ffi.astype(u32)
        k0f = jnp.sum(c.k0 * ffu, axis=0)
        k1f = jnp.sum(c.k1 * ffu, axis=0)
        ctrf = jnp.sum(ctr * ffu, axis=0)
        bits0, _ = threefry2x32(k0f, k1f, ctrf, jnp.zeros_like(ctrf))
        e = exponential_from_bits(bits0)                       # Exp(1) [T]
        kindf = jnp.sum(c.kind * ffi, axis=0)                  # [T] i32

        # ---- per-kind resample (Opt and unmatched kinds stay +inf) ----
        t_new = jnp.full((Tl,), inf, f32)
        ratef = jnp.sum(c.rate * ff, axis=0)
        t_new = jnp.where(
            kindf == KIND_POISSON,
            jnp.where(ratef > 0, tmin + e / jnp.maximum(ratef, 1e-30), inf),
            t_new)
        exc_new = None
        if spec.has_hawkes:
            l0f = jnp.sum(c.l0 * ff, axis=0)
            alphaf = jnp.sum(c.alpha * ff, axis=0)
            betaf = jnp.sum(c.beta * ff, axis=0)
            # where-gathers: carried state may hold a poisoned inf, and
            # 0 * inf would smear NaN across the whole lane tile.
            excf = jnp.sum(jnp.where(ff > 0.5, exc, 0.0), axis=0)
            exctf = jnp.sum(jnp.where(ff > 0.5, exc_t, 0.0), axis=0)
            exc_new = excf * safe_exp(-betaf * (tmin - exctf)) + alphaf
            tau = hawkes_invert(e, l0f, exc_new, betaf)
            t_new = jnp.where(kindf == KIND_HAWKES, tmin + tau, t_new)
        if spec.has_rd:
            ptrf = jnp.sum(rd_ptr * ffi, axis=0)
            ptr1 = ptrf + 1
            rdf = jnp.sum(jnp.where(ff[:, None, :] > 0.5, c.rd_times, 0.0),
                          axis=0)                              # [Kr, T]
            hit = c.iota_kr == ptr1[None, :]
            t_rd = jnp.sum(jnp.where(hit, rdf, 0.0), axis=0)
            t_rd = jnp.where(ptr1 < spec.Kr, t_rd, inf)
            t_new = jnp.where(kindf == KIND_REALDATA, t_rd, t_new)
        if spec.has_pw:
            pwtf = jnp.sum(jnp.where(ff[:, None, :] > 0.5, c.pw_times, 0.0),
                           axis=0)                             # [Kp, T]
            pwrf = jnp.sum(c.pw_rates * ff[:, None, :], axis=0)
            t_pw = _piecewise_invert_panel(e, tmin, pwtf, pwrf, spec.Kp)
            t_new = jnp.where(kindf == KIND_PIECEWISE, t_pw, t_new)

        # ---- write-back checks: never store a NaN time, flag the lane ----
        t_nan = t_new != t_new
        bits = bits | jnp.where(valid & t_nan, BIT_TIME, U0)
        t_new = jnp.where(t_nan, jnp.full((Tl,), inf, f32), t_new)
        sel = (ff > 0.5) & valid[None, :]
        t_next = jnp.where(sel, t_new[None, :], t_next)
        ctr = ctr + ffu * valid.astype(i32).astype(u32)
        if spec.has_hawkes:
            exc_bad = jnp.logical_not((exc_new - exc_new) == 0)  # inf or NaN
            bits = bits | jnp.where(
                valid & (kindf == KIND_HAWKES) & exc_bad, BIT_STATE, U0)
            sel_h = sel & (c.kind == KIND_HAWKES)
            exc = jnp.where(sel_h, exc_new[None, :], exc)
            exc_t = jnp.where(sel_h, tmin[None, :], exc_t)
        if spec.has_rd:
            rd_ptr = rd_ptr + (ffi * (c.kind == KIND_REALDATA).astype(i32)
                               * valid.astype(i32))

        # ---- react: each Opt row spawns a superposition clock ----
        if spec.opt_rows:
            feeds_hit = jnp.sum(c.adj * ff[:, None, :], axis=0)  # [F, T]
            for r in spec.opt_rows:
                aff = c.adj[r] * feeds_hit
                rs = jnp.sum(aff * c.opt_rates[r], axis=0)       # [T]
                react = (rs > 0) & (s_star != r) & valid
                bits_r, _ = threefry2x32(
                    c.k0[r], c.k1[r], ctr[r], jnp.ones((Tl,), u32))
                cand = tmin + (exponential_from_bits(bits_r)
                               / jnp.maximum(rs, 1e-30))
                t_next = t_next.at[r].set(
                    jnp.where(react, jnp.minimum(t_next[r], cand),
                              t_next[r]))
                ctr = ctr.at[r].set(
                    ctr[r] + react.astype(i32).astype(u32))

        # ---- emit event, advance clock (absorbing past horizon) ----
        times_ref[i, :] = jnp.where(valid, tmin, inf)
        srcs_ref[i, :] = jnp.where(valid, s_star, -1)
        t = jnp.where(valid, tmin, t)
        nev = nev + valid.astype(i32)
        # Ungated: sickness is recorded on the very step it appears; for
        # healthy lanes bits == 0 so this is a value-identical no-op.
        health = health | bits
        return (t_next, ctr, t, nev, health, exc, exc_t, rd_ptr)

    return step
