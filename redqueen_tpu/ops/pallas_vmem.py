"""Per-shape VMEM planning for the Pallas event megakernel.

Replaces the seed chunk engine's flat ``_VMEM_BUDGET`` guess with an
itemized per-shape plan: every block the megakernel asks Pallas to keep
resident — the policy-parameter rows/cubes for exactly the kinds the
config compiles, the carry in/out rows, and the double-buffered event-log
stream — is priced from its BlockSpec shape, the pipeline's
double-buffering is modeled explicitly (factor ``PIPELINE_BUFFERS`` on
every block, since Mosaic prefetches the next grid step's blocks while
the current one computes), and :func:`plan_vmem` picks the largest kernel
chunk capacity that fits the budget.  When even the minimum capacity does
not fit (the ``[S, F, lane]`` adjacency cube or a corpus-scale replay
cube dominates), the plan records WHY in ``VmemPlan.reason`` so the
engine dispatch (``sim.select_engine``) can degrade to the scan engine
with provenance instead of a Mosaic OOM deep in compilation.

The superchunk length ``k`` costs no VMEM at all — it is a grid
dimension, and only two log blocks are ever resident regardless of how
many chunks one launch runs — so ``k`` is a latency knob (host syncs per
run), never a memory knob.

These numbers are an exact accounting of the blocks the engine declares,
not a device measurement: VMEM occupancy is unobservable under interpret
mode, so the staged TPU watcher banks a Mosaic compile confirmation when
the tunnel next comes alive.  The budget default leaves headroom for
Mosaic's own scratch below the 16 MiB/core v5e figure.  Boundary
behavior is pinned by tests: a plan exactly at budget fits, one byte
over refuses.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from ..models.base import KIND_HAWKES, KIND_OPT, KIND_PIECEWISE, KIND_REALDATA

__all__ = [
    "VmemPlan",
    "plan_vmem",
    "vmem_blocks",
    "vmem_bytes",
    "DEFAULT_VMEM_BUDGET",
    "MIN_CAPACITY",
    "TILE",
    "PIPELINE_BUFFERS",
]

#: Lane tile: the batch axis rides the TPU's 128-wide lane dimension.
TILE = 128

#: v5e VMEM is 16 MiB/core; leave headroom for Mosaic's own scratch.
DEFAULT_VMEM_BUDGET = 12 * 2**20

#: Smallest kernel chunk capacity the planner will shrink to before
#: declaring the shape unfittable (chunks below this absorb too much
#: launch overhead to ever win against the scan engine).
MIN_CAPACITY = 32

#: Pallas pipelines grid steps: while step g computes, step g+1's blocks
#: are being fetched and step g-1's outputs drained, so every declared
#: block costs two VMEM residencies.
PIPELINE_BUFFERS = 2


@dataclasses.dataclass(frozen=True)
class VmemPlan:
    """The (capacity, k, tile) choice for one config shape, or the
    recorded reason it must run on the scan engine instead."""

    fits: bool
    reason: Optional[str]       # None when fits; the degrade provenance otherwise
    capacity: int               # kernel chunk capacity (events per chunk)
    k: int                      # chunks per launch (superchunk grid length)
    tile: int                   # lane tile (batch lanes per grid step)
    total_bytes: int            # modeled VMEM at the chosen capacity
    budget: int
    blocks: Tuple[Tuple[str, int], ...]  # itemized (name, bytes) accounting


def _kind_flags(cfg):
    kinds = set(cfg.present_kinds)
    return (KIND_OPT in kinds, KIND_HAWKES in kinds,
            KIND_REALDATA in kinds, KIND_PIECEWISE in kinds)


def vmem_blocks(cfg, S: int, F: int, Kr: int = 0, Kp: int = 0,
                capacity: Optional[int] = None,
                tile: int = TILE) -> Tuple[Tuple[str, int], ...]:
    """Itemized (name, bytes) VMEM accounting of the megakernel's blocks
    for one config shape — every dtype in the kernel is a 4-byte word and
    the lane axis is always ``tile`` wide.  Only the blocks the config's
    policy mix actually compiles are listed (a mix without Opt rows never
    pays the adjacency cube; one without replay rows never pays the
    ``[S, Kr, lane]`` trace cube)."""
    if capacity is None:
        capacity = cfg.capacity
    has_opt, has_hawkes, has_rd, has_pw = _kind_flags(cfg)
    w = 4 * tile
    blocks = [("params.base", 4 * S * w)]  # kind, rate, k0, k1 rows
    if has_opt:
        blocks.append(("params.opt", (S + F + S * F) * w))  # q + ssink + adj
    if has_hawkes:
        blocks.append(("params.hawkes", 3 * S * w))         # l0, alpha, beta
    if has_rd:
        blocks.append(("params.realdata", S * Kr * w))      # replay cube
    if has_pw:
        blocks.append(("params.piecewise", 2 * S * Kp * w))  # knots + rates
    carry_rows = 2 + (2 if has_hawkes else 0) + (1 if has_rd else 0)
    carry = (carry_rows * S + 3) * w  # rows + (t, nev, health) vectors
    blocks.append(("carry.in", carry))
    blocks.append(("carry.out", carry))
    blocks.append(("log.stream", 2 * capacity * w))  # (times, srcs) blocks
    return tuple(blocks)


def vmem_bytes(cfg, S: int, F: int, Kr: int = 0, Kp: int = 0,
               capacity: Optional[int] = None, tile: int = TILE) -> int:
    """Total modeled VMEM for one config shape at the given chunk
    capacity, pipeline double-buffering included."""
    return PIPELINE_BUFFERS * sum(
        b for _, b in vmem_blocks(cfg, S, F, Kr, Kp, capacity, tile))


def plan_vmem(cfg, S: int, F: int, Kr: int = 0, Kp: int = 0, *,
              k: int = 8, budget: Optional[int] = None,
              tile: int = TILE) -> VmemPlan:
    """Pick (capacity, k, tile) for one config shape, or record why the
    shape degrades to the scan engine.

    Starts from ``cfg.capacity`` and halves the kernel chunk capacity —
    the event-log stream is the only capacity-dependent block — until the
    itemized total fits ``budget``; a shape whose capacity-independent
    blocks alone exceed the budget gets ``fits=False`` with the dominant
    blocks named in ``reason``."""
    if budget is None:
        budget = DEFAULT_VMEM_BUDGET
    # Static plan math on HOST ints (SimConfig fields / call options) —
    # nothing here ever touches a traced value.
    k = int(k)  # rqlint: disable=RQ701 host ints
    cap = int(cfg.capacity)  # rqlint: disable=RQ701 host ints
    while True:
        blocks = vmem_blocks(cfg, S, F, Kr, Kp, cap, tile)
        total = PIPELINE_BUFFERS * sum(b for _, b in blocks)
        if total <= budget:
            return VmemPlan(fits=True, reason=None, capacity=cap, k=k,
                            tile=tile, total_bytes=total, budget=budget,
                            blocks=blocks)
        if cap <= MIN_CAPACITY:
            top = sorted(blocks, key=lambda nb: -nb[1])[:3]
            named = ", ".join(f"{n}={b / 2**20:.2f} MiB" for n, b in top)
            return VmemPlan(
                fits=False,
                reason=(
                    f"pallas megakernel VMEM plan: {total / 2**20:.2f} MiB "
                    f"at the minimum chunk capacity {MIN_CAPACITY} exceeds "
                    f"the {budget / 2**20:.2f} MiB budget (S={S}, F={F}, "
                    f"Kr={Kr}, Kp={Kp}; dominant blocks: {named}) — use "
                    f"the scan engine (sim.simulate_batch) or the star "
                    f"engine (parallel.bigf) for this shape"
                ),
                capacity=cap, k=k, tile=tile, total_bytes=total,
                budget=budget, blocks=blocks)
        cap = max(cap // 2, MIN_CAPACITY)
