"""Comparison baselines from the RedQueen paper's experiment suite.

The reference evaluates RedQueen against (SURVEY.md section 2 item 15 and
section 6): (a) budget-matched Poisson posting, (b) the *offline* optimal
"when-to-post" method of Karimi et al. (NIPS 2016 "Smart broadcasting: do you
want to be seen?"), whose solution is a piecewise-constant posting-rate
schedule fitted to the followers' (piecewise-constant) activity profiles, and
(c) the user's real posting trace. The reference carries (b) implicitly as
the ``PiecewiseConst`` broadcaster + ``create_manager_with_piecewise_const``
(reference ``redqueen/opt_model.py``, SURVEY.md section 2 item 6); the fitted
schedule itself came from the paper pipeline. This module supplies that
missing fit as a TPU-friendly convex water-filling solve, so the full paper
comparison (RedQueen vs Poisson vs offline oracle vs replay) runs end-to-end
inside this framework (see ``experiments/``).

Model for the offline fit: in segment s (duration d_s) follower f's wall
posts as Poisson with rate L[f, s]; if we broadcast as Poisson with rate
mu_s, the stationary probability of holding the top slot of f's feed is
mu_s / (mu_s + L[f, s]).  The offline problem is

    maximize_{mu >= 0}  sum_s d_s * sum_f  mu_s / (mu_s + L[f, s])
    subject to          sum_s d_s * mu_s = budget                    (E#posts)

— concave with a monotone KKT system: per segment,
g_s(mu) = sum_f L/(mu+L)^2 equals a global multiplier nu, i.e. water-filling.
Both the inner (per-segment mu) and outer (nu) solves are monotone
bisections, vectorized over segments — O(iters * F * S) with static shapes,
jit-friendly by construction.  Zero-rate (f, s) entries are ignored: a feed
receiving no competing posts is held at rank 0 by any single post, so it
contributes no gradient.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "offline_rates",
    "offline_visibility",
    "budget_matched_poisson_rate",
    "offline_schedule",
]

_INNER_ITERS = 60
_OUTER_ITERS = 60


def budget_matched_poisson_rate(n_posts: float, end_time: float,
                                start_time: float = 0.0) -> float:
    """Constant Poisson rate spending the same expected budget as an observed
    run — the paper's budget-matched Poisson baseline."""
    return float(n_posts) / (float(end_time) - float(start_time))


def _g(mu, L, active):
    """KKT derivative sum_f L/(mu+L)^2 per segment; [S] from L [F, S]."""
    terms = jnp.where(active, L / jnp.square(mu[None, :] + L), 0.0)
    return terms.sum(axis=0)


def _mu_of_nu(nu, L, active, mu_hi0):
    """Per-segment water level mu_s(nu): solve g_s(mu) = nu, monotone in mu.

    g_s(mu) <= sum_f L / mu^2, so the root lies in [0, sqrt(sum_f L / nu)];
    fixed-iteration bisection keeps the whole solve shape-static under jit.
    """
    lo = jnp.zeros_like(mu_hi0)
    hi = jnp.sqrt(jnp.where(active, L, 0.0).sum(axis=0) / nu) + 1e-12

    def body(i, bounds):
        lo, hi = bounds
        mid = 0.5 * (lo + hi)
        too_low = _g(mid, L, active) > nu  # g decreasing: root above mid
        return jnp.where(too_low, mid, lo), jnp.where(too_low, hi, mid)

    lo, hi = jax.lax.fori_loop(0, _INNER_ITERS, body, (lo, hi))
    mu = 0.5 * (lo + hi)
    # Segments already saturated at mu = 0 (g_s(0) <= nu) post nothing.
    return jnp.where(_g(jnp.zeros_like(mu), L, active) <= nu, 0.0, mu)


def offline_rates(wall_rates, durations, budget: float):
    """Karimi-style offline optimal posting rates.

    ``wall_rates``: [F, S] (or [S] for one follower) piecewise-constant wall
    intensity of each follower per segment; ``durations``: [S] segment
    lengths; ``budget``: expected total number of posts over the horizon.
    Returns mu [S] >= 0 with sum_s durations[s] * mu[s] == budget (to solver
    tolerance). Pure jittable function.
    """
    L = jnp.atleast_2d(jnp.asarray(wall_rates, jnp.float64 if
                                   jax.config.jax_enable_x64 else jnp.float32))
    d = jnp.asarray(durations, L.dtype)
    active = L > 0
    mu_hi0 = jnp.zeros(L.shape[1], L.dtype)

    def spent(nu):
        return (d * _mu_of_nu(nu, L, active, mu_hi0)).sum()

    # Outer bisection on nu (spent is decreasing in nu). nu_hi = max g_s(0)
    # spends 0 < budget; nu_lo shrinks geometrically until overspending.
    nu_hi = jnp.maximum(_g(jnp.zeros(L.shape[1], L.dtype), L, active).max(),
                        1e-12)
    budget = jnp.asarray(budget, L.dtype)

    def grow(state):
        nu_lo, _ = state
        return nu_lo * 0.25, spent(nu_lo * 0.25)

    def need_grow(state):
        nu_lo, sp = state
        return (sp < budget) & (nu_lo > 1e-30)

    nu_lo, _ = jax.lax.while_loop(
        need_grow, grow, (nu_hi, spent(nu_hi))
    )

    def body(i, bounds):
        lo, hi = bounds
        mid = jnp.sqrt(lo * hi)  # log-space: nu spans many decades
        over = spent(mid) > budget  # spending too much: raise nu
        return jnp.where(over, mid, lo), jnp.where(over, hi, mid)

    nu_lo, nu_hi = jax.lax.fori_loop(0, _OUTER_ITERS, body, (nu_lo, nu_hi))
    return _mu_of_nu(jnp.sqrt(nu_lo * nu_hi), L, active, mu_hi0)


def offline_visibility(mu, wall_rates, durations):
    """The objective the offline fit maximizes: expected time-at-top summed
    over followers, sum_s d_s sum_f mu_s/(mu_s+L) (zero-rate entries count as
    held — they cost nothing). Useful for optimality checks and experiment
    tables."""
    L = jnp.atleast_2d(jnp.asarray(wall_rates))
    mu = jnp.asarray(mu)
    d = jnp.asarray(durations)
    frac = jnp.where(L > 0, mu[None, :] / (mu[None, :] + L), 1.0)
    return (d[None, :] * frac).sum()


def offline_schedule(wall_rates, change_times, end_time: float,
                     budget: float) -> Tuple[np.ndarray, np.ndarray]:
    """Fit the offline baseline and return ``(change_times, rates)`` ready for
    ``GraphBuilder.add_piecewise`` / ``StarBuilder.ctrl_piecewise`` / the
    oracle's ``create_manager_with_piecewise_const`` (the reference's offline-
    baseline consumer surface).

    ``change_times``: [S] ascending segment starts (last segment ends at
    ``end_time``); ``wall_rates``: [F, S] or [S].
    """
    ct = np.asarray(change_times, np.float64)
    if not np.all(np.diff(ct) > 0):
        raise ValueError("change_times must be strictly increasing")
    durations = np.diff(np.concatenate([ct, [float(end_time)]]))
    if not np.all(durations > 0):
        raise ValueError(
            f"last change_time ({ct[-1]}) must precede end_time ({end_time})"
        )
    mu = offline_rates(wall_rates, durations, budget)
    # the fit runs on device (jnp bisection); fetch the [S] rates once
    return ct, np.asarray(jax.device_get(mu), np.float64)
