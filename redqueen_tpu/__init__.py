"""redqueen-tpu: a TPU-native smart-broadcasting framework.

A ground-up JAX/XLA rebuild of the capabilities of MPI-SWS/RedQueen
(Zarezade et al., WSDM 2017): event-driven simulation of marked temporal
point processes over broadcaster->follower feed graphs, the RedQueen optimal
posting policy, baselines (Poisson, Hawkes, piecewise-constant, real-trace
replay, neural RMTPP), and feed-rank evaluation metrics — all as scan-based
kernels that vmap over components and shard over a device mesh.

Public surface (reference counterparts in parentheses; the reference mount
was empty at build time, so parity targets are SURVEY.md sections 1-3 citing
``redqueen/opt_model.py`` and ``redqueen/utils.py``):

- ``GraphBuilder`` / ``SimConfig`` / ``SourceParams``  (``SimOpts``)
- ``simulate`` / ``simulate_batch`` / ``resume``       (``Manager.run_till``)
- ``EventLog`` + ``utils.dataframe.events_to_dataframe``
  (``State.get_dataframe``)
- ``utils.metrics`` (on-device) and ``utils.metrics_pandas``
  (``utils.time_in_top_k`` / ``average_rank`` / rank integrals)
- ``parallel.shard.simulate_sharded`` / ``parallel.bigf.simulate_star`` —
  mesh-sharded execution (no reference counterpart; single-process NumPy)
- ``baselines`` — budget-matched Poisson and the Karimi-style offline
  piecewise-constant oracle the paper compares against
- ``oracle.numpy_ref`` — the trusted NumPy parity oracle mirroring the
  reference's API (``SimOpts`` / ``Manager`` / ``Broadcaster`` subclasses)
- ``presets.build_preset`` / ``run_preset`` — the five BASELINE configs
"""

from __future__ import annotations

__version__ = "0.1.0"

import os as _os

# Serving worker children (RQ_SERVING_WORKER=1, set by
# serving.worker.WorkerHandle.spawn) must spawn cheap and stay jax-free
# until their first open/recover request loads the shard — the same
# import discipline the watchdog processes keep.  Under the flag the
# eager jax-pulling re-exports below are skipped; the module-level
# __getattr__ (PEP 562) resolves every one of them lazily, so the public
# surface is identical either way — only the import COST moves.
_RQ_MINIMAL_IMPORT = bool(_os.environ.get("RQ_SERVING_WORKER"))

# The resilience runtime (supervised dispatch, retry/backoff, TPU->CPU
# degradation, preemption safety, fault injection) is stdlib-only at
# import time — eager re-export costs nothing and every entry point
# needs it (the serving worker child included: faultinject drives its
# injected process faults).
from . import runtime  # noqa: F401

# name -> owning submodule: THE definition of the re-exported surface.
# The eager loop below and the PEP 562 fallback both read it, so a new
# export is added exactly once and behaves identically on both the
# normal and the minimal-import (worker-child) path.
_LAZY_ATTRS = {
    "ConfigValidationError": ".config", "GraphBuilder": ".config",
    "SimConfig": ".config", "SourceParams": ".config",
    "stack_components": ".config",
    "EventLog": ".sim", "NumericalHealthError": ".sim",
    "resume": ".sim", "simulate": ".sim", "simulate_batch": ".sim",
    "PRESETS": ".presets", "build_preset": ".presets",
    "run_preset": ".presets",
    "SweepResult": ".sweep", "run_sweep": ".sweep",
    "run_sweep_star": ".sweep",
    "utils": None,
}

# The learning subsystem stays import-on-use on BOTH paths (its solvers
# pull jax + the full sim stack; nothing at the top level needs it
# eagerly) — resolved by the PEP 562 fallback below, never the eager
# loop.
_IMPORT_ON_USE = {"learn": None}


def __getattr__(name):
    if name in _LAZY_ATTRS or name in _IMPORT_ON_USE:
        import importlib

        target = _LAZY_ATTRS.get(name, _IMPORT_ON_USE.get(name))
        if target is None:  # a subpackage re-export
            return importlib.import_module("." + name, __name__)
        return getattr(importlib.import_module(target, __name__), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


if not _RQ_MINIMAL_IMPORT:
    # Eager re-exports, derived from the same map the lazy path serves.
    # models/ops load eagerly through .sim (the driver registers the
    # built-in policies), and .sweep pulls in parallel.bigf/shard at
    # package import too (the price of a flat `redqueen_tpu.run_sweep`);
    # oracle and data stay import-on-use.
    for _n in _LAZY_ATTRS:
        globals()[_n] = __getattr__(_n)
    del _n

__all__ = [
    "runtime",
    "__version__",
    "GraphBuilder",
    "SimConfig",
    "SourceParams",
    "stack_components",
    "EventLog",
    "simulate",
    "simulate_batch",
    "resume",
    "PRESETS",
    "build_preset",
    "run_preset",
    "SweepResult",
    "run_sweep",
    "run_sweep_star",
    "ConfigValidationError",
    "NumericalHealthError",
    "utils",
    "learn",
]
