"""redqueen-tpu: a TPU-native smart-broadcasting framework.

A ground-up JAX/XLA rebuild of the capabilities of MPI-SWS/RedQueen
(Zarezade et al., WSDM 2017): event-driven simulation of marked temporal
point processes over broadcaster->follower feed graphs, the RedQueen optimal
posting policy, baselines (Poisson, Hawkes, piecewise-constant, real-trace
replay, neural RMTPP), and feed-rank evaluation metrics — all as scan-based
kernels that vmap over components and shard over a device mesh.

Public surface (reference counterparts in parentheses; the reference mount
was empty at build time, so parity targets are SURVEY.md sections 1-3 citing
``redqueen/opt_model.py`` and ``redqueen/utils.py``):

- ``GraphBuilder`` / ``SimConfig`` / ``SourceParams``  (``SimOpts``)
- ``simulate`` / ``simulate_batch`` / ``resume``       (``Manager.run_till``)
- ``EventLog`` + ``utils.dataframe.events_to_dataframe``
  (``State.get_dataframe``)
- ``utils.metrics`` (on-device) and ``utils.metrics_pandas``
  (``utils.time_in_top_k`` / ``average_rank`` / rank integrals)
- ``parallel.shard.simulate_sharded`` / ``parallel.bigf.simulate_star`` —
  mesh-sharded execution (no reference counterpart; single-process NumPy)
- ``baselines`` — budget-matched Poisson and the Karimi-style offline
  piecewise-constant oracle the paper compares against
- ``oracle.numpy_ref`` — the trusted NumPy parity oracle mirroring the
  reference's API (``SimOpts`` / ``Manager`` / ``Broadcaster`` subclasses)
- ``presets.build_preset`` / ``run_preset`` — the five BASELINE configs
"""

from __future__ import annotations

__version__ = "0.1.0"

from .config import (
    ConfigValidationError,
    GraphBuilder,
    SimConfig,
    SourceParams,
    stack_components,
)
from .sim import (
    EventLog,
    NumericalHealthError,
    resume,
    simulate,
    simulate_batch,
)
from .presets import PRESETS, build_preset, run_preset
from .sweep import SweepResult, run_sweep, run_sweep_star

# Subpackages re-exported for discoverability. models/ops load eagerly (the
# driver registers the built-in policies), and the sweep re-export above
# pulls in parallel.bigf/shard at package import too (the price of a
# flat `redqueen_tpu.run_sweep`); oracle and data stay import-on-use.
from . import utils  # noqa: F401

# The resilience runtime (supervised dispatch, retry/backoff, TPU->CPU
# degradation, preemption safety, fault injection) is stdlib-only at
# import time — eager re-export costs nothing and every entry point
# needs it.
from . import runtime  # noqa: F401

__all__ = [
    "runtime",
    "__version__",
    "GraphBuilder",
    "SimConfig",
    "SourceParams",
    "stack_components",
    "EventLog",
    "simulate",
    "simulate_batch",
    "resume",
    "PRESETS",
    "build_preset",
    "run_preset",
    "SweepResult",
    "run_sweep",
    "run_sweep_star",
    "ConfigValidationError",
    "NumericalHealthError",
    "utils",
]
