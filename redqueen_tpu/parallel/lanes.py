"""Unified lane batching & dispatch (ROADMAP item 3): ONE layer owning how
a set of simulation lanes becomes dense device dispatches.

Every engine used to reimplement its own slice of this: bench.py carried a
hard-coded ``CPU_SLAB = 2500`` and a private slab loop, the Pallas engine
rounded its lane count to 128-wide tiles inline, the serving runtime padded
every coalesced group to its full ``max_batch_events`` width, and the star
engine had its own batch stacker.  This module centralizes the three
mechanisms they all need:

- **Bucketed ragged batching** — a power-law follower graph (the paper's
  "millions of users" regime) has lane widths spanning 1..10k; padding
  every lane to the hub width wastes  almost the whole batch.
  :func:`plan_buckets` groups lanes into a BOUNDED number of
  geometric width buckets (compile shapes stay few) and
  :func:`simulate_ragged` dispatches each bucket densely — bit-identical
  per lane to the dense-padded reference on matched seeds, because every
  PRNG stream in the kernels depends only on (lane seed, source index,
  draw counter), never on the padded shape (SURVEY.md section 7).

- **Measured slab auto-tuning** — the CPU cache-locality optimum for the
  scan engine's lane count is a measured fact of the backend and shape,
  not a constant: :func:`measured_slab` times a few candidate slab sizes
  at first use per (backend, shape bucket) and caches the winner in an
  enveloped ``rq.lanes.autotune/1`` JSON artifact
  (:func:`autotune_cache_path`), so every later run reuses the
  measurement instead of a guess.  :func:`simulate_slabbed` (reached via
  ``sim.simulate_batch(..., slab=...)``) applies the choice with
  bit-identical results — equal slabs, identical per-lane seeds.

- **Pad-waste / occupancy telemetry** — every padding decision this
  module makes is recorded (``lanes.pad.real_elems`` /
  ``lanes.pad.padded_elems`` counters, ``lanes.bucket_plan`` events,
  ``lanes.*`` spans), so a trace's ``stage_breakdown`` shows the padding
  fraction per dispatch instead of hiding it inside "compute".

Fault addressing: ``RQ_FAULT=numeric:mode@laneN`` indexes lanes of the
CALLER'S original lane order — :func:`simulate_ragged` translates the
spec through its bucket reordering (``runtime.faultinject.numeric_scope``)
so the same spec hits the same logical lane under any bucket plan, and
health bits flow back to original lane positions.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..runtime import faultinject as _faultinject
from ..runtime import telemetry as _telemetry
from ..runtime.artifacts import atomic_write_json
from ..runtime.numerics import NumericalHealthError as _NumericalHealthError

__all__ = [
    "BucketPlan", "plan_buckets", "bucket_width", "pad_to_tile",
    "SlabChoice", "measured_slab", "slab_size", "iter_slabs",
    "simulate_slabbed", "dispatch_slabbed", "concat_slab_logs",
    "probe_slab_cost", "shape_budget", "ragged_bucket_component",
    "RaggedResult",
    "simulate_ragged", "AUTOTUNE_SCHEMA", "SLAB_CANDIDATES",
    "autotune_cache_path", "load_autotune_cache",
]


# ---------------------------------------------------------------------------
# Width rounding & tile padding
# ---------------------------------------------------------------------------


def bucket_width(n: int, floor: int = 1, cap: Optional[int] = None) -> int:
    """Padded width for a lane/group of true width ``n``: the next power
    of two at or above ``max(n, floor)``, clamped to ``cap``.  Pow-2
    ceilings bound the number of DISTINCT padded shapes a workload can
    produce to log2(range) — the whole point: few compile shapes, bounded
    pad waste (< 2x per lane)."""
    n = int(n)
    if n < 0:
        raise ValueError(f"width must be >= 0, got {n}")
    m = max(n, int(floor), 1)
    w = 1 << (m - 1).bit_length()
    if cap is not None:
        if n > int(cap):
            raise ValueError(
                f"true width {n} exceeds the cap {cap} — the caller's "
                f"fixed dispatch budget cannot hold this group")
        w = min(w, int(cap))
    return w


def pad_to_tile(n: int, tile: int) -> int:
    """Lanes padded to a whole number of hardware tiles (the Pallas
    engine's ``(lanes/128, k)`` launch planning).  Emits the occupancy
    counters so a traced run shows the padded-lane fraction per launch."""
    if tile < 1:
        raise ValueError(f"tile must be >= 1, got {tile}")
    padded = -(-int(n) // int(tile)) * int(tile)
    _telemetry.counter("lanes.pad.real_lanes", int(n))
    _telemetry.counter("lanes.pad.padded_lanes", padded - int(n))
    if padded != n:
        _telemetry.event("lanes.tile_pad", lanes=int(n), padded=padded,
                         tile=int(tile),
                         occupancy=round(int(n) / padded, 4))
    return padded


# ---------------------------------------------------------------------------
# Bucket planning
# ---------------------------------------------------------------------------


class BucketPlan(NamedTuple):
    """A bounded bucketing of ragged lane widths.

    ``widths`` are the padded bucket widths (ascending); ``lane_bucket``
    maps each original lane to its bucket index (the smallest width that
    holds it).  The pad-accounting fields compare the plan against the
    dense reference (every lane padded to ``dense_width``): the
    ``pad_frac_*`` properties are the fraction of PADDED elements that
    are waste — the headline number ``BENCH_r07.json`` commits."""

    widths: Tuple[int, ...]
    lane_bucket: np.ndarray      # i64[B] bucket index per original lane
    counts: np.ndarray           # i64[B] true width per original lane
    dense_width: int

    def lanes_of(self, b: int) -> np.ndarray:
        """Original lane indices of bucket ``b``, in original order."""
        return np.flatnonzero(self.lane_bucket == b)

    @property
    def n_buckets(self) -> int:
        return len(self.widths)

    @property
    def real_elems(self) -> int:
        return int(self.counts.sum())

    @property
    def bucketed_elems(self) -> int:
        w = np.asarray(self.widths, np.int64)
        return int(w[self.lane_bucket].sum())

    @property
    def dense_elems(self) -> int:
        return int(self.dense_width) * len(self.counts)

    @property
    def pad_frac_bucketed(self) -> float:
        b = self.bucketed_elems
        return (b - self.real_elems) / b if b else 0.0

    @property
    def pad_frac_dense(self) -> float:
        d = self.dense_elems
        return (d - self.real_elems) / d if d else 0.0

    @property
    def padded_elem_reduction(self) -> float:
        """Fraction of the dense plan's WASTED elements this plan
        eliminates — the ">= 60% reduction in padded-element waste"
        acceptance number."""
        dw = self.dense_elems - self.real_elems
        bw = self.bucketed_elems - self.real_elems
        return (dw - bw) / dw if dw else 0.0


#: Smallest bucket width the ragged planner emits.  Width 1 (a 2-source
#: component) compiles through XLA's tiny-shape scalar math path, whose
#: log1p/exp rounding can differ by 1 ULP from the vectorized path every
#: width >= 2 takes — measured: a width-1 bucket's Opt post times drift
#: one float32 ULP from the dense reference, while widths 2..512 are
#: bitwise consistent (tests/test_lanes.py pins this).  Padding a
#: single-follower lane to width 2 costs one dead source row and buys
#: the bit-identity contract; the bench's identity assertion would
#: refuse to record a speedup if a future backend moved the boundary.
MIN_BUCKET_WIDTH = 2


def plan_buckets(counts: Sequence[int], max_buckets: int = 8) -> BucketPlan:
    """Group ragged lane widths into at most ``max_buckets`` pow-2 width
    buckets (floored at :data:`MIN_BUCKET_WIDTH`), greedily merging the
    adjacent pair that adds the least padding until the bound holds.
    ``max_buckets=1`` IS the dense-padded reference plan (every lane
    padded to one width) — the comparison baseline the bit-identity
    tests and the bench artifact use."""
    counts = np.asarray(counts, np.int64)
    if counts.ndim != 1 or counts.size == 0:
        raise ValueError(
            f"counts must be a non-empty 1-D array, got shape "
            f"{counts.shape}")
    if (counts < 1).any():
        i = int(np.flatnonzero(counts < 1)[0])
        raise ValueError(
            f"lane widths must be >= 1, got {counts[i]} at lane {i}")
    if max_buckets < 1:
        raise ValueError(f"max_buckets must be >= 1, got {max_buckets}")
    # Pow-2 ceilings -> (width, lane count) histogram, ascending.
    # Vectorized (a per-lane Python bucket_width() call is ~seconds of
    # host time at 10^6 lanes — inside the timed bench region): frexp
    # gives m = mant * 2**e with mant in [0.5, 1), so the ceiling is m
    # itself at exact powers of two (mant == 0.5) and 2**e otherwise —
    # exact integer arithmetic, no log2 rounding edge.
    m = np.maximum(counts, MIN_BUCKET_WIDTH)
    mant, e = np.frexp(m.astype(np.float64))
    ceil = np.where(mant == 0.5, m,
                    np.int64(1) << e.astype(np.int64)).astype(np.int64)
    widths, n_lanes = np.unique(ceil, return_counts=True)
    widths = [int(w) for w in widths]
    n_lanes = [int(n) for n in n_lanes]
    # Greedy merge: absorbing bucket i into its next-larger neighbour
    # costs n_lanes[i] * (width[i+1] - width[i]) extra padded elements;
    # repeatedly take the cheapest merge until the bound holds.
    while len(widths) > max_buckets:
        costs = [n_lanes[i] * (widths[i + 1] - widths[i])
                 for i in range(len(widths) - 1)]
        i = int(np.argmin(costs))
        n_lanes[i + 1] += n_lanes[i]
        del widths[i], n_lanes[i]
    dense = int(max(widths))
    lane_bucket = np.searchsorted(np.asarray(widths, np.int64), ceil,
                                  side="left")
    plan = BucketPlan(widths=tuple(widths), lane_bucket=lane_bucket,
                      counts=counts, dense_width=dense)
    _telemetry.event("lanes.bucket_plan", n_buckets=plan.n_buckets,
                     lanes=len(counts), dense_width=dense,
                     pad_frac_bucketed=round(plan.pad_frac_bucketed, 4),
                     pad_frac_dense=round(plan.pad_frac_dense, 4))
    return plan


# ---------------------------------------------------------------------------
# Measured slab auto-tuning
# ---------------------------------------------------------------------------

#: Envelope schema of the autotune cache artifact; bump on layout changes
#: so a stale cache re-measures instead of being misread.
AUTOTUNE_SCHEMA = "rq.lanes.autotune/1"

#: Candidate slab TARGETS the first-use measurement times.  This tuple is
#: the autotuner's own search space — the one place a slab number may be
#: written down (rqlint RQ602 flags hard-coded slab constants everywhere
#: else).  Spanning 0.5x-2x the last hand-swept optimum keeps the
#: measurement cheap (<= 3 timed probes) while covering the regime where
#: the working set crosses the cache boundary.
SLAB_CANDIDATES = (1250, 2500, 5000)

ENV_AUTOTUNE_PATH = "RQ_LANES_AUTOTUNE"


def autotune_cache_path() -> str:
    """The autotune cache artifact's path: ``$RQ_LANES_AUTOTUNE`` when
    set (bench children inherit it, so one measurement serves a whole
    engine sweep), else a per-user cache file."""
    env = os.environ.get(ENV_AUTOTUNE_PATH)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "redqueen_tpu",
                        "lanes_autotune.json")


def load_autotune_cache(path: Optional[str] = None) -> Dict[str, dict]:
    """The cache's ``entries`` dict (``"backend|shape_key" -> entry``).
    Missing, torn, or wrong-schema files read as empty — the autotuner
    re-measures rather than trusting an unreadable artifact."""
    path = path or autotune_cache_path()
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(obj, dict) or obj.get("schema") != AUTOTUNE_SCHEMA:
        return {}
    entries = obj.get("entries")
    return entries if isinstance(entries, dict) else {}


def _store_autotune(path: str, key: str, entry: dict) -> None:
    entries = load_autotune_cache(path)
    entries[key] = entry
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    atomic_write_json(path, {"schema": AUTOTUNE_SCHEMA, "entries": entries})


def slab_size(B: int, target: int) -> int:
    """Largest divisor of ``B`` in (target/2, target]; ``B`` itself
    (unslabbed) when no divisor lands in that window — equal slabs only,
    so a timed loop never pays a ragged remainder-slab recompile."""
    B, target = int(B), int(target)
    if target >= B:
        return B
    for s in range(target, target // 2, -1):
        if B % s == 0:
            return s
    return B


def iter_slabs(B: int, slab: int):
    """``(start, stop)`` half-open lane ranges covering ``[0, B)`` in
    ``slab``-sized pieces (the last may be short when ``slab`` does not
    divide ``B`` — callers wanting equal slabs pick via
    :func:`slab_size`)."""
    B, slab = int(B), int(slab)
    if slab < 1:
        raise ValueError(f"slab must be >= 1, got {slab}")
    for s0 in range(0, B, slab):
        yield s0, min(s0 + slab, B)


def _choice_from_entries(entries: Dict[str, dict], B: int, *,
                         backend: str, shape_key: str):
    """Cache-hit consult against already-loaded entries (so per-bucket
    callers pay ONE file read per dispatch, never one per bucket);
    None on a miss."""
    entry = entries.get(f"{backend}|{shape_key}")
    if entry and isinstance(entry.get("target"), int):
        target = int(entry["target"])
        return SlabChoice(slab_size(int(B), target), target, "cache", {})
    return None


class SlabChoice(NamedTuple):
    """A slab decision and its provenance: ``source`` is ``"measured"``
    (timed now), ``"cache"`` (a previous measurement's winner),
    ``"fallback"`` (no ``time_fn`` and no cache — the median candidate),
    or ``"unslabbed"`` (batch no bigger than the smallest candidate).
    ``measurements`` maps candidate target -> the per-lane cost
    ``time_fn`` reported (empty unless measured this call)."""

    slab: int
    target: int
    source: str
    measurements: Dict[int, float]


def measured_slab(B: int, *, backend: str, shape_key: str,
                  time_fn: Optional[Callable[[int], float]] = None,
                  candidates: Sequence[int] = SLAB_CANDIDATES,
                  cache_path: Optional[str] = None,
                  force: bool = False) -> SlabChoice:
    """The slab size for a ``B``-lane batch on ``backend``, measured —
    not guessed.

    First use per ``(backend, shape_key)``: calls ``time_fn(slab)`` for
    each distinct candidate slab (``time_fn`` returns a comparable cost,
    canonically seconds per lane for one dispatch of that many lanes),
    picks the cheapest, and records the winner in the
    ``rq.lanes.autotune/1`` artifact at ``cache_path`` (default
    :func:`autotune_cache_path`).  Later calls reuse the cached winner
    without re-measuring (``force=True`` re-measures).  Without a
    ``time_fn`` and without a cache entry the median candidate is
    returned with ``source="fallback"`` — recorded, never silent."""
    B = int(B)
    cands = sorted({int(c) for c in candidates})
    if not cands or any(c < 1 for c in cands):
        raise ValueError(f"candidates must be positive, got {candidates}")
    if B <= cands[0]:
        return SlabChoice(B, B, "unslabbed", {})
    key = f"{backend}|{shape_key}"
    path = cache_path or autotune_cache_path()
    if not force:
        choice = _choice_from_entries(load_autotune_cache(path), B,
                                      backend=backend, shape_key=shape_key)
        if choice is not None:
            return choice
    if time_fn is None:
        target = cands[len(cands) // 2]
        return SlabChoice(slab_size(B, target), target, "fallback", {})
    with _telemetry.span("lanes.autotune", backend=backend,
                         shape_key=shape_key, lanes=B) as sp:
        measurements: Dict[int, float] = {}
        by_slab: Dict[int, int] = {}  # distinct slab -> its target
        for target in cands:
            by_slab.setdefault(slab_size(B, target), target)
        for slab, target in by_slab.items():
            measurements[target] = float(time_fn(slab))
        best_target = min(measurements, key=measurements.get)
        sp.set(winner=best_target, measurements=measurements)
    _store_autotune(path, key, {
        "target": int(best_target),
        "per_lane_cost": {str(t): measurements[t] for t in measurements},
        "lanes": B,
        "candidates": cands,
    })
    return SlabChoice(slab_size(B, best_target), int(best_target),
                      "measured", dict(measurements))


# ---------------------------------------------------------------------------
# Slab dispatch (the scan driver's batch splitter, library-side)
# ---------------------------------------------------------------------------


def _pad_log_width(times, srcs, width: int):
    import jax.numpy as jnp

    have = times.shape[-1]
    if have == width:
        return times, srcs
    pad = [(0, 0)] * (times.ndim - 1) + [(0, width - have)]
    return (jnp.pad(times, pad, constant_values=jnp.inf),
            jnp.pad(srcs, pad, constant_values=-1))


def dispatch_slabbed(cfg, params, adj, seeds, slab: int, *,
                     max_chunks: int = 100, sync_every: int = 8,
                     max_events=None, engine: str = "scan",
                     dispatch: Optional[Callable] = None):
    """The dispatch half of :func:`simulate_slabbed`: run the [B]-lane
    batch as consecutive ``slab``-lane dispatches and return the
    per-slab ``EventLog`` list, WITHOUT the concatenation — so a timed
    bench region can measure pure dispatch (the old protocol) and pay
    the merge once, after the clock stops.

    ``dispatch(cfg, params, adj, seeds) -> EventLog`` overrides the
    per-slab dispatch (bench harnesses close extra options over it);
    the default is :func:`~redqueen_tpu.sim.simulate_batch` with the
    keyword options here."""
    import jax

    if dispatch is None:
        from ..sim import simulate_batch  # local: sim imports are heavy

        def dispatch(c, p, a, s):
            return simulate_batch(c, p, a, s, max_chunks=max_chunks,
                                  sync_every=sync_every,
                                  max_events=max_events, engine=engine)

    B = int(np.shape(seeds)[0])
    slab = int(slab)
    # Seeds are a tiny [B] host list by contract (per-lane integers) —
    # slicing them host-side is the slab layer's job, not a hidden sync.
    seeds_np = np.asarray(seeds)  # rqlint: disable=RQ701 host seed list
    logs = []
    with _telemetry.span("lanes.slab", lanes=B, slab=slab) as sp:
        for s0, s1 in iter_slabs(B, slab):
            part = lambda x: x[s0:s1]  # noqa: E731 — slab slicer
            log = dispatch(cfg, jax.tree.map(part, params), part(adj),
                           seeds_np[s0:s1])
            logs.append(log)
        sp.set(dispatches=sum(lg.dispatches or 0 for lg in logs))
    _telemetry.counter("lanes.slab.dispatches", len(logs))
    return logs


def concat_slab_logs(cfg, logs):
    """Merge per-slab ``EventLog``s (from :func:`dispatch_slabbed`) into
    one batch log: slabs that ran fewer chunks are padded with the
    buffer's own (+inf, -1) fill, and ``chunk_steps`` preserves the true
    summed scan-step count for roofline accounting."""
    import jax.numpy as jnp

    if len(logs) == 1:
        out = logs[0]
        out.chunk_steps = out.times.shape[-1]
        return out
    from ..sim import EventLog

    width = max(lg.times.shape[-1] for lg in logs)
    padded = [_pad_log_width(lg.times, lg.srcs, width) for lg in logs]
    out = EventLog(
        jnp.concatenate([t for t, _ in padded], axis=0),  # rqlint: disable=RQ702 host list of per-slab arrays
        jnp.concatenate([s for _, s in padded], axis=0),  # rqlint: disable=RQ702 host list of per-slab arrays
        jnp.concatenate([jnp.atleast_1d(jnp.asarray(lg.n_events))
                         for lg in logs]),
        cfg,
        health=jnp.concatenate(
            [jnp.atleast_1d(jnp.asarray(lg.health)) for lg in logs])
        if logs[0].health is not None else None,
        dispatches=sum(lg.dispatches or 0 for lg in logs),
        engine=logs[0].engine,
        engine_reason=next(
            (lg.engine_reason for lg in logs if lg.engine_reason), None),
    )
    # True scan-step total across slabs (the concat pads short slabs, so
    # the buffer width alone would over-count roofline steps).
    out.chunk_steps = sum(lg.times.shape[-1] for lg in logs)
    return out


def simulate_slabbed(cfg, params, adj, seeds, slab: int, *,
                     max_chunks: int = 100, sync_every: int = 8,
                     max_events=None, engine: str = "scan",
                     dispatch: Optional[Callable] = None):
    """Dispatch a [B]-lane batch as consecutive ``slab``-lane dispatches
    with bit-identical per-lane results (identical seeds and streams; the
    slabs only bound the working set — the CPU cache-locality win the
    autotuner measures).  Returns one concatenated ``EventLog``
    (:func:`dispatch_slabbed` + :func:`concat_slab_logs`).

    The all-lanes-sick :class:`~redqueen_tpu.runtime.numerics
    .NumericalHealthError` contract tightens to slab granularity here (a
    fully-sick slab raises even if another slab is healthy) — strictly
    earlier detection, same failure type."""
    return concat_slab_logs(cfg, dispatch_slabbed(
        cfg, params, adj, seeds, slab, max_chunks=max_chunks,
        sync_every=sync_every, max_events=max_events, engine=engine,
        dispatch=dispatch))


def probe_slab_cost(run: Callable[[], object], n: int) -> float:
    """The canonical ``time_fn`` body for :func:`measured_slab`: one
    warm pass of ``run()`` (an ``n``-lane dispatch returning an
    ``EventLog`` — pays the compile), one timed pass, seconds per lane.
    Single-sourced next to ``SLAB_CANDIDATES`` so every cache entry
    under ``AUTOTUNE_SCHEMA`` was measured under the same protocol."""
    import time

    import jax

    lg = run()
    jax.block_until_ready(lg.times)
    t0 = time.perf_counter()
    lg = run()
    jax.block_until_ready(lg.times)
    return (time.perf_counter() - t0) / int(n)


# ---------------------------------------------------------------------------
# Bucketed ragged dispatch
# ---------------------------------------------------------------------------


def ragged_bucket_component(counts, width: int, *, end_time: float,
                            q: float = 1.0, wall_rate: float = 1.0,
                            capacity: int = 256, start_time: float = 0.0):
    """One bucket's dense batch, built VECTORIZED (a million-lane plan
    cannot afford a GraphBuilder per lane): per lane, source 0 is the Opt
    broadcaster and sources 1..width are Poisson walls feeding sinks
    0..width-1 — wall j of lane i runs at ``wall_rate`` when j <
    counts[i] and at rate 0 (never fires, absorbing from step 0)
    otherwise, which is exactly GraphBuilder's benign-default padding.
    The Opt row follows only the lane's REAL feeds, so metrics never
    average over padding.  Returns ``(cfg, params [B_b], adj [B_b])``
    matching :func:`~redqueen_tpu.config.GraphBuilder.build` semantics
    lane-for-lane (pinned by tests/test_lanes.py)."""
    import jax.numpy as jnp

    from ..config import SimConfig, SourceParams
    from ..models.base import KIND_OPT, KIND_POISSON

    counts = np.asarray(counts, np.int64)
    width = int(width)
    if (counts < 1).any() or (counts > width).any():
        raise ValueError(
            f"bucket of width {width} holds counts in "
            f"[{counts.min()}, {counts.max()}] — every lane must satisfy "
            f"1 <= count <= width")
    Bb, S, F = len(counts), width + 1, width
    kind = np.zeros((Bb, S), np.int32)
    kind[:, 0] = KIND_OPT
    kind[:, 1:] = KIND_POISSON
    real = np.arange(F)[None, :] < counts[:, None]       # [Bb, F]
    rate = np.ones((Bb, S), np.float64)
    rate[:, 1:] = np.where(real, float(wall_rate), 0.0)
    q_arr = np.ones((Bb, S), np.float64)
    q_arr[:, 0] = float(q)
    adj = np.zeros((Bb, S, F), bool)
    adj[:, 0, :] = real                                   # Opt: real feeds
    adj[:, 1:, :] = np.eye(F, dtype=bool)[None]           # wall j -> sink j
    # GraphBuilder's benign defaults: dummy piecewise row (one segment,
    # rate 0, +inf tail) and +inf replay padding.
    pw_t = np.full((Bb, S, 1), np.inf)
    pw_t[:, :, 0] = 0.0
    cfg = SimConfig(
        n_sources=S, n_sinks=F, end_time=float(end_time),
        start_time=float(start_time), capacity=int(capacity),
        rmtpp_hidden=1,
        present_kinds=tuple(sorted({KIND_POISSON, KIND_OPT})),
        opt_rows=(0,),
    )
    f32 = jnp.float32
    params = SourceParams(
        kind=jnp.asarray(kind),
        rate=jnp.asarray(rate, f32),
        l0=jnp.ones((Bb, S), f32),
        alpha=jnp.zeros((Bb, S), f32),
        beta=jnp.ones((Bb, S), f32),
        pw_times=jnp.asarray(pw_t, f32),
        pw_rates=jnp.zeros((Bb, S, 1), f32),
        rd_times=jnp.full((Bb, S, 1), jnp.inf, f32),
        q=jnp.asarray(q_arr, f32),
        s_sink=jnp.ones((Bb, F), f32),
    )
    return cfg, params, jnp.asarray(adj)


def shape_budget(width: int, end_time: float, wall_rate: float,
                 capacity: Optional[int] = None):
    """``(capacity, max_chunks)`` for a broadcaster component of
    ``width`` Poisson-feed followers — THE measured sizing rule, shared
    by bench.py and the ragged bucket dispatcher so the two can never
    diverge: chunk capacity ~mean_events/16 (pow2, clamped [64, 2048] —
    the re-swept optimum between absorbed-step waste and per-chunk
    dispatch cost under the superchunk driver) unless the caller pins
    one, with a ~4x event-count chunk allowance floored at 64 (a flat
    allowance silently capped big-F runs; the overflow contract must
    fail on real overflow, not a harness artifact)."""
    mean_ev = end_time * wall_rate * width * 1.25
    if capacity is None:
        capacity = int(min(2048, max(
            64, 1 << int(np.log2(max(mean_ev / 16, 1)) + 0.5))))
    max_chunks = max(64, int(4 * mean_ev / capacity) + 1)
    return int(capacity), int(max_chunks)


class RaggedResult(NamedTuple):
    """Per-lane summaries of a bucketed ragged dispatch, in the CALLER'S
    original lane order (bucket reordering is internal).  ``logs`` is
    ``None`` unless ``return_logs=True`` (test/debug shapes): per lane,
    the ``(times, srcs)`` arrays trimmed to its valid events."""

    n_events: np.ndarray       # i64[B]
    top_k: np.ndarray          # f64[B] mean time-in-top-K over real feeds
    posts: np.ndarray          # f64[B] broadcaster posts
    health: np.ndarray         # u32[B] lane-health bitmask
    plan: BucketPlan
    dispatches: int
    engine: str
    logs: Optional[List[Tuple[np.ndarray, np.ndarray]]]

    @property
    def events(self) -> int:
        return int(self.n_events.sum())


def _numeric_fault_site(counts_len: int):
    """(original lane, mode) of the env numeric fault when it addresses
    this ragged dispatch, else None — evaluated ONCE against the
    original lane order so bucket reordering cannot change which logical
    lane gets hit."""
    return _faultinject.active_numeric_lane(counts_len)


def simulate_ragged(counts, seeds, *, end_time: float, q: float = 1.0,
                    wall_rate: float = 1.0, engine: str = "scan",
                    max_buckets: int = 8, capacity: Optional[int] = None,
                    sync_every: int = 8, slab_target: Optional[int] = None,
                    max_lane_elems: int = 32_000_000, metric_K: int = 1,
                    cache_path: Optional[str] = None,
                    return_logs: bool = False) -> RaggedResult:
    """Simulate ``B`` ragged broadcaster components (1 Opt vs
    ``counts[i]`` Poisson-feed followers — the headline per-broadcaster
    component at per-lane width) as at most ``max_buckets`` dense bucket
    dispatches.

    Per-lane results are BIT-IDENTICAL to the dense-padded reference
    (``max_buckets=1``) on matched seeds — and to the unpadded
    single-component ``GraphBuilder`` build — because padding adds only
    rate-0 sources whose streams nothing consumes (pinned by
    tests/test_lanes.py for the scan engine and the pallas interpreter).

    ``seeds`` [B] ride with their lanes through the bucket reordering;
    ``engine`` forwards to :func:`~redqueen_tpu.sim.simulate_batch`.
    Each bucket dispatches in slabs sized by the autotune cache (
    ``slab_target`` overrides; a per-slab element ceiling
    ``max_lane_elems`` bounds host+device memory at big widths).
    ``RQ_FAULT=numeric:*@laneN`` addresses lane N of the ORIGINAL order.
    """
    from ..utils.metrics import feed_metrics_batch, num_posts

    counts = np.asarray(counts, np.int64)
    seeds = np.asarray(seeds)
    if seeds.ndim != 1 or len(seeds) != len(counts):
        raise ValueError(
            f"seeds must be 1-D with one entry per lane, got "
            f"{seeds.shape} for {len(counts)} lanes")
    plan = plan_buckets(counts, max_buckets=max_buckets)
    B = len(counts)
    # Evaluate the env numeric fault ONCE against the original lane
    # order: fault_lane is the addressed ORIGINAL lane (None when the
    # spec misses this dispatch), abs_lane the spec's absolute index
    # (what nested scopes must translate against).
    fault_site = _numeric_fault_site(B)
    fault_chunk = _faultinject.numeric_scope_ctx()[0]
    fault_lane = fault_site[0] if fault_site is not None else None
    abs_lane = (_faultinject.numeric_fault().lane
                if fault_site is not None else None)

    n_events = np.zeros(B, np.int64)
    top_k = np.zeros(B, np.float64)
    posts = np.zeros(B, np.float64)
    health = np.zeros(B, np.uint32)
    logs: Optional[list] = [None] * B if return_logs else None
    dispatches = 0
    engine_used = engine
    # The autotune cache is read ONCE per dispatch (not once per bucket):
    # simulate_ragged runs inside timed bench regions, where a per-bucket
    # open()+parse would land avoidable file I/O on the clock.
    if slab_target is None:
        backend = _backend_name()
        at_entries = load_autotune_cache(cache_path)
    else:
        backend, at_entries = None, {}

    with _telemetry.span("lanes.ragged", lanes=B,
                         n_buckets=plan.n_buckets,
                         pad_frac=round(plan.pad_frac_bucketed, 4)):
        for b, width in enumerate(plan.widths):
            idx = plan.lanes_of(b)
            if idx.size == 0:
                continue
            cap_b, max_chunks = shape_budget(
                width, end_time, wall_rate, capacity)
            real_e = int(counts[idx].sum())
            _telemetry.counter("lanes.pad.real_elems", real_e)
            _telemetry.counter("lanes.pad.padded_elems",
                               width * idx.size - real_e)
            # Slab sizing: the autotuned target for this backend/width
            # bucket (cache consult only — ragged callers measure via
            # bench/tools, not mid-dispatch), clamped by the
            # per-dispatch element ceiling so hub-width buckets cannot
            # blow host/device memory.
            if slab_target is None:
                choice = _choice_from_entries(
                    at_entries, int(idx.size), backend=backend,
                    shape_key=f"ragged/W{width}")
                if choice is None and backend == "cpu":
                    # No measured entry: the recorded fallback (median
                    # candidate), same policy as measured_slab without
                    # a time_fn.
                    target = sorted(SLAB_CANDIDATES)[
                        len(SLAB_CANDIDATES) // 2]
                    choice = SlabChoice(
                        slab_size(int(idx.size), target), target,
                        "fallback", {})
                elif choice is None:
                    # The fallback candidates are CPU cache-locality
                    # numbers; on an accelerator with no MEASURED entry
                    # they would fragment the dispatch the chip wants
                    # whole — run the bucket unslabbed (the memory
                    # ceiling below still bounds it).
                    choice = SlabChoice(int(idx.size), int(idx.size),
                                        "unslabbed", {})
            else:
                choice = SlabChoice(
                    slab_size(int(idx.size), int(slab_target)),
                    int(slab_target), "caller", {})
            slab = max(1, min(choice.slab,
                              max_lane_elems // max(width * width, 1)))
            # Prefer equal slabs (one compiled shape), but NEVER let the
            # divisor window re-inflate past the memory ceiling:
            # slab_size returns the bucket size itself when no divisor
            # lands in (slab/2, slab], which at hub widths would undo
            # the clamp — a ragged remainder slab (one extra compile)
            # is the cheaper failure.
            eq = slab_size(int(idx.size), slab)
            slab = eq if eq <= slab else slab
            with _telemetry.span("lanes.ragged.bucket", width=width,
                                 lanes=int(idx.size), slab=slab) as bsp:
                for s0, s1 in iter_slabs(idx.size, slab):
                    oi = idx[s0:s1]
                    # The slab's arrays are built HERE, slab-sized
                    # (never the whole bucket): at 10^6 lanes a
                    # hub-width bucket's full [B_b, S, F] adjacency
                    # would not fit, and equal slabs share one compiled
                    # shape per bucket anyway.
                    cfg, params, adj = ragged_bucket_component(
                        counts[oi], width, end_time=end_time, q=q,
                        wall_rate=wall_rate, capacity=cap_b)
                    try:
                        log = _dispatch_ragged_slab(
                            cfg, params, adj, seeds[oi], oi, engine,
                            max_chunks, sync_every, fault_lane, abs_lane,
                            fault_chunk)
                    except _NumericalHealthError as e:
                        # Every lane of THIS slab died; the ragged layer
                        # owns lane granularity, so record the per-lane
                        # bits at their original positions (metrics stay
                        # zero — garbage is never reported) and keep the
                        # other buckets' results.  If the WHOLE dispatch
                        # is sick the caller sees it in
                        # RaggedResult.health, matching the sweep
                        # layer's quarantine contract.
                        health[oi] = e.health.astype(np.uint32)
                        dispatches += 1
                        continue
                    dispatches += log.dispatches or 1
                    engine_used = log.engine
                    m = feed_metrics_batch(
                        log.times, log.srcs, adj, 0, end_time,
                        K=metric_K)
                    # The bucket's one results boundary: reduced per-lane
                    # scalars cross to host here, never per event.
                    n_events[oi] = np.asarray(
                        _dg(log.n_events)).reshape(-1)
                    top_k[oi] = np.asarray(
                        _dg(m.mean_time_in_top_k())).reshape(-1)
                    posts[oi] = np.asarray(
                        _dg(num_posts(log.srcs, 0))).reshape(-1)
                    if log.health is not None:
                        health[oi] = np.asarray(
                            _dg(log.health)).reshape(-1)
                    if logs is not None:
                        t_np = np.asarray(_dg(log.times))
                        s_np = np.asarray(_dg(log.srcs))
                        for j, lane in enumerate(oi):
                            ne = int(n_events[lane])
                            logs[lane] = (t_np[j, :ne].copy(),
                                          s_np[j, :ne].copy())
                bsp.set(dispatches=dispatches)
    return RaggedResult(n_events=n_events, top_k=top_k, posts=posts,
                        health=health, plan=plan, dispatches=dispatches,
                        engine=engine_used, logs=logs)


def _dg(x):
    """The ragged dispatch's documented device->host boundary (one
    reduced per-lane vector per bucket slab)."""
    import jax

    return jax.device_get(x)  # rqlint: disable=RQ701 results boundary


def _backend_name() -> str:
    import jax

    return jax.devices()[0].platform


def _dispatch_ragged_slab(cfg, params, adj, seeds_oi, oi, engine,
                          max_chunks, sync_every, fault_lane, abs_lane,
                          fault_chunk):
    """One bucket-slab dispatch, with the env numeric fault translated
    into the slab's local lane space (or pushed out of range for slabs
    that do not contain the addressed original lane)."""
    from ..sim import simulate_batch

    kwargs = dict(max_chunks=max_chunks, sync_every=sync_every,
                  engine=engine)
    if fault_lane is None:
        return simulate_batch(cfg, params, adj, seeds_oi, **kwargs)
    pos = np.flatnonzero(oi == fault_lane)
    # lane_base translates the spec's absolute lane index onto this
    # slab's local position of the addressed ORIGINAL lane; a slab
    # without the lane gets a base that pushes the translated index
    # below 0 (never fires).
    base = (int(abs_lane) - int(pos[0]) if pos.size
            else int(abs_lane) + len(oi) + 1)
    with _faultinject.numeric_scope(chunk=fault_chunk, lane_base=base):
        return simulate_batch(cfg, params, adj, seeds_oi, **kwargs)
