"""Sharded sweep execution: the BASELINE's "broadcaster x follower graphs
shard over a TPU slice" path (north star; SURVEY.md section 2 parallelism
table). A 10k-broadcaster / 100k-follower bipartite graph decomposes into
independent per-broadcaster components (RedQueen broadcasters do not couple:
each one's u*(t) reads only its own followers' ranks), so the scale-out is
SPMD over the component batch: inputs land sharded over the ``data`` mesh
axis, the vmapped event-scan kernel runs with zero hot-loop communication,
and only metric aggregation reduces across devices.

This file deliberately contains no kernel logic: it places data
(``comm.shard_leading``) and reuses the exact ``sim`` driver, so sharded and
unsharded paths cannot drift apart.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import random as jr
from jax.sharding import Mesh

from ..config import SimConfig, SourceParams
from ..sim import simulate_batch
from . import comm

__all__ = ["simulate_sharded"]


def simulate_sharded(cfg: SimConfig, params: SourceParams, adj, seeds,
                     mesh: Mesh, axis="data",
                     max_chunks: int = 100, return_state: bool = False):
    """Run a component batch sharded over ``mesh`` axis ``axis``.

    ``axis`` may be a tuple of axis names to shard the batch over several
    mesh axes at once — the multi-slice layout (``("dcn", "data")``): the
    batch spreads over slices x chips-per-slice with zero hot-loop
    communication, exactly the regime DCN's lower bandwidth wants.

    ``params``/``adj``/``seeds`` carry a leading batch dim divisible by the
    (total) axis size. Results are identical (bit-for-bit at matched seeds)
    to ``simulate_batch`` on one device: sharding only changes placement,
    and the per-source PRNG streams are layout-independent by construction
    (SURVEY.md section 7 PRNG discipline; pinned by
    tests/test_sharding.py)."""
    B = jnp.asarray(seeds).shape[0]
    B_params = params.kind.shape[0]
    B_adj = adj.shape[0]
    if not (B == B_params == B_adj):
        raise ValueError(
            f"batch dims disagree: seeds={B}, params={B_params}, adj={B_adj}"
        )
    ax_size = comm.axis_total(mesh, axis)
    if B % ax_size != 0:
        raise ValueError(f"batch {B} not divisible by mesh axis {axis}={ax_size}")
    seeds = jnp.asarray(seeds)
    keys = jax.vmap(jr.PRNGKey)(seeds) if seeds.ndim == 1 else seeds
    with mesh:
        params_s = comm.shard_leading(params, mesh, axis)
        adj_s = comm.shard_leading(adj, mesh, axis)
        keys_s = comm.shard_leading(keys, mesh, axis)
        return simulate_batch(
            cfg, params_s, adj_s, keys_s,
            max_chunks=max_chunks, return_state=return_state,
        )
