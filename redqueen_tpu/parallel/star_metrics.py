"""Per-feed rank metrics for the star engine: the closed-form hot path and
its sequential merge-scan twin (the property-test oracle) — step 3 of the
``bigf.py`` design.

Split out of ``bigf.py`` (round-5 verdict item 7).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.metrics import FeedMetrics
from .star_types import StarConfig

__all__ = [
    "_feed_metrics_star",
    "_feed_metrics_star_scan",
    "_METRIC_FEED_BLOCK",
]

# Feeds per metrics block: bounds the closed form's peak memory at
# block x E (E = merged wall slots per feed) floats per wall-side
# intermediate while keeping blocks wide enough to saturate the vector
# units.
_METRIC_FEED_BLOCK = 8192


def _feed_metrics_star(cfg: StarConfig, feed_times, own_times, K: int):
    """Per-feed rank integrals in closed form — no sequential pass at all.

    The merge-scan twin (``_feed_metrics_star_scan``, kept as the test
    oracle) walks E+K events per feed; on TPU that is a length-(E+K)
    sequential dependency vmapped over feeds. But with one broadcaster the
    rank process decomposes per event (reference ``utils.py`` integrals,
    SURVEY.md section 2 items 11-14):

    - each wall event w raises the rank by 1 until the next own post (or the
      horizon), so  int r dt   = sum_e  (b_e - w_e)^+  and, numbering walls
      1..m within their inter-own-post window,
      int r^2 dt = sum_e (2 i_e - 1)(b_e - w_e)^+   (telescoping i^2),
      where b_e = min(first own post > w_e, T);
    - the rank is 0 from each own post (and from the start) until the first
      wall event >= it, clipped at the next own post and T.

    Everything is searchsorted + gathers over already-sorted arrays —
    embarrassingly parallel over events AND feeds, which is exactly what the
    VPU wants. Generalizing to K > 1: rank >= K holds exactly from each
    window's K-th wall event to the window end, so

        time_below_K = (end - start) - sum_{e: i_e == K} (b_e - max(w_e, s))^+

    — the top-K integral needs ONLY the wall-side arrays (i_e, b_e, dt)
    already built for the rank integrals. An earlier formulation walked the
    own-post windows with [post_cap+1] searchsorted/gather intermediates per
    feed; it was 72% of star-engine runtime on the 100k-feed config and is
    gone (the merge-scan twin still pins both numbers).

    Tie rule (matches the oracle's argmin-lowest-index pop): an own post at
    exactly a wall-event time applies FIRST, so the wall event counts into
    the window STARTED by that own post.

    Memory: feeds are processed in ``lax.map`` blocks of
    ``_METRIC_FEED_BLOCK`` to bound the [feed_block, E] intermediates at
    100k-feed scale."""
    Fl, E = feed_times.shape
    dtype = feed_times.dtype
    start = jnp.asarray(cfg.start_time, dtype)
    end = jnp.asarray(cfg.end_time, dtype)
    inf = jnp.asarray(jnp.inf, dtype)
    own_ext = jnp.concatenate([own_times, inf[None]])          # [Kp+1]
    # Window-start array for wall COUNTING: it must include pre-start walls
    # (the carried-rank convention: events before the window still build
    # rank history), so window 0 counts from -inf, not from start_time.
    own_cnt = jnp.concatenate([-inf[None], own_times])         # [Kp+1]

    def one_feed(w_row):
        # --- wall-event side: all three integrals -----------------------
        nxt_idx = jnp.searchsorted(own_times, w_row, side="right")
        b = jnp.minimum(own_ext[nxt_idx], end)                 # window end
        a = own_cnt[nxt_idx]                                   # window start
        walls_before = jnp.searchsorted(w_row, a, side="left")
        i_e = jnp.arange(E) - walls_before + 1                 # 1-based in-window
        # Left-clipping at start_time keeps the telescoped sum exact: wall i
        # contributes (i^2 - (i-1)^2) * (b - max(w_i, start))^+ .
        dt = jnp.maximum(b - jnp.maximum(w_row, start), 0.0)
        ir = dt.sum()
        ir2 = ((2.0 * i_e.astype(dtype) - 1.0) * dt).sum()
        # Padded wall slots (+inf) get dt = 0, so they drop out of every
        # sum including the top-K complement below.
        topk = (end - start) - jnp.where(i_e == K, dt, 0.0).sum()
        return topk, ir, ir2

    if Fl <= _METRIC_FEED_BLOCK:
        top, ir, ir2 = jax.vmap(one_feed)(feed_times)
    else:
        nb = -(-Fl // _METRIC_FEED_BLOCK)
        padded = jnp.concatenate([
            feed_times,
            jnp.full((nb * _METRIC_FEED_BLOCK - Fl, E), jnp.inf, dtype),
        ]) if nb * _METRIC_FEED_BLOCK != Fl else feed_times
        blocks = padded.reshape(nb, _METRIC_FEED_BLOCK, E)
        top, ir, ir2 = lax.map(
            lambda b: jax.vmap(one_feed)(b), blocks
        )
        top = top.reshape(-1)[:Fl]
        ir = ir.reshape(-1)[:Fl]
        ir2 = ir2.reshape(-1)[:Fl]
    return FeedMetrics(
        time_in_top_k=top, int_rank=ir, int_rank2=ir2,
        follows=jnp.ones((Fl,), bool), start_time=start, end_time=end,
    )


def _feed_metrics_star_scan(cfg: StarConfig, feed_times, own_times, K: int):
    """Sequential merge-scan twin of :func:`_feed_metrics_star` (the
    reference-shaped two-pointer walk). Kept as the property-test oracle for
    the closed form; not used in the hot path.

    Tie rule: an own post at exactly a wall-event time applies FIRST (the
    oracle's Manager pops the lowest source index — the controlled
    broadcaster is row 0)."""
    Fl, E = feed_times.shape
    Kp = own_times.shape[0]
    dtype = feed_times.dtype
    start = jnp.asarray(cfg.start_time, dtype)
    end = jnp.asarray(cfg.end_time, dtype)
    own_ext = jnp.concatenate([own_times, jnp.full((1,), jnp.inf, dtype)])

    def one_feed(times_row):
        row_ext = jnp.concatenate([times_row, jnp.full((1,), jnp.inf, dtype)])

        def step(carry, _):
            i, j, r, t_prev, top, ir, ir2 = carry
            t_w, t_o = row_ext[i], own_ext[j]
            own_first = t_o <= t_w
            t = jnp.minimum(t_w, t_o)
            valid = jnp.isfinite(t)
            t_clip = jnp.clip(jnp.where(valid, t, t_prev), start, end)
            dt = jnp.maximum(t_clip - t_prev, 0)
            rf = r.astype(dtype)
            top2 = top + dt * (r < K)
            ir_2 = ir + dt * rf
            ir2_2 = ir2 + dt * rf * rf
            r_new = jnp.where(own_first, 0, r + 1)
            return (
                jnp.where(valid & ~own_first, i + 1, i),
                jnp.where(valid & own_first, j + 1, j),
                jnp.where(valid, r_new, r),
                jnp.maximum(t_prev, t_clip),
                top2, ir_2, ir2_2,
            ), None

        zero = jnp.asarray(0.0, dtype)
        init = (jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
                jnp.zeros((), jnp.int32), start, zero, zero, zero)
        (i, j, r, t_prev, top, ir, ir2), _ = lax.scan(
            step, init, None, length=E + Kp
        )
        dt = jnp.maximum(end - t_prev, 0)
        rf = r.astype(dtype)
        return top + dt * (r < K), ir + dt * rf, ir2 + dt * rf * rf

    top, ir, ir2 = jax.vmap(one_feed)(feed_times)
    return FeedMetrics(
        time_in_top_k=top, int_rank=ir, int_rank2=ir2,
        follows=jnp.ones((Fl,), bool), start_time=start, end_time=end,
    )
