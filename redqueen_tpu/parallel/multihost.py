"""Multi-host execution: the rebuild's counterpart of an NCCL/MPI-style
distributed backend (SURVEY.md §5 "Distributed communication backend").

The reference is a single NumPy process; its scale-out story stops at one
core. Here the distributed story is JAX's multi-controller SPMD: every host
runs the same program, ``jax.distributed.initialize`` connects the
processes through the coordination service, ``jax.devices()`` becomes the
GLOBAL device list, and one ``Mesh`` spans every host — after which the
exact same ``comm``/``shard``/``bigf`` code that runs on one chip runs on a
pod, with XLA lowering the named-axis collectives onto ICI inside a slice
and DCN across slices. Nothing in the kernels knows how many processes
exist; that is the whole design (comm.py degrades every collective to a
no-op at axis size 1, and grows to cross-host collectives here).

Axis/layout contract (matches ``comm`` and the driver dryrun):

- the **process boundary rides the leading mesh axis** (conventionally
  ``"dcn"``). ``process_mesh`` guarantees this alignment, so a batch
  sharded over ``("dcn", "data")`` places each process's rows on its own
  local devices and the hot loop stays communication-free across DCN —
  exactly the layout ``simulate_sharded(..., axis=("dcn", "data"))``
  already exercises single-process (tests/test_sharding.py) and the driver
  dryrun compiles.
- metric aggregation (``comm.psum``) is the only cross-host traffic, one
  scalar-sized reduce per sweep — the regime DCN's bandwidth wants.

Verified end-to-end by ``tests/test_multihost.py``: two REAL coordinated
processes (4 virtual CPU devices each) build the global 8-device mesh, run
the sharded simulation, and the gathered event log is bit-identical to the
same mesh in one process — crossing a genuine process boundary changes
placement only, never results.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "initialize",
    "process_mesh",
    "gather_global",
    "process_summary",
]

# Environment contract for launchers (torchrun/mpirun analogue): every
# process of a run exports the same coordinator and count, its own id.
ENV_COORD = "RQ_COORDINATOR"      # host:port of process 0
ENV_NPROC = "RQ_NUM_PROCESSES"
ENV_PROC_ID = "RQ_PROCESS_ID"


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> Tuple[int, int]:
    """Join the multi-process run; return ``(process_index, process_count)``.

    Arguments fall back to the ``RQ_COORDINATOR`` / ``RQ_NUM_PROCESSES`` /
    ``RQ_PROCESS_ID`` environment (so launchers can configure without code
    changes). With no arguments and no environment this is a no-op single
    -process "run" — the same program works launched alone or under a
    multi-host launcher, like the reference user expects of an MPI program.

    Must be called BEFORE the first JAX computation (backend initialization
    pins the device topology). On real multi-host TPU, ``initialize()``
    with no arguments lets JAX's TPU auto-detection fill everything in.
    """
    import jax

    coordinator = coordinator or os.environ.get(ENV_COORD)
    if num_processes is None and os.environ.get(ENV_NPROC):
        num_processes = int(os.environ[ENV_NPROC])
    if process_id is None and os.environ.get(ENV_PROC_ID):
        process_id = int(os.environ[ENV_PROC_ID])

    if coordinator is None and (num_processes in (None, 1)):
        return jax.process_index(), jax.process_count()

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_index(), jax.process_count()


def process_mesh(local_axes: dict, process_axis: str = "dcn"):
    """Build a global mesh whose LEADING axis is the process dimension.

    ``local_axes`` describes the per-process (intra-host) axes, e.g.
    ``{"data": 4}``; the returned mesh is
    ``Mesh[(process_axis, *local_axes)]`` with the process axis varying
    slowest, so each process's addressable devices form one contiguous
    slice of the leading axis — the alignment that makes
    ``("dcn", "data")``-sharded batches land host-local.

    Uses the raw global device list ordered by (process_index, local id)
    rather than ``mesh_utils.create_device_mesh`` — topology-driven
    reordering must never move a device across the process boundary.
    Any ``local_axes`` value may be ``-1`` once for "all remaining local
    devices".
    """
    import jax
    from jax.sharding import Mesh

    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    n_proc = jax.process_count()
    if len(devs) % n_proc != 0:
        raise ValueError(
            f"{len(devs)} global devices not divisible by {n_proc} processes"
        )
    per_proc = len(devs) // n_proc
    names = list(local_axes)
    sizes = [int(s) for s in local_axes.values()]
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if per_proc % known != 0:
            raise ValueError(
                f"local axes {local_axes} do not divide {per_proc} "
                "devices/process"
            )
        sizes[sizes.index(-1)] = per_proc // known
    if int(np.prod(sizes)) != per_proc:
        raise ValueError(
            f"local axes {dict(zip(names, sizes))} != {per_proc} "
            "devices/process"
        )
    grid = np.array(devs, dtype=object).reshape((n_proc, *sizes))
    return Mesh(grid, (process_axis, *names))


def gather_global(tree):
    """Materialize globally-sharded arrays on EVERY process as NumPy.

    The multi-host analogue of ``np.asarray(log.times)``: after a sharded
    run each process holds only its addressable shards; evaluation layers
    (the pandas metrics twin, figure scripts) want the whole log. One
    all-gather over DCN+ICI, outside the hot loop.
    """
    import jax
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return jax.tree.map(np.asarray, tree)

    def _leaf(x):
        # Only process-sharded jax.Arrays need the all-gather. Replicated
        # host-NumPy leaves (e.g. StarResult.own_times riding along in the
        # same tree) are already whole on every process — all-gathering
        # them would concatenate process_count copies and silently change
        # their shape (round-4 advisor finding).
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(x)

    return jax.tree.map(_leaf, tree)


def process_summary() -> dict:
    """One line of topology facts for logs/artifacts (which process, how
    many, local vs global device counts, platform)."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "platform": jax.devices()[0].platform,
    }
