"""Wall-source and controlled-broadcaster stream sampling for the star
engine (step 1 of the ``bigf.py`` design: wall sources never react, so every
stream samples independently — ``vmap`` over feeds, sharded over the
``feed`` mesh axis).

Split out of ``bigf.py`` (round-5 verdict item 7).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..models.base import (
    KIND_HAWKES,
    KIND_PIECEWISE,
    KIND_POISSON,
    KIND_REALDATA,
    KIND_RMTPP,
)
from ..ops import streams
from .star_types import _EMPTY, StarConfig, WallParams

__all__ = ["_wall_branches", "_ctrl_stream", "_check_wall_kinds"]


def _wall_branches(cfg: StarConfig):
    """(codes, branch fns) for the wall-slot lax.switch, pruned to the kinds
    present (cfg.wall_kinds; empty tuple = all supported)."""
    t0, T, cap = cfg.start_time, cfg.end_time, cfg.wall_cap

    def b_empty(p, m, key):
        return streams.Stream(
            jnp.full((cap,), jnp.inf, jnp.float32),
            jnp.zeros((), jnp.int32), jnp.zeros((), bool),
        )

    def b_poisson(p, m, key):
        return streams.poisson_stream(key, p.rate[m], t0, T, cap)

    def b_hawkes(p, m, key):
        return streams.hawkes_stream(
            key, p.l0[m], p.alpha[m], p.beta[m], t0, T, cap
        )

    def b_piecewise(p, m, key):
        return streams.piecewise_stream(
            key, p.pw_times[m], p.pw_rates[m], t0, T, cap
        )

    def b_realdata(p, m, key):
        row = p.rd_times[m]
        Kr = row.shape[0]
        if Kr < cap:
            row = jnp.concatenate(
                [row, jnp.full((cap - Kr,), jnp.inf, row.dtype)]
            )
        s = streams.realdata_stream(row, t0, T)
        if Kr <= cap:
            return s
        # replay longer than the buffer: keep the first cap in-window events,
        # flag truncation if any were dropped.
        n_all = s.n
        return streams.Stream(
            s.times[:cap], jnp.minimum(n_all, cap), n_all > cap
        )

    table = {
        _EMPTY: b_empty,
        KIND_POISSON: b_poisson,
        KIND_HAWKES: b_hawkes,
        KIND_PIECEWISE: b_piecewise,
        KIND_REALDATA: b_realdata,
    }
    codes = sorted(cfg.wall_kinds) if cfg.wall_kinds else sorted(table)
    for c in codes:
        if c not in table:
            raise ValueError(f"unsupported wall-source kind {c}")
    return codes, [table[c] for c in codes]


def _ctrl_stream(cfg: StarConfig, ctrl, key):
    """Posting stream of a non-Opt controlled broadcaster (static dispatch on
    cfg.ctrl_kind — the reference's per-policy manager factories)."""
    t0, T, K = cfg.start_time, cfg.end_time, cfg.post_cap
    k = cfg.ctrl_kind
    if k == KIND_POISSON:
        return streams.poisson_stream(key, ctrl.rate, t0, T, K)
    if k == KIND_PIECEWISE:
        return streams.piecewise_stream(key, ctrl.pw_times, ctrl.pw_rates,
                                        t0, T, K)
    if k == KIND_HAWKES:
        # Hawkes is self-history-only, so it is a legal controlled stream
        # (the reference's vs-Hawkes posting comparison — SURVEY.md section 2
        # item 5 — at big F).
        if ctrl.l0 is None:
            raise ValueError(
                "ctrl_kind=HAWKES requires CtrlParams.l0/alpha/beta — build "
                "via StarBuilder.ctrl_hawkes"
            )
        return streams.hawkes_stream(
            key, ctrl.l0, ctrl.alpha, ctrl.beta, t0, T, K
        )
    if k == KIND_REALDATA:
        # Pad/clip the replay row to the documented [post_cap] contract
        # (StarResult.own_times is [post_cap]); keep the first post_cap
        # in-window posts and flag truncation, mirroring b_realdata.
        row = ctrl.rd_times
        Kr = row.shape[-1]
        if Kr < K:
            row = jnp.concatenate(
                [row, jnp.full((K - Kr,), jnp.inf, row.dtype)]
            )
        s = streams.realdata_stream(row, t0, T)
        if Kr <= K:
            return s
        n_all = s.n
        return streams.Stream(
            s.times[:K], jnp.minimum(n_all, K), n_all > K
        )
    if k == KIND_RMTPP:
        if ctrl.rmtpp is None:
            raise ValueError("ctrl_kind=RMTPP requires CtrlParams.rmtpp weights")
        return streams.rmtpp_stream(ctrl.rmtpp, key, t0, T, K,
                                    cfg.rmtpp_hidden)
    raise ValueError(f"unsupported ctrl_kind {k}")


def _check_wall_kinds(cfg: StarConfig, wall: WallParams):
    """A wall slot whose kind is outside the compiled branch set would be
    silently mis-dispatched by the lookup gather; reject host-side
    (wall.kind is concrete here — same guard as sim._check_kinds)."""
    codes, _ = _wall_branches(cfg)
    got = set(int(k) for k in np.unique(np.asarray(wall.kind)))
    if not got.issubset(codes):
        raise ValueError(
            f"wall slots contain kinds {sorted(got - set(codes))} not in the "
            f"config's wall_kinds {codes} — build wall params and config "
            f"from the same StarBuilder"
        )
