"""Follower-sharded simulation of star components: ONE controlled broadcaster
against a huge follower set (BASELINE configs 2 and 4: 1 broadcaster vs 1k
Hawkes feeds / 100k replay feeds) — the ``feed`` mesh axis of
redqueen_tpu.parallel.comm.

The batch kernel (ops.scan_core) replays the reference's global event loop
(reference ``Manager.run_till``, SURVEY.md section 3.1) one event per scan
step; at F = 100k followers that loop is hopeless (~F * rate * T sequential
steps). This module uses a TPU-first reformulation that deletes the loop
entirely, exact by construction:

1. Wall sources never react to anything (SURVEY.md section 2 items 4-7), so
   every feed's wall stream samples INDEPENDENTLY — ``vmap`` over feeds,
   sharded over the ``feed`` mesh axis (ops.streams).
2. The RedQueen policy's superposition clocks (reference ``Opt``, paper
   Algorithm 1): each wall event m at time t_m in feed f spawns one clock
   c_m = t_m + Exp(sqrt(s_f / q)), alive until the broadcaster's next post.
   Because every clock satisfies c_m > t_m, the k-th own post is simply

       fire_{k+1} = min{ c_m : t_m > fire_k },

   a suffix-minimum query over candidates ordered by wall time. So: draw ONE
   exponential per wall event (exactly the reference's draw count), sort
   locally by t_m, take a reverse running min, and the whole posting
   trajectory is a tiny ``lax.scan`` of searchsorted lookups whose only
   cross-device traffic is a scalar ``pmin`` over the ICI mesh axis per own
   post — the BASELINE north star's "global rank-in-feed reduction".
3. Feed-rank metrics (reference ``utils.py``) come from a per-feed
   merge-scan of (wall events, own posts), again vmapped and sharded; means
   reduce with ``psum``.

Controlled policies other than Opt (Poisson / PiecewiseConst / RealData
replay / RMTPP) depend only on their own history, so their posting stream
samples directly (ops.streams) and steps 2 is skipped — this covers the
reference's ``create_manager_with_poisson / _with_times / _with_piecewise_
const`` factory surface at big F.

Overflow (wall buffers or post buffer) is detected and raised, never silent.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax
from jax import random as jr
from jax.sharding import Mesh, PartitionSpec as P

from ..config import check_piecewise
from ..models.base import (
    KIND_HAWKES,
    KIND_OPT,
    KIND_PIECEWISE,
    KIND_POISSON,
    KIND_REALDATA,
    KIND_RMTPP,
)
from ..ops import streams
from ..utils.metrics import FeedMetrics
from . import comm

__all__ = [
    "StarConfig",
    "WallParams",
    "CtrlParams",
    "StarBuilder",
    "StarResult",
    "StarBatchResult",
    "simulate_star",
    "simulate_star_batch",
    "stack_star",
    "broadcast_star",
    "star_to_dataframe",
]

_EMPTY = -1  # wall-slot kind code for "no source in this slot"


@dataclasses.dataclass(frozen=True)
class StarConfig:
    """Static shape of a star component (hashable, jit-static)."""

    n_feeds: int
    walls_per_feed: int
    end_time: float
    start_time: float = 0.0
    wall_cap: int = 256    # events per wall source
    post_cap: int = 1024   # controlled-broadcaster posts
    ctrl_kind: int = KIND_OPT
    rmtpp_hidden: int = 1
    wall_kinds: tuple = ()  # kinds present in wall slots (branch pruning)


class WallParams(struct.PyTreeNode):
    """Wall-source parameters, [F, M] grids (feed-sharded leaves; slot kind
    ``_EMPTY`` marks unused slots)."""

    kind: jnp.ndarray       # i32[F, M]
    rate: jnp.ndarray       # f[F, M]
    l0: jnp.ndarray         # f[F, M]
    alpha: jnp.ndarray      # f[F, M]
    beta: jnp.ndarray       # f[F, M]
    pw_times: jnp.ndarray   # f[F, M, Kp]
    pw_rates: jnp.ndarray   # f[F, M, Kp]
    rd_times: jnp.ndarray   # f[F, M, Kr]
    s_sink: jnp.ndarray     # f[F] follower significance


class CtrlParams(struct.PyTreeNode):
    """Controlled-broadcaster parameters (replicated scalars/rows)."""

    q: jnp.ndarray          # f[] Opt posting cost
    rate: jnp.ndarray       # f[] Poisson rate
    pw_times: jnp.ndarray   # f[Kp] piecewise knots
    pw_rates: jnp.ndarray   # f[Kp]
    rd_times: jnp.ndarray   # f[Kr] replay timestamps
    l0: Optional[jnp.ndarray] = None     # f[] Hawkes base rate
    alpha: Optional[jnp.ndarray] = None  # f[] Hawkes jump
    beta: Optional[jnp.ndarray] = None   # f[] Hawkes decay
    rmtpp: Optional[dict] = None


class StarResult(NamedTuple):
    """Result of one star simulation.

    ``own_times`` [post_cap] ascending +inf-padded; ``wall_times`` [F, M*cap]
    per-feed merged ascending +inf-padded; ``wall_n`` [F] valid wall events
    per feed; ``metrics`` per-feed FeedMetrics over [start, T].

    Array fields are host NumPy in single-process runs. In a MULTIHOST run
    the feed-sharded fields (``wall_times``/``wall_n``/``metrics``) stay
    global ``jax.Array``s — no process can hold them whole — and
    ``parallel.multihost.gather_global`` materializes them everywhere;
    replicated fields (``own_times``, ``n_posts``) are NumPy/int as
    usual."""

    own_times: np.ndarray
    n_posts: int
    wall_times: "np.ndarray | jax.Array"
    wall_n: "np.ndarray | jax.Array"
    metrics: FeedMetrics
    cfg: StarConfig


# --------------------------------------------------------------------------
# kernel
# --------------------------------------------------------------------------


def _wall_branches(cfg: StarConfig):
    """(codes, branch fns) for the wall-slot lax.switch, pruned to the kinds
    present (cfg.wall_kinds; empty tuple = all supported)."""
    t0, T, cap = cfg.start_time, cfg.end_time, cfg.wall_cap

    def b_empty(p, m, key):
        return streams.Stream(
            jnp.full((cap,), jnp.inf, jnp.float32),
            jnp.zeros((), jnp.int32), jnp.zeros((), bool),
        )

    def b_poisson(p, m, key):
        return streams.poisson_stream(key, p.rate[m], t0, T, cap)

    def b_hawkes(p, m, key):
        return streams.hawkes_stream(
            key, p.l0[m], p.alpha[m], p.beta[m], t0, T, cap
        )

    def b_piecewise(p, m, key):
        return streams.piecewise_stream(
            key, p.pw_times[m], p.pw_rates[m], t0, T, cap
        )

    def b_realdata(p, m, key):
        row = p.rd_times[m]
        Kr = row.shape[0]
        if Kr < cap:
            row = jnp.concatenate(
                [row, jnp.full((cap - Kr,), jnp.inf, row.dtype)]
            )
        s = streams.realdata_stream(row, t0, T)
        if Kr <= cap:
            return s
        # replay longer than the buffer: keep the first cap in-window events,
        # flag truncation if any were dropped.
        n_all = s.n
        return streams.Stream(
            s.times[:cap], jnp.minimum(n_all, cap), n_all > cap
        )

    table = {
        _EMPTY: b_empty,
        KIND_POISSON: b_poisson,
        KIND_HAWKES: b_hawkes,
        KIND_PIECEWISE: b_piecewise,
        KIND_REALDATA: b_realdata,
    }
    codes = sorted(cfg.wall_kinds) if cfg.wall_kinds else sorted(table)
    for c in codes:
        if c not in table:
            raise ValueError(f"unsupported wall-source kind {c}")
    return codes, [table[c] for c in codes]


def _ctrl_stream(cfg: StarConfig, ctrl: CtrlParams, key):
    """Posting stream of a non-Opt controlled broadcaster (static dispatch on
    cfg.ctrl_kind — the reference's per-policy manager factories)."""
    t0, T, K = cfg.start_time, cfg.end_time, cfg.post_cap
    k = cfg.ctrl_kind
    if k == KIND_POISSON:
        return streams.poisson_stream(key, ctrl.rate, t0, T, K)
    if k == KIND_PIECEWISE:
        return streams.piecewise_stream(key, ctrl.pw_times, ctrl.pw_rates,
                                        t0, T, K)
    if k == KIND_HAWKES:
        # Hawkes is self-history-only, so it is a legal controlled stream
        # (the reference's vs-Hawkes posting comparison — SURVEY.md section 2
        # item 5 — at big F).
        if ctrl.l0 is None:
            raise ValueError(
                "ctrl_kind=HAWKES requires CtrlParams.l0/alpha/beta — build "
                "via StarBuilder.ctrl_hawkes"
            )
        return streams.hawkes_stream(
            key, ctrl.l0, ctrl.alpha, ctrl.beta, t0, T, K
        )
    if k == KIND_REALDATA:
        # Pad/clip the replay row to the documented [post_cap] contract
        # (StarResult.own_times is [post_cap]); keep the first post_cap
        # in-window posts and flag truncation, mirroring b_realdata.
        row = ctrl.rd_times
        Kr = row.shape[-1]
        if Kr < K:
            row = jnp.concatenate(
                [row, jnp.full((K - Kr,), jnp.inf, row.dtype)]
            )
        s = streams.realdata_stream(row, t0, T)
        if Kr <= K:
            return s
        n_all = s.n
        return streams.Stream(
            s.times[:K], jnp.minimum(n_all, K), n_all > K
        )
    if k == KIND_RMTPP:
        if ctrl.rmtpp is None:
            raise ValueError("ctrl_kind=RMTPP requires CtrlParams.rmtpp weights")
        return streams.rmtpp_stream(ctrl.rmtpp, key, t0, T, K,
                                    cfg.rmtpp_hidden)
    raise ValueError(f"unsupported ctrl_kind {k}")


def _rec_cap(E: int) -> int:
    """Static per-feed suffix-record budget for the compressed fire path.
    Records per feed are the right-to-left running minima of the candidate
    sequence; their count is ~ln E (~6 at E=256) when the superposition
    clocks are long relative to inter-event gaps (the low-intensity RedQueen
    regime: rate_f = sqrt(s/q) small), but approaches E when clocks are
    short (cand ~ w + tiny noise is nearly increasing). Overflow is checked
    loudly and the caller retries with compression off — never silent."""
    return int(max(64, 4 * np.ceil(np.log(max(E, 2)))))


def _opt_fires(cfg: StarConfig, feed_times, rate_f, key_tau, feed_offset,
               compress: bool = True, fire_mode: str = "auto"):
    """RedQueen posting times via the sorted suffix-min formulation.

    ``feed_times`` [F_local, E] ascending wall events per feed; ``rate_f``
    [F_local] = sqrt(s_f / q). Returns (own_times [post_cap], truncated,
    rec_trunc).

    ``fire_mode`` selects how the posting trajectory is extracted from the
    sorted (wall time, candidate) arrays: ``"loop"`` is the adaptive
    ``while_loop`` (one searchsorted + suffix lookup per post; under feed
    sharding also one ``pmin`` per post); ``"doubling"`` is the pointer-
    doubling formulation (see ``_fires_by_doubling``) — the SAME fires,
    bit for bit, in O(log post_cap) parallel gather passes with no
    sequential dependence on the number of posts. ``"auto"`` picks
    doubling on non-CPU backends when the feed axis is unsharded (the
    TPU's latency-bound regime) and the loop otherwise (CPU: the loop does
    ~10x less total work; sharded: the loop's pmin keeps records
    device-local).

    Suffix-record compression (``compress``): the fire loop only ever
    queries min{cand_e : w_e > t}. Within a feed, an event e1 with a later
    event e2 > e1 such that cand_e2 <= cand_e1 can NEVER be that min (any
    query admitting e1 also admits e2), so only the feed's suffix-record
    events — cand strictly below every later candidate in the row — matter,
    and the argmin of any query is itself a record. The global sort then
    shrinks from [F x E] to [F x rec_cap] with EXACT results — measured 5x
    on the 100k-feed config, where the 5M-element sort was the whole
    fire-phase cost. When a feed's record count exceeds the static budget
    (short-clock regime, see _rec_cap) the rec_trunc flag trips and
    simulate_star retries with ``compress=False`` (the full-sort path)."""
    Fl, E = feed_times.shape
    dtype = feed_times.dtype
    inf = jnp.asarray(jnp.inf, dtype)
    # Compaction into [Fl, R] slots only pays when R < E; at small E the
    # record buffer would be as large as the raw input and the cummin +
    # min-scatter passes are pure overhead (results are exact either way).
    compress = compress and E > _rec_cap(E)

    # One Exp clock per wall event — the reference's exact draw count, keyed
    # by GLOBAL feed index so mesh layout cannot change the streams.
    def feed_draws(f_global):
        return jr.exponential(jr.fold_in(key_tau, f_global), (E,), dtype)

    draws = jax.vmap(feed_draws)(feed_offset + jnp.arange(Fl))
    cand = feed_times + draws / jnp.maximum(rate_f[:, None], 1e-30)
    cand = jnp.where(rate_f[:, None] > 0, cand, jnp.inf)

    if compress:
        # --- per-feed suffix-record compaction (exact; see docstring) ---
        suf_incl = jnp.flip(lax.cummin(jnp.flip(cand, axis=1), axis=1), axis=1)
        suf_after = jnp.concatenate(
            [suf_incl[:, 1:], jnp.full((Fl, 1), jnp.inf, dtype)], axis=1
        )
        mask = cand < suf_after                  # +inf cands never qualify
        n_rec = mask.sum(axis=1)
        R = _rec_cap(E)
        rec_trunc = comm.pany((n_rec > R).any(), "feed")
        pos = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0, R - 1)
        # Min-scatter into the [Fl, R] record slots: records carry their
        # value, non-records carry +inf (a no-op under .min), and in-budget
        # record positions are unique per row, so (t, cand) pairs stay
        # aligned (the overflow case corrupts slot R-1, but rec_trunc then
        # forces the uncompressed retry before any result is used).
        val_t = jnp.where(mask, feed_times, inf)
        val_c = jnp.where(mask, cand, inf)
        t_src = jax.vmap(
            lambda p, v: jnp.full((R,), jnp.inf, dtype).at[p].min(v)
        )(pos, val_t)
        c_src = jax.vmap(
            lambda p, v: jnp.full((R,), jnp.inf, dtype).at[p].min(v)
        )(pos, val_c)
    else:
        t_src, c_src = feed_times, cand
        rec_trunc = jnp.zeros((), bool)

    t_sorted, c_sorted = lax.sort(
        (t_src.reshape(-1), c_src.reshape(-1)), num_keys=1
    )
    # suffix_min[i] = min candidate among (kept) wall events with idx >= i.
    suffix = jnp.flip(lax.cummin(jnp.flip(c_sorted)))
    suffix = jnp.concatenate([suffix, jnp.full((1,), jnp.inf, dtype)])

    sharded = comm.axis_present("feed")
    _check_fire_mode(fire_mode, feed_sharded=sharded)
    # One policy, one place: entry points resolve 'auto' before keying
    # their kernel caches; this delegate covers direct _make_kernel users.
    use_doubling = _resolve_fire_mode(fire_mode, sharded) == "doubling"

    if use_doubling:
        own, truncated = _fires_by_doubling(cfg, t_sorted, suffix)
        return own, truncated, rec_trunc

    # Adaptive fire loop: post_cap bounds the buffer, but the while_loop
    # exits as soon as the trajectory absorbs (a vmapped while runs until
    # every lane is done — with 4x-headroom caps that is typically a ~4x
    # shorter loop than a fixed-length scan). Sharded lanes stay in
    # lockstep: after the pmin the carry is identical on every shard, so
    # the loop condition is too.
    Kp = cfg.post_cap
    t0 = jnp.asarray(cfg.start_time, dtype)
    buf0 = jnp.full((Kp,), jnp.inf, dtype)

    def cond(c):
        t_last, n, _ = c
        return jnp.isfinite(t_last) & (n < Kp)

    def fire(c):
        t_last, n, buf = c
        idx = jnp.searchsorted(t_sorted, t_last, side="right")
        t_next = comm.pmin(suffix[idx], "feed")
        t_next = jnp.where(t_next <= cfg.end_time, t_next, jnp.inf)
        buf = buf.at[n].set(t_next)  # +inf write into +inf pad: no-op
        return t_next, n + jnp.isfinite(t_next).astype(n.dtype), buf

    t_last, _, own = lax.while_loop(
        cond, fire, (t0, jnp.zeros((), jnp.int32), buf0)
    )
    # Overflow: a further post would still fit before the horizon.
    idx = jnp.searchsorted(t_sorted, t_last, side="right")
    more = comm.pmin(suffix[idx], "feed") <= cfg.end_time
    truncated = jnp.isfinite(t_last) & more
    return own, truncated, rec_trunc


def _fires_by_doubling(cfg: StarConfig, t_sorted, suffix):
    """The posting trajectory as pointer doubling — the while_loop's fires,
    bit for bit, with no sequential dependence on the post count.

    The fire map is f(t) = suffix[sp(t)] with sp(t) = searchsorted(t_sorted,
    t, 'right') (the strict ``w > t`` query); every reachable fire value is
    a ``suffix`` element, so the orbit lives on POSITIONS: p_1 = sp(start),
    p_{k+1} = nxt[p_k] with nxt[i] = sp(suffix[i]), and own_k =
    suffix[p_k]. ``nxt`` is strictly forward (every candidate satisfies
    c >= its own wall time, and 'right' skips equals), so position N — the
    appended +inf suffix slot, a fixed point of nxt — absorbs every
    trajectory. Jump tables J_p = nxt^(2^p) then materialize all post_cap
    positions in ceil(log2(post_cap)) gather passes: the second half of the
    filled prefix is J_p applied to the first half. Work is
    O((N + post_cap) log post_cap) fully parallel gathers — vs the loop's
    O(posts) sequential searchsorted steps, which on a latency-bound
    backend (the TPU, especially through the tunnel) dominate the star
    engine's critical path.

    Horizon clipping happens AFTER the orbit: fires increase strictly, so
    where(raw <= end, raw, inf) is densely packed exactly like the loop's
    incremental buffer. The truncation flag mirrors the loop's: post_cap
    in-horizon fires AND one more would still fit."""
    Kp = cfg.post_cap
    end = cfg.end_time
    N = t_sorted.shape[0]

    nxt = jnp.searchsorted(t_sorted, suffix, side="right").astype(jnp.int32)
    p1 = jnp.searchsorted(
        t_sorted, jnp.asarray(cfg.start_time, t_sorted.dtype), side="right"
    ).astype(jnp.int32)
    pos = jnp.full((Kp,), N, jnp.int32).at[0].set(p1)
    jump = nxt
    filled = 1
    while filled < Kp:  # static unroll: ceil(log2(Kp)) levels
        take = min(filled, Kp - filled)
        pos = pos.at[filled:filled + take].set(jump[pos[:take]])
        filled += take
        if filled < Kp:
            jump = jump[jump]
    raw = suffix[pos]
    own = jnp.where(raw <= end, raw, jnp.inf)
    f_next = suffix[nxt[pos[Kp - 1]]]
    truncated = jnp.isfinite(own[Kp - 1]) & (f_next <= end)
    return own, truncated


def _feed_metrics_star(cfg: StarConfig, feed_times, own_times, K: int):
    """Per-feed rank integrals in closed form — no sequential pass at all.

    The merge-scan twin (``_feed_metrics_star_scan``, kept as the test
    oracle) walks E+K events per feed; on TPU that is a length-(E+K)
    sequential dependency vmapped over feeds. But with one broadcaster the
    rank process decomposes per event (reference ``utils.py`` integrals,
    SURVEY.md section 2 items 11-14):

    - each wall event w raises the rank by 1 until the next own post (or the
      horizon), so  int r dt   = sum_e  (b_e - w_e)^+  and, numbering walls
      1..m within their inter-own-post window,
      int r^2 dt = sum_e (2 i_e - 1)(b_e - w_e)^+   (telescoping i^2),
      where b_e = min(first own post > w_e, T);
    - the rank is 0 from each own post (and from the start) until the first
      wall event >= it, clipped at the next own post and T.

    Everything is searchsorted + gathers over already-sorted arrays —
    embarrassingly parallel over events AND feeds, which is exactly what the
    VPU wants. Generalizing to K > 1: rank >= K holds exactly from each
    window's K-th wall event to the window end, so

        time_below_K = (end - start) - sum_{e: i_e == K} (b_e - max(w_e, s))^+

    — the top-K integral needs ONLY the wall-side arrays (i_e, b_e, dt)
    already built for the rank integrals. An earlier formulation walked the
    own-post windows with [post_cap+1] searchsorted/gather intermediates per
    feed; it was 72% of star-engine runtime on the 100k-feed config and is
    gone (the merge-scan twin still pins both numbers).

    Tie rule (matches the oracle's argmin-lowest-index pop): an own post at
    exactly a wall-event time applies FIRST, so the wall event counts into
    the window STARTED by that own post.

    Memory: feeds are processed in ``lax.map`` blocks of
    ``_METRIC_FEED_BLOCK`` to bound the [feed_block, E] intermediates at
    100k-feed scale."""
    Fl, E = feed_times.shape
    dtype = feed_times.dtype
    start = jnp.asarray(cfg.start_time, dtype)
    end = jnp.asarray(cfg.end_time, dtype)
    inf = jnp.asarray(jnp.inf, dtype)
    own_ext = jnp.concatenate([own_times, inf[None]])          # [Kp+1]
    # Window-start array for wall COUNTING: it must include pre-start walls
    # (the carried-rank convention: events before the window still build
    # rank history), so window 0 counts from -inf, not from start_time.
    own_cnt = jnp.concatenate([-inf[None], own_times])         # [Kp+1]

    def one_feed(w_row):
        # --- wall-event side: all three integrals -----------------------
        nxt_idx = jnp.searchsorted(own_times, w_row, side="right")
        b = jnp.minimum(own_ext[nxt_idx], end)                 # window end
        a = own_cnt[nxt_idx]                                   # window start
        walls_before = jnp.searchsorted(w_row, a, side="left")
        i_e = jnp.arange(E) - walls_before + 1                 # 1-based in-window
        # Left-clipping at start_time keeps the telescoped sum exact: wall i
        # contributes (i^2 - (i-1)^2) * (b - max(w_i, start))^+ .
        dt = jnp.maximum(b - jnp.maximum(w_row, start), 0.0)
        ir = dt.sum()
        ir2 = ((2.0 * i_e.astype(dtype) - 1.0) * dt).sum()
        # Padded wall slots (+inf) get dt = 0, so they drop out of every
        # sum including the top-K complement below.
        topk = (end - start) - jnp.where(i_e == K, dt, 0.0).sum()
        return topk, ir, ir2

    if Fl <= _METRIC_FEED_BLOCK:
        top, ir, ir2 = jax.vmap(one_feed)(feed_times)
    else:
        nb = -(-Fl // _METRIC_FEED_BLOCK)
        padded = jnp.concatenate([
            feed_times,
            jnp.full((nb * _METRIC_FEED_BLOCK - Fl, E), jnp.inf, dtype),
        ]) if nb * _METRIC_FEED_BLOCK != Fl else feed_times
        blocks = padded.reshape(nb, _METRIC_FEED_BLOCK, E)
        top, ir, ir2 = lax.map(
            lambda b: jax.vmap(one_feed)(b), blocks
        )
        top = top.reshape(-1)[:Fl]
        ir = ir.reshape(-1)[:Fl]
        ir2 = ir2.reshape(-1)[:Fl]
    return FeedMetrics(
        time_in_top_k=top, int_rank=ir, int_rank2=ir2,
        follows=jnp.ones((Fl,), bool), start_time=start, end_time=end,
    )


# Feeds per metrics block: bounds the closed form's peak memory at
# block x E (E = merged wall slots per feed) floats per wall-side
# intermediate while keeping blocks wide enough to saturate the vector
# units.
_METRIC_FEED_BLOCK = 8192


def _feed_metrics_star_scan(cfg: StarConfig, feed_times, own_times, K: int):
    """Sequential merge-scan twin of :func:`_feed_metrics_star` (the
    reference-shaped two-pointer walk). Kept as the property-test oracle for
    the closed form; not used in the hot path.

    Tie rule: an own post at exactly a wall-event time applies FIRST (the
    oracle's Manager pops the lowest source index — the controlled
    broadcaster is row 0)."""
    Fl, E = feed_times.shape
    Kp = own_times.shape[0]
    dtype = feed_times.dtype
    start = jnp.asarray(cfg.start_time, dtype)
    end = jnp.asarray(cfg.end_time, dtype)
    own_ext = jnp.concatenate([own_times, jnp.full((1,), jnp.inf, dtype)])

    def one_feed(times_row):
        row_ext = jnp.concatenate([times_row, jnp.full((1,), jnp.inf, dtype)])

        def step(carry, _):
            i, j, r, t_prev, top, ir, ir2 = carry
            t_w, t_o = row_ext[i], own_ext[j]
            own_first = t_o <= t_w
            t = jnp.minimum(t_w, t_o)
            valid = jnp.isfinite(t)
            t_clip = jnp.clip(jnp.where(valid, t, t_prev), start, end)
            dt = jnp.maximum(t_clip - t_prev, 0)
            rf = r.astype(dtype)
            top2 = top + dt * (r < K)
            ir_2 = ir + dt * rf
            ir2_2 = ir2 + dt * rf * rf
            r_new = jnp.where(own_first, 0, r + 1)
            return (
                jnp.where(valid & ~own_first, i + 1, i),
                jnp.where(valid & own_first, j + 1, j),
                jnp.where(valid, r_new, r),
                jnp.maximum(t_prev, t_clip),
                top2, ir_2, ir2_2,
            ), None

        zero = jnp.asarray(0.0, dtype)
        init = (jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
                jnp.zeros((), jnp.int32), start, zero, zero, zero)
        (i, j, r, t_prev, top, ir, ir2), _ = lax.scan(
            step, init, None, length=E + Kp
        )
        dt = jnp.maximum(end - t_prev, 0)
        rf = r.astype(dtype)
        return top + dt * (r < K), ir + dt * rf, ir2 + dt * rf * rf

    top, ir, ir2 = jax.vmap(one_feed)(feed_times)
    return FeedMetrics(
        time_in_top_k=top, int_rank=ir, int_rank2=ir2,
        follows=jnp.ones((Fl,), bool), start_time=start, end_time=end,
    )


def _make_kernel(cfg: StarConfig, metric_K: int,
                 compress: bool = True, fire_mode: str = "auto"):
    codes, branches = _wall_branches(cfg)
    lookup = np.full(max(codes) + 2, 0, np.int32)  # +1 shift for _EMPTY
    for i, c in enumerate(codes):
        lookup[c + 1] = i
    lookup = jnp.asarray(lookup)

    def kernel(wall: WallParams, ctrl: CtrlParams, key):
        Fl, M = wall.kind.shape
        feed_offset = (
            lax.axis_index("feed") * Fl if comm.axis_present("feed") else 0
        )

        # 1) independent wall streams, vmapped over the [F_local, M] grid.
        key_wall = jr.fold_in(key, 101)
        key_tau = jr.fold_in(key, 202)
        key_own = jr.fold_in(key, 303)

        def one_slot(p_row, f_global, m):
            k = jr.fold_in(key_wall, f_global * M + m)
            return lax.switch(
                lookup[p_row.kind[m] + 1], branches, p_row, m, k
            )

        def one_feed(p_row, f_global):
            return jax.vmap(one_slot, (None, None, 0))(
                p_row, f_global, jnp.arange(M)
            )

        wall_nos = WallParams(  # drop s_sink for the per-feed rows
            kind=wall.kind, rate=wall.rate, l0=wall.l0, alpha=wall.alpha,
            beta=wall.beta, pw_times=wall.pw_times, pw_rates=wall.pw_rates,
            rd_times=wall.rd_times, s_sink=jnp.zeros((Fl,)),
        )
        per_feed_rows = jax.tree.map(
            lambda x: x if x.ndim > 1 else x[:, None], wall_nos
        )
        st = jax.vmap(one_feed)(per_feed_rows, feed_offset + jnp.arange(Fl))
        # [F_local, M, cap] -> per-feed merged ascending [F_local, M*cap]
        feed_times = jnp.sort(st.times.reshape(Fl, -1), axis=-1)
        wall_n = st.n.sum(axis=-1)
        wall_trunc = comm.pany(st.truncated.any(), "feed")

        # 2) controlled broadcaster posting times.
        if cfg.ctrl_kind == KIND_OPT:
            rate_f = jnp.sqrt(wall.s_sink / jnp.maximum(ctrl.q, 1e-30))
            own, post_trunc, rec_trunc = _opt_fires(
                cfg, feed_times, rate_f.astype(feed_times.dtype),
                key_tau, feed_offset, compress=compress,
                fire_mode=fire_mode,
            )
        else:
            s = _ctrl_stream(cfg, ctrl, key_own)
            own, post_trunc = s.times, s.truncated
            rec_trunc = jnp.zeros((), bool)
        n_posts = jnp.isfinite(own).sum()

        # 3) per-feed metrics + flags.
        metrics = _feed_metrics_star(cfg, feed_times, own, metric_K)
        return (own, n_posts, feed_times, wall_n, metrics, wall_trunc,
                post_trunc, rec_trunc)

    return kernel


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------


_FN_CACHE: dict = {}


def _resolve_fire_mode(fire_mode: str, feed_sharded: bool) -> str:
    """Resolve 'auto' to the concrete mode BEFORE any kernel cache is
    keyed: the choice depends on jax.default_backend(), so caching under
    the literal 'auto' would reuse a kernel whose loop-vs-doubling
    decision was made for a different backend after a mid-process platform
    flip (results stay bit-identical either way; only the measured
    performance policy would silently be the wrong one)."""
    if fire_mode != "auto":
        return fire_mode
    return ("loop" if feed_sharded or jax.default_backend() == "cpu"
            else "doubling")


def _get_fn(cfg: StarConfig, metric_K: int, mesh: Optional[Mesh], axis: str,
            wall: WallParams, ctrl: CtrlParams, compress: bool = True,
            fire_mode: str = "auto"):
    """Jitted-kernel cache keyed on everything that forces a retrace
    (StarConfig is hashable for exactly this — the sim.py convention)."""
    fire_mode = _resolve_fire_mode(fire_mode, feed_sharded=mesh is not None)
    cache_key = (cfg, metric_K, mesh, axis, compress, fire_mode,
                 jax.tree.structure((wall, ctrl)))
    fn = _FN_CACHE.get(cache_key)
    if fn is not None:
        return fn
    kernel = _make_kernel(cfg, metric_K, compress, fire_mode)
    if mesh is None:
        fn = jax.jit(kernel)
    else:
        wall_spec = jax.tree.map(
            lambda x: P(axis, *([None] * (jnp.asarray(x).ndim - 1))), wall
        )
        ctrl_spec = jax.tree.map(lambda x: P(), ctrl)
        feedP = P(axis)
        metrics_spec = FeedMetrics(
            time_in_top_k=feedP, int_rank=feedP, int_rank2=feedP,
            follows=feedP, start_time=P(), end_time=P(),
        )
        out_specs = (P(), P(), P(axis, None), feedP, metrics_spec, P(), P(),
                     P())
        fn = jax.jit(jax.shard_map(
            kernel, mesh=mesh, in_specs=(wall_spec, ctrl_spec, P()),
            out_specs=out_specs, check_vma=False,
        ))
    _FN_CACHE[cache_key] = fn
    return fn


def _check_wall_kinds(cfg: StarConfig, wall: WallParams):
    """A wall slot whose kind is outside the compiled branch set would be
    silently mis-dispatched by the lookup gather; reject host-side
    (wall.kind is concrete here — same guard as sim._check_kinds)."""
    codes, _ = _wall_branches(cfg)
    got = set(int(k) for k in np.unique(np.asarray(wall.kind)))
    if not got.issubset(codes):
        raise ValueError(
            f"wall slots contain kinds {sorted(got - set(codes))} not in the "
            f"config's wall_kinds {codes} — build wall params and config "
            f"from the same StarBuilder"
        )


# Configs whose candidate statistics overflowed the record budget once are
# remembered for the process lifetime and skip straight to the uncompressed
# path — the retry is then a one-time cost, not a per-call tax (config-2's
# short-clock shape measured 40% slower when every call re-tried).
_COMPRESS_BLOCKLIST: set = set()


def _regime_key(ctrl: CtrlParams, wall: WallParams):
    """Coarse clock-regime signature for the compression blocklist: the
    record-count regime is set by rate_f = sqrt(s_sink/q), so a sweep
    reusing one StarConfig must not let one short-clock (q, s_sink) point
    disable compression for every other point (3-significant-figure bucket
    of the mean clock rate — q alone misses the s_sink half of the rate)."""
    q = np.asarray(ctrl.q)
    s = np.asarray(wall.s_sink)
    if q.size == 0 or s.size == 0:
        return None
    m = float(np.sqrt(s.mean() / max(q.mean(), 1e-30)))
    return float(f"{m:.3g}") if np.isfinite(m) else None


def _run_with_fallback(cfg: StarConfig, metric_K: int, ctrl: CtrlParams,
                       wall: WallParams, run):
    """Run the star kernel compressed-first with the uncompressed fallback
    (shared by simulate_star and simulate_star_batch so the retry semantics
    cannot drift). ``run(compress) -> kernel out tuple``; overflow checks
    happen here, rec-first (see _check_overflow)."""
    key = (cfg, metric_K, _regime_key(ctrl, wall))
    if key not in _COMPRESS_BLOCKLIST:
        try:
            out = run(True)
            jax.block_until_ready(out[0])
            _check_overflow(cfg, out[5], out[6], out[7])
            return out
        except RecordBudgetOverflow:
            _COMPRESS_BLOCKLIST.add(key)
    out = run(False)
    jax.block_until_ready(out[0])
    _check_overflow(cfg, out[5], out[6])
    return out


class RecordBudgetOverflow(RuntimeError):
    """The compressed fire path's per-feed suffix-record budget overflowed
    (short-clock regime; see _rec_cap). simulate_star/_batch catch this and
    retry with compression disabled — results stay exact either way."""


# module-level so repeated overflow checks hit jit's warm cache
_sum_i32 = jax.jit(lambda a: jnp.sum(a.astype(jnp.int32)))


def _host_int_sum(x) -> int:
    """Total of ``x`` as a host int, valid when ``x`` is sharded across
    PROCESSES (multihost batch runs): reduce on-device to a replicated
    scalar first — a fully-replicated value is readable everywhere."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        return int(_sum_i32(x))
    return int(np.asarray(x).sum())


def _materialize(x):
    """Result materialization policy: NumPy when the array is locally
    materializable (single-process — today's behavior, unchanged); the
    global ``jax.Array`` when it spans processes, where a host copy is
    impossible per-process — gather explicitly with
    ``parallel.multihost.gather_global`` if the whole array is wanted."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        if x.is_fully_replicated:
            return np.asarray(x)  # every process holds the whole value
        return x
    return np.asarray(x)


def _check_overflow(cfg: StarConfig, wall_trunc, post_trunc, rec_trunc=None):
    """Raise (never truncate silently) when any lane's buffers filled.
    rec_trunc is checked FIRST: a record-budget overflow corrupts the
    compressed path's last slot and can spuriously fill the post buffer, so
    post_trunc is only meaningful once rec_trunc is clear."""
    if rec_trunc is not None and _host_int_sum(rec_trunc):
        raise RecordBudgetOverflow(
            "suffix-record budget overflow (a feed produced more "
            "right-to-left candidate minima than bigf._rec_cap allows — "
            "the short-clock regime); retrying with compression off"
        )
    n_wall = _host_int_sum(wall_trunc)
    if n_wall:
        raise RuntimeError(
            f"wall stream overflow ({n_wall} lane(s) hit wall_cap="
            f"{cfg.wall_cap} before the horizon) — raise StarConfig.wall_cap "
            f"(refusing to truncate silently)"
        )
    n_post = _host_int_sum(post_trunc)
    if n_post:
        raise RuntimeError(
            f"posting buffer overflow ({n_post} lane(s) hit post_cap="
            f"{cfg.post_cap} before the horizon) — raise StarConfig.post_cap "
            f"(refusing to truncate silently)"
        )


_FIRE_MODES = ("auto", "loop", "doubling")


def _check_fire_mode(fire_mode: str, feed_sharded: bool):
    """Early public-API validation: non-Opt control policies never reach
    _opt_fires, so without this a typo'd mode (or doubling on a sharded
    feed axis) would be silently ignored on those configs."""
    if fire_mode not in _FIRE_MODES:
        raise ValueError(
            f"unknown fire_mode {fire_mode!r} (choose from {_FIRE_MODES})"
        )
    if fire_mode == "doubling" and feed_sharded:
        raise ValueError(
            "fire_mode='doubling' needs the full sorted record arrays on "
            "every device; it does not support a sharded feed axis "
            "(use 'loop'/'auto')"
        )


def simulate_star(cfg: StarConfig, wall: WallParams, ctrl: CtrlParams,
                  seed, mesh: Optional[Mesh] = None, axis: str = "feed",
                  metric_K: int = 1, fire_mode: str = "auto") -> StarResult:
    """Simulate one star component to its horizon.

    With ``mesh``, the feed axis shards over ``mesh.shape[axis]`` devices
    (F must divide evenly); results are bit-identical to the unsharded run
    at matched seeds (PRNG streams key off GLOBAL feed indices). Raises on
    wall-buffer or post-buffer overflow instead of truncating.

    ``fire_mode``: how the Opt posting trajectory is extracted —
    ``"loop"`` (sequential while_loop), ``"doubling"`` (parallel pointer
    doubling; unsharded only), or ``"auto"`` (doubling on accelerators,
    loop on CPU/sharded — see _opt_fires for the measured tradeoff)."""
    key = jr.PRNGKey(seed) if isinstance(seed, (int, np.integer)) else seed
    _check_fire_mode(fire_mode, feed_sharded=mesh is not None)
    _check_wall_kinds(cfg, wall)
    if mesh is not None and axis != "feed":
        # The kernel's collectives (pmin/pany and the global-feed-index PRNG
        # offset) are bound to the axis NAME "feed"; any other name would
        # silently skip the reduction and corrupt results.
        raise ValueError(f"the follower mesh axis must be named 'feed', got "
                         f"{axis!r}")

    def run(compress):
        if mesh is None:
            return _get_fn(cfg, metric_K, None, axis, wall, ctrl,
                           compress, fire_mode)(wall, ctrl, key)
        n_dev = mesh.shape[axis]
        if cfg.n_feeds % n_dev != 0:
            raise ValueError(
                f"n_feeds={cfg.n_feeds} not divisible by mesh axis "
                f"{axis}={n_dev}"
            )
        fn = _get_fn(cfg, metric_K, mesh, axis, wall, ctrl, compress,
                     fire_mode)
        with mesh:
            return fn(comm.shard_leading(wall, mesh, axis),
                      comm.replicate(ctrl, mesh), comm.replicate(key, mesh))

    (own, n_posts, feed_times, wall_n, metrics, *_flags) = \
        _run_with_fallback(cfg, metric_K, ctrl, wall, run)
    # own/n_posts are replicated (readable on every process); the per-feed
    # arrays stay global jax.Arrays when the feed axis spans processes
    return StarResult(
        own_times=_materialize(own), n_posts=int(n_posts),
        wall_times=_materialize(feed_times), wall_n=_materialize(wall_n),
        metrics=metrics, cfg=cfg,
    )


class StarBatchResult(NamedTuple):
    """Result of a batched star run: leaves carry a leading [B] axis
    (``metrics`` is a FeedMetrics of [B, F] arrays). Host NumPy in
    single-process runs; in a multihost run batch-sharded fields stay
    global ``jax.Array``s (gather with
    ``parallel.multihost.gather_global``)."""

    own_times: "np.ndarray | jax.Array"   # [B, post_cap]
    n_posts: "np.ndarray | jax.Array"     # [B]
    wall_n: "np.ndarray | jax.Array"      # [B, F]
    metrics: FeedMetrics
    cfg: StarConfig


def stack_star(wall_list: Sequence[WallParams],
               ctrl_list: Sequence[CtrlParams]):
    """Stack same-shape star components along a leading batch axis (the
    sweep/bipartite axis — one lane per broadcaster of the reference's
    10k x 100k graph, SURVEY.md section 3.5). Parameters may differ freely
    across lanes; shapes and the controlled-policy kind may not."""
    wall = jax.tree.map(lambda *xs: jnp.stack(xs), *wall_list)
    ctrl = jax.tree.map(lambda *xs: jnp.stack(xs), *ctrl_list)
    return wall, ctrl


def broadcast_star(wall: WallParams, ctrl: CtrlParams, B: int):
    """Tile ONE component to a [B]-lane batch without materializing copies
    host-side (lanes differ only by seed)."""
    return (
        jax.tree.map(lambda x: jnp.broadcast_to(x, (B,) + x.shape), wall),
        jax.tree.map(
            lambda x: jnp.broadcast_to(jnp.asarray(x), (B,) + jnp.asarray(x).shape),
            ctrl,
        ),
    )


_BATCH_FN_CACHE: dict = {}


def _batch_specs(wall: WallParams, ctrl: CtrlParams, dp: str, fp):
    """(in_specs, out_specs) for shard_map over a [B]-batched star kernel:
    batch dim over ``dp``; the per-feed dim (axis 1 of wall leaves) over
    ``fp`` when given."""
    def wall_spec(x):
        rest = [None] * (jnp.asarray(x).ndim - 2)
        return P(dp, fp, *rest)

    def lead_spec(x):
        rest = [None] * (jnp.asarray(x).ndim - 1)
        return P(dp, *rest)

    in_specs = (
        jax.tree.map(wall_spec, wall),
        jax.tree.map(lead_spec, ctrl),
        P(dp, None),                      # keys [B, 2]
    )
    feedP = P(dp, fp)
    metrics_spec = FeedMetrics(
        time_in_top_k=feedP, int_rank=feedP, int_rank2=feedP,
        follows=feedP,
        start_time=P(dp), end_time=P(dp),  # vmapped scalars -> [B]
    )
    out_specs = (
        P(dp, None),     # own_times [B, post_cap] (replicated over feed)
        P(dp),           # n_posts [B]
        P(dp, fp, None),  # feed_times [B, F, E]
        P(dp, fp),       # wall_n [B, F]
        metrics_spec,
        P(dp),           # wall_trunc [B] (pany over feed inside the kernel)
        P(dp),           # post_trunc [B]
        P(dp),           # rec_trunc [B]
    )
    return in_specs, out_specs


def simulate_star_batch(cfg: StarConfig, wall: WallParams, ctrl: CtrlParams,
                        seeds, mesh: Optional[Mesh] = None,
                        axis: str = "data", feed_axis: Optional[str] = None,
                        metric_K: int = 1,
                        fire_mode: str = "auto") -> StarBatchResult:
    """Run B star components in lockstep — the loop-free engine for the
    bipartite sweep (BASELINE configs 1/3 and the headline 10k x 100k
    graph): every lane is one broadcaster vs its follower feeds, the whole
    batch is one ``vmap`` of the stream/suffix-min kernel, and with ``mesh``
    the batch shards over the ``data`` axis by input placement (the
    redqueen_tpu.parallel.shard convention — no kernel changes, so sharded
    and unsharded runs are bit-identical at matched seeds).

    ``wall``/``ctrl`` leaves carry a leading [B] dim (see :func:`stack_star`
    / :func:`broadcast_star`); ``seeds`` is an int array [B] or key array
    [B, 2]. Raises on any lane's buffer overflow, never truncates silently.

    With ``feed_axis`` as well, the mesh is 2-D — components over ``axis``
    (dp) x followers-within-a-component over ``feed_axis`` (the sequence-
    parallel analogue): the kernel runs under ``shard_map`` with the
    RedQueen clock reduction riding ``pmin`` over the feed axis, and per-
    source PRNG streams keyed off GLOBAL feed indices, so every mesh layout
    (1x8, 2x4, 8x1, unsharded) is bit-identical at matched seeds.
    """
    seeds = jnp.asarray(seeds)
    keys = jax.vmap(jr.PRNGKey)(seeds) if seeds.ndim == 1 else seeds
    B = keys.shape[0]
    if wall.kind.shape[0] != B:
        raise ValueError(
            f"batch dims disagree: seeds={B}, wall={wall.kind.shape[0]}"
        )
    ctrl_q = jnp.asarray(ctrl.q)
    if ctrl_q.ndim != 1 or ctrl_q.shape[0] != B:
        # A stack_star/broadcast_star mismatch would otherwise surface as an
        # opaque vmap shape error deep in the kernel.
        raise ValueError(
            f"batch dims disagree: seeds={B}, ctrl="
            f"{ctrl_q.shape[0] if ctrl_q.ndim else 'unbatched'} — build the "
            f"batch with stack_star/broadcast_star"
        )
    _check_fire_mode(fire_mode,
                     feed_sharded=mesh is not None and feed_axis is not None)
    fire_mode = _resolve_fire_mode(
        fire_mode, feed_sharded=mesh is not None and feed_axis is not None)
    _check_wall_kinds(cfg, wall)
    if feed_axis is not None and feed_axis != "feed":
        raise ValueError(f"the follower mesh axis must be named 'feed', got "
                         f"{feed_axis!r} (kernel collectives bind to the "
                         f"name)")

    def get_fn(compress):
        cache_key = (cfg, metric_K, mesh, axis, feed_axis, compress,
                     fire_mode, jax.tree.structure((wall, ctrl)))
        fn = _BATCH_FN_CACHE.get(cache_key)
        if fn is None:
            vk = jax.vmap(_make_kernel(cfg, metric_K, compress, fire_mode))
            if mesh is not None and feed_axis is not None:
                in_specs, out_specs = _batch_specs(wall, ctrl, axis, feed_axis)
                vk = jax.shard_map(vk, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_vma=False)
            fn = jax.jit(vk)
            _BATCH_FN_CACHE[cache_key] = fn
        return fn

    def run(compress):
        fn = get_fn(compress)
        if mesh is None:
            return fn(wall, ctrl, keys)
        n_dev = mesh.shape[axis]
        if B % n_dev != 0:
            raise ValueError(
                f"batch {B} not divisible by mesh axis {axis}={n_dev}"
            )
        if feed_axis is not None:
            n_feed = mesh.shape[feed_axis]
            if cfg.n_feeds % n_feed != 0:
                raise ValueError(
                    f"n_feeds={cfg.n_feeds} not divisible by mesh axis "
                    f"{feed_axis}={n_feed}"
                )
            with mesh:
                return fn(wall, ctrl, keys)
        with mesh:
            return fn(comm.shard_leading(wall, mesh, axis),
                      comm.shard_leading(ctrl, mesh, axis),
                      comm.shard_leading(keys, mesh, axis))

    (own, n_posts, _feed_times, wall_n, metrics, *_flags) = \
        _run_with_fallback(cfg, metric_K, ctrl, wall, run)
    return StarBatchResult(
        own_times=_materialize(own), n_posts=_materialize(n_posts),
        wall_n=_materialize(wall_n), metrics=metrics, cfg=cfg,
    )


class StarBuilder:
    """Front end assembling a star component (the big-F counterpart of
    config.GraphBuilder / the reference's ``SimOpts``). One wall slot list
    per feed; exactly one controlled broadcaster."""

    def __init__(self, n_feeds: int, end_time: float, start_time: float = 0.0,
                 s_sink: Optional[Sequence[float]] = None):
        self.n_feeds = int(n_feeds)
        self.end_time = float(end_time)
        self.start_time = float(start_time)
        self.s_sink = (
            np.ones(n_feeds) if s_sink is None
            else np.asarray(s_sink, np.float64)
        )
        if self.s_sink.shape != (self.n_feeds,):
            raise ValueError(
                f"s_sink must have shape ({self.n_feeds},), got "
                f"{self.s_sink.shape}"
            )
        self._walls = [[] for _ in range(self.n_feeds)]
        self._ctrl = None

    # ---- wall sources (one feed each) ----

    def wall_poisson(self, feed: int, rate: float):
        self._walls[feed].append(dict(kind=KIND_POISSON, rate=float(rate)))
        return self

    def wall_hawkes(self, feed: int, l0: float, alpha: float, beta: float):
        self._walls[feed].append(
            dict(kind=KIND_HAWKES, l0=float(l0), alpha=float(alpha),
                 beta=float(beta))
        )
        return self

    def wall_piecewise(self, feed: int, change_times, rates):
        self._walls[feed].append(
            dict(kind=KIND_PIECEWISE, pw=check_piecewise(change_times, rates))
        )
        return self

    def wall_replay(self, feed: int, times):
        t = np.sort(np.asarray(times, np.float64))
        self._walls[feed].append(dict(kind=KIND_REALDATA, rd=t))
        return self

    # ---- controlled broadcaster (reference: the manager factories) ----

    def ctrl_opt(self, q: float = 1.0):
        if not q > 0:
            raise ValueError(f"Opt requires q > 0, got q={q}")
        self._ctrl = dict(kind=KIND_OPT, q=float(q))
        return self

    def ctrl_poisson(self, rate: float):
        self._ctrl = dict(kind=KIND_POISSON, rate=float(rate))
        return self

    def ctrl_hawkes(self, l0: float, alpha: float, beta: float):
        """Hawkes posting as the CONTROLLED broadcaster (the reference's
        vs-Hawkes comparison at big F) — legal because Hawkes depends only on
        its own history. Stationary iff alpha < beta (expected posts
        ~ l0*T/(1 - alpha/beta))."""
        if not (l0 >= 0 and alpha >= 0 and beta > 0):
            raise ValueError(
                f"Hawkes requires l0 >= 0, alpha >= 0, beta > 0; got "
                f"l0={l0}, alpha={alpha}, beta={beta}"
            )
        self._ctrl = dict(
            kind=KIND_HAWKES, l0=float(l0), alpha=float(alpha),
            beta=float(beta),
        )
        return self

    def ctrl_piecewise(self, change_times, rates):
        self._ctrl = dict(
            kind=KIND_PIECEWISE, pw=check_piecewise(change_times, rates)
        )
        return self

    def ctrl_replay(self, times):
        self._ctrl = dict(
            kind=KIND_REALDATA, rd=np.sort(np.asarray(times, np.float64))
        )
        return self

    def ctrl_rmtpp(self, weights, hidden: int = 16):
        self._ctrl = dict(kind=KIND_RMTPP, rmtpp=weights, hidden=int(hidden))
        return self

    # ---- assembly ----

    def build(self, wall_cap: int = 256, post_cap: int = 1024,
              dtype=jnp.float32):
        if self._ctrl is None:
            raise ValueError("no controlled broadcaster set (ctrl_* methods)")
        F = self.n_feeds
        M = max((len(w) for w in self._walls), default=0)
        M = max(M, 1)
        Kp = max(
            [len(w["pw"][0]) for row in self._walls for w in row
             if "pw" in w] + (
                [len(self._ctrl["pw"][0])] if "pw" in self._ctrl else []
            ),
            default=1,
        )
        Kr = max(
            [len(w["rd"]) for row in self._walls for w in row if "rd" in w],
            default=1,
        )
        kind = np.full((F, M), _EMPTY, np.int32)
        rate = np.ones((F, M)); l0 = np.ones((F, M))
        alpha = np.zeros((F, M)); beta = np.ones((F, M))
        pw_t = np.full((F, M, Kp), np.inf); pw_t[:, :, 0] = 0.0
        pw_r = np.zeros((F, M, Kp))
        rd_t = np.full((F, M, Kr), np.inf)
        kinds_present = set()
        for f, row in enumerate(self._walls):
            for m, w in enumerate(row):
                kind[f, m] = w["kind"]
                kinds_present.add(int(w["kind"]))
                if w["kind"] == KIND_POISSON:
                    rate[f, m] = w["rate"]
                elif w["kind"] == KIND_HAWKES:
                    l0[f, m] = w["l0"]; alpha[f, m] = w["alpha"]
                    beta[f, m] = w["beta"]
                elif w["kind"] == KIND_PIECEWISE:
                    ct, r = w["pw"]
                    pw_t[f, m] = np.inf
                    pw_t[f, m, : len(ct)] = ct
                    pw_r[f, m, : len(r)] = r
                elif w["kind"] == KIND_REALDATA:
                    rd_t[f, m, : len(w["rd"])] = w["rd"]
        kinds_present.add(_EMPTY)

        c = self._ctrl
        c_pw_t = np.full(Kp, np.inf); c_pw_t[0] = 0.0
        c_pw_r = np.zeros(Kp)
        if "pw" in c:
            ct, r = c["pw"]
            c_pw_t[:] = np.inf
            c_pw_t[: len(ct)] = ct
            c_pw_r[: len(r)] = r
        c_rd = (
            np.asarray(c["rd"], np.float64) if "rd" in c
            else np.full(1, np.inf)
        )
        cfg = StarConfig(
            n_feeds=F, walls_per_feed=M, end_time=self.end_time,
            start_time=self.start_time, wall_cap=int(wall_cap),
            post_cap=int(post_cap), ctrl_kind=int(c["kind"]),
            rmtpp_hidden=int(c.get("hidden", 1)),
            wall_kinds=tuple(sorted(kinds_present)),
        )
        wall = WallParams(
            kind=jnp.asarray(kind),
            rate=jnp.asarray(rate, dtype), l0=jnp.asarray(l0, dtype),
            alpha=jnp.asarray(alpha, dtype), beta=jnp.asarray(beta, dtype),
            pw_times=jnp.asarray(pw_t, dtype),
            pw_rates=jnp.asarray(pw_r, dtype),
            rd_times=jnp.asarray(rd_t, dtype),
            s_sink=jnp.asarray(self.s_sink, dtype),
        )
        ctrl = CtrlParams(
            q=jnp.asarray(c.get("q", 1.0), dtype),
            rate=jnp.asarray(c.get("rate", 1.0), dtype),
            pw_times=jnp.asarray(c_pw_t, dtype),
            pw_rates=jnp.asarray(c_pw_r, dtype),
            rd_times=jnp.asarray(c_rd, dtype),
            l0=jnp.asarray(c.get("l0", 0.0), dtype),
            alpha=jnp.asarray(c.get("alpha", 0.0), dtype),
            beta=jnp.asarray(c.get("beta", 1.0), dtype),
            rmtpp=c.get("rmtpp"),
        )
        return cfg, wall, ctrl


def star_to_dataframe(res: StarResult, src_id=0, wall_src_offset: int = 100):
    """Export a star run as the reference-schema event DataFrame (one row per
    (event, sink); columns event_id/t/time_delta/src_id/sink_id) so the
    backend-agnostic pandas metric layer applies unchanged — intended for
    small-F validation, not 100k-feed exports.

    Wall source ids are ``wall_src_offset + feed``; own posts land in every
    feed. Tie order matches the oracle: own post first."""
    import pandas as pd

    F = res.cfg.n_feeds
    own = res.own_times[np.isfinite(res.own_times)]
    rows = []  # (t, order, src, sinks)
    for t in own:
        rows.append((float(t), 0, src_id, None))
    for f in range(F):
        for t in res.wall_times[f][: int(res.wall_n[f])]:
            rows.append((float(t), 1, wall_src_offset + f, f))
    rows.sort(key=lambda r: (r[0], r[1]))
    recs = []
    last = {}
    for eid, (t, _, src, sink) in enumerate(rows):
        delta = t - last.get(src, res.cfg.start_time)
        last[src] = t
        sinks = range(F) if sink is None else [sink]
        for sk in sinks:
            recs.append((eid, t, delta, src, sk))
    return pd.DataFrame(
        recs, columns=["event_id", "t", "time_delta", "src_id", "sink_id"]
    )
