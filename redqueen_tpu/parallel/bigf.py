"""Follower-sharded execution for single-broadcaster / huge-F components
(BASELINE configs 2 and 4: 1 broadcaster against 1k Hawkes / 100k replay
feeds) — the ``feed`` mesh axis of redqueen_tpu.parallel.comm.

Design (implemented incrementally; see simulate_bigf below for what is live):
the component's followers and their dedicated wall sources shard over the
``feed`` axis via ``shard_map``; each device scans its local feeds' wall
events independently, and the one cross-device coupling — the controlled
broadcaster's superposition clock, the min over all followers' candidate
clocks — rides ``pmin`` over the ICI mesh axis, exactly the "lax.psum for
the global rank-in-feed reduction" of the BASELINE north star.
"""

from __future__ import annotations

__all__ = ["simulate_bigf"]


def simulate_bigf(*args, **kwargs):
    raise NotImplementedError(
        "follower-sharded big-F kernel lands after the batch path; use "
        "parallel.shard.simulate_sharded (component-batch axis) or a "
        "single-device component meanwhile"
    )
