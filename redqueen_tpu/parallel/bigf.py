"""Follower-sharded simulation of star components: ONE controlled broadcaster
against a huge follower set (BASELINE configs 2 and 4: 1 broadcaster vs 1k
Hawkes feeds / 100k replay feeds) — the ``feed`` mesh axis of
redqueen_tpu.parallel.comm.

The batch kernel (ops.scan_core) replays the reference's global event loop
(reference ``Manager.run_till``, SURVEY.md section 3.1) one event per scan
step; at F = 100k followers that loop is hopeless (~F * rate * T sequential
steps). This module uses a TPU-first reformulation that deletes the loop
entirely, exact by construction:

1. Wall sources never react to anything (SURVEY.md section 2 items 4-7), so
   every feed's wall stream samples INDEPENDENTLY — ``vmap`` over feeds,
   sharded over the ``feed`` mesh axis (star_streams / ops.streams).
2. The RedQueen policy's superposition clocks (reference ``Opt``, paper
   Algorithm 1): each wall event m at time t_m in feed f spawns one clock
   c_m = t_m + Exp(sqrt(s_f / q)), alive until the broadcaster's next post.
   Because every clock satisfies c_m > t_m, the k-th own post is simply

       fire_{k+1} = min{ c_m : t_m > fire_k },

   a suffix-minimum query over candidates ordered by wall time. So: draw ONE
   exponential per wall event (exactly the reference's draw count), sort
   locally by t_m, take a reverse running min, and the whole posting
   trajectory is a tiny ``lax.scan`` of searchsorted lookups whose only
   cross-device traffic is a scalar ``pmin`` over the ICI mesh axis per own
   post — the BASELINE north star's "global rank-in-feed reduction"
   (star_fire).
3. Feed-rank metrics (reference ``utils.py``) come from a per-feed
   merge-scan of (wall events, own posts), again vmapped and sharded; means
   reduce with ``psum`` (star_metrics).

Controlled policies other than Opt (Poisson / PiecewiseConst / RealData
replay / RMTPP) depend only on their own history, so their posting stream
samples directly (ops.streams) and step 2 is skipped — this covers the
reference's ``create_manager_with_poisson / _with_times / _with_piecewise_
const`` factory surface at big F.

Overflow (wall buffers or post buffer) is detected and raised, never silent.

This module is the IMPORT SURFACE for the star engine; the implementation
lives in focused submodules (round-5 verdict item 7 split):

- ``star_types``    — StarConfig / param pytrees / results / overflow error
- ``star_streams``  — wall-slot branch table + controlled streams (step 1)
- ``star_fire``     — suffix-min Opt fires, loop + doubling modes (step 2)
- ``star_metrics``  — closed-form rank integrals + merge-scan twin (step 3)
- ``star_run``      — fused kernel, dispatch caches, simulate_star(_batch)
- ``star_builder``  — StarBuilder front end + DataFrame export

Every name (public and the ``_``-private internals the test suite pins) is
re-exported here unchanged, so ``from redqueen_tpu.parallel.bigf import X``
keeps working verbatim.
"""

from __future__ import annotations

# ruff: noqa: F401  — re-export surface
from .star_builder import StarBuilder, star_to_dataframe
from .star_fire import (
    _FIRE_MODES,
    _check_fire_mode,
    _fires_by_doubling,
    _opt_fires,
    _rec_cap,
    _resolve_fire_mode,
)
from .star_metrics import (
    _METRIC_FEED_BLOCK,
    _feed_metrics_star,
    _feed_metrics_star_scan,
)
from .star_run import (
    _BATCH_FN_CACHE,
    _COMPRESS_BLOCKLIST,
    _FN_CACHE,
    _batch_specs,
    _check_overflow,
    _get_fn,
    _host_int_sum,
    _make_kernel,
    _materialize,
    _regime_key,
    _run_with_fallback,
    broadcast_star,
    simulate_star,
    simulate_star_batch,
    stack_star,
)
from .star_streams import _check_wall_kinds, _ctrl_stream, _wall_branches
from .star_types import (
    _EMPTY,
    CtrlParams,
    RecordBudgetOverflow,
    StarBatchResult,
    StarConfig,
    StarResult,
    WallParams,
)

__all__ = [  # identical to the pre-split surface (API.md is the contract)
    "StarConfig",
    "WallParams",
    "CtrlParams",
    "StarBuilder",
    "StarResult",
    "StarBatchResult",
    "simulate_star",
    "simulate_star_batch",
    "stack_star",
    "broadcast_star",
    "star_to_dataframe",
]
