"""Collective/communication layer: the TPU-native equivalent of a
distributed backend (SURVEY.md section 5 "Distributed communication
backend"). The reference is single-process; here all cross-device talk goes
through this one module so kernel code stays mesh-shape-agnostic: on a
1-device mesh (or when the named axis is absent) every collective degrades
to a no-op, and the same code scales to an ICI mesh axis (devices in one
slice) with a DCN axis reserved for multi-slice scale-out.

Axis conventions:
- ``data``  — independent simulation components (broadcasters of the
  bipartite graph, sweep seeds/q points). Pure SPMD, no communication in
  the hot loop; metrics aggregate with ``psum``.
- ``feed``  — followers of ONE component (the 100k-follower configs). The
  RedQueen candidate-clock reduction rides ``pmin``/``psum`` over this axis
  (see redqueen_tpu.parallel.bigf).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh",
    "axis_present",
    "axis_size_or_1",
    "psum",
    "pmin",
    "pmax",
    "pany",
    "shard_leading",
    "shard_map",
    "replicate",
    "axis_total",
]


def make_mesh(axes: dict, devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from {axis_name: size}; sizes must multiply to the device
    count (use -1 once for 'all remaining'). ``make_mesh({'data': 8})``."""
    devices = jax.devices() if devices is None else list(devices)
    names = tuple(axes)
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(f"mesh {dict(zip(names, sizes))} != {len(devices)} devices")
    mesh_devices = mesh_utils.create_device_mesh(sizes, devices=devices)
    return Mesh(mesh_devices, names)


def shard_map(f, mesh, in_specs, out_specs, **kw):
    """``jax.shard_map`` across JAX pins: top-level where it exists,
    ``jax.experimental.shard_map`` otherwise (this pin), translating the
    replication-check kwarg across its rename (new ``check_vma`` <-> old
    ``check_rep``) so kernel code writes ONE spelling."""
    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm

        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
    else:
        if "check_rep" in kw:
            kw["check_vma"] = kw.pop("check_rep")
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def _axis_size(axis_name: str) -> int:
    """Size of ``axis_name`` where bound; raises ``NameError`` when the
    axis is unbound here.  ``lax.axis_size`` only exists in newer JAX
    (this pin raises ``AttributeError`` on the lookup), so fall back to
    ``psum(1, axis)`` — constant-folded to the axis size at trace time and
    raising the SAME unbound-axis ``NameError``, which keeps the
    no-op-outside-collectives contract identical across pins."""
    try:
        fn = lax.axis_size
    except AttributeError:
        return lax.psum(1, axis_name)
    return fn(axis_name)


def _in_collective(axis_name: str) -> bool:
    """True iff ``axis_name`` is a bound collective axis here (inside
    shard_map/vmap with that axis); collectives outside are no-ops."""
    try:
        _axis_size(axis_name)
        return True
    except NameError:
        return False


def axis_present(axis_name: str) -> bool:
    return _in_collective(axis_name)


def axis_size_or_1(axis_name: str) -> int:
    try:
        return _axis_size(axis_name)
    except NameError:
        return 1


def psum(x, axis_name: str = "data"):
    """Sum over the named mesh axis; identity when the axis is unbound or
    size 1 — kernel code never branches on mesh shape."""
    return lax.psum(x, axis_name) if _in_collective(axis_name) else x


def pmin(x, axis_name: str = "data"):
    return lax.pmin(x, axis_name) if _in_collective(axis_name) else x


def pmax(x, axis_name: str = "data"):
    return lax.pmax(x, axis_name) if _in_collective(axis_name) else x


def pany(x, axis_name: str = "data"):
    """Logical-or reduction across the axis (failure/overflow detection)."""
    if not _in_collective(axis_name):
        return x
    return lax.pmax(x.astype(jnp.int32), axis_name) > 0


def shard_leading(tree, mesh: Mesh, axis="data"):
    """Place every array in ``tree`` with its LEADING dim sharded over
    ``axis`` (rest replicated) — the component-batch layout. ``axis`` may be
    a tuple of mesh axis names to shard one dim over several axes at once —
    the multi-slice pattern (e.g. ``("dcn", "data")``: slices over the DCN
    axis x chips within a slice). Leading dims must divide the total axis
    size evenly."""
    def put(x):
        x = jnp.asarray(x)
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, tree)


def axis_total(mesh: Mesh, axis) -> int:
    """Device count behind ``axis`` — a name or tuple of names."""
    if isinstance(axis, str):
        return mesh.shape[axis]
    return int(np.prod([mesh.shape[a] for a in axis]))


def replicate(tree, mesh: Mesh):
    """Fully replicate ``tree`` over the mesh."""
    return jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x), NamedSharding(mesh, P())), tree
    )
