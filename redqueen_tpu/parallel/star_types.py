"""Shared types of the star (big-F) engine: static config, parameter
pytrees, result containers, and the overflow exception.

Split out of ``bigf.py`` (round-5 verdict item 7); the design rationale for
the engine itself lives in ``bigf.py``'s module docstring, which remains
the package's import surface for all of it.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..models.base import KIND_OPT
from ..utils.metrics import FeedMetrics

__all__ = [
    "StarConfig",
    "WallParams",
    "CtrlParams",
    "StarResult",
    "StarBatchResult",
    "RecordBudgetOverflow",
    "_EMPTY",
]

_EMPTY = -1  # wall-slot kind code for "no source in this slot"


@dataclasses.dataclass(frozen=True)
class StarConfig:
    """Static shape of a star component (hashable, jit-static)."""

    n_feeds: int
    walls_per_feed: int
    end_time: float
    start_time: float = 0.0
    wall_cap: int = 256    # events per wall source
    post_cap: int = 1024   # controlled-broadcaster posts
    ctrl_kind: int = KIND_OPT
    rmtpp_hidden: int = 1
    wall_kinds: tuple = ()  # kinds present in wall slots (branch pruning)


class WallParams(struct.PyTreeNode):
    """Wall-source parameters, [F, M] grids (feed-sharded leaves; slot kind
    ``_EMPTY`` marks unused slots)."""

    kind: jnp.ndarray       # i32[F, M]
    rate: jnp.ndarray       # f[F, M]
    l0: jnp.ndarray         # f[F, M]
    alpha: jnp.ndarray      # f[F, M]
    beta: jnp.ndarray       # f[F, M]
    pw_times: jnp.ndarray   # f[F, M, Kp]
    pw_rates: jnp.ndarray   # f[F, M, Kp]
    rd_times: jnp.ndarray   # f[F, M, Kr]
    s_sink: jnp.ndarray     # f[F] follower significance


class CtrlParams(struct.PyTreeNode):
    """Controlled-broadcaster parameters (replicated scalars/rows)."""

    q: jnp.ndarray          # f[] Opt posting cost
    rate: jnp.ndarray       # f[] Poisson rate
    pw_times: jnp.ndarray   # f[Kp] piecewise knots
    pw_rates: jnp.ndarray   # f[Kp]
    rd_times: jnp.ndarray   # f[Kr] replay timestamps
    l0: Optional[jnp.ndarray] = None     # f[] Hawkes base rate
    alpha: Optional[jnp.ndarray] = None  # f[] Hawkes jump
    beta: Optional[jnp.ndarray] = None   # f[] Hawkes decay
    rmtpp: Optional[dict] = None


class StarResult(NamedTuple):
    """Result of one star simulation.

    ``own_times`` [post_cap] ascending +inf-padded; ``wall_times`` [F, M*cap]
    per-feed merged ascending +inf-padded; ``wall_n`` [F] valid wall events
    per feed; ``metrics`` per-feed FeedMetrics over [start, T].

    Array fields are host NumPy in single-process runs. In a MULTIHOST run
    the feed-sharded fields (``wall_times``/``wall_n``/``metrics``) stay
    global ``jax.Array``s — no process can hold them whole — and
    ``parallel.multihost.gather_global`` materializes them everywhere;
    replicated fields (``own_times``, ``n_posts``) are NumPy/int as
    usual."""

    own_times: np.ndarray
    n_posts: int
    wall_times: "np.ndarray | jax.Array"
    wall_n: "np.ndarray | jax.Array"
    metrics: FeedMetrics
    cfg: StarConfig


class StarBatchResult(NamedTuple):
    """Result of a batched star run: leaves carry a leading [B] axis
    (``metrics`` is a FeedMetrics of [B, F] arrays). Host NumPy in
    single-process runs; in a multihost run batch-sharded fields stay
    global ``jax.Array``s (gather with
    ``parallel.multihost.gather_global``)."""

    own_times: "np.ndarray | jax.Array"   # [B, post_cap]
    n_posts: "np.ndarray | jax.Array"     # [B]
    wall_n: "np.ndarray | jax.Array"      # [B, F]
    metrics: FeedMetrics
    cfg: StarConfig


class RecordBudgetOverflow(RuntimeError):
    """The compressed fire path's per-feed suffix-record budget overflowed
    (short-clock regime; see star_fire._rec_cap). simulate_star/_batch catch
    this and retry with compression disabled — results stay exact either
    way."""
