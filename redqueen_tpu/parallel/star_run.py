"""Star-engine execution: the fused kernel (streams → fires → metrics),
jitted/shard_mapped dispatch caches, overflow handling with the
compressed→uncompressed retry, and the public ``simulate_star`` /
``simulate_star_batch`` entry points.

Split out of ``bigf.py`` (round-5 verdict item 7); ``bigf.py`` remains the
import surface and carries the engine's design docstring.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import random as jr
from jax.sharding import Mesh, PartitionSpec as P

from ..models.base import KIND_OPT
from ..utils.metrics import FeedMetrics
from . import comm
from .star_fire import _check_fire_mode, _opt_fires, _resolve_fire_mode
from .star_metrics import _feed_metrics_star
from .star_streams import _check_wall_kinds, _ctrl_stream, _wall_branches
from .star_types import (
    CtrlParams,
    RecordBudgetOverflow,
    StarBatchResult,
    StarConfig,
    StarResult,
    WallParams,
)

__all__ = [
    "simulate_star",
    "simulate_star_batch",
    "stack_star",
    "broadcast_star",
]


def _make_kernel(cfg: StarConfig, metric_K: int,
                 compress: bool = True, fire_mode: str = "auto"):
    codes, branches = _wall_branches(cfg)
    lookup = np.full(max(codes) + 2, 0, np.int32)  # +1 shift for _EMPTY
    for i, c in enumerate(codes):
        lookup[c + 1] = i
    lookup = jnp.asarray(lookup)

    def kernel(wall: WallParams, ctrl: CtrlParams, key):
        Fl, M = wall.kind.shape
        feed_offset = (
            lax.axis_index("feed") * Fl if comm.axis_present("feed") else 0
        )

        # 1) independent wall streams, vmapped over the [F_local, M] grid.
        key_wall = jr.fold_in(key, 101)
        key_tau = jr.fold_in(key, 202)
        key_own = jr.fold_in(key, 303)

        def one_slot(p_row, f_global, m):
            k = jr.fold_in(key_wall, f_global * M + m)
            return lax.switch(
                lookup[p_row.kind[m] + 1], branches, p_row, m, k
            )

        def one_feed(p_row, f_global):
            return jax.vmap(one_slot, (None, None, 0))(
                p_row, f_global, jnp.arange(M)
            )

        wall_nos = WallParams(  # drop s_sink for the per-feed rows
            kind=wall.kind, rate=wall.rate, l0=wall.l0, alpha=wall.alpha,
            beta=wall.beta, pw_times=wall.pw_times, pw_rates=wall.pw_rates,
            rd_times=wall.rd_times, s_sink=jnp.zeros((Fl,)),
        )
        per_feed_rows = jax.tree.map(
            lambda x: x if x.ndim > 1 else x[:, None], wall_nos
        )
        st = jax.vmap(one_feed)(per_feed_rows, feed_offset + jnp.arange(Fl))
        # [F_local, M, cap] -> per-feed merged ascending [F_local, M*cap]
        feed_times = jnp.sort(st.times.reshape(Fl, -1), axis=-1)
        wall_n = st.n.sum(axis=-1)
        wall_trunc = comm.pany(st.truncated.any(), "feed")

        # 2) controlled broadcaster posting times.
        if cfg.ctrl_kind == KIND_OPT:
            rate_f = jnp.sqrt(wall.s_sink / jnp.maximum(ctrl.q, 1e-30))
            own, post_trunc, rec_trunc = _opt_fires(
                cfg, feed_times, rate_f.astype(feed_times.dtype),
                key_tau, feed_offset, compress=compress,
                fire_mode=fire_mode,
            )
        else:
            s = _ctrl_stream(cfg, ctrl, key_own)
            own, post_trunc = s.times, s.truncated
            rec_trunc = jnp.zeros((), bool)
        n_posts = jnp.isfinite(own).sum()

        # 3) per-feed metrics + flags.
        metrics = _feed_metrics_star(cfg, feed_times, own, metric_K)
        return (own, n_posts, feed_times, wall_n, metrics, wall_trunc,
                post_trunc, rec_trunc)

    return kernel


# --------------------------------------------------------------------------
# dispatch caches + overflow machinery
# --------------------------------------------------------------------------

_FN_CACHE: dict = {}


def _get_fn(cfg: StarConfig, metric_K: int, mesh: Optional[Mesh], axis: str,
            wall: WallParams, ctrl: CtrlParams, compress: bool = True,
            fire_mode: str = "auto"):
    """Jitted-kernel cache keyed on everything that forces a retrace
    (StarConfig is hashable for exactly this — the sim.py convention)."""
    fire_mode = _resolve_fire_mode(fire_mode, feed_sharded=mesh is not None)
    cache_key = (cfg, metric_K, mesh, axis, compress, fire_mode,
                 jax.tree.structure((wall, ctrl)))
    fn = _FN_CACHE.get(cache_key)
    if fn is not None:
        return fn
    kernel = _make_kernel(cfg, metric_K, compress, fire_mode)
    if mesh is None:
        fn = jax.jit(kernel)
    else:
        wall_spec = jax.tree.map(
            lambda x: P(axis, *([None] * (jnp.asarray(x).ndim - 1))), wall
        )
        ctrl_spec = jax.tree.map(lambda x: P(), ctrl)
        feedP = P(axis)
        metrics_spec = FeedMetrics(
            time_in_top_k=feedP, int_rank=feedP, int_rank2=feedP,
            follows=feedP, start_time=P(), end_time=P(),
        )
        out_specs = (P(), P(), P(axis, None), feedP, metrics_spec, P(), P(),
                     P())
        fn = jax.jit(comm.shard_map(
            kernel, mesh=mesh, in_specs=(wall_spec, ctrl_spec, P()),
            out_specs=out_specs, check_vma=False,
        ))
    _FN_CACHE[cache_key] = fn
    return fn


# Configs whose candidate statistics overflowed the record budget once are
# remembered for the process lifetime and skip straight to the uncompressed
# path — the retry is then a one-time cost, not a per-call tax (config-2's
# short-clock shape measured 40% slower when every call re-tried).
_COMPRESS_BLOCKLIST: set = set()


def _regime_key(ctrl: CtrlParams, wall: WallParams):
    """Coarse clock-regime signature for the compression blocklist: the
    record-count regime is set by rate_f = sqrt(s_sink/q), so a sweep
    reusing one StarConfig must not let one short-clock (q, s_sink) point
    disable compression for every other point (3-significant-figure bucket
    of the mean clock rate — q alone misses the s_sink half of the rate)."""
    q = np.asarray(ctrl.q)
    s = np.asarray(wall.s_sink)
    if q.size == 0 or s.size == 0:
        return None
    m = float(np.sqrt(s.mean() / max(q.mean(), 1e-30)))
    return float(f"{m:.3g}") if np.isfinite(m) else None


def _run_with_fallback(cfg: StarConfig, metric_K: int, ctrl: CtrlParams,
                       wall: WallParams, run):
    """Run the star kernel compressed-first with the uncompressed fallback
    (shared by simulate_star and simulate_star_batch so the retry semantics
    cannot drift). ``run(compress) -> kernel out tuple``; overflow checks
    happen here, rec-first (see _check_overflow)."""
    key = (cfg, metric_K, _regime_key(ctrl, wall))
    if key not in _COMPRESS_BLOCKLIST:
        try:
            out = run(True)
            jax.block_until_ready(out[0])
            _check_overflow(cfg, out[5], out[6], out[7])
            return out
        except RecordBudgetOverflow:
            _COMPRESS_BLOCKLIST.add(key)
    out = run(False)
    jax.block_until_ready(out[0])
    _check_overflow(cfg, out[5], out[6])
    return out


# module-level so repeated overflow checks hit jit's warm cache
_sum_i32 = jax.jit(lambda a: jnp.sum(a.astype(jnp.int32)))


def _host_int_sum(x) -> int:
    """Total of ``x`` as a host int, valid when ``x`` is sharded across
    PROCESSES (multihost batch runs): reduce on-device to a replicated
    scalar first — a fully-replicated value is readable everywhere."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        return int(_sum_i32(x))
    return int(np.asarray(x).sum())


def _materialize(x):
    """Result materialization policy: NumPy when the array is locally
    materializable (single-process — today's behavior, unchanged); the
    global ``jax.Array`` when it spans processes, where a host copy is
    impossible per-process — gather explicitly with
    ``parallel.multihost.gather_global`` if the whole array is wanted."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        if x.is_fully_replicated:
            return np.asarray(x)  # every process holds the whole value
        return x
    return np.asarray(x)


def _check_overflow(cfg: StarConfig, wall_trunc, post_trunc, rec_trunc=None):
    """Raise (never truncate silently) when any lane's buffers filled.
    rec_trunc is checked FIRST: a record-budget overflow corrupts the
    compressed path's last slot and can spuriously fill the post buffer, so
    post_trunc is only meaningful once rec_trunc is clear."""
    if rec_trunc is not None and _host_int_sum(rec_trunc):
        raise RecordBudgetOverflow(
            "suffix-record budget overflow (a feed produced more "
            "right-to-left candidate minima than bigf._rec_cap allows — "
            "the short-clock regime); retrying with compression off"
        )
    n_wall = _host_int_sum(wall_trunc)
    if n_wall:
        raise RuntimeError(
            f"wall stream overflow ({n_wall} lane(s) hit wall_cap="
            f"{cfg.wall_cap} before the horizon) — raise StarConfig.wall_cap "
            f"(refusing to truncate silently)"
        )
    n_post = _host_int_sum(post_trunc)
    if n_post:
        raise RuntimeError(
            f"posting buffer overflow ({n_post} lane(s) hit post_cap="
            f"{cfg.post_cap} before the horizon) — raise StarConfig.post_cap "
            f"(refusing to truncate silently)"
        )


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------


def simulate_star(cfg: StarConfig, wall: WallParams, ctrl: CtrlParams,
                  seed, mesh: Optional[Mesh] = None, axis: str = "feed",
                  metric_K: int = 1, fire_mode: str = "auto") -> StarResult:
    """Simulate one star component to its horizon.

    With ``mesh``, the feed axis shards over ``mesh.shape[axis]`` devices
    (F must divide evenly); results are bit-identical to the unsharded run
    at matched seeds (PRNG streams key off GLOBAL feed indices). Raises on
    wall-buffer or post-buffer overflow instead of truncating.

    ``fire_mode``: how the Opt posting trajectory is extracted —
    ``"loop"`` (sequential while_loop), ``"doubling"`` (parallel pointer
    doubling; unsharded only), or ``"auto"`` (doubling on accelerators,
    loop on CPU/sharded — see star_fire._opt_fires for the measured
    tradeoff)."""
    key = jr.PRNGKey(seed) if isinstance(seed, (int, np.integer)) else seed
    _check_fire_mode(fire_mode, feed_sharded=mesh is not None)
    _check_wall_kinds(cfg, wall)
    if mesh is not None and axis != "feed":
        # The kernel's collectives (pmin/pany and the global-feed-index PRNG
        # offset) are bound to the axis NAME "feed"; any other name would
        # silently skip the reduction and corrupt results.
        raise ValueError(f"the follower mesh axis must be named 'feed', got "
                         f"{axis!r}")

    def run(compress):
        if mesh is None:
            return _get_fn(cfg, metric_K, None, axis, wall, ctrl,
                           compress, fire_mode)(wall, ctrl, key)
        n_dev = mesh.shape[axis]
        if cfg.n_feeds % n_dev != 0:
            raise ValueError(
                f"n_feeds={cfg.n_feeds} not divisible by mesh axis "
                f"{axis}={n_dev}"
            )
        fn = _get_fn(cfg, metric_K, mesh, axis, wall, ctrl, compress,
                     fire_mode)
        with mesh:
            return fn(comm.shard_leading(wall, mesh, axis),
                      comm.replicate(ctrl, mesh), comm.replicate(key, mesh))

    (own, n_posts, feed_times, wall_n, metrics, *_flags) = \
        _run_with_fallback(cfg, metric_K, ctrl, wall, run)
    # own/n_posts are replicated (readable on every process); the per-feed
    # arrays stay global jax.Arrays when the feed axis spans processes
    return StarResult(
        own_times=_materialize(own), n_posts=int(n_posts),
        wall_times=_materialize(feed_times), wall_n=_materialize(wall_n),
        metrics=metrics, cfg=cfg,
    )


def stack_star(wall_list: Sequence[WallParams],
               ctrl_list: Sequence[CtrlParams]):
    """Stack same-shape star components along a leading batch axis (the
    sweep/bipartite axis — one lane per broadcaster of the reference's
    10k x 100k graph, SURVEY.md section 3.5). Parameters may differ freely
    across lanes; shapes and the controlled-policy kind may not."""
    wall = jax.tree.map(lambda *xs: jnp.stack(xs), *wall_list)
    ctrl = jax.tree.map(lambda *xs: jnp.stack(xs), *ctrl_list)
    return wall, ctrl


def broadcast_star(wall: WallParams, ctrl: CtrlParams, B: int):
    """Tile ONE component to a [B]-lane batch without materializing copies
    host-side (lanes differ only by seed)."""
    return (
        jax.tree.map(lambda x: jnp.broadcast_to(x, (B,) + x.shape), wall),
        jax.tree.map(
            lambda x: jnp.broadcast_to(jnp.asarray(x), (B,) + jnp.asarray(x).shape),
            ctrl,
        ),
    )


_BATCH_FN_CACHE: dict = {}


def _batch_specs(wall: WallParams, ctrl: CtrlParams, dp: str, fp):
    """(in_specs, out_specs) for shard_map over a [B]-batched star kernel:
    batch dim over ``dp``; the per-feed dim (axis 1 of wall leaves) over
    ``fp`` when given."""
    def wall_spec(x):
        rest = [None] * (jnp.asarray(x).ndim - 2)
        return P(dp, fp, *rest)

    def lead_spec(x):
        rest = [None] * (jnp.asarray(x).ndim - 1)
        return P(dp, *rest)

    in_specs = (
        jax.tree.map(wall_spec, wall),
        jax.tree.map(lead_spec, ctrl),
        P(dp, None),                      # keys [B, 2]
    )
    feedP = P(dp, fp)
    metrics_spec = FeedMetrics(
        time_in_top_k=feedP, int_rank=feedP, int_rank2=feedP,
        follows=feedP,
        start_time=P(dp), end_time=P(dp),  # vmapped scalars -> [B]
    )
    out_specs = (
        P(dp, None),     # own_times [B, post_cap] (replicated over feed)
        P(dp),           # n_posts [B]
        P(dp, fp, None),  # feed_times [B, F, E]
        P(dp, fp),       # wall_n [B, F]
        metrics_spec,
        P(dp),           # wall_trunc [B] (pany over feed inside the kernel)
        P(dp),           # post_trunc [B]
        P(dp),           # rec_trunc [B]
    )
    return in_specs, out_specs


def simulate_star_batch(cfg: StarConfig, wall: WallParams, ctrl: CtrlParams,
                        seeds, mesh: Optional[Mesh] = None,
                        axis: str = "data", feed_axis: Optional[str] = None,
                        metric_K: int = 1,
                        fire_mode: str = "auto") -> StarBatchResult:
    """Run B star components in lockstep — the loop-free engine for the
    bipartite sweep (BASELINE configs 1/3 and the headline 10k x 100k
    graph): every lane is one broadcaster vs its follower feeds, the whole
    batch is one ``vmap`` of the stream/suffix-min kernel, and with ``mesh``
    the batch shards over the ``data`` axis by input placement (the
    redqueen_tpu.parallel.shard convention — no kernel changes, so sharded
    and unsharded runs are bit-identical at matched seeds).

    ``wall``/``ctrl`` leaves carry a leading [B] dim (see :func:`stack_star`
    / :func:`broadcast_star`); ``seeds`` is an int array [B] or key array
    [B, 2]. Raises on any lane's buffer overflow, never truncates silently.

    With ``feed_axis`` as well, the mesh is 2-D — components over ``axis``
    (dp) x followers-within-a-component over ``feed_axis`` (the sequence-
    parallel analogue): the kernel runs under ``shard_map`` with the
    RedQueen clock reduction riding ``pmin`` over the feed axis, and per-
    source PRNG streams keyed off GLOBAL feed indices, so every mesh layout
    (1x8, 2x4, 8x1, unsharded) is bit-identical at matched seeds.
    """
    seeds = jnp.asarray(seeds)
    keys = jax.vmap(jr.PRNGKey)(seeds) if seeds.ndim == 1 else seeds
    B = keys.shape[0]
    if wall.kind.shape[0] != B:
        raise ValueError(
            f"batch dims disagree: seeds={B}, wall={wall.kind.shape[0]}"
        )
    ctrl_q = jnp.asarray(ctrl.q)
    if ctrl_q.ndim != 1 or ctrl_q.shape[0] != B:
        # A stack_star/broadcast_star mismatch would otherwise surface as an
        # opaque vmap shape error deep in the kernel.
        raise ValueError(
            f"batch dims disagree: seeds={B}, ctrl="
            f"{ctrl_q.shape[0] if ctrl_q.ndim else 'unbatched'} — build the "
            f"batch with stack_star/broadcast_star"
        )
    _check_fire_mode(fire_mode,
                     feed_sharded=mesh is not None and feed_axis is not None)
    fire_mode = _resolve_fire_mode(
        fire_mode, feed_sharded=mesh is not None and feed_axis is not None)
    _check_wall_kinds(cfg, wall)
    if feed_axis is not None and feed_axis != "feed":
        raise ValueError(f"the follower mesh axis must be named 'feed', got "
                         f"{feed_axis!r} (kernel collectives bind to the "
                         f"name)")

    def get_fn(compress):
        cache_key = (cfg, metric_K, mesh, axis, feed_axis, compress,
                     fire_mode, jax.tree.structure((wall, ctrl)))
        fn = _BATCH_FN_CACHE.get(cache_key)
        if fn is None:
            vk = jax.vmap(_make_kernel(cfg, metric_K, compress, fire_mode))
            if mesh is not None and feed_axis is not None:
                in_specs, out_specs = _batch_specs(wall, ctrl, axis, feed_axis)
                vk = comm.shard_map(vk, mesh=mesh, in_specs=in_specs,
                                    out_specs=out_specs, check_vma=False)
            fn = jax.jit(vk)
            _BATCH_FN_CACHE[cache_key] = fn
        return fn

    def run(compress):
        fn = get_fn(compress)
        if mesh is None:
            return fn(wall, ctrl, keys)
        n_dev = mesh.shape[axis]
        if B % n_dev != 0:
            raise ValueError(
                f"batch {B} not divisible by mesh axis {axis}={n_dev}"
            )
        if feed_axis is not None:
            n_feed = mesh.shape[feed_axis]
            if cfg.n_feeds % n_feed != 0:
                raise ValueError(
                    f"n_feeds={cfg.n_feeds} not divisible by mesh axis "
                    f"{feed_axis}={n_feed}"
                )
            with mesh:
                return fn(wall, ctrl, keys)
        with mesh:
            return fn(comm.shard_leading(wall, mesh, axis),
                      comm.shard_leading(ctrl, mesh, axis),
                      comm.shard_leading(keys, mesh, axis))

    (own, n_posts, _feed_times, wall_n, metrics, *_flags) = \
        _run_with_fallback(cfg, metric_K, ctrl, wall, run)
    return StarBatchResult(
        own_times=_materialize(own), n_posts=_materialize(n_posts),
        wall_n=_materialize(wall_n), metrics=metrics, cfg=cfg,
    )
