"""Distributed execution over jax.sharding meshes (SURVEY.md section 5):
collective wrappers that no-op at mesh size 1 (comm), component-batch SPMD
sharding (shard), and the follower-sharded big-F kernel (bigf)."""

from .comm import (  # noqa: F401
    axis_total,
    make_mesh,
    pany,
    pmax,
    pmin,
    psum,
    replicate,
    shard_leading,
)
from .shard import simulate_sharded  # noqa: F401
from .lanes import (  # noqa: F401
    measured_slab,
    plan_buckets,
    simulate_ragged,
    simulate_slabbed,
)
from .bigf import (  # noqa: F401
    StarBuilder,
    StarConfig,
    StarResult,
    simulate_star,
    star_to_dataframe,
)
