"""Distributed execution over jax.sharding meshes (SURVEY.md section 5):
collective wrappers that no-op at mesh size 1 (comm), component-batch SPMD
sharding (shard), and the follower-sharded big-F kernel (bigf)."""

from .comm import make_mesh, psum, pmin, pmax, pany, shard_leading, replicate  # noqa: F401
from .shard import simulate_sharded  # noqa: F401
from .bigf import (  # noqa: F401
    StarBuilder,
    StarConfig,
    StarResult,
    simulate_star,
    star_to_dataframe,
)
