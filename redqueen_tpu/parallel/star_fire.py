"""RedQueen (Opt) posting-time extraction for the star engine: the sorted
suffix-min formulation (step 2 of the ``bigf.py`` design), its two fire
modes (adaptive while_loop vs pointer doubling), and the suffix-record
compression of the global sort.

Split out of ``bigf.py`` (round-5 verdict item 7).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import random as jr

from . import comm
from .star_types import StarConfig

__all__ = [
    "_rec_cap",
    "_opt_fires",
    "_fires_by_doubling",
    "_resolve_fire_mode",
    "_check_fire_mode",
    "_FIRE_MODES",
]

_FIRE_MODES = ("auto", "loop", "doubling")


def _resolve_fire_mode(fire_mode: str, feed_sharded: bool) -> str:
    """Resolve 'auto' to the concrete mode BEFORE any kernel cache is
    keyed: the choice depends on jax.default_backend(), so caching under
    the literal 'auto' would reuse a kernel whose loop-vs-doubling
    decision was made for a different backend after a mid-process platform
    flip (results stay bit-identical either way; only the measured
    performance policy would silently be the wrong one)."""
    if fire_mode != "auto":
        return fire_mode
    return ("loop" if feed_sharded or jax.default_backend() == "cpu"
            else "doubling")


def _check_fire_mode(fire_mode: str, feed_sharded: bool):
    """Early public-API validation: non-Opt control policies never reach
    _opt_fires, so without this a typo'd mode (or doubling on a sharded
    feed axis) would be silently ignored on those configs."""
    if fire_mode not in _FIRE_MODES:
        raise ValueError(
            f"unknown fire_mode {fire_mode!r} (choose from {_FIRE_MODES})"
        )
    if fire_mode == "doubling" and feed_sharded:
        raise ValueError(
            "fire_mode='doubling' needs the full sorted record arrays on "
            "every device; it does not support a sharded feed axis "
            "(use 'loop'/'auto')"
        )


def _rec_cap(E: int) -> int:
    """Static per-feed suffix-record budget for the compressed fire path.
    Records per feed are the right-to-left running minima of the candidate
    sequence; their count is ~ln E (~6 at E=256) when the superposition
    clocks are long relative to inter-event gaps (the low-intensity RedQueen
    regime: rate_f = sqrt(s/q) small), but approaches E when clocks are
    short (cand ~ w + tiny noise is nearly increasing). Overflow is checked
    loudly and the caller retries with compression off — never silent."""
    return int(max(64, 4 * np.ceil(np.log(max(E, 2)))))


def _opt_fires(cfg: StarConfig, feed_times, rate_f, key_tau, feed_offset,
               compress: bool = True, fire_mode: str = "auto"):
    """RedQueen posting times via the sorted suffix-min formulation.

    ``feed_times`` [F_local, E] ascending wall events per feed; ``rate_f``
    [F_local] = sqrt(s_f / q). Returns (own_times [post_cap], truncated,
    rec_trunc).

    ``fire_mode`` selects how the posting trajectory is extracted from the
    sorted (wall time, candidate) arrays: ``"loop"`` is the adaptive
    ``while_loop`` (one searchsorted + suffix lookup per post; under feed
    sharding also one ``pmin`` per post); ``"doubling"`` is the pointer-
    doubling formulation (see ``_fires_by_doubling``) — the SAME fires,
    bit for bit, in O(log post_cap) parallel gather passes with no
    sequential dependence on the number of posts. ``"auto"`` picks
    doubling on non-CPU backends when the feed axis is unsharded (the
    TPU's latency-bound regime) and the loop otherwise (CPU: the loop does
    ~10x less total work; sharded: the loop's pmin keeps records
    device-local).

    Suffix-record compression (``compress``): the fire loop only ever
    queries min{cand_e : w_e > t}. Within a feed, an event e1 with a later
    event e2 > e1 such that cand_e2 <= cand_e1 can NEVER be that min (any
    query admitting e1 also admits e2), so only the feed's suffix-record
    events — cand strictly below every later candidate in the row — matter,
    and the argmin of any query is itself a record. The global sort then
    shrinks from [F x E] to [F x rec_cap] with EXACT results — measured 5x
    on the 100k-feed config, where the 5M-element sort was the whole
    fire-phase cost. When a feed's record count exceeds the static budget
    (short-clock regime, see _rec_cap) the rec_trunc flag trips and
    simulate_star retries with ``compress=False`` (the full-sort path)."""
    Fl, E = feed_times.shape
    dtype = feed_times.dtype
    inf = jnp.asarray(jnp.inf, dtype)
    # Compaction into [Fl, R] slots only pays when R < E; at small E the
    # record buffer would be as large as the raw input and the cummin +
    # min-scatter passes are pure overhead (results are exact either way).
    compress = compress and E > _rec_cap(E)

    # One Exp clock per wall event — the reference's exact draw count, keyed
    # by GLOBAL feed index so mesh layout cannot change the streams.
    def feed_draws(f_global):
        return jr.exponential(jr.fold_in(key_tau, f_global), (E,), dtype)

    draws = jax.vmap(feed_draws)(feed_offset + jnp.arange(Fl))
    cand = feed_times + draws / jnp.maximum(rate_f[:, None], 1e-30)
    cand = jnp.where(rate_f[:, None] > 0, cand, jnp.inf)

    if compress:
        # --- per-feed suffix-record compaction (exact; see docstring) ---
        suf_incl = jnp.flip(lax.cummin(jnp.flip(cand, axis=1), axis=1), axis=1)
        suf_after = jnp.concatenate(
            [suf_incl[:, 1:], jnp.full((Fl, 1), jnp.inf, dtype)], axis=1
        )
        mask = cand < suf_after                  # +inf cands never qualify
        n_rec = mask.sum(axis=1)
        R = _rec_cap(E)
        rec_trunc = comm.pany((n_rec > R).any(), "feed")
        pos = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0, R - 1)
        # Min-scatter into the [Fl, R] record slots: records carry their
        # value, non-records carry +inf (a no-op under .min), and in-budget
        # record positions are unique per row, so (t, cand) pairs stay
        # aligned (the overflow case corrupts slot R-1, but rec_trunc then
        # forces the uncompressed retry before any result is used).
        val_t = jnp.where(mask, feed_times, inf)
        val_c = jnp.where(mask, cand, inf)
        t_src = jax.vmap(
            lambda p, v: jnp.full((R,), jnp.inf, dtype).at[p].min(v)
        )(pos, val_t)
        c_src = jax.vmap(
            lambda p, v: jnp.full((R,), jnp.inf, dtype).at[p].min(v)
        )(pos, val_c)
    else:
        t_src, c_src = feed_times, cand
        rec_trunc = jnp.zeros((), bool)

    t_sorted, c_sorted = lax.sort(
        (t_src.reshape(-1), c_src.reshape(-1)), num_keys=1
    )
    # suffix_min[i] = min candidate among (kept) wall events with idx >= i.
    suffix = jnp.flip(lax.cummin(jnp.flip(c_sorted)))
    suffix = jnp.concatenate([suffix, jnp.full((1,), jnp.inf, dtype)])

    sharded = comm.axis_present("feed")
    _check_fire_mode(fire_mode, feed_sharded=sharded)
    # One policy, one place: entry points resolve 'auto' before keying
    # their kernel caches; this delegate covers direct _make_kernel users.
    use_doubling = _resolve_fire_mode(fire_mode, sharded) == "doubling"

    if use_doubling:
        own, truncated = _fires_by_doubling(cfg, t_sorted, suffix)
        return own, truncated, rec_trunc

    # Adaptive fire loop: post_cap bounds the buffer, but the while_loop
    # exits as soon as the trajectory absorbs (a vmapped while runs until
    # every lane is done — with 4x-headroom caps that is typically a ~4x
    # shorter loop than a fixed-length scan). Sharded lanes stay in
    # lockstep: after the pmin the carry is identical on every shard, so
    # the loop condition is too.
    Kp = cfg.post_cap
    t0 = jnp.asarray(cfg.start_time, dtype)
    buf0 = jnp.full((Kp,), jnp.inf, dtype)

    def cond(c):
        t_last, n, _ = c
        return jnp.isfinite(t_last) & (n < Kp)

    def fire(c):
        t_last, n, buf = c
        idx = jnp.searchsorted(t_sorted, t_last, side="right")
        t_next = comm.pmin(suffix[idx], "feed")
        t_next = jnp.where(t_next <= cfg.end_time, t_next, jnp.inf)
        buf = buf.at[n].set(t_next)  # +inf write into +inf pad: no-op
        return t_next, n + jnp.isfinite(t_next).astype(n.dtype), buf

    t_last, _, own = lax.while_loop(
        cond, fire, (t0, jnp.zeros((), jnp.int32), buf0)
    )
    # Overflow: a further post would still fit before the horizon.
    idx = jnp.searchsorted(t_sorted, t_last, side="right")
    more = comm.pmin(suffix[idx], "feed") <= cfg.end_time
    truncated = jnp.isfinite(t_last) & more
    return own, truncated, rec_trunc


def _fires_by_doubling(cfg: StarConfig, t_sorted, suffix):
    """The posting trajectory as pointer doubling — the while_loop's fires,
    bit for bit, with no sequential dependence on the post count.

    The fire map is f(t) = suffix[sp(t)] with sp(t) = searchsorted(t_sorted,
    t, 'right') (the strict ``w > t`` query); every reachable fire value is
    a ``suffix`` element, so the orbit lives on POSITIONS: p_1 = sp(start),
    p_{k+1} = nxt[p_k] with nxt[i] = sp(suffix[i]), and own_k =
    suffix[p_k]. ``nxt`` is strictly forward (every candidate satisfies
    c >= its own wall time, and 'right' skips equals), so position N — the
    appended +inf suffix slot, a fixed point of nxt — absorbs every
    trajectory. Jump tables J_p = nxt^(2^p) then materialize all post_cap
    positions in ceil(log2(post_cap)) gather passes: the second half of the
    filled prefix is J_p applied to the first half. Work is
    O((N + post_cap) log post_cap) fully parallel gathers — vs the loop's
    O(posts) sequential searchsorted steps, which on a latency-bound
    backend (the TPU, especially through the tunnel) dominate the star
    engine's critical path.

    Horizon clipping happens AFTER the orbit: fires increase strictly, so
    where(raw <= end, raw, inf) is densely packed exactly like the loop's
    incremental buffer. The truncation flag mirrors the loop's: post_cap
    in-horizon fires AND one more would still fit."""
    Kp = cfg.post_cap
    end = cfg.end_time
    N = t_sorted.shape[0]

    nxt = jnp.searchsorted(t_sorted, suffix, side="right").astype(jnp.int32)
    p1 = jnp.searchsorted(
        t_sorted, jnp.asarray(cfg.start_time, t_sorted.dtype), side="right"
    ).astype(jnp.int32)
    pos = jnp.full((Kp,), N, jnp.int32).at[0].set(p1)
    jump = nxt
    filled = 1
    while filled < Kp:  # static unroll: ceil(log2(Kp)) levels
        take = min(filled, Kp - filled)
        pos = pos.at[filled:filled + take].set(jump[pos[:take]])
        filled += take
        if filled < Kp:
            jump = jump[jump]
    raw = suffix[pos]
    own = jnp.where(raw <= end, raw, jnp.inf)
    f_next = suffix[nxt[pos[Kp - 1]]]
    truncated = jnp.isfinite(own[Kp - 1]) & (f_next <= end)
    return own, truncated
