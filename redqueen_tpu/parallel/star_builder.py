"""StarBuilder (the big-F front end: the reference's ``SimOpts`` analogue at
scale) and the small-F DataFrame export.

Split out of ``bigf.py`` (round-5 verdict item 7).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..config import ConfigValidationError, _require_finite, check_piecewise
from ..models.base import (
    KIND_HAWKES,
    KIND_OPT,
    KIND_PIECEWISE,
    KIND_POISSON,
    KIND_REALDATA,
    KIND_RMTPP,
)
from .star_types import _EMPTY, CtrlParams, StarConfig, StarResult, WallParams

__all__ = ["StarBuilder", "star_to_dataframe"]


class StarBuilder:
    """Front end assembling a star component (the big-F counterpart of
    config.GraphBuilder / the reference's ``SimOpts``). One wall slot list
    per feed; exactly one controlled broadcaster."""

    def __init__(self, n_feeds: int, end_time: float, start_time: float = 0.0,
                 s_sink: Optional[Sequence[float]] = None):
        self.n_feeds = int(n_feeds)
        self.end_time = _require_finite("end_time", end_time)
        self.start_time = _require_finite("start_time", start_time)
        if not self.end_time > self.start_time:
            raise ConfigValidationError(
                f"end_time must be > start_time, got "
                f"[{self.start_time!r}, {self.end_time!r}]")
        self.s_sink = (
            np.ones(n_feeds) if s_sink is None
            else np.asarray(s_sink, np.float64)
        )
        if self.s_sink.shape != (self.n_feeds,):
            raise ValueError(
                f"s_sink must have shape ({self.n_feeds},), got "
                f"{self.s_sink.shape}"
            )
        bad = ~(np.isfinite(self.s_sink) & (self.s_sink >= 0))
        if bad.any():
            i = int(np.flatnonzero(bad)[0])
            raise ConfigValidationError(
                f"s_sink must be finite and >= 0, got {self.s_sink[i]!r} at "
                f"feed {i}")
        self._walls = [[] for _ in range(self.n_feeds)]
        self._ctrl = None

    # ---- wall sources (one feed each) ----
    # Same validated boundary as config.GraphBuilder (runtime.numerics):
    # garbage is rejected here with the feed index, not detected device-
    # side as a quarantined lane.

    def wall_poisson(self, feed: int, rate: float):
        rate = _require_finite("Poisson rate", rate, feed, minimum=0.0)
        self._walls[feed].append(dict(kind=KIND_POISSON, rate=rate))
        return self

    def wall_hawkes(self, feed: int, l0: float, alpha: float, beta: float):
        l0 = _require_finite("Hawkes l0 (base rate)", l0, feed, minimum=0.0)
        alpha = _require_finite("Hawkes alpha (jump size)", alpha, feed,
                                minimum=0.0)
        beta = _require_finite("Hawkes beta (decay)", beta, feed,
                               minimum=0.0, strict=True)
        self._walls[feed].append(
            dict(kind=KIND_HAWKES, l0=l0, alpha=alpha, beta=beta)
        )
        return self

    def wall_piecewise(self, feed: int, change_times, rates):
        self._walls[feed].append(
            dict(kind=KIND_PIECEWISE,
                 pw=check_piecewise(change_times, rates, component=feed))
        )
        return self

    def wall_replay(self, feed: int, times):
        t = np.asarray(times, np.float64)
        if t.size and not np.isfinite(t).all():
            i = int(np.flatnonzero(~np.isfinite(t))[0])
            raise ConfigValidationError(
                f"replay times must be finite, got {t[i]!r} at index {i}",
                feed)
        # the corpus path feeds bulk per-user slices here — sorting is a
        # service at this seam (GraphBuilder.add_realdata, the per-source
        # front end, rejects non-monotone input instead)
        self._walls[feed].append(dict(kind=KIND_REALDATA, rd=np.sort(t)))
        return self

    # ---- controlled broadcaster (reference: the manager factories) ----

    def ctrl_opt(self, q: float = 1.0):
        if not (np.isfinite(q) and q > 0):
            raise ConfigValidationError(
                f"Opt requires finite q > 0, got q={q!r}")
        self._ctrl = dict(kind=KIND_OPT, q=float(q))
        return self

    def ctrl_poisson(self, rate: float):
        rate = _require_finite("Poisson rate", rate, minimum=0.0)
        self._ctrl = dict(kind=KIND_POISSON, rate=rate)
        return self

    def ctrl_hawkes(self, l0: float, alpha: float, beta: float):
        """Hawkes posting as the CONTROLLED broadcaster (the reference's
        vs-Hawkes comparison at big F) — legal because Hawkes depends only on
        its own history. Stationary iff alpha < beta (expected posts
        ~ l0*T/(1 - alpha/beta))."""
        l0 = _require_finite("Hawkes l0 (base rate)", l0, minimum=0.0)
        alpha = _require_finite("Hawkes alpha (jump size)", alpha,
                                minimum=0.0)
        beta = _require_finite("Hawkes beta (decay)", beta, minimum=0.0,
                               strict=True)
        self._ctrl = dict(kind=KIND_HAWKES, l0=l0, alpha=alpha, beta=beta)
        return self

    def ctrl_piecewise(self, change_times, rates):
        self._ctrl = dict(
            kind=KIND_PIECEWISE, pw=check_piecewise(change_times, rates)
        )
        return self

    def ctrl_replay(self, times):
        t = np.asarray(times, np.float64)
        if t.size and not np.isfinite(t).all():
            i = int(np.flatnonzero(~np.isfinite(t))[0])
            raise ConfigValidationError(
                f"replay times must be finite, got {t[i]!r} at index {i}")
        self._ctrl = dict(kind=KIND_REALDATA, rd=np.sort(t))
        return self

    def ctrl_rmtpp(self, weights, hidden: int = 16):
        self._ctrl = dict(kind=KIND_RMTPP, rmtpp=weights, hidden=int(hidden))
        return self

    # ---- assembly ----

    def build(self, wall_cap: int = 256, post_cap: int = 1024,
              dtype=jnp.float32):
        if self._ctrl is None:
            raise ValueError("no controlled broadcaster set (ctrl_* methods)")
        F = self.n_feeds
        M = max((len(w) for w in self._walls), default=0)
        M = max(M, 1)
        Kp = max(
            [len(w["pw"][0]) for row in self._walls for w in row
             if "pw" in w] + (
                [len(self._ctrl["pw"][0])] if "pw" in self._ctrl else []
            ),
            default=1,
        )
        Kr = max(
            [len(w["rd"]) for row in self._walls for w in row if "rd" in w],
            default=1,
        )
        kind = np.full((F, M), _EMPTY, np.int32)
        rate = np.ones((F, M)); l0 = np.ones((F, M))
        alpha = np.zeros((F, M)); beta = np.ones((F, M))
        pw_t = np.full((F, M, Kp), np.inf); pw_t[:, :, 0] = 0.0
        pw_r = np.zeros((F, M, Kp))
        rd_t = np.full((F, M, Kr), np.inf)
        kinds_present = set()
        for f, row in enumerate(self._walls):
            for m, w in enumerate(row):
                kind[f, m] = w["kind"]
                kinds_present.add(int(w["kind"]))
                if w["kind"] == KIND_POISSON:
                    rate[f, m] = w["rate"]
                elif w["kind"] == KIND_HAWKES:
                    l0[f, m] = w["l0"]; alpha[f, m] = w["alpha"]
                    beta[f, m] = w["beta"]
                elif w["kind"] == KIND_PIECEWISE:
                    ct, r = w["pw"]
                    pw_t[f, m] = np.inf
                    pw_t[f, m, : len(ct)] = ct
                    pw_r[f, m, : len(r)] = r
                elif w["kind"] == KIND_REALDATA:
                    rd_t[f, m, : len(w["rd"])] = w["rd"]
        kinds_present.add(_EMPTY)

        c = self._ctrl
        c_pw_t = np.full(Kp, np.inf); c_pw_t[0] = 0.0
        c_pw_r = np.zeros(Kp)
        if "pw" in c:
            ct, r = c["pw"]
            c_pw_t[:] = np.inf
            c_pw_t[: len(ct)] = ct
            c_pw_r[: len(r)] = r
        c_rd = (
            np.asarray(c["rd"], np.float64) if "rd" in c
            else np.full(1, np.inf)
        )
        cfg = StarConfig(
            n_feeds=F, walls_per_feed=M, end_time=self.end_time,
            start_time=self.start_time, wall_cap=int(wall_cap),
            post_cap=int(post_cap), ctrl_kind=int(c["kind"]),
            rmtpp_hidden=int(c.get("hidden", 1)),
            wall_kinds=tuple(sorted(kinds_present)),
        )
        wall = WallParams(
            kind=jnp.asarray(kind),
            rate=jnp.asarray(rate, dtype), l0=jnp.asarray(l0, dtype),
            alpha=jnp.asarray(alpha, dtype), beta=jnp.asarray(beta, dtype),
            pw_times=jnp.asarray(pw_t, dtype),
            pw_rates=jnp.asarray(pw_r, dtype),
            rd_times=jnp.asarray(rd_t, dtype),
            s_sink=jnp.asarray(self.s_sink, dtype),
        )
        ctrl = CtrlParams(
            q=jnp.asarray(c.get("q", 1.0), dtype),
            rate=jnp.asarray(c.get("rate", 1.0), dtype),
            pw_times=jnp.asarray(c_pw_t, dtype),
            pw_rates=jnp.asarray(c_pw_r, dtype),
            rd_times=jnp.asarray(c_rd, dtype),
            l0=jnp.asarray(c.get("l0", 0.0), dtype),
            alpha=jnp.asarray(c.get("alpha", 0.0), dtype),
            beta=jnp.asarray(c.get("beta", 1.0), dtype),
            rmtpp=c.get("rmtpp"),
        )
        return cfg, wall, ctrl


def star_to_dataframe(res: StarResult, src_id=0, wall_src_offset: int = 100):
    """Export a star run as the reference-schema event DataFrame (one row per
    (event, sink); columns event_id/t/time_delta/src_id/sink_id) so the
    backend-agnostic pandas metric layer applies unchanged — intended for
    small-F validation, not 100k-feed exports.

    Wall source ids are ``wall_src_offset + feed``; own posts land in every
    feed. Tie order matches the oracle: own post first."""
    import pandas as pd

    F = res.cfg.n_feeds
    own = res.own_times[np.isfinite(res.own_times)]
    rows = []  # (t, order, src, sinks)
    for t in own:
        rows.append((float(t), 0, src_id, None))
    for f in range(F):
        for t in res.wall_times[f][: int(res.wall_n[f])]:
            rows.append((float(t), 1, wall_src_offset + f, f))
    rows.sort(key=lambda r: (r[0], r[1]))
    recs = []
    last = {}
    for eid, (t, _, src, sink) in enumerate(rows):
        delta = t - last.get(src, res.cfg.start_time)
        last[src] = t
        sinks = range(F) if sink is None else [sink]
        for sk in sinks:
            recs.append((eid, t, delta, src, sk))
    return pd.DataFrame(
        recs, columns=["event_id", "t", "time_delta", "src_id", "sink_id"]
    )
