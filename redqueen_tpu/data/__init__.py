"""Trace data layer: loading, padding/bucketing, and synthetic generation of
real-event replay traces (reference: the Twitter dataset consumed by the
``RealData`` broadcaster and ``SimOpts.create_manager_with_times``)."""

from .traces import (  # noqa: F401
    bucket_traces,
    load_csv,
    normalize_traces,
    pad_traces,
    save_npz,
    load_npz,
    replay_buckets,
    star_from_traces,
    synthetic_twitter,
)
