"""Event-trace handling for real-data replay at scale.

The reference replays a Twitter trace through its ``RealData`` broadcaster
(SURVEY.md section 2 item 7) and feeds real user posting times to
``SimOpts.create_manager_with_times``. At 100k followers the irregular
per-user event lists must become device-ready tensors (SURVEY.md section 7
hard parts: "padded/bucketed tensors; watch memory"): this module loads
traces (CSV / NPZ), normalizes their time axis (absolute epochs overflow
float32 resolution), pads them into ``[U, L]`` +inf-padded arrays, buckets
by length to bound padding waste, and generates heavy-tailed synthetic
"twitter-like" corpora for benchmarks when no real dataset is mounted (the
environment has no network; see SURVEY.md section 0).

No instructions from data files are ever executed — traces are parsed as
numbers only.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "load_csv",
    "save_npz",
    "load_npz",
    "LoadStats",
    "TraceOrderError",
    "normalize_traces",
    "pad_traces",
    "bucket_traces",
    "gaps_from_traces",
    "synthetic_twitter",
    "star_from_traces",
]

Traces = List[np.ndarray]  # one ascending float64 time array per user


class TraceOrderError(ValueError):
    """A trace row's timestamp cannot be ordered (NaN): typed rejection
    instead of a silently NaN-sorted corpus.  Raised identically by both
    loader engines — downstream consumers (the serving ingest path's
    reorder window, the RealData replay kernel) all assume orderable
    times, so an unorderable row must die at the boundary with a line
    number, not three layers later as a quarantined lane."""


class LoadStats(NamedTuple):
    """What the parse observed about the corpus's ORDER quality — the
    measured input contract for the serving reorder window (a corpus
    with many non-monotonic rows needs a wide window; duplicates feed
    the duplicate-drop expectation).  Counted identically by both
    engines (pinned by tests/test_native_loader.py)."""

    n_rows: int                 # events parsed (post header/blank skip)
    n_users: int                # distinct users
    duplicate_timestamps: int   # same user, exactly equal timestamps
    non_monotonic_rows: int     # rows that regressed vs the same user's
    #                             previous row in FILE order


def load_csv(path: str, user_col: int = 0, time_col: int = 1,
             delimiter: str = ",", skip_header: int = 1,
             engine: str = "auto", return_stats: bool = False):
    """Load (user, timestamp) rows into per-user ascending time arrays.

    Users are ordered by first appearance; times sort per user. This is the
    rebuild's loader for the reference's Twitter-trace input format.

    ``engine``: ``"auto"`` uses the native C++ parser
    (redqueen_tpu.native.loader; measured 3-5x faster at million-row
    corpora, larger at low user cardinality — benchmarks/trace_io.py)
    when it builds on this machine and falls back to pure Python
    otherwise; ``"native"`` requires it; ``"python"`` forces the
    interpreter path. Both engines produce identical output (pinned by
    tests/test_native_loader.py).

    ``return_stats=True`` returns ``(traces, LoadStats)`` — the
    duplicate-timestamp / non-monotonic-row counts are surfaced, never
    silently absorbed by the per-user sort.  A NaN timestamp raises
    :class:`TraceOrderError` (it cannot be ordered) in both engines."""
    if engine not in ("auto", "native", "python"):
        raise ValueError(f"unknown engine {engine!r}")
    # Arguments only the Python path supports (multi-char or non-ASCII
    # delimiters, negative column indices) keep "auto" on the Python path;
    # "native" means the caller requires the C++ parser, so let it reject
    # them. The delimiter crosses the C ABI as ONE byte, hence encode().
    native_ok = (len(delimiter.encode()) == 1
                 and user_col >= 0 and time_col >= 0)
    if engine == "native" or (engine == "auto" and native_ok):
        from ..native import loader as _native

        if engine == "native" or _native.available():
            return _native.load_csv_native(
                path, user_col=user_col, time_col=time_col,
                delimiter=delimiter, skip_header=skip_header,
                return_stats=return_stats,
            )
    users: Dict = {}
    order: List = []
    n_rows = 0
    non_monotonic = 0
    with open(path) as f:
        for i, line in enumerate(f):
            if i < skip_header or not line.strip():
                continue
            parts = line.rstrip("\n").split(delimiter)
            u = parts[user_col]
            t = float(parts[time_col])
            if t != t:  # NaN: unorderable — same wording as the C parser
                raise TraceOrderError(
                    f"{path}: line {i}: unorderable timestamp "
                    f"'{parts[time_col].strip()}' (NaN rows cannot be "
                    f"ordered)")
            if u not in users:
                users[u] = []
                order.append(u)
            elif users[u] and t < users[u][-1]:
                non_monotonic += 1
            users[u].append(t)
            n_rows += 1
    out = [np.sort(np.asarray(users[u], np.float64)) for u in order]
    if not return_stats:
        return out
    duplicates = sum(int(np.sum(a[1:] == a[:-1])) for a in out if len(a))
    return out, LoadStats(
        n_rows=n_rows, n_users=len(order),
        duplicate_timestamps=duplicates,
        non_monotonic_rows=non_monotonic)


def save_csv(path: str, traces: Traces, float_format: str = "%.9g") -> None:
    """Write traces as (user, time) CSV rows, user-major, so
    :func:`load_csv` round-trips to the same per-user arrays: users are
    ordered by first appearance (= writing order) and times are already
    ascending per user. This is the corpus→disk half of the config-4
    ingestion pipeline (benchmarks/run.py): a corpus written once is then
    re-ingested through the native loader on every bench run instead of
    being regenerated.

    ``float_format`` %.9g keeps ~1e-9 relative precision — beyond the
    float32 resolution the simulation kernels run at, so a round-tripped
    corpus simulates identically at f32 (exact f64 round-trip needs
    %.17g at ~2x the file size). Users with EMPTY traces write no rows and
    therefore vanish on round trip (CSV cannot represent them) — the
    config-4 pipeline records the loaded user count for exactly this
    reason (e.g. 99,982 of 100,000 synthetic users have >=1 event)."""
    import pandas as pd

    lens = [len(t) for t in traces]
    users = np.repeat(
        np.asarray([f"u{i:06d}" for i in range(len(traces))]), lens
    )
    times = (np.concatenate([np.asarray(t, np.float64) for t in traces])
             if traces else np.empty(0))
    pd.DataFrame({"user": users, "time": times}).to_csv(
        path, index=False, float_format=float_format
    )


def save_npz(path: str, traces: Traces) -> None:
    """Persist traces as one array per user (``u000001``...)."""
    np.savez_compressed(
        path, **{f"u{i:06d}": t for i, t in enumerate(traces)}
    )


def load_npz(path: str) -> Traces:
    with np.load(path) as z:
        return [np.asarray(z[k], np.float64) for k in sorted(z.files)]


def normalize_traces(traces: Traces, end_time: float,
                     t_min: Optional[float] = None,
                     t_max: Optional[float] = None) -> Traces:
    """Affinely map absolute timestamps onto [0, end_time].

    Raw epoch seconds (~1.5e9) exceed float32's useful resolution; the
    simulation kernels run in float32 on TPU, so traces must be rescaled to
    a small window first. Events outside [t_min, t_max] are dropped."""
    all_t = np.concatenate([t for t in traces if len(t)]) if traces else np.empty(0)
    if t_min is None:
        t_min = float(all_t.min()) if len(all_t) else 0.0
    if t_max is None:
        t_max = float(all_t.max()) if len(all_t) else 1.0
    span = max(t_max - t_min, 1e-12)
    out = []
    for t in traces:
        t = t[(t >= t_min) & (t <= t_max)]
        out.append((t - t_min) * (end_time / span))
    return out


def pad_traces(traces: Traces, length: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Pad to a ``[U, L]`` float array (+inf tail) plus lengths ``[U]`` — the
    ``RealData`` replay-row layout consumed by the kernels."""
    lens = np.array([len(t) for t in traces], np.int64)
    L = int(lens.max()) if length is None else int(length)
    if length is not None and lens.max() > length:
        raise ValueError(
            f"trace of length {int(lens.max())} exceeds requested pad length "
            f"{length} — refusing to truncate silently"
        )
    out = np.full((len(traces), max(L, 1)), np.inf, np.float64)
    for i, t in enumerate(traces):
        out[i, : len(t)] = t
    return out, lens


def bucket_traces(traces: Traces, edges: Sequence[int] = (16, 64, 256, 1024)
                  ) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Group users into length buckets to bound padding waste at 100k users
    (SURVEY.md section 7: "padded/bucketed tensors").

    Returns a list of (user_indices [u], padded [u, L_b], lengths [u]) —
    one entry per non-empty bucket, L_b the bucket's pad length. Run one
    sharded star simulation per bucket and scatter metrics back by index."""
    lens = np.array([len(t) for t in traces], np.int64)
    bounds = list(edges)
    if len(lens) and lens.max() > bounds[-1]:
        bounds.append(int(lens.max()))
    out = []
    lo = -1  # length-0 traces belong in the first bucket, not nowhere
    for hi in bounds:
        idx = np.where((lens > lo) & (lens <= hi))[0]
        if len(idx):
            padded, ls = pad_traces([traces[i] for i in idx], length=hi)
            out.append((idx, padded, ls))
        lo = hi
    return out


def gaps_from_traces(traces: Traces, length: Optional[int] = None
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-user inter-event-gap sequences for likelihood training
    (``models.rmtpp.fit``): ``(taus [U, L], mask [U, L])``, zero-padded
    with a boolean validity mask.

    The first gap is measured from t=0, matching the simulation kernel's
    convention (the RMTPP policy's recurrent state starts at the component
    origin, models/rmtpp.py on_init). Empty traces become all-masked rows."""
    gaps = [np.diff(t, prepend=0.0) if len(t) else np.empty(0) for t in traces]
    lens = np.array([len(g) for g in gaps], np.int64)
    L = int(max(lens.max() if len(lens) else 0, 1)) if length is None else int(length)
    if lens.max(initial=0) > L:
        raise ValueError(
            f"trace with {int(lens.max())} events exceeds requested length "
            f"{L} — refusing to truncate silently"
        )
    taus = np.zeros((len(traces), L), np.float64)
    mask = np.zeros((len(traces), L), bool)
    for i, g in enumerate(gaps):
        taus[i, : len(g)] = g
        mask[i, : len(g)] = True
    return taus, mask


def synthetic_twitter(seed: int, n_users: int, end_time: float,
                      mean_rate: float = 1.0, sigma: float = 1.0,
                      diurnal: float = 0.5, max_len: Optional[int] = None
                      ) -> Traces:
    """Heavy-tailed synthetic posting corpus standing in for the reference's
    Twitter dataset (no network here — SURVEY.md section 0).

    Per-user base rates are log-normal (few loud users, many quiet — the
    empirical follower-feed regime the paper evaluates on), modulated by a
    sinusoidal diurnal profile and sampled exactly by thinning against the
    per-user peak rate."""
    rng = np.random.RandomState(seed)
    base = rng.lognormal(mean=np.log(mean_rate) - sigma ** 2 / 2,
                         sigma=sigma, size=n_users)
    out = []
    for u in range(n_users):
        peak = base[u] * (1 + diurnal)
        n = rng.poisson(peak * end_time)
        t = np.sort(rng.uniform(0, end_time, n))
        lam = base[u] * (1 + diurnal * np.sin(2 * np.pi * t / max(end_time / 4, 1e-9)))
        keep = rng.uniform(0, peak, n) < lam
        t = t[keep]
        if max_len is not None and len(t) > max_len:
            t = t[np.sort(rng.choice(len(t), max_len, replace=False))]
        out.append(t)
    return out


def star_from_traces(traces: Traces, end_time: float, ctrl: str = "opt",
                     q: float = 1.0, ctrl_times: Optional[np.ndarray] = None,
                     s_sink: Optional[Sequence[float]] = None,
                     post_cap: int = 2048):
    """Build the BASELINE config-4 star component: one controlled broadcaster
    against per-follower real-trace walls (reference: RealData walls +
    ``create_manager_with_times`` / ``create_manager_with_opt``).

    ``ctrl``: "opt" (RedQueen against the replayed feeds) or "replay"
    (``ctrl_times`` — e.g. the real user's own posting record, the paper's
    real-user-behavior comparison). Returns (cfg, wall, ctrl_params)."""
    from ..parallel.bigf import StarBuilder

    padded, lens = pad_traces(traces)
    F, L = padded.shape
    sb = StarBuilder(n_feeds=F, end_time=end_time, s_sink=s_sink)
    for f in range(F):
        sb.wall_replay(f, padded[f, : lens[f]])
    if ctrl == "opt":
        sb.ctrl_opt(q=q)
    elif ctrl == "replay":
        if ctrl_times is None:
            raise ValueError('ctrl="replay" requires ctrl_times')
        sb.ctrl_replay(ctrl_times)
    else:
        raise ValueError(f"unknown ctrl {ctrl!r}")
    return sb.build(wall_cap=max(int(lens.max()), 1), post_cap=post_cap)


def replay_buckets(traces: Traces, end_time: float, ctrl_times: np.ndarray,
                   edges: Sequence[int] = (16, 64, 256, 1024),
                   s_sink: Optional[Sequence[float]] = None):
    """Length-bucketed star components for a REPLAY-controlled broadcaster:
    the exact, memory-bounded path for huge trace corpora.

    With ``ctrl="replay"`` the broadcaster's posts are a fixed sequence, so
    feeds decouple completely and the component may be split into per-bucket
    simulations without changing any distribution — each bucket pads only to
    its own edge instead of the global max (the difference between ~100 MB
    and multi-GB at 100k heavy-tailed users). This decomposition is NOT
    valid for ``ctrl="opt"``: RedQueen's posting clock couples every feed,
    so Opt at full scale must run as one component (bound memory by capping
    trace length at generation/preparation instead).

    Returns a list of (user_indices, cfg, wall, ctrl) — run each through
    ``parallel.bigf.simulate_star`` and scatter per-feed metrics back via
    ``user_indices``."""
    out = []
    for idx, padded, lens in bucket_traces(traces, edges=edges):
        out.append(
            (idx,)
            + star_from_traces(
                [padded[i, : lens[i]] for i in range(len(idx))], end_time,
                ctrl="replay", ctrl_times=ctrl_times,
                s_sink=None if s_sink is None
                else [s_sink[i] for i in idx],
            )
        )
    return out
