"""NumPy parity oracle: an event-driven feed simulator with the semantics of
MPI-SWS/RedQueen's ``redqueen/opt_model.py``.

This module is the trusted, boring, pure-Python/NumPy reference that the JAX
kernels are validated against (SURVEY.md section 4.1 and section 7 step 0). The
reference mount (/root/reference) was EMPTY at build time — see SURVEY.md
section 0 — so parity targets are the class/function inventory documented in
SURVEY.md sections 1–3 (reference files: ``redqueen/opt_model.py`` for
Event/State/Broadcaster/Poisson/Poisson2/Hawkes/PiecewiseConst/RealData/Opt/
Manager/SimOpts, ``redqueen/utils.py`` for the metric layer) and the RedQueen
paper (Zarezade et al., WSDM 2017, arXiv:1610.05773), Algorithm 1.

Model recap: ``sinks`` are followers, each with a feed. ``sources`` are
broadcasters posting into the feeds of the sinks they are connected to
(``edge_list``). The rank r_i(t) of a source in sink i's feed is the number of
posts by OTHER sources into that feed since the source's own most recent post
(0 = top of feed). The RedQueen policy ``Opt`` posts with intensity
u*(t) = sum_i sqrt(s_i / q) * r_i(t), sampled online via the superposition
trick (one new exponential clock per rank increment, keep the running min).
"""

from __future__ import annotations

import bisect
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

__all__ = [
    "Event",
    "State",
    "Broadcaster",
    "Poisson",
    "Poisson2",
    "Hawkes",
    "PiecewiseConst",
    "RealData",
    "Opt",
    "RMTPP",
    "Manager",
    "SimOpts",
]


class Event:
    """One broadcast event (reference: ``Event`` in redqueen/opt_model.py).

    Attributes mirror the reference record: ``event_id`` (sequence number),
    ``cur_time`` (absolute event time), ``time_delta`` (time since the source's
    previous event), ``src_id``, ``sink_ids`` (feeds the post lands in).
    """

    __slots__ = ("event_id", "cur_time", "time_delta", "src_id", "sink_ids")

    def __init__(self, event_id, cur_time, time_delta, src_id, sink_ids):
        self.event_id = event_id
        self.cur_time = cur_time
        self.time_delta = time_delta
        self.src_id = src_id
        self.sink_ids = sink_ids

    def __repr__(self):
        return (
            f"Event(id={self.event_id}, t={self.cur_time:.6f}, "
            f"src={self.src_id}, sinks={list(self.sink_ids)})"
        )


class State:
    """Append-only world state (reference: ``State`` in redqueen/opt_model.py).

    Holds the current time and the event log; exports a pandas DataFrame with
    one row per (event, sink) — the schema the evaluation layer consumes
    (SURVEY.md section 3.4).
    """

    def __init__(self, start_time: float = 0.0):
        self.time = float(start_time)
        self.events: List[Event] = []

    def apply_event(self, event: Event) -> None:
        assert event.cur_time >= self.time, "events must be time-ordered"
        self.time = event.cur_time
        self.events.append(event)

    def get_dataframe(self) -> pd.DataFrame:
        """One row per (event, sink): columns event_id, t, time_delta, src_id, sink_id."""
        rows = []
        for ev in self.events:
            for sink_id in ev.sink_ids:
                rows.append(
                    (ev.event_id, ev.cur_time, ev.time_delta, ev.src_id, sink_id)
                )
        return pd.DataFrame(
            rows, columns=["event_id", "t", "time_delta", "src_id", "sink_id"]
        )


class Broadcaster:
    """Abstract posting policy (reference: ``Broadcaster`` base class).

    Protocol: ``init_state(...)`` wires the broadcaster into the simulation;
    ``get_next_event_time(event)`` is called with ``None`` once at start and
    then with every world event; it returns the broadcaster's next posting
    time (absolute), or +inf if it will not post.
    """

    def __init__(self, src_id, seed: int):
        self.src_id = src_id
        self.seed = seed
        self.random_state = np.random.RandomState(seed)
        self.start_time = 0.0
        self.end_time = np.inf
        self.sink_ids: List = []

    def init_state(self, start_time, all_sink_ids, follower_sink_ids, end_time):
        self.start_time = float(start_time)
        self.end_time = float(end_time)
        self.sink_ids = list(follower_sink_ids)

    def get_next_event_time(self, event: Optional[Event]) -> float:
        raise NotImplementedError


class Poisson(Broadcaster):
    """Constant-rate Poisson posting (reference: ``Poisson``).

    Variant with *precomputed* inter-arrival times: a block of exponentials is
    drawn up front and consumed sequentially (extended lazily if exhausted).
    Distributionally identical to ``Poisson2``.
    """

    _BLOCK = 256

    def __init__(self, src_id, seed, rate: float = 1.0):
        super().__init__(src_id, seed)
        self.rate = float(rate)
        self._deltas: np.ndarray = np.empty(0)
        self._idx = 0
        self._t_next: Optional[float] = None

    def _next_delta(self) -> float:
        if self._idx >= len(self._deltas):
            self._deltas = self.random_state.exponential(
                scale=1.0 / self.rate, size=self._BLOCK
            )
            self._idx = 0
        d = self._deltas[self._idx]
        self._idx += 1
        return float(d)

    def get_next_event_time(self, event: Optional[Event]) -> float:
        if event is None:
            self._t_next = self.start_time + self._next_delta()
        elif event.src_id == self.src_id:
            self._t_next = event.cur_time + self._next_delta()
        return self._t_next


class Poisson2(Broadcaster):
    """Constant-rate Poisson posting, incremental draw variant (reference:
    ``Poisson2``): one exponential is drawn per own event, at decision time."""

    def __init__(self, src_id, seed, rate: float = 1.0):
        super().__init__(src_id, seed)
        self.rate = float(rate)
        self._t_next: Optional[float] = None

    def get_next_event_time(self, event: Optional[Event]) -> float:
        if event is None:
            self._t_next = self.start_time + self.random_state.exponential(
                scale=1.0 / self.rate
            )
        elif event.src_id == self.src_id:
            self._t_next = event.cur_time + self.random_state.exponential(
                scale=1.0 / self.rate
            )
        return self._t_next


class Hawkes(Broadcaster):
    """Self-exciting posting (reference: ``Hawkes``).

    Intensity lambda(t) = l_0 + alpha * sum_{t_j < t} exp(-beta (t - t_j)) over
    the broadcaster's OWN past events. The next event time is sampled with
    Ogata's thinning (SURVEY.md section 3.3): propose from the current upper
    bound (valid because the exponential-kernel intensity decays between
    events), accept with probability lambda(t)/lambda_bar, tighten the bound on
    rejection.
    """

    def __init__(self, src_id, seed, l_0: float = 1.0, alpha: float = 1.0, beta: float = 2.0):
        super().__init__(src_id, seed)
        self.l_0 = float(l_0)
        self.alpha = float(alpha)
        self.beta = float(beta)
        # Excitation S(t) = alpha * sum exp(-beta (t - t_j)), tracked at _exc_t.
        self._exc = 0.0
        self._exc_t = 0.0
        self._t_next: Optional[float] = None

    def _intensity_at(self, t: float) -> float:
        return self.l_0 + self._exc * np.exp(-self.beta * (t - self._exc_t))

    def _sample_next(self, t_from: float) -> float:
        t = t_from
        while True:
            lbd_bar = self._intensity_at(t)
            t += self.random_state.exponential(scale=1.0 / lbd_bar)
            u = self.random_state.uniform()
            if u * lbd_bar <= self._intensity_at(t):
                return t

    def get_next_event_time(self, event: Optional[Event]) -> float:
        if event is None:
            self._exc = 0.0
            self._exc_t = self.start_time
            self._t_next = self._sample_next(self.start_time)
        elif event.src_id == self.src_id:
            t = event.cur_time
            self._exc = self._exc * np.exp(-self.beta * (t - self._exc_t)) + self.alpha
            self._exc_t = t
            self._t_next = self._sample_next(t)
        return self._t_next


class PiecewiseConst(Broadcaster):
    """Inhomogeneous Poisson with piecewise-constant rate (reference:
    ``PiecewiseConst``; models diurnal follower activity and the shape of the
    Karimi et al. offline baseline).

    ``change_times`` are segment boundaries (ascending, first <= start_time);
    ``rates[k]`` applies on [change_times[k], change_times[k+1]). Sampling is
    exact inversion: draw E ~ Exp(1) and push the cumulative hazard forward
    through the segments.
    """

    def __init__(self, src_id, seed, change_times: Sequence[float], rates: Sequence[float]):
        super().__init__(src_id, seed)
        self.change_times = np.asarray(change_times, dtype=np.float64)
        self.rates = np.asarray(rates, dtype=np.float64)
        assert len(self.change_times) == len(self.rates)
        assert np.all(np.diff(self.change_times) > 0)
        assert np.all(self.rates >= 0)
        self._t_next: Optional[float] = None

    def _sample_next(self, t_from: float) -> float:
        target = self.random_state.exponential()  # Exp(1) hazard target
        if t_from < self.change_times[0]:
            # Rate is 0 before the first segment: hazard starts accruing at
            # change_times[0], so the next event cannot land before it.
            k, t = 0, float(self.change_times[0])
        else:
            k = bisect.bisect_right(self.change_times, t_from) - 1
            t = t_from
        n = len(self.rates)
        while True:
            seg_end = self.change_times[k + 1] if k + 1 < n else np.inf
            rate = self.rates[k]
            if rate > 0:
                dt_needed = target / rate
                if t + dt_needed <= seg_end:
                    return t + dt_needed
                target -= rate * (seg_end - t)
            if not np.isfinite(seg_end):
                return np.inf  # zero tail rate: no more events
            t = seg_end
            k += 1

    def get_next_event_time(self, event: Optional[Event]) -> float:
        if event is None:
            self._t_next = self._sample_next(self.start_time)
        elif event.src_id == self.src_id:
            self._t_next = self._sample_next(event.cur_time)
        return self._t_next


class RealData(Broadcaster):
    """Replays a fixed array of real event timestamps (reference: ``RealData``,
    Twitter trace replay)."""

    def __init__(self, src_id, times: Sequence[float]):
        super().__init__(src_id, seed=0)
        self.times = np.sort(np.asarray(times, dtype=np.float64))
        self._ptr = 0

    def get_next_event_time(self, event: Optional[Event]) -> float:
        if event is None:
            self._ptr = int(np.searchsorted(self.times, self.start_time, side="left"))
        elif event.src_id == self.src_id:
            self._ptr += 1
        if self._ptr < len(self.times):
            return float(self.times[self._ptr])
        return np.inf


class Opt(Broadcaster):
    """RedQueen optimal online broadcaster (reference: ``Opt``; paper Alg. 1).

    Tracks the rank r_i(t) in each follower's feed and posts with intensity
    u*(t) = sum_i sqrt(s_i / q) * r_i(t). Because u* is piecewise constant
    between events, the next posting time is sampled by superposition: each
    rank increment of follower i spawns an Exp(sqrt(s_i/q)) candidate clock and
    the running minimum is kept; the broadcaster's own post resets every rank
    (and hence every candidate).
    """

    def __init__(self, src_id, seed, q: float = 1.0, s: Optional[Dict] = None):
        super().__init__(src_id, seed)
        if not q > 0:
            raise ValueError(f"Opt requires q > 0, got q={q}")
        self.q = float(q)
        self._s_spec = s  # sink_id -> significance; None = 1.0 everywhere
        self.r: Dict = {}
        self._t_candidate = np.inf

    def init_state(self, start_time, all_sink_ids, follower_sink_ids, end_time):
        super().init_state(start_time, all_sink_ids, follower_sink_ids, end_time)
        self.r = {i: 0 for i in self.sink_ids}
        self.s = {
            i: (1.0 if self._s_spec is None else float(self._s_spec[i]))
            for i in self.sink_ids
        }
        self._t_candidate = np.inf

    def get_next_event_time(self, event: Optional[Event]) -> float:
        if event is None:
            return self._t_candidate
        if event.src_id == self.src_id:
            for i in self.r:
                self.r[i] = 0
            self._t_candidate = np.inf
        else:
            t = event.cur_time
            for i in event.sink_ids:
                if i in self.r:
                    self.r[i] += 1
                    rate = np.sqrt(self.s[i] / self.q)
                    tau = self.random_state.exponential(scale=1.0 / rate)
                    self._t_candidate = min(self._t_candidate, t + tau)
        return self._t_candidate


class RMTPP(Broadcaster):
    """RMTPP neural-intensity broadcaster (BASELINE config 5) — the pure
    NumPy twin of ``models.rmtpp``: a GRU consumes the source's own
    inter-event gaps and the conditional intensity until the next own post
    is lambda(tau) = exp(a + w tau) with a = v.h + b, sampled exactly by
    inverse CDF (no thinning; same closed form as
    ``ops.sampling.rmtpp_next_delta``).

    ``weights`` is the flax param tree of ``models.rmtpp.RMTPPCell`` as
    plain nested dicts of NumPy arrays (convert a trained tree with
    ``jax.tree.map(np.asarray, w)``); the GRU recurrence mirrors flax's
    ``nn.GRUCell`` gate layout exactly (r/z gates without hidden bias, the
    candidate's hidden projection biased INSIDE the reset product), pinned
    to the jax cell by tests/test_rmtpp.py."""

    def __init__(self, src_id, seed, weights, hidden: int):
        super().__init__(src_id, seed)
        self.weights = weights
        self.hidden = int(hidden)
        self.h = np.zeros(self.hidden, np.float64)
        self._t_last = 0.0
        self._t_next = np.inf

    @staticmethod
    def _sigmoid(x):
        return 1.0 / (1.0 + np.exp(-x))

    def _gru(self, h, tau):
        g = self.weights["gru"]
        x = np.array([tau, np.log1p(tau)], np.float64)
        r = self._sigmoid(x @ g["ir"]["kernel"] + g["ir"]["bias"]
                          + h @ g["hr"]["kernel"])
        z = self._sigmoid(x @ g["iz"]["kernel"] + g["iz"]["bias"]
                          + h @ g["hz"]["kernel"])
        n = np.tanh(x @ g["in"]["kernel"] + g["in"]["bias"]
                    + r * (h @ g["hn"]["kernel"] + g["hn"]["bias"]))
        return (1.0 - z) * n + z * h

    def _head(self, h):
        a = float(h @ np.asarray(self.weights["v"]["kernel"])[:, 0]
                  + np.asarray(self.weights["v"]["bias"])[0])
        return a, float(np.asarray(self.weights["w"]))

    def _sample_delta(self):
        a, w = self._head(self.h)
        e = self.random_state.exponential()
        if abs(w) < 1e-6:
            return e * np.exp(-a)  # w ~ 0: constant intensity exp(a)
        z = w * e * np.exp(-a)
        # w < 0: finite total hazard exp(a)/(-w); a draw beyond it means
        # the process never fires again.
        return np.log1p(z) / w if z > -1.0 else np.inf

    def init_state(self, start_time, all_sink_ids, follower_sink_ids,
                   end_time):
        super().init_state(start_time, all_sink_ids, follower_sink_ids,
                           end_time)
        self.h = np.zeros(self.hidden, np.float64)
        self._t_last = self.start_time

    def get_next_event_time(self, event: Optional[Event]) -> float:
        if event is None:
            self._t_next = self.start_time + self._sample_delta()
        elif event.src_id == self.src_id:
            tau = event.cur_time - self._t_last
            self.h = self._gru(self.h, tau)
            self._t_last = event.cur_time
            self._t_next = event.cur_time + self._sample_delta()
        return self._t_next


class Manager:
    """Event-loop simulation driver (reference: ``Manager``).

    The hot loop (SURVEY.md section 3.1): ask every source for its next event
    time, pop the global minimum (ties broken by LOWEST source position — the
    rebuild's JAX argmin must match this exactly), apply the event to world
    state, and notify every source so it can re-decide.
    """

    def __init__(self, sources: Sequence[Broadcaster], sink_ids: Sequence,
                 edge_list: Dict, end_time: float, start_time: float = 0.0):
        self.sources = list(sources)
        self.sink_ids = list(sink_ids)
        self.edge_list = {k: list(v) for k, v in edge_list.items()}
        self.end_time = float(end_time)
        self.start_time = float(start_time)
        self.state = State(start_time)
        self._last_self_time = {s.src_id: None for s in self.sources}
        self._t_next: Optional[np.ndarray] = None  # lazily drawn on first run
        self._event_id = 0
        for src in self.sources:
            src.init_state(
                start_time, self.sink_ids, self.edge_list[src.src_id], end_time
            )

    def run_till(self, end_time: Optional[float] = None, max_events: Optional[int] = None) -> "Manager":
        """Run the event loop up to ``end_time`` (or ``max_events`` more
        events). Re-entrant: a second call continues from the current state
        rather than re-initializing the broadcasters."""
        T = self.end_time if end_time is None else float(end_time)
        if self._t_next is None:
            self._t_next = np.array(
                [src.get_next_event_time(None) for src in self.sources],
                dtype=np.float64,
            )
        t_next = self._t_next
        event_id = self._event_id
        events_this_call = 0
        while True:
            k = int(np.argmin(t_next))  # first occurrence = lowest source index
            t = t_next[k]
            if not np.isfinite(t) or t > T:
                break
            src = self.sources[k]
            prev = self._last_self_time[src.src_id]
            delta = t - (self.start_time if prev is None else prev)
            self._last_self_time[src.src_id] = t
            ev = Event(event_id, t, delta, src.src_id, self.edge_list[src.src_id])
            self.state.apply_event(ev)
            event_id += 1
            events_this_call += 1
            for j, s in enumerate(self.sources):
                t_next[j] = s.get_next_event_time(ev)
            if max_events is not None and events_this_call >= max_events:
                break
        self._event_id = event_id
        return self

    # Name kept for parity with the reference API surface.
    def run_dynamic(self, max_events: int) -> "Manager":
        return self.run_till(max_events=max_events)


class SimOpts:
    """Experiment config / manager factory (reference: ``SimOpts``).

    Bundles the follower set, the broadcaster->follower edge list, the "other
    source" specs, the horizon, and the Opt hyperparameters (q, s). Factory
    methods build a Manager with the controlled broadcaster swapped per policy
    — the reference's policy-pluggable seam (SURVEY.md section 1).
    """

    _WALL_REGISTRY: Dict[str, Callable] = {}

    def __init__(self, src_id, sink_ids, other_sources, end_time,
                 q: float = 1.0, s: Optional[Dict] = None, start_time: float = 0.0,
                 edge_list: Optional[Dict] = None):
        self.src_id = src_id
        self.sink_ids = list(sink_ids)
        # other_sources: list of (kind, kwargs) where kwargs contains src_id,
        # sink_ids (the feeds it posts into) and policy parameters.
        self.other_sources = list(other_sources)
        self.end_time = float(end_time)
        self.q = float(q)
        self.s = s
        self.start_time = float(start_time)
        # Controlled broadcaster posts to every sink unless an edge_list says otherwise.
        self.edge_list = edge_list

    def update(self, d: Dict) -> "SimOpts":
        kw = dict(
            src_id=self.src_id, sink_ids=self.sink_ids,
            other_sources=self.other_sources, end_time=self.end_time,
            q=self.q, s=self.s, start_time=self.start_time,
            edge_list=self.edge_list,
        )
        kw.update(d)
        return SimOpts(**kw)

    def _make_others(self) -> List[Broadcaster]:
        out = []
        for kind, kwargs in self.other_sources:
            kw = dict(kwargs)
            kw.pop("sink_ids", None)  # connectivity lives in the edge list
            kind_l = kind.lower()
            if kind_l == "poisson":
                out.append(Poisson(kw.pop("src_id"), kw.pop("seed"), **kw))
            elif kind_l == "poisson2":
                out.append(Poisson2(kw.pop("src_id"), kw.pop("seed"), **kw))
            elif kind_l == "hawkes":
                out.append(Hawkes(kw.pop("src_id"), kw.pop("seed"), **kw))
            elif kind_l == "piecewiseconst":
                out.append(PiecewiseConst(kw.pop("src_id"), kw.pop("seed"), **kw))
            elif kind_l == "realdata":
                out.append(RealData(kw.pop("src_id"), **kw))
            else:
                raise ValueError(f"unknown other-source kind: {kind}")
        return out

    def _other_edges(self) -> Dict:
        edges = {}
        for kind, kwargs in self.other_sources:
            edges[kwargs["src_id"]] = list(kwargs.get("sink_ids", self.sink_ids))
        return edges

    def _manager(self, our: Broadcaster) -> Manager:
        edge_list = dict(self._other_edges())
        if self.edge_list is not None:
            edge_list.update({k: list(v) for k, v in self.edge_list.items()})
        edge_list.setdefault(self.src_id, list(self.sink_ids))
        sources = [our] + self._make_others()
        return Manager(sources, self.sink_ids, edge_list, self.end_time,
                       self.start_time)

    def create_manager_with_opt(self, seed: int) -> Manager:
        return self._manager(Opt(self.src_id, seed, q=self.q, s=self.s))

    def create_manager_with_poisson(self, seed: int, rate: float) -> Manager:
        return self._manager(Poisson(self.src_id, seed, rate=rate))

    def create_manager_with_piecewise_const(self, seed: int, change_times, rates) -> Manager:
        return self._manager(
            PiecewiseConst(self.src_id, seed, change_times=change_times, rates=rates)
        )

    def create_manager_with_times(self, times) -> Manager:
        """RealData replay of the controlled broadcaster (reference:
        ``create_manager_with_times`` — real user posting trace)."""
        return self._manager(RealData(self.src_id, times=times))

    def create_manager_with_rmtpp(self, seed: int, weights,
                                  hidden: int) -> Manager:
        """RMTPP neural-intensity controlled broadcaster (BASELINE config
        5); ``weights`` = the flax RMTPPCell tree as nested NumPy dicts."""
        return self._manager(
            RMTPP(self.src_id, seed, weights=weights, hidden=hidden)
        )

    def create_manager_with_broadcaster(self, broadcaster: Broadcaster) -> Manager:
        """Open seam: any Broadcaster implementation (the reference's Opt-subclass
        registration point, per BASELINE.json north star)."""
        assert broadcaster.src_id == self.src_id
        return self._manager(broadcaster)
