"""Evaluation and persistence utilities.

- ``metrics`` — on-device (JAX) feed-rank metrics over event logs
  (reference: ``redqueen/utils.py`` re-implemented as one scan pass).
- ``metrics_pandas`` — the backend-agnostic pandas twin consuming the
  reference-schema DataFrame (``time_in_top_k`` / ``average_rank`` / rank
  integrals / budget helpers).
- ``dataframe`` — event-buffer -> reference-schema DataFrame export
  (reference: ``State.get_dataframe``).
- ``checkpoint`` — orbax round-trip of sweep state and learned-policy
  weights (no reference counterpart; SURVEY.md section 5).
"""

from . import dataframe, metrics, metrics_pandas  # noqa: F401

__all__ = ["dataframe", "metrics", "metrics_pandas", "checkpoint"]


def __getattr__(name):
    # orbax import is slow; load the checkpoint module on first use only.
    # (importlib, not `from . import`: a from-import would re-probe this
    # __getattr__ before the submodule binds and recurse forever.)
    if name == "checkpoint":
        import importlib

        module = importlib.import_module(".checkpoint", __name__)
        globals()["checkpoint"] = module
        return module
    raise AttributeError(name)
