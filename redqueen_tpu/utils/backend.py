"""Default-backend liveness probing for the axon TPU tunnel.

The single real TPU chip in this environment sits behind a remote tunnel; a
killed/timeouted TPU process can wedge the tunnel so that ``jax.devices()``
HANGS forever rather than raising (observed as the round-1 rc=124
MULTICHIP failure and the all-session bench fallback). An in-process
try/except cannot catch a hang, so the probe runs ``jax.devices()`` in a
SUBPROCESS with a deadline. Both ``bench.py`` and ``__graft_entry__.py``
share this helper so tunnel-behavior fixes land in exactly one place.
"""

from __future__ import annotations

import subprocess
import sys
from typing import Callable, Optional, Tuple

__all__ = ["probe_default_backend", "parse_last_json_line"]


def parse_last_json_line(text: Optional[str], require_ok: bool = False):
    """Parse the child-subprocess stdout protocol shared by ``bench.py``
    (``--as-engine`` children) and ``tools/tpu_watcher.py``: the last stdout
    line that is a JSON dict is the result. Returns that dict or ``None``.
    With ``require_ok``, only dicts carrying a truthy ``"ok"`` key count —
    one parser so the two callers cannot drift."""
    import json

    for line in reversed((text or "").strip().splitlines()):
        try:
            obj = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(obj, dict) and (not require_ok or obj.get("ok")):
            return obj
    return None


def probe_default_backend(
    deadline_s: float = 120.0, log: Optional[Callable] = None
) -> Tuple[bool, int, str]:
    """Probe the DEFAULT jax backend in a subprocess with a deadline.

    Returns ``(alive, n_devices, platform)``; ``alive`` is True iff backend
    init completed within the deadline. Never initializes a backend in the
    calling process.
    """
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print('PROBE_OK', len(d), d[0].platform)"],
            timeout=deadline_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        if log:
            log(f"default backend probe hung > {deadline_s}s; assuming TPU "
                f"tunnel is down")
        return False, 0, ""
    # Parse defensively: jax/plugin init may print banners around our line.
    for line in reversed(r.stdout.strip().splitlines() if r.stdout else []):
        parts = line.split()
        if len(parts) == 3 and parts[0] == "PROBE_OK" and r.returncode == 0:
            return True, int(parts[1]), parts[2]
    if log:
        tail = r.stderr.strip().splitlines()[-1] if r.stderr.strip() else ""
        log(f"default backend probe failed (rc={r.returncode}): {tail}")
    return False, 0, ""
