"""Default-backend liveness probing for the axon TPU tunnel.

The single real TPU chip in this environment sits behind a remote tunnel; a
killed/timeouted TPU process can wedge the tunnel so that ``jax.devices()``
HANGS forever rather than raising (observed as the round-1 rc=124
MULTICHIP failure and the all-session bench fallback). An in-process
try/except cannot catch a hang, so the probe runs ``jax.devices()`` in a
SUBPROCESS with a deadline. Both ``bench.py`` and ``__graft_entry__.py``
share this helper so tunnel-behavior fixes land in exactly one place.
"""

from __future__ import annotations

import subprocess
import sys
import time
from typing import Callable, Optional, Sequence, Tuple

__all__ = ["probe_default_backend", "parse_last_json_line",
           "default_backend_alive", "ensure_live_backend"]


def _stderr_log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def parse_last_json_line(text: Optional[str], require_ok: bool = False):
    """Parse the child-subprocess stdout protocol shared by ``bench.py``
    (``--as-engine`` children) and ``tools/tpu_watcher.py``: the last stdout
    line that is a JSON dict is the result. Returns that dict or ``None``.
    With ``require_ok``, only dicts carrying a truthy ``"ok"`` key count —
    one parser so the two callers cannot drift."""
    import json

    for line in reversed((text or "").strip().splitlines()):
        try:
            obj = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(obj, dict) and (not require_ok or obj.get("ok")):
            return obj
    return None


def default_backend_alive(
    log: Optional[Callable] = None,
    deadlines: Sequence[float] = (90.0, 40.0),
    backoff_s: float = 15.0,
) -> Tuple[bool, int, str]:
    """THE liveness policy for the default backend, shared by bench.py and
    every harness entry point (one policy, one place — two entry points
    must never disagree about liveness at the same moment). The tunnel was
    down for all of rounds 1-2 and can recover between hangs, so one
    failed probe gets one shorter retry — total worst case ~145s, bounded
    so a dead tunnel can never eat a driver timeout. Returns the last
    probe's ``(alive, n_devices, platform)``."""
    for attempt, deadline_s in enumerate(deadlines):
        alive, n, plat = probe_default_backend(deadline_s, log=log)
        if alive:
            if log:
                log(f"default backend alive: {n} x {plat}")
            return True, n, plat
        if attempt + 1 < len(deadlines):
            if log:
                log(f"probe attempt {attempt + 1}/{len(deadlines)} failed; "
                    f"retrying in {backoff_s:.0f}s")
            time.sleep(backoff_s)
    return False, 0, ""


def ensure_live_backend(log: Callable = _stderr_log,
                        deadlines: Sequence[float] = (90.0, 40.0)) -> str:
    """Probe the DEFAULT jax backend (``default_backend_alive`` policy) and
    flip this process to CPU when it is down
    (``jax.config.update("jax_platforms", "cpu")``).

    Every harness/experiment entry point that would otherwise touch the
    default backend unguarded calls this first: a wedged axon tunnel HANGS
    ``jax.devices()`` forever, so without the probe a script launched
    without ``--cpu`` simply never starts (observed: benchmarks/run.py
    wedged for 20 minutes on one axon-init line). Must run before the
    first backend touch in the process. Returns the platform that will be
    used ("cpu" after a fallback) — record it in any artifact the caller
    writes, so a fallback can never pass as a TPU measurement."""
    import jax

    alive, n, plat = default_backend_alive(log=log, deadlines=deadlines)
    if alive:
        return plat
    if log:
        log("default backend did not initialize within the probe deadlines "
            "(tunnel down/wedged); falling back to CPU")
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def probe_default_backend(
    deadline_s: float = 120.0, log: Optional[Callable] = None
) -> Tuple[bool, int, str]:
    """Probe the DEFAULT jax backend in a subprocess with a deadline.

    Returns ``(alive, n_devices, platform)``; ``alive`` is True iff backend
    init completed within the deadline. Never initializes a backend in the
    calling process.
    """
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print('PROBE_OK', len(d), d[0].platform)"],
            timeout=deadline_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        if log:
            log(f"default backend probe hung > {deadline_s}s; assuming TPU "
                f"tunnel is down")
        return False, 0, ""
    # Parse defensively: jax/plugin init may print banners around our line.
    for line in reversed(r.stdout.strip().splitlines() if r.stdout else []):
        parts = line.split()
        if len(parts) == 3 and parts[0] == "PROBE_OK" and r.returncode == 0:
            return True, int(parts[1]), parts[2]
    if log:
        tail = r.stderr.strip().splitlines()[-1] if r.stderr.strip() else ""
        log(f"default backend probe failed (rc={r.returncode}): {tail}")
    return False, 0, ""
