"""Pandas evaluation layer: feed-rank metrics over the event-log DataFrame.

Parity target: ``redqueen/utils.py`` in MPI-SWS/RedQueen (mount empty at build
time — see SURVEY.md section 0; inventory from SURVEY.md section 2 items
11–14: ``rank_of_src_in_df``, ``time_in_top_k``, ``average_rank``, loss/budget
helpers). This layer is backend-agnostic by construction: it consumes ONLY the
(event, sink) DataFrame schema emitted both by the NumPy oracle
(``State.get_dataframe``) and by the JAX event buffer export
(``redqueen_tpu.utils.dataframe.events_to_dataframe``), per the BASELINE north
star ("without touching the evaluation code in utils.py").

Conventions (shared with the JAX metric kernels in
``redqueen_tpu.utils.metrics``):
- r_i(t) = number of posts by OTHER sources into sink i's feed since ``src_id``
  last posted there; r_i(start_time) = 0.
- ``time_in_top_k`` returns the PER-SINK MEAN of the integral
  int_start^end 1[r_i(t) < K] dt.
- ``average_rank`` returns the per-sink mean of int r_i(t) dt / (end - start).
"""

from __future__ import annotations

from typing import Dict

import numpy as np
import pandas as pd

__all__ = [
    "rank_of_src_in_df",
    "time_in_top_k",
    "average_rank",
    "int_rank_dt",
    "int_rank2_dt",
    "num_posts_of_src",
    "is_sorted",
]


def is_sorted(x) -> bool:
    """True iff x is non-decreasing (reference: ``is_sorted`` helper)."""
    x = np.asarray(x)
    return bool(np.all(x[1:] >= x[:-1]))


def rank_of_src_in_df(df: pd.DataFrame, src_id) -> Dict:
    """Per-sink rank step function of ``src_id`` (reference:
    ``rank_of_src_in_df``).

    Returns {sink_id: (times, ranks)} where ``ranks[j]`` holds on
    [times[j], times[j+1]). The first entry is the first feed event; the rank
    before any feed activity is 0 by convention.
    """
    out = {}
    for sink_id, g in df.groupby("sink_id", sort=True):
        g = g.sort_values(["t", "event_id"], kind="mergesort")
        times = g["t"].to_numpy()
        own = (g["src_id"] == src_id).to_numpy()
        # Vectorized "others since our last post": with c = running count
        # of other-source events (inclusive), the rank at event j is
        # c[j] - c[last own event <= j] (0 baseline before any own post),
        # and 0 at own posts. Equivalent to the per-event loop
        # r = 0 if own else r + 1, at numpy speed for big logs.
        c = np.cumsum(~own)
        base = np.maximum.accumulate(np.where(own, c, 0))
        ranks = np.where(own, 0, c - base).astype(np.int64)
        out[sink_id] = (times, ranks)
    return out


_EMPTY = (np.empty(0), np.empty(0, dtype=np.int64))


def _per_sink_integral(df: pd.DataFrame, src_id, start_time: float,
                       end_time: float, f, sink_ids=None) -> Dict:
    """int_start^end f(r_i(t)) dt per sink, r piecewise-constant.

    The rank step function is built from the FULL event history, then
    integrated over the [start_time, end_time] window only — a rank built up
    before the window carries into it. Pass ``sink_ids`` (e.g.
    ``SimOpts.sink_ids``) so followers whose feeds received no events still
    contribute their full-horizon rank-0 value; inferring sinks from the
    DataFrame alone would silently drop them and bias the per-sink mean.
    """
    rank_ts = rank_of_src_in_df(df, src_id)
    if sink_ids is None:
        sinks = sorted(rank_ts.keys())
    else:
        sinks = list(sink_ids)
    out = {}
    for sink_id in sinks:
        times, ranks = rank_ts.get(sink_id, _EMPTY)
        inside = (times > start_time) & (times < end_time)
        # Rank in effect at start_time: value of the last event at t <= start.
        idx = int(np.searchsorted(times, start_time, side="right")) - 1
        r0 = int(ranks[idx]) if idx >= 0 else 0
        knots = np.concatenate(([start_time], times[inside], [end_time]))
        vals = np.concatenate(([r0], ranks[inside]))
        out[sink_id] = float(np.sum(np.diff(knots) * f(vals.astype(np.float64))))
    return out


def time_in_top_k(df: pd.DataFrame, K: int, end_time: float,
                  src_id, start_time: float = 0.0,
                  per_sink: bool = False, sink_ids=None):
    """Mean over sinks of int 1[r_i(t) < K] dt (reference: ``time_in_top_k`` —
    the BASELINE quality metric at K=1)."""
    per = _per_sink_integral(
        df, src_id, start_time, end_time,
        lambda r: (r < K).astype(np.float64), sink_ids=sink_ids,
    )
    if per_sink:
        return per
    return float(np.mean(list(per.values()))) if per else 0.0


def int_rank_dt(df: pd.DataFrame, end_time: float, src_id,
                start_time: float = 0.0, per_sink: bool = False, sink_ids=None):
    """Mean over sinks of int r_i(t) dt (reference: rank-over-time integral)."""
    per = _per_sink_integral(df, src_id, start_time, end_time, lambda r: r,
                             sink_ids=sink_ids)
    if per_sink:
        return per
    return float(np.mean(list(per.values()))) if per else 0.0


def int_rank2_dt(df: pd.DataFrame, end_time: float, src_id,
                 start_time: float = 0.0, per_sink: bool = False, sink_ids=None):
    """Mean over sinks of int r_i(t)^2 dt (reference: quadratic loss term)."""
    per = _per_sink_integral(df, src_id, start_time, end_time, lambda r: r * r,
                             sink_ids=sink_ids)
    if per_sink:
        return per
    return float(np.mean(list(per.values()))) if per else 0.0


def average_rank(df: pd.DataFrame, end_time: float, src_id,
                 start_time: float = 0.0, sink_ids=None) -> float:
    """Time-averaged mean rank: int_rank_dt / (end - start) (reference:
    ``average_rank``)."""
    return int_rank_dt(df, end_time, src_id, start_time, sink_ids=sink_ids) / (
        end_time - start_time
    )


def num_posts_of_src(df: pd.DataFrame, src_id) -> int:
    """Number of posts by ``src_id`` (budget check; reference: int u dt
    helper — for a counting realization the integral IS the post count)."""
    return int(df[df["src_id"] == src_id]["event_id"].nunique())
