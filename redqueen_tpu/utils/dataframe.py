"""Event-log -> pandas export: the backend-agnostic observability contract.

The reference's single observable artifact is the (event, sink) DataFrame
from ``State.get_dataframe()`` (SURVEY.md section 5 "observability"); the
BASELINE north star requires the TPU backend to feed the *unchanged* pandas
evaluation layer. This module turns the device event buffer (times, srcs)
plus the adjacency into exactly that schema:
``event_id, t, time_delta, src_id, sink_id`` — one row per (event, sink).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

__all__ = ["events_to_dataframe"]


def events_to_dataframe(times, srcs, adj, src_ids=None,
                        sink_ids=None, start_time: float = 0.0) -> pd.DataFrame:
    """Expand one component's event log to the reference DataFrame schema.

    ``times`` [E] / ``srcs`` [E] (invalid tail: src == -1), ``adj`` [S, F].
    ``src_ids``/``sink_ids`` optionally relabel rows/columns to external ids
    (the oracle's arbitrary hashable ids); defaults are positional indices.
    ``time_delta`` is the gap since the same source's previous post, measured
    from ``start_time`` (the simulation start) for a source's first post
    (reference Event semantics, SURVEY.md section 2 item 1).
    """
    times = np.asarray(times, np.float64)
    srcs = np.asarray(srcs, np.int64)
    adj = np.asarray(adj, bool)
    valid = srcs >= 0
    times, srcs = times[valid], srcs[valid]
    S = adj.shape[0]
    src_ids = np.arange(S) if src_ids is None else np.asarray(src_ids)
    sink_ids = (
        np.arange(adj.shape[1]) if sink_ids is None else np.asarray(sink_ids)
    )

    # time_delta: per-source consecutive gaps (first post from start_time),
    # vectorized as a grouped shift — the export must stay fast at the
    # millions-of-events sweep scale this module is the contract for.
    prev = pd.Series(times).groupby(srcs).shift()
    deltas = times - prev.fillna(float(start_time)).to_numpy()

    # (event, sink) expansion via a CSR-style gather over per-source sink
    # lists: no per-event Python work.
    indptr = np.zeros(S + 1, np.int64)
    indptr[1:] = adj.sum(axis=1).cumsum()
    # row-major flatnonzero is already grouped by source row == CSR order
    indices = np.flatnonzero(adj) % adj.shape[1]
    counts = np.diff(indptr)[srcs]  # sinks per event
    rows = np.repeat(np.arange(len(times)), counts)
    total = int(counts.sum())
    if total:
        starts = np.repeat(indptr[srcs], counts)
        offset = np.arange(total) - np.repeat(
            np.concatenate(([0], counts.cumsum()[:-1])), counts
        )
        sink_idx = indices[starts + offset]
    else:
        sink_idx = np.empty(0, np.int64)
    return pd.DataFrame(
        {
            "event_id": rows,
            "t": times[rows],
            "time_delta": deltas[rows],
            "src_id": src_ids[srcs[rows]],
            "sink_id": sink_ids[sink_idx],
        }
    )
