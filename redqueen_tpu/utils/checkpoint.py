"""Checkpoint/resume for long sweeps and learned policies (SURVEY.md
section 5: the reference has none — runs are minutes-long and seeded — but
the rebuild's long sweeps and RMTPP training are restartable via
orbax-checkpoint).

Three checkpointable artifacts, all plain pytrees:
- RMTPP weights (+ optax state) from ``models.rmtpp.fit``;
- a ``SimState`` carry (resume a long-horizon simulation with ``sim.resume``);
- sweep results (metric pytrees accumulated across seed/q grids).

Read paths (``restore``, ``latest_step``) NEVER create directories: a
typo'd path must raise/return-None, not leave an empty checkpoint tree
that a later writer mistakes for a real one.  Writes register with
``runtime.preempt`` so a SIGTERM mid-save waits out the in-flight orbax
write before the process exits.

Corrupt-tolerant recovery: orbax's own write path is atomic-ish (tmp dir
then rename), but nothing protects a LANDED step from truncation/bit rot,
and a multi-hour sweep must resume from the newest step that actually
restores — not die on the newest directory present.
:func:`latest_valid_step` scans backward from the newest step, proving
each candidate by restoring it; a step that fails is QUARANTINED
(renamed ``<step>.corrupt-<ts>`` + structured report, via
``runtime.integrity``) so no later reader trusts it either.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from ..runtime import integrity as _integrity
from ..runtime import preempt as _preempt

__all__ = ["save", "restore", "latest_step", "latest_valid_step"]

# Managers with a potentially in-flight async save; the preemption flusher
# waits these out so a SIGTERM never truncates an orbax step directory.
_IN_FLIGHT: set = set()


@_preempt.register_flush
def _flush_in_flight_saves() -> None:
    for mgr in list(_IN_FLIGHT):
        try:
            mgr.wait_until_finished()
        except Exception:  # noqa: BLE001 — flush must not block exit
            pass


def _manager(path: str, create: bool) -> ocp.CheckpointManager:
    """``create=True`` only on the write path; read paths must never
    materialize an empty checkpoint directory (the failure mode: a
    missing-path ``restore`` leaving behind a dir that a later
    ``latest_step`` call reads as an empty-but-real checkpoint)."""
    return ocp.CheckpointManager(
        os.path.abspath(path),
        options=ocp.CheckpointManagerOptions(max_to_keep=3, create=create),
    )


def save(path: str, step: int, tree: Any) -> None:
    """Save a pytree (weights/opt state/SimState/metrics) under ``path`` at
    ``step``. Keeps the last 3 steps."""
    mgr = _manager(path, create=True)
    _IN_FLIGHT.add(mgr)
    try:
        mgr.save(step, args=ocp.args.StandardSave(tree))
        mgr.wait_until_finished()
    finally:
        _IN_FLIGHT.discard(mgr)
        mgr.close()


def latest_step(path: str) -> Optional[int]:
    if not os.path.isdir(path):
        return None
    mgr = _manager(path, create=False)
    step = mgr.latest_step()
    mgr.close()
    return step


def _step_dirs(path: str):
    """Step numbers present on disk, newest first — by direct listing,
    not via a manager: a corrupt step must be enumerable even when orbax
    metadata reads would die on it.  Only pure-integer names count
    (orbax tmp dirs and quarantined ``N.corrupt-*`` entries are not
    steps)."""
    steps = []
    for name in sorted(os.listdir(path)):
        if name.isdigit() and os.path.isdir(os.path.join(path, name)):
            steps.append(int(name))
    return sorted(steps, reverse=True)


def latest_valid_step(path: str, like: Any = None,
                      quarantine: bool = True) -> Optional[int]:
    """The newest step that actually RESTORES, scanning backward past
    torn/corrupt ones.  Each failing candidate is quarantined (renamed
    ``<path>/<step>.corrupt-<ts>`` with a structured report beside it —
    set ``quarantine=False`` to only skip) so the bad bytes leave the
    read path without being destroyed.  Returns None when no step
    verifies (or the path is missing): the caller starts from scratch —
    never from a checkpoint that cannot be proven whole.

    Only DESERIALIZATION failures condemn a step: when ``like`` is given
    and the targeted restore fails, a raw (target-less) restore
    disambiguates — if the bytes deserialize, the mismatch is the
    caller's ``like`` tree (drifted config), the step counts as valid
    and is never quarantined.

    Cost note: the proof IS a full restore, so ``restore(path, step)``
    afterwards reads the winning step a second time — paid once per
    process start, the price of never resuming from unproven bytes."""
    if not os.path.isdir(path):
        return None
    for step in _step_dirs(path):
        try:
            # A full restore IS the verification: metadata, manifest and
            # every array chunk must deserialize.  Fresh manager per
            # candidate — a cached step listing would go stale the moment
            # a newer sibling is quarantined.
            restore(path, step=step, like=like)
            return step
        except Exception as e:  # noqa: BLE001 — classified below
            if like is not None:
                # Disambiguate before condemning the bytes: a RAW
                # restore (no target tree) proves on-disk integrity.
                # If it succeeds, the failure above was the CALLER's
                # ``like`` (drifted model config, wrong dtypes) — the
                # step is whole and must not be quarantined.
                try:
                    restore(path, step=step)
                    return step
                except Exception as e2:  # noqa: BLE001
                    e = e2
            step_dir = os.path.join(path, str(step))
            if quarantine and os.path.isdir(step_dir):
                _integrity.quarantine(
                    step_dir, "checkpoint step failed to restore",
                    f"step {step}: {type(e).__name__}: {e}")
    return None


def restore(path: str, step: Optional[int] = None, like: Any = None):
    """Restore the pytree saved at ``step`` (default: latest). ``like``
    optionally provides the target structure/dtypes (required to restore
    custom pytree nodes such as SimState).  Raises ``FileNotFoundError``
    on a missing path WITHOUT creating anything."""
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint under {path}")
    mgr = _manager(path, create=False)
    try:
        step = mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {path}")
        if like is None:
            # Explicit StandardRestore: a bare mgr.restore(step) only
            # works in the process that SAVED (orbax registers the item
            # handler at save time) — a resuming run is a fresh process.
            out = mgr.restore(step, args=ocp.args.StandardRestore())
        else:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            out = mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    finally:
        mgr.close()
    return out
