"""Checkpoint/resume for long sweeps and learned policies (SURVEY.md
section 5: the reference has none — runs are minutes-long and seeded — but
the rebuild's long sweeps and RMTPP training are restartable via
orbax-checkpoint).

Three checkpointable artifacts, all plain pytrees:
- RMTPP weights (+ optax state) from ``models.rmtpp.fit``;
- a ``SimState`` carry (resume a long-horizon simulation with ``sim.resume``);
- sweep results (metric pytrees accumulated across seed/q grids).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

__all__ = ["save", "restore", "latest_step"]


def _manager(path: str) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(path),
        options=ocp.CheckpointManagerOptions(max_to_keep=3, create=True),
    )


def save(path: str, step: int, tree: Any) -> None:
    """Save a pytree (weights/opt state/SimState/metrics) under ``path`` at
    ``step``. Keeps the last 3 steps."""
    mgr = _manager(path)
    mgr.save(step, args=ocp.args.StandardSave(tree))
    mgr.wait_until_finished()
    mgr.close()


def latest_step(path: str) -> Optional[int]:
    if not os.path.isdir(path):
        return None
    mgr = _manager(path)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore(path: str, step: Optional[int] = None, like: Any = None):
    """Restore the pytree saved at ``step`` (default: latest). ``like``
    optionally provides the target structure/dtypes (required to restore
    custom pytree nodes such as SimState)."""
    mgr = _manager(path)
    step = mgr.latest_step() if step is None else step
    if step is None:
        mgr.close()
        raise FileNotFoundError(f"no checkpoint under {path}")
    if like is None:
        out = mgr.restore(step)
    else:
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
        out = mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    mgr.close()
    return out
