"""Checkpoint/resume for long sweeps and learned policies (SURVEY.md
section 5: the reference has none — runs are minutes-long and seeded — but
the rebuild's long sweeps and RMTPP training are restartable via
orbax-checkpoint).

Three checkpointable artifacts, all plain pytrees:
- RMTPP weights (+ optax state) from ``models.rmtpp.fit``;
- a ``SimState`` carry (resume a long-horizon simulation with ``sim.resume``);
- sweep results (metric pytrees accumulated across seed/q grids).

Read paths (``restore``, ``latest_step``) NEVER create directories: a
typo'd path must raise/return-None, not leave an empty checkpoint tree
that a later writer mistakes for a real one.  Writes register with
``runtime.preempt`` so a SIGTERM mid-save waits out the in-flight orbax
write before the process exits.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from ..runtime import preempt as _preempt

__all__ = ["save", "restore", "latest_step"]

# Managers with a potentially in-flight async save; the preemption flusher
# waits these out so a SIGTERM never truncates an orbax step directory.
_IN_FLIGHT: set = set()


@_preempt.register_flush
def _flush_in_flight_saves() -> None:
    for mgr in list(_IN_FLIGHT):
        try:
            mgr.wait_until_finished()
        except Exception:  # noqa: BLE001 — flush must not block exit
            pass


def _manager(path: str, create: bool) -> ocp.CheckpointManager:
    """``create=True`` only on the write path; read paths must never
    materialize an empty checkpoint directory (the failure mode: a
    missing-path ``restore`` leaving behind a dir that a later
    ``latest_step`` call reads as an empty-but-real checkpoint)."""
    return ocp.CheckpointManager(
        os.path.abspath(path),
        options=ocp.CheckpointManagerOptions(max_to_keep=3, create=create),
    )


def save(path: str, step: int, tree: Any) -> None:
    """Save a pytree (weights/opt state/SimState/metrics) under ``path`` at
    ``step``. Keeps the last 3 steps."""
    mgr = _manager(path, create=True)
    _IN_FLIGHT.add(mgr)
    try:
        mgr.save(step, args=ocp.args.StandardSave(tree))
        mgr.wait_until_finished()
    finally:
        _IN_FLIGHT.discard(mgr)
        mgr.close()


def latest_step(path: str) -> Optional[int]:
    if not os.path.isdir(path):
        return None
    mgr = _manager(path, create=False)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore(path: str, step: Optional[int] = None, like: Any = None):
    """Restore the pytree saved at ``step`` (default: latest). ``like``
    optionally provides the target structure/dtypes (required to restore
    custom pytree nodes such as SimState).  Raises ``FileNotFoundError``
    on a missing path WITHOUT creating anything."""
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint under {path}")
    mgr = _manager(path, create=False)
    try:
        step = mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {path}")
        if like is None:
            out = mgr.restore(step)
        else:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            out = mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    finally:
        mgr.close()
    return out
