"""Utilization model for the event-scan engines (the MFU analogue).

An event simulator does almost no FLOPs — its roofline axis is HBM
traffic, not matmul throughput. The quantity that says whether a measured
events/s number is 5% or 95% of what the chip can do is the achieved
bytes/s of the sequential scan against the device's peak memory bandwidth
(SURVEY.md section 5: the profiling harness is first-class; round-4
verdict item "what's missing" 4).

Model (documented so every emitted number is decomposable):

- A *step* is one sequential iteration of the event scan: every lane
  advances by (at most) one event. The scan carry (``SimState``) must be
  read and written once per step; the policy parameters (``SourceParams``)
  and adjacency are read once per step; one (time f32, src i32) log slot
  per lane is written per step. Counter-addressed PRNG draws touch no
  memory. This is the MINIMUM traffic the algorithm requires if nothing
  stays resident — XLA/Mosaic keeping the carry in registers/VMEM can
  only *reduce* real HBM traffic below the model, so
  ``hbm_frac = modeled_bytes/s / peak`` is an upper bound on how close
  the scan is to the bandwidth wall, and ``1 - hbm_frac`` is a lower
  bound on the latency/dispatch headroom. (That split is exactly the
  DESIGN.md decomposition question: the full-shape TPU scan measured
  8.99M ev/s in r04 — is it bandwidth-bound or per-step latency-bound?)

Peak bandwidths are public per-generation figures; the device kind string
comes from ``jax.Device.device_kind``. Unknown kinds (and the CPU
fallback backend, whose DRAM peak this 1-core box does not advertise)
report ``hbm_peak_gbps: null`` and ``hbm_frac: null`` rather than a
made-up denominator.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = [
    "hbm_peak_gbps",
    "pytree_nbytes",
    "scan_step_traffic_bytes",
    "roofline_fields",
]

# Public per-generation peak HBM bandwidth, GB/s (vendor-published specs).
# Matched case-insensitively as substrings of jax.Device.device_kind
# (e.g. "TPU v4", "TPU v5 lite", "TPU v5p"); longest match wins so
# "v5p" is tried before "v5".
_HBM_PEAK_GBPS = {
    "v2": 700.0,
    "v3": 900.0,
    "v4": 1228.0,
    "v5 lite": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v6 lite": 1638.0,
    "v6e": 1638.0,
}


def hbm_peak_gbps(device_kind: str) -> Optional[float]:
    """Peak HBM bandwidth for a device-kind string, or None if unknown."""
    kind = (device_kind or "").lower()
    best = None
    for pat, gbps in _HBM_PEAK_GBPS.items():
        if pat in kind and (best is None or len(pat) > len(best[0])):
            best = (pat, gbps)
    return best[1] if best else None


def pytree_nbytes(tree) -> int:
    """Total bytes of every array leaf in a pytree (shape metadata only —
    works on jax.ShapeDtypeStruct trees from ``jax.eval_shape``, so no
    device memory is touched)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


def scan_step_traffic_bytes(cfg, params, adj) -> int:
    """Modeled HBM bytes one sequential scan step must move for ONE
    dispatch of the given (possibly batched) component shape.

    ``params``/``adj`` are the arrays actually passed to the engine
    (leading batch axis included) — the state footprint is derived with
    ``jax.eval_shape`` on the real ``init_state``, so the model can never
    drift from the carry the kernel actually materializes.
    """
    import jax
    import jax.random as jr

    from ..ops.scan_core import init_state

    batched = getattr(params.kind, "ndim", 1) == 2

    def init(p, a):
        # Runs only under jax.eval_shape below: the key's VALUE is never
        # materialized, only its shape/dtype — any constant works.
        key = jr.PRNGKey(0)  # rqlint: disable=RQ502
        if batched:
            keys = jax.vmap(jr.PRNGKey)(
                np.zeros((p.kind.shape[0],), np.int32))
            return jax.vmap(lambda pp, aa, kk: init_state(cfg, pp, aa, kk))(
                p, a, keys)
        return init_state(cfg, p, a, key)

    state = jax.eval_shape(init, params, adj)
    state_b = pytree_nbytes(state)
    params_b = pytree_nbytes(params) + pytree_nbytes(adj)
    n_lanes = params.kind.shape[0] if batched else 1
    log_b = n_lanes * 8  # one (f32 time, i32 src) slot per lane per step
    # read state + write state + read params/adj + write log slot
    return 2 * state_b + params_b + log_b


def roofline_fields(n_steps: int, secs: float, bytes_per_step: int,
                    platform: str, device_kind: str) -> dict:
    """The utilization block for a bench result line.

    ``n_steps`` = sequential scan steps executed (summed over slabs);
    ``secs`` = the timed best-of-N wall for those steps; ``bytes_per_step``
    from :func:`scan_step_traffic_bytes` (per dispatch — slab-level when
    the batch runs in slabs).
    """
    if n_steps <= 0 or not np.isfinite(secs) or secs <= 0:
        return {}
    step_ns = secs / n_steps * 1e9
    gbps = bytes_per_step * n_steps / secs / 1e9
    peak = hbm_peak_gbps(device_kind) if platform == "tpu" else None
    return {
        "steps": int(n_steps),
        "step_ns": round(step_ns, 1),
        "bytes_per_step": int(bytes_per_step),
        "hbm_gbps": round(gbps, 3),
        "hbm_peak_gbps": peak,
        "hbm_frac": round(gbps / peak, 4) if peak else None,
    }
