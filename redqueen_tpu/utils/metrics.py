"""On-device feed-rank metrics over the event log.

JAX re-implementation of the reference's ``redqueen/utils.py`` evaluation
layer (SURVEY.md section 2 items 11–14: rank time-series, ``time_in_top_k``,
``average_rank``, rank integrals) so that sweeps at scale never leave HBM.
The pandas twin (``redqueen_tpu.utils.metrics_pandas``) consumes the exported
DataFrame with identical conventions; ``tests/test_metrics.py`` pins the two
layers to each other.

One ``lax.scan`` over the event log reconstructs the tracked source's rank
step function per follower and accumulates every integral in a single pass;
``vmap`` handles batched logs. Invalid tail entries (src == -1) are no-ops.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["FeedMetrics", "feed_metrics", "feed_metrics_batch", "num_posts"]


class FeedMetrics(NamedTuple):
    """Per-sink integrals over [start_time, end_time] for the tracked source;
    sinks the tracked source does not post to hold 0 and are excluded from
    the means. Arrays [F] (or [B, F] for batched logs); the integration
    window is carried along so derived quantities cannot silently use a
    different window than the integrals."""

    time_in_top_k: jnp.ndarray  # int 1[r_i(t) < K] dt
    int_rank: jnp.ndarray       # int r_i(t) dt
    int_rank2: jnp.ndarray      # int r_i(t)^2 dt
    follows: jnp.ndarray        # bool: tracked source posts into this feed
    start_time: jnp.ndarray     # window start the integrals used
    end_time: jnp.ndarray       # window end the integrals used

    def mean_time_in_top_k(self):
        n = jnp.maximum(self.follows.sum(-1), 1)
        return (self.time_in_top_k * self.follows).sum(-1) / n

    def mean_average_rank(self):
        n = jnp.maximum(self.follows.sum(-1), 1)
        return (self.int_rank * self.follows).sum(-1) / n / (
            self.end_time - self.start_time
        )


def feed_metrics(times, srcs, adj, src_index, end_time, K: int = 1,
                 start_time: float = 0.0) -> FeedMetrics:
    """Single pass over one event log [E] (reference: ``rank_of_src_in_df`` +
    the integral metrics, SURVEY.md section 3.4).

    ``times``/``srcs`` may contain (+inf, -1) tail entries; ``adj`` is the
    component's [S, F] adjacency; ``src_index`` is the tracked source's row.
    Events before ``start_time`` still build rank history (the carried-rank
    convention shared with the pandas layer)."""
    F = adj.shape[1]
    dtype = times.dtype
    follows = adj[src_index]
    end = jnp.asarray(end_time, dtype)
    start = jnp.asarray(start_time, dtype)

    def step(carry, ev):
        r, t_prev, top, ir, ir2 = carry
        t, s = ev
        valid = s >= 0
        # Integrate the held rank over the in-window part of [t_prev, t).
        t_clip = jnp.clip(jnp.where(valid, t, t_prev), start, end)
        dt = jnp.maximum(t_clip - t_prev, 0)
        rf = r.astype(dtype)
        top = top + dt * (r < K)
        ir = ir + dt * rf
        ir2 = ir2 + dt * rf * rf
        # Then apply the event to the rank vector.
        hit = adj[jnp.maximum(s, 0)] & follows
        own = s == src_index
        r_new = jnp.where(hit, jnp.where(own, 0, r + 1), r)
        r = jnp.where(valid, r_new, r)
        t_prev = jnp.maximum(t_prev, t_clip)
        return (r, t_prev, top, ir, ir2), None

    zeros = jnp.zeros((F,), dtype)
    init = (jnp.zeros((F,), jnp.int32), start, zeros, zeros, zeros)
    (r, t_prev, top, ir, ir2), _ = lax.scan(step, init, (times, srcs))
    # Flush the final segment to the horizon.
    dt = jnp.maximum(end - t_prev, 0)
    rf = r.astype(dtype)
    top = top + dt * (r < K)
    ir = ir + dt * rf
    ir2 = ir2 + dt * rf * rf
    return FeedMetrics(
        time_in_top_k=top * follows, int_rank=ir * follows,
        int_rank2=ir2 * follows, follows=follows,
        start_time=start, end_time=end,
    )


def feed_metrics_batch(times, srcs, adj, src_index, end_time, K: int = 1,
                       start_time: float = 0.0) -> FeedMetrics:
    """vmap of ``feed_metrics`` over a batched log [B, E] / adjacency
    [B, S, F]; ``src_index`` may be scalar (same row per component)."""
    fn = lambda t, s, a: feed_metrics(t, s, a, src_index, end_time, K, start_time)
    return jax.vmap(fn)(times, srcs, adj)


def num_posts(srcs, src_index):
    """Posting budget actually spent: #events by the tracked source
    (reference: the int u dt helper — for a counting path the integral is the
    post count). Works on [E] or [B, E]."""
    return (srcs == src_index).sum(axis=-1)
