"""Parameter sweeps as ONE device dispatch — the reference's experiment
pattern (nested ``for seed: for q:`` host loops around
``SimOpts.update({...}) -> create_manager -> run_till -> metrics``,
SURVEY.md section 3.5) promoted from a script idiom to a library API.

A sweep point is one component (cfg, params, adj) from
:class:`~redqueen_tpu.config.GraphBuilder`; all points must share the same
STATIC config (shapes/kinds/horizon — the jit cache key), while traced
parameters (q, rates, significances) vary freely. The (point x seed) grid
flattens to one ``simulate_batch`` — optionally sharded over a mesh via the
same placement-only path as :func:`~redqueen_tpu.parallel.shard
.simulate_sharded` — and the feed-rank metrics reduce on device, so nothing
of size O(events) ever reaches the host. :func:`run_sweep_star` is the
star-engine twin over :class:`~redqueen_tpu.parallel.bigf.StarBuilder`
components.

``experiments/tradeoff.py`` is the figure-level consumer of this API.
"""

from __future__ import annotations

import hashlib
import os
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .config import stack_components
from .parallel.bigf import simulate_star_batch, stack_star
from .parallel.shard import simulate_sharded
from .runtime import faultinject as _faultinject
from .runtime import integrity as _integrity
from .runtime import numerics as _numerics
from .runtime import preempt as _preempt
from .runtime.supervisor import heartbeat as _heartbeat
from .sim import simulate_batch
from .utils.metrics import feed_metrics_batch, num_posts

__all__ = ["SweepResult", "run_sweep", "run_sweep_star",
           "run_sweep_checkpointed"]


class SweepResult(NamedTuple):
    """Per-(point, seed) scalars, shape [n_points, n_seeds] (numpy, on
    host — these are O(grid) summaries, not O(events) logs).

    ``health`` is the lane-health grid (uint32 bitmasks, runtime.numerics
    BIT_*): 0 = trustworthy, non-zero = that (point, seed) lane went
    numerically sick — its metric values are garbage and
    ``run_sweep_checkpointed`` quarantines + re-runs exactly those lanes.
    The scan engine reports the kernel mask; both engines additionally
    get the host-side non-finite-result backstop (BIT_NONFINITE_RESULT).
    """

    time_in_top_k: np.ndarray   # mean over followed feeds, absolute time
    average_rank: np.ndarray    # time-averaged rank, mean over feeds
    n_posts: np.ndarray         # tracked source's posting budget spent
    int_rank2: np.ndarray       # int r^2 dt, mean over feeds (loss term)
    health: np.ndarray          # u32 lane-health bitmask grid

    @property
    def n_points(self) -> int:
        return self.time_in_top_k.shape[0]

    @property
    def n_seeds(self) -> int:
        return self.time_in_top_k.shape[1]


def _validate_points(points, n_seeds, vary_hint: str):
    """Shared sweep-grid validation; returns (points list, shared cfg)."""
    if n_seeds < 1:
        raise ValueError(f"n_seeds must be >= 1, got {n_seeds}")
    points = list(points)
    if not points:
        raise ValueError("empty sweep: no points given")
    cfg0 = points[0][0]
    for i, (cfg, _, _) in enumerate(points):
        if cfg != cfg0:
            raise ValueError(
                f"sweep point {i} has a different static config than point "
                f"0 — all points must share shapes/kinds/horizon (vary "
                f"traced {vary_hint} instead, or run separate sweeps)"
            )
    return points, cfg0


def _grid_host(x, P: int, n_seeds: int) -> np.ndarray:
    """The sweep's documented device->host boundary: one explicit
    ``jax.device_get`` per reduced [B] metric vector, reshaped to the
    [P, n_seeds] grid.  Every SweepResult field crosses here and nowhere
    else — host code downstream works on numpy."""
    return np.asarray(jax.device_get(x)).reshape(P, n_seeds)


def _reduce_to_grid(m, n_posts, P: int, n_seeds: int,
                    kernel_health=None) -> SweepResult:
    """FeedMetrics [B, F] + per-lane post counts -> [P, n_seeds] grids.
    Window normalization comes from the FeedMetrics object itself (it
    carries the window its integrals used) — never recomputed here.

    ``kernel_health`` is the per-lane mask from the event-scan kernel
    ([B] uint32; None for the star engine, which has no in-kernel mask
    yet).  Either way a host-side backstop ORs BIT_NONFINITE_RESULT into
    any lane whose reduced grids hold a non-finite value, so a NaN can
    never ride a SweepResult out unlabeled."""
    follows_n = jnp.maximum(m.follows.sum(-1), 1)
    ir2 = (m.int_rank2 * m.follows).sum(-1) / follows_n

    values = dict(
        time_in_top_k=_grid_host(m.mean_time_in_top_k(), P, n_seeds),
        average_rank=_grid_host(m.mean_average_rank(), P, n_seeds),
        n_posts=_grid_host(n_posts, P, n_seeds),
        int_rank2=_grid_host(ir2, P, n_seeds),
    )
    health = (np.zeros((P, n_seeds), np.uint32) if kernel_health is None
              else _grid_host(kernel_health, P, n_seeds).astype(np.uint32))
    bad = np.zeros((P, n_seeds), bool)
    for v in values.values():
        bad |= ~np.isfinite(np.asarray(v, np.float64))
    health = health | np.where(
        bad, np.uint32(_numerics.BIT_NONFINITE_RESULT), np.uint32(0))
    return SweepResult(health=health, **values)


def run_sweep(points: Sequence, n_seeds: int, src_index: int = 0,
              metric_K: int = 1, seed0: int = 0,
              mesh: Optional[Mesh] = None, axis="data",
              max_chunks: int = 100, engine: str = "scan") -> SweepResult:
    """Run every sweep point across ``n_seeds`` Monte-Carlo seeds in one
    batch and return per-lane metric summaries.

    ``points`` — sequence of ``(cfg, params, adj)`` triples (the
    ``GraphBuilder.build()`` output); every point's ``cfg`` must be EQUAL
    (one compiled kernel serves the whole sweep — vary traced params, not
    shapes). ``src_index`` is the tracked broadcaster's source row (the
    GraphBuilder ``add_opt`` return value in the usual layout).

    Seeds are ``seed0 + arange(n_points * n_seeds)`` laid out point-major,
    so APPENDING POINTS extends — never reshuffles — earlier points'
    streams (growing ``n_seeds`` re-seeds every point after the first;
    grow a Monte-Carlo run by sweeping a fresh ``seed0`` range instead).
    With ``mesh``, the batch shards over ``axis`` (a name or tuple of
    names, e.g. ``("dcn", "data")``) with bit-identical results.

    ``engine`` forwards to :func:`~redqueen_tpu.sim.simulate_batch`
    (``"scan"`` / ``"pallas"`` / ``"auto"``): the pallas megakernel's
    in-kernel lane-health mask flows through the same ``SweepResult``
    grid, so the checkpointed quarantine/heal machinery is
    engine-agnostic.  Sharded sweeps (``mesh``) are scan-only — the
    megakernel owns its own lane layout.
    """
    points, cfg0 = _validate_points(points, n_seeds, "SourceParams")
    if mesh is not None and engine != "scan":
        raise ValueError(
            "sharded sweeps (mesh=...) run on the scan engine only — the "
            "pallas megakernel owns its lane layout; drop mesh or pass "
            "engine='scan'")
    P = len(points)
    params, adj = stack_components(
        [p for _, p, _ in points for _ in range(n_seeds)],
        [a for _, _, a in points for _ in range(n_seeds)],
    )
    seeds = np.arange(P * n_seeds) + seed0
    if mesh is None:
        log = simulate_batch(cfg0, params, adj, seeds, max_chunks=max_chunks,
                             engine=engine)
    else:
        log = simulate_sharded(cfg0, params, adj, seeds, mesh, axis=axis,
                               max_chunks=max_chunks)
    m = feed_metrics_batch(log.times, log.srcs, adj, src_index,
                           cfg0.end_time, K=metric_K,
                           start_time=cfg0.start_time)
    return _reduce_to_grid(m, num_posts(log.srcs, src_index), P, n_seeds,
                           kernel_health=log.health)


def run_sweep_star(points: Sequence, n_seeds: int, metric_K: int = 1,
                   seed0: int = 0, mesh: Optional[Mesh] = None,
                   axis: str = "data", feed_axis: Optional[str] = None,
                   fire_mode: str = "auto") -> SweepResult:
    """The star-engine twin of :func:`run_sweep`: sweep points are
    ``(cfg, wall, ctrl)`` triples from
    :class:`~redqueen_tpu.parallel.bigf.StarBuilder` (one controlled
    broadcaster vs its feeds), crossed with ``n_seeds`` into one
    ``simulate_star_batch`` dispatch. Same grid layout and seed rule as
    ``run_sweep`` (point-major; appending points preserves earlier points'
    streams). With ``mesh``, the grid shards over ``axis``; pass
    ``feed_axis`` as well for the 2-D (grid x follower) mesh at big F —
    both forwarded to ``simulate_star_batch`` unchanged. Memory scales
    with n_points x n_seeds x the wall leaves — at the 100k-feed scale
    keep the grid small or shard the feed axis.
    """
    points, cfg0 = _validate_points(points, n_seeds, "Wall/CtrlParams")
    P = len(points)
    # Point-major [P * n_seeds] lanes via the engine's own stacker (the
    # same list-repeat idiom run_sweep uses with stack_components).
    wall_b, ctrl_b = stack_star(
        [w for _, w, _ in points for _ in range(n_seeds)],
        [c for _, _, c in points for _ in range(n_seeds)],
    )
    seeds = np.arange(P * n_seeds) + seed0
    res = simulate_star_batch(cfg0, wall_b, ctrl_b, seeds, mesh=mesh,
                              axis=axis, feed_axis=feed_axis,
                              metric_K=metric_K, fire_mode=fire_mode)
    return _reduce_to_grid(res.metrics, res.n_posts, P, n_seeds)


# Envelope schema tag for chunk artifacts; bump on layout changes so a
# resume after an upgrade recomputes instead of misreading.
# /2: SweepResult grew the lane-health grid (in-computation numerics guard).
_CHUNK_SCHEMA = "rq.sweep.chunk/2"


def _heal_sick_lanes(chunk: SweepResult, pts, n_seeds: int,
                     seed0_chunk: int, runner, ci: int, kwargs: dict):
    """Quarantine recovery at LANE granularity: re-run exactly the sick
    (point, seed) lanes of one chunk grid and patch the healed values in.

    Each lane re-runs as its own single-lane dispatch with the seed the
    point-major layout assigned it (``seed0_chunk + p * n_seeds + s``), so
    a healed lane is bit-identical to what an uninjected/uncorrupted run
    would have produced — the same replay guarantee the chunk-level resume
    machinery gives, one level finer.  A lane that is STILL sick after the
    re-run (deterministically bad inputs, or a fault injection that is
    still active — the re-run dispatch runs inside a ``numeric_scope``
    whose ``lane_base`` maps the env spec onto the same logical lane)
    keeps its recorded health bits.  Returns ``(chunk, n_healed)``."""
    sick = np.argwhere(np.asarray(chunk.health) != 0)
    if sick.size == 0:
        return chunk, 0
    # A single-lane batch cannot shard (mesh axes never divide 1) — and
    # does not need to: sharding is placement-only with bit-identical
    # results, so the re-run executes unsharded and still reproduces the
    # lane's stream exactly.
    solo_kwargs = {k: v for k, v in kwargs.items() if k != "mesh"}
    fields = {f: np.array(getattr(chunk, f)) for f in SweepResult._fields}
    healed = 0
    for p, s in sick:
        p, s = int(p), int(s)
        lane = p * n_seeds + s
        try:
            with _faultinject.numeric_scope(chunk=ci, lane_base=lane):
                solo = runner([pts[p]], 1, seed0=seed0_chunk + lane,
                              **solo_kwargs)
        except _numerics.NumericalHealthError:
            continue  # the lane's one lane died again: bits stay recorded
        if int(np.asarray(solo.health)[0, 0]) != 0:
            continue
        for f in fields:
            fields[f][p, s] = np.asarray(getattr(solo, f))[0, 0]
        healed += 1
    return SweepResult(**fields), healed


def _chunk_fingerprint(chunk_idx: int, pts, n_seeds: int, seed0_chunk: int,
                       star: bool, kwargs: dict) -> str:
    """Content hash of everything that determines a chunk's result: the
    static config, every traced leaf byte, the seed layout, and the sweep
    options. A resumed sweep only reuses a stored chunk whose inputs are
    bit-identical — silently mixing stale results with edited inputs is
    the failure mode this exists to prevent."""
    h = hashlib.sha256()
    h.update(repr((chunk_idx, n_seeds, seed0_chunk, star,
                   sorted(kwargs.items()), pts[0][0])).encode())
    for _, a, b in pts:
        for leaf in jax.tree.leaves((a, b)):
            arr = np.asarray(leaf)
            h.update(str((arr.dtype, arr.shape)).encode())
            h.update(arr.tobytes())
    return h.hexdigest()[:16]


def run_sweep_checkpointed(points: Sequence, n_seeds: int, ckpt_dir: str,
                           chunk_points: int = 8, star: bool = False,
                           seed0: int = 0, **kwargs) -> SweepResult:
    """Restartable sweep (SURVEY.md §5 checkpoint/resume at the SWEEP
    level): the point grid runs in chunks of ``chunk_points`` points, each
    chunk's [p, n_seeds] result grids landing in ``ckpt_dir`` as one
    atomically-renamed, checksum-enveloped ``.npz`` (``runtime.integrity``)
    keyed by a fingerprint of the chunk's full inputs. A killed sweep
    rerun with the same arguments recomputes ONLY the missing chunks; a
    chunk whose inputs changed recomputes and overwrites (never mixes
    stale numbers); a chunk that fails verification on read — truncated,
    bit-flipped, forged checksum — is quarantined
    (``*.corrupt-<ts>`` + report) and re-runs, so the resumed grid stays
    bit-identical to an uninterrupted run.

    Lane-level numeric quarantine rides the same machinery one level
    finer (runtime.numerics): a lane that went numerically sick mid-run
    (in-computation NaN/Inf — detected and frozen by the kernel, so
    sibling lanes are untouched) is recorded in the chunk artifact's
    ``health`` grid and re-run as its own single-lane dispatch with its
    original seed, making the healed grid bit-identical to an
    uncorrupted run; lanes that stay sick keep their recorded bits for
    the next resume.  If EVERY lane of a dispatch dies, the sim driver
    raises :class:`~redqueen_tpu.runtime.numerics.NumericalHealthError`
    with per-lane provenance instead of returning garbage.

    Results are bit-identical to the corresponding single-dispatch
    ``run_sweep``/``run_sweep_star`` call: each chunk starting at point p0
    uses ``seed0 + p0 * n_seeds``, exactly the slice of the point-major
    seed layout the unchunked sweep would assign those lanes.

    ``star`` selects the engine (``points`` then carry StarBuilder
    triples); ``kwargs`` forward to the underlying sweep.

    Chunk artifacts are flat ``.npz`` (not ``utils.checkpoint``/orbax,
    which serves the step-sequenced pytrees: RMTPP training state and
    ``SimState`` carries): a chunk is one immutable content-addressed
    value — fingerprint + four grids — where a single atomically-renamed
    file IS the whole consistency story, and orbax's step numbering /
    retention would only obscure the per-chunk invalidation."""
    # Validate the WHOLE grid up front (not per chunk): a cfg change at a
    # chunk boundary would otherwise run silently where the unchunked
    # run_sweep/run_sweep_star call raises — breaking the bit-identical
    # promise above (round-4 advisor finding).
    points, _ = _validate_points(
        points, n_seeds, "Wall/CtrlParams" if star else "SourceParams")
    if chunk_points < 1:
        raise ValueError(f"chunk_points must be >= 1, got {chunk_points}")
    os.makedirs(ckpt_dir, exist_ok=True)
    runner = run_sweep_star if star else run_sweep
    grids = []
    for ci, p0 in enumerate(range(0, len(points), chunk_points)):
        pts = points[p0:p0 + chunk_points]
        seed0_chunk = seed0 + p0 * n_seeds
        fp = _chunk_fingerprint(ci, pts, n_seeds, seed0_chunk, star, kwargs)
        path = os.path.join(ckpt_dir, f"chunk_{ci:05d}.npz")
        chunk = None
        if os.path.exists(path):
            try:
                z = _integrity.load_npz(path, schema=_CHUNK_SCHEMA,
                                        quarantine_schema_mismatch=False)
            except _integrity.CorruptArtifactError:
                # Torn/bit-flipped/forged-checksum chunk (or a
                # pre-envelope legacy file): load_npz has QUARANTINED it
                # (renamed ``*.corrupt-<ts>`` + structured report) so no
                # later resume trusts it either; this chunk simply
                # re-runs below — the fingerprinted seed layout makes the
                # recomputation bit-identical to what the lost file held.
                # A checksum-VALID archive with an older schema tag (a
                # pre-upgrade chunk) raises too but is NOT quarantined
                # (stale is not corrupt): it recomputes and overwrites
                # like any stale layout, no false corruption report.
                pass
            except Exception:
                # unreadable for non-corruption reasons (permissions,
                # races on a shared dir): recompute without judging
                pass
            else:
                try:
                    if str(z["fingerprint"]) == fp:
                        chunk = SweepResult(
                            *(z[f] for f in SweepResult._fields))
                except KeyError:
                    # archive verified but an expected field is missing
                    # (SweepResult layout drifted without a schema
                    # bump): stale layout, not corruption — recompute
                    # and overwrite, like a fingerprint mismatch
                    chunk = None
                # fingerprint mismatch = STALE inputs, not corruption:
                # recompute and overwrite, exactly as before
        fresh = chunk is None
        if fresh:
            # numeric_scope: the env fault protocol (RQ_FAULT=
            # numeric:mode@laneN,chunkM) addresses lanes per sweep chunk;
            # the scope is a no-op when no numeric fault is configured.
            with _faultinject.numeric_scope(chunk=ci):
                chunk = runner(pts, n_seeds, seed0=seed0_chunk, **kwargs)
        # Lane-level quarantine: any sick lane — freshly detected by the
        # kernel mask, or recorded in a previously landed artifact — re-
        # runs as its own dispatch, bit-identically.  Healed (or freshly
        # computed) grids land atomically, sick bits and all, so a resume
        # knows exactly which lanes to retry.
        chunk, healed = _heal_sick_lanes(
            chunk, pts, n_seeds, seed0_chunk, runner, ci, kwargs)
        if fresh or healed:
            _integrity.savez(
                path, schema=_CHUNK_SCHEMA, fingerprint=fp,
                **{f2: getattr(chunk, f2) for f2 in SweepResult._fields})
        grids.append(chunk)
        # Chunk boundary = the durable safe point: everything appended so
        # far is an atomically-renamed artifact on disk.  Prove progress
        # to a supervising process, then honor a pending SIGTERM/SIGINT
        # (runtime.preempt) — a preempted sweep rerun with the same
        # arguments resumes from exactly these chunks, bit-identically.
        _heartbeat()
        _preempt.check_preempt(f"run_sweep_checkpointed chunk {ci}")
    return SweepResult(*(
        np.concatenate([getattr(g, f) for g in grids], axis=0)
        for f in SweepResult._fields
    ))
