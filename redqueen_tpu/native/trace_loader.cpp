// Native trace ingestion: the framework's C++ data-loader component.
//
// The reference feeds Twitter traces to its RealData broadcaster from
// Python (SURVEY.md section 2 item 7); at the rebuild's target scale
// (100k+ users, millions of rows) the pure-Python CSV path in
// redqueen_tpu/data/traces.py::load_csv is minutes of interpreter loop
// before the first device step. This file is the same contract --
// (user, timestamp) rows -> per-user ascending time arrays, users ordered
// by first appearance -- parsed natively. Python binds it with ctypes
// (redqueen_tpu/native/loader.py); semantics are pinned row-for-row
// against the Python loader by tests/test_native_loader.py.
//
// Parsing is allocation-light by design: the whole file is read once,
// fields are std::string_view slices into that buffer, user keys hash as
// views (materialized only on first appearance via the map's key), and
// timestamps take a std::from_chars fast path with a strtod_l("C") slow
// path for the cases from_chars can't express (leading '+', Python's
// digit-separating underscores, out-of-range magnitudes that must round
// to +-inf/0 the way Python float() does).
//
// Deliberate C ABI (no pybind11 in this environment): an opaque handle
// carries the parse result; the caller sizes NumPy buffers from
// rq_n_users/rq_total_events and rq_fill copies into them; rq_free
// releases. Every error path reports through errbuf -- no exceptions
// cross the boundary.

#include <algorithm>
#include <cctype>
#include <cerrno>
#include <charconv>
#include <cstdio>
#include <cstdlib>
#include <cstdint>
#include <cstring>
#include <locale.h>
#include <string>
#include <string_view>
#include <vector>

namespace {

struct ParseResult {
  std::string data;  // the whole file; field views point into it
  std::vector<std::vector<double>> per_user;  // first-appearance order
  // Load stats (the serving reorder window's measured input contract):
  // rows whose timestamp regressed vs the SAME user's previous row in
  // file order, and exact duplicate timestamps within a user (counted
  // post-sort as adjacent equals).
  long n_nonmonotonic = 0;
  long n_duplicates = 0;
};

// Open-addressing user-key index (FNV-1a, linear probing, stored hashes,
// power-of-two capacity, grow at 70% load). std::unordered_map's
// node-per-key layout was the measured hot spot of the whole parse (50%+
// of samples in _M_find_before_node; one heap node + pointer chase per
// row): a flat probe array with the hash pre-compared costs one cache
// line for almost every lookup.
struct UserIndex {
  struct Slot {
    std::string_view key;
    size_t val = 0;
    uint64_t hash = 0;
    bool used = false;
  };
  std::vector<Slot> slots;
  size_t count = 0;

  explicit UserIndex(size_t cap = 1 << 17) : slots(cap) {}

  static uint64_t fnv1a(std::string_view s) {
    uint64_t h = 1469598103934665603ull;
    for (char c : s) {
      h ^= static_cast<unsigned char>(c);
      h *= 1099511628211ull;
    }
    return h;
  }

  // Returns the value slot for `k`, inserting `next_val` (and setting
  // *inserted) when the key is new.
  size_t find_or_insert(std::string_view k, size_t next_val,
                        bool* inserted) {
    uint64_t h = fnv1a(k);
    size_t mask = slots.size() - 1;
    size_t i = h & mask;
    for (;;) {
      Slot& s = slots[i];
      if (!s.used) {
        if ((count + 1) * 10 > slots.size() * 7) {
          grow();
          return find_or_insert(k, next_val, inserted);
        }
        s.used = true;
        s.key = k;
        s.val = next_val;
        s.hash = h;
        ++count;
        *inserted = true;
        return next_val;
      }
      if (s.hash == h && s.key == k) {
        *inserted = false;
        return s.val;
      }
      i = (i + 1) & mask;
    }
  }

  void grow() {
    std::vector<Slot> old = std::move(slots);
    slots.assign(old.size() * 2, {});
    size_t mask = slots.size() - 1;
    for (auto& s : old) {
      if (!s.used) continue;
      size_t i = s.hash & mask;
      while (slots[i].used) i = (i + 1) & mask;
      slots[i] = s;
    }
  }
};

void set_err(char* errbuf, int errlen, const std::string& msg) {
  if (errbuf && errlen > 0) {
    std::snprintf(errbuf, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
}

// ASCII whitespace (' ', \t \n \v \f \r) inlined — the corpora are ASCII
// by contract (see parse_time) and std::isspace is an opaque call through
// the locale table on the hottest per-field path.
inline bool is_space(char c) {
  return c == ' ' || (c >= '\t' && c <= '\r');
}

// Mirror of Python "not line.strip()": every char is whitespace.
bool is_blank(std::string_view s) {
  for (char c : s) {
    if (!is_space(c)) return false;
  }
  return true;
}

locale_t c_locale() {
  static locale_t loc = ::newlocale(LC_ALL_MASK, "C", (locale_t)0);
  return loc;
}

// Slow path: Python-float() features std::from_chars can't express.
// Validates digit-separating underscores (dropping them), a single
// leading '+', then strtod_l under an explicit "C" locale (an embedding
// process's LC_NUMERIC must never change which corpora load) with
// full-consumption required. strtod's overflow/underflow behavior
// (+-HUGE_VAL / +-0 with ERANGE) matches Python float()'s.
bool parse_time_slow(std::string_view sv, double* out) {
  std::string s;
  s.reserve(sv.size());
  for (size_t i = 0; i < sv.size(); ++i) {
    char c = sv[i];
    // strtod-only envelope Python float() rejects: hex literals and
    // nan(...) payloads (the fast path rejects them too; this guard
    // covers the slow-path-only inputs like "+0x10")
    if (c == 'x' || c == 'X' || c == '(') return false;
    if (c == '_') {
      // Python: underscores only BETWEEN digits (also inside exponents)
      if (i == 0 || i + 1 >= sv.size() ||
          !std::isdigit(static_cast<unsigned char>(sv[i - 1])) ||
          !std::isdigit(static_cast<unsigned char>(sv[i + 1]))) {
        return false;
      }
      continue;  // drop the separator for strtod
    }
    s.push_back(c);
  }
  const char* cs = s.c_str();
  char* end = nullptr;
  errno = 0;
  double v = ::strtod_l(cs, &end, c_locale());
  if (end == cs || *end != '\0') return false;
  *out = v;
  return true;
}

// Mirror of Python float(field): optional surrounding whitespace, ASCII
// digit-separating underscores allowed, the full field must be consumed;
// empty/invalid -> error (returns false). The strtod envelope EXTRAS are
// rejected to match Python -- hex literals ("0x10") stop at 'x' and fail
// full consumption, "nan(chars)" is rejected explicitly. Non-ASCII
// numerals (which Python's float() accepts) are out of scope for the
// native parser: they report as a bad-float error rather than silently
// diverging.
bool parse_time(std::string_view sv, double* out) {
  while (!sv.empty() && is_space(sv.front())) sv.remove_prefix(1);
  while (!sv.empty() && is_space(sv.back())) sv.remove_suffix(1);
  if (sv.empty()) return false;
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  // Fast path: std::from_chars for doubles (libstdc++ >= 11 / libc++).
  if (sv.front() == '+') return parse_time_slow(sv, out);  // rare
  double v;
  auto [p, ec] = std::from_chars(sv.data(), sv.data() + sv.size(), v);
  if (ec == std::errc() && p == sv.data() + sv.size()) {
    // from_chars consumes "nan(charseq)"; Python float() rejects it —
    // scan for the payload parens only on the nan hit itself
    if (v != v && sv.find('(') != std::string_view::npos) return false;
    *out = v;
    return true;
  }
  if (ec == std::errc::result_out_of_range &&
      p == sv.data() + sv.size()) {
    // out-of-range magnitudes: strtod rounds to +-inf / +-0 exactly
    // like Python float()
    return parse_time_slow(sv, out);
  }
  // from_chars stopped early; the only Python-valid reason is a
  // digit-separating underscore
  if (sv.find('_') != std::string_view::npos) {
    return parse_time_slow(sv, out);
  }
  return false;
#else
  // Toolchains without floating-point from_chars (libstdc++ 10, the
  // container's g++) take the strtod_l slow path for EVERY field — the
  // semantic reference the fast path above mirrors, so the two builds
  // parse identically; only the throughput differs.
  return parse_time_slow(sv, out);
#endif
}

}  // namespace

extern "C" {

// Parse the CSV at `path`. Returns an opaque handle, or nullptr with
// errbuf filled. Column semantics match data/traces.py::load_csv: rows
// split on `delimiter`, `user_col`/`time_col` index the split fields, the
// first `skip_header` lines are skipped, blank lines are skipped, the
// user key is the raw (unstripped) field text.
void* rq_parse_csv(const char* path, int user_col, int time_col,
                   char delimiter, int skip_header, char* errbuf,
                   int errlen) {
  if (user_col < 0 || time_col < 0) {  // would index out of bounds below
    set_err(errbuf, errlen, "column indices must be non-negative");
    return nullptr;
  }
  std::FILE* f = std::fopen(path, "rb");
  if (!f) {
    set_err(errbuf, errlen, std::string("cannot open ") + path);
    return nullptr;
  }
  auto* res = new ParseResult();
  std::fseek(f, 0, SEEK_END);
  long fsize = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (fsize > 0) {
    res->data.resize(static_cast<size_t>(fsize));
    size_t got = std::fread(res->data.data(), 1, res->data.size(), f);
    res->data.resize(got);
  } else {
    // Non-seekable (FIFO, /dev/stdin) or stat-size-0 (/proc) inputs:
    // ftell reports -1/0 there, so stream in chunks instead of silently
    // parsing an empty buffer.
    char buf[1 << 16];
    size_t got;
    while ((got = std::fread(buf, 1, sizeof buf, f)) > 0) {
      res->data.append(buf, got);
    }
  }
  std::fclose(f);

  UserIndex index;

  const size_t u_col = static_cast<size_t>(user_col);
  const size_t t_col = static_cast<size_t>(time_col);
  const size_t needed = std::max(u_col, t_col) + 1;
  const char* base = res->data.data();
  const size_t n = res->data.size();

  size_t pos = 0;
  long lineno = -1;
  while (pos < n) {
    // Universal-newline parity with the Python engine (binary read keeps
    // raw terminators): '\n', '\r', and '\r\n' all end a line — a '\r'
    // left in a field would silently split e.g. "alice" / "alice\r" into
    // two users on mixed-endings files, and CR-only (classic-Mac) files
    // would collapse to one giant line.
    const char* lf = static_cast<const char*>(
        std::memchr(base + pos, '\n', n - pos));
    // '\r' search bounded to the LF-terminated span: an unbounded scan of
    // the remaining buffer would be O(corpus) per line on LF-only files.
    const size_t span = lf ? static_cast<size_t>(lf - (base + pos)) : n - pos;
    const char* cr = static_cast<const char*>(
        std::memchr(base + pos, '\r', span));
    const char* nl = cr ? cr : lf;
    size_t le = nl ? static_cast<size_t>(nl - base) : n;
    std::string_view line(base + pos, le - pos);
    size_t next = le + 1;
    if (nl && *nl == '\r' && next < n && base[next] == '\n') {
      ++next;  // CRLF: consume both terminator bytes
    }
    ++lineno;
    if (lineno < skip_header || is_blank(line)) {
      pos = next;
      continue;
    }
    // Walk the fields in place; only the two interesting columns are kept.
    std::string_view uf, tf;
    size_t field_idx = 0, start = 0;
    for (size_t i = 0; i <= line.size(); ++i) {
      if (i == line.size() || line[i] == delimiter) {
        if (field_idx == u_col) uf = line.substr(start, i - start);
        if (field_idx == t_col) tf = line.substr(start, i - start);
        ++field_idx;
        start = i + 1;
      }
    }
    if (field_idx < needed) {
      set_err(errbuf, errlen,
              "line " + std::to_string(lineno) + ": expected at least " +
                  std::to_string(needed) + " fields, got " +
                  std::to_string(field_idx));
      delete res;
      return nullptr;
    }
    double t;
    if (!parse_time(tf, &t)) {
      set_err(errbuf, errlen,
              "line " + std::to_string(lineno) + ": bad float '" +
                  std::string(tf) + "'");
      delete res;
      return nullptr;
    }
    if (t != t) {
      // A NaN row cannot be ordered against any other row of its user:
      // typed rejection (the Python side maps "unorderable" onto
      // TraceOrderError), matching data/traces.py's Python engine —
      // including its .strip()ed field in the message (wording parity
      // is fuzz-pinned).
      std::string_view tt = tf;
      while (!tt.empty() && is_space(tt.front())) tt.remove_prefix(1);
      while (!tt.empty() && is_space(tt.back())) tt.remove_suffix(1);
      set_err(errbuf, errlen,
              "line " + std::to_string(lineno) + ": unorderable timestamp '" +
                  std::string(tt) + "' (NaN rows cannot be ordered)");
      delete res;
      return nullptr;
    }
    bool inserted;
    // key views into res->data: stable for the index's lifetime
    size_t ui = index.find_or_insert(uf, res->per_user.size(), &inserted);
    if (inserted) res->per_user.emplace_back();
    std::vector<double>& uv = res->per_user[ui];
    if (!uv.empty() && t < uv.back()) ++res->n_nonmonotonic;
    uv.push_back(t);
    pos = next;
  }
  for (auto& v : res->per_user) {
    // NaNs are rejected at parse above, so operator< is a strict weak
    // order here and plain std::sort is defined.
    std::sort(v.begin(), v.end());
    for (size_t i = 1; i < v.size(); ++i) {
      if (v[i] == v[i - 1]) ++res->n_duplicates;
    }
  }
  return res;
}

long rq_n_nonmonotonic(void* h) {
  return static_cast<ParseResult*>(h)->n_nonmonotonic;
}

long rq_n_duplicates(void* h) {
  return static_cast<ParseResult*>(h)->n_duplicates;
}

long rq_n_users(void* h) {
  return static_cast<long>(static_cast<ParseResult*>(h)->per_user.size());
}

long rq_total_events(void* h) {
  long total = 0;
  for (const auto& v : static_cast<ParseResult*>(h)->per_user)
    total += static_cast<long>(v.size());
  return total;
}

// times_out: rq_total_events doubles (per-user blocks, ascending within
// each); offsets_out: rq_n_users + 1 longs, user u's times are
// times_out[offsets_out[u] : offsets_out[u+1]].
void rq_fill(void* h, double* times_out, long* offsets_out) {
  auto* res = static_cast<ParseResult*>(h);
  long pos = 0;
  size_t u = 0;
  for (; u < res->per_user.size(); ++u) {
    offsets_out[u] = pos;
    const auto& v = res->per_user[u];
    std::memcpy(times_out + pos, v.data(), v.size() * sizeof(double));
    pos += static_cast<long>(v.size());
  }
  offsets_out[u] = pos;
}

void rq_free(void* h) { delete static_cast<ParseResult*>(h); }

}  // extern "C"
